"""TowerBFT vote tower (ref: src/choreo/tower/fd_tower.h — the long
tutorial comment defines every rule implemented here; state transitions
ref: src/choreo/tower/fd_tower.c:30-100 simulate_vote/push_vote).

The tower is a deque of (slot, conf) votes, newest at the top:

  lockout(vote)    = 2^conf
  expiration(vote) = slot + lockout

Voting for S first pops votes whose expiration < S, top-down and
contiguously (a surviving vote shields everything below it), then
increments conf for the still-consecutive run under the new vote
("doubling lockouts"), then pushes (S, 1). When the tower is full
(max_lockout_history votes) after expiry, the bottom vote roots and
pops — rooting drives state pruning everywhere else (ghost.publish,
funk publish; ref: fd_tower.h rooting discussion).

Checks (ref: fd_tower.c:14-16 THRESHOLD_DEPTH 8, THRESHOLD_RATIO 2/3,
SWITCH_RATIO 0.38):

  lockout_check    may not vote for a different fork than vote v until
                   slot > expiration(v); fork identity via ghost
  threshold_check  the vote at depth 8 (after simulated expiry) must be
                   supported by >= 2/3 of stake's latest votes
  switch_check     >= 38% of stake must sit on forks branching off the
                   GCA(last_vote, switch_target) other than our own
                   (the fd_tower.h switch-check diagram: subtrees of the
                   GCA excluding the child containing our last vote)
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from .ghost import Ghost

MAX_LOCKOUT_HISTORY = 31
THRESHOLD_DEPTH = 8
THRESHOLD_RATIO = 2.0 / 3.0
SWITCH_RATIO = 0.38


@dataclass
class TowerVote:
    slot: int
    conf: int

    @property
    def lockout(self) -> int:
        return 1 << self.conf

    @property
    def expiration(self) -> int:
        return self.slot + self.lockout


class Tower:
    def __init__(self, max_lockout_history: int = MAX_LOCKOUT_HISTORY):
        self.votes: deque[TowerVote] = deque()   # [0] oldest ... [-1] newest
        self.max = max_lockout_history
        self.root: int | None = None

    # -- state transitions --------------------------------------------------

    def simulate(self, slot: int) -> int:
        """Surviving vote count were we to vote for slot: expire from the
        top while expiration < slot; a surviving vote stops the scan
        (top-down contiguous expiry, ref: fd_tower.c simulate_vote)."""
        cnt = len(self.votes)
        while cnt and self.votes[cnt - 1].expiration < slot:
            cnt -= 1
        return cnt

    def vote(self, slot: int) -> int | None:
        """Apply a vote; returns the newly-rooted slot, if any."""
        if self.votes and slot <= self.votes[-1].slot:
            raise ValueError(f"vote {slot} <= last {self.votes[-1].slot}")
        cnt = self.simulate(slot)
        while len(self.votes) > cnt:
            self.votes.pop()
        rooted = None
        if len(self.votes) >= self.max:      # bottom vote reaches max lockout
            rooted = self.votes.popleft().slot
            self.root = rooted
        # double lockouts for the consecutive run under the new vote:
        # from the top, conf must read 1, 2, 3, ... to keep doubling
        # (ref: fd_tower.c push_vote rev iteration)
        expect = 0
        for v in reversed(self.votes):
            expect += 1
            if v.conf != expect:
                break
            v.conf += 1
        self.votes.append(TowerVote(slot, 1))
        return rooted

    # -- checks -------------------------------------------------------------

    def lockout_check(self, target_block: bytes, target_slot: int,
                      ghost: Ghost,
                      vote_blocks: dict[int, bytes]) -> bool:
        """May we vote for target without violating any lockout?
        vote_blocks maps our tower's vote slots to the blocks voted for
        (the tower stores slots; fork identity needs blocks)."""
        for v in self.votes:
            b = vote_blocks.get(v.slot)
            if b is not None and b in ghost.nodes \
                    and ghost.is_ancestor(b, target_block):
                continue                       # same fork: no lockout
            if target_slot > v.expiration:
                continue                       # expired by this vote
            return False
        return True

    def threshold_check(self, slot: int,
                        voter_towers: list[tuple[int, "Tower"]],
                        total_stake: int) -> bool:
        """2/3 of stake must support our vote at THRESHOLD_DEPTH.
        Each voter's tower is simulated voting for `slot` first, so
        long-stale votes expire and don't count
        (ref: fd_tower.c threshold_check)."""
        cnt = self.simulate(slot)
        if cnt < THRESHOLD_DEPTH:
            return True
        # depth 8 including the simulated vote at depth 0
        threshold_slot = self.votes[cnt - THRESHOLD_DEPTH].slot
        threshold_stake = 0
        for stake, tower in voter_towers:
            vcnt = tower.simulate(slot)
            if not vcnt:
                continue
            if tower.votes[vcnt - 1].slot >= threshold_slot:
                threshold_stake += stake
        return threshold_stake >= THRESHOLD_RATIO * total_stake

    def switch_check(self, target_block: bytes, last_vote_block: bytes,
                     ghost: Ghost) -> bool:
        """>= 38% of latest-vote stake must sit on GCA-descendant forks
        other than our own (ref: fd_tower.h switch-check diagram — the
        subtree of the GCA containing our last vote never counts, even
        branches of it that diverge above our vote)."""
        if last_vote_block not in ghost.nodes:
            return True                        # nothing voted: free switch
        gca = ghost.gca(last_vote_block, target_block)
        if gca == last_vote_block:
            return True                        # target on our fork: no switch
        own_child = ghost.path_child(gca, last_vote_block)
        switch_stake = sum(
            ghost.weight(cid)
            for cid in ghost.nodes[gca].children if cid != own_child)
        return switch_stake >= SWITCH_RATIO * ghost.total_stake
