"""Equivocation detection (ref: src/choreo/eqvoc/fd_eqvoc.h:1-60).

Equivocation is a shred producer emitting two or more versions of a
block for one slot. Detection indexes FEC-set metadata per
(slot, fec_set_idx): every shred in a FEC set signs the same merkle
root, so two shreds for the same key with different signatures (or
merkle roots) are a DIRECT proof of equivocation. An INDIRECT proof
arises when overlapping FEC-set extents imply two block layouts for the
same slot (here: a second FEC set whose index range overlaps an already
recorded one with different metadata).

The detector is bounded: state below the published root is pruned, the
same lifecycle the reference drives from tower rooting.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class FecMeta:
    slot: int
    fec_set_idx: int
    merkle_root: bytes
    signature: bytes
    data_cnt: int = 0            # shreds in the set (0 = unknown)


@dataclass(frozen=True)
class EquivocationProof:
    """Two conflicting records that cannot both be honest."""
    slot: int
    a: FecMeta
    b: FecMeta
    kind: str                    # "direct" | "overlap"


class EqvocDetector:
    def __init__(self):
        # (slot, fec_set_idx) -> FecMeta (first version seen)
        self.fecs: dict[tuple[int, int], FecMeta] = {}
        # slot -> first block_id seen (block-level duplicate tracking)
        self.block_ids: dict[int, bytes] = {}

    def insert_fec(self, meta: FecMeta) -> EquivocationProof | None:
        """Record one FEC set's metadata; returns a proof on conflict.

        Direct conflict: same (slot, fec_set_idx), different merkle root
        or signature (ref: fd_eqvoc.h — "every FEC set must have the
        same signature for every shred in the set").
        Overlap conflict: a set whose [idx, idx+data_cnt) range overlaps
        a previously recorded set at a different starting index."""
        key = (meta.slot, meta.fec_set_idx)
        prev = self.fecs.get(key)
        if prev is not None:
            if (prev.merkle_root != meta.merkle_root
                    or prev.signature != meta.signature):
                return EquivocationProof(meta.slot, prev, meta, "direct")
            if not (prev.data_cnt == 0 and meta.data_cnt):
                return None
            # extent was unknown at first sight (partial FEC set): fall
            # through so the now-known data_cnt is overlap-checked and
            # recorded — otherwise an early partial insert would disable
            # overlap detection for this set forever
        # overlap scan against other sets in the same slot
        for (s, idx), other in self.fecs.items():
            if s != meta.slot or idx == meta.fec_set_idx:
                continue
            lo, hi = sorted([(idx, other.data_cnt),
                             (meta.fec_set_idx, meta.data_cnt)])
            if lo[1] and lo[0] + lo[1] > hi[0]:
                return EquivocationProof(meta.slot, other, meta, "overlap")
        self.fecs[key] = meta
        return None

    def note_block_id(self, slot: int, block_id: bytes) -> bool:
        """Track the block id per slot; True = duplicate block observed
        (two distinct ids for one slot — the caller marks both invalid
        in ghost, ref: fd_ghost.h equivocation handling)."""
        prev = self.block_ids.get(slot)
        if prev is None:
            self.block_ids[slot] = block_id
            return False
        return prev != block_id

    def prune(self, root_slot: int):
        """Drop state below the published root."""
        self.fecs = {k: v for k, v in self.fecs.items() if k[0] >= root_slot}
        self.block_ids = {s: b for s, b in self.block_ids.items()
                          if s >= root_slot}
