"""Confirmation tracking ("notar") — per-slot and per-block vote-stake
accumulation with the three Solana confirmation thresholds
(ref: src/choreo/notar/fd_notar.h:1-130).

Unlike ghost (which sums stake over subtrees under the LMD rule, one
fork per validator), notar counts a vote toward the voted slot/block
only, and a validator's stake may count toward multiple blocks if it
switches forks (ref header's ghost-vs-notar discussion). Votes come
from both replay and gossip; only the latest vote slot's block id is
known per vote txn, so notar keys block confirmation by block id and
slot confirmation by slot.

Thresholds (integer arithmetic, no floats — consensus math):
  * propagated           — slot-level, >= 1/3 of total stake
  * duplicate confirmed  — block-level, > 52/100 of total stake
  * optimistically conf. — block-level, >= 2/3 of total stake

When a block id reaches duplicate confirmation for a slot whose
recorded block id differs, the recorded id is replaced (the cluster
converged on the other version — ref fd_notar.h "If notar observes a
duplicate confirmation for a different block_id ... it updates").

Divergence from the reference, documented: the reference tracks voter
sets for the current and previous epoch separately (stake weights can
differ across the boundary); here one stake snapshot applies at a time
and `set_epoch_stakes` re-weights nothing retroactively. Fine for the
self-contained clusters this framework runs; flagged for interop.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class SlotEntry:
    slot: int
    parent_slot: int = 0
    is_leader: bool = False
    prev_leader_slot: int | None = None
    voters: set = field(default_factory=set)
    stake: int = 0
    is_propagated: bool = False
    block_ids: set = field(default_factory=set)


@dataclass
class BlockEntry:
    block_id: bytes
    slot: int
    voters: set = field(default_factory=set)
    stake: int = 0
    dup_conf: bool = False
    opt_conf: bool = False


@dataclass(frozen=True)
class Confirmation:
    """Threshold-crossing notification for downstream consumers."""
    kind: str                   # "propagated" | "duplicate" | "optimistic"
    slot: int
    block_id: bytes | None      # None for slot-level (propagated)


class Notar:
    def __init__(self, total_stake: int = 0):
        self.total_stake = int(total_stake)
        self.stakes: dict[bytes, int] = {}
        self.slots: dict[int, SlotEntry] = {}
        self.blocks: dict[bytes, BlockEntry] = {}
        self.slot_block_id: dict[int, bytes] = {}   # our view, remappable
        self.dup_confirmed_id: dict[int, bytes] = {}
        self.root = 0

    # -- epoch / topology bookkeeping ------------------------------------

    def set_epoch_stakes(self, stakes: dict[bytes, int]):
        self.stakes = dict(stakes)
        self.total_stake = sum(self.stakes.values())

    def on_block(self, slot: int, parent_slot: int, block_id: bytes,
                 is_leader: bool = False,
                 prev_leader_slot: int | None = None):
        """Register a replayed block (our view of slot -> block id)."""
        e = self.slots.setdefault(slot, SlotEntry(slot))
        e.parent_slot = parent_slot
        e.is_leader = is_leader
        e.prev_leader_slot = prev_leader_slot
        e.block_ids.add(block_id)
        # if the cluster already dup-confirmed a version of this slot,
        # that version wins regardless of which one we replayed
        self.slot_block_id[slot] = self.dup_confirmed_id.get(
            slot, self.slot_block_id.get(slot, block_id))

    # -- vote ingest -----------------------------------------------------

    def on_vote(self, voter: bytes, slot: int,
                block_id: bytes) -> list[Confirmation]:
        """Count one (voter, slot, block_id) observation; idempotent per
        (voter, slot) at the slot level and per (voter, block) at the
        block level. Returns newly crossed thresholds."""
        if slot < self.root:
            return []
        stake = self.stakes.get(voter, 0)
        out: list[Confirmation] = []

        se = self.slots.setdefault(slot, SlotEntry(slot))
        se.block_ids.add(block_id)
        if voter not in se.voters:
            se.voters.add(voter)
            se.stake += stake
            if not se.is_propagated and 3 * se.stake >= self.total_stake \
                    and self.total_stake:
                se.is_propagated = True
                out.append(Confirmation("propagated", slot, None))

        be = self.blocks.setdefault(block_id, BlockEntry(block_id, slot))
        if voter not in be.voters:
            be.voters.add(voter)
            be.stake += stake
            if not be.dup_conf and self.total_stake \
                    and 100 * be.stake > 52 * self.total_stake:
                be.dup_conf = True
                out.append(Confirmation("duplicate", slot, block_id))
                # converge our slot -> block id view on the dup-confirmed
                # version — including for replays that arrive later
                # (on_block consults dup_confirmed_id)
                self.dup_confirmed_id[slot] = block_id
                self.slot_block_id[slot] = block_id
            if not be.opt_conf and self.total_stake \
                    and 3 * be.stake >= 2 * self.total_stake:
                be.opt_conf = True
                out.append(Confirmation("optimistic", slot, block_id))
        return out

    # -- queries ---------------------------------------------------------

    def is_propagated(self, slot: int) -> bool:
        e = self.slots.get(slot)
        return bool(e and e.is_propagated)

    def may_vote(self, slot: int) -> bool:
        """Voting rule: our previous leader block as of `slot` must have
        propagated (unless the slot is our own leader block) —
        ref fd_notar.h:19-23."""
        e = self.slots.get(slot)
        if e is None:
            return False
        if e.is_leader:
            return True
        if e.prev_leader_slot is None:
            return True
        return self.is_propagated(e.prev_leader_slot)

    def is_duplicate_confirmed(self, block_id: bytes) -> bool:
        b = self.blocks.get(block_id)
        return bool(b and b.dup_conf)

    def is_optimistically_confirmed(self, block_id: bytes) -> bool:
        b = self.blocks.get(block_id)
        return bool(b and b.opt_conf)

    # -- pruning ---------------------------------------------------------

    def publish(self, root: int):
        """Drop state below the new root (same lifecycle the reference
        drives from tower rooting)."""
        self.root = root
        dead = [s for s in self.slots if s < root]
        for s in dead:
            del self.slots[s]
            self.slot_block_id.pop(s, None)
            self.dup_confirmed_id.pop(s, None)
        dead_b = [k for k, b in self.blocks.items() if b.slot < root]
        for k in dead_b:
            del self.blocks[k]
