"""Vote-account ("voter") accessors — direct-offset reads of the
serialized vote state, the analog of the reference's zero-copy struct
casts (ref: src/choreo/voter/fd_voter.h:22-100).

The consensus stack reads three things from a vote account on the hot
path — the latest vote slot, the root slot, and the tower — and the
reference does so without a full deserialize by exploiting the fixed
bincode layout:

    u32  kind                    (1 = V1_14_11 / "V2", 2 = current / "V3")
    32B  node_pubkey
    32B  authorized_withdrawer
    u8   commission
    u64  votes_cnt
    votes_cnt x {  u64 slot, u32 conf }            (V2, stride 12)
                {  u8 latency, u64 slot, u32 conf } (V3, stride 13)
    u8   root_option  [u64 root]

The full decode path stays in flamenco/types.py (byte-pinned there);
these accessors read only the prefix above and never allocate the tail.
"""
from __future__ import annotations

import struct

V2 = 1          # VoteStateVersions::V1_14_11
V3 = 2          # VoteStateVersions::Current

_HDR = 4 + 32 + 32 + 1          # kind + node_pubkey + withdrawer + commission
_STRIDE = {V2: 12, V3: 13}
_SLOT_OFF = {V2: 0, V3: 1}      # V3 entries lead with the latency byte


class VoterError(ValueError):
    pass


def _kind_cnt(data: bytes) -> tuple[int, int, int]:
    if len(data) < _HDR + 8:
        raise VoterError("vote account too short")
    kind = struct.unpack_from("<I", data, 0)[0]
    if kind not in _STRIDE:
        raise VoterError(f"unsupported vote state kind {kind}")
    cnt = struct.unpack_from("<Q", data, _HDR)[0]
    if cnt > 64:
        raise VoterError(f"implausible tower length {cnt}")
    end = _HDR + 8 + cnt * _STRIDE[kind]
    if len(data) < end + 1:
        raise VoterError("vote account truncated")
    return kind, cnt, end


def kind(data: bytes) -> int:
    return _kind_cnt(data)[0]


def node_pubkey(data: bytes) -> bytes:
    _kind_cnt(data)
    return bytes(data[4:36])


def last_vote_slot(data: bytes) -> int | None:
    """Most recent vote slot in the tower, None if empty
    (the reference returns ULONG_MAX)."""
    k, cnt, _ = _kind_cnt(data)
    if not cnt:
        return None
    off = _HDR + 8 + (cnt - 1) * _STRIDE[k] + _SLOT_OFF[k]
    return struct.unpack_from("<Q", data, off)[0]


def root_slot(data: bytes) -> int | None:
    k, cnt, end = _kind_cnt(data)
    if not data[end]:
        return None
    if len(data) < end + 9:
        raise VoterError("vote account truncated at root")
    return struct.unpack_from("<Q", data, end + 1)[0]


def tower(data: bytes) -> list[tuple[int, int]]:
    """[(slot, confirmation_count)] oldest-first."""
    k, cnt, _ = _kind_cnt(data)
    stride, soff = _STRIDE[k], _SLOT_OFF[k]
    out = []
    for i in range(cnt):
        off = _HDR + 8 + i * stride + soff
        slot = struct.unpack_from("<Q", data, off)[0]
        conf = struct.unpack_from("<I", data, off + 8)[0]
        out.append((slot, conf))
    return out
