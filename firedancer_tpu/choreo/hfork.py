"""Hard-fork detection (ref: src/choreo/hfork/fd_hfork.h:1-80).

Consumes a stream of (vote_account, block_id, bank_hash, stake)
observations — from replayed blocks or gossip, validity of the source
block irrelevant as long as the vote is validly signed — and maintains
Map<block_id, Map<bank_hash, stake>>. A hard fork (consensus bug) is
raised when:

  * > 52% of stake agrees on a bank hash for a block id that differs
    from the hash WE computed for that block id, or
  * > 52% of stake agrees on a bank hash for a block we marked dead
    (failed to execute), or
  * our own validator identity votes a hash different from ours
    (immediate self-check, no threshold).

Per-voter state is a bounded ring of the last `max_live` votes; when a
newer vote evicts an older one, the evicted stake is subtracted — the
same heuristic bound the reference uses to keep memory finite.
"""
from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass

DEAD = b"\x00" * 32             # sentinel for blocks we failed to execute


@dataclass(frozen=True)
class HardFork:
    block_id: bytes
    cluster_hash: bytes
    our_hash: bytes | None      # None = we marked the block dead
    stake: int
    total_stake: int
    reason: str                 # "divergent" | "dead" | "self"


class HforkDetector:
    def __init__(self, total_stake: int = 0, max_live: int = 32,
                 identity: bytes | None = None, max_blocks: int = 4096):
        self.total_stake = int(total_stake)
        self.max_live = int(max_live)
        self.max_blocks = int(max_blocks)
        self.identity = identity
        # block_id -> bank_hash -> stake
        self.weights: dict[bytes, dict[bytes, int]] = {}
        # voter -> ring of (block_id, bank_hash, stake)
        self.rings: dict[bytes, deque] = {}
        self.ours: OrderedDict[bytes, bytes | None] = OrderedDict()
        self.alerts: list[HardFork] = []
        self._alerted: set = set()    # (block_id, hash, reason) dedup

    def set_total_stake(self, total: int):
        self.total_stake = int(total)

    def on_our_result(self, block_id: bytes, bank_hash: bytes | None):
        """Record the hash we computed for block_id (None = marked
        dead). Re-checks any already-accumulated cluster weight. `ours`
        is an LRU capped at max_blocks — on eviction the evicted block's
        accumulated weights and alert-dedup keys go with it, so a
        permanently-resident detector stays bounded."""
        self.ours[block_id] = bank_hash
        self.ours.move_to_end(block_id)
        while len(self.ours) > self.max_blocks:
            old_bid, _ = self.ours.popitem(last=False)
            self.weights.pop(old_bid, None)
            self._alerted = {k for k in self._alerted if k[0] != old_bid}
        for h, st in self.weights.get(block_id, {}).items():
            self._check(block_id, h, st)

    def on_vote(self, voter: bytes, block_id: bytes, bank_hash: bytes,
                stake: int) -> list[HardFork]:
        """Ingest one signed vote observation. Returns alerts raised by
        this observation (also appended to self.alerts). Idempotent per
        (voter, block_id, bank_hash): the same vote arriving via both
        replay and gossip counts once."""
        before = len(self.alerts)
        ring = self.rings.setdefault(voter, deque())
        if any(e[0] == block_id and e[1] == bank_hash for e in ring):
            return []
        ring.append((block_id, bank_hash, stake))
        if len(ring) > self.max_live:
            old_bid, old_h, old_st = ring.popleft()
            per = self.weights.get(old_bid)
            if per is not None and old_h in per:
                per[old_h] -= old_st
                if per[old_h] <= 0:
                    del per[old_h]
                if not per:
                    del self.weights[old_bid]
        per = self.weights.setdefault(block_id, {})
        per[bank_hash] = per.get(bank_hash, 0) + stake

        if self.identity is not None and voter == self.identity:
            mine = self.ours.get(block_id, bank_hash)
            if mine != bank_hash:
                self._raise(block_id, bank_hash, mine, stake, "self")
        self._check(block_id, bank_hash, per[bank_hash])
        return self.alerts[before:]

    def _raise(self, block_id, bank_hash, mine, stake, reason):
        key = (block_id, bank_hash, reason)
        if key in self._alerted:
            return
        self._alerted.add(key)
        self.alerts.append(HardFork(
            block_id, bank_hash, mine, stake, self.total_stake, reason))

    def _check(self, block_id: bytes, bank_hash: bytes, stake: int):
        if not self.total_stake or 100 * stake <= 52 * self.total_stake:
            return
        if block_id not in self.ours:
            return
        mine = self.ours[block_id]
        if mine is None:
            self._raise(block_id, bank_hash, None, stake, "dead")
        elif mine != bank_hash:
            self._raise(block_id, bank_hash, mine, stake, "divergent")
