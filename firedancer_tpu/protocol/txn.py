"""Solana transaction wire parser.

Equivalent of the reference's zero-copy txn parser
(ref: src/ballet/txn/fd_txn.h:181-227 — `fd_txn_t` descriptor table;
fd_txn_parse.c), re-shaped for the TPU pipeline: instead of an in-place
descriptor struct, `parse_txn` returns the offsets/views the verify and
pack tiles need — signatures, signer pubkeys, the signed message region,
account metadata and instruction table.

Wire layout (legacy and v0):
  compact-u16 signature count | sigs (64B each) | message
  message: [0x80|version byte if v0] header(3B: n_signed, n_ro_signed,
  n_ro_unsigned) | compact-u16 account count | accounts (32B each) |
  recent blockhash (32B) | compact-u16 instr count | instrs
  {prog_idx u8, compact-u16 n_acct + idxs, compact-u16 n_data + bytes}
  v0 only: compact-u16 ALUT count | {key 32B, w_idxs, ro_idxs}

Limits mirror the reference: MTU 1232 bytes
(src/ballet/txn/fd_txn.h:102-104), <= 12 actual signatures
(FD_TXN_ACTUAL_SIG_MAX, src/ballet/txn/fd_txn.h:68).
"""
from __future__ import annotations

from dataclasses import dataclass, field

MTU = 1232
SIG_MAX = 12
ACCT_MAX = 128
INSTR_MAX = 64


class TxnParseError(ValueError):
    pass


def _cu16(data: bytes, off: int) -> tuple[int, int]:
    """Decode compact-u16 (1-3 byte LEB-style varint, max 0xffff)."""
    v = 0
    for i in range(3):
        if off >= len(data):
            raise TxnParseError("truncated compact-u16")
        b = data[off]
        off += 1
        v |= (b & 0x7F) << (7 * i)
        if not (b & 0x80):
            if i == 2 and b > 0x03:
                raise TxnParseError("compact-u16 overflow")
            # non-minimal encodings rejected (consensus rule)
            if i > 0 and b == 0:
                raise TxnParseError("non-minimal compact-u16")
            return v, off
    raise TxnParseError("compact-u16 too long")


@dataclass
class Instr:
    prog_idx: int
    acct_idxs: bytes
    data_off: int
    data_sz: int


@dataclass
class ParsedTxn:
    """Offsets are into the original payload (zero-copy discipline)."""
    sig_cnt: int
    sig_off: int              # signatures start (64B each)
    msg_off: int              # signed region: [msg_off, len(payload))
    version: int              # -1 legacy, 0 = v0
    n_signed: int
    n_ro_signed: int
    n_ro_unsigned: int
    acct_cnt: int
    acct_off: int             # account keys start (32B each)
    blockhash_off: int
    instrs: list[Instr] = field(default_factory=list)
    alut_cnt: int = 0
    # v0: [(table_key, writable_idxs bytes, readonly_idxs bytes)]
    aluts: tuple = ()
    size: int = 0             # consumed wire bytes (== len for strict)

    def signatures(self, payload: bytes) -> list[bytes]:
        return [payload[self.sig_off + 64 * i: self.sig_off + 64 * (i + 1)]
                for i in range(self.sig_cnt)]

    def signer_pubkeys(self, payload: bytes) -> list[bytes]:
        return [payload[self.acct_off + 32 * i: self.acct_off + 32 * (i + 1)]
                for i in range(self.sig_cnt)]

    def message(self, payload: bytes) -> bytes:
        end = self.size if self.size else len(payload)
        return payload[self.msg_off:end]

    def account_keys(self, payload: bytes) -> list[bytes]:
        return [payload[self.acct_off + 32 * i: self.acct_off + 32 * (i + 1)]
                for i in range(self.acct_cnt)]

    def is_writable(self, idx: int) -> bool:
        """Static account write permission (legacy/v0 static keys).
        Mirrors the reference's account classification
        (src/ballet/txn/fd_txn.h message header semantics)."""
        if idx < self.n_signed:
            return idx < self.n_signed - self.n_ro_signed
        unsigned_idx = idx - self.n_signed
        n_unsigned = self.acct_cnt - self.n_signed
        return unsigned_idx < n_unsigned - self.n_ro_unsigned


def parse_txn(payload: bytes, allow_trailing: bool = False) -> ParsedTxn:
    """allow_trailing=True parses a txn at a PREFIX of payload and
    reports the consumed size (the fd_txn_parse_core return-size
    contract the gossip vote parser relies on,
    ref src/flamenco/gossip/fd_gossip_msg_parse.c:114)."""
    if len(payload) > MTU and not allow_trailing:
        raise TxnParseError(f"payload {len(payload)} > MTU {MTU}")
    sig_cnt, off = _cu16(payload, 0)
    if not 1 <= sig_cnt <= SIG_MAX:
        raise TxnParseError(f"bad signature count {sig_cnt}")
    sig_off = off
    off += 64 * sig_cnt
    if off > len(payload):
        raise TxnParseError("truncated signatures")
    msg_off = off

    if off >= len(payload):
        raise TxnParseError("empty message")
    version = -1
    if payload[off] & 0x80:
        version = payload[off] & 0x7F
        if version != 0:
            raise TxnParseError(f"unsupported txn version {version}")
        off += 1
    if off + 3 > len(payload):
        raise TxnParseError("truncated header")
    n_signed, n_ro_signed, n_ro_unsigned = payload[off:off + 3]
    off += 3
    if n_signed != sig_cnt:
        raise TxnParseError("header signer count != signature count")
    if n_ro_signed >= n_signed:
        # the fee payer (signer 0) must be writable
        raise TxnParseError("readonly signed count out of range")

    acct_cnt, off = _cu16(payload, off)
    if not n_signed <= acct_cnt <= ACCT_MAX:
        raise TxnParseError(f"bad account count {acct_cnt}")
    if n_ro_unsigned > acct_cnt - n_signed:
        raise TxnParseError("readonly unsigned count out of range")
    acct_off = off
    off += 32 * acct_cnt
    if off > len(payload):
        raise TxnParseError("truncated account keys")
    blockhash_off = off
    off += 32
    if off > len(payload):
        raise TxnParseError("truncated blockhash")

    instr_cnt, off = _cu16(payload, off)
    if instr_cnt > INSTR_MAX:
        raise TxnParseError(f"too many instructions {instr_cnt}")
    instrs = []
    for _ in range(instr_cnt):
        if off >= len(payload):
            raise TxnParseError("truncated instruction")
        prog_idx = payload[off]
        off += 1
        if prog_idx >= acct_cnt:
            raise TxnParseError("instr program index out of range")
        n_acct, off = _cu16(payload, off)
        acct_idxs = payload[off:off + n_acct]
        off += n_acct
        if off > len(payload):
            raise TxnParseError("truncated instr accounts")
        if version != 0 and any(ix >= acct_cnt for ix in acct_idxs):
            # v0 indexes may address table-loaded accounts; bounded
            # below once the alut section is parsed
            raise TxnParseError("instr account index out of range")
        n_data, off = _cu16(payload, off)
        data_off = off
        off += n_data
        if off > len(payload):
            raise TxnParseError("truncated instr data")
        instrs.append(Instr(prog_idx, acct_idxs, data_off, n_data))

    alut_cnt = 0
    aluts = []
    if version == 0:
        alut_cnt, off = _cu16(payload, off)
        for _ in range(alut_cnt):
            tkey = payload[off:off + 32]
            off += 32
            n_w, off = _cu16(payload, off)
            w_idxs = payload[off:off + n_w]
            off += n_w
            n_ro, off = _cu16(payload, off)
            ro_idxs = payload[off:off + n_ro]
            off += n_ro
            if off > len(payload):
                raise TxnParseError("truncated address lookup table")
            aluts.append((tkey, w_idxs, ro_idxs))
        # now the loaded-account count is known: bound every instr
        # index against static + loaded (consumers like the pack cost
        # model index keys BEFORE resolution and must never IndexError)
        n_loaded = sum(len(w) + len(r) for _, w, r in aluts)
        for ins in instrs:
            if ins.prog_idx >= acct_cnt + n_loaded or any(
                    ix >= acct_cnt + n_loaded for ix in ins.acct_idxs):
                raise TxnParseError("instr account index out of range")

    if off != len(payload) and not allow_trailing:
        raise TxnParseError(f"trailing bytes: {len(payload) - off}")

    return ParsedTxn(sig_cnt, sig_off, msg_off, version, n_signed,
                     n_ro_signed, n_ro_unsigned, acct_cnt, acct_off,
                     blockhash_off, instrs, alut_cnt, tuple(aluts),
                     size=off)


def parse_message_shape(data: bytes) -> bool:
    """Is `data` structurally a txn MESSAGE (the signed region — header,
    accounts, blockhash, instructions — without the signature table)?
    Used by the keyguard to identify vote-txn signing requests
    (ref: src/disco/keyguard/fd_keyguard_match.c txn identification).
    Shape-only: no semantic validation."""
    try:
        off = 0
        if not data:
            return False
        version = -1
        if data[0] & 0x80:
            version = data[0] & 0x7F
            if version != 0:
                return False
            off = 1
        if off + 3 > len(data):
            return False
        n_signed, n_ro_signed, n_ro_unsigned = data[off:off + 3]
        off += 3
        if not 1 <= n_signed <= SIG_MAX or n_ro_signed >= n_signed:
            return False
        acct_cnt, off = _cu16(data, off)
        if not n_signed <= acct_cnt <= ACCT_MAX \
                or n_ro_unsigned > acct_cnt - n_signed:
            return False
        off += 32 * acct_cnt + 32          # keys + blockhash
        if off > len(data):
            return False
        instr_cnt, off = _cu16(data, off)
        if instr_cnt > INSTR_MAX:
            return False
        for _ in range(instr_cnt):
            if off >= len(data):
                return False
            if data[off] >= acct_cnt:
                return False
            off += 1
            n_acct, off = _cu16(data, off)
            off += n_acct
            n_data, off = _cu16(data, off)
            off += n_data
            if off > len(data):
                return False
        if version == 0:
            alut_cnt, off = _cu16(data, off)
            for _ in range(alut_cnt):
                off += 32
                n_w, off = _cu16(data, off)
                off += n_w
                n_ro, off = _cu16(data, off)
                off += n_ro
                if off > len(data):
                    return False
        return off == len(data)
    except TxnParseError:
        return False


# ---------------------------------------------------------------------------
# construction (tests / synthetic load gen — the benchg analog,
# ref: src/app/shared_dev/commands/bench/fd_benchg_tile.c)
# ---------------------------------------------------------------------------

def _cu16_enc(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def build_message(signer_pubkeys: list[bytes], extra_accounts: list[bytes],
                  blockhash: bytes, instrs: list[tuple[int, bytes, bytes]],
                  n_ro_signed: int = 0, n_ro_unsigned: int = 0,
                  version: int = -1, aluts=()) -> bytes:
    """instrs: (prog_idx, acct_idxs, data).
    aluts (v0): [(table_key, writable_idxs, readonly_idxs)] — loaded
    addresses extend the key list past the static accounts, writables
    first (the reference's v0 address-table section,
    src/ballet/txn/fd_txn.h address table lookups)."""
    accounts = list(signer_pubkeys) + list(extra_accounts)
    out = bytearray()
    if version == 0:
        out.append(0x80)
    out += bytes([len(signer_pubkeys), n_ro_signed, n_ro_unsigned])
    out += _cu16_enc(len(accounts))
    for a in accounts:
        assert len(a) == 32
        out += a
    assert len(blockhash) == 32
    out += blockhash
    out += _cu16_enc(len(instrs))
    for prog_idx, acct_idxs, data in instrs:
        out.append(prog_idx)
        out += _cu16_enc(len(acct_idxs)) + bytes(acct_idxs)
        out += _cu16_enc(len(data)) + bytes(data)
    if version == 0:
        out += _cu16_enc(len(aluts))
        for tkey, w_idxs, ro_idxs in aluts:
            assert len(tkey) == 32
            out += tkey
            out += _cu16_enc(len(w_idxs)) + bytes(w_idxs)
            out += _cu16_enc(len(ro_idxs)) + bytes(ro_idxs)
    return bytes(out)


def build_txn(signatures: list[bytes], message: bytes) -> bytes:
    out = bytearray(_cu16_enc(len(signatures)))
    for s in signatures:
        assert len(s) == 64
        out += s
    out += message
    return bytes(out)
