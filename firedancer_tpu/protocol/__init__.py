"""Wire-protocol parsing and construction (the reference's src/ballet/txn,
shred, gossip wire structs — host-side, feeding TPU microbatches)."""
from .txn import TxnParseError, parse_txn, ParsedTxn  # noqa: F401
