"""fdgui CLI: attach to a topology's dashboard, or render the report.

    tools/fdgui <topology>                       # print the live URL
    tools/fdgui <topology> --report out.html     # static artifact
        [--bench 'BENCH_r*.json']                #  + trend charts
    tools/fdgui --bench 'BENCH_r*.json' --report out.html
                                                 # bench-only report
    tools/fdgui <topology> --report out.html --archive DIR
                                                 # shm gone? fall back
                                                 # to the fdflight dir

Attaches via the plan JSON the runner drops in /dev/shm (the monitor
CLI's discipline), so the report works POST-MORTEM: the workspace
outlives the tiles, and a crashed run's final counters, SLO breach
history and folded stacks all land in the artifact. When even the shm
is gone (reboot, unlink), --archive renders the history tab from the
fdflight on-disk archive alone.
"""
from __future__ import annotations

import argparse
import glob
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fdgui",
        description="fdgui: live dashboard URL or static HTML report "
                    "over a topology's shm (live or post-mortem)")
    ap.add_argument("topology", nargs="?",
                    help="topology name (omit for a bench-only report)")
    ap.add_argument("--report", metavar="OUT.html",
                    help="write the self-contained HTML artifact")
    ap.add_argument("--bench", metavar="GLOB",
                    help="BENCH_r*.json glob for the trend charts")
    ap.add_argument("--archive", metavar="DIR",
                    help="fdflight archive dir: post-mortem history "
                         "fallback when the topology's shm is gone")
    args = ap.parse_args(argv)

    if args.topology is None:
        if args.report and args.archive:
            from .report import report_from_archive
            out = report_from_archive(args.archive, args.report,
                                      bench_glob=args.bench)
            print(f"fdgui: wrote {out} (archive-only, "
                  f"{args.archive})")
            return 0
        if not (args.report and args.bench):
            ap.error("without a topology, --report plus --bench or "
                     "--archive is required")
        from .report import report_from_bench
        paths = sorted(glob.glob(args.bench))
        if not paths:
            print(f"fdgui: no files match {args.bench!r}",
                  file=sys.stderr)
            return 1
        out = report_from_bench(paths, args.report)
        print(f"fdgui: wrote {out} ({len(paths)} bench rounds)")
        return 0

    if args.report:
        from .report import report_from_shm
        try:
            out = report_from_shm(args.topology, args.report,
                                  bench_glob=args.bench)
        except FileNotFoundError:
            if args.archive:   # shm gone: render from disk alone
                from .report import report_from_archive
                out = report_from_archive(args.archive, args.report,
                                          bench_glob=args.bench,
                                          topology=args.topology)
                print(f"fdgui: shm gone for {args.topology!r}; wrote "
                      f"{out} from archive {args.archive}")
                return 0
            print(f"fdgui: no plan for topology {args.topology!r} "
                  f"(is it running, or was its shm unlinked? "
                  f"--archive DIR renders from the fdflight dir)",
                  file=sys.stderr)
            return 1
        print(f"fdgui: wrote {out}")
        return 0

    # no --report: find the live gui tile and print its URL
    from ..disco.monitor import attach
    from ..disco.topo import read_metrics
    try:
        plan, wksp = attach(args.topology)
    except FileNotFoundError:
        print(f"fdgui: no plan for topology {args.topology!r}",
              file=sys.stderr)
        return 1
    try:
        for tn, spec in plan["tiles"].items():
            if spec["kind"] != "gui":
                continue
            names = spec.get("metrics_names", [])
            if "port" not in names:
                continue
            vals = read_metrics(wksp, plan, tn)
            port = int(vals[names.index("port")])
            if port:
                addr = spec.get("args", {}).get("bind_addr",
                                                "127.0.0.1")
                if addr in ("0.0.0.0", "::"):   # wildcard: loopback
                    addr = "127.0.0.1"          # is always reachable
                print(f"http://{addr}:{port}/   (tile {tn!r})")
                return 0
        print(f"fdgui: topology {args.topology!r} has no gui tile "
              f"with a bound port (add [[tile]] kind='gui', or use "
              f"--report for a headless artifact)", file=sys.stderr)
        return 1
    finally:
        wksp.close()


if __name__ == "__main__":
    raise SystemExit(main())
