"""The fdgui v2 frontend: one self-contained HTML page, no build step.

The reference bundles a compiled frontend into the gui tile binary
(fd_gui_tile.c serves it from memory); the python re-expression keeps
the same deployment shape with inline JS — the tile (and the headless
report) serve exactly this string, so the dashboard works with zero
assets, offline, from `file://`.

Two data paths, one renderer:

  * live: the page opens `ws://<host>/ws`, receives one `snapshot`
    then `delta` frames (gui/schema.py protocol), reconnects on drop;
    flamegraph and bench tabs fetch `/flame.json` / `/bench.json` on
    demand.
  * report: `window.FDGUI_DATA = {...}` is injected where the
    REPORT_MARKER comment sits (gui/report.py) and the page renders
    statically from it — same code, no server.

Rendered surfaces: live topology graph (links colored by activity /
backpressure, the saturating hop highlighted), per-tile occupancy
sparklines, the tile table (state / heartbeat / metrics / latency),
an SLO status + breach-history panel, an on-demand flamegraph view
over fdprof folded stacks, the bench-trend page over BENCH_r*.json
rounds, and a history tab backed by the fdflight on-disk archive
(`/history.json` live, `DATA.history` in reports) so sparklines
survive shm ring wraps and workspace teardown.
"""
from __future__ import annotations

REPORT_MARKER = "<!--FDGUI_DATA-->"

PAGE = r"""<!doctype html><html><head><meta charset="utf-8">
<title>fdgui &mdash; firedancer-tpu</title><style>
:root{--bg:#0b0e14;--panel:#11151f;--line:#1f2430;--fg:#d6d9e0;
--dim:#565f89;--acc:#7aa2f7;--ok:#9ece6a;--warn:#e0af68;--bad:#f7768e}
body{font-family:ui-monospace,monospace;background:var(--bg);
color:var(--fg);margin:18px}h1{font-size:16px;color:var(--acc);
margin:0 0 10px}small{color:var(--dim)}.badge{font-size:11px;
border:1px solid var(--line);border-radius:3px;padding:1px 6px;
color:var(--warn);margin-left:8px}.kpis{display:flex;gap:18px;
margin:8px 0}.kpi{background:var(--panel);border:1px solid var(--line);
border-radius:4px;padding:6px 14px}.kv{font-size:22px;color:var(--ok)}
.kv.bad{color:var(--bad)}.kl{font-size:11px;color:var(--dim)}
nav{margin:10px 0}nav button{background:var(--panel);color:var(--dim);
border:1px solid var(--line);padding:4px 12px;cursor:pointer;
font:inherit}nav button.on{color:var(--acc);border-color:var(--acc)}
table{border-collapse:collapse;margin-top:10px}td,th{padding:3px 10px;
border-bottom:1px solid var(--line);text-align:left;font-size:12px}
th{color:var(--acc)}.run{color:var(--ok)}.boot{color:var(--warn)}
.halt,.FAIL{color:var(--bad)}#graph{background:var(--panel);
border:1px solid var(--line);border-radius:4px}#sat{font-size:12px;
color:var(--bad);margin:6px 0;min-height:14px}
.frow{display:flex;height:17px;margin-top:1px}
.fcell{overflow:hidden;white-space:nowrap;font-size:10px;
color:#0b0e14;padding:1px 3px;border-radius:2px;margin-right:1px;
cursor:default}
.chart{background:var(--panel);border:1px solid var(--line);
border-radius:4px;margin:8px 0;padding:6px}
.chart h3{font-size:12px;color:var(--acc);margin:0 0 4px}
#flame h3{font-size:12px;color:var(--acc);margin:12px 0 2px}
#prov{background:var(--panel);border:1px solid var(--line);
border-radius:4px;padding:6px 12px;margin:8px 0;font-size:12px}
#prov .wbadge{display:inline-block;border:1px solid var(--line);
border-radius:3px;padding:0 6px;margin:1px 4px 1px 0;font-size:11px}
#prov .wit{color:var(--ok);border-color:var(--ok)}
#prov .fallb{color:var(--warn);border-color:var(--warn)}
#prov .wfail{color:var(--bad);border-color:var(--bad)}
</style></head><body>
<h1>firedancer-tpu <span id="topo"></span>
<small id="digest"></small><span id="mode" class="badge">live</span></h1>
<div class="kpis">
<div class="kpi"><div class="kv" id="tps">-</div><div class="kl">TPS</div></div>
<div class="kpi"><div class="kv" id="kbreach">0</div><div class="kl">SLO breached now</div></div>
<div class="kpi"><div class="kv" id="ktiles">-</div><div class="kl">tiles up</div></div>
<div class="kpi" id="kcatch" hidden><div class="kv" id="kbehind">-</div>
<div class="kl">slots behind <span id="kcdetail"></span></div></div>
<div class="kpi" id="ktune" hidden><div class="kv" id="kpress">-</div>
<div class="kl">tune pressure <span id="ktdetail"></span></div></div>
</div>
<div id="prov" hidden></div>
<nav>
<button data-tab="topo" class="on">topology</button>
<button data-tab="slo">slo</button>
<button data-tab="flame">flamegraph</button>
<button data-tab="bench">bench trends</button>
<button data-tab="history">history</button>
</nav>
<section id="tab-topo">
<svg id="graph" width="960" height="10"></svg>
<div id="sat"></div>
<table id="tiles"><thead><tr><th>tile</th><th>kind</th><th>state</th>
<th>occupancy</th><th>hb age</th><th>work p99 &micro;s</th>
<th>metrics</th></tr></thead><tbody></tbody></table>
</section>
<section id="tab-slo" hidden>
<table id="slotab"><thead><tr><th>breached</th><th>total breaches</th>
</tr></thead><tbody><tr><td id="sbr">0</td><td id="sbs">0</td></tr>
</tbody></table>
<table id="sloev"><thead><tr><th>ts</th><th>target</th><th>value</th>
</tr></thead><tbody></tbody></table>
</section>
<section id="tab-flame" hidden><div id="flame">
<small>host-sampler folded stacks per profiled tile (fdprof)</small>
</div></section>
<section id="tab-bench" hidden><div id="bench">
<small>no bench rounds loaded</small></div></section>
<section id="tab-history" hidden><div id="history">
<small>no flight archive loaded (is [flight] enabled?)</small></div>
</section>
<!--FDGUI_DATA-->
<script>
"use strict";
const $=id=>document.getElementById(id);
const DATA=window.FDGUI_DATA||null;
let S=null,prev=null,occHist={},edgeEl={},nodeEl={},sparkEl={};
const fmt=v=>v>=1e6?(v/1e6).toFixed(1)+"M":v>=1e3?(v/1e3).toFixed(1)+"K"
  :(+v).toFixed(0);

/* ---- tabs ---- */
for(const b of document.querySelectorAll("nav button")){
 b.onclick=()=>{for(const x of document.querySelectorAll("nav button"))
   x.classList.toggle("on",x===b);
  for(const s of document.querySelectorAll("section"))
   s.hidden=s.id!=="tab-"+b.dataset.tab;
  if(b.dataset.tab==="flame")loadFlame();
  if(b.dataset.tab==="bench")loadBench();
  if(b.dataset.tab==="history")loadHistory();};}

/* ---- topology graph: longest-path layering, SVG nodes + edges ---- */
function layering(s){
 const depth={},prod={};
 for(const[ln,l]of Object.entries(s.links))if(l.producer)prod[ln]=l.producer;
 const d=(tn,seen)=>{if(depth[tn]!=null)return depth[tn];
  if(seen.has(tn))return 0;seen.add(tn);
  const ups=s.tiles[tn].ins.map(ln=>prod[ln]).filter(p=>p&&p!==tn);
  return depth[tn]=ups.length?1+Math.max(...ups.map(p=>d(p,seen))):0;};
 for(const tn in s.tiles)d(tn,new Set());
 const maxd=Math.max(0,...Object.values(depth));
 for(const tn in s.tiles){const t=s.tiles[tn];
  if(!t.ins.length&&!t.outs.length)depth[tn]=maxd+1;}
 return depth;
}
function buildGraph(){
 const svg=$("graph");svg.innerHTML="";edgeEl={};nodeEl={};
 const depth=layering(S),cols={};
 for(const tn in depth)(cols[depth[tn]]=cols[depth[tn]]||[]).push(tn);
 const ncol=Object.keys(cols).length,cw=Math.max(150,920/Math.max(1,ncol));
 const rows=Math.max(...Object.values(cols).map(c=>c.length));
 const H=Math.max(80,rows*46+20);svg.setAttribute("height",H);
 svg.setAttribute("width",Math.max(960,ncol*cw+40));
 const pos={};
 Object.keys(cols).sort((a,b)=>a-b).forEach((dstr,ci)=>{
  cols[dstr].sort().forEach((tn,ri)=>{
   pos[tn]=[20+ci*cw,14+ri*46];});});
 const NS="http://www.w3.org/2000/svg";
 for(const[ln,l]of Object.entries(S.links)){
  const p=l.producer;if(!p||!pos[p])continue;
  for(const c of l.consumers){if(!pos[c])continue;
   const e=document.createElementNS(NS,"path");
   const[x1,y1]=pos[p],[x2,y2]=pos[c];
   const sx=x1+120,sy=y1+14,ex=x2,ey=y2+14,mx=(sx+ex)/2;
   e.setAttribute("d",`M${sx},${sy} C${mx},${sy} ${mx},${ey} ${ex},${ey}`);
   e.setAttribute("fill","none");e.setAttribute("stroke","#565f89");
   e.setAttribute("stroke-width","1.5");
   const t=document.createElementNS(NS,"title");
   t.textContent=ln;e.appendChild(t);
   svg.appendChild(e);(edgeEl[ln]=edgeEl[ln]||[]).push(e);}}
 for(const tn in pos){const[x,y]=pos[tn];
  const g=document.createElementNS(NS,"g");
  const r=document.createElementNS(NS,"rect");
  r.setAttribute("x",x);r.setAttribute("y",y);
  r.setAttribute("width",120);r.setAttribute("height",28);
  r.setAttribute("rx",4);r.setAttribute("fill","#0b0e14");
  r.setAttribute("stroke","#565f89");
  const tx=document.createElementNS(NS,"text");
  tx.setAttribute("x",x+8);tx.setAttribute("y",y+18);
  tx.setAttribute("fill","#d6d9e0");tx.setAttribute("font-size","11");
  tx.textContent=tn+" ("+S.tiles[tn].kind+")";
  g.appendChild(r);g.appendChild(tx);svg.appendChild(g);
  nodeEl[tn]=r;}
}

/* ---- delta application ---- */
function linkRates(d){
 const out={};if(!d.links)return out;
 for(const[ln,rec]of Object.entries(d.links)){
  const p=prev&&prev.links&&prev.links[ln],dt=prev?(d.ts-prev.ts)/1e9:0;
  const cons=Object.values(rec.consumers||{});
  const lag=cons.length?Math.max(0,...cons.map(c=>c.lag||0)):0;
  out[ln]={pub:rec.pub,bp:rec.backpressure,lag,
   pubRate:p&&dt>0?Math.max(0,(rec.pub-p.pub)/dt):0,
   bpDelta:p?Math.max(0,rec.backpressure-p.backpressure):0};}
 return out;
}
function applyDelta(d){
 if(!S)return;
 $("tps").textContent=fmt(d.tps||0);
 const up=Object.values(d.tiles||{}).filter(t=>t.state==="run").length;
 $("ktiles").textContent=up+"/"+Object.keys(d.tiles||{}).length;
 const br=(d.slo&&d.slo.breach)||0;
 $("kbreach").textContent=br;
 $("kbreach").classList.toggle("bad",br>0);
 /* edges: gray idle, green flowing, amber lossy, red backpressured;
    the saturating hop = the link taking the most new bp ticks */
 const rates=linkRates(d);let sat=null,satBp=0;
 for(const[ln,r]of Object.entries(rates)){
  if(r.bpDelta>satBp){satBp=r.bpDelta;sat=ln;}}
 for(const[ln,els]of Object.entries(edgeEl)){
  const r=rates[ln];if(!r)continue;
  let col="#565f89",w=1.5;
  if(r.pubRate>0)col="#9ece6a";
  if(r.lag>0)col="#e0af68";
  if(r.bpDelta>0){col="#f7768e";w=2.5;}
  if(ln===sat&&satBp>0)w=4;
  for(const e of els){e.setAttribute("stroke",col);
   e.setAttribute("stroke-width",w);}}
 $("sat").textContent=sat&&satBp>0?
  "saturating hop: "+sat+" (+"+satBp+" backpressure ticks, "+
  "producer "+(S.links[sat]?S.links[sat].producer:"?")+")":"";
 /* tile nodes + table */
 const tb=document.querySelector("#tiles tbody");
 for(const[tn,row]of Object.entries(d.tiles||{})){
  if(nodeEl[tn])nodeEl[tn].setAttribute("stroke",
   row.state==="run"?"#9ece6a":row.state==="boot"?"#e0af68":"#f7768e");
  const occ=(row.occupancy&&row.occupancy.work)||0;
  (occHist[tn]=occHist[tn]||[]).push(occ);
  if(occHist[tn].length>60)occHist[tn].shift();
  let tr=document.getElementById("tr-"+tn);
  if(!tr){tr=document.createElement("tr");tr.id="tr-"+tn;
   tr.innerHTML="<td>"+tn+"</td><td>"+row.kind+"</td>"+
    "<td class='st'></td><td class='oc'></td><td class='hb'></td>"+
    "<td class='wk'></td><td class='ms'></td>";
   tb.appendChild(tr);}
  const st=tr.querySelector(".st");
  st.textContent=row.state;st.className="st "+row.state;
  tr.querySelector(".oc").innerHTML=spark(occHist[tn])+
   " "+(occ*100).toFixed(0)+"%"+
   (row.occupancy&&row.occupancy.tpu?
    " <small>tpu "+(row.occupancy.tpu*100).toFixed(0)+"%</small>":"");
  tr.querySelector(".hb").textContent=fmt(row.hb_age_ticks);
  const w=(row.latency&&row.latency.work)||{};
  tr.querySelector(".wk").textContent=w.count?w.p99_us.toFixed(0):"-";
  tr.querySelector(".ms").innerHTML="<small>"+
   Object.entries(row.metrics||{}).filter(([k,v])=>v)
   .map(([k,v])=>k+"="+fmt(v)).join(" ")+"</small>";}
 /* catch-up panel (follower topologies only: d.catchup != null) */
 const cu=d.catchup;
 $("kcatch").hidden=!cu;
 if(cu){
  $("kbehind").textContent=fmt(cu.behind||0);
  $("kbehind").classList.toggle("bad",!!cu.divergent_slot);
  let det="replay "+fmt(cu.replay_tps||0)+" tps";
  if(cu.restore_pct!=null&&cu.restore_pct<100)
   det="restore "+cu.restore_pct+"%";
  if(cu.divergent_slot)det="DIVERGED @ slot "+cu.divergent_slot;
  $("kcdetail").textContent="· "+det;}
 /* fdtune panel (controller topologies only: d.tune != null) —
    what the controller changed, when, and which hop justified it */
 const tu=d.tune;
 $("ktune").hidden=!tu;
 if(tu){
  $("kpress").textContent=(tu.pressure_pct||0)+"%";
  $("kpress").classList.toggle("bad",(tu.pressure_pct||0)>=50);
  const steered=Object.entries(tu.knobs||{})
   .filter(([k,v])=>v.steered).map(([k,v])=>k+"="+v.value);
  let det=tu.decisions+" moves";
  if(steered.length)det+=" · "+steered.join(" ");
  const rec=(tu.recent||[]).slice(-1)[0];
  if(rec)det+=" · last "+rec.knob+"->"+rec.value+
   (rec.hop?" ["+rec.hop+"]":"");
  $("ktdetail").textContent="· "+det;}
 /* slo tab */
 if(d.slo){$("sbr").textContent=d.slo.breach||0;
  $("sbs").textContent=d.slo.breaches||0;
  const eb=document.querySelector("#sloev tbody");eb.innerHTML="";
  for(const e of(d.slo.events||[]).slice().reverse()){
   const tr=document.createElement("tr");
   tr.innerHTML="<td>"+e.ts+"</td><td class='FAIL'>"+e.target+
    "</td><td>"+(e.value==null?"-":fmt(e.value))+"</td>";
   eb.appendChild(tr);}}
 prev=d;
}
function spark(vals){
 const w=60,h=14,n=vals.length;if(!n)return"";
 const pts=vals.map((v,i)=>((i*(w-2)/Math.max(1,n-1))+1)+","+
  (h-1-Math.min(1,Math.max(0,v))*(h-2))).join(" ");
 return"<svg width='"+w+"' height='"+h+"'><polyline points='"+pts+
  "' fill='none' stroke='#7aa2f7' stroke-width='1'/></svg>";
}

/* ---- flamegraph over fdprof folded stacks ---- */
let flameLoaded=false;
function loadFlame(){
 if(flameLoaded)return;flameLoaded=true;
 if(DATA){renderFlame(DATA.flame||{});return;}
 fetch("flame.json").then(r=>r.json()).then(renderFlame)
  .catch(()=>{$("flame").innerHTML="<small>no profile data "+
   "(is [prof] enabled?)</small>";flameLoaded=false;});
}
const FLAMECOL=["#7aa2f7","#9ece6a","#e0af68","#f7768e","#bb9af7",
 "#7dcfff"];
function renderFlame(data){
 const root=$("flame");root.innerHTML="";
 if(!Object.keys(data).length){root.innerHTML=
  "<small>no profile data (is [prof] enabled?)</small>";return;}
 for(const tn of Object.keys(data).sort()){
  const h=document.createElement("h3");h.textContent=tn;
  root.appendChild(h);
  const tree={c:{},n:0};
  for(const[stack,states]of Object.entries(data[tn])){
   const w=Object.values(states).reduce((a,b)=>a+b,0);
   let node=tree;node.n+=w;
   for(const fr of stack.split(";")){
    node=node.c[fr]=node.c[fr]||{c:{},n:0};node.n+=w;}}
  const render=(node,depth,into)=>{
   const kids=Object.entries(node.c);if(!kids.length)return;
   /* widths are fractions of the PARENT node: each wrapper below is
      already scaled by its own ancestry, so dividing by tree.n here
      would shrink deep frames quadratically */
   const row=document.createElement("div");row.className="frow";
   for(const[fr,kid]of kids.sort((a,b)=>b[1].n-a[1].n)){
    const cell=document.createElement("div");cell.className="fcell";
    cell.style.width=(100*kid.n/node.n)+"%";
    cell.style.background=FLAMECOL[depth%FLAMECOL.length];
    cell.textContent=fr.split(":").pop();
    cell.title=fr+" ("+kid.n+" samples)";
    row.appendChild(cell);}
   into.appendChild(row);
   /* one flat row per depth keeps layout simple: recurse per child
      into width-proportional wrappers */
   const wrap=document.createElement("div");wrap.className="frow";
   wrap.style.height="auto";wrap.style.display="flex";
   for(const[fr,kid]of kids.sort((a,b)=>b[1].n-a[1].n)){
    const cw=document.createElement("div");
    cw.style.width=(100*kid.n/node.n)+"%";
    render(kid,depth+1,cw);wrap.appendChild(cw);}
   into.appendChild(wrap);};
  render(tree,0,root);}
}

/* ---- bench trends ---- */
let benchLoaded=false;
function loadBench(){
 if(benchLoaded)return;benchLoaded=true;
 if(DATA){renderBench(DATA.bench||[]);return;}
 fetch("bench.json").then(r=>r.json()).then(renderBench)
  .catch(()=>{benchLoaded=false;});
}
function renderBench(rows){
 const root=$("bench");root.innerHTML="";
 if(!rows.length){root.innerHTML="<small>no BENCH_r*.json rounds "+
  "found</small>";return;}
 for(const[key,label]of[["value","kernel verifies/s"],
   ["e2e_tps","e2e pipeline tps"],["e2e_knee_tps","e2e knee tps"],
   ["e2e_leader_knee_tps","leader-loop knee tps"],
   ["exec_scale_tps_1","exec-scale tps (1 shard)"],
   ["exec_scale_tps_2","exec-scale tps (2 shards)"],
   ["exec_scale_tps_4","exec-scale tps (4 shards)"],
   ["replay_tps","replay slots/s"],
   ["catchup_s","catch-up seconds (lower is better)"]]){
  const pts=rows.map((r,i)=>[i,r[key]]).filter(p=>p[1]!=null);
  const div=document.createElement("div");div.className="chart";
  const max=Math.max(...pts.map(p=>p[1]),1);
  const W=680,H=90;
  let svg="<svg width='"+W+"' height='"+H+"'>";
  if(pts.length){
   const xy=p=>[(30+p[0]*(W-60)/Math.max(1,rows.length-1)),
    (H-18-(p[1]/max)*(H-34))];
   svg+="<polyline fill='none' stroke='#7aa2f7' stroke-width='1.5' "+
    "points='"+pts.map(p=>xy(p).join(",")).join(" ")+"'/>";
   for(const p of pts){const[cx,cy]=xy(p);
    svg+="<circle cx='"+cx+"' cy='"+cy+"' r='2.5' fill='#9ece6a'>"+
     "<title>"+rows[p[0]].file+": "+fmt(p[1])+"</title></circle>";}}
  rows.forEach((r,i)=>{svg+="<text x='"+
   (30+i*(W-60)/Math.max(1,rows.length-1))+"' y='"+(H-4)+
   "' fill='#565f89' font-size='9' text-anchor='middle'>"+
   (r.file||"").replace(/^BENCH_|\.json$/g,"")+"</text>";});
  svg+="</svg>";
  div.innerHTML="<h3>"+label+(pts.length?" (max "+fmt(max)+")":
   " (no data)")+"</h3>"+svg;
  root.appendChild(div);}
}

/* ---- history: flight-archive sparklines (fdflight on-disk) ---- */
let histLoaded=false;
function loadHistory(){
 if(histLoaded)return;histLoaded=true;
 if(DATA){renderHistory(DATA.history||null);return;}
 fetch("history.json").then(r=>r.ok?r.json():null).then(renderHistory)
  .catch(()=>{histLoaded=false;});
}
function renderHistory(h){
 const root=$("history");
 if(!h||!h.series||!Object.keys(h.series).length){root.innerHTML=
  "<small>no flight archive loaded (is [flight] enabled?)</small>";
  return;}
 root.innerHTML="";
 const span=(h.t1_ns-h.t0_ns)/1e9;
 const hd=document.createElement("div");
 hd.innerHTML="<small>archive window "+span.toFixed(1)+"s · "+
  Object.keys(h.series).length+" series"+
  (h.dropped?" · <span class='FAIL'>"+h.dropped+
   " torn frames dropped</span>":"")+"</small>";
 root.appendChild(hd);
 const W=680,H=70,t0=h.t0_ns,tn=Math.max(1,h.t1_ns-h.t0_ns);
 const X=ts=>30+(ts-t0)*(W-60)/tn;
 for(const key of Object.keys(h.series).sort()){
  const pts=h.series[key];if(!pts.length)continue;
  const max=Math.max(...pts.map(p=>p[1]),1);
  const div=document.createElement("div");div.className="chart";
  let svg="<svg width='"+W+"' height='"+H+"'>";
  /* SLO transitions as vertical markers: red=breach, green=clear */
  for(const e of h.slo||[]){
   const x=X(e.ts),col=e.kind==="breach"?"#f7768e":"#9ece6a";
   svg+="<line x1='"+x+"' y1='6' x2='"+x+"' y2='"+(H-14)+
    "' stroke='"+col+"' stroke-dasharray='2,2'>"+
    "<title>"+e.kind+" "+e.target+"</title></line>";}
  svg+="<polyline fill='none' stroke='#7aa2f7' stroke-width='1.5' "+
   "points='"+pts.map(p=>X(p[0])+","+
   (H-14-(p[1]/max)*(H-26))).join(" ")+"'/></svg>";
  div.innerHTML="<h3>"+key+" (max "+fmt(max)+")</h3>"+svg;
  root.appendChild(div);}
 if((h.marks||[]).length){
  const mk=document.createElement("div");
  mk.innerHTML="<small>marks: "+h.marks.map(m=>m.name).join(", ")+
   "</small>";
  root.appendChild(mk);}
}

/* ---- provenance / witness header (fdwitness chain summary) ---- */
function renderProv(w){
 const el=$("prov");if(!w){el.hidden=true;return;}
 el.hidden=false;
 /* stage results come verbatim from stage-subprocess stdout and land
    in single-quoted title attributes below — escape ' too */
 const esc=s=>String(s==null?"":s).replace(/[&<>"']/g,
  c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;",
       "'":"&#39;"}[c]));
 const g=w.git||{},d=w.device||{},v=w.versions||{};
 let h="<b>witnessed run</b> "+esc(w.run_id||"?")+
  (w.cpu_smoke?" <span class='wbadge fallb'>cpu-smoke</span>":"")+
  "<br>git "+esc((g.sha||"?").slice(0,12))+
  (g.dirty?" <span class='wbadge wfail'>dirty</span>":
   " <span class='wbadge wit'>clean</span>")+
  "&nbsp; device "+esc(d.platform||"?")+
  (d.device_kind?" / "+esc(d.device_kind):"")+
  (d.device_count?" &times;"+esc(d.device_count):"")+
  (v.jax?"&nbsp; jax "+esc(v.jax):"")+
  (w.head?"&nbsp; chain "+esc(String(w.head).slice(0,12))+"&hellip;":
   "")+"<br>";
 for(const s of w.stages||[]){
  const cls=s.status!=="ok"?"wfail":s.witnessed?"wit":"fallb";
  const tag=s.status!=="ok"?s.status:
   s.witnessed?"witnessed":"cpu-fallback";
  h+="<span class='wbadge "+cls+"' title='"+esc((s.platform||"")+
   (s.duration_s!=null?" "+s.duration_s+"s":""))+"'>"+
   esc(s.stage)+": "+tag+"</span>";}
 el.innerHTML=h;
}

/* ---- boot: static report vs live websocket ---- */
function boot(snapshot){
 S=snapshot;$("topo").textContent=S.topology;
 $("digest").textContent="cfg "+S.cfg_digest;
 occHist={};prev=null;buildGraph();
}
if(DATA){
 $("mode").textContent="static report";
 boot(DATA.snapshot);
 renderProv(DATA.witness||null);
 for(const d of DATA.deltas||[])applyDelta(d);
 loadFlame();loadBench();loadHistory();
}else{
 (function connect(){
  const ws=new WebSocket((location.protocol==="https:"?"wss://":
   "ws://")+location.host+"/ws");
  ws.onmessage=e=>{const m=JSON.parse(e.data);
   if(m.type==="snapshot")boot(m);
   else if(m.type==="delta")applyDelta(m);};
  ws.onopen=()=>{$("mode").textContent="live";};
  ws.onclose=()=>{$("mode").textContent="disconnected";
   setTimeout(connect,2000);};
 })();
}
</script></body></html>"""


def page_html() -> str:
    return PAGE
