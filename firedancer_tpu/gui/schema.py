"""fdgui v2: `[tile.gui]` arg schema + the snapshot/delta protocol.

The reference documents its gui wire protocol explicitly
(book/api/websocket.md): on connect the client receives one FULL
topology snapshot, then a stream of incremental updates — never a
re-poll. This module is that protocol's server side, pure functions
over (plan, wksp):

  snapshot_doc(plan)     the on-connect document: topology shape
                         (tiles, links, cfg digest), declared SLO
                         targets, which tiles are traced/profiled
  DeltaSource.delta()    one per-housekeeping update: TPS, per-tile
                         state/metrics/latency/occupancy (+ CNC and
                         supervisor counters), per-link
                         pub/consumed/loss/backpressure + consume
                         quantiles, SLO status + recent breach events

Everything is READ-side over the existing shm surfaces (metric slots,
cnc, wait/work/tpu histograms, link telemetry blocks, the metric
tile's trace ring) — the gui adds zero writer-side cost, the fdtrace
disabled-path stance applied to a whole subsystem.

The arg schema (`normalize_gui`) follows the [trace]/[prof] three-
layer contract: validated at config load (registry key gate), at
topo.build, and by fdlint's bad-gui rule — with a did-you-mean on
typos.
"""
from __future__ import annotations

import hashlib
import json

GUI_DEFAULTS = {
    "port": 0,
    "bind_addr": "127.0.0.1",
    "tps_tile": "sink",
    "tps_metric": "rx",
    "ws_max_clients": 8,     # concurrent upgrades; excess get 503
    "ws_queue": 64,          # per-client frame high-water (drop-oldest)
    "ws_sndbuf": 0,          # kernel send-buffer cap (0 = OS default)
    "bench_glob": "BENCH_r*.json",   # /bench.json trend source
    "report_on_halt": None,  # write a static report artifact on halt
}


def _suggest(key: str, candidates) -> str:
    from ..lint.registry import suggest
    return suggest(str(key), candidates)


def normalize_gui(args) -> dict:
    """Validate + default-fill a gui tile's args (the full tile-arg
    dict: structural/common keys are ignored, they belong to the
    stem/launcher). Raises ValueError with a did-you-mean on typos —
    the same fail-before-launch stance as supervise/trace/prof."""
    from ..lint.registry import COMMON_KEYS
    out = dict(GUI_DEFAULTS)
    if args is None:
        return out
    if not isinstance(args, dict):
        raise ValueError(f"gui args must be a table, got {args!r}")
    skip = set(COMMON_KEYS) | {"name", "kind", "ins", "outs"}
    unknown = {k for k in args if k not in GUI_DEFAULTS
               and k not in skip}
    if unknown:
        key = sorted(unknown)[0]
        raise ValueError(f"unknown gui key(s) {sorted(unknown)}"
                         + _suggest(key, GUI_DEFAULTS))
    out.update({k: v for k, v in args.items() if k in GUI_DEFAULTS})
    out["port"] = int(out["port"])
    if out["port"] < 0:
        raise ValueError(f"gui.port must be >= 0, got {out['port']}")
    for k in ("tps_tile", "tps_metric", "bind_addr", "bench_glob"):
        if not isinstance(out[k], str) or not out[k]:
            raise ValueError(f"gui.{k} must be a non-empty string, "
                             f"got {out[k]!r}")
    for k, lo in (("ws_max_clients", 1), ("ws_queue", 2),
                  ("ws_sndbuf", 0)):
        out[k] = int(out[k])
        if out[k] < lo:
            raise ValueError(f"gui.{k} must be >= {lo}, got {out[k]}")
    if out["report_on_halt"] is not None and (
            not isinstance(out["report_on_halt"], str)
            or not out["report_on_halt"]):
        raise ValueError("gui.report_on_halt must be a non-empty "
                         "path string")
    return out


# ---------------------------------------------------------------------------
# the protocol documents
# ---------------------------------------------------------------------------

def cfg_digest(plan: dict) -> str:
    """Short stable digest of the topology SHAPE (links + tiles with
    kinds/wiring/args) — lets a reconnecting client detect that the
    topology it knew was rebuilt under the same name."""
    shape = {
        "links": {ln: {"depth": li["depth"], "mtu": li["mtu"]}
                  for ln, li in plan["links"].items()},
        "tiles": {tn: {"kind": s["kind"], "ins": s["ins"],
                       "outs": s["outs"], "args": s.get("args", {})}
                  for tn, s in plan["tiles"].items()},
    }
    blob = json.dumps(shape, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def snapshot_doc(plan: dict) -> dict:
    """The on-connect document: everything static about the topology.
    Pure function of the plan — no shm read, safe even mid-teardown."""
    from ..disco.metrics import link_producers
    producers = link_producers(plan)
    consumers: dict[str, list[str]] = {ln: [] for ln in plan["links"]}
    tiles = {}
    for tn, spec in plan["tiles"].items():
        tiles[tn] = {
            "kind": spec["kind"],
            "ins": [i["link"] for i in spec.get("ins", [])],
            "outs": list(spec.get("outs", [])),
            "metrics_names": list(spec.get("metrics_names", [])),
            "traced": spec.get("trace_off") is not None,
            "profiled": spec.get("prof_off") is not None,
        }
        for i in spec.get("ins", []):
            consumers.setdefault(i["link"], []).append(tn)
    links = {
        ln: {"depth": li["depth"], "mtu": li["mtu"],
             "producer": producers.get(ln),
             "consumers": consumers.get(ln, [])}
        for ln, li in plan["links"].items()
    }
    slo = plan.get("slo") or {}
    return {
        "type": "snapshot", "v": 2,
        "topology": plan.get("topology", "?"),
        "cfg_digest": cfg_digest(plan),
        "tiles": tiles,
        "links": links,
        "slo": {"targets": [{"name": t["name"], "expr": t["expr"]}
                            for t in slo.get("target", [])]},
    }


class DeltaSource:
    """Stateful per-housekeeping delta builder (one per gui tile or
    report pass). State exists only to turn cumulative shm counters
    into rates/occupancies between calls; the first call falls back
    to lifetime ratios so a post-mortem report still shows where the
    time went."""

    def __init__(self, plan: dict, wksp, tps_tile: str = "sink",
                 tps_metric: str = "rx", tps_window_s: float = 1.0):
        from collections import deque
        self.plan, self.wksp = plan, wksp
        self.tps_tile, self.tps_metric = tps_tile, tps_metric
        self.tps_window_s = float(tps_window_s)
        self.tps = 0.0
        self._tps_win: deque = deque()       # (ns, count) samples
        self._hist_last: dict[str, tuple[int, int, int, int]] = {}
        self._metric_tile = next(
            (tn for tn, s in plan["tiles"].items()
             if s["kind"] == "metric"), None)
        # catch-up surface (r17): replay + snapld tiles, if the
        # topology has them (follower mode)
        self._replay_tile = next(
            (tn for tn, s in plan["tiles"].items()
             if s["kind"] == "replay"), None)
        self._snapld_tile = next(
            (tn for tn, s in plan["tiles"].items()
             if s["kind"] == "snapld"), None)
        self._replay_win: deque = deque()    # (ns, txns) samples
        # fdtune surface (r20): the controller tile, if steering
        self._controller_tile = next(
            (tn for tn, s in plan["tiles"].items()
             if s["kind"] == "controller"), None)

    # -- TPS (satellite fix: tempo.monotonic_ns, THE topology clock —
    # the rate must agree with trace/prof timelines, not drift on a
    # second perf_counter epoch). Computed over a rolling window, not
    # adjacent samples: the gui samples faster than the writer's stem
    # flushes its shm slots, so a consecutive-sample rate reads
    # spurious zeros whenever two passes land inside one flush
    # interval (the SLO engine's rate rationale, disco/slo.py) -------------

    def sample_tps(self) -> float:
        from ..disco.topo import read_metrics
        from ..utils.tempo import monotonic_ns
        spec = self.plan["tiles"].get(self.tps_tile)
        if spec is None:
            return self.tps
        names = spec.get("metrics_names", [])
        if self.tps_metric not in names:
            return self.tps
        vals = read_metrics(self.wksp, self.plan, self.tps_tile)
        cnt = int(vals[names.index(self.tps_metric)])
        now = monotonic_ns()
        self._tps_win.append((now, cnt))
        lo = now - int(self.tps_window_s * 1e9)
        while len(self._tps_win) > 1 and self._tps_win[1][0] <= lo:
            self._tps_win.popleft()   # keep one sample at the edge
        t0, c0 = self._tps_win[0]
        if now > t0:
            self.tps = max(0.0, (cnt - c0) / ((now - t0) / 1e9))
        return self.tps

    # -- per-tile occupancy --------------------------------------------------

    def _occupancy(self, tn: str, now_ns: int) -> dict:
        """{"work": fraction of poll time productive, "tpu": fraction
        of wall time on-device} over the interval since the previous
        delta (lifetime ratios on the first call)."""
        from ..disco.metrics import read_hists
        hists = read_hists(self.wksp, self.plan, tn)
        wait = hists.get("wait", {}).get("sum_ns", 0)
        work = hists.get("work", {}).get("sum_ns", 0)
        tpu = hists.get("tpu", {}).get("sum_ns", 0)
        last = self._hist_last.get(tn)
        self._hist_last[tn] = (now_ns, wait, work, tpu)
        if last is None or now_ns <= last[0]:
            tot = wait + work
            return {"work": round(work / tot, 4) if tot else 0.0,
                    "tpu": 0.0}
        dwall = now_ns - last[0]
        dwait = max(0, wait - last[1])
        dwork = max(0, work - last[2])
        dtpu = max(0, tpu - last[3])
        tot = dwait + dwork
        return {
            "work": round(dwork / tot, 4) if tot else 0.0,
            "tpu": round(min(1.0, dtpu / dwall), 4),
        }

    # -- SLO (read-side: the metric tile's slots + trace ring + dumps) ------

    def _slo(self) -> dict:
        from ..disco.monitor import slo_breach_events
        out: dict = {"breach": 0, "breaches": 0, "events": []}
        mt = self._metric_tile
        if mt is not None:
            from ..disco.topo import read_metrics
            spec = self.plan["tiles"][mt]
            names = spec.get("metrics_names", [])
            vals = read_metrics(self.wksp, self.plan, mt)
            for k in ("slo_breach", "slo_breaches"):
                if k in names:
                    out[k.replace("slo_", "")] = int(
                        vals[names.index(k)])
        out["events"] = slo_breach_events(self.plan, self.wksp)
        return out

    # -- catch-up progress (r17 follower surface) ---------------------------

    def _tile_metrics(self, tn: str) -> dict:
        from ..disco.topo import read_metrics
        spec = self.plan["tiles"].get(tn) or {}
        names = spec.get("metrics_names", [])
        vals = read_metrics(self.wksp, self.plan, tn)
        return {n: int(vals[i]) for i, n in enumerate(names)}

    def _catchup(self, now_ns: int) -> dict | None:
        """Follower catch-up panel: slots behind the live tip, the
        rolling replayed-txn rate, restore stream progress. None on a
        topology with no replay tile (the common leader case — the
        delta stays lean)."""
        if self._replay_tile is None:
            return None
        rm = self._tile_metrics(self._replay_tile)
        self._replay_win.append((now_ns, rm.get("txns", 0)))
        lo = now_ns - int(self.tps_window_s * 1e9)
        while len(self._replay_win) > 1 \
                and self._replay_win[1][0] <= lo:
            self._replay_win.popleft()
        t0, c0 = self._replay_win[0]
        rate = 0.0
        if now_ns > t0:
            rate = max(0.0, (self._replay_win[-1][1] - c0)
                       / ((now_ns - t0) / 1e9))
        out = {
            "behind": rm.get("behind", 0),
            "replay_tps": round(rate, 1),
            "slots_replayed": rm.get("slots_replayed", 0),
            "restore_slot": rm.get("restore_slot", 0),
            "divergent_slot": rm.get("divergent_slot", 0),
            "restore_pct": None,
        }
        if self._snapld_tile is not None:
            sm = self._tile_metrics(self._snapld_tile)
            total = sm.get("total_bytes", 0)
            if total:
                out["restore_pct"] = round(
                    100.0 * min(sm.get("bytes", 0), total) / total, 1)
        return out

    # -- fdtune panel (r20 controller surface) ------------------------------

    def _tune(self) -> dict | None:
        """Tuning panel: what the controller changed, when, and which
        saturating hop justified it — controller counters, the live
        knob-mailbox state (steered vs config-authoritative), and the
        recent EV_TUNE decisions off the controller's trace ring. None
        on a topology with no controller tile (the delta stays lean)."""
        ct = self._controller_tile
        names = self.plan.get("tune_knobs")
        off = self.plan.get("tune_mailbox_off")
        if ct is None or not names or off is None:
            return None
        from ..runtime import KnobMailbox
        cm = self._tile_metrics(ct)
        mb = KnobMailbox(self.wksp, off, len(names))
        knobs = {}
        for i, n in enumerate(names):
            value, seq = mb.read(i)
            knobs[n] = {"value": value if seq else None,
                        "steered": bool(seq)}
        out = {
            "pressure_pct": cm.get("pressure_pct", 0),
            "breached": cm.get("breached", 0),
            "decisions": cm.get("decisions", 0),
            "reverts": cm.get("reverts", 0),
            "moves_in_window": cm.get("moves_in_window", 0),
            "knobs": knobs,
            "recent": [],
        }
        if self.plan["tiles"][ct].get("trace_off") is not None:
            from ..trace import export
            from ..trace.events import EV_TUNE
            evs = export.read_rings(self.plan, self.wksp,
                                    tiles=[ct]).get(ct, [])
            out["recent"] = [
                {"ts": e["ts"],
                 "knob": (names[e["count"]]
                          if e["count"] < len(names)
                          else f"knob[{e['count']}]"),
                 "value": e["arg"], "hop": e["link"]}
                for e in evs if e["etype"] == EV_TUNE][-8:]
        return out

    def delta(self) -> dict:
        """One protocol delta. Raises on a torn/halting topology —
        callers own the 503/skip policy (the gui tile's summary route
        guard, the report collector's retry)."""
        from ..disco.monitor import links_table, snapshot
        from ..disco.metrics import read_link_metrics
        from ..utils.tempo import monotonic_ns
        now = monotonic_ns()
        self.sample_tps()
        tiles = snapshot(self.plan, self.wksp)
        for tn, row in tiles.items():
            row["occupancy"] = self._occupancy(tn, now)
        return {
            "type": "delta", "ts": now, "tps": round(self.tps, 1),
            "tiles": tiles,
            "links": links_table(
                read_link_metrics(self.wksp, self.plan)),
            "slo": self._slo(),
            "catchup": self._catchup(now),
            "tune": self._tune(),
        }
