"""fdgui headless mode: render the dashboard as ONE static HTML file.

The live dashboard answers "what is saturating right now"; CI and
post-mortems need the same answer as a durable artifact. This module
collects the exact documents the WebSocket would have streamed
(snapshot + deltas from gui/schema.py, flamegraph data from fdprof,
bench trends from BENCH_r*.json) and injects them into the frontend
page at its REPORT_MARKER — the result is self-contained (inline JS,
inline data, no server, no assets) and renders from `file://`.

Works from LIVE shm or POST-MORTEM shm alike: the workspace and the
plan JSON outlive the tiles (the fdtrace stance), so
`tools/fdgui <topo> --report out.html` after a crash still shows the
final counters, occupancies, SLO breach history and folded stacks.
Bench-only reports (no shm at all) render the trend page from the
BENCH jsons alone — the artifact bench.py drops next to each round
when FDTPU_BENCH_REPORT is set.
"""
from __future__ import annotations

import glob
import json
import os
import time

from .page import PAGE, REPORT_MARKER


def bench_series(paths) -> list[dict]:
    """BENCH_r*.json paths -> the trend rows the frontend charts
    (kernel vps / e2e tps / knee per round), in CALLER order — the
    trajectory's last point must be whatever the caller put last
    (bench.py appends the in-flight round from a tempdir whose path
    would sort anywhere). Unreadable files are skipped — a report
    must render from whatever rounds exist."""
    from ..prof.bench_diff import load_bench
    rows = []
    for p in paths:
        try:
            rec = load_bench(p)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict):
            continue

        def _num(key, rec=rec):
            v = rec.get(key)
            if v is None and key.startswith("e2e"):
                v = rec.get("witnessed_tpu", {}).get(key)
            try:
                return float(v) if v is not None else None
            except (TypeError, ValueError):
                return None
        rows.append({
            "file": os.path.basename(p),
            "value": _num("value"),
            "e2e_tps": _num("e2e_tps"),
            "e2e_knee_tps": _num("e2e_knee_tps"),
            # leader-loop sweep (r13): the full pack->bank->poh->shred
            # knee + its saturating hop ride every round's trend row
            "e2e_leader_knee_tps": _num("e2e_leader_knee_tps"),
            "e2e_leader_hop": rec.get("e2e_leader_hop"),
            # exec-scaling (r16) + follower catch-up (r17) trends
            "exec_scale_tps_1": _num("exec_scale_tps_1"),
            "exec_scale_tps_2": _num("exec_scale_tps_2"),
            "exec_scale_tps_4": _num("exec_scale_tps_4"),
            "replay_tps": _num("replay_tps"),
            "catchup_s": _num("catchup_s"),
            "platform": rec.get("platform"),
        })
    return rows


def history_series(flight_dir: str, max_series: int = 12,
                   max_points: int = 400) -> dict:
    """Flight-archive -> the history-panel payload: cumulative series
    for the busiest counters, level series for moving gauges, SLO
    transitions and run seams — sparklines backed by DISK, so the
    panel (and the post-mortem report) shows what happened before the
    shm rings wrapped or the workspace died."""
    from ..flight.archive import read_frames
    from ..flight.codec import KIND_MARK, KIND_METRIC, KIND_SLO
    frames, dropped = read_frames(flight_dir)
    series: dict[str, list] = {}
    totals: dict[str, int] = {}
    cum: dict[str, int] = {}
    for fr in frames:
        if fr["kind"] != KIND_METRIC:
            continue
        key = f"{fr['source']}.{fr['name']}"
        if fr["aux"] & 1:
            v = fr["value"]
        else:
            v = cum.get(key, 0) + fr["value"]
            cum[key] = v
            totals[key] = totals.get(key, 0) + fr["value"]
        series.setdefault(key, []).append([fr["ts"], v])
    keep = sorted(totals, key=lambda k: totals[k],
                  reverse=True)[:max_series]
    gauges = [k for k in series if k not in totals
              and len({v for _, v in series[k]}) > 1]
    out_series = {}
    for k in [*keep, *gauges[:max_series]]:
        pts = series[k]
        if len(pts) > max_points:
            step = len(pts) / max_points
            pts = [pts[int(i * step)] for i in range(max_points)]
        out_series[k] = pts
    slo = [{"ts": fr["ts"], "target": fr["source"],
            "kind": fr["name"], "value": fr["value"]}
           for fr in frames if fr["kind"] == KIND_SLO]
    marks = [{"ts": fr["ts"], "name": fr["name"]}
             for fr in frames if fr["kind"] == KIND_MARK]
    return {"t0_ns": frames[0]["ts"] if frames else 0,
            "t1_ns": frames[-1]["ts"] if frames else 0,
            "dropped": dropped, "series": out_series,
            "slo": slo[-64:], "marks": marks[-64:]}


def _gui_tile_args(plan: dict) -> dict:
    """The (normalized) args of the plan's gui tile, defaults when the
    topology has none — the report's TPS source must match what the
    live dashboard was configured to show."""
    from .schema import GUI_DEFAULTS, normalize_gui
    for spec in plan["tiles"].values():
        if spec["kind"] == "gui":
            try:
                return normalize_gui(spec.get("args", {}))
            except ValueError:
                break     # older/foreign plan: fall back to defaults
    return dict(GUI_DEFAULTS)


def collect(plan: dict, wksp, deltas: int = 2,
            interval_s: float = 0.25) -> dict:
    """Snapshot + `deltas` protocol deltas + flamegraph data from one
    attached workspace. Two deltas spaced `interval_s` apart give the
    occupancy/rate fields a real interval even on a live topology; on
    a halted one the second delta simply repeats the final counters."""
    from ..prof.export import read_folded
    from .schema import DeltaSource, snapshot_doc
    ga = _gui_tile_args(plan)
    src = DeltaSource(plan, wksp, tps_tile=ga["tps_tile"],
                      tps_metric=ga["tps_metric"])
    docs = []
    for i in range(max(1, int(deltas))):
        if i:
            time.sleep(interval_s)
        docs.append(src.delta())
    try:
        flame = read_folded(plan, wksp)
    except Exception:   # noqa: BLE001 — a torn prof region loses the
        flame = {}      # flame tab, never the whole artifact
    history = None
    flight_dir = (plan.get("flight") or {}).get("dir")
    if flight_dir:
        try:
            history = history_series(flight_dir)
        except Exception:   # noqa: BLE001 — an unreadable archive
            history = None  # loses the history tab, not the artifact
    return {"snapshot": snapshot_doc(plan), "deltas": docs,
            "flame": flame, "history": history}


def render_html(data: dict) -> str:
    """Inject the collected data into the frontend page. `</script>`
    inside JSON strings is escaped so embedded stacks/exprs can never
    terminate the injected script block."""
    blob = json.dumps(data).replace("</", "<\\/")
    return PAGE.replace(
        REPORT_MARKER,
        f"<script>window.FDGUI_DATA={blob}</script>")


def witness_panel_data(witness: dict | None,
                       witnessed: dict | None = None) -> dict | None:
    """Compress an fdwitness chain block into what the provenance
    header panel renders: git sha + dirty flag, device fingerprint,
    run id, and one witnessed-vs-cpu-fallback badge per stanza. The
    full chain stays in the BENCH json; the report only needs the
    summary (a dashboard header, not an audit log)."""
    if not witness:
        return None
    from ..witness.artifact import stage_platform
    header = witness.get("header") or {}
    stages = []
    device = {}
    for ckpt in witness.get("stages", []):
        res = ckpt.get("result") or {}
        if ckpt.get("stage") == "device_probe" and res:
            device = res
        # same platform resolution as the artifact's witnessed map
        # (explicit stage platform, else the probe fingerprint the
        # runner stamped into the checkpoint's provenance)
        plat = stage_platform(ckpt, res)
        stages.append({
            "stage": ckpt.get("stage"),
            "status": ckpt.get("status"),
            "witnessed": ckpt.get("status") == "ok" and bool(plat)
            and not plat.startswith("cpu"),
            "platform": plat or None,
            "duration_s": ckpt.get("duration_s"),
        })
    return {
        "run_id": witness.get("run_id"),
        "cpu_smoke": bool(witness.get("cpu_smoke")),
        "git": header.get("git") or {},
        "versions": header.get("versions") or {},
        "host": header.get("host") or {},
        "device": {k: device.get(k)
                   for k in ("platform", "device_kind", "device_count")
                   if device.get(k) is not None},
        "head": witness.get("head"),
        "stages": stages,
        "metrics": witnessed or {},
    }


def report_from_shm(topology: str, out_path: str,
                    bench_glob: str | None = None,
                    witness: dict | None = None) -> str:
    """Attach by topology name (live or post-mortem shm) and write the
    artifact; returns the output path."""
    from ..disco.monitor import attach
    plan, wksp = attach(topology)
    try:
        data = collect(plan, wksp)
    finally:
        wksp.close()
    data["bench"] = bench_series(sorted(glob.glob(bench_glob))) \
        if bench_glob else []
    data["witness"] = witness_panel_data(witness)
    with open(out_path, "w") as f:
        f.write(render_html(data))
    return out_path


def report_from_archive(flight_dir: str, out_path: str,
                        bench_glob: str | None = None,
                        topology: str = "") -> str:
    """Post-mortem artifact from the fdflight archive ALONE: no shm
    workspace needed — the history tab (sparklines, SLO transitions,
    run seams) renders from disk, which is the whole point of the
    flight recorder when the run is long gone."""
    history = history_series(flight_dir)
    data = {
        "snapshot": {"type": "snapshot", "v": 2,
                     "topology": topology or f"archive {flight_dir}",
                     "cfg_digest": "-", "tiles": {}, "links": {},
                     "slo": {"targets": []}},
        "deltas": [], "flame": {}, "history": history,
        "bench": bench_series(sorted(glob.glob(bench_glob)))
        if bench_glob else [],
        "witness": witness_panel_data(None),
    }
    with open(out_path, "w") as f:
        f.write(render_html(data))
    return out_path


def report_from_bench(paths, out_path: str,
                      witness: dict | None = None,
                      witnessed: dict | None = None,
                      flame: dict | None = None) -> str:
    """Bench-only artifact: no shm, just the trend page (the shape
    bench.py emits per round under FDTPU_BENCH_REPORT). `witness` is
    an fdwitness chain block rendered as the provenance header panel;
    `flame` optional folded-stack data (the per-stage profile digests
    fdwitness merges into its final report)."""
    data = {
        "snapshot": {"type": "snapshot", "v": 2,
                     "topology": "bench trends", "cfg_digest": "-",
                     "tiles": {}, "links": {},
                     "slo": {"targets": []}},
        "deltas": [], "flame": flame or {},
        "bench": bench_series(paths),
        "witness": witness_panel_data(witness, witnessed),
    }
    with open(out_path, "w") as f:
        f.write(render_html(data))
    return out_path
