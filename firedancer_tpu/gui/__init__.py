"""fdgui v2: the operator dashboard over the shm observability plane.

One subsystem, two delivery modes (ref: src/disco/gui/fd_gui.c +
fd_gui_tile.c — the reference's bundled-frontend gui tile speaking a
snapshot+delta WebSocket protocol over the shared waltz/http server):

  * the `gui` tile (disco/tiles.py GuiAdapter) serves the live
    dashboard: HTTP page + `ws://.../ws` snapshot+delta stream over
    the shared TileHttpServer/WsConn plumbing (disco/httpd.py +
    disco/ws.py), read-side only over shm;
  * `tools/fdgui` / `python -m firedancer_tpu.gui` renders the same
    dashboard headlessly as one self-contained HTML artifact — from
    live OR post-mortem shm, and from BENCH_r*.json rounds alone.
"""
from .page import PAGE, REPORT_MARKER, page_html   # noqa: F401
from .report import (bench_series, collect, render_html,  # noqa: F401
                     report_from_bench, report_from_shm)
from .schema import (GUI_DEFAULTS, DeltaSource,    # noqa: F401
                     cfg_digest, normalize_gui, snapshot_doc)
