"""Leader schedule + chacha + forest/repair tests
(ref: src/flamenco/leaders/fd_leaders.h, src/ballet/chacha/,
src/discof/forest/fd_forest.h, src/discof/repair/fd_policy.h)."""
import numpy as np

from firedancer_tpu.flamenco import EpochLeaders
from firedancer_tpu.keyguard import ROLE_REPAIR, SIGN_TYPE_ED25519, authorize
from firedancer_tpu.repair import (
    DISC_ORPHAN, DISC_WINDOW_INDEX, Forest, RepairPolicy, parse_request,
)
from firedancer_tpu.utils.chacha import ChaChaRng, chacha20_block


def pk(i):
    return bytes([i]) * 32


# ---------------------------------------------------------------------------
# chacha
# ---------------------------------------------------------------------------

def test_chacha20_rfc8439_vector():
    """RFC 8439 §2.3.2 test vector (block 1)."""
    key = bytes(range(32))
    nonce = bytes.fromhex("000000090000004a00000000")
    block = chacha20_block(key, 1, nonce)
    want = bytes.fromhex(
        "10f1e7e4d13b5915500fdd1fa32071c4c7d1f4c733c068030422aa9ac3d46c4e"
        "d2826446079faa0914c2d705d98b02a2b5129cd1de164eb9cbd083e8a2503c4e")
    assert block == want


def test_chacha_rng_determinism_and_bound():
    a = ChaChaRng(b"\x07" * 32)
    b = ChaChaRng(b"\x07" * 32)
    xs = [a.next_u64() for _ in range(10)]
    assert xs == [b.next_u64() for _ in range(10)]
    assert xs != [ChaChaRng(b"\x08" * 32).next_u64() for _ in range(10)]
    r = ChaChaRng(b"\x01" * 32)
    draws = [r.roll_u64(7) for _ in range(200)]
    assert set(draws) <= set(range(7)) and len(set(draws)) == 7


# ---------------------------------------------------------------------------
# leader schedule
# ---------------------------------------------------------------------------

def test_leaders_deterministic_and_rotation():
    stakes = {pk(1): 100, pk(2): 200, pk(3): 50}
    a = EpochLeaders(2, b"\x05" * 32, stakes, slots_per_epoch=40)
    b = EpochLeaders(2, b"\x05" * 32, stakes, slots_per_epoch=40)
    slots = range(80, 120)
    assert [a.leader_for(s) for s in slots] == \
        [b.leader_for(s) for s in slots]
    # leader constant within each 4-slot rotation
    for r in range(10):
        base = 80 + 4 * r
        ls = {a.leader_for(base + i) for i in range(4)}
        assert len(ls) == 1
    # leader_slots inverts leader_for
    for key in stakes:
        for s in a.leader_slots(key):
            assert a.leader_for(s) == key


def test_leaders_stake_proportional():
    stakes = {pk(1): 900, pk(2): 90, pk(3): 10}
    el = EpochLeaders(0, b"\x09" * 32, stakes, slots_per_epoch=4000)
    counts = {k: len(el.leader_slots(k)) for k in stakes}
    assert counts[pk(1)] > counts[pk(2)] > counts[pk(3)]
    assert counts[pk(1)] > 0.8 * 4000
    # zero-stake nodes never lead
    stakes[pk(4)] = 0
    el2 = EpochLeaders(0, b"\x09" * 32, stakes, slots_per_epoch=400)
    assert not el2.leader_slots(pk(4))


def test_leaders_seed_changes_schedule():
    stakes = {pk(i): 100 for i in range(1, 6)}
    a = EpochLeaders(0, b"\x01" * 32, stakes, slots_per_epoch=400)
    b = EpochLeaders(0, b"\x02" * 32, stakes, slots_per_epoch=400)
    assert a.sched != b.sched


# ---------------------------------------------------------------------------
# forest
# ---------------------------------------------------------------------------

def test_forest_bfs_frontier_and_completion():
    f = Forest(root_slot=10)
    # 10 <- 11 <- 12 and a fork 10 <- 13
    f.shred(11, 0, parent_off=1)
    f.shred(11, 2, slot_complete=True)        # missing idx 1
    f.shred(12, 0, parent_off=1, slot_complete=True)
    f.shred(13, 1, parent_off=3)              # end unknown, missing 0
    assert f.frontier() == [11, 13]           # 12 complete; BFS order
    reqs = f.requests()
    assert (11, 1) in reqs and (13, 0) in reqs
    assert all(s != 12 for s, _ in reqs)
    f.shred(11, 1)
    assert f.blks[11].is_complete
    assert f.frontier() == [13]


def test_forest_orphans_then_link():
    f = Forest(root_slot=0)
    f.vote(20)                                # existence via gossip only
    assert 20 in f.frontier()                 # orphan, repairs last
    f.shred(20, 1, parent_off=2, slot_complete=True)   # idx 0 missing
    f.link(18, 17)
    f.shred(18, 0, parent_off=1, slot_complete=True)
    # 20's parent 18 now linked through 17: 17 missing entirely
    f.link(17, 0)
    front = f.frontier()
    assert front.index(17) < front.index(20)


def test_forest_publish_prunes():
    f = Forest(root_slot=0)
    f.shred(1, 0, parent_off=1, slot_complete=True)
    f.shred(2, 0, parent_off=2, slot_complete=True)   # fork off 0
    f.shred(3, 0, parent_off=2, slot_complete=False)  # child of 1
    f.publish(1)
    assert f.root == 1
    assert 2 not in f.blks                    # rival fork pruned
    assert 3 in f.blks


# ---------------------------------------------------------------------------
# repair policy
# ---------------------------------------------------------------------------

def test_policy_requests_dedup_and_roundrobin():
    ident = pk(9)
    f = Forest(root_slot=10)
    f.shred(11, 0, parent_off=1)
    f.shred(11, 3, slot_complete=True)        # missing 1, 2
    pol = RepairPolicy(ident, dedup_window_ns=1_000_000)
    pol.set_peers([pk(1), pk(2)])
    reqs = pol.plan(f, now_ns=0)
    assert len(reqs) == 2
    peers = [p for p, _ in reqs]
    assert peers == [pk(1), pk(2)]            # round-robin
    disc, sender, recipient, ts, nonce, slot, idx = \
        parse_request(reqs[0][1])
    assert disc == DISC_WINDOW_INDEX and sender == ident
    assert recipient == pk(1)
    assert slot == 11 and idx in (1, 2)
    # every request passes the keyguard's repair-role authorization
    for _, payload in reqs:
        assert authorize(ident, payload, ROLE_REPAIR, SIGN_TYPE_ED25519)
    # within the window: suppressed; after: resent
    assert pol.plan(f, now_ns=500_000) == []
    assert len(pol.plan(f, now_ns=2_000_000)) == 2


def test_policy_orphan_requests():
    ident = pk(9)
    f = Forest(root_slot=0)
    f.vote(33)
    pol = RepairPolicy(ident)
    pol.set_peers([pk(1)])
    reqs = pol.plan(f, now_ns=0)
    assert reqs
    disc, _, _, _, _, slot, _ = parse_request(reqs[0][1])
    assert disc == DISC_ORPHAN and slot == 33
