"""Gossip tile over real UDP: three nodes in separate OS processes
bootstrap off one entrypoint and converge their CRDS stores, with
signed values verified on receipt (ref: src/discof/gossip/ tile +
src/flamenco/gossip/fd_gossip.h)."""
import os
import time

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.gossip.crds import KIND_VOTE

SEEDS = [bytes([i]) * 32 for i in (1, 2, 3)]


def _free_ports(n):
    import socket
    socks = []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def test_three_nodes_converge_over_udp():
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    p0, p1, p2 = _free_ports(3)
    ep = [f"127.0.0.1:{p0}"]
    topo = Topology(f"gsp{os.getpid()}", wksp_size=1 << 22)
    for i, (seed, port, eps) in enumerate(
            [(SEEDS[0], p0, []), (SEEDS[1], p1, ep), (SEEDS[2], p2, ep)]):
        topo.tile(f"g{i}", "gossip", seed=seed.hex(), port=port,
                  entrypoints=eps,
                  publish=[{"kind": KIND_VOTE, "index": 0,
                            "data_hex": bytes([0x40 + i]).hex()}])
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        # each node: 3 contact infos + 3 votes = 6 values
        deadline = time.time() + 90
        while time.time() < deadline:
            vals = [runner.metrics(f"g{i}")["values"] for i in range(3)]
            if all(v >= 6 for v in vals):
                break
            time.sleep(0.25)
        for i in range(3):
            m = runner.metrics(f"g{i}")
            assert m["values"] >= 6, (i, m)
            assert m["contacts"] == 3, (i, m)
            assert m["bad_msg"] == 0, (i, m)
    finally:
        runner.halt()
        runner.close()
