"""Gossip tile over real UDP: three nodes in separate OS processes
bootstrap off one entrypoint and converge their CRDS stores, with
signed values verified on receipt (ref: src/discof/gossip/ tile +
src/flamenco/gossip/fd_gossip.h)."""
import pytest

pytestmark = pytest.mark.slow
import os
import time

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.flamenco import gossip_wire as gw
from firedancer_tpu.gossip.crds import KIND_VOTE
from firedancer_tpu.utils.ed25519_ref import keypair

SEEDS = [bytes([i]) * 32 for i in (1, 2, 3)]
VOTE_TXN_PATH = "/root/reference/src/flamenco/gossip/test_vote_txn.bin"


def _free_ports(n):
    import socket
    socks = []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def test_three_nodes_converge_over_udp():
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    p0, p1, p2 = _free_ports(3)
    ep = [f"127.0.0.1:{p0}"]
    topo = Topology(f"gsp{os.getpid()}", wksp_size=1 << 22)
    if os.path.exists(VOTE_TXN_PATH):
        vote_txn = open(VOTE_TXN_PATH, "rb").read()
    else:
        # fixture absent: synthesize a real signed TowerSync vote txn
        from firedancer_tpu.protocol.txn import build_message, build_txn
        from firedancer_tpu.svm.vote import VOTE_PROGRAM_ID, ix_tower_sync
        from firedancer_tpu.utils.ed25519_ref import sign as _sign
        _, _, vp = keypair(SEEDS[0])
        msg = build_message([vp], [vp, VOTE_PROGRAM_ID], bytes(32),
                            [(2, bytes([1]),
                              ix_tower_sync([(5, 1)], None, bytes(32),
                                            bytes(32)))],
                            n_ro_unsigned=1)
        vote_txn = build_txn([_sign(SEEDS[0], msg)], msg)
    for i, (seed, port, eps) in enumerate(
            [(SEEDS[0], p0, []), (SEEDS[1], p1, ep), (SEEDS[2], p2, ep)]):
        _, _, pub = keypair(seed)
        # a REAL CrdsData::Vote payload (index, origin, vote txn,
        # wallclock) — the receivers parse it with the wire codec
        payload = gw.encode_vote(0, pub, vote_txn, 1000 + i)
        topo.tile(f"g{i}", "gossip", seed=seed.hex(), port=port,
                  entrypoints=eps,
                  publish=[{"kind": KIND_VOTE, "index": 0,
                            "data_hex": payload.hex()}])
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        # each node: 3 contact infos + 3 votes = 6 values
        deadline = time.time() + 90
        while time.time() < deadline:
            vals = [runner.metrics(f"g{i}")["values"] for i in range(3)]
            if all(v >= 6 for v in vals):
                break
            time.sleep(0.25)
        for i in range(3):
            m = runner.metrics(f"g{i}")
            assert m["values"] >= 6, (i, m)
            assert m["contacts"] == 3, (i, m)
            assert m["bad_msg"] == 0, (i, m)
    finally:
        runner.halt()
        runner.close()


def test_gossvf_batch_verify_drops_forgeries():
    """The gossvf device batch admits valid CRDS values and drops
    forged ones — same verdicts as the host oracle, one kernel call."""
    from firedancer_tpu.gossip.crds import CrdsValue
    from firedancer_tpu.gossip.gossvf import batch_verify
    from firedancer_tpu.utils.ed25519_ref import keypair, sign
    import dataclasses
    vals = []
    for i in range(6):
        seed = bytes([i + 1]) * 32
        _, _, pub = keypair(seed)
        v = CrdsValue(pub, 1, 0, 1000 + i, b"data-%d" % i)  # store-only payload
        sig = bytes(64) if i % 3 == 2 else sign(seed, v.signable())
        vals.append(dataclasses.replace(v, signature=sig))
    got = batch_verify(vals)
    assert got == [True, True, False, True, True, False]
    # malformed signature length: verdict False, no crash
    vals[0] = dataclasses.replace(vals[0], signature=b"short")
    assert batch_verify(vals)[0] is False
