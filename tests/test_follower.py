"""Follower mode (r17): cold-start from a shm-store snapshot + catch-up
replay over the exec tile family.

The drills pinned here are the ISSUE-16 acceptance set, in-process so
they run in tier-1:

* end-to-end cold start: leader oracle replays N slots (InlineFanout —
  the same WaveExecutor engine the exec shards run), snapshots at S;
  the follower restores the snapshot into a WireFunk through the real
  snapld -> snapin cores, picks up the restore marker, replays the
  tail over a real ExecFanout + 2 ExecAdapters, and lands on the
  oracle's per-slot bank hashes and balances.
* divergence verdict: a diverging block flips the divergent_slot
  metric and fails the tile loudly, naming the first divergent slot —
  never a silent wrong state.
* kill-exec-shard: a shard dead mid-wave forces timeout cancel +
  whole-wave redispatch under a fresh fork; when the shard rejoins the
  wave completes — exactly-once application, no wedged producer.
"""
import hashlib
import os
import struct
import threading
import time
from types import SimpleNamespace

import pytest

from firedancer_tpu.runtime import Ring, Store, Workspace

pytestmark = pytest.mark.exec

os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")

N_GENESIS = 8

# timing scale (test_supervise.py WEDGE_S precedent): the shard
# threads, the wave spin, and pytest's own workers share the box, so
# the fixed deadlines that are honest on >=4 cpus flake on tiny CI
# hosts — widen them there instead of everywhere
_FAST_BOX = (os.cpu_count() or 1) >= 4
JOIN_S = 10 if _FAST_BOX else 30
RESTORE_SPINS = 10_000 if _FAST_BOX else 40_000
REDISPATCH_S = 0.3 if _FAST_BOX else 1.0
RESTART_DELAY_S = 1.0 if _FAST_BOX else 3.0


def _genesis(n=N_GENESIS):
    from firedancer_tpu.tiles.synth import synth_signer_seed
    from firedancer_tpu.utils.ed25519_ref import keypair
    return {keypair(synth_signer_seed(i))[-1]: 1 << 44
            for i in range(n)}


def _slot_slices(txns, n_slots):
    """slot -> one complete slice carrying one entry batch (hand-built
    tip, PoH verify off — the bank-hash chain is what's under test)."""
    from firedancer_tpu.tiles.shred import pack_slice
    per = max(1, len(txns) // n_slots)
    out = {}
    for s in range(1, n_slots + 1):
        chunk = txns[(s - 1) * per:s * per]
        tip = hashlib.sha256(b"fo-tip-%d" % s).digest()
        batch = struct.pack("<I", 1) + tip + struct.pack("<I", len(chunk))
        for t in chunk:
            batch += struct.pack("<H", len(t)) + t
        out[s] = pack_slice(s, 0, True, batch)
    return out


def _mk_oracle(genesis):
    from firedancer_tpu.funk.funk import Funk
    from firedancer_tpu.tiles.replay import InlineFanout, ReplayCore
    funk = Funk()
    return ReplayCore(genesis=genesis, verify_poh=False, funk=funk,
                      fanout=InlineFanout(funk))


def _mk_follower(wksp, n_exec=2, redispatch_s=5.0, expected=None,
                 **core_kw):
    """ReplayCore + real ExecFanout over rings + n_exec ExecAdapters
    (the test_exec_tile harness shape, replay-side)."""
    from firedancer_tpu.disco.tiles import ExecAdapter, ExecFanout
    from firedancer_tpu.funk.shmfunk import WireFunk
    from firedancer_tpu.tiles.replay import ReplayCore
    st = Store(wksp, rec_max=4096, txn_max=64, heap_sz=1 << 20)
    funk_plan = {"backend": "shm", "rec_max": 4096, "txn_max": 64,
                 "heap_mb": 1, "off": st.off, "heap_sz": 1 << 20}
    links = {}
    for i in range(n_exec):
        links[f"exec_disp{i}"] = {"mtu": 4096}
        links[f"exec_done{i}"] = {"mtu": 64}
    rings = {ln: Ring.create(wksp, depth=64, mtu=li["mtu"])
             for ln, li in links.items()}
    plan = {"links": links, "funk": funk_plan}
    funk = WireFunk.from_plan(wksp, funk_plan)
    disp = [f"exec_disp{i}" for i in range(n_exec)]
    done = [f"exec_done{i}" for i in range(n_exec)]
    ctx = SimpleNamespace(
        tile_name="replay", plan=plan, wksp=wksp,
        in_rings={ln: rings[ln] for ln in done},
        out_rings={ln: rings[ln] for ln in disp},
        out_fseqs={ln: [] for ln in disp}, in_seq0={})
    fanout = ExecFanout(ctx, funk, disp, done,
                        m={"exec_waves": 0, "exec_redispatch": 0,
                           "overruns": 0},
                        redispatch_s=redispatch_s)
    core = ReplayCore(funk=funk, fanout=fanout, verify_poh=False,
                      expected=expected or {}, **core_kw)
    fanout.m = core.metrics
    execs = []
    for i in range(n_exec):
        ectx = SimpleNamespace(
            tile_name=f"exec{i}", plan=plan, wksp=wksp,
            in_rings={f"exec_disp{i}": rings[f"exec_disp{i}"]},
            out_rings={f"exec_done{i}": rings[f"exec_done{i}"]},
            out_fseqs={f"exec_done{i}": []}, in_seq0={})
        execs.append(ExecAdapter(ectx, {"batch": 8}))
    return core, execs, rings, funk


class _ShardThreads:
    """Poll exec adapters from background threads: ReplayCore's
    _execute_fanout spins the wave to completion on the caller's
    thread, so the shards must make progress concurrently (in the real
    topology they are separate processes)."""

    def __init__(self):
        self.stop = threading.Event()
        self.threads = []

    def run(self, adapter, delay_s=0.0):
        def loop():
            if delay_s:
                time.sleep(delay_s)
            while not self.stop.is_set():
                adapter.poll_once()
                time.sleep(1e-4)
        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self.threads.append(t)

    def join(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=JOIN_S)


@pytest.fixture()
def wksp():
    w = Workspace(f"/fdtpu_fol_{os.getpid()}", 1 << 24)
    yield w
    w.close()
    w.unlink()


def test_follower_cold_start_catchup_end_to_end(wksp, tmp_path):
    """Cold start from a ShmFunk snapshot -> snapld/snapin restore ->
    marker release -> multi-slot tail replayed over 2 exec shards ->
    bank hashes match the leader oracle's per-slot hashes, balances
    match exactly."""
    from firedancer_tpu.tiles.snapshot import (SnapInserter, SnapLoader,
                                               state_fingerprint)
    from firedancer_tpu.tiles.synth import make_signed_txns
    from firedancer_tpu.utils.checkpt import snapshot_write_atomic
    n_slots, snap_slot = 6, 2
    genesis = _genesis()
    txns = make_signed_txns(24, seed=41)
    slices = _slot_slices(txns, n_slots)

    oracle = _mk_oracle(genesis)
    snap_path = str(tmp_path / "snap.ckpt")
    want_fp = None
    for s in range(1, n_slots + 1):
        oracle.on_slice(slices[s])
        if s == snap_slot:
            snapshot_write_atomic(snap_path, oracle.funk, slot=s,
                                  bank_hash=oracle.bank_hash_of[s])
            want_fp = state_fingerprint(oracle.funk)
    assert oracle.metrics["txns"] == len(txns)

    expected = {s: oracle.bank_hash_of[s]
                for s in range(snap_slot + 1, n_slots + 1)}
    core, execs, rings, funk = _mk_follower(wksp, n_exec=2,
                                            expected=expected,
                                            wait_restore=True)
    # the tail arrives BEFORE the restore finishes (the catch-up race):
    # everything buffers behind the restore gate
    for s in range(snap_slot + 1, n_slots + 1):
        core.on_slice(slices[s])
    assert core.metrics["slots_replayed"] == 0
    assert core.metrics["buffered"] == n_slots - snap_slot
    assert not core.check_restore()

    # restore through the real snapld -> snapin cores over a ring
    snap_ring = Ring.create(wksp, depth=64, mtu=4096)
    loader = SnapLoader(snap_path, snap_ring, [], chunk=1024)
    inserter = SnapInserter(snap_ring, funk=funk, min_slot=snap_slot)
    for _ in range(RESTORE_SPINS):
        loader.poll_once()
        inserter.poll_once()
        if inserter.metrics["restored"]:
            break
    assert inserter.metrics["restored"] == 1
    assert inserter.metrics["slot"] == snap_slot
    # fingerprint of the restore == the oracle AT the snapshot slot
    assert inserter.metrics["fingerprint"] == want_fp

    shards = _ShardThreads()
    for e in execs:
        shards.run(e)
    try:
        # marker arrival seeds the chain and releases the buffered tail
        assert core.check_restore()
        assert core.metrics["restore_slot"] == snap_slot
        assert core.metrics["slots_replayed"] == n_slots - snap_slot
        assert core.metrics["divergent_slot"] == 0
        assert core.metrics["buffered"] == 0 and core.metrics["behind"] == 0
        assert core.metrics["exec_waves"] >= n_slots - snap_slot
    finally:
        shards.join()
    # the expected pins did not raise AND the hashes are the oracle's
    for s in range(snap_slot + 1, n_slots + 1):
        assert core.bank_hash_of[s] == oracle.bank_hash_of[s]
    # exactly-once balances across restore + fan-out replay
    for pk in genesis:
        assert funk.rec_query(None, pk) \
            == oracle.funk.rec_query(None, pk)
    # both shards carried work
    assert all(e.m["txns"] > 0 for e in execs)


def test_follower_divergence_verdict_names_first_slot(tmp_path):
    """A diverging block must flip divergent_slot and fail loudly
    naming the first divergent slot — before any tower publish."""
    from firedancer_tpu.funk.funk import Funk
    from firedancer_tpu.tiles.replay import InlineFanout, ReplayCore
    from firedancer_tpu.tiles.synth import make_signed_txns
    n_slots = 3
    genesis = _genesis()
    txns = make_signed_txns(12, seed=43)
    slices = _slot_slices(txns, n_slots)
    oracle = _mk_oracle(genesis)
    for s in range(1, n_slots + 1):
        oracle.on_slice(slices[s])

    funk = Funk()
    follower = ReplayCore(
        genesis=genesis, verify_poh=False, funk=funk,
        fanout=InlineFanout(funk),
        expected={s: oracle.bank_hash_of[s]
                  for s in range(1, n_slots + 1)})
    follower.on_slice(slices[1])
    assert follower.metrics["slots_replayed"] == 1
    follower._diverge_seed = 7          # the diverge_block chaos seam
    with pytest.raises(RuntimeError, match="divergence at slot 2"):
        follower.on_slice(slices[2])
    assert follower.metrics["divergent_slot"] == 2


def test_follower_exec_shard_death_redispatch(wksp):
    """Shard 0 dead at dispatch time: the wave cannot commit partial,
    the deadline forces cancel + whole-wave redispatch under a fresh
    fork, and once the shard rejoins (ring re-read from seq 0, stale
    frames abandoned) the wave completes — exactly-once balances, no
    wedge."""
    from firedancer_tpu.svm.executor import execute_block_serial
    from firedancer_tpu.tiles.synth import make_signed_txns
    n_slots = 1
    genesis = _genesis()
    txns = make_signed_txns(8, seed=47)
    slices = _slot_slices(txns, n_slots)
    oracle = _mk_oracle(genesis)
    oracle.on_slice(slices[1])

    core, execs, rings, funk = _mk_follower(
        wksp, n_exec=2, redispatch_s=REDISPATCH_S,
        expected={1: oracle.bank_hash_of[1]},
        genesis=genesis)
    shards = _ShardThreads()
    shards.run(execs[1])                 # shard 0 is dead...
    shards.run(execs[0], delay_s=RESTART_DELAY_S)   # ...until restart
    try:
        core.on_slice(slices[1])         # spins until the wave commits
    finally:
        shards.join()
    assert core.metrics["slots_replayed"] == 1
    assert core.metrics["exec_redispatch"] >= 1
    assert core.metrics["divergent_slot"] == 0
    assert core.bank_hash_of[1] == oracle.bank_hash_of[1]
    # exactly-once: despite cancelled attempts, balances match one
    # serial application (srcs AND the fresh dest accounts)
    oracle_bal = dict(_genesis().items())
    transfers, _ = core._extract_transfers(txns)
    execute_block_serial(oracle_bal, transfers)
    for pk, want in oracle_bal.items():
        got = funk.rec_query(None, pk)
        assert getattr(got, "lamports", got) == want
    # the restarted shard saw and abandoned the cancelled fork's frames
    assert execs[0].m["stale_xid"] >= 1
