"""Draw-for-draw Agave leader-schedule parity, pinned against the
reference's mainnet-beta epoch 454 fixtures (real cluster data, read
as binary TEST DATA from /root/reference/src/flamenco/leaders/fixtures
— the conformance oracle the reference's own test_leaders.c uses).

What this locks down (VERDICT r4 item 5, the interop blocker):
- rand_chacha ChaCha20Rng keystream consumption (8-byte LE reads),
- the epoch→seed derivation (LE u64 into a zeroed 32-byte key),
- rand 0.7 Uniform<u64> MODE_MOD widening-multiply rejection,
- WeightedIndex cumulative search boundary,
- the (stake desc, pubkey desc) consensus sort.
A single draw off anywhere diverges the remaining 108k-draw sequence,
so matching all 432000 slots is a byte-exact proof of the whole chain.
"""
import os
import struct

import pytest

from firedancer_tpu.flamenco.leaders import (EpochLeaders,
                                             INDETERMINATE_LEADER,
                                             WeightedSampler,
                                             epoch_seed, sort_stakes)
from firedancer_tpu.utils.chacha import ChaChaRng

FIXDIR = "/root/reference/src/flamenco/leaders/fixtures"
SLOT0 = 196_128_000              # epoch 454 * 432000
SPE = 432_000


def _load_fixtures():
    if not os.path.isdir(FIXDIR):
        pytest.skip("reference fixtures unavailable")
    raw = open(os.path.join(FIXDIR, "epoch-stakes-454.bin"), "rb").read()
    stakes = {}
    for off in range(0, len(raw), 40):
        key = raw[off:off + 32]
        stake = struct.unpack_from("<Q", raw, off + 32)[0]
        stakes[key] = stakes.get(key, 0) + stake
    idx = open(os.path.join(FIXDIR,
                            "epoch-leaders-idx-454.bin"), "rb").read()
    leaders_idx = struct.unpack("<%dI" % (len(idx) // 4), idx)
    pubs = open(os.path.join(FIXDIR,
                             "epoch-leaders-454.bin"), "rb").read()
    return stakes, leaders_idx, pubs


def test_epoch454_full_schedule_matches_mainnet():
    stakes, leaders_idx, pubs = _load_fixtures()
    assert len(stakes) == 3373 and len(leaders_idx) == SPE
    weighted = sort_stakes(stakes)
    sampler = WeightedSampler(weighted)
    rng = ChaChaRng(epoch_seed(454))
    n_rot = SPE // 4
    sched = [sampler.sample_idx(rng) for _ in range(n_rot)]
    # every slot index in the epoch, expanded by 4-slot rotation
    for slot in range(SPE):
        assert sched[slot // 4] == leaders_idx[slot], \
            f"diverged at slot {slot}"
    # and the first 10k slots byte-for-byte against the pubkey dump
    for i in range(len(pubs) // 32):
        assert weighted[sched[i // 4]][0] == pubs[32 * i:32 * i + 32], \
            f"pubkey mismatch at slot {i}"


def test_epoch454_via_epochleaders_api():
    stakes, leaders_idx, _ = _load_fixtures()
    el = EpochLeaders(454, None, stakes, SPE)
    weighted = sort_stakes(stakes)
    for slot in (0, 1, 3, 4, 999, 10_000, 431_999):
        assert el.leader_for(SLOT0 + slot) \
            == weighted[leaders_idx[slot]][0]


def test_excluded_stake_tail_maps_to_indeterminate():
    stakes, leaders_idx, _ = _load_fixtures()
    weighted = sort_stakes(stakes)
    short = len(weighted) // 2
    excluded = sum(s for _, s in weighted[short:])
    sampler = WeightedSampler(weighted[:short], excluded=excluded)
    rng = ChaChaRng(epoch_seed(454))
    for slot in range(0, 40_000, 4):
        got = sampler.sample_idx(rng)
        want = leaders_idx[slot]
        if want >= short:
            assert got >= short        # poison tail → indeterminate
        else:
            assert got == want
    assert len(INDETERMINATE_LEADER) == 32
