"""Keyswitch (live identity hot-swap) + logging subsystem tests
(ref: src/disco/keyguard/fd_keyswitch.h, set_identity command;
src/util/log/fd_log.h dual-sink discipline)."""
import pytest

pytestmark = pytest.mark.slow
import os

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.keyguard import KeyguardClient, keyswitch as ks
from firedancer_tpu.runtime import Ring
from firedancer_tpu.utils.ed25519_ref import keypair, verify

SEED_A = bytes(range(32))
SEED_B = bytes(range(32, 64))


def test_keyswitch_hot_swap_in_topology():
    """Sign tile switches identity live: signatures before the switch
    verify under key A, after under key B, with no restart."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"ks{os.getpid()}", wksp_size=1 << 22)
        .link("req", depth=16, mtu=1280)
        .link("rsp", depth=16, mtu=128)
        # declared producer for the req link; not started — the test
        # process drives the ring directly as the client
        .tile("driver", "synth", outs=["req"], count=0)
        .tile("sign", "sign", ins=[("req", False)], outs=["rsp"],
              seed=SEED_A.hex(),
              clients=[{"role": "leader", "req": "req", "resp": "rsp"}])
        .tile("sink", "sink", ins=[("rsp", False)])
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start(tiles=["sign"])
    try:
        runner.wait_running(timeout_s=120)
        li = plan["links"]
        req = Ring(runner.wksp, li["req"]["ring_off"], li["req"]["depth"],
                   li["req"]["arena_off"], li["req"]["mtu"])
        rsp = Ring(runner.wksp, li["rsp"]["ring_off"], li["rsp"]["depth"],
                   li["rsp"]["arena_off"], li["rsp"]["mtu"])
        client = KeyguardClient(req, rsp)
        _, _, pk_a = keypair(SEED_A)
        _, _, pk_b = keypair(SEED_B)

        root = os.urandom(32)
        sig = client.sign(root)
        assert sig and verify(sig, pk_a, root)

        ks_off = plan["tiles"]["sign"]["keyswitch_off"]
        ks.request_switch(runner.wksp, ks_off, SEED_B)
        assert ks.wait_completed(runner.wksp, ks_off, timeout_s=30)

        root2 = os.urandom(32)
        sig2 = client.sign(root2)
        assert sig2 and verify(sig2, pk_b, root2)
        assert not verify(sig2, pk_a, root2)
        assert runner.metrics("sign")["keyswitches"] == 1
        # the staged seed is scrubbed after the swap
        assert ks.read_state(runner.wksp, ks_off) == ks.STATE_COMPLETED
        assert bytes(runner.wksp.view(ks_off + 8, 32)) == bytes(32)
    finally:
        runner.halt()
        runner.close()


def test_log_dual_sink(tmp_path, capsys):
    from firedancer_tpu.utils import log
    path = tmp_path / "tile.log"
    log.init("test:tile", path=str(path), stderr_level=log.WARNING)
    log.debug("debug line")
    log.notice("notice line")
    log.err("error line")
    out = capsys.readouterr().err
    # stderr: only >= WARNING
    assert "error line" in out and "notice line" not in out
    # permanent sink: everything, thread-tagged
    body = path.read_text()
    for frag in ("debug line", "notice line", "error line",
                 "test:tile", str(os.getpid())):
        assert frag in body
    assert "DEBUG" in body and "ERR" in body
    log.init("test:tile")            # detach the file sink
