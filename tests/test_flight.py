"""fdflight (r19): durable flight-data archive — codec, segment
rotation/retention, torn-tail recovery, recorder equivalence, incident
bundles, and the post-mortem query surfaces.

The acceptance spine, pinned live:

* query-vs-live exactness: counters are archived as DELTAS with a zero
  baseline, so re-integrating the archive reproduces the live /metrics
  value EXACTLY — `fdflight --series --cumulative` is the same number
  the scrape showed, just durable.
* incident survivability: an SLO breach under seeded chaos seals a
  self-contained bundle (frames around the breach, saturating hop,
  embedded chrome trace); SIGKILL of every tile afterwards loses
  nothing — the bundle still exports to Perfetto from disk alone.
* torn tails are detected and dropped on read, never propagated.
"""
import json
import os
import signal
import time

import pytest

from firedancer_tpu.flight import (FLIGHT_DEFAULTS, FLIGHT_SOURCES,
                                   normalize_flight)
from firedancer_tpu.flight.archive import (ArchiveWriter, cumulative,
                                           incident_paths, read_frames,
                                           series, window_summary,
                                           write_atomic_json)
from firedancer_tpu.flight.codec import (FRAME_SZ, KIND_LINK,
                                         KIND_MARK, KIND_METRIC,
                                         KIND_SLO, KIND_TRACE,
                                         decode_frame, decode_frames,
                                         encode_frame)

pytestmark = pytest.mark.flight

os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------

def test_codec_roundtrip_all_fields():
    buf = encode_frame(KIND_METRIC, 123_456_789, 7, "verify", "rx",
                       -42, aux=1)
    assert len(buf) == FRAME_SZ
    fr = decode_frame(buf)
    assert fr == {"ts": 123_456_789, "node": 7, "kind": KIND_METRIC,
                  "kind_name": "metric", "ver": fr["ver"],
                  "source": "verify", "name": "rx", "value": -42,
                  "aux": 1}


def test_codec_names_truncate_utf8_safe():
    # a >16-byte name with a multibyte char straddling the cut must
    # not decode to mojibake or raise
    fr = decode_frame(encode_frame(KIND_METRIC, 1, 0,
                                   "tile_with_longéname",
                                   "m" * 40, 1))
    assert fr is not None
    assert len(fr["source"].encode()) <= 16
    assert fr["name"] == "m" * 16


def test_codec_rejects_short_corrupt_and_wrong_magic():
    buf = encode_frame(KIND_LINK, 5, 0, "a_b", "pub", 9)
    assert decode_frame(buf) is not None                  # sanity
    assert decode_frame(buf[:FRAME_SZ - 1]) is None       # torn tail
    corrupt = buf[:20] + bytes([buf[20] ^ 0x5A]) + buf[21:]
    assert decode_frame(corrupt) is None                  # bad CRC
    assert decode_frame(b"\x00" * FRAME_SZ) is None       # bad magic


def test_decode_frames_counts_torn_slots():
    good = encode_frame(KIND_METRIC, 1, 0, "t", "m", 1) \
        + encode_frame(KIND_METRIC, 2, 0, "t", "m", 2)
    frames, dropped = decode_frames(good + b"\xde\xad\xbe")
    assert [f["value"] for f in frames] == [1, 2]
    assert dropped == 1                                   # the partial
    frames, dropped = decode_frames(good[:FRAME_SZ] +
                                    b"\x00" * FRAME_SZ + good[FRAME_SZ:])
    assert [f["value"] for f in frames] == [1, 2]
    assert dropped == 1                                   # the bad slot


# ---------------------------------------------------------------------------
# [flight] schema
# ---------------------------------------------------------------------------

def test_normalize_flight_fills_defaults():
    cfg = normalize_flight({"dir": "/tmp/x"})
    assert set(cfg) == set(FLIGHT_DEFAULTS)
    assert cfg["hz"] == FLIGHT_DEFAULTS["hz"]


def test_normalize_flight_rejections():
    with pytest.raises(ValueError, match="segment_mb"):
        normalize_flight({"segmnt_mb": 4.0})              # did-you-mean
    with pytest.raises(ValueError):
        normalize_flight({"hz": 0})
    with pytest.raises(ValueError):
        normalize_flight({"hz": 2000})
    with pytest.raises(ValueError):
        normalize_flight({"segment_mb": 8.0, "retain_mb": 1.0})
    with pytest.raises(ValueError):
        normalize_flight({"dir": ""})
    with pytest.raises(ValueError):
        normalize_flight({"node_id": 1 << 16})
    with pytest.raises(ValueError, match="links"):
        normalize_flight({"sources": ["linkz"]})
    assert normalize_flight({"sources": list(FLIGHT_SOURCES)})


# ---------------------------------------------------------------------------
# archive writer: rotation, retention, atomicity
# ---------------------------------------------------------------------------

def test_segment_rotation_and_retention(tmp_path):
    d = str(tmp_path / "arch")
    # ~16 frames per segment, keep ~2 segments
    w = ArchiveWriter(d, segment_mb=0.001, retain_mb=0.002)
    n = 200
    for i in range(n):
        w.append(KIND_METRIC, 1000 + i, "t", "m", 1)
    w.close()
    assert w.frames == n
    assert w.rotations > 5
    assert w.aged_out > 0
    segs = [p for p in os.listdir(d) if p.endswith(".fdf")]
    # retention honored (active segment exempt, hence the slack)
    assert 0 < len(segs) <= 4
    frames, dropped = read_frames(d)
    assert dropped == 0
    # the tail of history survives in order; the head aged out
    assert [f["ts"] for f in frames] == sorted(f["ts"] for f in frames)
    assert frames[-1]["ts"] == 1000 + n - 1
    assert len(frames) < n


def test_retention_never_deletes_active_segment(tmp_path):
    d = str(tmp_path / "arch")
    w = ArchiveWriter(d, segment_mb=0.001, retain_mb=0.001)
    for i in range(40):
        w.append(KIND_METRIC, i, "t", "m", 1)
    w.flush()
    # the frame just written is always readable back
    frames, _ = read_frames(d)
    assert frames and frames[-1]["ts"] == 39
    w.close()


def test_torn_tail_dropped_on_read_after_kill(tmp_path):
    """A writer SIGKILLed mid-frame leaves a torn tail; readers must
    drop exactly the torn slot and keep everything before it."""
    d = str(tmp_path / "arch")
    w = ArchiveWriter(d)
    for i in range(10):
        w.append(KIND_METRIC, i, "t", "m", 1)
    w.flush()
    seg = w._f.name
    w.close()
    with open(seg, "ab") as f:     # simulate the torn final write
        f.write(encode_frame(KIND_METRIC, 99, 0, "t", "m", 1)[:17])
    frames, dropped = read_frames(d)
    assert len(frames) == 10 and dropped == 1
    assert all(f["ts"] != 99 for f in frames)


def test_write_atomic_json_no_partial(tmp_path):
    path = str(tmp_path / "inc.json")
    write_atomic_json(path, {"ok": 1})
    with open(path) as f:
        assert json.load(f) == {"ok": 1}
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]


# ---------------------------------------------------------------------------
# query helpers + CLI over a synthetic archive
# ---------------------------------------------------------------------------

def _synthetic_archive(d: str):
    """Two drain passes of metric deltas + a link + an SLO transition
    + marks: enough shape for every query surface."""
    w = ArchiveWriter(d, node_id=3)
    t0 = 1_000_000_000
    w.append(KIND_MARK, t0, "demo", "boot", 1)
    w.append(KIND_METRIC, t0 + 100, "sink", "rx", 5)       # delta
    w.append(KIND_LINK, t0 + 100, "a_b", "backpressure", 2)
    w.append(KIND_METRIC, t0 + 200, "sink", "rx", 7)       # delta
    w.append(KIND_METRIC, t0 + 200, "sink", "depth", 4, aux=1)  # gauge
    w.append(KIND_SLO, t0 + 250, "lat", "breach", 9, 1)
    w.append(KIND_MARK, t0 + 300, "demo", "halt", 1)
    w.close()
    return t0


def test_series_and_cumulative(tmp_path):
    d = str(tmp_path / "arch")
    t0 = _synthetic_archive(d)
    frames, dropped = read_frames(d)
    assert dropped == 0 and all(f["node"] == 3 for f in frames)
    pts = series(frames, "sink", "rx")
    assert pts == [(t0 + 100, 5), (t0 + 200, 7)]
    assert cumulative(pts) == [(t0 + 100, 5), (t0 + 200, 12)]
    summ = window_summary(frames)
    assert summ["metrics"]["sink.rx"]["total"] == 12


def test_fdflight_cli_summary_slice_series_diff(tmp_path, capsys):
    from firedancer_tpu.flight.cli import main
    d = str(tmp_path / "arch")
    t0 = _synthetic_archive(d)
    assert main([d]) == 0
    out = capsys.readouterr().out
    assert "7 frames" in out and "incidents: 0" in out
    # time-range slice to NDJSON: only the second pass
    assert main([d, "--since", str(t0 + 150), "--ndjson"]) == 0
    docs = [json.loads(ln) for ln in
            capsys.readouterr().out.splitlines()]
    assert {fr["name"] for fr in docs} >= {"rx", "depth"}
    assert all(fr["ts"] >= t0 + 150 for fr in docs)
    # series extraction, re-integrated
    assert main([d, "--series", "sink.rx", "--cumulative"]) == 0
    lines = capsys.readouterr().out.split()
    assert lines[-1] == "12"
    # kind filter + csv
    assert main([d, "--kind", "slo", "--csv"]) == 0
    assert "breach" in capsys.readouterr().out
    # window diff: pass 1 vs pass 2 rates
    assert main([d, "diff", f"{t0}:{t0 + 150}",
                 f"{t0 + 150}:{t0 + 300}"]) == 0
    assert "sink.rx" in capsys.readouterr().out


def test_monitor_archive_snapshots_reintegrates(tmp_path):
    """monitor --archive replays the archive as the same per-pass
    document shape `monitor --json` emits live — counters re-integrated
    so each doc equals what /metrics showed at that instant."""
    from firedancer_tpu.disco.monitor import archive_snapshots
    d = str(tmp_path / "arch")
    t0 = _synthetic_archive(d)
    docs = archive_snapshots(d)
    assert len(docs) == 2
    assert docs[0]["tiles"]["sink"]["rx"] == 5
    assert docs[1]["tiles"]["sink"]["rx"] == 12            # integrated
    assert docs[1]["tiles"]["sink"]["depth"] == 4          # level
    assert docs[0]["links"]["a_b"]["backpressure"] == 2
    # --since resumes after a cursor
    assert [d2["ts"] for d2 in archive_snapshots(d, since_ns=t0 + 100)] \
        == [t0 + 200]


def test_history_series_payload(tmp_path):
    from firedancer_tpu.gui.report import history_series
    d = str(tmp_path / "arch")
    t0 = _synthetic_archive(d)
    h = history_series(d)
    assert h["series"]["sink.rx"] == [[t0 + 100, 5], [t0 + 200, 12]]
    assert h["slo"] == [{"ts": t0 + 250, "target": "lat",
                         "kind": "breach", "value": 9}]
    assert [m["name"] for m in h["marks"]] == ["boot", "halt"]
    assert h["t0_ns"] == t0 and h["dropped"] == 0


# ---------------------------------------------------------------------------
# live: recorder equivalence + incident survivability (tier-1, no jax)
# ---------------------------------------------------------------------------

def test_recorder_archive_equals_live_metrics(tmp_path):
    """The exactness contract: counters ride as deltas with a zero
    baseline, so the re-integrated archive == the live /metrics value,
    not approximately — and the halt-path final drain catches the tail
    between the last housekeeping pass and shutdown."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    d = str(tmp_path / "arch")
    count = 900
    topo = (
        Topology(f"fleq{os.getpid()}", wksp_size=1 << 22,
                 flight={"dir": d, "hz": 100.0, "node_id": 5,
                         "incident_window_s": 0.0})
        .link("a_b", depth=64, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=count, unique=32,
              burst=16)
        .tile("b", "sink", ins=["a_b"])
        .tile("flight", "flight")
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        runner.wait_idle("b", "rx", count, timeout_s=120)
        live_rx = runner.metrics("b")["rx"]
        live_tx = runner.metrics("a")["tx"]
        deadline = time.time() + 30
        while runner.metrics("flight").get("frames", 0) == 0 \
                and time.time() < deadline:
            runner.check_failures()
            time.sleep(0.02)
        assert runner.metrics("flight")["drains"] > 0
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()
    frames, dropped = read_frames(d)
    assert dropped == 0
    assert all(f["node"] == 5 for f in frames)
    got_rx = sum(f["value"] for f in frames
                 if f["kind"] == KIND_METRIC and f["source"] == "b"
                 and f["name"] == "rx")
    got_tx = sum(f["value"] for f in frames
                 if f["kind"] == KIND_METRIC and f["source"] == "a"
                 and f["name"] == "tx")
    assert got_rx == live_rx == count                      # EXACT
    assert got_tx == live_tx
    marks = [f["name"] for f in frames if f["kind"] == KIND_MARK]
    assert marks[0] == "boot" and marks[-1] == "halt"


@pytest.mark.chaos
def test_slo_breach_seals_incident_that_survives_sigkill(tmp_path):
    """The r19 acceptance drill: seeded stall_fseq chaos drives an SLO
    breach; the flight tile seals a self-contained incident bundle;
    then every tile is SIGKILLed — and the bundle still lists, loads,
    and exports its embedded chrome trace via the fdflight CLI, with
    the archive's torn tail (if any) detected and dropped."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.disco.slo import slo_dump_path
    from firedancer_tpu.flight.cli import main as fdflight
    d = str(tmp_path / "arch")
    topo = (
        Topology(f"flinc{os.getpid()}", wksp_size=1 << 22,
                 trace={"enable": True, "depth": 1024, "sample": 1},
                 slo={"fast_window_s": 0.5, "slow_window_s": 10.0,
                      "target": [{
                          "name": "sink-bp",
                          "expr": "link.a_b.backpressure rate < 5/s"}]},
                 flight={"dir": d, "hz": 50.0,
                         "incident_window_s": 0.5})
        .link("a_b", depth=32, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=1_000_000, unique=16,
              burst=8)
        .tile("b", "sink", ins=["a_b"],
              chaos={"events": [{"action": "stall_fseq", "at_rx": 8}]})
        .tile("metric", "metric", port=0)
        .tile("flight", "flight")
    )
    runner = TopologyRunner(topo.build()).start()
    sealed = None
    try:
        runner.wait_running(timeout_s=540)
        deadline = time.time() + 60
        while time.time() < deadline:
            runner.check_failures()
            incs = incident_paths(d)
            if incs:
                sealed = incs[0]
                break
            time.sleep(0.05)
        assert sealed, (runner.metrics("metric"),
                        runner.metrics("flight"))
        # chaos half 2: SIGKILL every tile — no clean halt, no final
        # drain, the disk state is all that survives
        for p in runner.procs.values():
            if p.pid and p.is_alive():
                os.kill(p.pid, signal.SIGKILL)
        time.sleep(0.2)
    finally:
        runner.halt(join_timeout_s=5)
        runner.close()
        try:
            os.unlink(slo_dump_path(f"flinc{os.getpid()}", "sink-bp"))
        except OSError:
            pass
    with open(sealed) as f:
        doc = json.load(f)
    assert doc["target"] == "sink-bp" and doc["value"] > 0
    assert doc["slo_dump"]["kind"] == "breach"
    assert doc["saturating_hop"] == "a_b"
    # the ±window frames captured the damage around the breach
    bp = [f for f in doc["frames"] if f["kind"] == KIND_LINK
          and f["name"] == "backpressure"]
    assert bp and sum(f["value"] for f in bp) > 0
    assert any(f["kind"] == KIND_TRACE for f in doc["frames"])
    # chrome trace embedded at seal time -> exports with shm long gone
    out = str(tmp_path / "incident.chrome.json")
    assert fdflight([d, "--incident", os.path.basename(sealed),
                     "--out", out]) == 0
    with open(out) as f:
        chrome = json.load(f)
    assert chrome["traceEvents"]
    # the archive itself reads back post-SIGKILL; torn tails (the
    # killed writer's last partial frame) are dropped, not propagated
    frames, _dropped = read_frames(d)
    assert frames and any(f["kind"] == KIND_SLO and
                          f["name"] == "breach" for f in frames)
