"""solcap capture/diff — differential-debugging workflow
(ref: src/flamenco/capture/fd_solcap_writer.h, fd_solcap_diff.c)."""
import io
import struct

from firedancer_tpu.flamenco.solcap import (
    CapturingExecutor, CapWriter, diff, main as solcap_main, read_records,
)
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import AccDb, Account
from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID
from firedancer_tpu.svm.programs import OK, SYS_TRANSFER, TxnExecutor


def k(n):
    return bytes([n]) * 32


# transfers creating an account must leave it rent-exempt (the Agave
# check_rent_state discipline the executor enforces): 0-data minimum is
# 128 * 3480 * 2 = 890_880 lamports
EXEMPT = 890_880
A0, A1 = EXEMPT + 300, EXEMPT + 250
FUNDING = 10_000_000


def transfer_txn(src, dst, lamports):
    data = struct.pack("<IQ", SYS_TRANSFER, lamports)
    msg = build_message([src], [dst, SYSTEM_PROGRAM_ID], b"\x11" * 32,
                        [(2, bytes([0, 1]), data)])
    return build_txn([bytes(64)], msg)


def _run_ledger(amounts, fp):
    """Execute one block of transfers under capture; capture -> fp."""
    funk = Funk()
    funk.rec_write(None, k(1), Account(lamports=FUNDING))
    funk.txn_prepare(None, "blk")
    w = CapWriter(fp)
    cex = CapturingExecutor(TxnExecutor(AccDb(funk)), w)
    w.slot(7, b"\xAA" * 32)
    results = [cex.execute("blk", transfer_txn(k(1), k(2), a))
               for a in amounts]
    w.bank(b"\xBB" * 32)
    w.fini()
    return results


def test_capture_roundtrip_and_contents():
    fp = io.BytesIO()
    res = _run_ledger([A0, A1], fp)
    assert all(r.status == OK for r in res)
    fp.seek(0)
    recs = list(read_records(fp))
    kinds = [kd for kd, _ in recs]
    assert kinds == ["slot", "txn", "txn", "bank"]
    t0 = recs[1][1]
    assert t0["status"] == OK and t0["index"] == 0
    # pre/post for payer, dest, and the program account
    assert t0["pre"][k(2)] is None            # dest did not exist yet
    assert t0["post"][k(2)]["lamports"] == A0
    assert t0["pre"][k(1)]["lamports"] == FUNDING
    delta = t0["pre"][k(1)]["lamports"] - t0["post"][k(1)]["lamports"]
    assert delta == A0 + t0["fee"]


def test_identical_ledgers_diff_clean():
    fa, fb = io.BytesIO(), io.BytesIO()
    _run_ledger([A0, A1], fa)
    _run_ledger([A0, A1], fb)
    fa.seek(0), fb.seek(0)
    assert diff(fa, fb) is None


def test_divergent_execution_pinpointed():
    """One lamport of divergence in txn 1 must be reported at the
    account level for txn index 1 — the fd_solcap_diff workflow."""
    fa, fb = io.BytesIO(), io.BytesIO()
    _run_ledger([A0, A1], fa)
    _run_ledger([A0, A1 + 1], fb)
    fa.seek(0), fb.seek(0)
    d = diff(fa, fb)
    assert d is not None and d["slot"] == 7
    assert d["where"] in ("txn_payload", "account")
    assert d["txn"] == 1


def test_divergent_bank_hash_detected(tmp_path):
    fa, fb = io.BytesIO(), io.BytesIO()
    for fp, bh in ((fa, b"\xBB" * 32), (fb, b"\xCC" * 32)):
        w = CapWriter(fp)
        w.slot(9, b"\xAA" * 32)
        w.bank(bh)
        w.fini()
    fa.seek(0), fb.seek(0)
    d = diff(fa, fb)
    assert d["where"] == "bank_hash" and d["slot"] == 9
    # CLI round-trip: exit 1 + divergence line on stdout
    pa, pb = tmp_path / "a.cap", tmp_path / "b.cap"
    pa.write_bytes(fa.getvalue())
    pb.write_bytes(fb.getvalue())
    assert solcap_main(["diff", str(pa), str(pb)]) == 1
    assert solcap_main(["dump", str(pa)]) == 0


def test_v0_alut_txn_captures_looked_up_accounts():
    """A v0 transfer whose destination exists only via a lookup table:
    the capture must include the resolved key's pre/post state."""
    from firedancer_tpu.protocol.txn import build_message as bm
    from firedancer_tpu.svm.alut import (
        ALUT_PROGRAM_ID, derive_table_address, ix_create, ix_extend,
    )

    funk = Funk()
    funk.rec_write(None, k(1), Account(lamports=1 << 30))
    funk.txn_prepare(None, "blk")
    ex = TxnExecutor(AccDb(funk))
    ex.slot = 100

    def vtxn(extra, instrs, **kw):
        msg = bm([k(1)], extra, b"\x11" * 32, instrs, **kw)
        return build_txn([bytes(64)], msg)

    table, bump = derive_table_address(k(1), 90)
    assert ex.execute("blk", vtxn(
        [table, ALUT_PROGRAM_ID],
        [(2, bytes([1, 0]), ix_create(90, bump))],
        n_ro_unsigned=1)).status == OK
    looked_up = k(0x42)
    assert ex.execute("blk", vtxn(
        [table, ALUT_PROGRAM_ID],
        [(2, bytes([1, 0]), ix_extend([looked_up]))],
        n_ro_unsigned=1)).status == OK

    fp = io.BytesIO()
    w = CapWriter(fp)
    cex = CapturingExecutor(ex, w)
    w.slot(11, bytes(32))
    t = vtxn([SYSTEM_PROGRAM_ID],
             [(1, bytes([0, 2]),
               struct.pack("<IQ", SYS_TRANSFER, EXEMPT + 999))],
             n_ro_unsigned=1, version=0, aluts=[(table, bytes([0]), b"")])
    assert cex.execute("blk", t).status == OK
    w.bank(bytes(32))
    w.fini()
    fp.seek(0)
    trec = [v for kd, v in read_records(fp) if kd == "txn"][0]
    assert trec["pre"][looked_up] is None
    assert trec["post"][looked_up]["lamports"] == EXEMPT + 999


def test_pre_state_divergence_reported_at_first_txn():
    """A divergence that entered OUTSIDE txn execution (differing
    snapshot state) and is overwritten identically by execution must
    still be pinned to the first txn that saw it, phase=pre."""
    caps = []
    for initial in (1_000_000, 1_000_001):
        funk = Funk()
        funk.rec_write(None, k(1), Account(lamports=1_000_000))
        funk.rec_write(None, k(2), Account(lamports=initial))
        funk.txn_prepare(None, "blk")
        fp = io.BytesIO()
        w = CapWriter(fp)
        cex = CapturingExecutor(TxnExecutor(AccDb(funk)), w)
        w.slot(5, bytes(32))
        # CreateAccount-less absolute overwrite isn't available via
        # transfer, so make post identical by hand: drain k2 fully into
        # k1 then refund a fixed amount — post lamports equal either way
        # is NOT achievable with transfers alone; instead just touch k2
        # read-only via a 0-lamport transfer INTO it, leaving pre
        # divergent and post divergent too — the point is the report
        # must carry phase="pre" for the earliest divergent view.
        cex.execute("blk", transfer_txn(k(1), k(2), 0))
        w.bank(bytes(32))
        w.fini()
        fp.seek(0)
        caps.append(fp)
    d = diff(*caps)
    assert d["where"] == "account" and d["phase"] == "pre"
    assert d["txn"] == 0 and d["pubkey"] == k(2).hex()


def test_cli_missing_args_usage():
    assert solcap_main(["diff", "only_one.cap"]) == 2
    assert solcap_main(["dump"]) == 2
    assert solcap_main([]) == 2


def test_failed_txn_captured_with_rollback_state():
    """A failing instruction rolls state back; the capture must show
    post == pre except the fee debit (that is the differential signal
    the reference's solcap exists to catch)."""
    fp = io.BytesIO()
    funk = Funk()
    funk.rec_write(None, k(1), Account(lamports=10_000))
    funk.txn_prepare(None, "blk")
    w = CapWriter(fp)
    cex = CapturingExecutor(TxnExecutor(AccDb(funk)), w)
    w.slot(3, bytes(32))
    r = cex.execute("blk", transfer_txn(k(1), k(2), 50_000))  # overdraft
    w.bank(bytes(32))
    w.fini()
    assert r.status != OK
    fp.seek(0)
    trec = [v for kd, v in read_records(fp) if kd == "txn"][0]
    assert trec["status"] != OK
    assert trec["post"][k(2)] is None
    assert trec["post"][k(1)]["lamports"] == 10_000 - trec["fee"]
