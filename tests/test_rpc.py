"""JSON-RPC server tests (ref: src/discof/rpc/fd_rpc_tile.c subset)."""
import json
import urllib.request

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.rpc import RpcServer
from firedancer_tpu.svm import Account
from firedancer_tpu.utils.base58 import b58_encode_32


def call(port, method, params=None, rid=1):
    body = json.dumps({"jsonrpc": "2.0", "id": rid, "method": method,
                       "params": params or []}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_rpc_methods():
    funk = Funk()
    k1 = b"\x01" * 32
    k2 = b"\x02" * 32
    funk.rec_write(None, k1, Account(lamports=777, data=b"acct-data",
                                     owner=b"\x09" * 32, rent_epoch=3))
    funk.rec_write(None, k2, 1234)         # legacy int record
    srv = RpcServer(lambda: {"funk": funk, "slot": 42, "txn_count": 17})
    try:
        assert call(srv.port, "getHealth")["result"] == "ok"
        assert call(srv.port, "getSlot")["result"] == 42
        assert call(srv.port, "getTransactionCount")["result"] == 17

        r = call(srv.port, "getBalance", [b58_encode_32(k1)])
        assert r["result"]["value"] == 777
        assert r["result"]["context"]["slot"] == 42
        assert call(srv.port, "getBalance",
                    [b58_encode_32(k2)])["result"]["value"] == 1234
        assert call(srv.port, "getBalance",
                    [b58_encode_32(b"\x07" * 32)])["result"]["value"] == 0

        acct = call(srv.port, "getAccountInfo",
                    [b58_encode_32(k1)])["result"]["value"]
        assert acct["lamports"] == 777
        assert acct["rentEpoch"] == 3
        import base64
        assert base64.b64decode(acct["data"][0]) == b"acct-data"
        assert call(srv.port, "getAccountInfo",
                    [b58_encode_32(b"\x07" * 32)])["result"]["value"] is None

        err = call(srv.port, "noSuchMethod")
        assert err["error"]["code"] == -32601
        err = call(srv.port, "getBalance", ["not-base58!!!"])
        assert "error" in err
    finally:
        srv.close()


def test_get_version_and_epoch_info():
    import json
    import urllib.request

    from firedancer_tpu.funk.funk import Funk
    from firedancer_tpu.rpc import RpcServer
    funk = Funk()
    srv = RpcServer(lambda: {"funk": funk, "slot": 500_123,
                             "txn_count": 42}, port=0)
    try:
        def call(method):
            req = json.dumps({"jsonrpc": "2.0", "id": 1,
                              "method": method}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/", data=req,
                    headers={"Content-Type": "application/json"}),
                    timeout=30) as r:
                return json.loads(r.read())["result"]
        v = call("getVersion")
        assert "solana-core" in v
        e = call("getEpochInfo")
        assert e["epoch"] == 500_123 // 432_000
        assert e["absoluteSlot"] == 500_123
        assert e["transactionCount"] == 42
    finally:
        srv.close()



def test_extended_methods():
    """r4 additions: block height, latest blockhash, rent exemption,
    genesis hash, identity, supply."""
    funk = Funk()
    funk.rec_write(None, b"\x01" * 32, Account(lamports=500))
    funk.rec_write(None, b"\x02" * 32, 250)
    srv = RpcServer(lambda: {"funk": funk, "slot": 10,
                             "blockhash": b"\xab" * 32,
                             "identity": b"\xcd" * 32})
    try:
        p = srv.port
        assert call(p, "getBlockHeight")["result"] == 10
        bh = call(p, "getLatestBlockhash")["result"]
        assert bh["value"]["blockhash"] == b58_encode_32(b"\xab" * 32)
        assert bh["value"]["lastValidBlockHeight"] == 160
        from firedancer_tpu.svm.sysvars import rent_exempt_minimum
        assert call(p, "getMinimumBalanceForRentExemption",
                    [100])["result"] == rent_exempt_minimum(100)
        assert isinstance(call(p, "getGenesisHash")["result"], str)
        assert call(p, "getIdentity")["result"]["identity"] == \
            b58_encode_32(b"\xcd" * 32)
        sup = call(p, "getSupply")["result"]["value"]
        assert sup["total"] == 750 and sup["nonCirculating"] == 0
    finally:
        srv.close()


def test_get_vote_accounts():
    """getVoteAccounts over a genesis-built funk: stake resolves
    through the same aggregation consensus uses."""
    from firedancer_tpu.app.genesis import build_genesis
    funk, validators = build_genesis(n_validators=2, stake=750)
    srv = RpcServer(lambda: {"funk": funk, "slot": 200,
                             "slots_per_epoch": 100})
    try:
        r = call(srv.port, "getVoteAccounts")["result"]
        assert len(r["current"]) == 2 and r["delinquent"] == []
        for va in r["current"]:
            assert va["activatedStake"] == 750
            assert isinstance(va["votePubkey"], str)
            assert isinstance(va["nodePubkey"], str)
            assert va["commission"] >= 0
    finally:
        srv.close()


def test_leader_schedule_and_slot_leader():
    """getLeaderSchedule/getSlotLeader over a genesis funk: the same
    EpochLeaders consensus uses, rendered in the Solana shape."""
    from firedancer_tpu.app.genesis import build_genesis
    funk, validators = build_genesis(n_validators=3, stake=100)
    srv = RpcServer(lambda: {"funk": funk, "slot": 250,
                             "slots_per_epoch": 100})
    try:
        sched = call(srv.port, "getLeaderSchedule")["result"]
        assert sched and sum(len(v) for v in sched.values()) == 100
        leader = call(srv.port, "getSlotLeader")["result"]
        assert isinstance(leader, str) and len(leader) >= 32
        # the slot's leader appears at the right index in the schedule
        assert 50 in sched[leader] or any(
            250 % 100 in idxs for k, idxs in sched.items()
            if k == leader)
    finally:
        srv.close()
