"""Host txn executor + system program semantics tests
(ref: src/flamenco/runtime/program/fd_system_program.c:59-330,
fd_executor atomic-rollback + fee-first discipline)."""
import struct

import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import AccDb, Account
from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID
from firedancer_tpu.svm.programs import (
    ERR_ALREADY_IN_USE, ERR_FEE, ERR_HAS_DATA, ERR_INSUFFICIENT,
    ERR_INVALID_OWNER, ERR_MISSING_SIG, ERR_UNKNOWN_PROGRAM, OK,
    SYS_ALLOCATE, SYS_ASSIGN, SYS_CREATE_ACCOUNT, SYS_TRANSFER,
    TxnExecutor,
)

FEE = 5000


def k(n):
    return bytes([n]) * 32


def make_txn(signers, extra, instrs, n_ro_unsigned=0):
    """Unsigned-signature txn (executor doesn't re-verify sigs — the
    verify tile did; same split as the reference)."""
    msg = build_message(signers, extra, b"\x11" * 32, instrs,
                        n_ro_unsigned=n_ro_unsigned)
    return build_txn([bytes(64)] * len(signers), msg)


def sys_ix(prog_idx, accts, disc, *fields):
    data = struct.pack("<I", disc)
    for f in fields:
        data += f if isinstance(f, bytes) else struct.pack("<Q", f)
    return (prog_idx, bytes(accts), data)


@pytest.fixture
def env():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, k(1), Account(lamports=1_000_000))
    funk.txn_prepare(None, "blk")
    # legacy micro-balance vectors predate the rent-state
    # discipline; rent coverage lives in tests/test_rent.py +
    # the conformance vectors (enforce_rent defaults ON)
    return funk, db, TxnExecutor(db, enforce_rent=False)


def test_transfer_ok_and_fee(env):
    funk, db, ex = env
    # accounts: [payer k1, dest k2, program]
    txn = make_txn([k(1)], [k(2), SYSTEM_PROGRAM_ID],
                   [sys_ix(2, [0, 1], SYS_TRANSFER, 300)])
    r = ex.execute("blk", txn)
    assert r.status == OK and r.fee == FEE
    assert db.lamports("blk", k(1)) == 1_000_000 - FEE - 300
    assert db.lamports("blk", k(2)) == 300


def test_failed_instruction_rolls_back_but_charges_fee(env):
    funk, db, ex = env
    txn = make_txn([k(1)], [k(2), SYSTEM_PROGRAM_ID],
                   [sys_ix(2, [0, 1], SYS_TRANSFER, 100),
                    sys_ix(2, [0, 1], SYS_TRANSFER, 10**12)])
    r = ex.execute("blk", txn)
    assert r.status == ERR_INSUFFICIENT
    # first transfer rolled back; fee charged
    assert db.lamports("blk", k(1)) == 1_000_000 - FEE
    assert db.lamports("blk", k(2)) == 0
    assert any("insufficient lamports" in ln for ln in r.logs)


def test_fee_payer_insufficient(env):
    funk, db, ex = env
    funk.rec_write("blk", k(3), Account(lamports=10))
    txn = make_txn([k(3)], [k(2), SYSTEM_PROGRAM_ID],
                   [sys_ix(2, [0, 1], SYS_TRANSFER, 1)])
    r = ex.execute("blk", txn)
    assert r.status == ERR_FEE and r.fee == 0
    assert db.lamports("blk", k(3)) == 10


def test_create_account(env):
    funk, db, ex = env
    owner = k(9)
    txn = make_txn([k(1), k(5)], [SYSTEM_PROGRAM_ID],
                   [sys_ix(2, [0, 1], SYS_CREATE_ACCOUNT, 1000, 64,
                           owner)])
    r = ex.execute("blk", txn)
    assert r.status == OK
    acct = db.peek("blk", k(5))
    assert acct.lamports == 1000 and acct.owner == owner
    assert acct.data == bytes(64)
    # creating again: already in use
    funk.rec_write("blk", k(1),
                   Account(lamports=1_000_000))     # top up payer
    r2 = ex.execute("blk", txn)
    assert r2.status == ERR_ALREADY_IN_USE


def test_create_requires_both_signers(env):
    funk, db, ex = env
    txn = make_txn([k(1)], [k(5), SYSTEM_PROGRAM_ID],
                   [sys_ix(2, [0, 1], SYS_CREATE_ACCOUNT, 1000, 0,
                           k(9))])
    assert ex.execute("blk", txn).status == ERR_MISSING_SIG


def test_assign_and_allocate(env):
    funk, db, ex = env
    txn = make_txn([k(1)], [SYSTEM_PROGRAM_ID],
                   [sys_ix(1, [0], SYS_ALLOCATE, 32),
                    sys_ix(1, [0], SYS_ASSIGN, k(7))])
    r = ex.execute("blk", txn)
    assert r.status == OK
    acct = db.peek("blk", k(1))
    assert acct.data == bytes(32) and acct.owner == k(7)
    # now non-system-owned: further assigns refused
    txn2 = make_txn([k(1)], [SYSTEM_PROGRAM_ID],
                    [sys_ix(1, [0], SYS_ASSIGN, k(8))])
    assert ex.execute("blk", txn2).status == ERR_INVALID_OWNER


def test_transfer_from_data_account_refused(env):
    funk, db, ex = env
    funk.rec_write("blk", k(4), Account(lamports=500, data=b"state"))
    funk.rec_write("blk", k(1), Account(lamports=1_000_000))
    txn = make_txn([k(1), k(4)], [k(2), SYSTEM_PROGRAM_ID],
                   [sys_ix(3, [1, 2], SYS_TRANSFER, 10)])
    assert ex.execute("blk", txn).status == ERR_HAS_DATA


def test_unknown_program(env):
    funk, db, ex = env
    txn = make_txn([k(1)], [k(0x42)], [(1, bytes([0]), b"\x01")])
    assert ex.execute("blk", txn).status == ERR_UNKNOWN_PROGRAM


def test_fork_isolation(env):
    """Execution in a fork never leaks to the root until publish."""
    funk, db, ex = env
    txn = make_txn([k(1)], [k(2), SYSTEM_PROGRAM_ID],
                   [sys_ix(2, [0, 1], SYS_TRANSFER, 300)])
    assert ex.execute("blk", txn).status == OK
    assert db.lamports(None, k(2)) == 0
    funk.txn_publish("blk")
    assert db.lamports(None, k(2)) == 300


def test_transfer_from_foreign_owned_account_refused(env):
    # ADVICE r3: a signer must not drain a data-empty account that was
    # Assigned to another program (ref Agave ExternalAccountLamportSpend)
    funk, db, ex = env
    funk.rec_write("blk", k(4),
                   Account(lamports=500, owner=b"NotSystem" + bytes(23)))
    txn = make_txn([k(1), k(4)], [k(2), SYSTEM_PROGRAM_ID],
                   [sys_ix(3, [1, 2], SYS_TRANSFER, 100)])
    r = ex.execute("blk", txn)
    assert r.status == ERR_INVALID_OWNER
    assert db.lamports("blk", k(4)) == 500


def test_assign_requires_writable(env):
    funk, db, ex = env
    from firedancer_tpu.svm.programs import ERR_NOT_WRITABLE
    funk.rec_write("blk", k(5), Account(lamports=10))
    # signer 1 (k5) demoted to read-only via n_ro_signed=1
    txn = make_txn([k(1), k(5)], [SYSTEM_PROGRAM_ID],
                   [sys_ix(2, [1], SYS_ASSIGN, b"\x07" * 32)])
    msg_ro = build_message([k(1), k(5)], [SYSTEM_PROGRAM_ID],
                           b"\x11" * 32,
                           [(2, bytes([1]),
                             struct.pack("<I", SYS_ASSIGN) + b"\x07" * 32)],
                           n_ro_signed=1)
    r = ex.execute("blk", build_txn([bytes(64)] * 2, msg_ro))
    assert r.status == ERR_NOT_WRITABLE
    # writable form succeeds
    r2 = ex.execute("blk", txn)
    assert r2.status == OK


def test_compute_budget_limit_enforced(env):
    """SetComputeUnitLimit caps BPF execution through the shared txn
    meter (ref: fd_compute_budget_program.h -> VM budget)."""
    from firedancer_tpu.pack.cost import COMPUTE_BUDGET_PROGRAM_ID
    from firedancer_tpu.svm.programs import BPF_LOADER_ID, ERR_VM
    from firedancer_tpu.vm import asm
    funk, db, ex = env
    # ~3000-instruction spin loop then clean exit
    prog = asm("""
        mov64 r1, 1000
        jeq r1, 0, +2
        sub64 r1, 1
        ja -3
        mov64 r0, 0
        exit
    """)
    funk.rec_write("blk", k(7), Account(
        lamports=1, data=prog, owner=BPF_LOADER_ID, executable=True))
    cb_set_limit = bytes([2]) + (100).to_bytes(4, "little")  # 100 CU
    txn_capped = make_txn(
        [k(1)], [k(7), COMPUTE_BUDGET_PROGRAM_ID],
        [(2, [], cb_set_limit), (1, [], b"")], n_ro_unsigned=2)
    r = ex.execute("blk", txn_capped)
    assert r.status == ERR_VM                    # budget exhausted
    txn_free = make_txn([k(1)], [k(7)], [(1, [], b"")],
                        n_ro_unsigned=1)
    assert ex.execute("blk", txn_free).status == OK


def test_log_collector_truncates(env):
    from firedancer_tpu.svm.programs import LogCollector
    lc = LogCollector()
    for i in range(200):
        lc.append("x" * 100)
    assert lc[-1] == "Log truncated"
    assert sum(len(ln) for ln in lc[:-1]) <= LogCollector.MAX_BYTES
    n = len(lc)
    lc.append("more")                            # dropped after marker
    assert len(lc) == n


def _seed_ix(disc, *parts):
    out = struct.pack("<I", disc)
    for p in parts:
        if isinstance(p, tuple) and p[0] == "str":
            out += struct.pack("<Q", len(p[1])) + p[1]
        elif isinstance(p, bytes):
            out += p
        else:
            out += struct.pack("<Q", p)
    return out


def test_create_account_with_seed(env):
    from firedancer_tpu.svm.programs import (
        SYS_CREATE_WITH_SEED, create_with_seed,
    )
    funk, db, ex = env
    owner = k(9)
    derived = create_with_seed(k(1), b"vault", owner)
    ix = _seed_ix(SYS_CREATE_WITH_SEED, k(1), ("str", b"vault"),
                  5_000, 16, owner)
    txn = make_txn([k(1)], [derived, SYSTEM_PROGRAM_ID],
                   [(2, [0, 1], ix)], n_ro_unsigned=1)
    r = ex.execute("blk", txn)
    assert r.status == OK, r.status
    a = db.peek("blk", derived)
    assert a.lamports == 5_000 and a.owner == owner \
        and len(a.data) == 16
    # wrong derived address refused
    ix_bad = _seed_ix(SYS_CREATE_WITH_SEED, k(1), ("str", b"other"),
                      5_000, 16, owner)
    txn = make_txn([k(1)], [derived, SYSTEM_PROGRAM_ID],
                   [(2, [0, 1], ix_bad)], n_ro_unsigned=1)
    assert ex.execute("blk", txn).status == ERR_INVALID_OWNER


def test_transfer_with_seed(env):
    from firedancer_tpu.svm.programs import (
        SYS_CREATE_WITH_SEED, SYS_TRANSFER_WITH_SEED, create_with_seed,
    )
    funk, db, ex = env
    derived = create_with_seed(k(1), b"w", SYSTEM_PROGRAM_ID)
    ix = _seed_ix(SYS_CREATE_WITH_SEED, k(1), ("str", b"w"),
                  9_000, 0, SYSTEM_PROGRAM_ID)
    assert ex.execute("blk", make_txn(
        [k(1)], [derived, SYSTEM_PROGRAM_ID],
        [(2, [0, 1], ix)], n_ro_unsigned=1)).status == OK
    # move funds out of the derived account with only BASE's signature
    ixt = _seed_ix(SYS_TRANSFER_WITH_SEED, 2_500, ("str", b"w"),
                   SYSTEM_PROGRAM_ID)
    r = ex.execute("blk", make_txn(
        [k(1)], [derived, k(5), SYSTEM_PROGRAM_ID],
        [(3, [1, 0, 2], ixt)], n_ro_unsigned=1))
    assert r.status == OK, r.status
    assert db.lamports("blk", derived) == 6_500
    assert db.lamports("blk", k(5)) == 2_500


def test_nonce_lifecycle(env):
    from firedancer_tpu.svm.programs import (
        ERR_BAD_IX_DATA, NONCE_STATE_SZ, SYS_ADVANCE_NONCE,
        SYS_AUTHORIZE_NONCE, SYS_INIT_NONCE, SYS_WITHDRAW_NONCE,
        _parse_nonce,
    )
    funk, db, ex = env
    from firedancer_tpu.svm.sysvars import rent_exempt_minimum
    funk.rec_write("blk", k(4), Account(
        lamports=rent_exempt_minimum(NONCE_STATE_SZ) + 20_000,
        data=bytes(NONCE_STATE_SZ)))
    ex.slot = 9
    # init with k(1) as authority (account pre-allocated: the guard)
    r = ex.execute("blk", make_txn(
        [k(1), k(4)], [SYSTEM_PROGRAM_ID],
        [(2, [1], struct.pack("<I", SYS_INIT_NONCE) + k(1))],
        n_ro_unsigned=1))
    assert r.status == OK, r.status
    auth, d1 = _parse_nonce(db.peek("blk", k(4)).data)
    assert auth == k(1)
    # advance moves the durable nonce
    ex.slot = 10
    assert ex.execute("blk", make_txn(
        [k(1), k(4)], [SYSTEM_PROGRAM_ID],
        [(2, [1], struct.pack("<I", SYS_ADVANCE_NONCE))],
        n_ro_unsigned=1)).status == OK
    _, d2 = _parse_nonce(db.peek("blk", k(4)).data)
    assert d2 != d1
    # non-authority cannot advance
    funk.rec_write("blk", k(7), Account(lamports=1 << 30))
    r = ex.execute("blk", make_txn(
        [k(7), k(4)], [SYSTEM_PROGRAM_ID],
        [(2, [1], struct.pack("<I", SYS_ADVANCE_NONCE))],
        n_ro_unsigned=1))
    assert r.status == ERR_MISSING_SIG
    # authorize a new authority, then withdraw with it
    assert ex.execute("blk", make_txn(
        [k(1), k(4)], [SYSTEM_PROGRAM_ID],
        [(2, [1], struct.pack("<I", SYS_AUTHORIZE_NONCE) + k(7))],
        n_ro_unsigned=1)).status == OK
    r = ex.execute("blk", make_txn(
        [k(7), k(4)], [k(8), SYSTEM_PROGRAM_ID],
        [(3, [1, 2], struct.pack("<IQ", SYS_WITHDRAW_NONCE, 1_000))],
        n_ro_unsigned=1))
    assert r.status == OK, r.status
    assert db.lamports("blk", k(8)) == 1_000
    # an UNALLOCATED account refuses init (no signer -> no drain)
    funk.rec_write("blk", k(9), Account(lamports=5_000))
    from firedancer_tpu.svm.programs import ERR_INVALID_OWNER as EIO
    r = ex.execute("blk", make_txn(
        [k(1), k(9)], [SYSTEM_PROGRAM_ID],
        [(2, [1], struct.pack("<I", SYS_INIT_NONCE) + k(1))],
        n_ro_unsigned=1))
    assert r.status == EIO
    # same-slot double-advance refuses (nonce must move)
    ex.slot = 20
    assert ex.execute("blk", make_txn(
        [k(7), k(4)], [SYSTEM_PROGRAM_ID],
        [(2, [1], struct.pack("<I", SYS_ADVANCE_NONCE))],
        n_ro_unsigned=1)).status == OK
    r = ex.execute("blk", make_txn(
        [k(7), k(4)], [SYSTEM_PROGRAM_ID],
        [(2, [1], struct.pack("<I", SYS_ADVANCE_NONCE))],
        n_ro_unsigned=1))
    assert r.status == ERR_BAD_IX_DATA


def test_uninitialized_nonce_account_recoverable(env):
    """An allocated-but-never-initialized nonce account can withdraw
    with ITS OWN signature (no stuck funds), but never without it."""
    from firedancer_tpu.svm.programs import (
        NONCE_STATE_SZ, SYS_WITHDRAW_NONCE,
    )
    funk, db, ex = env
    funk.rec_write("blk", k(4), Account(lamports=7_000,
                                        data=bytes(NONCE_STATE_SZ)))
    # without the account's signature: refused
    r = ex.execute("blk", make_txn(
        [k(1)], [k(4), k(8), SYSTEM_PROGRAM_ID],
        [(3, [1, 2], struct.pack("<IQ", SYS_WITHDRAW_NONCE, 7_000))],
        n_ro_unsigned=1))
    assert r.status == ERR_INVALID_OWNER
    # with it: recoverable
    r = ex.execute("blk", make_txn(
        [k(1), k(4)], [k(8), SYSTEM_PROGRAM_ID],
        [(3, [1, 2], struct.pack("<IQ", SYS_WITHDRAW_NONCE, 7_000))],
        n_ro_unsigned=1))
    assert r.status == OK, r.status
    assert db.lamports("blk", k(8)) == 7_000
