"""Solana bincode wire-type tests: size pins against the well-known
Agave constants, round-trips, and runtime-state conversions
(ref: src/flamenco/types/fd_types.c generated codecs; sizes
StakeStateV2::size_of()==200, vote account size 3762)."""
import pytest

from firedancer_tpu.choreo.tower import TowerVote
from firedancer_tpu.flamenco import types as t
from firedancer_tpu.svm.stake import (
    EPOCH_NONE, ST_DELEGATED, StakeState,
)
from firedancer_tpu.svm.vote import VoteState


def k(n):
    return bytes([n]) * 32


def test_stake_state_size_pins():
    # the famous 200-byte stake account
    assert len(t.encode_stake_state("uninitialized")) == 200
    assert len(t.encode_stake_state("stake", staker=k(1),
                                    withdrawer=k(2))) == 200
    # unpadded content sizes: disc 4 + meta 120 (= 4+8+32+32+8+8+32
    # ... exactly Agave's Meta) + stake 73
    raw = t.Writer()
    raw.u32(2)
    assert len(t.encode_stake_state("initialized").rstrip(b"\x00")) \
        <= 4 + 120


def test_stake_state_roundtrip():
    b = t.encode_stake_state(
        "stake", rent_exempt_reserve=2282880, staker=k(3),
        withdrawer=k(4), voter=k(5), stake=7_000_000_000,
        activation_epoch=11, deactivation_epoch=(1 << 64) - 1,
        credits_observed=42, stake_flags=0)
    d = t.decode_stake_state(b)
    assert d["state"] == "stake"
    assert d["rent_exempt_reserve"] == 2282880
    assert d["voter"] == k(5) and d["stake"] == 7_000_000_000
    assert d["warmup_cooldown_rate"] == 0.25
    assert d["credits_observed"] == 42


def test_vote_state_size_pin_and_roundtrip():
    b = t.encode_vote_state(k(1), k(2), k(3), 5,
                            votes=[(100, 31), (101, 30)],
                            root_slot=99,
                            epoch_credits=[(7, 1000, 900)],
                            last_ts_slot=101, last_ts=1234567)
    assert len(b) == 3762                    # the vote account size
    d = t.decode_vote_state(b)
    assert d["node_pubkey"] == k(1)
    assert d["authorized_voter"] == k(2)
    assert d["authorized_withdrawer"] == k(3)
    assert d["commission"] == 5
    assert d["votes"] == [(100, 31), (101, 30)]
    assert d["root_slot"] == 99
    assert d["epoch_credits"] == [(7, 1000, 900)]
    assert d["last_ts"] == 1234567


def test_vote_instruction_roundtrip():
    b = t.encode_vote_instruction([5, 6, 7], k(9), timestamp=1700000000)
    d = t.decode_vote_instruction(b)
    assert d == {"slots": [5, 6, 7], "hash": k(9),
                 "timestamp": 1700000000}
    # layout spot-pin: u32 disc | u64 len | slots.. | hash | opt tag
    assert b[:4] == b"\x02\x00\x00\x00"
    assert b[4:12] == (3).to_bytes(8, "little")
    assert b[12:20] == (5).to_bytes(8, "little")
    b2 = t.encode_vote_instruction([1], k(1))
    assert b2[-1:] == b"\x00"                # None timestamp tag


def test_option_and_vec_edges():
    r = t.Reader(b"\x02")
    with pytest.raises(t.BincodeError):
        r.option(r.u64)                      # bad tag
    r = t.Reader((1 << 30).to_bytes(8, "little"))
    with pytest.raises(t.BincodeError):
        r.vec(r.u64)                         # absurd length
    with pytest.raises(t.BincodeError):
        t.Reader(b"\x01\x02").u64()          # truncated


def test_runtime_stake_conversion_roundtrip():
    st = StakeState(ST_DELEGATED, k(1), k(2), 1000, k(3), 5_000_000,
                    4, EPOCH_NONE)
    wire = t.stake_state_to_wire(st)
    assert len(wire) == 200
    back = t.stake_state_from_wire(wire)
    assert (back.state, back.staker, back.withdrawer,
            back.rent_reserve, back.voter, back.amount,
            back.activation_epoch, back.deactivation_epoch) == \
        (ST_DELEGATED, k(1), k(2), 1000, k(3), 5_000_000, 4, EPOCH_NONE)


def test_runtime_vote_conversion_roundtrip():
    vs = VoteState(k(1), k(2), k(3), 7)
    for v in ((10, 3), (12, 2), (13, 1)):
        vs.tower.votes.append(TowerVote(*v))
    vs.root_slot = 9
    vs.last_ts = 555
    wire = t.vote_state_to_wire(vs)
    assert len(wire) == 3762
    back = t.vote_state_from_wire(wire)
    assert back.node_pubkey == k(1)
    assert back.authorized_voter == k(2)
    assert [(v.slot, v.conf) for v in back.tower.votes] == \
        [(10, 3), (12, 2), (13, 1)]
    assert back.root_slot == 9 and back.last_ts == 555
