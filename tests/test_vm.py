"""sBPF VM tests: ISA semantics, memory-map faults, calls/stack,
compute budget, syscalls (ref: src/flamenco/vm/fd_vm_interp_core.c,
test tiers per src/flamenco/vm/test_vm_interp.c)."""
import hashlib

import pytest

from firedancer_tpu.vm import (
    DEFAULT_SYSCALLS, ERR_ABORT, ERR_BUDGET, ERR_DEPTH, ERR_DIV0,
    ERR_NONE, ERR_OOB, ERR_SYSCALL, INPUT_START, Vm, asm, syscall_id,
)


def run(src, **kw):
    vm = Vm(asm(src), syscalls=DEFAULT_SYSCALLS, **kw)
    return vm.run()


def test_alu64_basics():
    r = run("""
        mov64 r1, 7
        add64 r1, 5
        mul64 r1, 3          // 36
        mov64 r2, 5
        div64 r1, r2         // 7
        lsh64 r1, 4          // 112
        or64 r1, 1
        xor64 r1, 2          // 115
        mov64 r0, r1
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 115


def test_alu32_truncates():
    r = run("""
        lddw r1, 0x1FFFFFFFF
        add32 r1, 1          // truncates to 32 bits: 0
        mov64 r0, r1
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 0


def test_neg_arsh_signed():
    r = run("""
        mov64 r1, 16
        neg64 r1             // -16
        arsh64 r1, 2         // -4
        mov64 r0, r1
        exit
    """)
    assert r.error == ERR_NONE
    assert r.r0 == (-4) & ((1 << 64) - 1)


def test_byteswap():
    r = run("""
        lddw r1, 0x1122334455667788
        be r1, 64
        mov64 r0, r1
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 0x8877665544332211


def test_div_by_zero_faults():
    r = run("mov64 r1, 1; mov64 r2, 0; div64 r1, r2; exit")
    assert r.error == ERR_DIV0
    r = run("mov64 r1, 1; mov64 r2, 0; mod64 r1, r2; exit")
    assert r.error == ERR_DIV0


def test_jumps_signed_unsigned():
    # -1 unsigned-gt 1, but signed-lt 1
    r = run("""
        mov64 r1, 0
        sub64 r1, 1          // r1 = -1
        mov64 r2, 1
        mov64 r0, 0
        jgt r1, r2, +1       // taken (unsigned)
        exit
        add64 r0, 1
        jslt r1, r2, +1      // taken (signed)
        exit
        add64 r0, 2
        mov64 r0, r0
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 3


def test_stack_load_store_and_guard():
    r = run("""
        mov64 r1, 0x1234
        stxdw [r10-8], r1
        ldxdw r0, [r10-8]
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 0x1234
    # writing above the frame pointer crosses into the guard gap
    r = run("mov64 r1, 1; stxdw [r10+16], r1; exit")
    assert r.error == ERR_OOB


def test_input_region_rw():
    vm = Vm(asm("""
        ldxw r0, [r1+0]
        add64 r0, 1
        stxw [r1+4], r0
        exit
    """), input_data=(41).to_bytes(4, "little") + bytes(4))
    r = vm.run()
    assert r.error == ERR_NONE and r.r0 == 42
    assert vm.mem_read(INPUT_START + 4, 4) == (42).to_bytes(4, "little")


def test_rodata_not_writable():
    r = run("mov64 r1, 1; lddw r2, 0x100000000; stxdw [r2+0], r1; exit")
    assert r.error == ERR_OOB


def test_internal_call_and_shadow_regs():
    """call_fn saves r6..r9 + frame pointer; callee clobbers r6 and
    uses its own stack frame; caller's r6 survives."""
    r = run("""
        mov64 r6, 7
        mov64 r1, 5
        call_fn 5
        add64 r0, r6         // r6 restored: +7
        exit
        mov64 r6, 99         // callee clobbers
        stxdw [r10-8], r1
        ldxdw r0, [r10-8]    // callee frame works
        add64 r0, 10         // r0 = 15
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 22


def test_recursion_depth_limit():
    r = run("call_fn 0; exit")            # infinite self-call
    assert r.error == ERR_DEPTH


def test_compute_budget():
    r = run("ja -1", compute_budget=1000)  # infinite loop
    assert r.error == ERR_BUDGET
    assert r.compute_used == 1001


def test_syscalls_log_memops_sha():
    msg = b"hello vm"
    sid_log = syscall_id(b"sol_log_")
    sid_sha = syscall_id(b"sol_sha256")
    vm = Vm(asm(f"""
        // log the first 8 input bytes
        mov64 r1, r1
        mov64 r2, 8
        call {sid_log}
        // sha256 of one slice (vaddr=INPUT, len=8); slice vec on stack
        lddw r1, {INPUT_START}
        stxdw [r10-32], r1
        mov64 r1, 8
        stxdw [r10-24], r1
        mov64 r1, r10
        add64 r1, -32
        mov64 r2, 1
        lddw r3, {INPUT_START + 16}
        call {sid_sha}
        mov64 r0, 0
        exit
    """), input_data=msg + bytes(56), syscalls=DEFAULT_SYSCALLS)
    r = vm.run()
    assert r.error == ERR_NONE
    assert r.log == ["hello vm"]
    assert vm.mem_read(INPUT_START + 16, 32) == \
        hashlib.sha256(msg).digest()


def test_abort_and_unknown_syscall():
    r = run(f"call {syscall_id(b'abort')}; exit")
    assert r.error == ERR_ABORT
    r = run("call 0xdeadbeef; exit")
    assert r.error == ERR_SYSCALL


def test_callx():
    r = run("""
        lddw r3, 0x100000028   // instruction 5 (lddw spans slots 0-1)
        callx r3
        add64 r0, 1
        exit
        mov64 r0, 41
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 42


@pytest.mark.parametrize("prog,err", [
    ("ldxdw r0, [r1+4096]", ERR_OOB),            # past input end
    ("mov64 r1, 0; ldxdw r0, [r1+0]", ERR_OOB),  # null deref
])
def test_memory_faults(prog, err):
    vm = Vm(asm(prog + "; exit"), input_data=bytes(8))
    assert vm.run().error == err


def test_jmp32_compares_low_bits():
    # jeq32 sees only the low 32 bits; jeq sees all 64
    r = run("""
        lddw r1, 0x100000007
        mov64 r0, 0
        jeq32 r1, 7, +1
        exit
        mov64 r0, 1          // taken: low word == 7
        jeq r1, 7, +2
        mov64 r2, 1          // not taken for 64-bit compare
        exit
        mov64 r0, 99
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 1


def test_jmp32_signed():
    # -1 (32-bit) is signed-less-than 0 under jslt32, but its zero-
    # extended 64-bit form 0xFFFFFFFF is NOT signed-less-than 0
    r = run("""
        lddw r1, 0xFFFFFFFF
        mov64 r0, 0
        jslt r1, 0, +3
        jslt32 r1, 0, +1
        exit
        mov64 r0, 1
        exit
        mov64 r0, 99
        exit
    """)
    assert r.error == ERR_NONE and r.r0 == 1


def test_syscall_raising_becomes_typed_fault():
    # ADVICE r3: a buggy syscall must not escape run() as a raw
    # exception — it converts to ERR_ABORT
    def boom(vm, *a):
        raise RuntimeError("bug in syscall")
    vm = Vm(asm("call 0x99\nexit"), syscalls={0x99: boom})
    r = vm.run()
    assert r.error == ERR_ABORT


def test_tracer_captures_instructions_and_disasm():
    """vm/trace.py: per-instruction capture with mnemonics, bounded
    ring (ref: src/flamenco/vm/fd_vm_trace.c, fd_vm_disasm.c)."""
    from firedancer_tpu.vm.trace import Tracer, disasm
    from firedancer_tpu.vm.asm import asm
    prog = asm("""
        mov64 r1, 7
        mov64 r2, 5
        add64 r1, r2
        lsh64 r1, 1
        exit
    """)
    vm = Vm(prog)
    tr = Tracer(limit=3).attach(vm)
    res = vm.run()
    assert res.error == ERR_NONE and res.r0 == 0
    assert tr.count == 5
    assert len(tr.entries) == 3              # bounded ring kept newest
    assert tr.entries[-1].text == "exit"
    assert tr.entries[0].text == "add64 r1, r2"
    # regs snapshot is pre-execution
    assert tr.entries[0].regs[1] == 7 and tr.entries[0].regs[2] == 5
    assert tr.entries[1].regs[1] == 12       # after the add
    # disasm spot checks
    assert disasm(asm("jeq r3, 9, +4")) == "jeq r3, 9, +4"
    assert disasm(asm("ldxdw r2, [r1+8]")) == "ldxdw r2, [r1+8]"
    assert disasm(asm("stxw [r10-4], r3")) == "stxw [r10-4], r3"
    assert "format" and tr.format(2).count("\n") == 1
