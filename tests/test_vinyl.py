"""Vinyl disk store tests: log-structured ops, crash recovery with a
torn tail, compaction, and the funk root round-trip
(ref: src/vinyl/fd_vinyl.h:13-29 SYNC/GC verbs, bstream recovery)."""
import os

import numpy as np
import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm.accdb import Account
from firedancer_tpu.vinyl import Vinyl


def k(n):
    return bytes([n]) * 32


def test_basic_ops_and_reopen(tmp_path):
    p = str(tmp_path / "v.log")
    v = Vinyl(p)
    v.put(k(1), b"one")
    v.put(k(2), b"two")
    v.put(k(1), b"one-v2")            # overwrite
    v.delete(k(2))
    assert v.get(k(1)) == b"one-v2"
    assert v.get(k(2)) is None
    assert len(v) == 1
    v.sync()
    v.close()
    # reopen: index rebuilt from the log
    v2 = Vinyl(p)
    assert v2.get(k(1)) == b"one-v2"
    assert v2.get(k(2)) is None
    assert len(v2) == 1
    v2.close()


def test_randomized_model_vs_dict(tmp_path):
    p = str(tmp_path / "m.log")
    v = Vinyl(p)
    rng = np.random.default_rng(3)
    model = {}
    for _ in range(500):
        op = rng.integers(0, 3)
        key = bytes([int(rng.integers(0, 24))]) * 32
        if op < 2:
            val = rng.bytes(int(rng.integers(0, 200)))
            v.put(key, val)
            model[key] = val
        else:
            v.delete(key)
            model.pop(key, None)
    for key in (bytes([i]) * 32 for i in range(24)):
        assert v.get(key) == model.get(key)
    # survives reopen
    v.close()
    v2 = Vinyl(p)
    for key in (bytes([i]) * 32 for i in range(24)):
        assert v2.get(key) == model.get(key)
    v2.close()


def test_torn_tail_recovery(tmp_path):
    p = str(tmp_path / "t.log")
    v = Vinyl(p)
    v.put(k(1), b"alpha")
    v.put(k(2), b"beta")
    v.sync()
    v.close()
    # simulate a crash mid-append: garbage + partial record at the tail
    with open(p, "ab") as f:
        f.write(b"\xde\xad\xbe")
    v2 = Vinyl(p)
    assert v2.get(k(1)) == b"alpha"
    assert v2.get(k(2)) == b"beta"
    # the torn tail was truncated: new writes land cleanly
    v2.put(k(3), b"gamma")
    v2.close()
    v3 = Vinyl(p)
    assert v3.get(k(3)) == b"gamma"
    assert len(v3) == 3
    v3.close()


def test_corrupt_record_crc_stops_scan(tmp_path):
    p = str(tmp_path / "c.log")
    v = Vinyl(p)
    v.put(k(1), b"keepme")
    off2 = v.index[k(1)][1]           # second record starts here
    v.put(k(2), b"corruptme")
    v.close()
    raw = bytearray(open(p, "rb").read())
    raw[off2 + 20] ^= 0xFF            # flip a byte inside record 2
    open(p, "wb").write(bytes(raw))
    v2 = Vinyl(p)
    assert v2.get(k(1)) == b"keepme"
    assert v2.get(k(2)) is None       # bad CRC: record dropped
    v2.close()


def test_compaction_reclaims_dead_bytes(tmp_path):
    p = str(tmp_path / "g.log")
    v = Vinyl(p)
    for i in range(50):
        v.put(k(1), bytes(100) + bytes([i]))     # 50 overwrites
    v.put(k(2), b"live")
    size_before = os.path.getsize(p)
    assert v.dead_bytes > 0
    v.compact()
    assert os.path.getsize(p) < size_before
    assert v.dead_bytes == 0
    assert v.get(k(1))[-1] == 49
    assert v.get(k(2)) == b"live"
    # reopen after compaction
    v.close()
    v2 = Vinyl(p)
    assert v2.get(k(1))[-1] == 49 and v2.get(k(2)) == b"live"
    v2.close()


def test_maybe_compact_threshold(tmp_path):
    p = str(tmp_path / "h.log")
    v = Vinyl(p)
    v.put(k(1), bytes(1000))
    for _ in range(10):
        v.put(k(1), bytes(1000))
    assert v.dead_bytes > v.live_bytes
    v.maybe_compact(gc_thresh=0.5)
    assert v.dead_bytes == 0
    v.close()


def test_funk_root_roundtrip(tmp_path):
    p = str(tmp_path / "f.log")
    from firedancer_tpu.vinyl.vinyl import load_root, store_root
    funk = Funk()
    funk.rec_write(None, k(1), Account(lamports=5, data=b"xy",
                                       owner=k(9)))
    funk.rec_write(None, k(2), Account(lamports=7))
    funk.rec_write(None, k(3), 12345)            # plain u64 record
    v = Vinyl(p)
    store_root(funk, v)
    v.close()

    funk2 = Funk()
    v2 = Vinyl(p)
    load_root(funk2, v2)
    a = funk2.rec_query(None, k(1))
    assert a.lamports == 5 and a.data == b"xy" and a.owner == k(9)
    assert funk2.rec_query(None, k(2)).lamports == 7
    assert funk2.rec_query(None, k(3)) == 12345
    v2.close()


def test_load_root_refuses_short_disk_keys(tmp_path):
    """An on-disk vinyl record with a non-32-byte key must refuse to
    restore: installed under a garbage-extended native key, no other
    process could ever derive it (the r17 follower-gate wedge class)."""
    from firedancer_tpu.utils.checkpt import _enc_val
    from firedancer_tpu.vinyl import VinylError
    from firedancer_tpu.vinyl.vinyl import load_root, store_root
    p = str(tmp_path / "short.log")
    v = Vinyl(p)
    v.put(b"root", _enc_val(7))          # hand-written short key
    v.sync()
    funk = Funk()
    with pytest.raises(VinylError, match="4-byte record key"):
        load_root(funk, v)
    assert funk.root_items() == {}       # nothing installed
    v.close()
    # store_root normalizes through key32: a short in-memory key is a
    # hard error at the write side too
    funk2 = Funk()
    funk2.rec_write(None, k(1), 1)
    v2 = Vinyl(str(tmp_path / "ok.log"))
    store_root(funk2, v2)
    assert v2.get(k(1)) is not None
    v2.close()
