"""Topology/stem/launcher tests: a multi-process pipeline driven purely
by a declarative topology description.

Reference tiers mirrored: multi-process tango shell tests
(src/tango/test_ipc_full), the topology builder + launcher
(src/disco/topo/), fail-fast supervision (src/app/shared/commands/run/
run.c:925 — any tile death kills the topology), and the monitor
(src/app/shared/commands/monitor/monitor.c).
"""
import os

import pytest

pytestmark = pytest.mark.slow

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.disco.monitor import attach, snapshot, format_table

N_UNIQUE = 24
N_SENT = 48


@pytest.fixture(scope="module")
def pipeline():
    """synth -> verify -> dedup -> sink, four OS processes.

    verify's local tcache is tiny (depth 8 < 24 unique txns), so the
    second round of duplicates survives verify and must be caught by the
    global dedup tile — exercising both dedup layers distinctly."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"t{os.getpid()}", wksp_size=1 << 24)
        .link("synth_verify", depth=64, mtu=1280)
        .link("verify_dedup", depth=64, mtu=1280)
        .link("dedup_sink", depth=64, mtu=1280)
        .tcache("verify_tc", depth=8)
        .tcache("dedup_tc", depth=4096)
        .tile("synth", "synth", outs=["synth_verify"],
              count=N_SENT, unique=N_UNIQUE, seed=3)
        .tile("verify", "verify", ins=["synth_verify"],
              outs=["verify_dedup"], batch=32, tcache="verify_tc")
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_sink"],
              tcache="dedup_tc")
        .tile("sink", "sink", ins=["dedup_sink"])
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    yield runner
    runner.halt()
    runner.close()


def test_pipeline_end_to_end(pipeline):
    import time
    pipeline.wait_running(timeout_s=540)
    # all 48 sent; 24 unique reach the sink exactly once; the 24 dups
    # are dropped across the TWO dedup layers: verify's ha-dedup (its
    # depth-8 tcache leaks evicted tags, but the r6 in-flight
    # reservation catches dups inside the async pipeline window — the
    # layer split is timing-dependent) and the global dedup tile, which
    # must drop every leaked duplicate. Wait on drop CONSERVATION (the
    # final dup is dropped only after all 48 sends flowed through).
    deadline = time.time() + 540
    while time.time() < deadline:
        pipeline.check_failures()
        v = pipeline.metrics("verify")
        d = pipeline.metrics("dedup")
        if v["dedup_drop"] + d["dup"] >= N_SENT - N_UNIQUE:
            break
        time.sleep(0.05)
    pipeline.wait_idle("sink", "rx", N_UNIQUE, timeout_s=60)
    assert pipeline.metrics("synth")["tx"] == N_SENT
    v = pipeline.metrics("verify")
    assert v["rx"] == N_SENT
    assert v["verify_fail"] == 0
    d = pipeline.metrics("dedup")
    # no loss, no duplication: every dup dropped exactly once,
    # somewhere; every unique forwarded exactly once, everywhere
    assert v["dedup_drop"] + d["dup"] == N_SENT - N_UNIQUE
    assert v["tx"] == N_SENT - v["dedup_drop"] == d["rx"]
    assert d["tx"] == N_UNIQUE
    assert pipeline.metrics("sink")["rx"] == N_UNIQUE


def test_monitor_snapshot(pipeline):
    plan, wksp = attach(pipeline.plan["topology"])
    try:
        snap = snapshot(plan, wksp)
        assert set(snap) == {"synth", "verify", "dedup", "sink"}
        assert snap["verify"]["state"] == "run"
        assert snap["sink"]["metrics"]["rx"] == N_UNIQUE
        table = format_table(snap)
        assert "verify" in table and "rx=" in table
    finally:
        wksp.close()


def test_heartbeats_live(pipeline):
    import time
    hb1 = pipeline.heartbeats()
    time.sleep(0.1)
    hb2 = pipeline.heartbeats()
    assert set(hb1) == {"synth", "verify", "dedup", "sink"}
    # ages stay bounded (tiles heartbeat every ~10ms housekeeping)
    for tn, age in hb2.items():
        assert age < 2_000_000_000, f"{tn} heartbeat stalled"


def test_fail_fast_on_tile_death():
    """A tile whose kind cannot be constructed dies at boot; the
    supervisor must detect it and tear the topology down."""
    topo = (
        Topology(f"tf{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=16, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=4, unique=4)
        .tile("b", "nosuch_kind", ins=["a_b"])
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        with pytest.raises(RuntimeError, match="died"):
            for _ in range(3000):
                runner.check_failures()
                import time
                time.sleep(0.01)
            raise AssertionError("supervisor never noticed dead tile")
    finally:
        runner.halt(join_timeout_s=5)
        runner.close()


def test_leader_pipeline_with_pack_and_banks():
    """Full leader hot path: synth -> verify -> dedup -> pack ->
    2 parallel bank tiles -> completion links back to pack.
    (ref wiring: src/app/fdctl/topology.c:88-113 — quic_verify ->
    verify_dedup -> dedup_pack -> pack_bank -> bank_poh)."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    n = 24
    topo = (
        Topology(f"tl{os.getpid()}", wksp_size=1 << 24)
        .link("synth_verify", depth=64, mtu=1280)
        .link("verify_dedup", depth=64, mtu=1280)
        .link("dedup_pack", depth=64, mtu=1280)
        .link("pack_bank0", depth=16, mtu=8192)
        .link("pack_bank1", depth=16, mtu=8192)
        .link("bank0_done", depth=16, mtu=64)
        .link("bank1_done", depth=16, mtu=64)
        .tcache("verify_tc", depth=4096)
        .tcache("dedup_tc", depth=4096)
        .tile("synth", "synth", outs=["synth_verify"],
              count=n, unique=n, seed=5)
        .tile("verify", "verify", ins=["synth_verify"],
              outs=["verify_dedup"], batch=32, tcache="verify_tc")
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_pack"],
              tcache="dedup_tc")
        .tile("pack", "pack",
              ins=["dedup_pack", "bank0_done", "bank1_done"],
              outs=["pack_bank0", "pack_bank1"],
              txn_in="dedup_pack",
              bank_links=["pack_bank0", "pack_bank1"],
              done_links=["bank0_done", "bank1_done"],
              max_txn_per_microblock=4)
        .tile("bank0", "bank", ins=["pack_bank0"], outs=["bank0_done"])
        .tile("bank1", "bank", ins=["pack_bank1"], outs=["bank1_done"])
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=540)
        runner.wait_idle("pack", "scheduled", n, timeout_s=540)
        runner.wait_idle("pack", "completions", 1, timeout_s=60)
        p = runner.metrics("pack")
        assert p["inserted"] == n
        assert p["parse_fail"] == 0
        # every scheduled microblock eventually completes
        runner.wait_idle("pack", "completions", p["microblocks"],
                         timeout_s=60)
        # bank shm metrics flush one housekeeping interval behind the
        # completion frags — poll, don't snapshot
        import time
        deadline = time.time() + 30
        while True:
            b0 = runner.metrics("bank0")
            b1 = runner.metrics("bank1")
            if b0["txns"] + b1["txns"] == n or time.time() > deadline:
                break
            time.sleep(0.05)
        assert b0["txns"] + b1["txns"] == n
        assert b0["microblocks"] + b1["microblocks"] == p["microblocks"]
        # synth txns share the fee-payer across a 16-key pool, so true
        # parallelism across two banks is conflict-limited but nonzero
        assert p["microblocks"] >= n // 4
    finally:
        runner.halt()
        runner.close()


def test_topology_validation():
    with pytest.raises(ValueError, match="two producers"):
        (Topology("tv1").link("l")
         .tile("a", "synth", outs=["l"])
         .tile("b", "synth", outs=["l"])
         .tile("c", "sink", ins=["l"])._validate())
    with pytest.raises(ValueError, match="no producer"):
        (Topology("tv2").link("l")
         .tile("c", "sink", ins=["l"])._validate())
    with pytest.raises(ValueError, match="no consumer"):
        (Topology("tv3").link("l")
         .tile("a", "synth", outs=["l"])._validate())
    with pytest.raises(ValueError, match="unknown"):
        (Topology("tv4")
         .tile("a", "synth", outs=["zzz"])._validate())
