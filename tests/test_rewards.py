"""Partitioned epoch rewards (flamenco/rewards.py): inflation
schedule, points proportionality, commission split, compounding, and
partition coverage (ref: src/flamenco/rewards/fd_rewards.c)."""
import pytest

from firedancer_tpu.flamenco import rewards as rw
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm.accdb import Account
from firedancer_tpu.svm.stake import (STAKE_PROGRAM_ID, ST_DELEGATED,
                                      StakeState)
from firedancer_tpu.svm.vote import VOTE_PROGRAM_ID, VoteState

SPE = 432_000


def _mk(funk, xid, voters, stakes, rewarded_epoch=1):
    """voters: vote_key -> (commission, credits_in_epoch);
    stakes: stake_key -> (vote_key, amount)."""
    for vk, (comm, credits) in voters.items():
        vs = VoteState(vk, vk, vk, commission=comm)
        for _ in range(credits):
            vs._increment_credits(rewarded_epoch)
        funk.rec_write(xid, vk, Account(
            1_000_000, bytearray(vs.to_bytes()), VOTE_PROGRAM_ID))
    for sk, (vk, amt) in stakes.items():
        st = StakeState(state=ST_DELEGATED, staker=sk, withdrawer=sk,
                        voter=vk, amount=amt,
                        activation_epoch=rewarded_epoch - 1)
        funk.rec_write(xid, sk, Account(
            amt, bytearray(st.to_bytes()), STAKE_PROGRAM_ID))


def test_inflation_schedule_tapers_to_terminal():
    r0 = rw.inflation_rate_bps(0, SPE)
    assert r0 == rw.INITIAL_RATE_BPS
    # one epoch = 432000 slots * 0.4 s = 2 days -> year ~ 183 epochs
    r_year1 = rw.inflation_rate_bps(183, SPE)
    assert r_year1 == 800 * 8500 // 10_000
    # taper reaches the floor after ~11 years
    r_far = rw.inflation_rate_bps(183 * 40, SPE)
    assert r_far == rw.TERMINAL_RATE_BPS


def test_issuance_is_deterministic_integer():
    a = rw.epoch_validator_issuance(10**15, 3, SPE)
    b = rw.epoch_validator_issuance(10**15, 3, SPE)
    assert a == b and isinstance(a, int) and a > 0


def test_points_proportional_and_commission_split():
    funk = Funk()
    funk.txn_prepare(None, "e")
    v1, v2 = b"\x01" * 32, b"\x02" * 32
    s1, s2, s3 = b"\x0a" * 32, b"\x0b" * 32, b"\x0c" * 32
    _mk(funk, "e",
        {v1: (0, 10), v2: (50, 10)},
        {s1: (v1, 3_000_000), s2: (v1, 1_000_000),
         s3: (v2, 4_000_000)})
    issuance = 1_000_000
    rewards, points = rw.calculate_stake_rewards(funk, "e", 1, issuance)
    assert points == (3_000_000 + 1_000_000 + 4_000_000) * 10
    by_stake = {r[0]: r for r in rewards}
    # proportional: s1 gets 3/8 of issuance (commission 0)
    assert by_stake[s1][1] == issuance * 3 // 8
    assert by_stake[s1][3] == 0
    # s3: 4/8 of issuance, half to the vote account (50% commission)
    total3 = issuance * 4 // 8
    assert by_stake[s3][3] == total3 // 2
    assert by_stake[s3][1] == total3 - total3 // 2


def test_zero_credit_voter_earns_nothing():
    funk = Funk()
    funk.txn_prepare(None, "e")
    v1, v2 = b"\x01" * 32, b"\x02" * 32
    _mk(funk, "e", {v1: (0, 5), v2: (0, 0)},
        {b"\x0a" * 32: (v1, 100), b"\x0b" * 32: (v2, 100)})
    rewards, _ = rw.calculate_stake_rewards(funk, "e", 1, 1000)
    assert [r[0] for r in rewards] == [b"\x0a" * 32]


def test_distribution_compounds_stake():
    funk = Funk()
    funk.txn_prepare(None, "e")
    v1 = b"\x01" * 32
    s1 = b"\x0a" * 32
    _mk(funk, "e", {v1: (10, 4)}, {s1: (v1, 10_000_000)})
    summary = rw.distribute_epoch_rewards(
        funk, "e", 1, capitalization=10**15, slots_per_epoch=SPE,
        parent_blockhash=b"\x42" * 32)
    assert summary["accounts"] == 1 and summary["partitions"] == 1
    assert summary["paid"] > 0
    acct = funk.rec_query("e", s1)
    st = StakeState.from_bytes(acct.data)
    assert st.amount > 10_000_000              # compounded
    assert acct.lamports == st.amount          # lamports follow
    va = funk.rec_query("e", v1)
    assert va.lamports > 1_000_000             # commission landed
    # conservation: paid == sum of deltas
    assert summary["paid"] == (st.amount - 10_000_000) \
        + (va.lamports - 1_000_000)


def test_partitions_cover_each_account_exactly_once():
    rewards = [(bytes([i]) * 32, 10, b"\xEE" * 32, 0)
               for i in range(200)]
    parts = 4
    seen = []
    bh = b"\x33" * 32
    for p in range(parts):
        for r in rewards:
            if rw.partition_of(r[0], bh, parts) == p:
                seen.append(r[0])
    assert sorted(seen) == sorted(r[0] for r in rewards)
    # determinism
    assert rw.partition_of(rewards[0][0], bh, parts) == \
        rw.partition_of(rewards[0][0], bh, parts)


def test_epoch_credits_survive_vote_roundtrip():
    vs = VoteState(b"\x05" * 32, b"\x05" * 32, b"\x05" * 32)
    for ep in (0, 0, 1, 1, 1):
        vs._increment_credits(ep)
    blob = vs.to_bytes()
    back = VoteState.from_bytes(blob)
    assert back.epoch_credits == [(0, 2, 0), (1, 5, 2)]
    assert back.credits == 5
    # pre-r4 blob (no trailer) parses with empty history
    legacy = blob[:len(blob) - 2 - 24 * 2]
    assert VoteState.from_bytes(legacy).epoch_credits == []


def test_quiet_epochs_all_paid_and_marker_persists():
    """Every crossed epoch is rewarded even when no block landed in
    it, and the paid-through marker prevents re-payment after a
    restart (r4 review findings)."""
    funk = Funk()
    funk.txn_prepare(None, "e")
    v1, s1 = b"\x01" * 32, b"\x0a" * 32
    _mk(funk, "e", {v1: (0, 3)}, {s1: (v1, 1_000_000)},
        rewarded_epoch=1)
    # also credits in epoch 2
    va = funk.rec_query("e", v1)
    vs = VoteState.from_bytes(va.data)
    for _ in range(4):
        vs._increment_credits(2)
    funk.rec_write("e", v1, Account(va.lamports,
                                    bytearray(vs.to_bytes()),
                                    VOTE_PROGRAM_ID))
    # catch-up across epochs 1 and 2 (as the bank does on entering 3)
    assert rw.paid_through(funk, "e") == 0
    paid = 0
    for e in (1, 2):
        paid += rw.distribute_epoch_rewards(
            funk, "e", e, None, SPE, b"\x01" * 32)["paid"]
    rw.mark_paid_through(funk, "e", 3)
    assert paid > 0
    assert rw.paid_through(funk, "e") == 3
    # a "restarted" bank reads the marker and pays nothing again
    st = StakeState.from_bytes(funk.rec_query("e", s1).data)
    amt_after = st.amount
    start = rw.paid_through(funk, "e")
    assert start == 3                    # nothing below 3 re-paid
    assert amt_after > 1_000_000


def test_inflation_years_is_exact_integer_ratio():
    # years must come from an exact integer ratio, not IEEE rounding
    # (ADVICE r4): epoch*spe*0.4s vs 31557600 s/yr.
    from firedancer_tpu.flamenco import rewards as rw
    spe = 432_000
    # one Julian year = 78_894_000 slots at 0.4 s → epoch 182.625*spe
    edge = (10 * 31_557_600) // 4 // spe + 1      # first epoch past 1yr
    assert rw.inflation_rate_bps(edge, spe) < rw.INITIAL_RATE_BPS
    assert rw.inflation_rate_bps(0, spe) == rw.INITIAL_RATE_BPS
