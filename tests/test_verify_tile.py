"""End-to-end slice: synth load -> verify tile (device batch) -> out ring.

The reference's equivalent tiers: tile unit test without a cluster
(src/app/shared/fd_tile_unit_test.h — drive one tile's rings directly)
plus the bench topology TPS measurement (benchg -> verify -> ...).
"""
import os

import numpy as np
import pytest

from firedancer_tpu.runtime import Workspace, Ring, Tcache, Cnc
from firedancer_tpu.tiles.synth import SynthTile, make_signed_txns
from firedancer_tpu.tiles.verify import VerifyTile

BATCH = 32


@pytest.fixture(scope="module")
def wksp():
    w = Workspace(f"/fdtpu_vt_{os.getpid()}", 1 << 24)
    yield w
    w.close()
    w.unlink()


@pytest.fixture(scope="module")
def txns():
    return make_signed_txns(24, seed=1)


def test_verify_tile_end_to_end(wksp, txns):
    in_ring = Ring.create(wksp, depth=64, mtu=1280)
    out_ring = Ring.create(wksp, depth=64, mtu=1280)
    tc = Tcache(wksp, depth=512)
    tile = VerifyTile(in_ring, out_ring, tc, batch=BATCH)

    # valid txns + one corrupted signature + one garbage payload
    bad_sig = bytearray(txns[0])
    bad_sig[2] ^= 1           # flip a bit inside signature 0
    bad_sig[-1] ^= 1          # ...and in the message so dedup doesn't drop
    SynthTile(in_ring, txns).run(len(txns))
    in_ring.publish(bytes(bad_sig), sig=900)
    in_ring.publish(b"\xff\x00garbage", sig=901)

    while tile.poll_once():
        pass
    m = tile.metrics
    assert m["rx"] == len(txns) + 2
    assert m["parse_fail"] == 1
    assert m["verify_fail"] == 1
    assert m["dedup_drop"] == 0
    assert m["tx"] == len(txns)

    # out ring carries exactly the valid payloads, in order
    got = []
    seq = 0
    while True:
        rc, frag = out_ring.consume(seq)
        if rc != 0:
            break
        got.append(bytes(out_ring.payload(frag)))
        seq += 1
    assert got == txns


def test_verify_tile_dedup(wksp, txns):
    in_ring = Ring.create(wksp, depth=64, mtu=1280)
    out_ring = Ring.create(wksp, depth=64, mtu=1280)
    tc = Tcache(wksp, depth=512)
    tile = VerifyTile(in_ring, out_ring, tc, batch=BATCH)

    SynthTile(in_ring, txns[:4]).run(8)   # each txn sent twice
    while tile.poll_once():
        pass
    assert tile.metrics["tx"] == 4
    assert tile.metrics["dedup_drop"] == 4


def test_dedup_not_poisoned_by_invalid_sig(wksp, txns):
    """A garbage txn carrying a victim's signature bytes must NOT censor
    the victim: tags are inserted only after verify passes (advisor
    finding r1; ref ordering src/disco/verify/fd_verify_tile.h:84-101)."""
    in_ring = Ring.create(wksp, depth=64, mtu=1280)
    out_ring = Ring.create(wksp, depth=64, mtu=1280)
    tc = Tcache(wksp, depth=512)
    tile = VerifyTile(in_ring, out_ring, tc, batch=BATCH)

    victim = txns[0]
    # attacker copies the victim's signature but alters the message, so
    # the signature fails; previously its tag still entered the tcache
    attacker = bytearray(victim)
    attacker[-1] ^= 0xFF
    in_ring.publish(bytes(attacker), sig=1)
    while tile.poll_once():
        pass
    assert tile.metrics["verify_fail"] == 1

    in_ring.publish(victim, sig=2)
    while tile.poll_once():
        pass
    assert tile.metrics["dedup_drop"] == 0
    assert tile.metrics["tx"] == 1    # victim delivered


def test_verify_tile_credit_gating(wksp, txns):
    """With a reliable downstream fseq attached, the tile must not lap
    the consumer: publishes wait for credits (advisor finding r1)."""
    from firedancer_tpu.runtime import Fseq

    depth = 8
    in_ring = Ring.create(wksp, depth=64, mtu=1280)
    out_ring = Ring.create(wksp, depth=depth, mtu=1280)
    tc = Tcache(wksp, depth=512)
    fs = Fseq(wksp)

    import threading
    tile = VerifyTile(in_ring, out_ring, tc, batch=BATCH, out_fseqs=[fs])
    n = 16            # 2x out-ring depth: must backpressure without loss
    SynthTile(in_ring, txns[:n]).run(n)

    got = []

    def consumer():
        seq = 0
        while len(got) < n:
            rc, frag = out_ring.consume(seq)
            if rc != 0:
                continue
            got.append(bytes(out_ring.payload(frag)))
            seq += 1
            fs.update(seq)

    th = threading.Thread(target=consumer)
    th.start()
    while tile.poll_once():
        pass
    th.join(timeout=30)
    assert not th.is_alive()
    assert got == txns[:n]


def test_verify_tile_pipelined_inflight(wksp, txns):
    """Multiple microbatches queue on the device before the first
    verdict is read back; ordering, dedup and fail-closed semantics
    hold across the in-flight window, and flush() retires the tail."""
    in_ring = Ring.create(wksp, depth=256, mtu=1280)
    out_ring = Ring.create(wksp, depth=256, mtu=1280)
    tc = Tcache(wksp, depth=512)
    os.environ["FDTPU_VERIFY_INFLIGHT"] = "3"
    try:
        tile = VerifyTile(in_ring, out_ring, tc, batch=16)
    finally:
        del os.environ["FDTPU_VERIFY_INFLIGHT"]
    assert tile.inflight == 3
    bad = bytearray(txns[4])
    bad[2] ^= 1
    bad[-1] ^= 1
    feed = [bytes(t) for t in txns[:12]] + [bytes(bad)]
    # feed in small groups with polls between, so several gathered
    # sets stack up inside the in-flight window
    for k in range(0, len(feed), 3):
        for t in feed[k:k + 3]:
            in_ring.publish(t, sig=1)
        tile.poll_once()
    assert len(tile._pending) >= 1
    for _ in range(16):
        tile.poll_once()
    tile.flush()
    assert not tile._pending
    m = tile.metrics
    assert m["rx"] == 13 and m["verify_fail"] == 1 and m["tx"] == 12
    got = []
    seq = 0
    while True:
        rc, frag = out_ring.consume(seq)
        if rc != 0:
            break
        got.append(bytes(out_ring.payload(frag)))
        seq += 1
    assert got == txns[:12]
