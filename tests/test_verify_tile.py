"""End-to-end slice: synth load -> verify tile (device batch) -> out ring.

The reference's equivalent tiers: tile unit test without a cluster
(src/app/shared/fd_tile_unit_test.h — drive one tile's rings directly)
plus the bench topology TPS measurement (benchg -> verify -> ...).
"""
import os

import numpy as np
import pytest

from firedancer_tpu.runtime import Workspace, Ring, Tcache, Cnc
from firedancer_tpu.tiles.synth import SynthTile, make_signed_txns
from firedancer_tpu.tiles.verify import VerifyTile

BATCH = 32


@pytest.fixture(scope="module")
def wksp():
    w = Workspace(f"/fdtpu_vt_{os.getpid()}", 1 << 24)
    yield w
    w.close()
    w.unlink()


@pytest.fixture(scope="module")
def txns():
    return make_signed_txns(24, seed=1)


def test_verify_tile_end_to_end(wksp, txns):
    in_ring = Ring.create(wksp, depth=64, mtu=1280)
    out_ring = Ring.create(wksp, depth=64, mtu=1280)
    tc = Tcache(wksp, depth=512)
    tile = VerifyTile(in_ring, out_ring, tc, batch=BATCH)

    # valid txns + one corrupted signature + one garbage payload
    bad_sig = bytearray(txns[0])
    bad_sig[2] ^= 1           # flip a bit inside signature 0
    bad_sig[-1] ^= 1          # ...and in the message so dedup doesn't drop
    SynthTile(in_ring, txns).run(len(txns))
    in_ring.publish(bytes(bad_sig), sig=900)
    in_ring.publish(b"\xff\x00garbage", sig=901)

    while tile.poll_once():
        pass
    m = tile.metrics
    assert m["rx"] == len(txns) + 2
    assert m["parse_fail"] == 1
    assert m["verify_fail"] == 1
    assert m["dedup_drop"] == 0
    assert m["tx"] == len(txns)

    # out ring carries exactly the valid payloads, in order
    got = []
    seq = 0
    while True:
        rc, frag = out_ring.consume(seq)
        if rc != 0:
            break
        got.append(bytes(out_ring.payload(frag)))
        seq += 1
    assert got == txns


def test_verify_tile_dedup(wksp, txns):
    in_ring = Ring.create(wksp, depth=64, mtu=1280)
    out_ring = Ring.create(wksp, depth=64, mtu=1280)
    tc = Tcache(wksp, depth=512)
    tile = VerifyTile(in_ring, out_ring, tc, batch=BATCH)

    SynthTile(in_ring, txns[:4]).run(8)   # each txn sent twice
    while tile.poll_once():
        pass
    assert tile.metrics["tx"] == 4
    assert tile.metrics["dedup_drop"] == 4
