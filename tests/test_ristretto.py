"""ristretto255 (RFC 9496) + curve syscalls (ref:
src/ballet/ed25519/fd_ristretto255.h, src/flamenco/vm/syscall/
fd_vm_syscall_curve.c)."""
import struct

import pytest

from firedancer_tpu.utils import ristretto as rr
from firedancer_tpu.utils.ed25519_ref import L

# RFC 9496 §A.1 — the generator's small multiples (entries 0..2)
GEN_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
]


def test_rfc9496_generator_multiples():
    for n, want in enumerate(GEN_MULTIPLES):
        got = rr.encode(rr.mul(n, rr.base())) if n else \
            rr.encode((0, 1, 1, 0))
        assert got.hex() == want, n


def test_roundtrip_and_group_laws():
    B = rr.base()
    for n in (1, 2, 7, 100, L - 1):
        e = rr.encode(rr.mul(n, B))
        p = rr.decode(e)
        assert p is not None and rr.encode(p) == e
    # commutativity + associativity on encodings
    p2, p3 = rr.mul(2, B), rr.mul(3, B)
    assert rr.encode(rr.add(p2, p3)) == rr.encode(rr.add(p3, p2))
    assert rr.encode(rr.add(p2, p3)) == rr.encode(rr.mul(5, B))
    # order: l*B = identity
    assert rr.encode(rr.mul(L, B)).hex() == GEN_MULTIPLES[0]


def test_decode_rejections():
    # negative s (odd), non-canonical (>= p), wrong length
    assert rr.decode(b"\x01" + bytes(31)) is None          # s odd
    assert rr.decode(b"\xff" * 32) is None                 # >= p
    assert rr.decode(bytes(16)) is None
    # a compressed EDWARDS point is generally not a valid ristretto
    # encoding of anything in canonical form: the all-zero string IS
    # valid (identity); flip high bit
    bad = bytearray(rr.encode(rr.base()))
    bad[31] |= 0x80
    assert rr.decode(bytes(bad)) is None


def _vm_with(code_calls):
    from firedancer_tpu.vm import Vm
    from firedancer_tpu.vm.asm import asm
    from firedancer_tpu.vm.elf import murmur3_32
    return Vm, asm, murmur3_32


def test_curve_syscalls_in_vm():
    """sol_curve_validate_point + sol_curve_group_op through a real VM
    program: validate B, compute 2B+B via ADD then check against MUL 3."""
    from firedancer_tpu.vm import ERR_NONE, Vm
    from firedancer_tpu.vm.asm import asm
    from firedancer_tpu.vm.elf import murmur3_32
    from firedancer_tpu.vm.interp import INPUT_START

    Bh = rr.encode(rr.base())
    B2 = rr.encode(rr.mul(2, rr.base()))
    B3 = rr.encode(rr.mul(3, rr.base()))
    three = (3).to_bytes(32, "little")
    # input layout: [0:32]=B [32:64]=2B [64:96]=scalar3 [96:128]=out
    inp = Bh + B2 + three + bytes(32)
    prog = asm(f"""
        mov64 r1, 1
        lddw r2, {INPUT_START}
        call {hex(murmur3_32(b"sol_curve_validate_point"))}
        jne r0, 0, +11
        mov64 r1, 1
        mov64 r2, 0
        lddw r3, {INPUT_START + 32}
        lddw r4, {INPUT_START}
        lddw r5, {INPUT_START + 96}
        call {hex(murmur3_32(b"sol_curve_group_op"))}
        jne r0, 0, +1
        exit
        mov64 r0, 99
        exit
    """)
    from firedancer_tpu.vm.syscalls import DEFAULT_SYSCALLS
    vm = Vm(prog, input_data=inp, syscalls=DEFAULT_SYSCALLS)
    res = vm.run()
    assert res.error == ERR_NONE and res.r0 == 0, (res.error, res.r0)
    got = vm.mem_read(INPUT_START + 96, 32)
    assert got == B3                       # 2B + B == 3B
    # MUL path directly via the syscall function
    from firedancer_tpu.vm.syscalls import (CURVE_OP_MUL,
                                            CURVE_RISTRETTO,
                                            sys_curve_group_op)
    vm.mem_write(INPUT_START + 96, bytes(32))
    rc = sys_curve_group_op(vm, CURVE_RISTRETTO, CURVE_OP_MUL,
                            INPUT_START + 64, INPUT_START,
                            INPUT_START + 96, )
    assert rc == 0
    assert vm.mem_read(INPUT_START + 96, 32) == B3
    # non-canonical scalar rejected
    vm.mem_write(INPUT_START + 64, (L).to_bytes(32, "little"))
    rc = sys_curve_group_op(vm, CURVE_RISTRETTO, CURVE_OP_MUL,
                            INPUT_START + 64, INPUT_START,
                            INPUT_START + 96)
    assert rc == 1


def test_curve_syscall_edwards_and_sub():
    from firedancer_tpu.utils.ed25519_ref import (BASEPOINT,
                                                  pt_compress, pt_mul)
    from firedancer_tpu.vm import Vm
    from firedancer_tpu.vm.interp import INPUT_START
    from firedancer_tpu.vm.syscalls import (CURVE_EDWARDS,
                                            CURVE_OP_SUB,
                                            sys_curve_group_op,
                                            sys_curve_validate_point)
    B = pt_compress(BASEPOINT)
    B3 = pt_compress(pt_mul(3, BASEPOINT))
    B2 = pt_compress(pt_mul(2, BASEPOINT))
    vm = Vm(b"\x95" + bytes(7), input_data=B3 + B + bytes(32))
    vm.compute_budget = 10_000
    vm._cu = 0                   # direct syscall calls outside run()
    assert sys_curve_validate_point(vm, CURVE_EDWARDS,
                                    INPUT_START, 0, 0, 0) == 0
    rc = sys_curve_group_op(vm, CURVE_EDWARDS, CURVE_OP_SUB,
                            INPUT_START, INPUT_START + 32,
                            INPUT_START + 64)
    assert rc == 0
    assert vm.mem_read(INPUT_START + 64, 32) == B2   # 3B - B = 2B
    # invalid point encoding fails validation
    vm.mem_write(INPUT_START, b"\xff" * 32)
    assert sys_curve_validate_point(vm, CURVE_EDWARDS,
                                    INPUT_START, 0, 0, 0) == 1
