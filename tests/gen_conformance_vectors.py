"""Generate the conformance fixture corpus (VERDICT r4 item 6).

Emits tests/vectors/conformance/*.json in a solfuzz-shaped fixture
format (ref: src/flamenco/runtime/tests/fd_solfuzz.c — pre-state
txn-context -> expected effects), so vectors are machine-importable
and diffable. Every vector's expected status/balances are written
from the REFERENCE semantics being pinned (cited per group), not
captured from this runtime — the loader (tests/test_conformance.py)
is the gate that this runtime matches them.

Run: python tests/gen_conformance_vectors.py   (deterministic output)

Fixture schema:
  {"name", "cites",
   "context": {"accounts": [{address,lamports,data,owner,executable}],
               "tx": {"signers", "extra", "n_ro_signed",
                      "n_ro_unsigned",
                      "instructions": [{program_index, accounts,
                                        data}]},
               "epoch", "slot", "enforce_rent"},
   "effects": {"status", "fee",
               "accounts": [{address, lamports, data?}]}}
All byte fields are hex strings.
"""
from __future__ import annotations

import json
import os
import struct
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID  # noqa: E402
from firedancer_tpu.svm.alut import (  # noqa: E402
    ALUT_PROGRAM_ID, IX_FREEZE, derive_table_address, ix_create,
    ix_deactivate as alut_ix_deactivate, ix_extend,
)
from firedancer_tpu.pack.cost import (  # noqa: E402
    COMPUTE_BUDGET_PROGRAM_ID,
)
from firedancer_tpu.svm.precompiles import (  # noqa: E402
    ED25519_PROGRAM_ID, SECP256K1_PROGRAM_ID,
)
from firedancer_tpu.svm.programs import (  # noqa: E402
    NONCE_STATE_SZ, SYS_ADVANCE_NONCE, SYS_ALLOCATE,
    SYS_ALLOCATE_WITH_SEED, SYS_ASSIGN, SYS_ASSIGN_WITH_SEED,
    SYS_AUTHORIZE_NONCE, SYS_CREATE_ACCOUNT, SYS_CREATE_WITH_SEED,
    SYS_INIT_NONCE, SYS_TRANSFER, SYS_TRANSFER_WITH_SEED,
    SYS_WITHDRAW_NONCE, create_with_seed,
)
from firedancer_tpu.svm.stake import (  # noqa: E402
    STAKE_PROGRAM_ID, STATE_SZ, ST_DELEGATED, StakeState, ix_deactivate,
    ix_delegate, ix_initialize, ix_withdraw as stake_ix_withdraw,
)
from firedancer_tpu.svm.sysvars import (  # noqa: E402
    STAKE_HISTORY_ID, SYSVAR_OWNER, enc_stake_history,
    rent_exempt_minimum,
)
from firedancer_tpu.svm.vote import (  # noqa: E402
    AUTH_KIND_VOTER, AUTH_KIND_WITHDRAWER, VOTE_IX_AUTHORIZE,
    VOTE_IX_UPDATE_COMMISSION, VOTE_PROGRAM_ID, VoteState,
    ix_initialize as vote_ix_initialize, ix_tower_sync, ix_vote,
    ix_withdraw as vote_ix_withdraw,
)
from firedancer_tpu.utils.ed25519_ref import keypair, sign  # noqa: E402

OUT_DIR = os.path.join(os.path.dirname(__file__), "vectors",
                       "conformance")
FEE = 5000
EXEMPT0 = rent_exempt_minimum(0)
STAKE_MIN = rent_exempt_minimum(STATE_SZ)
BIG = 1 << 40


def k(n: int) -> bytes:
    return bytes([n]) * 32


A, B, C, D, E = k(1), k(2), k(3), k(4), k(5)
EVIL = k(0x66)


def h(b: bytes) -> str:
    return bytes(b).hex()


def acct(address, lamports=0, data=b"", owner=SYSTEM_PROGRAM_ID,
         executable=False):
    return {"address": h(address), "lamports": int(lamports),
            "data": h(data), "owner": h(owner),
            "executable": bool(executable)}


def vec(name, cites, accounts, signers, extra, instrs, status,
        fee=None, post=(), n_ro_signed=0, n_ro_unsigned=0,
        enforce_rent=True, epoch=0, slot=0):
    """instrs: [(program_index, [account indexes], data bytes)].
    fee=None derives len(signers) x FEE (the per-signature rule)."""
    if fee is None:
        fee = len(signers) * FEE
    return {
        "name": name, "cites": cites,
        "context": {
            "accounts": accounts,
            "tx": {"signers": [h(s) for s in signers],
                   "extra": [h(e) for e in extra],
                   "n_ro_signed": n_ro_signed,
                   "n_ro_unsigned": n_ro_unsigned,
                   "instructions": [
                       {"program_index": p, "accounts": list(ai),
                        "data": h(d)} for p, ai, d in instrs]},
            "epoch": epoch, "slot": slot,
            "enforce_rent": enforce_rent},
        "effects": {"status": status, "fee": fee,
                    "accounts": [
                        {"address": h(ad), "lamports": int(lp),
                         **({"data": h(dt)} if dt is not None else {})}
                        for ad, lp, dt in post]},
    }


def sys_ix(disc, *fields):
    data = struct.pack("<I", disc)
    for f in fields:
        data += f if isinstance(f, bytes) else struct.pack("<Q", f)
    return data


def vote_state(node=k(0x31), voter=A, withdrawer=A, commission=0):
    return VoteState(node, voter, withdrawer, commission).to_bytes()


def stake_state(**kw):
    return StakeState(**kw).to_bytes()


# ---------------------------------------------------------------------------
# system program (fd_system_program.c)
# ---------------------------------------------------------------------------

def gen_system():
    CITE = "fd_system_program.c:59-330"
    out = []
    pays = [acct(A, BIG)]
    dst_ok = [acct(B, EXEMPT0)]

    def t(amount):
        return sys_ix(SYS_TRANSFER, amount)

    # transfers
    out += [
        vec("sys_transfer_ok", CITE, pays + dst_ok, [A],
            [B, SYSTEM_PROGRAM_ID], [(2, [0, 1], t(1 << 20))], "ok",
            post=[(A, BIG - FEE - (1 << 20), None),
                  (B, EXEMPT0 + (1 << 20), None)], n_ro_unsigned=1),
        vec("sys_transfer_zero_ok", CITE, pays + dst_ok, [A],
            [B, SYSTEM_PROGRAM_ID], [(2, [0, 1], t(0))], "ok",
            post=[(B, EXEMPT0, None)], n_ro_unsigned=1),
        vec("sys_transfer_insufficient", CITE,
            [acct(A, EXEMPT0 + FEE + 10)] + dst_ok, [A],
            [B, SYSTEM_PROGRAM_ID], [(2, [0, 1], t(1 << 30))],
            "insufficient_funds", post=[(A, EXEMPT0 + 10, None)],
            n_ro_unsigned=1),
        vec("sys_transfer_from_data_account_refused", CITE,
            pays + [acct(C, EXEMPT0 + (1 << 20), data=b"state")]
            + dst_ok,
            [A, C], [B, SYSTEM_PROGRAM_ID], [(3, [1, 2], t(100))],
            "account_has_data", n_ro_unsigned=1),
        vec("sys_transfer_from_foreign_owner_refused", CITE,
            pays + [acct(C, BIG, owner=k(9))] + dst_ok,
            [A, C], [B, SYSTEM_PROGRAM_ID], [(3, [1, 2], t(100))],
            "invalid_account_owner", n_ro_unsigned=1),
        vec("sys_transfer_missing_signer", CITE,
            pays + [acct(C, BIG)] + dst_ok,
            [A], [C, B, SYSTEM_PROGRAM_ID], [(3, [1, 2], t(100))],
            "missing_required_signature", n_ro_unsigned=1),
        vec("sys_two_transfers_accumulate", CITE, pays + dst_ok, [A],
            [B, SYSTEM_PROGRAM_ID],
            [(2, [0, 1], t(1 << 20)), (2, [0, 1], t(1 << 20))], "ok",
            post=[(B, EXEMPT0 + (2 << 20), None)], n_ro_unsigned=1),
        vec("sys_rollback_on_second_failure", CITE, pays + dst_ok,
            [A], [B, SYSTEM_PROGRAM_ID],
            [(2, [0, 1], t(1 << 20)), (2, [0, 1], t(1 << 60))],
            "insufficient_funds",
            post=[(A, BIG - FEE, None), (B, EXEMPT0, None)],
            n_ro_unsigned=1),
        # draining an account to exactly zero closes it
        vec("sys_transfer_drain_to_zero_closes", CITE,
            [acct(A, BIG), acct(C, 1 << 20), acct(B, EXEMPT0)],
            [A, C], [B, SYSTEM_PROGRAM_ID],
            [(3, [1, 2], t(1 << 20))], "ok",
            post=[(C, 0, None), (B, EXEMPT0 + (1 << 20), None)],
            n_ro_unsigned=1),
    ]

    # rent transitions via transfer (Agave check_rent_state)
    out += [
        vec("rent_new_below_min_refused",
            "fd_sysvar_rent.c minimum-balance", pays, [A],
            [B, SYSTEM_PROGRAM_ID], [(2, [0, 1], t(EXEMPT0 - 1))],
            "insufficient_funds_for_rent", n_ro_unsigned=1),
        vec("rent_new_at_min_ok", "fd_sysvar_rent.c", pays, [A],
            [B, SYSTEM_PROGRAM_ID], [(2, [0, 1], t(EXEMPT0))], "ok",
            post=[(B, EXEMPT0, None)], n_ro_unsigned=1),
        vec("rent_paying_grow_refused", "Agave check_rent_state",
            pays + [acct(B, 500)], [A], [B, SYSTEM_PROGRAM_ID],
            [(2, [0, 1], t(100))], "insufficient_funds_for_rent",
            n_ro_unsigned=1),
        vec("rent_paying_topup_to_exempt_ok", "Agave check_rent_state",
            pays + [acct(B, 500)], [A], [B, SYSTEM_PROGRAM_ID],
            [(2, [0, 1], t(EXEMPT0 - 500))], "ok",
            post=[(B, EXEMPT0, None)], n_ro_unsigned=1),
        vec("rent_paying_shrink_ok", "Agave check_rent_state",
            pays + [acct(C, 500), acct(B, EXEMPT0)], [A, C],
            [B, SYSTEM_PROGRAM_ID], [(3, [1, 2], t(100))], "ok",
            post=[(C, 400, None)], n_ro_unsigned=1),
        vec("rent_disabled_allows_small_transfer",
            "legacy mode (enforce_rent off)", pays, [A],
            [B, SYSTEM_PROGRAM_ID], [(2, [0, 1], t(123))], "ok",
            post=[(B, 123, None)], n_ro_unsigned=1,
            enforce_rent=False),
    ]

    # create_account
    def cr(lamports, space, owner):
        return sys_ix(SYS_CREATE_ACCOUNT, lamports, space) + owner

    need64 = rent_exempt_minimum(64)
    out += [
        vec("sys_create_ok", CITE, pays, [A, B], [SYSTEM_PROGRAM_ID],
            [(2, [0, 1], cr(need64, 64, k(9)))], "ok",
            post=[(B, need64, bytes(64))]),
        vec("sys_create_zero_space_ok", CITE, pays, [A, B],
            [SYSTEM_PROGRAM_ID],
            [(2, [0, 1], cr(EXEMPT0, 0, k(9)))], "ok",
            post=[(B, EXEMPT0, b"")]),
        vec("sys_create_in_use_refused", CITE,
            pays + [acct(B, EXEMPT0)], [A, B], [SYSTEM_PROGRAM_ID],
            [(2, [0, 1], cr(need64, 64, k(9)))],
            "account_already_in_use"),
        vec("sys_create_missing_new_signer", CITE, pays, [A],
            [B, SYSTEM_PROGRAM_ID],
            [(2, [0, 1], cr(need64, 64, k(9)))],
            "missing_required_signature", n_ro_unsigned=1),
        vec("sys_create_below_rent_refused", CITE, pays, [A, B],
            [SYSTEM_PROGRAM_ID],
            [(2, [0, 1], cr(need64 - 1, 64, k(9)))],
            "insufficient_funds_for_rent"),
        vec("sys_create_payer_insufficient", CITE,
            [acct(A, 2 * FEE + 100)], [A, B], [SYSTEM_PROGRAM_ID],
            [(2, [0, 1], cr(need64, 64, k(9)))],
            "insufficient_funds", post=[(A, 100, None)]),
    ]

    # assign / allocate
    out += [
        vec("sys_allocate_assign_ok", CITE, pays, [A],
            [SYSTEM_PROGRAM_ID],
            [(1, [0], sys_ix(SYS_ALLOCATE, 32)),
             (1, [0], struct.pack("<I", SYS_ASSIGN) + k(7))], "ok",
            post=[(A, BIG - FEE, bytes(32))]),
        vec("sys_assign_foreign_refused", CITE,
            pays + [acct(C, BIG, owner=k(8))], [A, C],
            [SYSTEM_PROGRAM_ID],
            [(2, [1], struct.pack("<I", SYS_ASSIGN) + k(7))],
            "invalid_account_owner"),
        vec("sys_allocate_unsigned_refused", CITE,
            pays + [acct(C, BIG)], [A], [C, SYSTEM_PROGRAM_ID],
            [(2, [1], sys_ix(SYS_ALLOCATE, 32))],
            "missing_required_signature", n_ro_unsigned=1),
    ]

    # seed family
    der = create_with_seed(A, b"seed", SYSTEM_PROGRAM_ID)

    def seed_ix(disc, *parts):
        data = struct.pack("<I", disc)
        for p in parts:
            if isinstance(p, tuple) and p[0] == "str":
                data += struct.pack("<Q", len(p[1])) + p[1]
            elif isinstance(p, bytes):
                data += p
            else:
                data += struct.pack("<Q", p)
        return data

    out += [
        vec("sys_create_with_seed_ok", CITE, pays, [A],
            [der, SYSTEM_PROGRAM_ID],
            [(2, [0, 1], seed_ix(SYS_CREATE_WITH_SEED, A,
                                 ("str", b"seed"), EXEMPT0, 0,
                                 SYSTEM_PROGRAM_ID))], "ok",
            post=[(der, EXEMPT0, b"")], n_ro_unsigned=1),
        vec("sys_create_with_seed_wrong_derived_refused", CITE, pays,
            [A], [k(0x55), SYSTEM_PROGRAM_ID],
            [(2, [0, 1], seed_ix(SYS_CREATE_WITH_SEED, A,
                                 ("str", b"seed"), EXEMPT0, 0,
                                 SYSTEM_PROGRAM_ID))],
            "invalid_account_owner", n_ro_unsigned=1),
        vec("sys_transfer_with_seed_ok", CITE,
            pays + [acct(der, EXEMPT0 + (1 << 20)),
                    acct(B, EXEMPT0)],
            [A], [der, B, SYSTEM_PROGRAM_ID],
            [(3, [1, 0, 2], seed_ix(SYS_TRANSFER_WITH_SEED, 1 << 20,
                                    ("str", b"seed"),
                                    SYSTEM_PROGRAM_ID))], "ok",
            post=[(der, EXEMPT0, None),
                  (B, EXEMPT0 + (1 << 20), None)], n_ro_unsigned=1),
    ]

    # fees scale with signature count
    for n in (1, 2, 3, 4, 6, 8):
        signers = ([A, C, D] + [k(0x58 + i) for i in range(8)])[:n]
        accounts = [acct(s, BIG) for s in signers] + dst_ok
        out.append(vec(
            f"fee_scales_{n}_sigs", "fd_executor.c fee-before-dispatch",
            accounts, signers, [B, SYSTEM_PROGRAM_ID],
            [(n + 1, [0, n], t(1 << 20))], "ok", fee=n * FEE,
            post=[(A, BIG - n * FEE - (1 << 20), None)],
            n_ro_unsigned=1))
    out.append(vec(
        "fee_payer_cannot_pay", "fd_executor.c",
        [acct(A, FEE - 1)] + dst_ok, [A], [B, SYSTEM_PROGRAM_ID],
        [(2, [0, 1], t(1))], "fee_payer_insufficient", fee=0,
        post=[(A, FEE - 1, None)], n_ro_unsigned=1))

    # transfer amount sweep: exact balance conservation at every scale
    for amt in (1 << 20, EXEMPT0, EXEMPT0 + 1, 17 * EXEMPT0,
                (1 << 35) + 12345):
        out.append(vec(
            f"sys_transfer_amount_{amt}", CITE, pays + dst_ok, [A],
            [B, SYSTEM_PROGRAM_ID], [(2, [0, 1], t(amt))], "ok",
            post=[(A, BIG - FEE - amt, None),
                  (B, EXEMPT0 + amt, None)], n_ro_unsigned=1))
    # create-space sweep: per-size rent minimum is the exact boundary
    for space in (0, 1, 8, 64, 165, 256, 1024, 4096, 10240):
        need = rent_exempt_minimum(space)
        out.append(vec(
            f"sys_create_space_{space}_at_min_ok", CITE, pays, [A, B],
            [SYSTEM_PROGRAM_ID],
            [(2, [0, 1], cr(need, space, k(9)))], "ok",
            post=[(B, need, bytes(space))]))
        out.append(vec(
            f"sys_create_space_{space}_below_min_refused", CITE, pays,
            [A, B], [SYSTEM_PROGRAM_ID],
            [(2, [0, 1], cr(need - 1, space, k(9)))],
            "insufficient_funds_for_rent"))
    # unknown program / unknown instruction / readonly violations
    out += [
        vec("sys_unknown_program_refused", "fd_executor.c dispatch",
            pays + dst_ok, [A], [B, k(0x7E)],
            [(2, [0, 1], t(1))], "unknown_program", n_ro_unsigned=1),
        vec("sys_unknown_discriminant_refused", CITE, pays + dst_ok,
            [A], [B, SYSTEM_PROGRAM_ID],
            [(2, [0, 1], sys_ix(99, 1))], "unknown_instruction",
            n_ro_unsigned=1),
        vec("sys_transfer_readonly_dest_refused", CITE,
            pays + dst_ok, [A], [B, SYSTEM_PROGRAM_ID],
            [(2, [0, 1], t(1 << 20))], "account_not_writable",
            n_ro_unsigned=2),
        vec("sys_transfer_self_ok", CITE, pays, [A],
            [SYSTEM_PROGRAM_ID], [(1, [0, 0], t(1 << 20))], "ok",
            post=[(A, BIG - FEE, None)]),
    ]
    # allocate size sweep (on the rent-exempt payer itself)
    for space in (1, 32, 256, 4096):
        out.append(vec(
            f"sys_allocate_{space}_ok", CITE, pays, [A],
            [SYSTEM_PROGRAM_ID],
            [(1, [0], sys_ix(SYS_ALLOCATE, space))], "ok",
            post=[(A, BIG - FEE, bytes(space))]))
    # assign/allocate with seed
    der2 = create_with_seed(A, b"aw", SYSTEM_PROGRAM_ID)

    def seed_ix2(disc, *parts):
        data = struct.pack("<I", disc)
        for p in parts:
            if isinstance(p, tuple) and p[0] == "str":
                data += struct.pack("<Q", len(p[1])) + p[1]
            elif isinstance(p, bytes):
                data += p
            else:
                data += struct.pack("<Q", p)
        return data

    out += [
        vec("sys_allocate_with_seed_ok", CITE,
            pays + [acct(der2, EXEMPT0 + rent_exempt_minimum(16))],
            [A], [der2, SYSTEM_PROGRAM_ID],
            [(2, [1, 0], seed_ix2(SYS_ALLOCATE_WITH_SEED, A,
                                  ("str", b"aw"), 16,
                                  SYSTEM_PROGRAM_ID))], "ok",
            n_ro_unsigned=1),
        vec("sys_allocate_with_seed_wrong_base_refused", CITE,
            pays + [acct(der2, EXEMPT0), acct(EVIL, BIG)],
            [A, EVIL], [der2, SYSTEM_PROGRAM_ID],
            [(3, [2, 1], seed_ix2(SYS_ALLOCATE_WITH_SEED, EVIL,
                                  ("str", b"aw"), 16,
                                  SYSTEM_PROGRAM_ID))],
            "invalid_account_owner", fee=2 * FEE, n_ro_unsigned=1),
        # assign derives against the TARGET owner
        vec("sys_assign_with_seed_ok", CITE,
            pays + [acct(create_with_seed(A, b"as", k(0x33)),
                         EXEMPT0)], [A],
            [create_with_seed(A, b"as", k(0x33)), SYSTEM_PROGRAM_ID],
            [(2, [1, 0], seed_ix2(SYS_ASSIGN_WITH_SEED, A,
                                  ("str", b"as"), k(0x33)))], "ok",
            n_ro_unsigned=1),
    ]
    # chained transfers through an intermediary, exact conservation
    for hops in (2, 3, 4):
        mids = [k(0x50 + i) for i in range(hops - 1)]
        accounts = pays + [acct(m, EXEMPT0) for m in mids] + dst_ok
        signers = [A] + mids
        extra = [B, SYSTEM_PROGRAM_ID]
        chain = [A] + mids + [B]
        idx = {key: i for i, key in enumerate(signers + extra)}
        instrs = [(idx[SYSTEM_PROGRAM_ID],
                   [idx[chain[i]], idx[chain[i + 1]]], t(1 << 20))
                  for i in range(hops)]
        out.append(vec(
            f"sys_transfer_chain_{hops}_hops", CITE, accounts,
            signers, extra, instrs, "ok", fee=hops * FEE,
            post=[(B, EXEMPT0 + (1 << 20), None)]
            + [(m, EXEMPT0, None) for m in mids], n_ro_unsigned=1))
    return out


# ---------------------------------------------------------------------------
# nonce (fd_system_program.c durable nonces)
# ---------------------------------------------------------------------------

def gen_nonce():
    CITE = "fd_system_program.c durable nonce family"
    out = []
    NMIN = rent_exempt_minimum(NONCE_STATE_SZ)
    blank = [acct(A, BIG),
             acct(B, NMIN + (1 << 20), data=bytes(NONCE_STATE_SZ))]
    init = struct.pack("<I", SYS_INIT_NONCE) + A
    out += [
        vec("nonce_init_ok", CITE, blank, [A, B], [SYSTEM_PROGRAM_ID],
            [(2, [1], init)], "ok", slot=3),
        vec("nonce_init_unallocated_refused", CITE,
            [acct(A, BIG), acct(B, NMIN)], [A, B],
            [SYSTEM_PROGRAM_ID], [(2, [1], init)],
            "invalid_account_owner", slot=3),
        vec("nonce_advance_then_reuse_same_slot_refused", CITE, blank,
            [A, B], [SYSTEM_PROGRAM_ID],
            [(2, [1], init),
             (2, [1], struct.pack("<I", SYS_ADVANCE_NONCE)),
             (2, [1], struct.pack("<I", SYS_ADVANCE_NONCE))],
            "bad_instruction_data", slot=3),
        vec("nonce_withdraw_partial_ok", CITE,
            blank + [acct(C, EXEMPT0)], [A, B],
            [C, SYSTEM_PROGRAM_ID],
            [(3, [1, 2], struct.pack("<IQ", SYS_WITHDRAW_NONCE,
                                     1 << 20))], "ok",
            post=[(C, EXEMPT0 + (1 << 20), None),
                  (B, NMIN, None)], n_ro_unsigned=1, slot=3),
        vec("nonce_withdraw_into_reserve_refused", CITE,
            blank + [acct(C, EXEMPT0)], [A, B],
            [C, SYSTEM_PROGRAM_ID],
            [(3, [1, 2], struct.pack("<IQ", SYS_WITHDRAW_NONCE,
                                     (1 << 20) + 1))],
            "insufficient_funds", n_ro_unsigned=1, slot=3),
        vec("nonce_authorize_requires_authority", CITE, blank,
            [A, B], [SYSTEM_PROGRAM_ID],
            [(2, [1], init),
             (2, [1], struct.pack("<I", SYS_AUTHORIZE_NONCE) + EVIL),
             (2, [1], struct.pack("<I", SYS_AUTHORIZE_NONCE) + A)],
            "missing_required_signature", slot=3),
        vec("nonce_authorize_handoff_ok", CITE,
            [acct(A, BIG), acct(C, BIG),
             acct(B, NMIN + (1 << 20), data=bytes(NONCE_STATE_SZ))],
            [A, C, B], [SYSTEM_PROGRAM_ID],
            [(3, [2], init),
             (3, [2], struct.pack("<I", SYS_AUTHORIZE_NONCE) + C)],
            "ok", fee=3 * FEE, slot=3),
        vec("nonce_withdraw_full_closes", CITE,
            blank + [acct(C, EXEMPT0)], [A, B],
            [C, SYSTEM_PROGRAM_ID],
            [(3, [1, 2], struct.pack("<IQ", SYS_WITHDRAW_NONCE,
                                     NMIN + (1 << 20)))], "ok",
            post=[(B, 0, None),
                  (C, EXEMPT0 + NMIN + (1 << 20), None)],
            n_ro_unsigned=1, slot=3),
        vec("nonce_advance_needs_authority_sig", CITE,
            [acct(EVIL, BIG),
             acct(B, NMIN + (1 << 20), data=bytes(NONCE_STATE_SZ)),
             acct(A, BIG)],
            [EVIL, A, B], [SYSTEM_PROGRAM_ID],
            [(3, [2], init)], "ok", fee=3 * FEE, slot=3),
    ]
    return out


# ---------------------------------------------------------------------------
# stake program (fd_stake_program.c)
# ---------------------------------------------------------------------------

def gen_stake():
    CITE = "fd_stake_program.c"
    out = []
    blank = acct(B, STAKE_MIN + (1 << 20), data=bytes(STATE_SZ),
                 owner=STAKE_PROGRAM_ID)
    votea = acct(C, EXEMPT0, data=vote_state(), owner=VOTE_PROGRAM_ID)
    pays = [acct(A, BIG)]
    init_st = stake_state(state=1, staker=A, withdrawer=A,
                          rent_reserve=STAKE_MIN)
    inited = acct(B, STAKE_MIN + (1 << 20), data=init_st,
                  owner=STAKE_PROGRAM_ID)

    out += [
        vec("stake_init_ok", CITE, pays + [blank], [A],
            [B, STAKE_PROGRAM_ID],
            [(2, [1], ix_initialize(A, A))], "ok",
            post=[(B, STAKE_MIN + (1 << 20), init_st)],
            n_ro_unsigned=1),
        vec("stake_init_below_reserve_refused", CITE,
            pays + [acct(B, STAKE_MIN - 1, data=bytes(STATE_SZ),
                         owner=STAKE_PROGRAM_ID)], [A],
            [B, STAKE_PROGRAM_ID],
            [(2, [1], ix_initialize(A, A))], "insufficient_funds",
            n_ro_unsigned=1),
        vec("stake_init_twice_refused", CITE, pays + [inited], [A],
            [B, STAKE_PROGRAM_ID],
            [(2, [1], ix_initialize(A, A))], "invalid_account_owner",
            n_ro_unsigned=1),
        vec("stake_delegate_ok", CITE, pays + [inited, votea], [A],
            [B, C, STAKE_PROGRAM_ID],
            [(3, [1, 2], ix_delegate())], "ok",
            post=[(B, STAKE_MIN + (1 << 20),
                   stake_state(state=ST_DELEGATED, staker=A,
                               withdrawer=A, rent_reserve=STAKE_MIN,
                               voter=C, amount=1 << 20,
                               activation_epoch=4))],
            n_ro_unsigned=2, epoch=4),
        vec("stake_delegate_nonvote_refused", CITE,
            pays + [inited, acct(C, EXEMPT0)], [A],
            [B, C, STAKE_PROGRAM_ID],
            [(3, [1, 2], ix_delegate())], "invalid_account_owner",
            n_ro_unsigned=2),
        vec("stake_delegate_unsigned_staker_refused", CITE,
            [acct(EVIL, BIG), inited, votea], [EVIL],
            [B, C, STAKE_PROGRAM_ID],
            [(3, [1, 2], ix_delegate())],
            "missing_required_signature", n_ro_unsigned=2),
        vec("stake_deactivate_undelegated_refused", CITE,
            pays + [inited], [A], [B, STAKE_PROGRAM_ID],
            [(2, [1], ix_deactivate())], "invalid_account_owner",
            n_ro_unsigned=1),
    ]
    # lifecycle across epochs: delegated at 1, deactivated at 3
    live = acct(B, STAKE_MIN + (1 << 20),
                data=stake_state(state=ST_DELEGATED, staker=A,
                                 withdrawer=A, rent_reserve=STAKE_MIN,
                                 voter=C, amount=1 << 20,
                                 activation_epoch=1,
                                 deactivation_epoch=3),
                owner=STAKE_PROGRAM_ID)
    dest = acct(D, EXEMPT0)
    out += [
        vec("stake_withdraw_while_active_refused", CITE,
            pays + [live, dest], [A], [B, D, STAKE_PROGRAM_ID],
            [(3, [1, 2], stake_ix_withdraw(1))],
            "insufficient_funds", n_ro_unsigned=1, epoch=2),
        vec("stake_withdraw_cooldown_boundary_refused", CITE,
            pays + [live, dest], [A], [B, D, STAKE_PROGRAM_ID],
            [(3, [1, 2], stake_ix_withdraw(1))],
            "insufficient_funds", n_ro_unsigned=1, epoch=3),
        vec("stake_withdraw_after_cooldown_ok", CITE,
            pays + [live, dest], [A], [B, D, STAKE_PROGRAM_ID],
            [(3, [1, 2], stake_ix_withdraw((1 << 20) + STAKE_MIN))],
            "ok", post=[(B, 0, None),
                        (D, EXEMPT0 + (1 << 20) + STAKE_MIN, None)],
            n_ro_unsigned=1, epoch=4),
        vec("stake_withdraw_wrong_authority_refused", CITE,
            [acct(EVIL, BIG), live, dest], [EVIL],
            [B, D, STAKE_PROGRAM_ID],
            [(3, [1, 2], stake_ix_withdraw(1))],
            "missing_required_signature", n_ro_unsigned=1, epoch=5),
    ]
    # rate-limited cooldown under an explicit StakeHistory sysvar:
    # cluster deactivating 2x ours -> epoch 4 only ~45K of 1M freed
    hist = enc_stake_history([
        (4, (1_840_000, 0, 1_840_000)),
        (3, (2_000_000, 0, 2_000_000))])
    hist_acct = acct(STAKE_HISTORY_ID,
                     rent_exempt_minimum(len(hist)), data=hist,
                     owner=SYSVAR_OWNER)
    cooling = acct(B, STAKE_MIN + 1_000_000,
                   data=stake_state(state=ST_DELEGATED, staker=A,
                                    withdrawer=A,
                                    rent_reserve=STAKE_MIN, voter=C,
                                    amount=1_000_000,
                                    activation_epoch=0,
                                    deactivation_epoch=3),
                   owner=STAKE_PROGRAM_ID)
    # at epoch 4 with rate 0.09: cluster frees 0.09*2M = 180K; our
    # share (1M/2M) = 90K -> 910K still locked (+ reserve)
    out += [
        vec("stake_withdraw_history_rate_limited", CITE,
            pays + [cooling, dest, hist_acct], [A],
            [B, D, STAKE_PROGRAM_ID],
            [(3, [1, 2], stake_ix_withdraw(90_001))],
            "insufficient_funds", n_ro_unsigned=1, epoch=4),
        vec("stake_withdraw_history_freed_portion_ok", CITE,
            pays + [cooling, dest, hist_acct], [A],
            [B, D, STAKE_PROGRAM_ID],
            [(3, [1, 2], stake_ix_withdraw(90_000))], "ok",
            post=[(D, EXEMPT0 + 90_000, None)],
            n_ro_unsigned=1, epoch=4),
    ]
    # multi-epoch cooldown schedule: 1M deactivated at epoch 3 on a
    # cluster that always has 2x our deactivating stake; per-epoch the
    # freed amount follows rate x prev cluster-effective, our weight
    # current/prev-deactivating (hand-computed):
    #   e4: 0.5 x 0.09 x 2,000,000 = 90,000  -> current 910,000
    #   e5: 0.5 x 0.09 x 1,840,000 = 82,800  -> current 827,200
    #   e6: 0.5 x 0.09 x 1,674,400 = 75,348  -> current 751,852
    hist6 = enc_stake_history([
        (6, (1_524_004, 0, 1_503_704)),
        (5, (1_674_400, 0, 1_654_400)),
        (4, (1_840_000, 0, 1_820_000)),
        (3, (2_000_000, 0, 2_000_000))])
    hist6_acct = acct(STAKE_HISTORY_ID,
                      rent_exempt_minimum(len(hist6)), data=hist6,
                      owner=SYSVAR_OWNER)
    for epoch, freed in ((4, 90_000), (5, 172_800), (6, 248_148)):
        out.append(vec(
            f"stake_cooldown_epoch{epoch}_freed_{freed}_ok", CITE,
            pays + [cooling, dest, hist6_acct], [A],
            [B, D, STAKE_PROGRAM_ID],
            [(3, [1, 2], stake_ix_withdraw(freed))], "ok",
            post=[(D, EXEMPT0 + freed, None)], n_ro_unsigned=1,
            epoch=epoch))
        out.append(vec(
            f"stake_cooldown_epoch{epoch}_over_freed_refused", CITE,
            pays + [cooling, dest, hist6_acct], [A],
            [B, D, STAKE_PROGRAM_ID],
            [(3, [1, 2], stake_ix_withdraw(freed + 1))],
            "insufficient_funds", n_ro_unsigned=1, epoch=epoch))
    # delegation at each epoch pins activation_epoch in the state
    for ep in (0, 1, 2, 5, 9):
        out.append(vec(
            f"stake_delegate_epoch{ep}_state_pinned", CITE,
            pays + [inited, votea], [A], [B, C, STAKE_PROGRAM_ID],
            [(3, [1, 2], ix_delegate())], "ok",
            post=[(B, STAKE_MIN + (1 << 20),
                   stake_state(state=ST_DELEGATED, staker=A,
                               withdrawer=A, rent_reserve=STAKE_MIN,
                               voter=C, amount=1 << 20,
                               activation_epoch=ep))],
            n_ro_unsigned=2, epoch=ep))
    return out


# ---------------------------------------------------------------------------
# vote program (fd_vote_program.c)
# ---------------------------------------------------------------------------

def gen_vote():
    CITE = "fd_vote_program.c"
    out = []
    NODE, VOTER = k(0x31), k(0x21)
    pays = [acct(A, BIG), acct(NODE, BIG), acct(VOTER, BIG)]
    fresh = acct(B, EXEMPT0 + (1 << 20), data=bytes(0),
                 owner=VOTE_PROGRAM_ID)
    vs0 = vote_state(node=NODE, voter=VOTER, withdrawer=VOTER)
    # fund for GROWTH: applying votes enlarges the serialized state,
    # and the rent check reprices at the new size
    LIVE_BAL = rent_exempt_minimum(8192) + (1 << 20)
    live = acct(B, LIVE_BAL, data=vs0, owner=VOTE_PROGRAM_ID)

    out += [
        vec("vote_init_ok", CITE, pays + [fresh], [A, NODE],
            [B, VOTE_PROGRAM_ID],
            [(3, [2], vote_ix_initialize(NODE, VOTER, VOTER))], "ok",
            fee=2 * FEE, post=[(B, EXEMPT0 + (1 << 20), vs0)],
            n_ro_unsigned=1),
        vec("vote_init_without_node_sig_refused", CITE,
            pays + [fresh], [A], [B, VOTE_PROGRAM_ID],
            [(2, [1], vote_ix_initialize(NODE, VOTER, VOTER))],
            "missing_required_signature", n_ro_unsigned=1),
        vec("vote_init_nonfresh_refused", CITE, pays + [live],
            [A, NODE], [B, VOTE_PROGRAM_ID],
            [(3, [2], vote_ix_initialize(NODE, VOTER, VOTER))],
            "invalid_account_owner", fee=2 * FEE, n_ro_unsigned=1),
        vec("vote_requires_voter_authority", CITE, pays + [live],
            [A], [B, VOTE_PROGRAM_ID],
            [(2, [1], ix_vote([1], bytes(32)))],
            "missing_required_signature", n_ro_unsigned=1),
        vec("vote_on_nonvote_account_refused", CITE,
            pays + [acct(B, BIG)], [A, VOTER],
            [B, VOTE_PROGRAM_ID],
            [(3, [2], ix_vote([1], bytes(32)))],
            "invalid_account_owner", fee=2 * FEE, n_ro_unsigned=1),
        vec("vote_empty_slots_refused", CITE, pays + [live],
            [A, VOTER], [B, VOTE_PROGRAM_ID],
            [(3, [2], ix_vote([], bytes(32)))],
            "bad_instruction_data", fee=2 * FEE, n_ro_unsigned=1),
        vec("vote_commission_update_needs_withdrawer", CITE,
            pays + [live], [A], [B, VOTE_PROGRAM_ID],
            [(2, [1], struct.pack("<I", VOTE_IX_UPDATE_COMMISSION)
              + bytes([42]))],
            "missing_required_signature", n_ro_unsigned=1),
        vec("vote_withdraw_needs_withdrawer", CITE,
            pays + [live, acct(D, EXEMPT0)], [A],
            [B, D, VOTE_PROGRAM_ID],
            [(3, [1, 2], vote_ix_withdraw(1))],
            "missing_required_signature", n_ro_unsigned=1),
        vec("vote_withdraw_ok", CITE, pays + [live, acct(D, EXEMPT0)],
            [A, VOTER], [B, D, VOTE_PROGRAM_ID],
            [(4, [2, 3], vote_ix_withdraw(1 << 20))], "ok",
            fee=2 * FEE,
            post=[(D, EXEMPT0 + (1 << 20), None)], n_ro_unsigned=1),
    ]
    # authorize matrix: (kind, signer, expected)
    NEW = k(0x44)
    for name, kind, signer, expect in [
        ("vote_authorize_voter_by_voter_ok", AUTH_KIND_VOTER, VOTER,
         "ok"),
        ("vote_authorize_voter_by_withdrawer_ok", AUTH_KIND_VOTER,
         VOTER, "ok"),
        ("vote_authorize_voter_by_stranger_refused", AUTH_KIND_VOTER,
         EVIL, "missing_required_signature"),
        ("vote_authorize_withdrawer_by_withdrawer_ok",
         AUTH_KIND_WITHDRAWER, VOTER, "ok"),
        ("vote_authorize_withdrawer_by_stranger_refused",
         AUTH_KIND_WITHDRAWER, EVIL, "missing_required_signature"),
    ]:
        out.append(vec(
            name, CITE,
            [acct(A, BIG), acct(signer, BIG), live], [A, signer],
            [B, VOTE_PROGRAM_ID],
            [(3, [2], struct.pack("<I", VOTE_IX_AUTHORIZE) + NEW
              + struct.pack("<I", kind))], expect, fee=2 * FEE,
            n_ro_unsigned=1))
    # tower sync: single and multi-lockout
    out += [
        vec("vote_tower_sync_ok", CITE, pays + [live], [A, VOTER],
            [B, VOTE_PROGRAM_ID],
            [(3, [2], ix_tower_sync([(4, 2), (5, 1)], None,
                                    bytes(32), bytes(32)))], "ok",
            fee=2 * FEE, n_ro_unsigned=1),
        vec("vote_tower_sync_with_root_and_ts_ok", CITE,
            pays + [live], [A, VOTER], [B, VOTE_PROGRAM_ID],
            [(3, [2], ix_tower_sync([(9, 1)], 3, bytes(32),
                                    bytes(32), timestamp=77))], "ok",
            fee=2 * FEE, n_ro_unsigned=1),
        vec("vote_tower_sync_empty_refused", CITE, pays + [live],
            [A, VOTER], [B, VOTE_PROGRAM_ID],
            [(3, [2], ix_tower_sync([], None, bytes(32),
                                    bytes(32)))],
            "bad_instruction_data", fee=2 * FEE, n_ro_unsigned=1),
    ]
    # ascending vote-chain sweep: every prefix applies cleanly and
    # the resulting VoteState bytes are pinned exactly
    for n in (1, 2, 3, 5, 8, 13, 21, 31):
        st = VoteState(NODE, VOTER, VOTER)
        st.apply_vote(list(range(1, n + 1)), 0, epoch=0)
        out.append(vec(
            f"vote_chain_{n}_slots_state_pinned", CITE,
            pays + [live], [A, VOTER], [B, VOTE_PROGRAM_ID],
            [(3, [2], ix_vote(list(range(1, n + 1)), bytes(32)))],
            "ok", fee=2 * FEE,
            post=[(B, LIVE_BAL, st.to_bytes())], n_ro_unsigned=1))
    # stale/duplicate slots are skipped, strictly-ascending applied
    st = VoteState(NODE, VOTER, VOTER)
    st.apply_vote([3, 7], 0, epoch=0)
    out.append(vec(
        "vote_stale_slots_skipped", CITE, pays + [live], [A, VOTER],
        [B, VOTE_PROGRAM_ID],
        [(3, [2], ix_vote([3, 3, 7], bytes(32))),
         (3, [2], ix_vote([5, 7], bytes(32)))], "ok", fee=2 * FEE,
        post=[(B, LIVE_BAL, st.to_bytes())], n_ro_unsigned=1))
    # tower-sync lockout-count sweep (incl. the 64-entry cap)
    for n in (1, 2, 4, 8, 16, 31, 64):
        lockouts = [(s + 1, 1) for s in range(n)]
        out.append(vec(
            f"vote_tower_sync_{n}_lockouts_ok", CITE, pays + [live],
            [A, VOTER], [B, VOTE_PROGRAM_ID],
            [(3, [2], ix_tower_sync(lockouts, None, bytes(32),
                                    bytes(32)))], "ok", fee=2 * FEE,
            n_ro_unsigned=1))
    out.append(vec(
        "vote_tower_sync_65_lockouts_refused", CITE, pays + [live],
        [A, VOTER], [B, VOTE_PROGRAM_ID],
        [(3, [2], ix_tower_sync([(s + 1, 1) for s in range(65)],
                                None, bytes(32), bytes(32)))],
        "bad_instruction_data", fee=2 * FEE, n_ro_unsigned=1))
    # commission sweep through update + state pin
    for comm in (0, 1, 50, 100, 255):
        stc = VoteState(NODE, VOTER, VOTER)
        stc.commission = comm
        out.append(vec(
            f"vote_commission_{comm}_pinned", CITE, pays + [live],
            [A, VOTER], [B, VOTE_PROGRAM_ID],
            [(3, [2], struct.pack("<I", VOTE_IX_UPDATE_COMMISSION)
              + bytes([comm]))], "ok", fee=2 * FEE,
            post=[(B, LIVE_BAL, stc.to_bytes())], n_ro_unsigned=1))
    return out


# ---------------------------------------------------------------------------
# precompiles (fd_precompiles.c layouts)
# ---------------------------------------------------------------------------

def gen_precompiles():
    CITE = "fd_precompiles.c ed25519/secp256k1 layouts"
    out = []
    seed = bytes(range(32))
    _, _, pub = keypair(seed)
    msg = b"conformance-msg"
    sig = sign(seed, msg)

    def ed_ix(count_entries):
        data = bytearray([len(count_entries), 0])
        blob = bytearray()
        base = 2 + 14 * len(count_entries)
        for s, p, m in count_entries:
            sig_off = base + len(blob)
            blob += s
            pub_off = base + len(blob)
            blob += p
            msg_off = base + len(blob)
            blob += m
            data += struct.pack("<HHHHHHH", sig_off, 0xFFFF, pub_off,
                                0xFFFF, msg_off, len(m), 0xFFFF)
        return bytes(data) + bytes(blob)

    pays = [acct(A, BIG)]
    out += [
        vec("ed25519_precompile_ok", CITE, pays, [A],
            [ED25519_PROGRAM_ID], [(1, [], ed_ix([(sig, pub, msg)]))],
            "ok", n_ro_unsigned=1),
        vec("ed25519_precompile_two_sigs_ok", CITE, pays, [A],
            [ED25519_PROGRAM_ID],
            [(1, [], ed_ix([(sig, pub, msg), (sig, pub, msg)]))],
            "ok", n_ro_unsigned=1),
        vec("ed25519_precompile_bad_sig_refused", CITE, pays, [A],
            [ED25519_PROGRAM_ID],
            [(1, [], ed_ix([(bytes(64), pub, msg)]))],
            "program_failed", n_ro_unsigned=1),
        vec("ed25519_precompile_truncated_refused", CITE, pays, [A],
            [ED25519_PROGRAM_ID],
            [(1, [], ed_ix([(sig, pub, msg)])[:-4])],
            "bad_instruction_data", n_ro_unsigned=1),
        vec("ed25519_precompile_wrong_msg_refused", CITE, pays, [A],
            [ED25519_PROGRAM_ID],
            [(1, [], ed_ix([(sig, pub, b"other-msg______")]))],
            "program_failed", n_ro_unsigned=1),
    ]
    # signature-count sweep (distinct keys/messages per entry)
    for n in (3, 4, 6, 8):
        entries = []
        for i in range(n):
            s_i = bytes([i + 1]) * 32
            _, _, p_i = keypair(s_i)
            m_i = b"msg-%02d" % i
            entries.append((sign(s_i, m_i), p_i, m_i))
        out.append(vec(
            f"ed25519_precompile_{n}_sigs_ok", CITE, pays, [A],
            [ED25519_PROGRAM_ID], [(1, [], ed_ix(entries))], "ok",
            n_ro_unsigned=1))
        bad = entries[:-1] + [(bytes(64),) + entries[-1][1:]]
        out.append(vec(
            f"ed25519_precompile_{n}_sigs_last_forged_refused", CITE,
            pays, [A], [ED25519_PROGRAM_ID],
            [(1, [], ed_ix(bad))], "program_failed",
            n_ro_unsigned=1))

    # secp256k1: Ethereum-style recovery layout (u8 indexes)
    from firedancer_tpu.utils.keccak import keccak256
    from firedancer_tpu.utils.secp256k1 import (
        GX, GY, _mul, eth_address, sign as ksign,
    )
    priv = 0xC0FFEE0DDF00D
    addr20 = eth_address(_mul(priv, (GX, GY)))
    kmsg = b"eth-style-message"
    r, s, rec = ksign(priv, keccak256(kmsg))
    sig65 = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([rec])

    def k1_ix(entries):
        data = bytearray([len(entries)])
        blob = bytearray()
        base = 1 + 11 * len(entries)
        for sg, ad, m in entries:
            sig_off = base + len(blob)
            blob += sg
            addr_off = base + len(blob)
            blob += ad
            msg_off = base + len(blob)
            blob += m
            data += struct.pack("<HBHBHHB", sig_off, 0xFF, addr_off,
                                0xFF, msg_off, len(m), 0xFF)
        return bytes(data) + bytes(blob)

    out += [
        vec("secp256k1_precompile_ok", CITE, pays, [A],
            [SECP256K1_PROGRAM_ID],
            [(1, [], k1_ix([(sig65, addr20, kmsg)]))], "ok",
            n_ro_unsigned=1),
        vec("secp256k1_precompile_wrong_addr_refused", CITE, pays,
            [A], [SECP256K1_PROGRAM_ID],
            [(1, [], k1_ix([(sig65, bytes(20), kmsg)]))],
            "program_failed", n_ro_unsigned=1),
        vec("secp256k1_precompile_wrong_msg_refused", CITE, pays,
            [A], [SECP256K1_PROGRAM_ID],
            [(1, [], k1_ix([(sig65, addr20, b"other")]))],
            "program_failed", n_ro_unsigned=1),
        vec("secp256k1_precompile_truncated_refused", CITE, pays,
            [A], [SECP256K1_PROGRAM_ID],
            [(1, [], k1_ix([(sig65, addr20, kmsg)])[:-3])],
            "bad_instruction_data", n_ro_unsigned=1),
    ]
    return out


# ---------------------------------------------------------------------------
# address lookup tables (fd_address_lookup_table_program.c)
# ---------------------------------------------------------------------------

def gen_alut():
    CITE = "fd_address_lookup_table_program.c"
    out = []
    pays = [acct(A, BIG)]
    slot = 10
    table, bump = derive_table_address(A, slot)
    create = ix_create(slot, bump)
    freeze = struct.pack("<I", IX_FREEZE)
    out += [
        vec("alut_create_ok", CITE, pays, [A],
            [table, ALUT_PROGRAM_ID],
            [(2, [1, 0, 0], create)], "ok",
            n_ro_unsigned=1, slot=slot),
        vec("alut_create_wrong_derivation_refused", CITE, pays, [A],
            [k(0x59), ALUT_PROGRAM_ID],
            [(2, [1, 0, 0], create)], "invalid_account_owner",
            n_ro_unsigned=1, slot=slot),
        vec("alut_create_then_extend_ok", CITE, pays, [A],
            [table, ALUT_PROGRAM_ID],
            [(2, [1, 0, 0], create),
             (2, [1, 0, 0], ix_extend([k(0x71), k(0x72)]))], "ok",
            n_ro_unsigned=1, slot=slot),
        vec("alut_extend_by_stranger_refused", CITE,
            pays + [acct(EVIL, BIG)], [A, EVIL],
            [table, ALUT_PROGRAM_ID],
            [(3, [2, 0, 0], create),
             (3, [2, 1, 1], ix_extend([k(0x71)]))],
            "invalid_account_owner", fee=2 * FEE,
            n_ro_unsigned=1, slot=slot),
        vec("alut_freeze_then_extend_refused", CITE, pays, [A],
            [table, ALUT_PROGRAM_ID],
            [(2, [1, 0, 0], create),
             (2, [1, 0], freeze),
             (2, [1, 0, 0], ix_extend([k(0x71)]))],
            "invalid_account_owner", n_ro_unsigned=1, slot=slot),
        vec("alut_deactivate_twice_refused", CITE, pays, [A],
            [table, ALUT_PROGRAM_ID],
            [(2, [1, 0, 0], create),
             (2, [1, 0], alut_ix_deactivate()),
             (2, [1, 0], alut_ix_deactivate())],
            "invalid_account_owner", n_ro_unsigned=1, slot=slot),
        vec("alut_extend_empty_refused", CITE, pays, [A],
            [table, ALUT_PROGRAM_ID],
            [(2, [1, 0, 0], create),
             (2, [1, 0, 0], ix_extend([]))],
            "bad_instruction_data", n_ro_unsigned=1, slot=slot),
    ]
    return out


# ---------------------------------------------------------------------------
# compute budget (fd_compute_budget_program.h)
# ---------------------------------------------------------------------------

def gen_compute_budget():
    CITE = "fd_compute_budget_program.h"
    out = []
    pays = [acct(A, BIG), acct(B, EXEMPT0)]

    def cb(disc, *fields):
        data = bytes([disc])
        for f in fields:
            data += struct.pack("<I" if f < (1 << 32) else "<Q", f)
        return data

    t = sys_ix(SYS_TRANSFER, 1 << 20)
    out += [
        vec("cb_set_cu_limit_ok", CITE, pays, [A],
            [B, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID],
            [(2, [], cb(2, 100_000)), (3, [0, 1], t)], "ok",
            post=[(B, EXEMPT0 + (1 << 20), None)], n_ro_unsigned=2),
        vec("cb_request_heap_ok", CITE, pays, [A],
            [B, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID],
            [(2, [], cb(1, 64 * 1024)), (3, [0, 1], t)], "ok",
            n_ro_unsigned=2),
        vec("cb_bad_heap_refused", CITE, pays, [A],
            [B, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID],
            [(2, [], cb(1, 1)), (3, [0, 1], t)],
            "bad_instruction_data", n_ro_unsigned=2),
        vec("cb_truncated_refused", CITE, pays, [A],
            [B, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID],
            [(2, [], b"\x02\x01"), (3, [0, 1], t)],
            "bad_instruction_data", n_ro_unsigned=2),
        vec("cb_duplicate_cu_limit_refused", CITE, pays, [A],
            [B, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID],
            [(2, [], cb(2, 100_000)), (2, [], cb(2, 50_000)),
             (3, [0, 1], t)], "bad_instruction_data",
            n_ro_unsigned=2),
        vec("cb_duplicate_heap_refused", CITE, pays, [A],
            [B, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID],
            [(2, [], cb(1, 64 * 1024)), (2, [], cb(1, 32 * 1024)),
             (3, [0, 1], t)], "bad_instruction_data",
            n_ro_unsigned=2),
        vec("cb_cu_and_heap_together_ok", CITE, pays, [A],
            [B, COMPUTE_BUDGET_PROGRAM_ID, SYSTEM_PROGRAM_ID],
            [(2, [], cb(2, 400_000)), (2, [], cb(1, 128 * 1024)),
             (3, [0, 1], t)], "ok",
            post=[(B, EXEMPT0 + (1 << 20), None)], n_ro_unsigned=2),
    ]
    return out


# ---------------------------------------------------------------------------
# cross-program transactions (fd_executor.c atomicity)
# ---------------------------------------------------------------------------

def gen_cross_program():
    CITE = "fd_executor.c atomic rollback across programs"
    out = []
    NODE, VOTER = k(0x31), k(0x21)
    vs0 = vote_state(node=NODE, voter=VOTER, withdrawer=VOTER)
    live = acct(B, rent_exempt_minimum(len(vs0)) + (1 << 20),
                data=vs0, owner=VOTE_PROGRAM_ID)
    stake_blank = acct(C, STAKE_MIN + (1 << 20), data=bytes(STATE_SZ),
                       owner=STAKE_PROGRAM_ID)
    pays = [acct(A, BIG), acct(VOTER, BIG), acct(D, EXEMPT0)]
    t = sys_ix(SYS_TRANSFER, 1 << 20)
    # transfer + vote + stake-init all land in ONE txn
    st_after = VoteState(NODE, VOTER, VOTER)
    st_after.apply_vote([9], 0, epoch=0)
    out.append(vec(
        "xprog_transfer_vote_stakeinit_ok", CITE,
        pays + [live, stake_blank], [A, VOTER],
        [B, C, D, STAKE_PROGRAM_ID, VOTE_PROGRAM_ID,
         SYSTEM_PROGRAM_ID],
        [(7, [0, 4], t),
         (6, [2], ix_vote([9], bytes(32))),
         (5, [3], ix_initialize(A, A))], "ok", fee=2 * FEE,
        post=[(D, EXEMPT0 + (1 << 20), None),
              (B, rent_exempt_minimum(len(vs0)) + (1 << 20),
               st_after.to_bytes())], n_ro_unsigned=3))
    # same txn but the LAST instruction fails: everything rolls back
    out.append(vec(
        "xprog_late_failure_rolls_back_all", CITE,
        pays + [live, stake_blank], [A, VOTER],
        [B, C, D, STAKE_PROGRAM_ID, VOTE_PROGRAM_ID,
         SYSTEM_PROGRAM_ID],
        [(7, [0, 4], t),
         (6, [2], ix_vote([9], bytes(32))),
         (5, [3], ix_initialize(A, A)),
         (7, [0, 4], sys_ix(SYS_TRANSFER, 1 << 60))],
        "insufficient_funds", fee=2 * FEE,
        post=[(D, EXEMPT0, None),
              (B, rent_exempt_minimum(len(vs0)) + (1 << 20), vs0),
              (C, STAKE_MIN + (1 << 20), bytes(STATE_SZ))],
        n_ro_unsigned=3))
    # precompile gate in front of a transfer: forged sig blocks it
    seed = bytes(range(32))
    _, _, pub = keypair(seed)
    msg = b"gate"
    good = sign(seed, msg)

    def ed1(s):
        base = 2 + 14
        data = bytearray([1, 0])
        data += struct.pack("<HHHHHHH", base, 0xFFFF, base + 64,
                            0xFFFF, base + 96, len(msg), 0xFFFF)
        return bytes(data) + s + pub + msg

    for nm, sg, expect, post in (
            ("xprog_precompile_gate_ok", good, "ok",
             [(D, EXEMPT0 + (1 << 20), None)]),
            ("xprog_precompile_gate_forged_blocks", bytes(64),
             "program_failed", [(D, EXEMPT0, None)])):
        out.append(vec(
            nm, CITE, pays[:1] + [acct(D, EXEMPT0)], [A],
            [D, ED25519_PROGRAM_ID, SYSTEM_PROGRAM_ID],
            [(2, [], ed1(sg)), (3, [0, 1], t)], expect,
            post=post, n_ro_unsigned=2))
    return out


# ---------------------------------------------------------------------------
# BPF loader execution (fd_bpf_loader + vm)
# ---------------------------------------------------------------------------

def gen_bpf():
    CITE = "fd_bpf_loader execution + ownership rule"
    from firedancer_tpu.svm.programs import BPF_LOADER_ID
    from firedancer_tpu.vm import asm
    out = []
    PROG = k(0x70)
    STRIDE = 42
    base = 2

    def mover(amount):
        lam0, lam1 = base + 32, base + STRIDE + 32
        return asm(f"""
            mov64 r6, r1
            ldxdw r2, [r6+{lam0}]
            ldxdw r3, [r6+{lam1}]
            sub64 r2, {amount}
            add64 r3, {amount}
            stxdw [r6+{lam0}], r2
            stxdw [r6+{lam1}], r3
            mov64 r0, 0
            exit
        """)

    err_prog = asm("""
        mov64 r0, 1
        exit
    """)
    prog_acct = acct(PROG, 1, data=mover(1 << 20), owner=BPF_LOADER_ID,
                     executable=True)
    err_acct = acct(PROG, 1, data=err_prog, owner=BPF_LOADER_ID,
                    executable=True)
    held = [acct(C, EXEMPT0 + (1 << 20), owner=PROG),
            acct(D, EXEMPT0, owner=PROG)]
    pays = [acct(A, BIG)]
    out += [
        vec("bpf_mover_moves_lamports", CITE,
            pays + held + [prog_acct], [A], [C, D, PROG],
            [(3, [1, 2], b"")], "ok",
            post=[(C, EXEMPT0, None),
                  (D, EXEMPT0 + (1 << 20), None)], n_ro_unsigned=1),
        vec("bpf_nonzero_exit_fails_txn", CITE,
            pays + held + [err_acct], [A], [C, D, PROG],
            [(3, [1, 2], b"")], "program_failed",
            post=[(C, EXEMPT0 + (1 << 20), None)], n_ro_unsigned=1),
        vec("bpf_ownership_rule_blocks_foreign_debit", CITE,
            pays + [acct(C, EXEMPT0 + (1 << 20), owner=k(0x42)),
                    acct(D, EXEMPT0, owner=PROG), prog_acct],
            [A], [C, D, PROG],
            [(3, [1, 2], b"")], "invalid_account_owner",
            post=[(C, EXEMPT0 + (1 << 20), None)], n_ro_unsigned=1),
        vec("bpf_balance_conservation_enforced", CITE,
            pays + held + [acct(PROG, 1, data=asm(f"""
                mov64 r6, r1
                ldxdw r2, [r6+{base + 32}]
                add64 r2, 777
                stxdw [r6+{base + 32}], r2
                mov64 r0, 0
                exit
            """), owner=BPF_LOADER_ID, executable=True)],
            [A], [C, D, PROG],
            [(3, [1, 2], b"")], "sum_of_lamports_changed",
            n_ro_unsigned=1),
    ]
    return out


GROUPS = {
    "system": gen_system,
    "nonce": gen_nonce,
    "stake": gen_stake,
    "vote": gen_vote,
    "precompiles": gen_precompiles,
    "alut": gen_alut,
    "compute_budget": gen_compute_budget,
    "cross_program": gen_cross_program,
    "bpf": gen_bpf,
}


def main():
    os.makedirs(OUT_DIR, exist_ok=True)
    total = 0
    for group, gen in GROUPS.items():
        vecs = gen()
        names = [v["name"] for v in vecs]
        assert len(names) == len(set(names)), f"dup names in {group}"
        path = os.path.join(OUT_DIR, f"{group}.json")
        with open(path, "w") as f:
            json.dump(vecs, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"{group}: {len(vecs)} vectors -> {path}")
        total += len(vecs)
    print(f"total: {total}")


if __name__ == "__main__":
    main()
