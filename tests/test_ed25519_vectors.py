"""External crypto vector gates for the ed25519 verify kernel.

* Wycheproof EdDSA verify vectors (public test data from the Wycheproof
  project, via the reference's generated table — ref:
  src/ballet/ed25519/test_ed25519_wycheproof.c; extracted by
  vectors/convert_wycheproof.py). Expected verdicts are those of a
  strict cofactorless verifier (fd_ed25519_verify) — our parity target.
* Signature malleability corpus (Zcash/ed25519-zebra test data — ref:
  src/ballet/ed25519/test_ed25519_signature_malleability*.bin): 96-byte
  (sig, pub) records over the fixed message "Zcash".
* Randomized large-batch differential fuzz vs the pure-python RFC 8032
  oracle (VERDICT r1: >=4K lanes).

All device calls share ONE compiled shape (batch 128 x max_len 1024)
so the suite costs a single jit compile.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from firedancer_tpu.ops.ed25519 import verify_batch

HERE = os.path.dirname(os.path.abspath(__file__))
BATCH = 128
MAX_LEN = 1024

_fn = None


def _verify_chunked(sig, pub, msg, ln):
    """Run (n, ...) inputs through the fixed-shape jitted kernel."""
    global _fn
    if _fn is None:
        _fn = jax.jit(verify_batch)
    n = sig.shape[0]
    out = np.zeros(n, bool)
    for c0 in range(0, n, BATCH):
        c1 = min(c0 + BATCH, n)
        s = np.zeros((BATCH, 64), np.uint8)
        p = np.zeros((BATCH, 32), np.uint8)
        m = np.zeros((BATCH, MAX_LEN), np.uint8)
        L = np.zeros((BATCH,), np.int32)
        s[:c1 - c0] = sig[c0:c1]
        p[:c1 - c0] = pub[c0:c1]
        m[:c1 - c0] = msg[c0:c1]
        L[:c1 - c0] = ln[c0:c1]
        got = np.asarray(_fn(jnp.asarray(s), jnp.asarray(p),
                             jnp.asarray(m), jnp.asarray(L)))
        out[c0:c1] = got[:c1 - c0]
    return out


def test_wycheproof():
    with open(os.path.join(HERE, "vectors",
                           "ed25519_wycheproof.json")) as f:
        vecs = json.load(f)
    n = len(vecs)
    sig = np.zeros((n, 64), np.uint8)
    pub = np.zeros((n, 32), np.uint8)
    msg = np.zeros((n, MAX_LEN), np.uint8)
    ln = np.zeros((n,), np.int32)
    want = np.zeros((n,), bool)
    for i, v in enumerate(vecs):
        sig[i] = np.frombuffer(bytes.fromhex(v["sig"]), np.uint8)
        pub[i] = np.frombuffer(bytes.fromhex(v["pub"]), np.uint8)
        mb = bytes.fromhex(v["msg"])
        msg[i, :len(mb)] = np.frombuffer(mb, np.uint8)
        ln[i] = len(mb)
        want[i] = v["ok"]
    got = _verify_chunked(sig, pub, msg, ln)
    bad = [(vecs[i]["tc_id"], vecs[i]["comment"], bool(want[i]))
           for i in range(n) if got[i] != want[i]]
    assert not bad, f"{len(bad)} wycheproof mismatches: {bad[:10]}"


def test_malleability_corpus():
    recs = []
    for name, expect in [("malleability_should_pass.bin", True),
                         ("malleability_should_fail.bin", False)]:
        raw = open(os.path.join(HERE, "vectors", name), "rb").read()
        assert len(raw) % 96 == 0
        for off in range(0, len(raw), 96):
            recs.append((raw[off:off + 64], raw[off + 64:off + 96],
                         expect))
    n = len(recs)
    sig = np.zeros((n, 64), np.uint8)
    pub = np.zeros((n, 32), np.uint8)
    msg = np.zeros((n, MAX_LEN), np.uint8)
    ln = np.full((n,), 5, np.int32)
    msg[:, :5] = np.frombuffer(b"Zcash", np.uint8)
    want = np.zeros((n,), bool)
    for i, (s, p, e) in enumerate(recs):
        sig[i] = np.frombuffer(s, np.uint8)
        pub[i] = np.frombuffer(p, np.uint8)
        want[i] = e
    got = _verify_chunked(sig, pub, msg, ln)
    mism = np.nonzero(got != want)[0]
    assert mism.size == 0, (
        f"{mism.size}/{n} malleability mismatches, first at rec "
        f"{mism[:5]} (expected {want[mism[:5]]})")


def test_large_batch_differential_fuzz():
    """4096 lanes: mostly valid signatures with a scattering of
    corruptions; verdicts must match the RFC 8032 oracle exactly."""
    import hashlib
    from firedancer_tpu.utils.ed25519_ref import keypair, sign, verify

    rng = np.random.default_rng(123)
    n = 4096
    sig = np.zeros((n, 64), np.uint8)
    pub = np.zeros((n, 32), np.uint8)
    msg = np.zeros((n, MAX_LEN), np.uint8)
    ln = np.zeros((n,), np.int32)
    n_unique = 48
    base = []
    for i in range(n_unique):
        seed = hashlib.sha256(b"fuzz-%d" % i).digest()
        m = rng.integers(0, 256, int(rng.integers(0, 200)),
                         dtype=np.uint8).tobytes()
        _, _, pk = keypair(seed)
        s = sign(seed, m)
        base.append((s, pk, m))
    for i in range(n):
        s, pk, m = base[i % n_unique]
        s, pk, m = bytearray(s), bytearray(pk), bytearray(m)
        r = rng.random()
        if r < 0.15 and len(m):
            m[rng.integers(len(m))] ^= 1 << rng.integers(8)
        elif r < 0.3:
            s[rng.integers(64)] ^= 1 << rng.integers(8)
        elif r < 0.4:
            pk[rng.integers(32)] ^= 1 << rng.integers(8)
        sig[i] = np.frombuffer(bytes(s), np.uint8)
        pub[i] = np.frombuffer(bytes(pk), np.uint8)
        msg[i, :len(m)] = np.frombuffer(bytes(m), np.uint8)
        ln[i] = len(m)
    got = _verify_chunked(sig, pub, msg, ln)
    # oracle over the distinct (sig, pub, msg) triples
    cache = {}
    for i in range(n):
        key = (sig[i].tobytes(), pub[i].tobytes(),
               msg[i, :ln[i]].tobytes())
        if key not in cache:
            cache[key] = verify(key[0], key[1], key[2])
        assert got[i] == cache[key], f"lane {i}"
