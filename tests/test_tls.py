"""TLS 1.3 handshake (waltz/tls.py) + X25519 (utils/x25519.py).

External grounding, not just self-consistency: X25519 is pinned to the
RFC 7748 vectors and differentially checked against the OpenSSL-backed
`cryptography` implementation; the generated certificate must parse
under `cryptography.x509` and its self-signature must verify under
OpenSSL's Ed25519 — so the DER encoder, the key schedule's signing
input, and the host ed25519 oracle are all witnessed by an independent
stack. (Reference analog: src/waltz/tls/test_tls.c drives fd_tls
against OpenSSL in test_tls_openssl.c.)
"""
import os

import pytest

from firedancer_tpu.utils import ed25519_ref, x25519
from firedancer_tpu.waltz import tls


# ---------------------------------------------------------------------------
# x25519
# ---------------------------------------------------------------------------

def test_x25519_rfc7748_vectors():
    out = x25519.scalarmult(
        bytes.fromhex("a546e36bf0527c9d3b16154b82465edd"
                      "62144c0ac1fc5a18506a2244ba449ac4"),
        bytes.fromhex("e6db6867583030db3594c1a424b15f7c"
                      "726624ec26b3353b10a903a6d0ab1c4c"))
    assert out.hex() == ("c3da55379de9c6908e94ea4df28d084f"
                         "32eccf03491c71f754b4075577a28552")
    out = x25519.scalarmult(
        bytes.fromhex("4b66e9d4d1b4673c5ad22691957d6af5"
                      "c11b6421e0ea01d42ca4169e7918ba0d"),
        bytes.fromhex("e5210f12786811d3f4b7959d0538ae2c"
                      "31dbe7106fc03c3efc4cd549c715a493"))
    assert out.hex() == ("95cbde9476e8907d7aade45cb4b873f8"
                         "8b595a68799fa152e6f8f7647aac7957")


def test_x25519_rfc7748_dh():
    a = bytes.fromhex("77076d0a7318a57d3c16c17251b26645"
                      "df4c2f87ebc0992ab177fba51db92c2a")
    b = bytes.fromhex("5dab087e624a8a4b79e17f8b83800ee6"
                      "6f3bb1292618b6fd1c2f8b27ff88e0eb")
    assert x25519.pubkey(a).hex() == (
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a")
    assert x25519.pubkey(b).hex() == (
        "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f")
    shared = bytes.fromhex("4a5d9d5ba4ce2de1728e3bf480350f25"
                           "e07e21c947d19e3376f09b3c1e161742")
    assert x25519.shared(a, x25519.pubkey(b)) == shared
    assert x25519.shared(b, x25519.pubkey(a)) == shared


def test_x25519_differential_vs_openssl():
    import pytest
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
    )
    raw = serialization.Encoding.Raw, serialization.PublicFormat.Raw
    for _ in range(16):
        k = os.urandom(32)
        ours = x25519.pubkey(k)
        theirs = X25519PrivateKey.from_private_bytes(k) \
            .public_key().public_bytes(*raw)
        assert ours == theirs


def test_x25519_rejects_small_order():
    with pytest.raises(ValueError):
        x25519.shared(os.urandom(32), bytes(32))   # u=0 is small-order


# ---------------------------------------------------------------------------
# certificate
# ---------------------------------------------------------------------------

def test_cert_parses_and_verifies_under_openssl():
    import pytest
    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )
    seed = os.urandom(32)
    _, _, pub = ed25519_ref.keypair(seed)
    der = tls.make_cert(seed)
    cert = x509.load_der_x509_certificate(der)
    got = cert.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw)
    assert got == pub
    assert tls.cert_pubkey(der) == pub
    # self-signature verifies under an independent ed25519
    Ed25519PublicKey.from_public_bytes(pub).verify(
        cert.signature, cert.tbs_certificate_bytes)


# ---------------------------------------------------------------------------
# handshake
# ---------------------------------------------------------------------------

def _drive(cli, srv):
    cli.start()
    while not (srv.complete and cli.complete):
        progressed = False
        while cli.emit:
            lvl, data = cli.emit.pop(0)
            srv.on_crypto(lvl, data)
            progressed = True
        while srv.emit:
            lvl, data = srv.emit.pop(0)
            cli.on_crypto(lvl, data)
            progressed = True
        assert progressed, "handshake stalled"


def test_full_handshake_secrets_agree():
    seed = os.urandom(32)
    srv = tls.TlsServer(seed, quic_tp=b"\x05\x06")
    cli = tls.TlsClient(quic_tp=b"\x07\x08")
    _drive(cli, srv)
    for name in ("c_hs", "s_hs", "c_ap", "s_ap", "master"):
        assert getattr(srv.sched, name) == getattr(cli.sched, name)
        assert getattr(srv.sched, name) is not None
    # transport params crossed over
    assert srv.peer_quic_tp == b"\x07\x08"
    assert cli.peer_quic_tp == b"\x05\x06"
    # client learned the server identity from the certificate
    _, _, pub = ed25519_ref.keypair(seed)
    assert cli.server_pub == pub


def test_handshake_fragmented_delivery():
    """CRYPTO data arriving one byte at a time still completes."""
    seed = os.urandom(32)
    srv = tls.TlsServer(seed)
    cli = tls.TlsClient()
    cli.start()
    lvl, ch = cli.emit.pop(0)
    for i in range(len(ch)):
        srv.on_crypto(lvl, ch[i:i + 1])
    while srv.emit:
        lvl, data = srv.emit.pop(0)
        for i in range(0, len(data), 7):
            cli.on_crypto(lvl, data[i:i + 7])
    while cli.emit:
        lvl, data = cli.emit.pop(0)
        srv.on_crypto(lvl, data)
    assert srv.complete and cli.complete
    assert srv.sched.c_ap == cli.sched.c_ap


def test_client_rejects_wrong_identity():
    seed = os.urandom(32)
    srv = tls.TlsServer(seed)
    cli = tls.TlsClient(expect_pub=os.urandom(32))
    cli.start()
    lvl, ch = cli.emit.pop(0)
    srv.on_crypto(lvl, ch)
    with pytest.raises(tls.TlsError, match="identity"):
        for lvl, data in srv.emit:
            cli.on_crypto(lvl, data)


def test_client_rejects_forged_certificate_verify():
    """A MITM swapping the certificate (but not re-signing) must fail
    CertificateVerify."""
    seed = os.urandom(32)
    mitm_seed = os.urandom(32)
    srv = tls.TlsServer(seed)
    cli = tls.TlsClient()
    cli.start()
    lvl, ch = cli.emit.pop(0)
    srv.on_crypto(lvl, ch)
    (l1, sh), (l2, flight) = srv.emit
    # splice the attacker's certificate into the server flight
    msgs = list(tls.iter_messages(flight))
    out = b""
    for ht, body, raw in msgs:
        if ht == tls.HT_CERTIFICATE:
            out += tls.build_certificate(tls.make_cert(mitm_seed))
        else:
            out += raw
    cli.on_crypto(l1, sh)
    with pytest.raises(tls.TlsError):
        cli.on_crypto(l2, out)


def test_server_rejects_bad_client_finished():
    seed = os.urandom(32)
    srv = tls.TlsServer(seed)
    cli = tls.TlsClient()
    cli.start()
    lvl, ch = cli.emit.pop(0)
    srv.on_crypto(lvl, ch)
    for lvl, data in srv.emit:
        cli.on_crypto(lvl, data)
    lvl, fin = cli.emit.pop(0)
    bad = bytearray(fin)
    bad[-1] ^= 1
    with pytest.raises(tls.TlsError, match="Finished"):
        srv.on_crypto(lvl, bytes(bad))
    assert not srv.complete


def test_server_rejects_no_common_cipher():
    """A ClientHello without our suite/group is alerted, not served."""
    seed = os.urandom(32)
    srv = tls.TlsServer(seed)
    # well-formed CH but offering only an RSA-era suite and no x25519
    import struct
    body = (struct.pack(">H", tls.LEGACY_VERSION) + os.urandom(32)
            + bytes([0])
            + struct.pack(">HH", 2, 0x002F)      # TLS_RSA_AES128_CBC
            + bytes([1, 0]) + struct.pack(">H", 0))
    msg = bytes([tls.HT_CLIENT_HELLO]) \
        + len(body).to_bytes(3, "big") + body
    with pytest.raises(tls.TlsError):
        srv.on_crypto(tls.EL_INITIAL, msg)
    assert srv.alert is not None
