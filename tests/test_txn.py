"""Transaction parser tests (ref test model: src/ballet/txn/test_txn.c —
constructed vectors incl. malformed truncations)."""
import pytest

from firedancer_tpu.protocol.txn import (
    parse_txn, build_message, build_txn, TxnParseError, MTU, _cu16,
    _cu16_enc)


def _mk(n_signers=1, n_extra=2, version=-1, n_instr=1):
    signers = [bytes([i]) * 32 for i in range(1, n_signers + 1)]
    extras = [bytes([0x40 + i]) * 32 for i in range(n_extra)]
    instrs = [(n_signers + n_extra - 1, bytes([0, 1]), b"data%d" % k)
              for k in range(n_instr)]
    msg = build_message(signers, extras, b"\xbb" * 32, instrs,
                        n_ro_unsigned=1, version=version)
    sigs = [bytes([0x70 + i]) * 64 for i in range(n_signers)]
    return build_txn(sigs, msg), sigs, signers, msg


def test_compact_u16_roundtrip():
    for v in [0, 1, 0x7F, 0x80, 0x3FFF, 0x4000, 0xFFFF]:
        enc = _cu16_enc(v)
        got, off = _cu16(enc + b"rest", 0)
        assert got == v and off == len(enc)


def test_compact_u16_nonminimal_rejected():
    with pytest.raises(TxnParseError):
        _cu16(bytes([0x80, 0x00]), 0)   # 0 encoded in 2 bytes


def test_parse_legacy():
    payload, sigs, signers, msg = _mk()
    t = parse_txn(payload)
    assert t.version == -1
    assert t.sig_cnt == 1
    assert t.signatures(payload) == sigs
    assert t.signer_pubkeys(payload) == signers
    assert t.message(payload) == msg
    assert t.acct_cnt == 3
    assert len(t.instrs) == 1
    assert t.instrs[0].prog_idx == 2
    # fee payer writable; extras: first writable, last readonly
    assert t.is_writable(0) and t.is_writable(1) and not t.is_writable(2)


def test_parse_v0():
    payload, sigs, signers, msg = _mk(version=0)
    t = parse_txn(payload)
    assert t.version == 0
    assert t.alut_cnt == 0
    assert t.message(payload) == msg


def test_parse_multisig():
    payload, sigs, signers, _ = _mk(n_signers=3)
    t = parse_txn(payload)
    assert t.sig_cnt == 3
    assert t.signatures(payload) == sigs
    assert t.signer_pubkeys(payload) == signers


def test_parse_rejects_malformed():
    payload, *_ = _mk()
    with pytest.raises(TxnParseError):
        parse_txn(payload[:-1])          # trailing truncation
    with pytest.raises(TxnParseError):
        parse_txn(payload + b"\x00")     # trailing garbage
    with pytest.raises(TxnParseError):
        parse_txn(payload[:10])          # truncated sigs
    with pytest.raises(TxnParseError):
        parse_txn(b"\x00" + payload[1:])  # zero sigs
    with pytest.raises(TxnParseError):
        parse_txn(b"\x00" * (MTU + 1))   # over MTU
    # header signer count != sig count
    bad = bytearray(payload)
    t = parse_txn(payload)
    bad[t.msg_off] = 2
    with pytest.raises(TxnParseError):
        parse_txn(bytes(bad))


def test_parse_payload_ending_after_sigs():
    # payload that ends immediately after the signatures must raise
    # TxnParseError, not IndexError (advisor finding r1: remote DoS)
    with pytest.raises(TxnParseError):
        parse_txn(b"\x01" + b"\xab" * 64)


def test_mtu_sized_txn():
    # pad instruction data until exactly MTU
    payload, *_ = _mk()
    room = MTU - len(payload) - 3  # cu16(len) grows by <=2 bytes
    signers = [bytes([1]) * 32]
    extras = [bytes([0x41]) * 32, bytes([0x42]) * 32]
    msg = build_message(signers, extras, b"\xbb" * 32,
                        [(2, bytes([0, 1]), b"x" * (room + 5 - 64))],
                        n_ro_unsigned=1)
    txn = build_txn([bytes(64)], msg)
    assert len(txn) <= MTU
    t = parse_txn(txn)
    assert t.instrs[0].data_sz >= room - 64


def test_native_parser_differential():
    """Fuzz the C++ batch parser (fdtpu_txn_parse_batch) against the
    Python spec parser on valid, mutated, and random payloads."""
    import numpy as np
    from firedancer_tpu.protocol.txn import build_txn, build_message
    from firedancer_tpu.tiles.verify import parse_batch

    rng = np.random.default_rng(77)
    payloads = []
    for i in range(300):
        kind = i % 3
        if kind == 0:
            n_sig = int(rng.integers(1, 4))
            signers = [bytes(rng.integers(0, 256, 32, np.uint8).tobytes())
                       for _ in range(n_sig)]
            extra = [bytes(rng.integers(0, 256, 32, np.uint8).tobytes())
                     for _ in range(int(rng.integers(0, 3)))]
            instrs = [(0, bytes([0]),
                       rng.integers(0, 256,
                                    int(rng.integers(0, 40)),
                                    np.uint8).tobytes())
                      for _ in range(int(rng.integers(0, 3)))]
            m = build_message(signers, extra,
                              bytes(32), instrs,
                              n_ro_signed=0, n_ro_unsigned=len(extra) and 1,
                              version=int(rng.integers(0, 2)) - 1)
            p = build_txn([bytes(64) for _ in range(n_sig)], m)
            if kind == 0 and i % 6 == 3:   # mutate a byte
                p = bytearray(p)
                p[int(rng.integers(0, len(p)))] ^= int(rng.integers(1, 256))
                p = bytes(p)
        elif kind == 1:
            p = rng.integers(0, 256, int(rng.integers(1, 200)),
                             np.uint8).tobytes()
        else:
            p = rng.integers(0, 256, int(rng.integers(1, 1232)),
                             np.uint8).tobytes()
        payloads.append(p)

    stride = 1232
    buf = np.zeros((len(payloads), stride), np.uint8)
    sizes = np.zeros((len(payloads),), np.uint32)
    for i, p in enumerate(payloads):
        buf[i, :len(p)] = np.frombuffer(p, np.uint8)
        sizes[i] = len(p)
    meta, tags = parse_batch(buf, sizes, b"\x00" * 16)

    from firedancer_tpu.protocol.txn import parse_txn, TxnParseError
    for i, p in enumerate(payloads):
        try:
            t = parse_txn(p)
            want = (1, t.sig_cnt, t.sig_off, t.msg_off, t.acct_off,
                    t.acct_cnt, t.version)
        except (TxnParseError, ValueError, IndexError):
            want = None
        got = tuple(int(x) for x in meta[i, :7]) if meta[i, 0] else None
        assert got == want, (i, got, want, p.hex())

    # dedup tags: keyed on the first signature — equal payloads tag
    # equal, distinct first sigs tag distinct, and the key matters
    parsed = [i for i in range(len(payloads)) if meta[i, 0]]
    if len(parsed) >= 2:
        i, j = parsed[0], parsed[1]
        dup = np.stack([buf[i], buf[i], buf[j]])
        dsz = np.asarray([sizes[i], sizes[i], sizes[j]], np.uint32)
        m2, t2 = parse_batch(dup, dsz, b"\x00" * 16)
        assert t2[0] == t2[1]
        sig_i = bytes(buf[i][int(meta[i, 2]):int(meta[i, 2]) + 64])
        sig_j = bytes(buf[j][int(meta[j, 2]):int(meta[j, 2]) + 64])
        if sig_i != sig_j:
            assert t2[0] != t2[2]
        _, t3 = parse_batch(dup, dsz, b"\x01" * 16)
        assert t3[0] != t2[0]       # seed actually keys the hash
