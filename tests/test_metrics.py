"""Metrics subsystem tests: named-slot ABI, wait/work/tpu histograms,
per-link telemetry, SLO engine, prometheus exposition + metric tile
endpoints (ref: src/disco/metrics/fd_metrics.h:6-40, fd_prometheus.c,
fd_metric_tile.c; histograms src/util/hist/fd_histf.h).

The exposition is validated by a STRICT text-format parser below —
every emitted line must parse, every sample's family must be TYPE-
declared first, labels must unescape, and histograms must be
cumulative-monotone with +Inf == _count (including the raced-flush
clamp in metrics.py::_render_hist).
"""
import json
import os
import re
import time
import urllib.request

import pytest

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.disco.metrics import (
    HIST_U64, NBUCKETS, HistAccum, bucket_of, quantile_ns, read_hists,
    read_link_metrics, render_prometheus,
)
from firedancer_tpu.disco.monitor import attach, snapshot

# the histogram/quantile/parser/SLO unit tests below run in tier-1;
# only the live-topology pipeline tests are slow-marked (the fixture
# spawns processes and compiles the verify jit)
slow = pytest.mark.slow
slo = pytest.mark.slo


# ---------------------------------------------------------------------------
# strict prometheus text-format parser (the test-side contract)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # family
    r"(?:\{(.*)\})?"                        # optional label block
    r" (-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|\+?Inf|NaN))$")
_VALID_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


def _parse_labels(s: str) -> dict:
    """Parse `k="v",...` with exposition-format escapes; assert on any
    malformed label (unterminated string, bad escape, dup key)."""
    out: dict = {}
    i = 0
    while i < len(s):
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', s[i:])
        assert m, f"bad label at ...{s[i:]!r}"
        key = m.group(1)
        i += m.end()
        val = []
        while True:
            assert i < len(s), f"unterminated label value for {key}"
            ch = s[i]
            if ch == "\\":
                assert i + 1 < len(s) and s[i + 1] in '\\"n', \
                    f"bad escape in label {key}"
                val.append({"\\": "\\", '"': '"', "n": "\n"}[s[i + 1]])
                i += 2
                continue
            if ch == '"':
                i += 1
                break
            assert ch != "\n", f"raw newline in label {key}"
            val.append(ch)
            i += 1
        assert key not in out, f"duplicate label {key}"
        out[key] = "".join(val)
        if i < len(s):
            assert s[i] == ",", f"expected ',' at ...{s[i:]!r}"
            i += 1
    return out


def parse_prometheus(text: str):
    """Validate a whole exposition; returns (types, samples) where
    samples = [(family, labels, value)]. Histogram families are
    checked for le-ordering, cumulative monotonicity, +Inf presence,
    _count == +Inf and _sum presence."""
    assert text.endswith("\n"), "exposition must end with a newline"
    types: dict[str, str] = {}
    samples: list[tuple[str, dict, float]] = []
    for lineno, line in enumerate(text.splitlines(), 1):
        assert line == line.rstrip(), f"line {lineno}: trailing space"
        assert line, f"line {lineno}: blank line"
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, f"line {lineno}: bad TYPE"
            _, _, name, typ = parts
            assert _NAME_RE.match(name), f"line {lineno}: bad name"
            assert typ in _VALID_TYPES, f"line {lineno}: bad type"
            assert name not in types, f"line {lineno}: dup TYPE {name}"
            types[name] = typ
            continue
        if line.startswith("#"):
            continue                     # HELP/comment
        m = _SAMPLE_RE.match(line)
        assert m, f"line {lineno}: unparseable sample {line!r}"
        name, labels_s, value_s = m.groups()
        labels = _parse_labels(labels_s) if labels_s else {}
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                family = base
        assert family in types, \
            f"line {lineno}: sample {name!r} before its TYPE"
        if types[family] == "histogram" and name.endswith("_bucket"):
            assert "le" in labels, f"line {lineno}: bucket without le"
        value = float("inf") if value_s in ("+Inf", "Inf") \
            else float(value_s)
        samples.append((name, labels, value))
    # histogram structural checks
    hist_series: dict[tuple, list] = {}
    sums, counts = {}, {}
    for name, labels, value in samples:
        for suffix, store in (("_sum", sums), ("_count", counts)):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                store[(base, tuple(sorted(labels.items())))] = value
        base = name[:-7] if name.endswith("_bucket") else None
        if base and types.get(base) == "histogram":
            key = (base, tuple(sorted((k, v) for k, v in labels.items()
                                      if k != "le")))
            le = float("inf") if labels["le"] == "+Inf" \
                else float(labels["le"])
            hist_series.setdefault(key, []).append((le, value))
    for (base, lab), buckets in hist_series.items():
        les = [le for le, _ in buckets]
        assert les == sorted(les), f"{base}{lab}: le out of order"
        cum = [v for _, v in buckets]
        assert cum == sorted(cum), f"{base}{lab}: non-monotone buckets"
        assert les[-1] == float("inf"), f"{base}{lab}: no +Inf bucket"
        assert (base, lab) in counts, f"{base}{lab}: missing _count"
        assert (base, lab) in sums, f"{base}{lab}: missing _sum"
        assert counts[(base, lab)] == cum[-1], \
            f"{base}{lab}: _count != +Inf bucket"
    return types, samples


def test_bucket_of_log2():
    assert bucket_of(0) == 0
    assert bucket_of(1) == 0
    assert bucket_of(2) == 1
    assert bucket_of(3) == 1
    assert bucket_of(1024) == 10
    assert bucket_of(1 << 60) == NBUCKETS - 1


def test_quantile_upper_bound():
    h = HistAccum()
    for ns in [10, 10, 10, 10_000]:
        h.add(ns)
    d = {"count": h.count, "sum_ns": h.sum_ns, "buckets": h.buckets}
    assert quantile_ns(d, 0.5) == 16          # 2^(3+1): bucket of 10
    assert quantile_ns(d, 0.99) == 16384      # 2^(13+1): bucket of 10_000
    assert quantile_ns({"count": 0, "sum_ns": 0,
                        "buckets": [0] * NBUCKETS}, 0.5) == 0


def test_quantile_edges_empty_and_q0_q1():
    """Histogram edge pins: an EMPTY histogram is 0 at every q; q=0.0
    is the minimum sample's bucket bound — NOT bucket 0's bound when
    bucket 0 is empty — and q=1.0 is the maximum sample's bound."""
    empty = {"count": 0, "sum_ns": 0, "buckets": [0] * NBUCKETS}
    assert quantile_ns(empty, 0.0) == 0
    assert quantile_ns(empty, 1.0) == 0
    h = HistAccum()
    for ns in [10, 10_000]:
        h.add(ns)
    d = {"count": h.count, "sum_ns": h.sum_ns, "buckets": h.buckets}
    assert quantile_ns(d, 0.0) == 16          # min sample's bucket (10)
    assert quantile_ns(d, 1.0) == 16384       # max sample's bucket (10k)
    # a single sample far from bucket 0: q=0 must still find it
    h1 = HistAccum()
    h1.add(1 << 20)
    d1 = {"count": h1.count, "sum_ns": h1.sum_ns, "buckets": h1.buckets}
    assert quantile_ns(d1, 0.0) == quantile_ns(d1, 1.0) == 1 << 21


def test_flush_into_is_idempotent():
    """flush_into overwrites (cumulative counts, single writer): a
    second flush with no new samples must add NOTHING — a += bug here
    would double every counter each housekeeping pass."""
    import numpy as np
    h = HistAccum()
    for ns in [5, 50, 500]:
        h.add(ns)
    view = np.zeros(HIST_U64, np.uint64)
    h.flush_into(view)
    first = view.copy()
    h.flush_into(view)                    # no adds in between
    assert (view == first).all()
    assert int(view[0]) == 3 and int(view[1]) == 555
    assert int(view[2:].sum()) == 3
    h.add(7)                              # and a real add still lands
    h.flush_into(view)
    assert int(view[0]) == 4 and int(view[2:].sum()) == 4


# ---------------------------------------------------------------------------
# parser self-tests (a validator that cannot reject is no validator)
# ---------------------------------------------------------------------------

@slo
def test_parser_rejects_malformed_expositions():
    with pytest.raises(AssertionError, match="before its TYPE"):
        parse_prometheus('orphan{a="b"} 1\n')
    with pytest.raises(AssertionError, match="bad label"):
        parse_prometheus("# TYPE x counter\nx{a=b} 1\n")
    with pytest.raises(AssertionError, match="newline"):
        parse_prometheus("# TYPE x counter\nx 1")
    with pytest.raises(AssertionError, match="non-monotone"):
        parse_prometheus(
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\n'
            "h_sum 1\nh_count 3\n")
    with pytest.raises(AssertionError, match="no \\+Inf"):
        parse_prometheus(
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 5\nh_sum 1\nh_count 5\n')
    with pytest.raises(AssertionError, match="_count"):
        parse_prometheus(
            "# TYPE h histogram\n"
            'h_bucket{le="+Inf"} 5\nh_sum 1\nh_count 4\n')
    with pytest.raises(AssertionError, match="bad escape"):
        parse_prometheus('# TYPE x counter\nx{a="\\q"} 1\n')
    # the good shape parses
    types, samples = parse_prometheus(
        "# TYPE h histogram\n"
        'h_bucket{t="a\\"b",le="1"} 2\nh_bucket{t="a\\"b",le="+Inf"} 5\n'
        'h_sum{t="a\\"b"} 1.5\nh_count{t="a\\"b"} 5\n')
    assert types == {"h": "histogram"}
    assert samples[0][1]["t"] == 'a"b'   # label unescaping


# ---------------------------------------------------------------------------
# in-process drills: link-telemetry ABI + exposition, no process spawn
# ---------------------------------------------------------------------------

def _mk_inline(plan, tile_name):
    """Construct a tile adapter + stem inside THIS process (the tier-1
    way to exercise the stem's telemetry feed without multi-process
    overhead); callers alternate bounded stem.run(max_iters=...)."""
    from firedancer_tpu.disco.stem import Stem
    from firedancer_tpu.disco.tiles import REGISTRY
    from firedancer_tpu.disco.topo import TileCtx
    ctx = TileCtx(plan, tile_name)
    adapter = REGISTRY[plan["tiles"][tile_name]["kind"]](
        ctx, plan["tiles"][tile_name]["args"])
    return ctx, adapter, Stem(ctx, adapter)


@slo
def test_link_telemetry_abi_end_to_end_inline():
    """synth -> sink through real rings + stems, single process: the
    per-link blocks must agree with the tile-side truth — published ==
    consumed (lossless run), byte counts equal on both sides of the
    hop, the consume-latency histogram populated, and the rendered
    fdtpu_link_* series parser-clean."""
    from firedancer_tpu.runtime import Workspace
    topo = (
        Topology(f"lm{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=64, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=96, unique=8, burst=8)
        .tile("b", "sink", ins=["a_b"])
    )
    plan = topo.build()
    w = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                  create=False)
    try:
        ctx_a, _, stem_a = _mk_inline(plan, "a")
        ctx_b, _, stem_b = _mk_inline(plan, "b")
        for _ in range(6):               # alternate producer/consumer
            stem_a.run(max_iters=40)     # (credit-gated: synth blocks
            stem_b.run(max_iters=40)     #  at depth until sink drains)
        links = read_link_metrics(w, plan)
        rec = links["a_b"]
        assert rec["producer"] == "a"
        assert rec["pub"] == 96
        cons = rec["consumers"]["b"]
        assert cons["consumed"] == 96
        assert cons["bytes"] == rec["pub_bytes"] > 0
        assert cons["overruns"] == 0
        assert cons["hist"]["count"] > 0
        assert sum(cons["hist"]["buckets"]) == cons["hist"]["count"]
        # the rendered per-link series are parser-clean and carry the
        # link/producer/consumer labels
        text = render_prometheus(plan, w)
        types, samples = parse_prometheus(text)
        assert types["fdtpu_link_consume_seconds"] == "histogram"
        by_name = {}
        for name, labels, value in samples:
            by_name.setdefault(name, []).append((labels, value))
        (labels, value), = by_name["fdtpu_link_pub"]
        assert labels["link"] == "a_b" and labels["producer"] == "a"
        assert value == 96
        (labels, value), = by_name["fdtpu_link_consumed"]
        assert labels["consumer"] == "b" and value == 96
        (labels, value), = by_name["fdtpu_link_lag"]
        assert value == 0
        # the monitor surfaces the same telemetry (links table + the
        # --json document shape)
        from firedancer_tpu.disco.monitor import (format_links,
                                                  full_snapshot)
        doc = full_snapshot(plan, w)
        assert doc["links"]["a_b"]["consumers"]["b"]["consumed"] == 96
        table = format_links(doc["links"])
        assert "a_b" in table and "p99us" in table
        hist_count = cons["hist"]["count"]
        ctx_a.close()
        ctx_b.close()
        # restart continuity: a respawned tile (fresh TileCtx + stem,
        # exactly what the supervisor spawns) must RESUME the link's
        # cumulative series from shm, not rewind it — a zeroed flush
        # would turn everything consumed before the restart into
        # per-hop loss
        ctx_a2, _, stem_a2 = _mk_inline(plan, "a")
        ctx_b2, _, stem_b2 = _mk_inline(plan, "b")
        assert ctx_b2.in_rings["a_b"].m_consumed == 96
        assert ctx_a2.out_rings["a_b"].m_pub == 96
        stem_a2._flush_metrics()
        stem_b2._flush_metrics()
        rec = read_link_metrics(w, plan)["a_b"]
        assert rec["pub"] == 96
        assert rec["consumers"]["b"]["consumed"] == 96
        assert rec["consumers"]["b"]["hist"]["count"] == hist_count
        ctx_a2.close()
        ctx_b2.close()
    finally:
        w.close()
        Workspace.unlink_name(plan["wksp"]["name"])


@slo
def test_old_plan_hist_region_not_overread():
    """Version skew: a plan carved by a pre-tpu build holds a 2-kind
    hist region (and records no hist_u64 key). Readers and the stem
    must size their views from the PLAN, not the current
    HIST_REGION_U64 — reading 3 kinds there would decode the adjacent
    allocation as the tpu histogram (and a stem would flush over it)."""
    from firedancer_tpu.runtime import Workspace
    topo = (
        Topology(f"hv{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=64, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=8, unique=8)
        .tile("b", "sink", ins=["a_b"])
    )
    plan = topo.build()
    w = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                  create=False)
    try:
        # current plans record the region length
        assert plan["tiles"]["b"]["hist_u64"] == 3 * HIST_U64
        # simulate attaching to an old topology: 2-kind region, no key
        old = json.loads(json.dumps(plan))
        del old["tiles"]["b"]["hist_u64"]
        hists = read_hists(w, old, "b")
        assert sorted(hists) == ["wait", "work"]     # no phantom tpu
        ctx, _, stem = _mk_inline(old, "b")
        assert len(ctx.hist_view()) == 2 * HIST_U64
        # poison the u64 right after the old-sized region; a flush
        # through the old plan must leave it untouched
        import numpy as np
        sentinel_off = old["tiles"]["b"]["hist_off"] + 2 * HIST_U64 * 8
        view = w.view(sentinel_off, 8).view(np.uint64)
        view[0] = 0xDEADBEEF
        stem._hists["work"].add(100)
        stem._flush_metrics()
        assert int(view[0]) == 0xDEADBEEF
        ctx.close()
    finally:
        w.close()
        Workspace.unlink_name(plan["wksp"]["name"])


@slo
def test_render_clamps_raced_flush_and_escapes_labels():
    """A reader racing a flush can see count written ahead of buckets
    (metrics.py:flush order); the renderer must clamp +Inf/_count to
    stay monotone — and tile names with quotes/backslashes must
    escape. The whole document is run through the strict parser."""
    from firedancer_tpu.runtime import Workspace
    import numpy as np
    topo = (
        Topology(f"esc{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=64, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=8)
        .tile('we"ird\\tile', "sink", ins=["a_b"])
    )
    plan = topo.build()
    w = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                  create=False)
    try:
        # simulate the raced flush: count > sum(buckets) in shm
        off = plan["tiles"]['we"ird\\tile']["hist_off"]
        hv = w.view(off, HIST_U64 * 8).view(np.uint64)
        hv[2] = 3                        # work hist handled separately
        hv[0] = 7                        # count ahead of buckets
        hv[1] = 1000
        text = render_prometheus(plan, w)
        types, samples = parse_prometheus(text)   # must not raise
        waits = [(labels, v) for name, labels, v in samples
                 if name == "fdtpu_poll_wait_seconds_count"
                 and labels["tile"] == 'we"ird\\tile']
        assert waits and waits[0][1] == 7         # clamped to count
    finally:
        w.close()
        Workspace.unlink_name(plan["wksp"]["name"])


# ---------------------------------------------------------------------------
# SLO engine units (schema, grammar, burn windows)
# ---------------------------------------------------------------------------

@slo
def test_slo_schema_and_grammar():
    from firedancer_tpu.disco.slo import (SLO_DEFAULTS, TARGET_KEYS,
                                          normalize_slo, parse_expr)
    from firedancer_tpu.lint import registry as reg
    # registry mirror stays honest (the fdlint side of the schema)
    assert set(reg.SLO_SECTION_KEYS) == set(SLO_DEFAULTS)
    assert set(reg.SLO_TARGET_KEYS) == set(TARGET_KEYS)
    norm = normalize_slo(None)
    assert norm["target"] == [] and norm["fast_window_s"] > 0
    p = parse_expr("verify.work p99 < 500us")
    assert p == {"kind": "hist", "tile": "verify", "hist": "work",
                 "agg": "p99", "op": "<", "threshold": 500_000.0}
    p = parse_expr("sink.rx rate > 100/s")
    assert p["kind"] == "metric" and p["agg"] == "rate" \
        and p["threshold"] == 100.0
    p = parse_expr("link.a_b.backpressure rate < 1/s")
    assert p["kind"] == "link" and p["counter"] == "backpressure"
    with pytest.raises(ValueError, match="did you mean 'fast_window_s'"):
        normalize_slo({"fast_windw_s": 1})
    with pytest.raises(ValueError, match="unknown aggregation"):
        parse_expr("verify.work p98 < 1ms")
    with pytest.raises(ValueError, match="unknown operator"):
        parse_expr("verify.work p99 != 1ms")
    with pytest.raises(ValueError, match="duration unit"):
        parse_expr("verify.work p99 < 500")
    with pytest.raises(ValueError, match="rate"):
        parse_expr("sink.rx value > 100/s")
    with pytest.raises(ValueError, match="duplicate slo target"):
        normalize_slo({"target": [
            {"name": "x", "expr": "a.b > 1"},
            {"name": "x", "expr": "a.b > 2"}]})
    with pytest.raises(ValueError, match="burn_fast"):
        normalize_slo({"burn_fast": 1.5})
    # per-target overrides pass the same range gates as the section
    # (an unreachable burn would make the objective silently dead)
    with pytest.raises(ValueError, match="burn_fast"):
        normalize_slo({"target": [
            {"name": "x", "expr": "a.b > 1", "burn_fast": 1.5}]})
    with pytest.raises(ValueError, match="fast_window_s"):
        normalize_slo({"target": [
            {"name": "x", "expr": "a.b > 1", "fast_window_s": -1}]})
    # sample history is pruned to the slow window: a fast window past
    # it could never be covered, killing the acute breach path
    with pytest.raises(ValueError, match="<= slow_window_s"):
        normalize_slo({"fast_window_s": 120.0, "slow_window_s": 60.0})
    with pytest.raises(ValueError, match="<= slow_window_s"):
        normalize_slo({"target": [
            {"name": "x", "expr": "a.b > 1", "fast_window_s": 90.0}]})


@slo
def test_slo_burn_windows_with_fake_clock():
    """Burn-rate semantics against a scripted value source: no breach
    before the fast window is COVERED, breach once the window is all
    bad, clear only after the fast window is clean and the slow
    window's bad fraction drops under burn_slow."""
    from firedancer_tpu.disco.slo import SloEngine, normalize_slo
    cfg = normalize_slo({
        "fast_window_s": 1.0, "slow_window_s": 4.0,
        "burn_fast": 1.0, "burn_slow": 0.5,
        "target": [{"name": "lat", "expr": "v.work p99 < 1ms"}]})
    plan = {"topology": "fake", "tiles": {"v": {}}, "links": {},
            "slo": cfg}
    clock_now = [0.0]
    eng = SloEngine(plan, None, clock=lambda: clock_now[0], dump=False)
    values = [2e6]                       # scripted p99 values (ns)
    eng._read = lambda st, now: float(values[0])
    evs = []
    for _ in range(9):                   # 0.0 .. 1.2s, all bad
        evs += eng.sample()
        clock_now[0] += 0.15
    assert eng.breached == 1
    assert [e["kind"] for e in evs] == ["breach"]
    assert eng.total_breaches == 1
    # recovery: good values — fast window empties of bad samples but
    # the slow window still carries them until they age out
    values[0] = 5e5
    for _ in range(8):                   # +1.2s of good
        evs += eng.sample()
        clock_now[0] += 0.15
    assert eng.breached == 1             # slow window still >= 0.5 bad
    for _ in range(12):                  # bad samples age out of 4s
        evs += eng.sample()
        clock_now[0] += 0.15
    assert eng.breached == 0
    assert [e["kind"] for e in evs] == ["breach", "clear"]


@slo
def test_slo_fast_path_alive_when_windows_equal():
    """fast_window_s == slow_window_s passes validation, so the acute
    path must still fire there: coverage comes from the PRE-prune
    oldest sample — the post-prune oldest is >= now - slow_w by
    construction, which once left the fast path silently dead and the
    objective unmonitored at burn_fast < 1 <= burn_slow."""
    from firedancer_tpu.disco.slo import SloEngine, normalize_slo
    cfg = normalize_slo({
        "fast_window_s": 2.0, "slow_window_s": 2.0,
        "burn_fast": 0.5, "burn_slow": 1.0,
        "target": [{"name": "lat", "expr": "v.work p99 < 1ms"}]})
    plan = {"topology": "fake", "tiles": {"v": {}}, "links": {},
            "slo": cfg}
    clock_now = [0.0]
    eng = SloEngine(plan, None, clock=lambda: clock_now[0], dump=False)
    values = [2e6, 5e5]                  # alternate bad / good: 50%
    eng._read = lambda st, now: float(values[eng.evals % 2])
    for _ in range(40):                  # 5.2s of 50%-bad samples
        eng.sample()
        clock_now[0] += 0.13
    assert eng.breached == 1 and eng.total_breaches >= 1


# ---------------------------------------------------------------------------
# live acceptance: chaos stall -> backpressure ticks -> SLO breach ->
# EV_SLO in the trace ring, /metrics parser-clean (tier-1: no jax)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@slo
def test_stall_fseq_drives_backpressure_and_slo_breach():
    """The fdmetrics-v2 acceptance drill on a live chaos topology: a
    stall_fseq fault on the sink freezes its fseq publication; the
    producer's publish path starts taking backpressure ticks on the
    link; the SLO engine's fast window flips slo_breach on the metric
    tile; the breach leaves an EV_SLO event in the metric tile's
    flight-recorder ring and a dump next to the supervisor black
    boxes; and GET /metrics stays parser-clean with the fdtpu_link_*
    series showing the damage."""
    from firedancer_tpu.disco.slo import slo_dump_path
    from firedancer_tpu.trace import read_rings
    topo = (
        Topology(f"slo{os.getpid()}", wksp_size=1 << 22,
                 trace={"enable": True, "depth": 1024, "sample": 1},
                 slo={"fast_window_s": 0.5, "slow_window_s": 10.0,
                      "target": [{
                          "name": "sink-bp",
                          "expr": "link.a_b.backpressure rate < 5/s"}]})
        .link("a_b", depth=32, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=1_000_000, unique=16,
              burst=8)
        .tile("b", "sink", ins=["a_b"],
              chaos={"events": [{"action": "stall_fseq", "at_rx": 8}]})
        .tile("metric", "metric", port=0)
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        deadline = time.time() + 60
        while time.time() < deadline:
            runner.check_failures()
            if runner.metrics("metric").get("slo_breach", 0) >= 1:
                break
            time.sleep(0.05)
        m = runner.metrics("metric")
        assert m["slo_breach"] == 1, m
        assert m["slo_breaches"] >= 1 and m["slo_evals"] > 0
        # the fault drove backpressure ticks on the affected link
        links = read_link_metrics(runner.wksp, runner.plan)
        assert links["a_b"]["backpressure"] > 0
        # EV_SLO is recoverable from the metric tile's trace ring
        evs = read_rings(runner.plan, runner.wksp)["metric"]
        slo_evs = [e for e in evs if e["ev"] == "slo"]
        assert slo_evs and slo_evs[0]["count"] == 0   # target index
        # breach dump landed next to the supervisor black boxes
        path = slo_dump_path(runner.plan["topology"], "sink-bp")
        with open(path) as f:
            dump = json.load(f)
        assert dump["target"] == "sink-bp" \
            and dump["expr"].startswith("link.a_b")
        os.unlink(path)                  # test hygiene (/dev/shm)
        # /metrics: parser-clean, link series present and nonzero
        port = runner.metrics("metric")["port"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            body = r.read().decode()
        types, samples = parse_prometheus(body)
        bp = [v for name, labels, v in samples
              if name == "fdtpu_link_backpressure"
              and labels["link"] == "a_b"]
        assert bp and bp[0] > 0
        breach = [v for name, labels, v in samples
                  if name == "fdtpu_tile_gauge"
                  and labels.get("name") == "slo_breach"]
        assert breach == [1]
        # liveness roll-up stays healthy: a burning SLO is a service
        # problem, not a liveness one (every tile still heartbeats)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            health = json.loads(r.read())
        assert r.status == 200 and health["ok"]
        assert health["slo_breached"] == ["sink-bp"]
        # monitor --json: one machine-readable document off the same
        # shm, attached by topology name alone
        import contextlib
        import io
        from firedancer_tpu.disco import monitor as mon
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = mon.main([runner.plan["topology"], "--json"])
        assert rc == 0
        doc = json.loads(buf.getvalue())
        assert doc["links"]["a_b"]["backpressure"] > 0
        assert doc["tiles"]["b"]["state"] == "run"
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()


@pytest.fixture(scope="module")
def pipeline():
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"tm{os.getpid()}", wksp_size=1 << 23)
        .link("s_k", depth=64, mtu=1280)
        .tile("synth", "synth", outs=["s_k"], count=32, unique=8, seed=9)
        .tile("sink", "sink", ins=["s_k"])
        .tile("metric", "metric", port=0)
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=120)
        runner.wait_idle("sink", "rx", 32, timeout_s=120)
        yield runner
    finally:
        runner.halt()
        runner.close()


@slow
def test_plan_carries_slot_names(pipeline):
    tiles = pipeline.plan["tiles"]
    assert tiles["synth"]["metrics_names"] == \
        ["tx", "backpressure", "attack_tx", "attack_drop"]
    assert tiles["sink"]["metrics_names"] == ["rx", "bytes", "overruns"]
    # readers resolve by plan names — values land under the right keys
    # (synth publishes its whole count in one poll; give its NEXT
    # housekeeping flush a moment to land in shm)
    deadline = time.time() + 30
    while time.time() < deadline \
            and pipeline.metrics("synth")["tx"] < 32:
        time.sleep(0.05)
    assert pipeline.metrics("synth")["tx"] == 32
    assert pipeline.metrics("sink")["rx"] == 32


@slow
def test_histograms_populate(pipeline):
    # one housekeeping flush after the traffic
    deadline = time.time() + 30
    while time.time() < deadline:
        h = read_hists(pipeline.wksp, pipeline.plan, "sink")
        if h and h["work"]["count"] > 0 and h["wait"]["count"] > 0:
            break
        time.sleep(0.05)
    assert h["work"]["count"] > 0, "sink did work but no work samples"
    assert h["wait"]["count"] > 0, "sink idled but no wait samples"
    assert h["work"]["sum_ns"] > 0
    assert sum(h["work"]["buckets"]) == h["work"]["count"]
    # monitor surfaces latency quantiles
    plan, wksp = attach(pipeline.plan["topology"])
    try:
        snap = snapshot(plan, wksp)
        lat = snap["sink"]["latency"]
        assert lat["work"]["count"] > 0
        assert lat["work"]["p99_us"] >= lat["work"]["p50_us"] > 0
    finally:
        wksp.close()


@slow
def test_prometheus_endpoint(pipeline):
    port = pipeline.metrics("metric")["port"]
    assert port > 0
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        assert r.status == 200
        body = r.read().decode()
    assert '# TYPE fdtpu_tile_metric counter' in body
    assert 'tile="sink"' in body and 'name="rx"} 32' in body
    # histogram exposition: cumulative buckets, monotone, +Inf == count
    lines = [ln for ln in body.splitlines()
             if ln.startswith('fdtpu_poll_work_seconds_bucket{'
                              'topology') and 'tile="sink"' in ln]
    assert lines, body[:500]
    cum = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert cum == sorted(cum)
    count_ln = [ln for ln in body.splitlines()
                if ln.startswith("fdtpu_poll_work_seconds_count")
                and 'tile="sink"' in ln]
    assert int(count_ln[0].rsplit(" ", 1)[1]) == cum[-1]
    # scrape counter advanced
    deadline = time.time() + 15
    while time.time() < deadline:
        if pipeline.metrics("metric")["scrapes"] >= 1:
            break
        time.sleep(0.05)
    assert pipeline.metrics("metric")["scrapes"] >= 1


# ---------------------------------------------------------------------------
# e2e: the metric tile over a live synth -> verify -> sink topology
# (device telemetry + per-link series + healthz; slow: verify compile)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def verify_pipeline():
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    n = 48
    topo = (
        Topology(f"vm{os.getpid()}", wksp_size=1 << 23,
                 trace={"enable": True, "depth": 1024, "sample": 1},
                 slo={"fast_window_s": 1.0, "target": [
                     {"name": "verify-latency",
                      "expr": "verify.work p99 < 30s"}]})
        .link("s_v", depth=128, mtu=1280)
        .link("v_k", depth=128, mtu=1280)
        .tcache("tc", depth=1024)
        .tile("synth", "synth", outs=["s_v"], count=n, unique=n,
              seed=3)
        .tile("verify", "verify", ins=["s_v"], outs=["v_k"],
              batch=16, tcache="tc")
        .tile("sink", "sink", ins=["v_k"])
        .tile("metric", "metric", port=0)
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=600)
        runner.wait_idle("sink", "rx", n, timeout_s=600)
        yield runner
    finally:
        runner.halt()
        runner.close()


@slow
@slo
def test_metric_tile_e2e_tpu_and_link_series(verify_pipeline):
    """GET /metrics on a live verify topology: parser-clean text with
    fdtpu_link_* per-link series (every hop, with per-hop loss) and
    fdtpu_tile_tpu_* device telemetry (dispatch/readback histogram +
    jit/memory/inflight gauges from the verify tile)."""
    runner = verify_pipeline
    # one housekeeping flush after the traffic so the tpu hist landed
    deadline = time.time() + 30
    while time.time() < deadline:
        h = read_hists(runner.wksp, runner.plan, "verify")
        if h and h["tpu"]["count"] > 0:
            break
        time.sleep(0.05)
    assert h["tpu"]["count"] > 0, "verify dispatched but no tpu samples"
    port = runner.metrics("metric")["port"]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        assert r.status == 200
        body = r.read().decode()
    types, samples = parse_prometheus(body)
    # device telemetry series (the fdtpu_tile_tpu_* family)
    assert types["fdtpu_tile_tpu_seconds"] == "histogram"
    assert types["fdtpu_tile_tpu_jit_compiles"] == "gauge"
    by = {}
    for name, labels, value in samples:
        by.setdefault(name, []).append((labels, value))
    tpu_counts = [v for labels, v in by["fdtpu_tile_tpu_seconds_count"]
                  if labels["tile"] == "verify"]
    assert tpu_counts and tpu_counts[0] > 0
    (labels, compiles), = by["fdtpu_tile_tpu_jit_compiles"]
    assert labels["tile"] == "verify" and compiles >= 1
    # per-link series cover both hops with zero loss
    pubs = {labels["link"]: v for labels, v in by["fdtpu_link_pub"]}
    assert pubs["s_v"] == 48 and pubs["v_k"] == 48
    lags = {labels["link"]: v for labels, v in by["fdtpu_link_lag"]}
    assert lags == {"s_v": 0, "v_k": 0}
    cons = {(labels["link"], labels["consumer"]): v
            for labels, v in by["fdtpu_link_consumed"]}
    assert cons[("s_v", "verify")] == 48 and cons[("v_k", "sink")] == 48


@slow
@slo
def test_metric_tile_healthz_and_summary(verify_pipeline):
    runner = verify_pipeline
    port = runner.metrics("metric")["port"]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
        assert r.status == 200
        health = json.loads(r.read())
    assert health["ok"] and health["slo_breached"] == []
    assert set(health["tiles"]) == set(runner.plan["tiles"])
    assert all(t["healthy"] for t in health["tiles"].values())
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/summary.json", timeout=10) as r:
        doc = json.loads(r.read())
    assert doc["topology"] == runner.plan["topology"]
    assert doc["tiles"]["verify"]["state"] == "run"
    assert doc["links"]["s_v"]["consumers"]["verify"]["consumed"] == 48
    assert doc["slo"]["verify-latency"]["breached"] is False
    # the SLO engine is live (evals advancing) and the objective holds
    assert runner.metrics("metric")["slo_evals"] > 0
    assert runner.metrics("metric")["slo_breach"] == 0


def test_seed_from_snapshots_the_live_view():
    """Restart resurrect: seed_from must read ONE coherent copy of the
    shm block, not field-by-field loads of the live view — the dead
    tile's final flush writes count LAST, so a count belonging to newer
    buckets double-adds samples for the rest of the restarted tile's
    life. The lint torn-read rule pins the discipline; this pins the
    behavior."""
    import numpy as np
    h = HistAccum()
    for ns in [5, 50, 500]:
        h.add(ns)
    view = np.zeros(HIST_U64, np.uint64)
    h.flush_into(view)

    class TornView:
        """Simulates the racing writer: the first element access flips
        the block to the NEXT flush's contents mid-read."""
        def __init__(self, now, later):
            self._now, self._later, self._reads = now, later, 0
        def __getitem__(self, idx):
            self._reads += 1
            src = self._now if self._reads == 1 else self._later
            return src[idx]
        def __array__(self, dtype=None, copy=None):
            # np.array(view, copy=True) — the u64_snapshot path —
            # lands entirely on the pre-race contents
            return np.array(self._now, dtype=dtype)

    later = view.copy()
    later[0] += 100                       # racing flush bumps count
    h2 = HistAccum()
    h2.seed_from(TornView(view, later))
    assert h2.count == 3                  # coherent: pre-race block
    assert h2.sum_ns == 555
    assert sum(h2.buckets) == h2.count    # count never exceeds buckets

    # the ownership analyzer keeps the fixed module fixed
    from firedancer_tpu.lint.ownership import lint_ownership_source
    import firedancer_tpu.disco.metrics as m
    with open(m.__file__) as f:
        src = f.read()
    assert lint_ownership_source(src, "disco/metrics.py") == []
