"""Metrics subsystem tests: named-slot ABI, wait/work histograms,
prometheus endpoint (ref: src/disco/metrics/fd_metrics.h:6-40,
fd_prometheus.c, fd_metric_tile.c; histograms src/util/hist/fd_histf.h).
"""
import os
import time
import urllib.request

import pytest

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.disco.metrics import (
    HIST_U64, NBUCKETS, HistAccum, bucket_of, quantile_ns, read_hists,
)
from firedancer_tpu.disco.monitor import attach, snapshot

# the histogram/quantile unit tests below run in tier-1; only the
# live-topology pipeline tests are slow-marked (the fixture spawns
# processes)
slow = pytest.mark.slow


def test_bucket_of_log2():
    assert bucket_of(0) == 0
    assert bucket_of(1) == 0
    assert bucket_of(2) == 1
    assert bucket_of(3) == 1
    assert bucket_of(1024) == 10
    assert bucket_of(1 << 60) == NBUCKETS - 1


def test_quantile_upper_bound():
    h = HistAccum()
    for ns in [10, 10, 10, 10_000]:
        h.add(ns)
    d = {"count": h.count, "sum_ns": h.sum_ns, "buckets": h.buckets}
    assert quantile_ns(d, 0.5) == 16          # 2^(3+1): bucket of 10
    assert quantile_ns(d, 0.99) == 16384      # 2^(13+1): bucket of 10_000
    assert quantile_ns({"count": 0, "sum_ns": 0,
                        "buckets": [0] * NBUCKETS}, 0.5) == 0


def test_quantile_edges_empty_and_q0_q1():
    """Histogram edge pins: an EMPTY histogram is 0 at every q; q=0.0
    is the minimum sample's bucket bound — NOT bucket 0's bound when
    bucket 0 is empty — and q=1.0 is the maximum sample's bound."""
    empty = {"count": 0, "sum_ns": 0, "buckets": [0] * NBUCKETS}
    assert quantile_ns(empty, 0.0) == 0
    assert quantile_ns(empty, 1.0) == 0
    h = HistAccum()
    for ns in [10, 10_000]:
        h.add(ns)
    d = {"count": h.count, "sum_ns": h.sum_ns, "buckets": h.buckets}
    assert quantile_ns(d, 0.0) == 16          # min sample's bucket (10)
    assert quantile_ns(d, 1.0) == 16384       # max sample's bucket (10k)
    # a single sample far from bucket 0: q=0 must still find it
    h1 = HistAccum()
    h1.add(1 << 20)
    d1 = {"count": h1.count, "sum_ns": h1.sum_ns, "buckets": h1.buckets}
    assert quantile_ns(d1, 0.0) == quantile_ns(d1, 1.0) == 1 << 21


def test_flush_into_is_idempotent():
    """flush_into overwrites (cumulative counts, single writer): a
    second flush with no new samples must add NOTHING — a += bug here
    would double every counter each housekeeping pass."""
    import numpy as np
    h = HistAccum()
    for ns in [5, 50, 500]:
        h.add(ns)
    view = np.zeros(HIST_U64, np.uint64)
    h.flush_into(view)
    first = view.copy()
    h.flush_into(view)                    # no adds in between
    assert (view == first).all()
    assert int(view[0]) == 3 and int(view[1]) == 555
    assert int(view[2:].sum()) == 3
    h.add(7)                              # and a real add still lands
    h.flush_into(view)
    assert int(view[0]) == 4 and int(view[2:].sum()) == 4


@pytest.fixture(scope="module")
def pipeline():
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"tm{os.getpid()}", wksp_size=1 << 23)
        .link("s_k", depth=64, mtu=1280)
        .tile("synth", "synth", outs=["s_k"], count=32, unique=8, seed=9)
        .tile("sink", "sink", ins=["s_k"])
        .tile("metric", "metric", port=0)
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=120)
        runner.wait_idle("sink", "rx", 32, timeout_s=120)
        yield runner
    finally:
        runner.halt()
        runner.close()


@slow
def test_plan_carries_slot_names(pipeline):
    tiles = pipeline.plan["tiles"]
    assert tiles["synth"]["metrics_names"] == ["tx", "backpressure"]
    assert tiles["sink"]["metrics_names"] == ["rx", "bytes", "overruns"]
    # readers resolve by plan names — values land under the right keys
    assert pipeline.metrics("synth")["tx"] == 32
    assert pipeline.metrics("sink")["rx"] == 32


@slow
def test_histograms_populate(pipeline):
    # one housekeeping flush after the traffic
    deadline = time.time() + 30
    while time.time() < deadline:
        h = read_hists(pipeline.wksp, pipeline.plan, "sink")
        if h and h["work"]["count"] > 0 and h["wait"]["count"] > 0:
            break
        time.sleep(0.05)
    assert h["work"]["count"] > 0, "sink did work but no work samples"
    assert h["wait"]["count"] > 0, "sink idled but no wait samples"
    assert h["work"]["sum_ns"] > 0
    assert sum(h["work"]["buckets"]) == h["work"]["count"]
    # monitor surfaces latency quantiles
    plan, wksp = attach(pipeline.plan["topology"])
    try:
        snap = snapshot(plan, wksp)
        lat = snap["sink"]["latency"]
        assert lat["work"]["count"] > 0
        assert lat["work"]["p99_us"] >= lat["work"]["p50_us"] > 0
    finally:
        wksp.close()


@slow
def test_prometheus_endpoint(pipeline):
    port = pipeline.metrics("metric")["port"]
    assert port > 0
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        assert r.status == 200
        body = r.read().decode()
    assert '# TYPE fdtpu_tile_metric counter' in body
    assert 'tile="sink"' in body and 'name="rx"} 32' in body
    # histogram exposition: cumulative buckets, monotone, +Inf == count
    lines = [ln for ln in body.splitlines()
             if ln.startswith('fdtpu_poll_work_seconds_bucket{'
                              'topology') and 'tile="sink"' in ln]
    assert lines, body[:500]
    cum = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert cum == sorted(cum)
    count_ln = [ln for ln in body.splitlines()
                if ln.startswith("fdtpu_poll_work_seconds_count")
                and 'tile="sink"' in ln]
    assert int(count_ln[0].rsplit(" ", 1)[1]) == cum[-1]
    # scrape counter advanced
    deadline = time.time() + 15
    while time.time() < deadline:
        if pipeline.metrics("metric")["scrapes"] >= 1:
            break
        time.sleep(0.05)
    assert pipeline.metrics("metric")["scrapes"] >= 1
