"""Test harness config: force an 8-device virtual CPU mesh.

Real TPU hardware in CI has a single chip; multi-chip sharding paths are
validated on a virtual 8-device CPU platform, mirroring how the reference
tests tiles without a cluster (reference: doc/testing.md, fd_tile_unit_test).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
