"""Test harness config: force CPU with an 8-device virtual mesh.

Real TPU hardware in CI is a single chip reached through a slow exclusive
tunnel (the "axon" PJRT plugin, registered by sitecustomize with
JAX_PLATFORMS=axon); unit tests must not touch it. Multi-chip sharding
paths are validated on a virtual 8-device CPU platform, mirroring how the
reference tests tiles without a cluster (reference: doc/testing.md,
fd_tile_unit_test).

NOTE: sitecustomize imports jax at interpreter startup, so mutating
os.environ["JAX_PLATFORMS"] here is too late — jax.config already latched
"axon,cpu". Use jax.config.update. XLA_FLAGS is still read at (lazy) CPU
backend creation, so setting it here works.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests spawn
# when the axon tunnel is wedged, its sitecustomize register() can
# block EVERY spawned interpreter (PERF.md r4 outage notes); an empty
# pool-IP list skips registration entirely — tests never want the
# device, so this is always safe here and keeps the suite runnable
# during tunnel-down windows
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

# persistent compile cache: the big verify kernels take minutes to compile
# on CPU; cache hits bring suite re-runs down to seconds
_CACHE = os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
