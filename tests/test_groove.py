"""groove cold store: size-class reuse, persistence scan, torn-write
recovery (ref: src/groove/fd_groove.h:1-13)."""
import os
import struct

import pytest

from firedancer_tpu.groove import GrooveError, GrooveStore
from firedancer_tpu.groove.groove import _HDR_SZ, _class_for


def K(n):
    return bytes([n]) * 32


def test_put_get_delete_roundtrip(tmp_path):
    g = GrooveStore(str(tmp_path))
    g.put(K(1), b"hello cold world")
    g.put(K(2), b"x" * 5000)
    assert bytes(g.get(K(1))) == b"hello cold world"
    assert bytes(g.get(K(2))) == b"x" * 5000
    assert g.get(K(9)) is None
    assert g.delete(K(1)) and not g.delete(K(1))
    assert g.get(K(1)) is None
    assert len(g) == 1
    g.close()


def test_size_classes_and_reuse(tmp_path):
    g = GrooveStore(str(tmp_path))
    assert _class_for(1) == 7
    assert (1 << _class_for(5000)) >= _HDR_SZ + 5000 + 4
    with pytest.raises(GrooveError):
        _class_for(1 << 25)
    g.put(K(1), b"a" * 100)
    g.delete(K(1))
    g.put(K(2), b"b" * 100)          # same class: slot reused
    assert g.stats["reused"] == 1
    assert bytes(g.get(K(2))) == b"b" * 100
    g.close()


def test_overwrite_keeps_latest(tmp_path):
    g = GrooveStore(str(tmp_path))
    g.put(K(1), b"v1")
    g.put(K(1), b"v2-longer-payload")
    assert bytes(g.get(K(1))) == b"v2-longer-payload"
    assert len(g) == 1
    g.close()


def test_reopen_scan_recovers_everything(tmp_path):
    g = GrooveStore(str(tmp_path))
    blobs = {K(i): os.urandom(50 * i + 10) for i in range(1, 20)}
    for k, v in blobs.items():
        g.put(k, v)
    g.delete(K(3))
    g.put(K(1), b"overwritten")      # old copy tombstoned
    g.flush()
    g.close()

    g2 = GrooveStore(str(tmp_path))
    assert len(g2) == 18
    assert g2.get(K(3)) is None
    assert bytes(g2.get(K(1))) == b"overwritten"
    for k, v in blobs.items():
        if k in (K(1), K(3)):
            continue
        assert bytes(g2.get(k)) == v
    # freed slots survive reopen and get reused
    before = g2.stats["reused"]
    g2.put(K(99), b"c" * 40)
    assert g2.stats["reused"] == before + 1
    g2.close()


def test_torn_write_reclaimed_on_scan(tmp_path):
    g = GrooveStore(str(tmp_path))
    g.put(K(1), b"good record")
    g.put(K(2), b"will be torn")
    vid, off = g.meta[K(2)]
    # corrupt the payload without fixing the crc (simulated torn write)
    mm = g.vols[vid].mm
    mm[off + _HDR_SZ] ^= 0xFF
    g.flush()
    g.close()

    g2 = GrooveStore(str(tmp_path))
    assert bytes(g2.get(K(1))) == b"good record"
    assert g2.get(K(2)) is None          # failed crc -> not resurrected
    assert g2.stats["torn_reclaimed"] == 1
    g2.close()


def test_many_volumes(tmp_path):
    """Objects larger than one volume's remaining space spill into a
    new volume."""
    g = GrooveStore(str(tmp_path))
    big = os.urandom(1 << 22)            # 4 MiB per object
    for i in range(20):                  # ~80 MiB total -> 2 volumes
        g.put(K(i + 1), big[i:] + bytes(i))
    assert len(g.vols) >= 2
    for i in range(20):
        assert bytes(g.get(K(i + 1))) == big[i:] + bytes(i)
    g.close()


def test_corrupt_dlen_reclaimed_not_crash(tmp_path):
    """A corrupt length field must reclaim the slot on scan, never
    abort open() (r4 review)."""
    g = GrooveStore(str(tmp_path))
    g.put(K(1), b"keep me")
    g.put(K(2), b"corrupt my header")
    vid, off = g.meta[K(2)]
    struct.pack_into("<I", g.vols[vid].mm, off + 40, 0x7FFFFFFF)
    g.flush()
    g.close()
    g2 = GrooveStore(str(tmp_path))
    assert bytes(g2.get(K(1))) == b"keep me"
    assert g2.get(K(2)) is None
    assert g2.stats["torn_reclaimed"] == 1
    g2.close()


def test_crash_window_duplicate_reconciled(tmp_path):
    """Simulated crash inside put()'s overwrite window (new copy
    written, old not yet tombstoned): recovery keeps the higher-lsn
    copy and KILLS the loser so delete cannot be resurrected (r4
    review)."""
    g = GrooveStore(str(tmp_path))
    g.put(K(1), b"v1-old")
    old_loc = g.meta[K(1)]
    g.put(K(1), b"v2-new")
    # resurrect the old record as LIVE = the crash-window state
    vid, off = old_loc
    g.vols[vid].mm[off + 4] = 1          # ST_LIVE
    g.flush()
    g.close()

    g2 = GrooveStore(str(tmp_path))
    assert g2.stats["dup_reconciled"] == 1
    assert bytes(g2.get(K(1))) == b"v2-new"      # higher lsn won
    g2.delete(K(1))
    g2.flush()
    g2.close()
    g3 = GrooveStore(str(tmp_path))
    assert g3.get(K(1)) is None          # nothing resurrected
    g3.close()
