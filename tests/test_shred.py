"""Shred format, merkle commitment, and shredder tests.

Strategy mirrors the reference's (SURVEY §4): closed-form count
functions cross-checked against a brute-force sizing model, wire
round-trips, proof verification for every produced shred, and
RS-recovery of erased data shreds from the produced parity."""
import hashlib

import numpy as np
import pytest

from firedancer_tpu.shred import format as fmt
from firedancer_tpu.shred.merkle import (MerkleTree20, bmtree_depth,
                                         shred_merkle_leaf, verify_proof)
from firedancer_tpu.shred.shredder import (Shredder, count_data_shreds,
                                           count_fec_sets,
                                           count_parity_shreds,
                                           DATA_TO_PARITY)
from firedancer_tpu.utils import ed25519_ref, gf256

SEED = b"\x07" * 32


def _signer():
    calls = []

    def sign(root: bytes) -> bytes:
        calls.append(root)
        return ed25519_ref.sign(SEED, root)
    return sign, calls


# -- sizing policy -----------------------------------------------------------

def test_count_functions_normal_regime():
    # exact multiple of 31840 -> sz/31840 sets of exactly 32+32
    for k in (1, 2, 5):
        sz = 31840 * k
        assert count_fec_sets(sz, chained=False) == k
        assert count_data_shreds(sz, chained=False) == 32 * k
        assert count_parity_shreds(sz, chained=False) == 32 * k


@pytest.mark.parametrize("chained,resigned", [(False, False), (True, False),
                                              (True, True)])
def test_count_matches_brute_force(chained, resigned):
    # brute-force the spec formula: payload = 1115 - 20*ceil(log2(n))
    # - 32*chained - 64*resigned with n = d + p(d), picking the largest
    # consistent payload (fd_shredder.h:100-137)
    def brute(rem):
        best = None
        for d in range(1, 68):
            p = DATA_TO_PARITY[d] if d < len(DATA_TO_PARITY) else d
            depth = bmtree_depth(d + p) - 1
            payload = 1115 - 20 * depth - 32 * chained - 64 * resigned
            if (d - 1) * payload < rem <= d * payload:
                if best is None or payload > best[2]:
                    best = (d, p, payload)
        assert best, rem
        return best

    rng = np.random.default_rng(3)
    fec_pl = {(False, False): 31840, (True, False): 30816,
              (True, True): 28768}[(chained, resigned)]
    for rem in [1, 17, 954, 955, 1015, 1016, 9135, 9136, 20000,
                fec_pl, fec_pl + 1, 2 * fec_pl - 1,
                *rng.integers(1, 2 * fec_pl, 40).tolist()]:
        d, p, _ = brute(rem)
        assert count_data_shreds(rem, chained, resigned) == d, rem
        assert count_parity_shreds(rem, chained, resigned) == p, rem


# -- merkle tree -------------------------------------------------------------

def test_merkle_proofs_all_leaves():
    rng = np.random.default_rng(5)
    for n in (1, 2, 3, 5, 9, 32, 64, 67):
        leaves = [rng.integers(0, 256, 40, np.uint8).tobytes()
                  for _ in range(n)]
        tree = MerkleTree20.from_leaves(leaves)
        assert tree.proof_len == bmtree_depth(n) - 1
        for i in range(n):
            pf = tree.proof(i)
            assert verify_proof(shred_merkle_leaf(leaves[i]), i, pf,
                                tree.root)
            # a corrupted leaf must fail
            assert not verify_proof(
                shred_merkle_leaf(leaves[i] + b"x"), i, pf, tree.root)


def test_merkle_truncation_semantics():
    # children truncated to 20B at concat time; root is full sha256
    a = hashlib.sha256(b"\x00SOLANA_MERKLE_SHREDS_LEAF" + b"a").digest()
    b = hashlib.sha256(b"\x00SOLANA_MERKLE_SHREDS_LEAF" + b"b").digest()
    tree = MerkleTree20([a, b])
    expect = hashlib.sha256(
        b"\x01SOLANA_MERKLE_SHREDS_NODE" + a[:20] + b[:20]).digest()
    assert tree.root == expect and len(tree.root) == 32


# -- wire format -------------------------------------------------------------

def test_shred_wire_sizes():
    sign, _ = _signer()
    sets = Shredder(sign).shred_batch(b"z" * 5000, slot=7, parent_off=1,
                                      ref_tick=3, block_complete=False)
    assert len(sets) == 1
    fs = sets[0]
    assert all(len(w) == fmt.SHRED_MIN_SZ for w in fs.data_shreds)
    assert all(len(w) == fmt.SHRED_MAX_SZ for w in fs.parity_shreds)


def test_pack_parse_roundtrip():
    sign, _ = _signer()
    sets = Shredder(sign, shred_version=42).shred_batch(
        b"\xab" * 4000, slot=9, parent_off=2, ref_tick=11,
        block_complete=True)
    for fs in sets:
        total = b"".join(
            fmt.parse_shred(w).payload for w in fs.data_shreds)
        assert total == b"\xab" * 4000
        d0 = fmt.parse_shred(fs.data_shreds[0])
        assert (d0.slot, d0.version, d0.parent_off) == (9, 42, 2)
        assert d0.ref_tick == 11
        last = fmt.parse_shred(fs.data_shreds[-1])
        assert last.slot_complete and last.data_complete
        c0 = fmt.parse_shred(fs.parity_shreds[0])
        assert c0.data_cnt == len(fs.data_shreds)
        assert c0.code_cnt == len(fs.parity_shreds)


def test_parse_rejects_malformed():
    sign, _ = _signer()
    w = Shredder(sign).shred_batch(b"q" * 100, 1, 1, 0,
                                   False)[0].data_shreds[0]
    with pytest.raises(fmt.ShredParseError):
        fmt.parse_shred(w[:-1])               # truncated
    bad = bytearray(w)
    bad[fmt.VARIANT_OFF] = 0xA5               # legacy
    with pytest.raises(fmt.ShredParseError):
        fmt.parse_shred(bytes(bad))
    bad = bytearray(w)
    bad[0x56:0x58] = (60000).to_bytes(2, "little")  # size field overrun
    with pytest.raises(fmt.ShredParseError):
        fmt.parse_shred(bytes(bad))


# -- shredder end-to-end -----------------------------------------------------

def test_every_shred_proof_verifies_and_sig_covers_root():
    sign, roots = _signer()
    pub = ed25519_ref.keypair(SEED)[-1]
    sets = Shredder(sign).shred_batch(b"\x5c" * 9000, slot=3,
                                      parent_off=1, ref_tick=0,
                                      block_complete=False)
    fs = sets[0]
    d_var = fmt.parse_shred(fs.data_shreds[0]).variant
    c_var = fmt.parse_shred(fs.parity_shreds[0]).variant
    d_cnt = len(fs.data_shreds)
    for i, w in enumerate(fs.data_shreds + fs.parity_shreds):
        var = d_var if i < d_cnt else c_var
        region = (fmt.data_merkle_region_sz(var) if i < d_cnt
                  else fmt.code_merkle_region_sz(var))
        leaf = shred_merkle_leaf(w[64:64 + region])
        s = fmt.parse_shred(w)
        assert verify_proof(leaf, i, list(s.proof), fs.merkle_root), i
        assert ed25519_ref.verify(s.signature, pub, fs.merkle_root)
    assert roots == [fs.merkle_root]


def test_rs_recovery_from_parity():
    sign, _ = _signer()
    fs = Shredder(sign).shred_batch(b"\x11\x22\x33" * 2000, 5, 1, 2,
                                    False)[0]
    d = len(fs.data_shreds)
    p = len(fs.parity_shreds)
    var = fmt.parse_shred(fs.data_shreds[0]).variant
    region = fmt.payload_capacity(var) + fmt.DATA_HEADER_SZ - 64
    codeword = {}
    for i, w in enumerate(fs.data_shreds):
        codeword[i] = np.frombuffer(w[64:64 + region], np.uint8)
    for j, w in enumerate(fs.parity_shreds):
        codeword[d + j] = np.frombuffer(w[0x59:0x59 + region], np.uint8)
    # erase as many data shreds as there is parity, recover, compare
    rng = np.random.default_rng(7)
    erased = set(rng.choice(d, size=min(p, d), replace=False).tolist())
    surviving = {k: v for k, v in codeword.items() if k not in erased}
    rec = gf256.recover(surviving, d, p)
    for i in range(d):
        assert np.array_equal(rec[i], codeword[i]), i


def test_chained_roots_thread_across_sets():
    sign, _ = _signer()
    prev_root = b"\x99" * 32
    # two FEC sets (exact multiple of the chained payload)
    sets = Shredder(sign).shred_batch(b"r" * (30816 * 2), slot=2,
                                      parent_off=1, ref_tick=0,
                                      block_complete=False,
                                      chained_root=prev_root)
    assert len(sets) == 2
    s0 = fmt.parse_shred(sets[0].data_shreds[0])
    assert fmt.is_chained(s0.variant) and not fmt.is_resigned(s0.variant)
    assert s0.chained_root == prev_root
    s1 = fmt.parse_shred(sets[1].data_shreds[0])
    assert s1.chained_root == sets[0].merkle_root
    # chained+block_complete -> resigned variants with sig slot zeroed
    sets = Shredder(sign).shred_batch(b"r" * 100, slot=3, parent_off=1,
                                      ref_tick=0, block_complete=True,
                                      chained_root=prev_root)
    s = fmt.parse_shred(sets[0].data_shreds[0])
    assert fmt.is_resigned(s.variant)
    assert s.retransmit_sig == bytes(64)


def test_idx_bookkeeping_across_batches():
    sign, _ = _signer()
    sh = Shredder(sign)
    a = sh.shred_batch(b"a" * 2000, 7, 1, 0, False)[0]
    b = sh.shred_batch(b"b" * 2000, 7, 1, 0, False)[0]
    a_d = [fmt.parse_shred(w).idx for w in a.data_shreds]
    b_d = [fmt.parse_shred(w).idx for w in b.data_shreds]
    assert b_d[0] == a_d[-1] + 1                 # contiguous in slot
    assert b.fec_set_idx == b_d[0]
    c = sh.shred_batch(b"c" * 2000, 8, 1, 0, False)[0]  # new slot resets
    assert fmt.parse_shred(c.data_shreds[0]).idx == 0


def test_payload_sz_formula_pinned():
    # depth-6 tree (32+32 shreds): payload 995 unchained / 963 chained
    assert fmt.payload_capacity(fmt.TYPE_MERKLE_DATA | 6) == 995
    assert fmt.payload_capacity(fmt.TYPE_MERKLE_DATA_CHAINED | 6) == 963
    assert fmt.payload_capacity(
        fmt.TYPE_MERKLE_DATA_CHAINED_RESIGNED | 6) == 899
    # header+payload+proof must tile the wire exactly
    assert 88 + 995 + 20 * 6 == fmt.SHRED_MIN_SZ
    assert 89 + (995 + 24) + 20 * 6 == fmt.SHRED_MAX_SZ
