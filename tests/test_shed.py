"""Front-door policing engine (disco/shed.py): schema triple gate,
the lint/registry key mirror, and the PeerGate policy — token buckets,
bounded peer table with stake-aware eviction, stake-weighted overload
shedding with clock-expiry recovery. All host-side, no jax.
"""
import pytest

from firedancer_tpu.disco.shed import (PeerGate, SHED_DEFAULTS,
                                       TILE_SHED_KEYS, effective_shed,
                                       normalize_shed)

pytestmark = pytest.mark.flood

S = 1_000_000_000              # 1 s in ns (explicit now= clocks)


# -- schema -----------------------------------------------------------------

def test_normalize_defaults_and_typo_did_you_mean():
    out = normalize_shed({})
    assert out == SHED_DEFAULTS
    with pytest.raises(ValueError, match="did you mean 'rate_pps'"):
        normalize_shed({"rate_ppz": 1.0})
    with pytest.raises(ValueError, match="rate_pps must be > 0"):
        normalize_shed({"rate_pps": 0})
    with pytest.raises(ValueError, match="burst must be >= 1"):
        normalize_shed({"burst": 0.5})
    with pytest.raises(ValueError, match="max_peers must be >= 2"):
        normalize_shed({"max_peers": 1})
    with pytest.raises(ValueError, match="min_stake must be >= 0"):
        normalize_shed({"min_stake": -1})
    with pytest.raises(ValueError, match="overload_hold_s must be > 0"):
        normalize_shed({"overload_hold_s": 0})
    with pytest.raises(ValueError, match="stakes"):
        normalize_shed({"stakes": [1, 2]})
    with pytest.raises(ValueError, match="non-empty string"):
        normalize_shed({"stakes": {"": 5}})
    with pytest.raises(ValueError, match="must be >= 0"):
        normalize_shed({"stakes": {"1.2.3.4:5": -3}})
    with pytest.raises(ValueError, match="table"):
        normalize_shed("nope")


def test_per_tile_is_partial_and_registry_mirror_holds():
    # per-tile tables stay partial (the topology section fills the
    # rest at effective_shed time)
    assert normalize_shed({"rate_pps": 9.0}, per_tile=True) == \
        {"rate_pps": 9.0}
    assert normalize_shed(None, per_tile=True) == {}
    # fdlint's registry mirrors the one validator's key set — a key
    # added to SHED_DEFAULTS without the registry (or vice versa)
    # fails here, keeping did-you-mean suggestions honest
    from firedancer_tpu.lint import registry
    assert set(registry.SHED_SECTION_KEYS) == set(SHED_DEFAULTS)
    assert set(registry.TILE_SHED_KEYS) == set(TILE_SHED_KEYS)
    assert "shed" in registry.COMMON_KEYS


def test_effective_shed_merge_precedence():
    assert effective_shed(None, None) is None
    topo = {"rate_pps": 100.0, "stakes": {"a:1": 5}}
    assert effective_shed(topo, None)["rate_pps"] == 100.0
    eff = effective_shed(topo, {"rate_pps": 7.0, "stakes": {"b:2": 9}})
    assert eff["rate_pps"] == 7.0            # tile override wins
    assert eff["stakes"] == {"a:1": 5, "b:2": 9}   # stakes union
    assert eff["max_peers"] == SHED_DEFAULTS["max_peers"]
    # disable at either level -> no gate at all
    assert effective_shed({"enable": False}, None) is None
    assert effective_shed(topo, {"enable": False}) is None
    # a tile-only override polices even without a topology section
    assert effective_shed(None, {"rate_pps": 3.0})["rate_pps"] == 3.0


# -- triple gate ------------------------------------------------------------

def test_bad_shed_rejected_at_config_load_and_topo_build():
    from firedancer_tpu.app.config import build_topology
    cfg = {"topology": {"name": "t"},
           "link": [{"name": "a_b", "depth": 32}],
           "tile": [{"name": "s", "kind": "sock", "outs": ["a_b"]},
                    {"name": "d", "kind": "sink", "ins": ["a_b"]}],
           "shed": {"rate_ppz": 1.0}}
    with pytest.raises(ValueError, match="did you mean 'rate_pps'"):
        build_topology(cfg)
    # programmatic Topology skips config load: topo.build is the gate
    from firedancer_tpu.disco import Topology
    topo = (Topology("bad_shed", shed={"max_peers": 1})
            .link("a_b", depth=32)
            .tile("s", "sock", outs=["a_b"])
            .tile("d", "sink", ins=["a_b"]))
    with pytest.raises(ValueError, match="max_peers"):
        topo.build()
    # per-tile override validates too
    topo2 = (Topology("bad_shed2")
             .link("a_b", depth=32)
             .tile("s", "sock", outs=["a_b"],
                   shed={"overload_hold_s": -1})
             .tile("d", "sink", ins=["a_b"]))
    with pytest.raises(ValueError, match="overload_hold_s"):
        topo2.build()


def test_plan_carries_shed_and_breach_reader_is_zero_safe():
    from firedancer_tpu.disco import Topology
    from firedancer_tpu.disco.shed import slo_breach_count
    topo = (Topology("shedplan", shed={"rate_pps": 11.0})
            .link("a_b", depth=32)
            .tile("s", "sock", outs=["a_b"])
            .tile("d", "sink", ins=["a_b"]))
    plan = topo.build()
    assert plan["shed"]["rate_pps"] == 11.0
    assert plan["shed"]["burst"] == SHED_DEFAULTS["burst"]
    # no metric tile in the plan: the overload coupling reads 0, never
    # raises (ingest tiles poll this at housekeeping cadence)
    assert slo_breach_count(plan, None) == 0


# -- PeerGate: token buckets ------------------------------------------------

def test_token_bucket_rate_limits_per_peer():
    g = PeerGate({"rate_pps": 2.0, "burst": 2, "max_peers": 16})
    a, b = ("10.0.0.1", 5), ("10.0.0.2", 5)
    now = 0
    assert g.admit(a, now) and g.admit(a, now)
    assert not g.admit(a, now)           # burst exhausted
    assert g.admit(b, now)               # another peer: own bucket
    assert g.shed_total == 1 and g.shed_rate == 1
    # 1 s later the bucket earned rate_pps tokens back
    now += S
    assert g.admit(a, now) and g.admit(a, now)
    assert not g.admit(a, now)
    # ...and never more than burst accumulates
    now += 100 * S
    assert g.admit(a, now) and g.admit(a, now)
    assert not g.admit(a, now)


def test_key_namespaces_sockets_and_origins():
    assert PeerGate.key_of(("1.2.3.4", 80)) == "1.2.3.4:80"
    assert PeerGate.key_of(b"\xaa\xbb") == "aabb"   # gossip origins


# -- PeerGate: bounded table + eviction -------------------------------------

def test_sybil_flood_churns_unstaked_slots_never_staked():
    g = PeerGate({"rate_pps": 100.0, "burst": 4, "max_peers": 4,
                  "min_stake": 1,
                  "stakes": {"10.0.0.1:1": 100, "10.0.0.2:1": 100}})
    now = 0
    assert g.admit(("10.0.0.1", 1), now)
    assert g.admit(("10.0.0.2", 1), now)
    # a flood of fresh unstaked identities: table NEVER exceeds
    # max_peers, and the staked entries are never evicted
    for i in range(1000):
        g.admit((f"172.16.{i % 250}.{i // 250}", 9), now)
    assert len(g.peers) <= 4
    assert "10.0.0.1:1" in g.peers and "10.0.0.2:1" in g.peers
    assert g.evicted > 0


def test_all_staked_table_sheds_unstaked_newcomer():
    g = PeerGate({"rate_pps": 100.0, "burst": 4, "max_peers": 2,
                  "min_stake": 1,
                  "stakes": {"10.0.0.1:1": 50, "10.0.0.2:1": 50,
                             "10.0.0.3:1": 50}})
    now = 0
    assert g.admit(("10.0.0.1", 1), now)
    assert g.admit(("10.0.0.2", 1), now)
    # unstaked newcomer: shed at the door, no staked entry evicted
    assert not g.admit(("99.9.9.9", 1), now)
    assert g.shed_unstaked == 1
    assert set(g.peers) == {"10.0.0.1:1", "10.0.0.2:1"}
    # a STAKED newcomer may evict the oldest entry instead
    assert g.admit(("10.0.0.3", 1), now)
    assert "10.0.0.3:1" in g.peers and len(g.peers) == 2


# -- PeerGate: overload mode ------------------------------------------------

def test_overload_sheds_unstaked_first_and_recovers_on_expiry():
    g = PeerGate({"rate_pps": 100.0, "burst": 8, "max_peers": 16,
                  "min_stake": 10, "overload_hold_s": 1.0,
                  "stakes": {"10.0.0.1:1": 50, "10.0.0.9:1": 3}})
    now = 0
    staked, low, unstaked = ("10.0.0.1", 1), ("10.0.0.9", 1), ("6.6.6.6", 1)
    assert g.admit(unstaked, now)        # peacetime: everyone admitted
    g.trip_overload(now)
    assert g.overloaded(now)
    # overload: below-min_stake sheds at the door (no table growth),
    # staked keeps its token budget
    assert not g.admit(unstaked, now)
    assert not g.admit(low, now)         # stake 3 < min_stake 10
    assert g.admit(staked, now)
    peers_during = len(g.peers)
    for i in range(100):
        assert not g.admit((f"7.7.{i}.1", 1), now)
    assert len(g.peers) == peers_during  # overload cannot grow the table
    assert g.shed_unstaked >= 102
    # refresh keeps it latched; expiry IS the recovery
    g.trip_overload(now + S // 2)
    assert g.overloaded(now + S)
    assert not g.overloaded(now + S // 2 + S)
    assert g.admit(unstaked, now + S // 2 + S)


def test_count_drop_attributes_drop_newest():
    g = PeerGate({"stakes": {"10.0.0.1:1": 5}})
    g.count_drop(("10.0.0.1", 1))
    g.count_drop(("8.8.8.8", 1))
    assert g.shed_total == 2 and g.shed_drop == 2
    assert g.shed_unstaked == 1          # only the unstaked peer
    c = g.counters()
    assert c["shed"] == 2 and c["overload"] == 0 and c["peers"] == 0
