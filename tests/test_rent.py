"""Rent-state discipline (r5): modern consensus collects no rent, but
every account a transaction touches must LEAVE the transaction
rent-exempt — new accounts below the minimum are refused, pre-existing
rent-paying accounts may only be topped up, draining to exactly zero
closes an account (ref: src/flamenco/runtime/sysvar/fd_sysvar_rent.c
minimum-balance discipline; Agave check_rent_state transitions)."""
import struct

import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.programs import (
    ERR_RENT, OK, SYS_CREATE_ACCOUNT, SYS_TRANSFER, SYSTEM_PROGRAM_ID,
)
from firedancer_tpu.svm.sysvars import rent_exempt_minimum


def k(i):
    return bytes([i]) * 32


def _txn(signers, extra, instrs, **kw):
    msg = build_message(signers, extra, b"\x22" * 32, instrs, **kw)
    return build_txn([bytes(64)] * len(signers), msg)


@pytest.fixture
def env():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, k(1), Account(lamports=1 << 40))
    funk.txn_prepare(None, "blk")
    return funk, db, TxnExecutor(db)        # enforce_rent defaults ON


def test_create_below_minimum_refused(env):
    funk, db, ex = env
    need = rent_exempt_minimum(64)
    ix = struct.pack("<IQQ", SYS_CREATE_ACCOUNT, need - 1, 64) + k(9)
    r = ex.execute("blk", _txn([k(1), k(5)], [SYSTEM_PROGRAM_ID],
                               [(2, bytes([0, 1]), ix)]))
    assert r.status == ERR_RENT
    assert db.peek("blk", k(5)) is None     # rolled back
    ix = struct.pack("<IQQ", SYS_CREATE_ACCOUNT, need, 64) + k(9)
    r = ex.execute("blk", _txn([k(1), k(5)], [SYSTEM_PROGRAM_ID],
                               [(2, bytes([0, 1]), ix)]))
    assert r.status == OK


def test_transfer_creating_rent_paying_account_refused(env):
    funk, db, ex = env
    ix = struct.pack("<IQ", SYS_TRANSFER, 1000)
    r = ex.execute("blk", _txn([k(1)], [k(6), SYSTEM_PROGRAM_ID],
                               [(2, bytes([0, 1]), ix)]))
    assert r.status == ERR_RENT
    # funding to exactly the minimum is fine
    ix = struct.pack("<IQ", SYS_TRANSFER, rent_exempt_minimum(0))
    r = ex.execute("blk", _txn([k(1)], [k(6), SYSTEM_PROGRAM_ID],
                               [(2, bytes([0, 1]), ix)]))
    assert r.status == OK


def test_rent_paying_account_may_shrink_but_not_grow(env):
    """Agave's RentPaying->RentPaying transition: same data size and
    lamports NON-INCREASING (a top-up that doesn't reach exemption is
    refused; partial drains of grandfathered accounts are legal)."""
    funk, db, ex = env
    funk.rec_write("blk", k(7), Account(lamports=500))  # grandfathered
    ix = struct.pack("<IQ", SYS_TRANSFER, 100)
    r = ex.execute("blk", _txn([k(1)], [k(7), SYSTEM_PROGRAM_ID],
                               [(2, bytes([0, 1]), ix)]))
    assert r.status == ERR_RENT            # growth w/o exemption: no
    # topping up all the way to exemption IS allowed
    ix = struct.pack("<IQ", SYS_TRANSFER, rent_exempt_minimum(0) - 500)
    r = ex.execute("blk", _txn([k(1)], [k(7), SYSTEM_PROGRAM_ID],
                               [(2, bytes([0, 1]), ix)]))
    assert r.status == OK
    # a different grandfathered account may shrink (k1 pays the fee)
    funk.rec_write("blk", k(9), Account(lamports=500))
    funk.rec_write("blk", k(8), Account(lamports=1 << 30))
    ix = struct.pack("<IQ", SYS_TRANSFER, 100)
    r = ex.execute("blk", _txn([k(1), k(9)], [k(8), SYSTEM_PROGRAM_ID],
                               [(3, bytes([1, 2]), ix)]))
    assert r.status == OK
    assert db.lamports("blk", k(9)) == 400


def test_fee_cannot_push_exempt_payer_into_rent_paying(env):
    funk, db, ex = env
    funk.rec_write("blk", k(9), Account(
        lamports=rent_exempt_minimum(0)))   # exactly exempt
    funk.rec_write("blk", k(8), Account(lamports=1 << 30))
    ix = struct.pack("<IQ", SYS_TRANSFER, 1)
    r = ex.execute("blk", _txn([k(9)], [k(8), SYSTEM_PROGRAM_ID],
                               [(2, bytes([0, 1]), ix)]))
    assert r.status == ERR_RENT            # exempt -> rent-paying


def test_draining_to_zero_closes_account(env):
    funk, db, ex = env
    funk.rec_write("blk", k(9), Account(lamports=1 << 30))
    bal = 1 << 30
    fee = 5000
    ix = struct.pack("<IQ", SYS_TRANSFER, bal - fee)
    r = ex.execute("blk", _txn([k(9)], [k(1), SYSTEM_PROGRAM_ID],
                               [(2, bytes([0, 1]), ix)]))
    assert r.status == OK                  # 0-lamport account closes
    assert db.lamports("blk", k(9)) == 0
