"""spad frame allocator + feature gates (ref: src/util/spad/fd_spad.h,
src/flamenco/features/fd_features.h)."""
import pytest

from firedancer_tpu.flamenco import features as ft
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm.accdb import AccDb
from firedancer_tpu.utils.spad import Spad, SpadError, with_frame


def test_spad_frames_and_alignment():
    sp = Spad(1024)
    sp.frame_push()
    a = sp.alloc(10)
    a[:] = b"\x11" * 10
    b = sp.alloc(5, align=64)
    b[:] = b"\x22" * 5
    # alignment honored: the allocation started on a 64-byte boundary
    assert (sp.cursor - 5) % 64 == 0
    used_inner = sp.in_use()
    sp.frame_push()
    sp.alloc(100)
    sp.frame_pop()
    assert sp.in_use() == used_inner     # bulk free at pop
    sp.frame_pop()
    assert sp.in_use() == 0
    assert sp.peak >= 100


def test_spad_exhaustion_and_errors():
    sp = Spad(64)
    with pytest.raises(SpadError):
        sp.alloc(100)
    with pytest.raises(SpadError):
        sp.alloc(8, align=3)
    with pytest.raises(SpadError):
        sp.frame_pop()


def test_spad_with_frame_pops_on_error():
    sp = Spad(256)
    with pytest.raises(RuntimeError, match="boom"):
        with with_frame(sp):
            sp.alloc(64)
            raise RuntimeError("boom")
    assert sp.in_use() == 0 and sp.frame_depth == 0


def test_feature_roundtrip_and_gating():
    assert ft.decode_feature(ft.encode_feature(None)) is None
    assert ft.decode_feature(ft.encode_feature(123)) == 123

    funk = Funk()
    funk.txn_prepare(None, "blk")
    db = AccDb(funk)
    fid = ft.SECP256R1_PRECOMPILE
    assert not ft.is_active(db, "blk", fid, slot=50)
    ft.activate(funk, "blk", fid, slot=100)
    assert ft.activation_slot(db, "blk", fid) == 100
    assert not ft.is_active(db, "blk", fid, slot=99)
    assert ft.is_active(db, "blk", fid, slot=100)

    fs = ft.FeatureSet(db, "blk", slot=200)
    assert fs.secp256r1_precompile
    assert not fs.partitioned_epoch_rewards
    with pytest.raises(AttributeError):
        fs.not_a_feature
