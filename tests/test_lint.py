"""fdlint tier-1 gate + per-rule fixtures.

Two halves: (1) the shipped tree — `cfg/*.toml` + `firedancer_tpu/`
— must lint clean (zero non-baselined findings), so topology/contract/
purity regressions fail CI before they can wedge a topology at
runtime; (2) every shipped rule has a deliberately broken fixture
proving it fires exactly once (a rule that cannot fire is a rule that
silently rotted)."""
import json
import textwrap

import pytest

from firedancer_tpu.lint import core
from firedancer_tpu.lint.cli import main as lint_main
from firedancer_tpu.lint.contracts import (adapter_summaries,
                                           lint_tiles_source)
from firedancer_tpu.lint.graph import (lint_config, lint_config_file,
                                       lint_topology)
from firedancer_tpu.lint.jaxlint import lint_jax_source

pytestmark = pytest.mark.lint


def rule_count(findings, rule):
    return sum(1 for f in findings if f.rule == rule)


def fires_once(findings, rule):
    assert rule_count(findings, rule) == 1, \
        f"{rule}: expected exactly 1, got " \
        f"{[f.render() for f in findings]}"


# ---------------------------------------------------------------------------
# the shipped tree lints clean
# ---------------------------------------------------------------------------

def test_shipped_tree_is_clean(capsys):
    rc = lint_main(["--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["findings"] == [], doc["findings"]


def test_every_rule_has_severity_and_family():
    for rule, (family, sev, desc) in core.RULES.items():
        assert family in ("graph", "contract", "jax", "abi",
                          "ownership", "core")
        assert sev in core.SEVERITIES
        assert desc
    assert len(core.RULES) >= 12          # ISSUE 2 acceptance floor


def test_every_rule_has_a_fixture():
    """A rule without a broken-fixture test is a rule that can rot
    silently: scan this module's own source for fires_once(..., rule)
    call sites per catalog entry (a mere quoted mention — e.g. a
    does-NOT-fire assertion — must not count)."""
    import re
    with open(__file__) as f:
        src = f.read()
    exercised = set()
    for rule in core.RULES:
        # fires_once closes with `, "<rule>")` — either after the
        # findings expression's closing paren or a bare name
        if re.search(r'fires_once\(\w+,\s*"' + rule + r'"\)', src) or \
                re.search(r'\),\s*"' + rule + r'"\)', src):
            exercised.add(rule)
    missing = set(core.RULES) - exercised
    assert not missing, f"rules without fixtures: {sorted(missing)}"


def test_sup_constants_match_supervise():
    """The contract analyzer mirrors the supervisor slot ABI without
    importing the native runtime — keep the mirror honest."""
    from firedancer_tpu.disco.supervise import SUP_SLOT_MIN, SUP_SLOTS
    from firedancer_tpu.lint import contracts
    assert set(contracts.SUP_NAMES) == set(SUP_SLOTS)
    assert contracts.SUP_SLOT_MIN == SUP_SLOT_MIN


def test_registry_covers_every_adapter_kind():
    """lint/registry.py TILE_ARGS and the @register'd adapters are the
    same kind set — a new adapter must declare its arg keys."""
    from firedancer_tpu.lint.registry import TILE_ARGS
    kinds = set(adapter_summaries())
    assert kinds == set(TILE_ARGS), \
        kinds.symmetric_difference(TILE_ARGS)


# ---------------------------------------------------------------------------
# graph-family fixtures
# ---------------------------------------------------------------------------

def _cfg(links=None, tiles=None, **extra):
    cfg = {
        "link": links if links is not None else [
            {"name": "a_b", "depth": 64, "mtu": 1280}],
        "tile": tiles if tiles is not None else [
            {"name": "src", "kind": "synth", "outs": ["a_b"]},
            {"name": "dst", "kind": "sink", "ins": ["a_b"]}],
    }
    cfg.update(extra)
    return cfg


def test_graph_base_fixture_is_clean():
    assert lint_config(_cfg(), "<fixture>") == []


def test_dead_link():
    cfg = _cfg(tiles=[{"name": "src", "kind": "synth", "outs": ["a_b"]}])
    fires_once(lint_config(cfg, "<fixture>"), "dead-link")


def test_orphan_link():
    cfg = _cfg(tiles=[{"name": "dst", "kind": "sink", "ins": ["a_b"]}])
    fires_once(lint_config(cfg, "<fixture>"), "orphan-link")


def test_dup_producer():
    cfg = _cfg(tiles=[
        {"name": "s1", "kind": "synth", "outs": ["a_b"]},
        {"name": "s2", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"]}])
    fires_once(lint_config(cfg, "<fixture>"), "dup-producer")


def test_depth_pow2():
    cfg = _cfg(links=[{"name": "a_b", "depth": 96, "mtu": 1280}])
    fires_once(lint_config(cfg, "<fixture>"), "depth-pow2")


def test_mtu_underflow():
    cfg = _cfg(
        links=[{"name": "a_b", "depth": 64, "mtu": 1280},
               {"name": "b_c", "depth": 64, "mtu": 512}],
        tiles=[{"name": "src", "kind": "synth", "outs": ["a_b"]},
               {"name": "v", "kind": "verify", "ins": ["a_b"],
                "outs": ["b_c"]},
               {"name": "dst", "kind": "sink", "ins": ["b_c"]}])
    fires_once(lint_config(cfg, "<fixture>"), "mtu-underflow")


def test_backpressure_cycle():
    cfg = _cfg(
        links=[{"name": "a", "depth": 64, "mtu": 1280},
               {"name": "b", "depth": 64, "mtu": 1280}],
        tiles=[{"name": "t1", "kind": "dedup", "ins": ["b"],
                "outs": ["a"]},
               {"name": "t2", "kind": "dedup", "ins": ["a"],
                "outs": ["b"]}])
    fires_once(lint_config(cfg, "<fixture>"), "backpressure-cycle")


def test_reliable_sink():
    # metric never consumes rings: a RELIABLE in wedges the producer
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "m", "kind": "metric", "ins": ["a_b"]}])
    fires_once(lint_config(cfg, "<fixture>"), "reliable-sink")


def test_unread_in():
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "m", "kind": "metric", "ins": [["a_b", False]]}])
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "unread-in")
    assert rule_count(findings, "reliable-sink") == 0


def test_unknown_kind():
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synht", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"]}])
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "unknown-kind")
    assert "did you mean 'synth'" in findings[0].message


def test_bad_supervise():
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"],
         "supervise": {"policy": "sometimes"}}])
    fires_once(lint_config(cfg, "<fixture>"), "bad-supervise")


def test_bad_chaos_unknown_action():
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"],
         "chaos": {"events": [{"action": "explode"}]}}])
    fires_once(lint_config(cfg, "<fixture>"), "bad-chaos")


def test_bad_chaos_stall_fseq_unknown_link():
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"],
         "chaos": {"events": [{"action": "stall_fseq",
                               "link": "ghost", "at_rx": 4}]}}])
    fires_once(lint_config(cfg, "<fixture>"), "bad-chaos")


def test_dangling_ref():
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"]},
        {"name": "g", "kind": "gui", "tps_tile": "nosuch"}])
    fires_once(lint_config(cfg, "<fixture>"), "dangling-ref")


def test_bad_gui_schema_and_did_you_mean():
    # out-of-range ws bound (gui/schema.py normalize_gui, the same
    # validator topo.build runs)
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"]},
        {"name": "g", "kind": "gui", "ws_queue": 1}])
    fires_once(lint_config(cfg, "<fixture>"), "bad-gui")
    # unknown key with a did-you-mean (programmatic Topology builds
    # skip app/config.py's registry gate — the linter still catches it)
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"]},
        {"name": "g", "kind": "gui", "ws_quee": 8}])
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-gui")
    assert any("did you mean 'ws_queue'" in f.message
               for f in findings if f.rule == "bad-gui")


def test_bad_trace_unknown_key():
    cfg = _cfg(trace={"enable": True, "dept": 64})
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-trace")
    assert "did you mean 'depth'" in findings[0].message


def test_bad_trace_depth_and_tile_override():
    fires_once(lint_config(_cfg(trace={"enable": True, "depth": 100}),
                           "<fixture>"), "bad-trace")
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"],
         "trace": {"sample": 0}}])
    fires_once(lint_config(cfg, "<fixture>"), "bad-trace")


def test_bad_trace_unknown_tile_allowlist():
    cfg = _cfg(trace={"enable": True, "tiles": ["ghost"]})
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-trace")
    assert "not a declared tile" in findings[0].message


def test_trace_section_is_clean_when_valid():
    cfg = _cfg(trace={"enable": True, "depth": 256, "sample": 4,
                      "tiles": ["dst"]})
    assert lint_config(cfg, "<fixture>") == []


def test_bad_prof_unknown_key_and_shape():
    cfg = _cfg(prof={"enable": True, "slotz": 64})
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-prof")
    assert "did you mean 'slots'" in findings[0].message
    fires_once(lint_config(_cfg(prof={"enable": True, "ring": 100}),
                           "<fixture>"), "bad-prof")
    # per-tile override table goes through the same schema gate
    cfg = _cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"],
         "prof": {"hz": 0}}])
    fires_once(lint_config(cfg, "<fixture>"), "bad-prof")


def test_bad_prof_unknown_tile_refs():
    cfg = _cfg(prof={"enable": True, "tiles": ["ghost"]})
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-prof")
    assert "not a declared tile" in findings[0].message
    fires_once(lint_config(
        _cfg(prof={"enable": True, "breach_capture": ["ghost"]}),
        "<fixture>"), "bad-prof")


def test_prof_section_is_clean_when_valid():
    cfg = _cfg(prof={"enable": True, "hz": 29, "slots": 128,
                     "ring": 512, "tiles": ["dst"],
                     "breach_capture": ["dst"]})
    assert lint_config(cfg, "<fixture>") == []


def test_bad_slo_unknown_key_and_grammar():
    cfg = _cfg(slo={"fast_windw_s": 1.0})
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-slo")
    assert "did you mean 'fast_window_s'" in findings[0].message
    # broken expression grammar is a schema failure too
    fires_once(lint_config(_cfg(slo={"target": [
        {"name": "t", "expr": "dst.rx frobnicate > 1"}]}),
        "<fixture>"), "bad-slo")


def test_bad_slo_unknown_tile_metric_link():
    # target naming a metric the tile kind never exports: did-you-mean
    cfg = _cfg(slo={"target": [{"name": "t", "expr": "dst.bytez > 1"}]})
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-slo")
    assert "did you mean 'bytes'" in findings[0].message
    fires_once(lint_config(_cfg(slo={"target": [
        {"name": "t", "expr": "link.ghost.backpressure rate < 1/s"}]}),
        "<fixture>"), "bad-slo")
    fires_once(lint_config(_cfg(slo={"target": [
        {"name": "t", "expr": "ghost.work p99 < 1ms"}]}),
        "<fixture>"), "bad-slo")


def test_slo_section_is_clean_when_valid():
    cfg = _cfg(slo={"fast_window_s": 1.0, "target": [
        {"name": "lat", "expr": "dst.work p99 < 5ms"},
        {"name": "bp", "expr": "link.a_b.backpressure rate < 10/s"},
        {"name": "rx", "expr": "dst.rx rate > 1/s"}]})
    assert lint_config(cfg, "<fixture>") == []


def test_bad_shed_schema_did_you_mean_and_dead_config():
    # typo'd [shed] key: the disco/shed.py schema gate with suggestion
    cfg = _cfg(shed={"rate_ppz": 1.0})
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-shed")
    assert "did you mean 'rate_pps'" in findings[0].message
    # out-of-range value
    fires_once(lint_config(_cfg(shed={"max_peers": 1}), "<fixture>"),
               "bad-shed")
    # malformed per-tile override
    fires_once(lint_config(_cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"],
         "shed": {"burst": 0}}]), "<fixture>"), "bad-shed")
    # dead config: a shed override on a kind with no ingest door —
    # a topo that THINKS it is protected must actually be
    findings = lint_config(_cfg(tiles=[
        {"name": "src", "kind": "synth", "outs": ["a_b"]},
        {"name": "dst", "kind": "sink", "ins": ["a_b"],
         "shed": {"rate_pps": 5.0}}]), "<fixture>")
    fires_once(findings, "bad-shed")
    assert "no ingest door" in findings[0].message


def test_shed_section_is_clean_when_valid():
    cfg = _cfg(
        links=[{"name": "a_b", "depth": 64, "mtu": 1280}],
        tiles=[{"name": "src", "kind": "sock", "outs": ["a_b"],
                "shed": {"rate_pps": 50.0}},
               {"name": "dst", "kind": "sink", "ins": ["a_b"]}],
        shed={"rate_pps": 1000.0, "burst": 64, "max_peers": 256,
              "min_stake": 1, "overload_hold_s": 2.0,
              "stakes": {"127.0.0.1:9001": 500}})
    assert lint_config(cfg, "<fixture>") == []


def test_bad_witness_schema_and_stage_did_you_mean():
    # typo'd [witness] key: the witness/plan.py schema gate
    cfg = _cfg(witness={"stagez": ["kernel_vps"]})
    findings = lint_config(cfg, "<fixture>")
    fires_once(findings, "bad-witness")
    assert "did you mean 'stage'" in findings[0].message
    # unknown stage name with suggestion
    findings = lint_config(_cfg(witness={"stages": ["kernel_vp"]}),
                           "<fixture>")
    fires_once(findings, "bad-witness")
    assert "did you mean 'kernel_vps'" in findings[0].message
    # malformed per-stage override
    fires_once(lint_config(_cfg(witness={
        "stage": {"kernel_vps": {"cmd": "not an argv list"}}}),
        "<fixture>"), "bad-witness")
    # out-of-range park window
    fires_once(lint_config(_cfg(witness={"park_s": 10.0,
                                         "park_max_s": 1.0}),
                           "<fixture>"), "bad-witness")


def test_witness_section_is_clean_when_valid():
    cfg = _cfg(witness={"stages": ["device_probe", "kernel_vps"],
                        "park_s": 5.0, "park_max_s": 60.0,
                        "stage": {"kernel_vps": {"timeout_s": 900.0}}})
    assert lint_config(cfg, "<fixture>") == []


def test_bad_funk_schema_and_did_you_mean():
    # typo'd [funk] key: the funk/shmfunk.py schema gate
    findings = lint_config(_cfg(funk={"bakend": "shm"}), "<fixture>")
    fires_once(findings, "bad-funk")
    assert "did you mean 'backend'" in findings[0].message
    # unknown backend with suggestion
    findings = lint_config(_cfg(funk={"backend": "sm"}), "<fixture>")
    fires_once(findings, "bad-funk")
    assert "did you mean 'shm'" in findings[0].message
    # out-of-range heap
    fires_once(lint_config(_cfg(funk={"backend": "shm", "heap_mb": 0}),
                           "<fixture>"), "bad-funk")


def test_funk_section_is_clean_when_valid():
    cfg = _cfg(funk={"backend": "shm", "heap_mb": 4, "rec_max": 1024})
    assert lint_config(cfg, "<fixture>") == []


def test_bad_replay_schema_and_did_you_mean():
    # typo'd [replay] key: the tiles/replay.py schema gate
    findings = lint_config(_cfg(replay={"exec_tile_cn": 2}),
                           "<fixture>")
    fires_once(findings, "bad-replay")
    assert "did you mean 'exec_tile_cnt'" in findings[0].message
    # out-of-range values
    fires_once(lint_config(_cfg(replay={"exec_tile_cnt": -1}),
                           "<fixture>"), "bad-replay")
    fires_once(lint_config(_cfg(replay={"redispatch_s": 0}),
                           "<fixture>"), "bad-replay")


def test_bad_snapshot_schema_and_did_you_mean():
    # typo'd [snapshot] key: the tiles/snapshot.py schema gate
    findings = lint_config(_cfg(snapshot={"every_slot": 8}),
                           "<fixture>")
    fires_once(findings, "bad-snapshot")
    assert "did you mean 'every_slots'" in findings[0].message
    # out-of-range values
    fires_once(lint_config(_cfg(snapshot={"min_slot": -1}),
                           "<fixture>"), "bad-snapshot")
    fires_once(lint_config(_cfg(snapshot={"chunk": 8}),
                           "<fixture>"), "bad-snapshot")


def test_replay_snapshot_sections_clean_when_valid():
    cfg = _cfg(replay={"exec_tile_cnt": 2, "redispatch_s": 1.5},
               snapshot={"path": "/tmp/snap.ckpt", "every_slots": 8,
                         "min_slot": 4})
    assert lint_config(cfg, "<fixture>") == []


def test_replay_snapshot_registry_mirrors():
    """The lint registry's section-key tuples mirror the validators'
    defaults tables — a key added to one side without the other is a
    review gap."""
    from firedancer_tpu.lint.registry import (REPLAY_SECTION_KEYS,
                                              SNAPSHOT_SECTION_KEYS)
    from firedancer_tpu.tiles.replay import REPLAY_DEFAULTS
    from firedancer_tpu.tiles.snapshot import SNAPSHOT_DEFAULTS
    assert set(REPLAY_SECTION_KEYS) == set(REPLAY_DEFAULTS)
    assert set(SNAPSHOT_SECTION_KEYS) == set(SNAPSHOT_DEFAULTS)


def test_bad_flight_schema_and_did_you_mean():
    # typo'd [flight] key: the flight/__init__.py schema gate
    findings = lint_config(_cfg(flight={"segmnt_mb": 8.0}),
                           "<fixture>")
    fires_once(findings, "bad-flight")
    assert "did you mean 'segment_mb'" in findings[0].message
    # out-of-range values
    fires_once(lint_config(_cfg(flight={"hz": 0}),
                           "<fixture>"), "bad-flight")
    fires_once(lint_config(_cfg(flight={"segment_mb": 8.0,
                                        "retain_mb": 1.0}),
                           "<fixture>"), "bad-flight")
    # unknown source with suggestion
    findings = lint_config(_cfg(flight={"sources": ["metrics",
                                                    "linkz"]}),
                           "<fixture>")
    fires_once(findings, "bad-flight")
    assert "did you mean 'links'" in findings[0].message


def test_flight_section_is_clean_when_valid():
    cfg = _cfg(flight={"dir": "/tmp/fl", "segment_mb": 4.0,
                       "retain_mb": 32.0, "hz": 8.0,
                       "sources": ["metrics", "links", "slo"],
                       "incident_window_s": 2.0, "node_id": 3})
    assert lint_config(cfg, "<fixture>") == []


def test_bad_tune_schema_and_did_you_mean():
    # typo'd [tune] key: the tune/__init__.py schema gate
    findings = lint_config(_cfg(tune={"intervals": 0.5}), "<fixture>")
    fires_once(findings, "bad-tune")
    assert "did you mean 'interval_s'" in findings[0].message
    # out-of-range policy + a bad knob-override bound
    fires_once(lint_config(_cfg(tune={"hysteresis": 2.0}),
                           "<fixture>"), "bad-tune")
    fires_once(lint_config(
        _cfg(tune={"knob": {"coalesce_us": {"min": 9, "max": 3}}}),
        "<fixture>"), "bad-tune")
    # unknown knob with suggestion
    findings = lint_config(_cfg(tune={"knob": {"coalesce_u": {}}}),
                           "<fixture>")
    fires_once(findings, "bad-tune")
    assert "did you mean 'coalesce_us'" in findings[0].message
    # a controller tile with no enabled [tune] has nothing to steer
    cfg = _cfg()
    cfg["tile"].append({"name": "ctl", "kind": "controller"})
    fires_once(lint_config(cfg, "<fixture>"), "bad-tune")


def test_tune_section_is_clean_when_valid():
    cfg = _cfg(tune={"enable": True, "interval_s": 0.25,
                     "cooldown_s": 1.0, "recovery_s": 2.0,
                     "hysteresis": 0.25, "max_moves": 4,
                     "window_s": 5.0, "bp_ref": 100.0,
                     "knob": {"coalesce_us": {"max": 1000,
                                              "step": 50}}})
    cfg["tile"].append({"name": "ctl", "kind": "controller"})
    assert lint_config(cfg, "<fixture>") == []


def test_tune_registry_mirror():
    """TUNE_SECTION_KEYS/TUNE_KNOB_KEYS mirror the validator's tables
    — same contract the flight/replay/snapshot mirrors pin."""
    from firedancer_tpu.lint.registry import (TUNE_KNOB_KEYS,
                                              TUNE_SECTION_KEYS)
    from firedancer_tpu.tune import KNOB_KEYS, TUNE_DEFAULTS
    assert set(TUNE_SECTION_KEYS) == set(TUNE_DEFAULTS)
    assert set(TUNE_KNOB_KEYS) == set(KNOB_KEYS)


def test_flight_registry_mirror():
    """FLIGHT_SECTION_KEYS mirrors the validator's defaults table —
    same contract the replay/snapshot mirrors pin."""
    from firedancer_tpu.flight import FLIGHT_DEFAULTS
    from firedancer_tpu.lint.registry import FLIGHT_SECTION_KEYS
    assert set(FLIGHT_SECTION_KEYS) == set(FLIGHT_DEFAULTS)


def test_per_shard_ins_entry_expands_not_folds():
    """A sharded-tile per-shard ins entry (all-str list: shard k
    consumes entry[k]) must count every listed link as consumed — the
    old pair-folding read it as ('first', True) and orphaned the other
    shards' links into dead-link false positives."""
    cfg = _cfg(
        links=[{"name": "a_b0", "depth": 64, "mtu": 1280},
               {"name": "a_b1", "depth": 64, "mtu": 1280}],
        tiles=[{"name": "src", "kind": "synth",
                "outs": ["a_b0", "a_b1"]},
               {"name": "dst", "kind": "sink",
                "ins": [["a_b0", "a_b1"]]}])
    assert lint_config(cfg, "<fixture>") == []


def test_lint_topology_programmatic():
    """Programmatic Topology builds get the same pass as TOML."""
    from firedancer_tpu.disco import Topology
    topo = (Topology("lintfix")
            .link("a_b", depth=64, mtu=1280)
            .tile("src", "synth", outs=["a_b"]))
    fires_once(lint_topology(topo), "dead-link")


# ---------------------------------------------------------------------------
# contract-family fixtures
# ---------------------------------------------------------------------------

def _tiles_findings(src: str):
    return lint_tiles_source(textwrap.dedent(src), "<fixture.py>")


def test_reserved_metric():
    fires_once(_tiles_findings("""
        class T:
            METRICS = ["rx", "sup_down"]
        """), "reserved-metric")


def test_metrics_overflow():
    names = ", ".join(f'"m{i}"' for i in range(62))
    fires_once(_tiles_findings(f"""
        class T:
            METRICS = [{names}]
        """), "metrics-overflow")


def test_undeclared_gauge():
    fires_once(_tiles_findings("""
        class T:
            METRICS = ["rx"]
            GAUGES = ["port"]
        """), "undeclared-gauge")


def test_dup_metric():
    fires_once(_tiles_findings("""
        class T:
            METRICS = ["rx", "rx"]
        """), "dup-metric")


def test_uncredited_publish():
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                self.out_ring.publish(b"x", sig=1)
                return 1
        """), "uncredited-publish")


def test_credited_publish_is_clean():
    assert _tiles_findings("""
        class T:
            def poll_once(self):
                while self.fseqs and \\
                        self.out_ring.credits(self.fseqs) <= 0:
                    pass
                self.out_ring.publish(b"x", sig=1)
                return 1
        """) == []


def test_uncredited_publish_nested_credit_does_not_exempt():
    """A credit check inside a never-called nested helper must not
    exempt the OUTER function's publish (scope-sensitive scan)."""
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                def helper():
                    return self.out_ring.credits(self.fseqs)
                self.out_ring.publish(b"x", sig=1)
                return 1
        """), "uncredited-publish")


def test_uncredited_publish_in_nested_fn_reported_once():
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                def helper():
                    self.out_ring.publish(b"x", sig=1)
                return helper()
        """), "uncredited-publish")


def test_stale_outside_supervision():
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                self.fseq.mark_stale()
        """), "stale-outside-supervision")


def test_per_frag_loop_trace_frag():
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                for i in range(n):
                    self.trace.frag(3, sig=int(sigs[i]))
        """), "per-frag-loop")


def test_per_frag_loop_publish_via_helper_closure():
    """The rule follows poll_once's same-module call closure: a
    per-frag publish loop in a helper the hot path calls is just as
    hot as one written inline."""
    f = _tiles_findings("""
        class T:
            def poll_once(self):
                self._wait_credits()
                return self._egress(rows)
            def _egress(self, rows):
                self._wait_credits()
                for r in rows:
                    self.out_ring.publish(r, sig=1)
        """)
    fires_once(f, "per-frag-loop")


def test_per_frag_loop_indirect_through_tainted_helper():
    """A loop calling a helper whose closure reaches a single-item API
    is the same defect one frame deeper — the loop line is flagged."""
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                for i in range(n):
                    self._emit(buf[i])
            def _emit(self, frame):
                self.out_ring.publish(frame, sig=1)
        """), "per-frag-loop")


def test_per_frag_loop_callback_closure_is_hot():
    """A nested closure handed into a gather helper as a callback joins
    the hot closure via the argument edge — its own per-frag loop is
    flagged even though nothing calls it by name."""
    fires_once(_tiles_findings("""
        def _gather_all(ctx, handle):
            return 0
        class T:
            def poll_once(self):
                def cb(frame):
                    for s in frame.sigs:
                        self.trace.frag(3, sig=s)
                return _gather_all(self.ctx, cb)
        """), "per-frag-loop")


def test_per_frag_loop_untainted_helper_in_loop_is_clean():
    """Per-frame calls to helpers that do NOT reach single-item APIs
    (parse / state-machine work) stay legal — that is the
    frame-granular grain the rule's docstring carves out."""
    assert rule_count(_tiles_findings("""
        class T:
            def poll_once(self):
                for i in range(n):
                    self._handle(buf[i])
            def _handle(self, frame):
                return parse(frame)
        """), "per-frag-loop") == 0


def test_per_frag_loop_tcache_insert():
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                for s in sigs:
                    if self.tcache.insert(int(s)):
                        pass
        """), "per-frag-loop")


def test_per_frag_loop_pack_bank_fill_shape():
    """The pre-r13 pack shape — one credit-checked publish per idle
    bank inside the bank loop — is exactly what the wave rewrite
    removed; the rule must keep it out (publish_batch outside the
    loop is the fix)."""
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                for bank, ln in enumerate(self.bank_links):
                    out = self.ctx.out_rings[ln]
                    fseqs = self.ctx.out_fseqs[ln]
                    if fseqs and out.credits(fseqs) <= 0:
                        continue
                    metas = self.sched.schedule_microblock(bank)
                    if not metas:
                        continue
                    out.publish(self._serialize(bank, 0, metas), sig=0)
        """), "per-frag-loop")


def test_per_frag_loop_bank_microblock_shape():
    """The pre-r13 bank shape — per-gathered-frame execution emitting
    its poh + completion publishes inside the frame loop — stays
    flagged; the wave rewrite batches both publishes per poll."""
    f = _tiles_findings("""
        class T:
            def poll_once(self):
                self._wait_credits()
                for i in range(n):
                    frame = bytes(buf[i])
                    self._execute(frame)
                    self.poh_out.publish(frame, sig=1)
                    self.out.publish(b"done", sig=1)
        """)
    assert rule_count(f, "per-frag-loop") == 2


def test_per_frag_loop_wave_batch_shape_is_clean():
    """The r13 wave shape — schedule/serialize in the loop, ONE
    publish_batch after it (while-resume on backpressure) — is the
    rule-clean rewrite of both pack and bank."""
    assert rule_count(_tiles_findings("""
        class T:
            def poll_once(self):
                frames = []
                for bank in range(self.n_banks):
                    metas = self.sched.schedule_microblock(bank)
                    if metas:
                        frames.append(self._serialize(bank, 0, metas))
                start = 0
                while True:
                    stop, pub = self.out.publish_batch(
                        wb, sz, ids, mask, fseqs=self.fseqs,
                        start=start)
                    start = stop
                    if start >= len(frames):
                        break
        """), "per-frag-loop") == 0


def test_per_frag_loop_outside_hot_path_is_clean():
    """A per-frag loop in a function poll_once never reaches (boot
    code, test helpers) is not a hot-path defect."""
    assert rule_count(_tiles_findings("""
        class T:
            def poll_once(self):
                return 0
            def boot_fill(self, rows):
                for r in rows:
                    self.trace.frag(3, sig=1)
        """), "per-frag-loop") == 0


def test_per_frag_loop_batched_calls_are_clean():
    assert rule_count(_tiles_findings("""
        class T:
            def poll_once(self):
                self.trace.frag_batch(3, sigs)
                for ln in self.in_links:
                    n = self.rings[ln].gather(0, 64, 1280)
                stop, pub = self.out_ring.publish_batch(
                    buf, sizes, sigs, mask, fseqs=self.fseqs)
        """), "per-frag-loop") == 0


def test_per_frag_loop_suppression_on_loop_line():
    assert rule_count(_tiles_findings("""
        class T:
            def poll_once(self):
                # fdlint: disable=per-frag-loop — bounded recovery
                for s in sigs:
                    self.tcache.query(int(s))
        """), "per-frag-loop") == 0


def test_per_frag_loop_nested_loops_report_once():
    """A call inside nested fors is ONE defect, anchored at the
    outermost loop (the suppression point)."""
    f = _tiles_findings("""
        class T:
            def poll_once(self):
                for t in tags:
                    for p in pool[t]:
                        self.out_ring.publish(p, sig=t)
        """)
    fires_once(f, "per-frag-loop")


def test_silent_consumer():
    fires_once(_tiles_findings("""
        @register("demo")
        class D:
            def __init__(self, ctx, args):
                self.ring = ctx.in_rings["a"]

            def poll_once(self):
                return 0
        """), "silent-consumer")


def test_silent_consumer_with_in_seqs_is_clean():
    assert _tiles_findings("""
        @register("demo")
        class D:
            def __init__(self, ctx, args):
                self.ring = ctx.in_rings["a"]
                self.seq = 0

            def in_seqs(self):
                return {"a": self.seq}
        """) == []


# ---------------------------------------------------------------------------
# jax-family fixtures
# ---------------------------------------------------------------------------

def _jax_findings(src: str):
    return lint_jax_source(textwrap.dedent(src), "<fixture.py>")


def test_host_sync_item():
    fires_once(_jax_findings("""
        import jax

        @jax.jit
        def f(x):
            return x.sum().item()
        """), "host-sync-item")


def test_host_cast_traced():
    fires_once(_jax_findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return float(jnp.sum(x))
        """), "host-cast-traced")


def test_numpy_in_jit():
    fires_once(_jax_findings("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x)
        """), "numpy-in-jit")


def test_numpy_outside_jit_is_clean():
    assert _jax_findings("""
        import numpy as np

        def host_prep(x):
            return np.asarray(x, np.int64)
        """) == []


def test_traced_bool():
    fires_once(_jax_findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            if jnp.any(x > 0):
                return x
            return -x
        """), "traced-bool")


def test_x64_in_kernel():
    fires_once(_jax_findings("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return x.astype(jnp.int64)
        """), "x64-in-kernel")


def test_x64_in_pallas_kernel_body():
    # kernels are regions through the pallas_call reference, not a
    # decorator
    fires_once(_jax_findings("""
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...].astype(jnp.float64)

        def entry(x):
            return pl.pallas_call(kernel, out_shape=x)(x)
        """), "x64-in-kernel")


def test_prng_key_reuse():
    fires_once(_jax_findings("""
        import jax

        def f(key):
            a = jax.random.uniform(key, (2,))
            b = jax.random.normal(key, (2,))
            return a + b
        """), "prng-key-reuse")


def test_prng_split_is_clean():
    assert _jax_findings("""
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.uniform(k1, (2,))
            b = jax.random.normal(k2, (2,))
            return a + b
        """) == []


def test_prng_rebinding_idiom_is_clean():
    """The standard `key, sub = split(key)` loop rebinds sub between
    draws — not reuse."""
    assert _jax_findings("""
        import jax

        def f(key):
            key, sub = jax.random.split(key)
            a = jax.random.uniform(sub, (2,))
            key, sub = jax.random.split(key)
            b = jax.random.normal(sub, (2,))
            return a + b
        """) == []


def test_prng_reuse_in_nested_fn_reported_once():
    fires_once(_jax_findings("""
        import jax

        def outer(key):
            def inner():
                a = jax.random.uniform(key, (2,))
                b = jax.random.normal(key, (2,))
                return a + b
            return inner()
        """), "prng-key-reuse")


def test_missing_donate():
    fires_once(_jax_findings("""
        import jax

        def f(x):
            return x + 1

        g = jax.jit(f)
        """), "missing-donate")


def test_donated_jit_is_clean():
    assert _jax_findings("""
        import jax

        def f(x):
            return x + 1

        g = jax.jit(f, donate_argnums=(0,))
        """) == []


# ---------------------------------------------------------------------------
# suppressions, baseline, CLI
# ---------------------------------------------------------------------------

def test_inline_suppression_same_line():
    assert _tiles_findings("""
        class T:
            def poll_once(self):
                self.out_ring.publish(b"x")  # fdlint: disable=uncredited-publish — req/resp ring, depth-bounded
        """) == []


def test_inline_suppression_prev_line():
    assert _tiles_findings("""
        class T:
            def poll_once(self):
                # fdlint: disable=uncredited-publish — depth-bounded
                self.out_ring.publish(b"x")
        """) == []


def test_suppression_does_not_leak_to_other_rules():
    fires_once(_tiles_findings("""
        class T:
            def poll_once(self):
                self.out_ring.publish(b"x")  # fdlint: disable=dup-metric
        """), "uncredited-publish")


def test_baseline_filters_by_rule_path_line():
    f = core.finding("dead-link", "cfg/x.toml", 7, "m")
    assert core.filter_baselined(
        [f], [{"rule": "dead-link", "path": "x.toml"}]) == []
    assert core.filter_baselined(
        [f], [{"rule": "dead-link", "path": "x.toml", "line": 9}]) == [f]
    assert core.filter_baselined(
        [f], [{"rule": "orphan-link", "path": "x.toml"}]) == [f]


def test_baseline_path_needs_component_boundary():
    """An entry for demo.toml must not swallow cluster-demo.toml."""
    f = core.finding("dead-link", "cfg/cluster-demo.toml", 7, "m")
    assert core.filter_baselined(
        [f], [{"rule": "dead-link", "path": "demo.toml"}]) == [f]
    assert core.filter_baselined(
        [f], [{"rule": "dead-link", "path": "cluster-demo.toml"}]) == []


def test_bad_suppression():
    fires_once(core.check_suppressions(
        "x = 1  # fdlint: disable=missing-donte\n", "<fixture>"),
        "bad-suppression")
    assert core.check_suppressions(
        "x = 1  # fdlint: disable=missing-donate\n", "<fixture>") == []
    assert core.check_suppressions(
        "x = 1  # fdlint: disable=all\n", "<fixture>") == []


BROKEN_TOML = """
[[link]]
name = "a_b"
depth = 96
mtu = 1280

[[tile]]
name = "src"
kind = "synth"
outs = ["a_b"]
"""


def test_cli_nonzero_on_broken_fixture(tmp_path, capsys):
    p = tmp_path / "broken.toml"
    p.write_text(BROKEN_TOML)
    assert lint_main([str(p)]) == 1
    out = capsys.readouterr().out
    assert "depth-pow2" in out and "dead-link" in out


def test_cli_baseline_grandfathers(tmp_path, capsys):
    p = tmp_path / "broken.toml"
    p.write_text(BROKEN_TOML)
    bl = tmp_path / "bl.toml"
    bl.write_text('[[finding]]\nrule = "depth-pow2"\n'
                  'path = "broken.toml"\n'
                  '[[finding]]\nrule = "dead-link"\n'
                  'path = "broken.toml"\n')
    assert lint_main([str(p), "--baseline", str(bl)]) == 0
    capsys.readouterr()


def test_cli_json_is_stable(tmp_path, capsys):
    p = tmp_path / "broken.toml"
    p.write_text(BROKEN_TOML)
    lint_main([str(p), "--format", "json"])
    one = capsys.readouterr().out
    lint_main([str(p), "--format", "json"])
    two = capsys.readouterr().out
    assert one == two
    doc = json.loads(one)
    assert doc["fdlint"] == 1
    assert doc["counts"]["error"] == 2
    assert [sorted(f) for f in doc["findings"]] == [
        ["line", "message", "path", "rule", "severity"]] * 2


def test_overlay_layer_directive(tmp_path):
    """`# fdlint: layers=` loads the base stack; findings attribute to
    the layer that declares the entity, so ONE suppression in the base
    covers every stack (the cfg/cluster-demo.toml pattern)."""
    base = tmp_path / "base.toml"
    base.write_text(BROKEN_TOML)
    overlay = tmp_path / "over.toml"
    overlay.write_text("# fdlint: layers=base.toml\n"
                       '[[tile]]\nname = "dst"\nkind = "sink"\n'
                       'ins = ["a_b"]\n')
    findings = lint_config_file(str(overlay))
    assert rule_count(findings, "dead-link") == 0    # overlay consumes
    fires_once(findings, "depth-pow2")
    assert findings[0].path == str(base)             # attributed to base


# ---------------------------------------------------------------------------
# abi-family fixtures (lint/abi.py): wire contracts, short keys,
# registry drift
# ---------------------------------------------------------------------------

# a tower module whose three cataloged sites all match the catalog —
# the base the skew fixtures mutate
TOWER_OK = textwrap.dedent("""
    import struct
    def pack_block(slot, parent_slot, block_id, parent_id):
        return bytes([0]) + struct.pack("<QQ", slot, parent_slot) \\
            + block_id + parent_id
    def pack_vote(voter, stake, block_id):
        return bytes([1]) + voter + struct.pack("<Q", stake) + block_id
    class TowerCore:
        def handle(self, frame):
            slot, parent = struct.unpack_from("<QQ", frame, 1)
            (stake,) = struct.unpack_from("<Q", frame, 33)
""")


def test_wire_contracts_base_fixture_is_clean():
    from firedancer_tpu.lint.abi import lint_wire_contracts
    assert lint_wire_contracts({"tiles/tower.py": TOWER_OK}) == []


def test_wire_contracts_shipped_tree_is_clean():
    from firedancer_tpu.lint.abi import lint_wire_contracts
    assert lint_wire_contracts() == []


def test_wire_mismatch():
    # a cataloged site vanishing (rename/drop) is drift: the other
    # side of the wire still parses the cataloged layout
    from firedancer_tpu.lint.abi import lint_wire_contracts
    src = TOWER_OK.replace("def pack_vote", "def pack_vote_v2")
    findings = lint_wire_contracts({"tiles/tower.py": src})
    fires_once(findings, "wire-mismatch")
    assert "pack_vote" in findings[0].message


def test_wire_mismatch_skewed_format_names_the_site():
    """The static half of the skewed-wire drill: narrowing pack_vote's
    stake from <Q to <I flags exactly that site, both as a lost
    cataloged format and as uncataloged ABI growth."""
    from firedancer_tpu.lint.abi import lint_wire_contracts
    src = TOWER_OK.replace('struct.pack("<Q", stake)',
                           'struct.pack("<I", stake)')
    findings = lint_wire_contracts({"tiles/tower.py": src})
    assert findings and all(f.rule == "wire-mismatch" for f in findings)
    assert all("pack_vote" in f.message for f in findings)


def test_wire_mismatch_whitespace_in_format_is_not_drift():
    # struct ignores whitespace in format strings; the comparison must
    # too ("<Q Q" == "<QQ")
    from firedancer_tpu.lint.abi import lint_wire_contracts
    src = TOWER_OK.replace('struct.pack("<QQ", slot', 
                           'struct.pack("<Q Q", slot')
    assert lint_wire_contracts({"tiles/tower.py": src}) == []


def test_wire_mtu():
    # a tower vote frame is 73B fixed; a 64B out link asserts at the
    # first publish instead of failing review
    cfg = _cfg(
        links=[{"name": "a_b", "depth": 64, "mtu": 1280},
               {"name": "votes", "depth": 64, "mtu": 64}],
        tiles=[{"name": "src", "kind": "synth", "outs": ["a_b"]},
               {"name": "t", "kind": "tower", "ins": ["a_b"],
                "outs": ["votes"]},
               {"name": "dst", "kind": "sink", "ins": ["votes"]}])
    fires_once(lint_config(cfg, "<fixture>"), "wire-mtu")


def test_wire_mtu_exec_dispatch():
    # exec dispatch = 18B header + one 80B txn row minimum
    cfg = _cfg(
        links=[{"name": "a_b", "depth": 64, "mtu": 1280},
               {"name": "d0", "depth": 64, "mtu": 96},
               {"name": "c0", "depth": 64, "mtu": 8}],
        tiles=[{"name": "src", "kind": "synth", "outs": ["a_b"]},
               {"name": "b", "kind": "bank", "ins": ["a_b"],
                "outs": ["d0"], "exec_links": ["d0"],
                "exec_done": ["c0"]},
               {"name": "e", "kind": "exec", "ins": ["d0"],
                "outs": ["c0"]},
               {"name": "dst", "kind": "sink", "ins": ["c0"]}])
    findings = lint_config(cfg, "<fixture>")
    assert rule_count(findings, "wire-mtu") == 2   # dispatch AND done


def _abi_findings(body):
    from firedancer_tpu.lint.abi import lint_abi_source
    return lint_abi_source(textwrap.dedent(body), "<fixture>")


def test_short_key():
    fires_once(_abi_findings("""
        def install(funk, acct_hex):
            funk.rec_write(None, bytes.fromhex(acct_hex), 1)
    """), "short-key")


def test_short_key_provably_wrong_width():
    f = _abi_findings("""
        def install(store, h):
            store.rec_write(None, h[:15], 1)
    """)
    fires_once(f, "short-key")
    assert "provably 15 bytes" in f[0].message


def test_short_key_proofs_are_accepted():
    assert _abi_findings("""
        MARKER = b"m" * 32
        def install(funk, h, k, raw):
            funk.rec_write(None, key32(h), 1)       # helper
            funk.rec_write(None, h2(raw).digest(), 2)  # hash width
            funk.rec_write(None, raw[9:41], 3)      # const 32B slice
            funk.rec_write(None, MARKER, 4)         # module constant
            if len(k) != 32:
                raise ValueError("short")
            funk.rec_write(None, k, 5)              # guarded name
    """) == []


def test_short_key_kv_receiver_filter():
    # .put on a db/store/funk/vinyl receiver is a store write; .put on
    # anything else (dicts, caches) is not this rule's business
    f = _abi_findings("""
        def go(self, k):
            self.db.put(k, 1)
            self.cache.put(k, 2)
    """)
    fires_once(f, "short-key")


def test_registry_drift_unknown_arg():
    from firedancer_tpu.lint.abi import check_adapter_registry
    src = textwrap.dedent("""
        @register("sink")
        class SinkAdapter:
            def __init__(self, ctx, args):
                self.batch = args.get("batch", 1)
                self.bogus = args.get("not_a_registered_key")
    """)
    findings = check_adapter_registry(src, "<fixture>")
    fires_once(findings, "registry-drift")
    assert "not_a_registered_key" in findings[0].message


def test_registry_drift_did_you_mean():
    from firedancer_tpu.lint.abi import check_adapter_registry
    src = textwrap.dedent("""
        @register("sink")
        class SinkAdapter:
            def __init__(self, ctx, args):
                self.batch = args.get("bach", 1)
    """)
    findings = check_adapter_registry(src, "<fixture>")
    assert findings and "did you mean 'batch'" in findings[0].message


def test_registry_drift_unread_key():
    from firedancer_tpu.lint.abi import check_adapter_registry
    src = textwrap.dedent("""
        @register("sink")
        class SinkAdapter:
            def __init__(self, ctx, args):
                pass
    """)
    findings = check_adapter_registry(src, "<fixture>")
    fires_once(findings, "registry-drift")
    assert "'batch'" in findings[0].message


def test_registry_drift_section_mirror():
    from firedancer_tpu.lint import registry as reg
    from firedancer_tpu.lint.abi import check_section_mirror
    keys = ", ".join(f"{k!r}: None"
                     for k in reg.TRACE_SECTION_KEYS + ("bogus",))
    src = f"TRACE_DEFAULTS = {{{keys}}}\n"
    findings = check_section_mirror(
        "trace", src, "<fixture>", "TRACE_DEFAULTS",
        "TRACE_SECTION_KEYS")
    fires_once(findings, "registry-drift")
    assert "bogus" in findings[0].message


def test_registry_drift_shipped_mirrors_are_clean():
    from firedancer_tpu.lint.abi import lint_registry_drift
    assert lint_registry_drift() == []


def test_bad_suppression_new_rule_did_you_mean():
    f = core.check_suppressions(
        "x = 1  # fdlint: disable=wire-missmatch — why\n", "<f>")
    fires_once(f, "bad-suppression")
    assert "did you mean 'wire-mismatch'" in f[0].message


# ---------------------------------------------------------------------------
# ownership-family fixtures (lint/ownership.py)
# ---------------------------------------------------------------------------

def _own_findings(body, path="gossip/pusher.py"):
    from firedancer_tpu.lint.ownership import lint_ownership_source
    return lint_ownership_source(textwrap.dedent(body), path)


def test_dual_writer():
    fires_once(_own_findings("""
        def leak(self, etype):
            self._tr.frag(etype, sig=1)
    """), "dual-writer")


def test_dual_writer_sup_slots():
    fires_once(_own_findings("""
        def poke(slots, tn):
            slots[SUP_SLOTS["sup_restarts"]] = 0
    """, path="tiles/evil.py"), "dual-writer")


def test_dual_writer_restore_marker():
    fires_once(_own_findings("""
        def fake_restore(funk):
            funk.rec_write(None, RESTORE_MARKER_KEY, b"1")
    """, path="gossip/pusher.py"), "dual-writer")


def test_dual_writer_cataloged_writer_is_clean():
    # the snapshot inserter IS the restore marker's cataloged writer
    assert _own_findings("""
        def mark(funk):
            funk.rec_write(None, RESTORE_MARKER_KEY, b"1")
    """, path="tiles/snapshot.py") == []


def test_dual_writer_handoff_annotation():
    assert _own_findings("""
        def reap_mark(self, etype):
            # fdlint: disable=dual-writer — handoff: owner reaped
            self._tr.event(etype)
    """) == []


def test_torn_read():
    f = _own_findings("""
        def seed(self, view_u64):
            self.count = int(view_u64[0])
            self.sum = int(view_u64[1])
    """)
    fires_once(f, "torn-read")


def test_torn_read_snapshot_is_clean():
    assert _own_findings("""
        def seed(self, view_u64):
            snap = u64_snapshot(view_u64)
            self.count = int(snap[0])
            self.sum = int(snap[1])
    """) == []


def test_torn_read_slicing_subviews_is_clean():
    # carving sub-views at setup is lazy offset algebra, not a read
    assert _own_findings("""
        def carve(self, raw):
            v = raw.view()
            self.hdr = v[:64]
            self.ring = v[64:]
    """) == []


def test_torn_read_tango_is_exempt():
    # tango IS the atomic discipline: its speculative double-read of
    # seq around the payload copy is the protocol, not a bug
    assert _own_findings("""
        def consume(self, view_u64):
            a = view_u64[0]
            b = view_u64[0]
    """, path="runtime/tango.py") == []


# ---------------------------------------------------------------------------
# the fixed real defects stay fixed (abi/ownership rules on the
# shipped modules they flagged)
# ---------------------------------------------------------------------------

def test_fixed_defects_stay_clean():
    import os
    from firedancer_tpu.lint.abi import lint_abi_source, pkg_root
    from firedancer_tpu.lint.ownership import lint_ownership_source
    for rel in ("disco/metrics.py", "vinyl/vinyl.py",
                "utils/checkpt.py", "gossip/crds.py"):
        p = os.path.join(pkg_root(), *rel.split("/"))
        with open(p) as fp:
            src = fp.read()
        assert lint_abi_source(src, rel) == [], rel
        assert lint_ownership_source(src, rel) == [], rel


# ---------------------------------------------------------------------------
# --changed incremental mode
# ---------------------------------------------------------------------------

def test_changed_paths_lists_modified_and_untracked(tmp_path):
    import os
    import subprocess
    from firedancer_tpu.lint.cli import changed_paths
    repo = tmp_path / "r"
    repo.mkdir()
    env = {"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
           "PATH": os.environ["PATH"], "HOME": str(tmp_path)}
    def git(*a):
        subprocess.run(["git", *a], cwd=repo, env=env, check=True,
                       capture_output=True)
    git("init", "-q")
    (repo / "a.py").write_text("x = 1\n")
    git("add", "a.py")
    git("commit", "-qm", "seed")
    (repo / "a.py").write_text("x = 2\n")          # modified
    (repo / "b.toml").write_text("[link]\n")       # untracked
    got = {os.path.basename(p)
           for p in changed_paths(str(repo), "HEAD")}
    assert got == {"a.py", "b.toml"}


def test_cli_changed_mode_runs(capsys):
    # on whatever state the repo is in, --changed must produce valid
    # json and a sane exit code (full-run fallback included)
    rc = lint_main(["--changed", "--format", "json"])
    out = capsys.readouterr().out
    assert rc in (0, 1)
    if out.strip().startswith("{"):
        assert json.loads(out)["fdlint"] == 1


# ---------------------------------------------------------------------------
# provenance stamp
# ---------------------------------------------------------------------------

def test_provenance_block_carries_lint_stamp(tmp_path, monkeypatch):
    import firedancer_tpu.witness.provenance as prov
    monkeypatch.setattr(prov, "_LINT_STATE",
                        {"clean": True, "errors": 0, "warnings": 0})
    block = prov.provenance_block(str(tmp_path))
    assert block["lint"] == {"clean": True, "errors": 0, "warnings": 0}


def test_verify_artifact_flags_dirty_lint_stamp(tmp_path, capsys):
    from firedancer_tpu.witness import provenance as prov
    from firedancer_tpu.witness.cli import verify_artifact
    header = {"lint": {"clean": False, "errors": 3, "warnings": 0}}
    wit = {"header": header, "genesis": prov.chain_hash("", header),
           "stages": [], "run_id": "t"}
    p = tmp_path / "a.json"
    p.write_text(json.dumps({"witness": wit}))
    rc = verify_artifact(str(p))
    err = capsys.readouterr().err
    assert rc == 1
    assert "lint" in err
    # same artifact with a clean stamp verifies
    header2 = {"lint": {"clean": True, "errors": 0, "warnings": 0}}
    wit2 = {"header": header2, "genesis": prov.chain_hash("", header2),
            "stages": [], "run_id": "t"}
    p.write_text(json.dumps({"witness": wit2}))
    assert verify_artifact(str(p)) == 0


# ---------------------------------------------------------------------------
# the live skewed-wire drill: two real processes over a tango ring
# exchange vote frames under a deliberately narrowed stake field; the
# analyzer flagged exactly that site statically (see
# test_wire_mismatch_skewed_format_names_the_site) and the runtime
# consumer demonstrates the failure the flag prevented
# ---------------------------------------------------------------------------

SKEWED_PACK_VOTE = textwrap.dedent("""
    import struct
    def pack_vote(voter, stake, block_id):
        return bytes([1]) + voter + struct.pack("<I", stake) + block_id
""")


def _skewed_vote_producer(name, ring_off, arena_off, depth, mtu):
    from firedancer_tpu.runtime import Workspace, Ring
    w = Workspace(name, 1 << 22, create=False)
    ring = Ring(w, ring_off, depth, arena_off, mtu)
    ns = {}
    exec(compile(SKEWED_PACK_VOTE, "<skewed>", "exec"), ns)
    frame = ns["pack_vote"](b"v" * 32, 7, b"b" * 32)
    ring.publish(frame, sig=1)
    w.close()


def test_skewed_wire_drill_cross_process():
    import multiprocessing as mp
    import os
    import time
    from firedancer_tpu.lint.abi import lint_wire_contracts
    from firedancer_tpu.runtime import Workspace, Ring
    from firedancer_tpu.tiles.tower import TowerCore, pack_vote

    # static half: the analyzer flags the skewed producer site BEFORE
    # any process runs
    skewed_mod = TOWER_OK.replace('struct.pack("<Q", stake)',
                                  'struct.pack("<I", stake)')
    flagged = lint_wire_contracts({"tiles/tower.py": skewed_mod})
    assert flagged and all("pack_vote" in f.message for f in flagged)

    # runtime half: the skewed frame crosses a REAL ring between two
    # REAL processes and the consumer silently drops the vote — the
    # wedge class the static flag catches at review time
    name = f"/fdtpu_lintdrill_{os.getpid()}"
    w = Workspace(name, 1 << 22)
    try:
        depth, mtu = 8, 256
        ring = Ring.create(w, depth=depth, mtu=mtu)
        ctx = mp.get_context("fork")
        p = ctx.Process(target=_skewed_vote_producer,
                        args=(name, ring.off, ring.arena_off, depth,
                              mtu), daemon=True)
        p.start()
        deadline = time.monotonic() + 30
        frame = None
        while time.monotonic() < deadline:
            rc, frag = ring.consume(0)
            if rc == 0:
                frame = bytes(ring.payload(frag))
                break
        p.join(timeout=30)
        assert frame is not None, "producer never published"
        core_ = TowerCore(total_stake=100)
        core_.handle(frame)                   # skewed: 69B < 73B vote
        assert core_.metrics["bad_frames"] == 1
        assert core_.metrics["votes_in"] == 0
        # the correctly-packed frame from the same inputs is accepted
        core_.handle(pack_vote(b"v" * 32, 7, b"b" * 32))
        assert core_.metrics["bad_frames"] == 1
    finally:
        w.close()
        w.unlink()
