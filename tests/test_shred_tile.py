"""Shred tile tests: leader-side shredding to turbine UDP egress, and
the non-leader recover path (FEC resolve -> reassembled slices), both
in-process and as two live topologies speaking real UDP
(ref: src/disco/shred/fd_shred_tile.c:6-60 — one tile, both
directions; fd_fec_resolver.c; turbine first-hop via fd_shred_dest.c).
"""
import hashlib
import os
import socket
import struct
import time

import pytest

pytestmark = pytest.mark.slow

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.disco.monitor import attach
from firedancer_tpu.ops.poh import host_poh_append, host_poh_mixin
from firedancer_tpu.runtime import Ring
from firedancer_tpu.shred.shred_dest import ClusterNode
from firedancer_tpu.tiles.shred import (
    ShredLeaderCore, ShredRecoverCore, parse_entry_batch, parse_slice,
)
from firedancer_tpu.tiles.synth import make_signed_txns, synth_signer_seed
from firedancer_tpu.utils.ed25519_ref import keypair, sign

SEED = bytes(range(32))
_, _, LEADER_PUB = keypair(SEED)
PEER = b"\x55" * 32
N_TXNS = 24


def _wait(fn, timeout_s=540, dt=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if fn():
            return True
        time.sleep(dt)
    return False


def _entry_frame(slot, tick, num_hashes, has_mix, prev, h, mixin,
                 slot_done=False, txns=()):
    blob = b"".join(struct.pack("<H", len(t)) + t for t in txns)
    return (struct.pack("<QIIB", slot, tick, num_hashes, has_mix)
            + prev + h + mixin
            + bytes([1 if slot_done else 0])
            + struct.pack("<H", len(txns)) + blob)


class _CaptureRing:
    """Minimal ring stand-in for in-process core tests."""

    def __init__(self):
        self.frames = []

    def publish(self, frame, sig=0):
        self.frames.append((bytes(frame), sig))

    def credits(self, fseqs):
        return 1 << 30


def _gen_entries(slot, txn_groups, seed=bytes(32), ticks=2,
                 hashes_per_tick=8):
    """Synthesize a poh-consistent entry stream for one slot."""
    frames = []
    state = seed
    hashes_in_tick = 0
    tick = 0
    for txns in txn_groups:
        mixin = hashlib.sha256(b"".join(
            _first_sig(t) for t in txns)).digest()
        prev = state
        state = host_poh_mixin(prev, mixin)
        hashes_in_tick += 1
        frames.append(_entry_frame(slot, tick, 1, 1, prev, state, mixin,
                                   txns=txns))
    for i in range(ticks):
        remaining = hashes_per_tick - hashes_in_tick
        prev = state
        state = host_poh_append(prev, remaining)
        frames.append(_entry_frame(slot, tick, remaining, 0, prev,
                                   state, bytes(32),
                                   slot_done=(i == ticks - 1)))
        hashes_in_tick = 0
        tick += 1
    return frames, state


def _first_sig(txn: bytes) -> bytes:
    # compact-u16 sig count is 1 byte for small counts
    return txn[1:65]


def test_shred_cores_roundtrip_in_process():
    """Leader core shreds a slot of entries; recover core rebuilds the
    byte-identical entry batch from the (shuffled) shred wires."""
    txns = make_signed_txns(6, seed=9)
    frames, _ = _gen_entries(7, [txns[:3], txns[3:]])

    sent = []

    class _Sock:
        def sendto(self, wire, addr):
            sent.append(bytes(wire))

    batch_ring = _CaptureRing()
    core = ShredLeaderCore(
        lambda root: sign(SEED, root), LEADER_PUB,
        [ClusterNode(PEER, 100, ("127.0.0.1", 9))], _Sock(),
        batch_out=batch_ring)
    for f in frames:
        core.on_entry(f)
    assert core.metrics["slots"] == 1
    assert core.metrics["sent"] == len(sent) > 0
    assert core.metrics["sign_fail"] == 0

    (witness, _), = batch_ring.frames
    w_slot, w_complete = struct.unpack_from("<QB", witness, 0)
    batch = witness[9:]
    assert (w_slot, w_complete) == (7, 1)

    # recover from shreds in adversarial order (parity first, reversed)
    out = _CaptureRing()
    rec = ShredRecoverCore(LEADER_PUB, out, None)
    for wire in reversed(sent):
        rec.on_shred(wire)
    assert rec.metrics["slots_done"] == 1
    assert rec.metrics["parse_fail"] == 0
    slot, first, done, payload = parse_slice(out.frames[-1][0])
    assert (slot, first, done) == (7, 0, True)
    got = b"".join(parse_slice(f)[3] for f, _ in out.frames)
    assert got == batch                      # byte-identical block

    # the batch parses back into entries whose PoH chain verifies and
    # whose txns are the originals
    entries = parse_entry_batch(batch)
    all_txns = [t for _, _, ts in entries for t in ts]
    assert all_txns == txns
    state = bytes(32)
    for num_hashes, h, ts in entries:
        if ts:
            mixin = hashlib.sha256(
                b"".join(_first_sig(t) for t in ts)).digest()
            state = host_poh_mixin(
                host_poh_append(state, num_hashes - 1), mixin)
        else:
            state = host_poh_append(state, num_hashes)
        assert state == h


def test_recover_core_survives_loss():
    """Drop a data shred: parity recovers it and the slice still
    reproduces the batch."""
    txns = make_signed_txns(4, seed=11)
    frames, _ = _gen_entries(3, [txns])
    sent = []

    class _Sock:
        def sendto(self, wire, addr):
            sent.append(bytes(wire))

    batch_ring = _CaptureRing()
    core = ShredLeaderCore(
        lambda root: sign(SEED, root), LEADER_PUB,
        [ClusterNode(PEER, 100, ("127.0.0.1", 9))], _Sock(),
        batch_out=batch_ring)
    for f in frames:
        core.on_entry(f)
    batch = batch_ring.frames[0][0][9:]

    out = _CaptureRing()
    rec = ShredRecoverCore(LEADER_PUB, out, None)
    from firedancer_tpu.shred import format as fmt
    dropped = next(w for w in sent if fmt.is_data(w[fmt.VARIANT_OFF]))
    for wire in sent:
        if wire is not dropped:
            rec.on_shred(wire)
    assert rec.metrics["slots_done"] == 1
    assert rec.resolver.metrics["recovered"] >= 1
    got = b"".join(parse_slice(f)[3] for f, _ in out.frames)
    assert got == batch


# ---------------------------------------------------------------------------
# two live topologies over UDP
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_two_topology_shred_interop():
    """Topology A (leader loop + shred tile) transmits turbine shreds
    over real UDP; topology B (sock -> shred recover) FEC-resolves and
    reproduces every completed block byte-identically."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")

    # --- topology B: non-leader ingest ---
    topo_b = (
        Topology(f"shB{os.getpid()}", wksp_size=1 << 24)
        .link("sock_shred", depth=512, mtu=1280)
        .link("shred_slices", depth=64, mtu=1 << 16)
        .tile("sock", "sock", outs=["sock_shred"], port=0, batch=64,
              mtu=1280)
        .tile("shred", "shred", ins=["sock_shred"],
              outs=["shred_slices"], mode="recover",
              leader_pubkey_hex=LEADER_PUB.hex())
        .tile("slsink", "sink", ins=["shred_slices"])
    )
    plan_b = topo_b.build()
    runner_b = TopologyRunner(plan_b).start()
    try:
        runner_b.wait_running(timeout_s=540)
        assert _wait(lambda: runner_b.metrics("sock")["port"] != 0,
                     timeout_s=30)
        port_b = int(runner_b.metrics("sock")["port"])

        genesis = {}
        for i in range(16):
            pub = keypair(synth_signer_seed(i))[-1]
            genesis[pub.hex()] = 1 << 44
        cluster = [{"pubkey_hex": PEER.hex(), "stake": 100,
                    "addr": f"127.0.0.1:{port_b}"}]
        topo_a = (
            Topology(f"shA{os.getpid()}", wksp_size=1 << 25)
            .link("synth_verify", depth=128, mtu=1280)
            .link("verify_dedup", depth=128, mtu=1280)
            .link("dedup_pack", depth=128, mtu=1280)
            .link("pack_bank0", depth=32, mtu=1 << 14)
            .link("bank0_done", depth=32, mtu=64)
            .link("bank0_poh", depth=64, mtu=(1 << 14) + 22)
            .link("poh_entries", depth=256, mtu=(1 << 14) + 256)
            .link("poh_slots", depth=64, mtu=64)
            .link("shred_batches", depth=128, mtu=1 << 16)
            .link("shred_req", depth=16, mtu=1280)
            .link("sign_resp", depth=16, mtu=128)
            .tcache("verify_tc", depth=4096)
            .tcache("dedup_tc", depth=4096)
            .tile("synth", "synth", outs=["synth_verify"], count=N_TXNS,
                  unique=N_TXNS, seed=6)
            .tile("verify", "verify", ins=["synth_verify"],
                  outs=["verify_dedup"], batch=16, tcache="verify_tc")
            .tile("dedup", "dedup", ins=["verify_dedup"],
                  outs=["dedup_pack"], tcache="dedup_tc")
            .tile("pack", "pack", ins=["dedup_pack", "bank0_done",
                                       "poh_slots"],
                  outs=["pack_bank0"], txn_in="dedup_pack",
                  bank_links=["pack_bank0"], done_links=["bank0_done"],
                  slot_in="poh_slots", max_txn_per_microblock=8)
            .tile("bank0", "bank", ins=["pack_bank0"],
                  outs=["bank0_done", "bank0_poh"], exec="svm",
                  poh_link="bank0_poh", genesis=genesis,
                  forward_payloads=True)
            .tile("poh", "poh", ins=["bank0_poh"],
                  outs=["poh_entries", "poh_slots"],
                  slot_link="poh_slots", hashes_per_tick=16,
                  ticks_per_slot=4)
            .tile("shred", "shred",
                  ins=["poh_entries", ("sign_resp", False)],
                  outs=["shred_req", "shred_batches"], mode="leader",
                  identity_hex=LEADER_PUB.hex(), cluster=cluster,
                  req="shred_req", resp="sign_resp",
                  batches_link="shred_batches")
            .tile("sign", "sign", ins=[("shred_req", False)],
                  outs=["sign_resp"], seed=SEED.hex(),
                  clients=[{"role": "leader", "req": "shred_req",
                            "resp": "sign_resp"}])
            .tile("bsink", "sink", ins=["shred_batches"])
        )
        plan_a = topo_a.build()
        runner_a = TopologyRunner(plan_a).start()
        try:
            runner_a.wait_running(timeout_s=540)
            # leader shreds at least 2 complete slots
            assert _wait(lambda: runner_a.metrics("shred")["slots"] >= 2,
                         timeout_s=300)
            assert runner_a.metrics("shred")["sign_fail"] == 0
            assert runner_a.metrics("shred")["no_dest"] == 0
            # B completes those slots (UDP loss is covered by parity)
            assert _wait(
                lambda: runner_b.metrics("shred")["slots_done"] >= 2,
                timeout_s=120)

            # byte-identity per slot: A's batch witness vs B's slices.
            # Both producers keep ticking slots, so read a RECENT
            # window of each ring (late-attach, like a real observer)
            # and compare slots that are complete inside both windows.
            _, wksp_a = attach(plan_a["topology"])
            li = plan_a["links"]["shred_batches"]
            ring_a = Ring(wksp_a, li["ring_off"], li["depth"],
                          li["arena_off"], li["mtu"])
            _, wksp_b = attach(plan_b["topology"])
            lib = plan_b["links"]["shred_slices"]
            ring_b = Ring(wksp_b, lib["ring_off"], lib["depth"],
                          lib["arena_off"], lib["mtu"])

            deadline = time.monotonic() + 120
            common = {}
            while time.monotonic() < deadline and len(common) < 2:
                start_a = max(0, ring_a.seq - li["depth"] // 4)
                n, _, buf, sizes, _, _ = ring_a.gather(
                    start_a, li["depth"] // 4, li["mtu"])
                expected = {}                # slot -> batch bytes
                for i in range(n):
                    frame = bytes(buf[i, :sizes[i]])
                    slot, complete = struct.unpack_from("<QB", frame, 0)
                    if complete:             # single-flush slots only
                        expected.setdefault(slot, frame[9:])

                start_b = max(0, ring_b.seq - lib["depth"] // 4)
                nb, _, bufb, sizesb, _, _ = ring_b.gather(
                    start_b, lib["depth"] // 4, lib["mtu"])
                got = {}
                for i in range(nb):
                    slot, first, done, payload = parse_slice(
                        bytes(bufb[i, :sizesb[i]]))
                    if done and first == 0:
                        got.setdefault(slot, payload)
                common = {s: (expected[s], got[s])
                          for s in expected.keys() & got.keys()}
                if len(common) < 2:
                    time.sleep(0.5)
            assert len(common) >= 2, (len(expected), len(got))
            for slot, (exp, g) in common.items():
                assert g == exp, f"slot {slot}"
                assert parse_entry_batch(g)   # content parses back
        finally:
            runner_a.halt()
            runner_a.close()
    finally:
        runner_b.halt()
        runner_b.close()


def test_recover_core_retransmits_to_turbine_children():
    """A non-leader forwards valid shreds to its children in the
    stake-weighted tree; invalid shreds are never retransmitted."""
    import struct as _struct

    from firedancer_tpu.shred import format as fmt
    from firedancer_tpu.tiles.shred import ShredDest
    txns = make_signed_txns(2, seed=4)
    sent_out = []

    class _Sock:
        def sendto(self, wire, addr):
            sent_out.append((bytes(wire), addr))

    # leader produces several slots' shreds
    wires = []

    class _LeaderSock:
        def sendto(self, wire, addr):
            wires.append(bytes(wire))

    core = ShredLeaderCore(
        lambda root: sign(SEED, root), LEADER_PUB,
        [ClusterNode(PEER, 100, ("127.0.0.1", 9))], _LeaderSock())
    state = bytes(32)
    from tests.test_shred_tile import _gen_entries as gen
    for slot in range(4):
        frames, state = gen(slot, [txns] if slot == 1 else [],
                            seed=state)
        for f in frames:
            core.on_entry(f)

    ME, OTHER = b"\x61" * 32, b"\x62" * 32
    dest = ShredDest([ClusterNode(ME, 50, ("127.0.0.1", 21)),
                      ClusterNode(OTHER, 50, ("127.0.0.1", 22))],
                     self_pubkey=ME, fanout=1)
    rec = ShredRecoverCore(LEADER_PUB, _CaptureRing(), None,
                           dest=dest, identity=ME, sock=_Sock())
    expected = 0
    for w in wires:
        slot, = _struct.unpack_from("<Q", w, 0x41)
        idx, = _struct.unpack_from("<I", w, 0x49)
        t = 1 if fmt.is_data(w[fmt.VARIANT_OFF]) else 0
        expected += len(dest.children(slot, idx, t, LEADER_PUB))
        rec.on_shred(w)
    assert rec.metrics["retransmitted"] == expected
    assert expected > 0                      # we ARE root sometimes
    assert all(a == ("127.0.0.1", 22) for _, a in sent_out)
    # garbage never retransmits
    before = rec.metrics["retransmitted"]
    rec.on_shred(b"\xde\xad" * 100)
    assert rec.metrics["retransmitted"] == before
    # a REPLAYED shred never amplifies (per-shred dedup)
    rec.on_shred(wires[0])
    assert rec.metrics["retransmitted"] == before
    # repair responses never re-enter turbine
    rec.on_shred(wires[1], retransmit=False)
    assert rec.metrics["retransmitted"] == before
