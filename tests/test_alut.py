"""Address lookup table program + v0 resolution tests
(ref: src/flamenco/runtime/program/fd_address_lookup_table_program.c,
src/discof/resolv/ — the v0 loaded-addresses contract)."""
import struct

import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn, parse_txn
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID
from firedancer_tpu.svm.alut import (
    ALUT_PROGRAM_ID, AlutState, SLOT_MAX, derive_table_address,
    ix_close, ix_create, ix_deactivate, ix_extend, resolve_loaded_keys,
)
from firedancer_tpu.svm.programs import (
    ERR_ALUT, ERR_INVALID_OWNER, ERR_MISSING_SIG, OK,
)

FEE = 5000


def k(n):
    return bytes([n]) * 32


PAYER = k(1)
LOOKED_UP = [k(0x41), k(0x42), k(0x43)]


def txn(signers, extra, instrs, n_ro_unsigned=0, version=-1, aluts=()):
    msg = build_message(signers, extra, b"\x11" * 32, instrs,
                        n_ro_unsigned=n_ro_unsigned, version=version,
                        aluts=aluts)
    return build_txn([bytes(64)] * len(signers), msg)


@pytest.fixture
def env():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, PAYER, Account(lamports=1 << 30))
    funk.txn_prepare(None, "blk")
    ex = TxnExecutor(db, enforce_rent=False)
    ex.slot = 100
    return funk, db, ex


def _create_and_extend(funk, db, ex, addresses):
    table, bump = derive_table_address(PAYER, 90)
    r = ex.execute("blk", txn(
        [PAYER], [table, ALUT_PROGRAM_ID],
        [(2, bytes([1, 0]), ix_create(90, bump))], n_ro_unsigned=1))
    assert r.status == OK, r.status
    r = ex.execute("blk", txn(
        [PAYER], [table, ALUT_PROGRAM_ID],
        [(2, bytes([1, 0]), ix_extend(addresses))], n_ro_unsigned=1))
    assert r.status == OK, r.status
    return table


def test_create_extend_state(env):
    funk, db, ex = env
    table = _create_and_extend(funk, db, ex, LOOKED_UP)
    st = AlutState.from_bytes(db.peek("blk", table).data)
    assert st.addresses == LOOKED_UP
    assert st.authority == PAYER
    assert st.deactivation_slot == SLOT_MAX
    assert st.last_extended_slot == 100


def test_create_rejects_wrong_pda(env):
    funk, db, ex = env
    _, bump = derive_table_address(PAYER, 90)
    r = ex.execute("blk", txn(
        [PAYER], [k(0x77), ALUT_PROGRAM_ID],
        [(2, bytes([1, 0]), ix_create(90, bump))], n_ro_unsigned=1))
    assert r.status == ERR_INVALID_OWNER


def test_extend_requires_authority_signature(env):
    funk, db, ex = env
    table = _create_and_extend(funk, db, ex, LOOKED_UP[:1])
    evil = k(0x66)
    funk.rec_write("blk", evil, Account(lamports=1 << 30))
    r = ex.execute("blk", txn(
        [evil], [table, PAYER, ALUT_PROGRAM_ID],
        [(3, bytes([1, 2]), ix_extend([k(0x55)]))], n_ro_unsigned=2))
    # authority (PAYER) is present but NOT a signer
    assert r.status == ERR_MISSING_SIG


def test_v0_txn_executes_through_looked_up_account(env):
    """A v0 transfer whose destination exists ONLY via the lookup
    table: resolution extends the key list and the transfer lands."""
    funk, db, ex = env
    table = _create_and_extend(funk, db, ex, LOOKED_UP)
    # static keys: [PAYER, SYSTEM]; loaded writable idx 2 -> LOOKED_UP[1]
    t = txn([PAYER], [SYSTEM_PROGRAM_ID],
            [(1, bytes([0, 2]), struct.pack("<IQ", 2, 999))],
            n_ro_unsigned=1, version=0,
            aluts=[(table, bytes([1]), b"")])
    parsed = parse_txn(t)
    assert parsed.aluts[0][0] == table
    keys, flags = resolve_loaded_keys(db, "blk", parsed, slot=100)
    assert keys == [LOOKED_UP[1]] and flags == [True]
    r = ex.execute("blk", t)
    assert r.status == OK, r.status
    assert db.lamports("blk", LOOKED_UP[1]) == 999


def test_v0_loaded_readonly_cannot_be_written(env):
    funk, db, ex = env
    table = _create_and_extend(funk, db, ex, LOOKED_UP)
    t = txn([PAYER], [SYSTEM_PROGRAM_ID],
            [(1, bytes([0, 2]), struct.pack("<IQ", 2, 999))],
            n_ro_unsigned=1, version=0,
            aluts=[(table, b"", bytes([1]))])     # loaded as READONLY
    r = ex.execute("blk", t)
    assert r.status == "account_not_writable"
    assert db.lamports("blk", LOOKED_UP[1]) == 0


def test_v0_missing_table_fails_cleanly(env):
    funk, db, ex = env
    t = txn([PAYER], [SYSTEM_PROGRAM_ID],
            [(1, bytes([0, 2]), struct.pack("<IQ", 2, 1))],
            n_ro_unsigned=1, version=0,
            aluts=[(k(0x77), bytes([0]), b"")])
    r = ex.execute("blk", t)
    assert r.status == ERR_ALUT
    assert r.fee == FEE                  # fee still charged


def test_deactivate_blocks_resolution_then_close(env):
    funk, db, ex = env
    table = _create_and_extend(funk, db, ex, LOOKED_UP)
    r = ex.execute("blk", txn(
        [PAYER], [table, ALUT_PROGRAM_ID],
        [(2, bytes([1, 0]), ix_deactivate())], n_ro_unsigned=1))
    assert r.status == OK
    # resolution at a later slot fails (deactivated)
    ex.slot = 200
    t = txn([PAYER], [SYSTEM_PROGRAM_ID],
            [(1, bytes([0, 2]), struct.pack("<IQ", 2, 1))],
            n_ro_unsigned=1, version=0,
            aluts=[(table, bytes([0]), b"")])
    assert ex.execute("blk", t).status == ERR_ALUT
    # close after cooldown returns lamports to the recipient
    funk.rec_write("blk", table, Account(
        lamports=777, data=db.peek("blk", table).data,
        owner=ALUT_PROGRAM_ID))
    r = ex.execute("blk", txn(
        [PAYER], [table, k(0x50), ALUT_PROGRAM_ID],
        [(3, bytes([1, 0, 2]), ix_close())], n_ro_unsigned=1))
    assert r.status == OK, r.status
    assert db.lamports("blk", k(0x50)) == 777
    assert db.peek("blk", table).data == b""
