"""Direct SHA-256/512 kernel tests: fixed known-answer vectors (the
FIPS 180-4 examples the reference's CAVP suite starts from, ref:
src/ballet/sha512/cavp/ and test_sha256.c vectors), randomized
differential vs hashlib across lengths/block boundaries, and a
large-batch lane-independence check."""
import hashlib

import jax.numpy as jnp
import numpy as np
import pytest

from firedancer_tpu.ops.sha2 import sha256, sha512

# FIPS 180-4 / CAVP short-message known answers
KAT = [
    (b"", "sha256",
     "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"),
    (b"abc", "sha256",
     "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"),
    (b"abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq", "sha256",
     "248d6a61d20638b8e5c026930c3e6039a33ce45964ff2167f6ecedd419db06c1"),
    (b"", "sha512",
     "cf83e1357eefb8bdf1542850d66d8007d620e4050b5715dc83f4a921d36ce9ce"
     "47d0d13c5d85f2b0ff8318d2877eec2f63b931bd47417a81a538327af927da3e"),
    (b"abc", "sha512",
     "ddaf35a193617abacc417349ae20413112e6fa4e89a97ea20a9eeee64b55d39a"
     "2192992a274fc1a836ba3c23a3feebbd454d4423643ce80e2a9ac94fa54ca49f"),
    (b"abcdefghbcdefghicdefghijdefghijkefghijklfghijklmghijklmn"
     b"hijklmnoijklmnopjklmnopqklmnopqrlmnopqrsmnopqrstnopqrstu", "sha512",
     "8e959b75dae313da8cf4f72814fc143f8f7779c6eb9f7fa17299aeadb6889018"
     "501d289e4900f7e4331b99dec4b5433ac7d329eeb6dd26545e96e55b874be909"),
]


def _run(fn, data: bytes, max_len: int):
    msg = np.zeros((1, max_len), np.uint8)
    msg[0, :len(data)] = np.frombuffer(data, np.uint8)
    out = fn(jnp.asarray(msg), jnp.asarray([len(data)], jnp.int32))
    return bytes(np.asarray(out[0]))


@pytest.mark.parametrize("data,alg,want", KAT)
def test_known_answers(data, alg, want):
    fn = sha256 if alg == "sha256" else sha512
    got = _run(fn, data, max_len=128)
    assert got.hex() == want


@pytest.mark.parametrize("alg", ["sha256", "sha512"])
def test_differential_lengths(alg):
    """Every length across block/padding boundaries vs hashlib."""
    fn = sha256 if alg == "sha256" else sha512
    oracle = getattr(hashlib, alg)
    block = 64 if alg == "sha256" else 128
    max_len = 3 * block
    rng = np.random.default_rng(7)
    lens = list(range(0, 2 * block + 2)) + [max_len - 1, max_len]
    msgs = np.zeros((len(lens), max_len), np.uint8)
    for i, L in enumerate(lens):
        msgs[i, :L] = rng.integers(0, 256, L, dtype=np.uint8)
    out = np.asarray(fn(jnp.asarray(msgs),
                        jnp.asarray(lens, dtype=jnp.int32)))
    for i, L in enumerate(lens):
        want = oracle(msgs[i, :L].tobytes()).digest()
        assert bytes(out[i]) == want, f"len {L}"


def test_large_batch_lane_independence():
    """4K lanes, mixed lengths: each lane must match hashlib exactly
    (VERDICT r1: large-batch evidence was missing)."""
    rng = np.random.default_rng(11)
    n, max_len = 4096, 96
    lens = rng.integers(0, max_len + 1, n)
    msgs = np.zeros((n, max_len), np.uint8)
    for i, L in enumerate(lens):
        msgs[i, :L] = rng.integers(0, 256, L, dtype=np.uint8)
    out = np.asarray(sha256(jnp.asarray(msgs),
                            jnp.asarray(lens, dtype=jnp.int32)))
    idx = rng.choice(n, 64, replace=False)
    for i in idx:
        want = hashlib.sha256(msgs[i, :lens[i]].tobytes()).digest()
        assert bytes(out[i]) == want
    # full-batch check via vectorized comparison on a second pass
    want_all = np.stack([
        np.frombuffer(hashlib.sha256(msgs[i, :lens[i]].tobytes())
                      .digest(), np.uint8) for i in range(n)])
    assert np.array_equal(out, want_all)
