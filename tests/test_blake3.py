"""BLAKE3 + lthash tests: host oracle vs the standard public vectors
(tests/vectors/blake3_vectors.json, extracted by convert_blake3.py from
the reference's embedded copy of BLAKE3-team test_vectors.json), and
the batched jnp kernel pinned to the oracle (ref:
src/ballet/blake3/fd_blake3_ref.c, src/ballet/lthash/fd_lthash.h)."""
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from firedancer_tpu.ops.blake3 import (  # noqa: E402
    blake3_batch, lthash_batch, lthash_add, lthash_reduce, lthash_sub,
)
from firedancer_tpu.utils.blake3_ref import blake3, lthash  # noqa: E402

VEC = os.path.join(os.path.dirname(__file__), "vectors",
                   "blake3_vectors.json")


def _msg(v):
    return bytes(v["sz"]) if v["zeros"] else \
        bytes(i % 251 for i in range(v["sz"]))


def test_oracle_standard_vectors():
    vecs = json.load(open(VEC))
    assert len(vecs) >= 20
    for v in vecs:
        assert blake3(_msg(v)).hex() == v["hash"], v["sz"]


def test_oracle_xof_prefix_property():
    """XOF output extends the 32-byte digest."""
    m = b"xof-check"
    assert blake3(m, 128)[:32] == blake3(m, 32)
    assert len(lthash(m)) == 2048


def test_batch_kernel_matches_oracle():
    rng = np.random.default_rng(5)
    lens = [0, 1, 63, 64, 65, 300, 1023, 1024, 1025, 1500, 2047, 2048]
    max_len = 2048
    msg = np.zeros((len(lens), max_len), np.uint8)
    raw = []
    for i, ln in enumerate(lens):
        m = rng.bytes(ln)
        raw.append(m)
        msg[i, :ln] = np.frombuffer(m, np.uint8)
    out = np.asarray(blake3_batch(jnp.asarray(msg),
                                  jnp.asarray(lens, np.int32)))
    for i, m in enumerate(raw):
        assert bytes(out[i]) == blake3(m), f"len {lens[i]}"


def test_batch_kernel_masks_padding():
    """Bytes beyond msg_len must not affect the digest."""
    m = b"masked-tail"
    a = np.zeros((1, 256), np.uint8)
    a[0, :len(m)] = np.frombuffer(m, np.uint8)
    b = a.copy()
    b[0, len(m):] = 0xEE
    ln = jnp.asarray([len(m)], jnp.int32)
    assert bytes(np.asarray(blake3_batch(jnp.asarray(a), ln))[0]) == \
        bytes(np.asarray(blake3_batch(jnp.asarray(b), ln))[0]) == blake3(m)


def test_lthash_batch_and_homomorphism():
    rng = np.random.default_rng(7)
    msgs = [rng.bytes(40), rng.bytes(1200), b""]
    max_len = 2048
    arr = np.zeros((len(msgs), max_len), np.uint8)
    lens = np.zeros((len(msgs),), np.int32)
    for i, m in enumerate(msgs):
        arr[i, :len(m)] = np.frombuffer(m, np.uint8)
        lens[i] = len(m)
    vals = np.asarray(lthash_batch(jnp.asarray(arr), jnp.asarray(lens)))
    for i, m in enumerate(msgs):
        want = np.frombuffer(lthash(m), np.uint16)
        assert (vals[i] == want).all(), i
    # homomorphism: (a + b + c) - b == a + c, wrapping u16
    acc = np.zeros((1024,), np.uint16)
    acc = np.asarray(lthash_add(acc, vals[0]))
    acc = np.asarray(lthash_add(acc, vals[1]))
    acc = np.asarray(lthash_add(acc, vals[2]))
    acc = np.asarray(lthash_sub(acc, vals[1]))
    want = (vals[0].astype(np.uint32) + vals[2]) & 0xFFFF
    assert (acc == want.astype(np.uint16)).all()
    # order independence + reduce fan-in (the snapla/snapls property)
    r1 = np.asarray(lthash_reduce(jnp.asarray(vals)))
    r2 = np.asarray(lthash_reduce(jnp.asarray(vals[::-1].copy())))
    assert (r1 == r2).all()
