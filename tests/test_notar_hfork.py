"""notar confirmation thresholds, hfork detection, voter accessors
(ref: src/choreo/notar/fd_notar.h, src/choreo/hfork/fd_hfork.h,
src/choreo/voter/fd_voter.h)."""
import struct

import pytest

from firedancer_tpu.choreo.notar import Notar
from firedancer_tpu.choreo.hfork import HforkDetector
from firedancer_tpu.choreo import voter as voter_mod
from firedancer_tpu.flamenco import types as fdtypes


def _v(i):
    return bytes([i]) * 32


def _bid(i):
    return bytes([0xB0, i]) + bytes(30)


# ---------------------------------------------------------------------------
# notar
# ---------------------------------------------------------------------------

def test_notar_thresholds_in_order():
    # 10 voters x 10 stake; thresholds: propagated >=1/3 (34), dup >52%
    # (>52 -> 60), optimistic >=2/3 (>=67 -> 70)
    n = Notar()
    n.set_epoch_stakes({_v(i): 10 for i in range(10)})
    n.on_block(5, 4, _bid(1))
    kinds = []
    for i in range(10):
        for c in n.on_vote(_v(i), 5, _bid(1)):
            kinds.append((c.kind, i))
    assert kinds == [("propagated", 3),   # 4th voter -> 40 >= 33.3
                     ("duplicate", 5),    # 6th voter -> 60 > 52
                     ("optimistic", 6)]   # 7th voter -> 70 >= 66.7


def test_notar_no_double_count():
    n = Notar()
    n.set_epoch_stakes({_v(0): 60, _v(1): 40})
    # same voter voting twice contributes once
    n.on_vote(_v(1), 3, _bid(0))
    assert n.slots[3].stake == 40
    n.on_vote(_v(1), 3, _bid(0))
    assert n.slots[3].stake == 40
    assert n.blocks[_bid(0)].stake == 40


def test_notar_stake_counts_multiple_blocks_same_slot():
    """Unlike ghost, a switching validator counts toward both block
    versions of a slot (equivocation case)."""
    n = Notar()
    n.set_epoch_stakes({_v(i): 10 for i in range(10)})
    for i in range(10):
        n.on_vote(_v(i), 7, _bid(1))
    for i in range(10):
        n.on_vote(_v(i), 7, _bid(2))
    assert n.blocks[_bid(1)].stake == 100
    assert n.blocks[_bid(2)].stake == 100
    # slot-level stake still counts each voter once
    assert n.slots[7].stake == 100


def test_notar_dup_confirm_remaps_block_id():
    n = Notar()
    n.set_epoch_stakes({_v(i): 10 for i in range(10)})
    n.on_block(9, 8, _bid(1))            # we replayed version 1
    assert n.slot_block_id[9] == _bid(1)
    for i in range(7):
        n.on_vote(_v(i), 9, _bid(2))     # cluster dup-confirms version 2
    assert n.is_duplicate_confirmed(_bid(2))
    assert n.slot_block_id[9] == _bid(2)


def test_notar_late_replay_adopts_dup_confirmed_id():
    """Cluster dup-confirms a version BEFORE we replay the slot: our
    later on_block must adopt the confirmed id, not its own version."""
    n = Notar()
    n.set_epoch_stakes({_v(i): 10 for i in range(10)})
    for i in range(7):
        n.on_vote(_v(i), 9, _bid(2))
    assert n.is_duplicate_confirmed(_bid(2))
    n.on_block(9, 8, _bid(1))            # we replayed the other version
    assert n.slot_block_id[9] == _bid(2)


def test_notar_may_vote_requires_propagated_leader_slot():
    n = Notar()
    n.set_epoch_stakes({_v(i): 10 for i in range(10)})
    n.on_block(10, 9, _bid(1), is_leader=True)
    n.on_block(12, 10, _bid(2), prev_leader_slot=10)
    assert n.may_vote(10)                # own leader block: always
    assert not n.may_vote(12)            # leader slot 10 not propagated
    for i in range(4):
        n.on_vote(_v(i), 10, _bid(1))
    assert n.is_propagated(10)
    assert n.may_vote(12)


def test_notar_publish_prunes():
    n = Notar()
    n.set_epoch_stakes({_v(0): 1})
    n.on_vote(_v(0), 3, _bid(3))
    n.on_vote(_v(0), 8, _bid(8))
    n.publish(5)
    assert 3 not in n.slots and _bid(3) not in n.blocks
    assert 8 in n.slots and _bid(8) in n.blocks
    assert n.on_vote(_v(0), 4, _bid(4)) == []   # below root: ignored


# ---------------------------------------------------------------------------
# hfork
# ---------------------------------------------------------------------------

def test_hfork_divergent_hash_alarm():
    h = HforkDetector(total_stake=100)
    h.on_our_result(_bid(1), b"\x11" * 32)
    alerts = []
    for i in range(10):
        alerts += h.on_vote(_v(i), _bid(1), b"\x22" * 32, 10)
    assert len(alerts) == 1
    a = alerts[0]
    assert a.reason == "divergent" and a.our_hash == b"\x11" * 32
    assert a.cluster_hash == b"\x22" * 32 and a.stake > 52


def test_hfork_agreement_no_alarm():
    h = HforkDetector(total_stake=100)
    h.on_our_result(_bid(1), b"\x11" * 32)
    for i in range(10):
        assert h.on_vote(_v(i), _bid(1), b"\x11" * 32, 10) == []


def test_hfork_dead_block_alarm_and_late_our_result():
    h = HforkDetector(total_stake=100)
    # votes arrive before we know our own result
    for i in range(10):
        h.on_vote(_v(i), _bid(2), b"\x33" * 32, 10)
    assert h.alerts == []
    h.on_our_result(_bid(2), None)       # we marked it dead
    assert [a.reason for a in h.alerts] == ["dead"]


def test_hfork_self_vote_mismatch_immediate():
    me = _v(42)
    h = HforkDetector(total_stake=1000, identity=me)
    h.on_our_result(_bid(3), b"\x44" * 32)
    alerts = h.on_vote(me, _bid(3), b"\x55" * 32, 1)
    assert [a.reason for a in alerts] == ["self"]


def test_hfork_replay_plus_gossip_counts_once():
    """The same (voter, block, hash) observation via two paths must not
    double-count stake toward the 52% threshold."""
    h = HforkDetector(total_stake=100)
    h.on_our_result(_bid(1), b"\x11" * 32)
    v = _v(3)                            # 27% voter, seen twice
    assert h.on_vote(v, _bid(1), b"\x22" * 32, 27) == []
    assert h.on_vote(v, _bid(1), b"\x22" * 32, 27) == []
    assert h.weights[_bid(1)][b"\x22" * 32] == 27
    assert h.alerts == []


def test_hfork_ours_lru_bounded():
    h = HforkDetector(total_stake=100, max_blocks=4)
    for i in range(10):
        h.on_our_result(_bid(i), bytes([i]) * 32)
    assert len(h.ours) == 4
    assert _bid(9) in h.ours and _bid(0) not in h.ours


def test_hfork_ring_eviction_subtracts_stake():
    h = HforkDetector(total_stake=100, max_live=2)
    v = _v(7)
    h.on_vote(v, _bid(1), b"\x11" * 32, 60)
    h.on_vote(v, _bid(2), b"\x11" * 32, 60)
    h.on_vote(v, _bid(3), b"\x11" * 32, 60)   # evicts the _bid(1) entry
    assert _bid(1) not in h.weights or not h.weights[_bid(1)]
    # stale weight can no longer trip an alarm
    h.on_our_result(_bid(1), b"\x99" * 32)
    assert h.alerts == []


# ---------------------------------------------------------------------------
# voter accessors
# ---------------------------------------------------------------------------

def test_voter_accessors_v2_match_full_decode():
    votes = [(100, 5), (101, 4), (102, 3)]
    data = fdtypes.encode_vote_state(
        _v(1), _v(2), _v(3), commission=7, votes=votes, root_slot=99)
    assert voter_mod.kind(data) == voter_mod.V2
    assert voter_mod.node_pubkey(data) == _v(1)
    assert voter_mod.last_vote_slot(data) == 102
    assert voter_mod.root_slot(data) == 99
    assert voter_mod.tower(data) == votes
    full = fdtypes.decode_vote_state(data)
    assert full["votes"] == votes and full["root_slot"] == 99


def test_voter_accessors_v2_empty_tower():
    data = fdtypes.encode_vote_state(
        _v(1), _v(2), _v(3), commission=0, votes=[], root_slot=None)
    assert voter_mod.last_vote_slot(data) is None
    assert voter_mod.root_slot(data) is None
    assert voter_mod.tower(data) == []


def test_voter_accessors_v3_latency_stride():
    """Hand-built V3 (current) prefix: 13-byte entries with the leading
    latency byte (ref fd_voter.h votes_v3)."""
    votes = [(7, 31), (8, 30)]
    buf = struct.pack("<I", 2) + _v(9) + _v(8) + bytes([5])
    buf += struct.pack("<Q", len(votes))
    for slot, conf in votes:
        buf += bytes([1]) + struct.pack("<QI", slot, conf)
    buf += bytes([1]) + struct.pack("<Q", 6)      # root = Some(6)
    assert voter_mod.kind(buf) == voter_mod.V3
    assert voter_mod.last_vote_slot(buf) == 8
    assert voter_mod.root_slot(buf) == 6
    assert voter_mod.tower(buf) == votes


def test_voter_rejects_garbage():
    with pytest.raises(voter_mod.VoterError):
        voter_mod.kind(b"\x07\x00\x00\x00" + bytes(80))
    with pytest.raises(voter_mod.VoterError):
        voter_mod.last_vote_slot(bytes(10))
