"""keyguard tests: role-based signing authorization + the sign tile's
request/response rings (ref: src/disco/keyguard/fd_keyguard_authorize.c,
src/disco/sign/fd_sign_tile.c)."""
import os

from firedancer_tpu.keyguard import (
    ROLE_GOSSIP, ROLE_LEADER, ROLE_REPAIR, ROLE_SEND,
    SIGN_TYPE_ED25519, SIGN_TYPE_SHA256_ED25519,
    KeyguardClient, SignTile, authorize,
)
from firedancer_tpu.keyguard.keyguard import PING_TOKEN_PREFIX
from firedancer_tpu.protocol.txn import build_message
from firedancer_tpu.runtime import Ring, Workspace
from firedancer_tpu.utils.ed25519_ref import keypair, verify

SEED = bytes(range(32))
_, _, IDENTITY = keypair(SEED)


def vote_message() -> bytes:
    return build_message(
        [IDENTITY], [b"\x07" * 32], b"\x01" * 32,
        [(1, bytes([0]), b"vote-ix-data")])


# ---------------------------------------------------------------------------
# authorization matrix
# ---------------------------------------------------------------------------

def test_leader_signs_only_merkle_roots():
    root = os.urandom(32)
    assert authorize(IDENTITY, root, ROLE_LEADER, SIGN_TYPE_ED25519)
    assert not authorize(IDENTITY, root + b"x", ROLE_LEADER,
                         SIGN_TYPE_ED25519)
    assert not authorize(IDENTITY, vote_message(), ROLE_LEADER,
                         SIGN_TYPE_ED25519)


def test_send_signs_only_txn_messages():
    msg = vote_message()
    assert authorize(IDENTITY, msg, ROLE_SEND, SIGN_TYPE_ED25519)
    assert not authorize(IDENTITY, os.urandom(32), ROLE_SEND,
                         SIGN_TYPE_ED25519)
    # a gossip-ish blob must not be signable by the send role
    assert not authorize(IDENTITY, os.urandom(200), ROLE_SEND,
                         SIGN_TYPE_ED25519)


def test_gossip_ping_pong_prune():
    ping = PING_TOKEN_PREFIX + os.urandom(16)
    assert authorize(IDENTITY, ping, ROLE_GOSSIP, SIGN_TYPE_ED25519)
    pong = PING_TOKEN_PREFIX + os.urandom(32)
    assert authorize(IDENTITY, pong, ROLE_GOSSIP,
                     SIGN_TYPE_SHA256_ED25519)
    assert not authorize(IDENTITY, pong, ROLE_GOSSIP, SIGN_TYPE_ED25519)
    # prune must lead with OUR identity (ref: authorize.c:90)
    prune_ok = IDENTITY + os.urandom(32)
    prune_bad = os.urandom(64)
    assert authorize(IDENTITY, prune_ok, ROLE_GOSSIP, SIGN_TYPE_ED25519)
    assert authorize(IDENTITY, prune_bad, ROLE_GOSSIP,
                     SIGN_TYPE_ED25519)  # falls into CRDS-value class
    # but a repair-shaped request is NOT gossip-signable
    repair = (9).to_bytes(4, "little") + IDENTITY + os.urandom(60)
    assert not authorize(IDENTITY, repair, ROLE_GOSSIP, SIGN_TYPE_ED25519)


def test_repair_requires_own_sender_pubkey():
    body = os.urandom(60)
    ok = (9).to_bytes(4, "little") + IDENTITY + body
    wrong_key = (9).to_bytes(4, "little") + os.urandom(32) + body
    wrong_disc = (7).to_bytes(4, "little") + IDENTITY + body
    assert authorize(IDENTITY, ok, ROLE_REPAIR, SIGN_TYPE_ED25519)
    assert not authorize(IDENTITY, wrong_key, ROLE_REPAIR,
                         SIGN_TYPE_ED25519)
    assert not authorize(IDENTITY, wrong_disc, ROLE_REPAIR,
                         SIGN_TYPE_ED25519)
    # shred roots are not repair-signable
    assert not authorize(IDENTITY, os.urandom(32), ROLE_REPAIR,
                         SIGN_TYPE_ED25519)


def test_oversize_refused():
    assert not authorize(IDENTITY, b"\x00" * 2000, ROLE_GOSSIP,
                         SIGN_TYPE_ED25519)


# ---------------------------------------------------------------------------
# sign tile over rings
# ---------------------------------------------------------------------------

def test_sign_tile_request_response():
    w = Workspace(f"/fdtpu_kg{os.getpid()}", 1 << 21)
    try:
        req_l = Ring.create(w, depth=16, mtu=1280)   # leader leg
        rsp_l = Ring.create(w, depth=16, mtu=128)
        req_s = Ring.create(w, depth=16, mtu=1280)   # send leg
        rsp_s = Ring.create(w, depth=16, mtu=128)
        tile = SignTile(SEED, [
            {"role": ROLE_LEADER, "in_ring": req_l, "out_ring": rsp_l,
             "out_fseqs": []},
            {"role": ROLE_SEND, "in_ring": req_s, "out_ring": rsp_s,
             "out_fseqs": []},
        ])
        leader = KeyguardClient(req_l, rsp_l)
        sender = KeyguardClient(req_s, rsp_s)

        root = os.urandom(32)
        leader.req.publish(bytes([SIGN_TYPE_ED25519]) + root, sig=0)
        assert tile.poll_once() == 1
        n, _, buf, sizes, sigs, _ = rsp_l.gather(0, 4, 128)
        assert n == 1 and buf[0, 0] == 1
        sig = bytes(buf[0, 1:65])
        assert verify(sig, IDENTITY, root)
        assert tile.metrics["signed"] == 1

        # the leader leg must refuse a vote-txn message (role mismatch)
        msg = vote_message()
        leader.req.publish(bytes([SIGN_TYPE_ED25519]) + msg, sig=1)
        tile.poll_once()
        assert tile.metrics["refused"] == 1
        # ... while the send leg signs it
        sender.req.publish(bytes([SIGN_TYPE_ED25519]) + msg, sig=0)
        tile.poll_once()
        n, _, buf, sizes, sigs, _ = rsp_s.gather(0, 4, 128)
        assert n == 1 and buf[0, 0] == 1
        assert verify(bytes(buf[0, 1:65]), IDENTITY, msg)
    finally:
        w.close()
        w.unlink()


def test_keyguard_client_roundtrip_threaded():
    """Client blocks on the response ring while the tile polls in
    another thread — the full req/resp discipline."""
    import threading
    w = Workspace(f"/fdtpu_kg2_{os.getpid()}", 1 << 21)
    try:
        req = Ring.create(w, depth=16, mtu=1280)
        rsp = Ring.create(w, depth=16, mtu=128)
        tile = SignTile(SEED, [
            {"role": ROLE_LEADER, "in_ring": req, "out_ring": rsp,
             "out_fseqs": []}])
        stop = threading.Event()

        def pump():
            while not stop.is_set():
                tile.poll_once()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        try:
            client = KeyguardClient(req, rsp)
            root = os.urandom(32)
            sig = client.sign(root)
            assert sig is not None and verify(sig, IDENTITY, root)
            # refusal surfaces as None, not a timeout
            assert client.sign(b"\xff" * 100) is None
        finally:
            stop.set()
            t.join(5)
    finally:
        w.close()
        w.unlink()
