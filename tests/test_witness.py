"""fdwitness: the witnessed-sweep orchestrator (firedancer_tpu/witness/).

Covers the ISSUE 11 test checklist: plan schema + did-you-mean (and the
load/build/lint triple for [witness]), checkpoint/resume after a
scripted mid-sweep stage failure, provenance hash-chain verification
(tamper detected — in a stage, in the flat record, and in a checkpoint
on disk), watch-mode probe timeout with a hanging fake backend, and a
fast end-to-end smoke through the real orchestrator producing a
verifiable artifact + merged report. The stage commands in the fast
tests are scripted JSON-printing children (the committed
[witness.stage.<name>] cmd seam); the slow half runs the REAL
--cpu-smoke stages.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from firedancer_tpu.witness import (
    STAGES, WITNESS_DEFAULTS, WITNESS_STAGE_KEYS, WitnessRun,
    build_plan, latest_witnessed, next_round, normalize_witness,
    record_sha256, verify_chain, watch, witnessed_rounds,
)

pytestmark = pytest.mark.witness

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def _ok_cmd(doc: dict) -> list:
    return [PY, "-c", f"import json;print(json.dumps({doc!r}))"]


def _scripted_cfg(extra=None, stages=None):
    """A full scripted plan: every stage a tiny JSON-printing child."""
    stage = {
        "device_probe": {"cmd": _ok_cmd(
            {"platform": "tpu", "device_kind": "fake v5",
             "device_count": 2})},
        "kernel_vps": {"cmd": _ok_cmd(
            {"metric": "ed25519_verifies_per_sec", "value": 402819.5,
             "unit": "verifies/s/chip", "platform": "tpu",
             "rlc_bulk_vps": 551000.0})},
        "mxu_fmul": {"cmd": _ok_cmd(
            {"platform": "tpu", "mxu_verdict": "NO-GO",
             "mxu_speedup_vs_vpu": 1.06})},
        "e2e_feed": {"cmd": _ok_cmd(
            {"platform": "tpu", "e2e_tps": 53000.0,
             "e2e_knee_tps": 51000.0})},
        "leader_knee": {"cmd": _ok_cmd(
            {"platform": "cpu", "e2e_leader_tps": 1234.0,
             "e2e_leader_knee_tps": 1200.0})},
        "exec_scale": {"cmd": _ok_cmd(
            {"platform": "cpu", "exec_scale_count": 1024,
             "exec_scale_tps": {"1": 900.0, "2": 1400.0},
             "exec_scale_tps_1": 900.0, "exec_scale_tps_2": 1400.0,
             "exec_scale_monotonic_1_2": True})},
        "flood_soak": {"cmd": _ok_cmd(
            {"platform": "tpu", "flood_goodput_tps": 900.0,
             "flood_pass": True, "rlc_prefilter_vps": 480000.0})},
        "autotune": {"cmd": _ok_cmd(
            {"platform": "cpu", "tuned_vs_default_tps": 1.04,
             "autotune_knobs": {"coalesce_us": 400, "verify_batch": 32},
             "autotune_points": 9})},
        "multichip": {"cmd": _ok_cmd(
            {"platform": "tpu", "multichip_devices": 2,
             "layouts": {"one_mesh_tile": {"vps": 800000.0},
                         "rr_tiles": {"vps": 1010000.0}},
             "multichip_choice": "rr_tiles"})},
    }
    for name, ov in (extra or {}).items():
        stage[name] = ov
    cfg = {"stage": stage}
    if stages:
        cfg["stages"] = stages
    return cfg


# -- schema ------------------------------------------------------------------

def test_normalize_witness_defaults_and_validation():
    d = normalize_witness(None)
    assert d["stages"] is None and d["out_dir"] == ".fdwitness"
    assert d["park_max_s"] >= d["park_s"] > 0
    with pytest.raises(ValueError, match="did you mean 'stage'"):
        normalize_witness({"stagez": 1})
    with pytest.raises(ValueError, match="did you mean 'kernel_vps'"):
        normalize_witness({"stages": ["kernel_vp"]})
    with pytest.raises(ValueError, match="park_max_s"):
        normalize_witness({"park_s": 10.0, "park_max_s": 1.0})
    with pytest.raises(ValueError, match="probe_timeout_s"):
        normalize_witness({"probe_timeout_s": 0})
    with pytest.raises(ValueError, match="did you mean 'timeout_s'"):
        normalize_witness({"stage": {"kernel_vps": {"timeoutz_s": 1}}})
    with pytest.raises(ValueError, match="argv list"):
        normalize_witness({"stage": {"kernel_vps": {"cmd": "x y"}}})
    with pytest.raises(ValueError, match="string -> string"):
        normalize_witness({"stage": {"kernel_vps":
                                     {"env": {"A": 1}}}})
    # subsets normalize into CATALOG order (the chain order)
    got = normalize_witness({"stages": ["kernel_vps",
                                        "device_probe"]})["stages"]
    assert got == ["device_probe", "kernel_vps"]


def test_registry_mirrors_witness_keys():
    """The fdlint key registry's [witness] mirror must track the one
    validator's schema (the [trace]/[slo]/[prof]/[shed] honesty
    contract)."""
    from firedancer_tpu.lint import registry as reg
    assert set(reg.WITNESS_SECTION_KEYS) == set(WITNESS_DEFAULTS)
    assert set(reg.WITNESS_STAGE_KEYS) == set(WITNESS_STAGE_KEYS)


def test_build_plan_resolves_stages_and_overrides():
    plan = build_plan(None, REPO, cpu_smoke=True)
    assert [s["name"] for s in plan] == list(STAGES)
    kern = next(s for s in plan if s["name"] == "kernel_vps")
    assert kern["env"]["FDTPU_BENCH_CHILD"] == "1"
    assert kern["env"]["JAX_PLATFORMS"] == "cpu"
    # per-stage override wins; disabled stages drop out
    cfg = {"stage": {"kernel_vps": {"cmd": ["echo", "hi"],
                                    "timeout_s": 7.0},
                     "flood_soak": {"enable": False}}}
    plan = build_plan(cfg, REPO, stages=["kernel_vps", "flood_soak"])
    assert [s["name"] for s in plan] == ["kernel_vps"]
    assert plan[0]["cmd"] == ["echo", "hi"]
    assert plan[0]["timeout_s"] == 7.0
    with pytest.raises(ValueError, match="empty"):
        build_plan({"stage": {"kernel_vps": {"enable": False}}},
                   REPO, stages=["kernel_vps"])


def test_config_triple_gate(tmp_path):
    """[witness] gets the standard load/build/lint triple: a typo'd
    key fails topology build with a did-you-mean AND lands as a
    bad-witness fdlint finding; the clean section passes both."""
    from firedancer_tpu.app.config import build_topology, load_config
    from firedancer_tpu.lint.graph import lint_config_file
    bad = tmp_path / "bad.toml"
    bad.write_text("[witness]\nstagez = [\"kernel_vps\"]\n")
    with pytest.raises(ValueError, match="did you mean 'stage'"):
        build_topology(load_config(str(bad)))
    fs = lint_config_file(str(bad))
    assert [f.rule for f in fs] == ["bad-witness"]
    assert "did you mean" in fs[0].message
    good = tmp_path / "good.toml"
    good.write_text("[witness]\nstages = [\"device_probe\"]\n"
                    "park_s = 1.0\npark_max_s = 2.0\n"
                    "[witness.stage.device_probe]\ntimeout_s = 5.0\n")
    build_topology(load_config(str(good)))
    assert lint_config_file(str(good)) == []
    # a typo'd SECTION name is still rejected at parse (typo safety)
    typo = tmp_path / "typo.toml"
    typo.write_text("[witnes]\nx = 1\n")
    with pytest.raises(ValueError, match="unknown config sections"):
        load_config(str(typo))


# -- provenance chain --------------------------------------------------------

def test_chain_seal_and_tamper_detection():
    from firedancer_tpu.witness.provenance import chain_hash, seal
    header = {"git": {"sha": "abc", "dirty": False}}
    genesis = chain_hash("", header)
    c1 = seal({"stage": "a", "status": "ok", "result": {"v": 1}},
              genesis)
    c2 = seal({"stage": "b", "status": "ok", "result": {"v": 2}},
              c1["hash"])
    wit = {"header": header, "genesis": genesis,
           "stages": [c1, c2], "head": c2["hash"]}
    assert verify_chain(wit) == []
    # tamper a stage result -> content mismatch at that stage
    c1t = dict(c1)
    c1t["result"] = {"v": 999}
    errs = verify_chain({**wit, "stages": [c1t, c2]})
    assert any("'a'" in e and "tampered" in e for e in errs)
    # tamper the header -> genesis breaks
    errs = verify_chain({**wit,
                         "header": {"git": {"sha": "evil",
                                            "dirty": False}}})
    assert any("header tampered" in e for e in errs)
    # reorder/relink -> prev_hash breaks
    c2t = dict(c2)
    c2t["prev_hash"] = genesis
    c2t["hash"] = chain_hash(genesis,
                             {k: v for k, v in c2t.items()
                              if k != "hash"})
    errs = verify_chain({**wit, "stages": [c2t, c1]})
    assert any("broke the chain" in e for e in errs)


def test_provenance_block_shape():
    from firedancer_tpu.witness.provenance import provenance_block
    os.environ["FDTPU_BENCH_TESTKNOB"] = "7"
    try:
        b = provenance_block(REPO, extra_env={"FDTPU_BENCH_X": "1"})
    finally:
        del os.environ["FDTPU_BENCH_TESTKNOB"]
    assert len(b["git"]["sha"]) >= 7 and isinstance(b["git"]["dirty"],
                                                    bool)
    assert b["knobs"]["FDTPU_BENCH_TESTKNOB"] == "7"
    assert b["knobs"]["FDTPU_BENCH_X"] == "1"   # the env the stage SAW
    assert b["clock"]["monotonic_ns"] > 0
    assert "jax" in b["versions"]


# -- checkpoint / resume -----------------------------------------------------

def test_mid_sweep_failure_then_resume(tmp_path):
    """A scripted stage failure parks the sweep; rerunning the same
    run-id skips every completed stage (checkpoints untouched), reruns
    the failed one, finishes, and the chain verifies end to end."""
    marker = tmp_path / "flaky_marker"
    flaky = {"cmd": [PY, "-c", (
        "import json,os,sys\n"
        f"m = {str(marker)!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close(); sys.exit(9)\n"
        "print(json.dumps({'platform': 'tpu', 'e2e_tps': 53000.0}))\n"
    )]}
    cfg = _scripted_cfg(extra={"e2e_feed": flaky})
    art = str(tmp_path / "BENCH_r97_witnessed.json")
    run = WitnessRun.create(REPO, run_id="flap", cfg=cfg,
                            out_dir=str(tmp_path), artifact_path=art,
                            log=lambda *a: None)
    assert run.run() == 1                     # parked at the failure
    assert not os.path.exists(art)
    ck = run.checkpoints()
    assert [c["status"] for c in ck] == ["ok", "ok", "ok", "failed"]
    kernel_hash = ck[1]["hash"]
    # resume: no run-id given -> the latest unfinalized run continues
    run2 = WitnessRun.create(REPO, cfg=cfg, out_dir=str(tmp_path),
                             artifact_path=art, log=lambda *a: None)
    assert run2.doc["run_id"] == "flap"
    assert run2.run() == 0
    ck = run2.checkpoints()
    assert [c["status"] for c in ck] == ["ok"] * len(STAGES)
    assert ck[1]["hash"] == kernel_hash       # completed: NOT rerun
    doc = json.load(open(art))
    assert verify_chain(doc["witness"]) == []
    assert doc["witness"]["record_sha256"] == record_sha256(doc)


def test_timeout_is_kill_hardened(tmp_path):
    """A hanging stage (the tunnel's documented failure mode) is killed
    at its deadline and checkpointed as `timeout`; resume reruns it."""
    cfg = _scripted_cfg(
        extra={"kernel_vps": {"cmd": [PY, "-c",
                                      "import time; time.sleep(60)"],
                              "timeout_s": 0.5}},
        stages=["device_probe", "kernel_vps"])
    run = WitnessRun.create(REPO, run_id="hang", cfg=cfg,
                            out_dir=str(tmp_path),
                            artifact_path=str(tmp_path / "a.json"),
                            log=lambda *a: None)
    t0 = time.monotonic()
    assert run.run() == 1
    assert time.monotonic() - t0 < 10
    ck = run.checkpoints()
    assert ck[-1]["status"] == "timeout"
    assert "deadline" in ck[-1]["result"]["error"]


def test_tampered_checkpoint_refuses_resume(tmp_path):
    """Editing a checkpoint on disk breaks the chain; the runner
    refuses to extend a tampered run (exit 2)."""
    cfg = _scripted_cfg(stages=["device_probe", "kernel_vps",
                                "e2e_feed"])
    # fail the LAST stage so there is something left to resume
    cfg["stage"]["e2e_feed"] = {"cmd": [PY, "-c",
                                        "import sys; sys.exit(3)"]}
    run = WitnessRun.create(REPO, run_id="tamper", cfg=cfg,
                            out_dir=str(tmp_path),
                            artifact_path=str(tmp_path / "a.json"),
                            log=lambda *a: None)
    assert run.run() == 1
    kp = os.path.join(run.run_dir, "01_kernel_vps.json")
    doc = json.load(open(kp))
    doc["result"]["value"] = 1.0
    json.dump(doc, open(kp, "w"))
    assert run.run() == 2


def test_nonzero_exit_with_json_line_is_failed(tmp_path):
    """A stage that exits nonzero is a failure even when it printed a
    structured JSON line (multichip's no-mesh error shape) — it must
    rerun on resume, not be skipped as completed."""
    cfg = _scripted_cfg(
        extra={"kernel_vps": {"cmd": [PY, "-c", (
            "import json,sys;"
            "print(json.dumps({'error': 'no mesh'}));sys.exit(1)")]}},
        stages=["device_probe", "kernel_vps"])
    run = WitnessRun.create(REPO, run_id="rcfail", cfg=cfg,
                            out_dir=str(tmp_path),
                            artifact_path=str(tmp_path / "a.json"),
                            log=lambda *a: None)
    assert run.run() == 1
    ck = run.checkpoints()
    assert ck[-1]["status"] == "failed"
    assert ck[-1]["result"]["stage_rc"] == 1


def test_witnessed_platform_falls_back_to_probe_fingerprint():
    """Stages that emit no platform (leader/flood children) or the
    'device' placeholder (the e2e parent) inherit the probe stage's
    fingerprint; an explicit 'cpu*' platform stays authoritative."""
    from firedancer_tpu.witness.artifact import merge_stages
    from firedancer_tpu.witness.provenance import seal

    def ck(stage, result, device, status="ok"):
        return seal({"stage": stage, "status": status,
                     "result": result,
                     "provenance": {"device": device}}, "p")
    tpu = {"platform": "tpu", "device_kind": "v5"}
    m = merge_stages([
        ck("flood_soak", {"flood_goodput_tps": 9.0}, tpu),   # no plat
        ck("e2e_feed", {"e2e_tps": 5.0, "platform": "device"}, tpu),
        ck("leader_knee", {"e2e_leader_tps": 2.0,
                           "platform": "cpu"}, tpu),  # explicit wins
    ])["witnessed"]
    assert m["flood_goodput_tps"]["witnessed"] is True
    assert m["e2e_tps"]["witnessed"] is True
    assert m["e2e_leader_tps"]["witnessed"] is False
    # no probe fingerprint at all -> never witnessed
    m = merge_stages([ck("e2e_feed", {"e2e_tps": 5.0,
                                      "platform": "device"}, {})])
    assert m["witnessed"]["e2e_tps"]["witnessed"] is False


def test_auto_resume_requires_matching_plan(tmp_path):
    """A leftover unfinalized run must not hijack an invocation with a
    different plan (e.g. --cpu-smoke after a parked full run); mutable
    execution knobs (--keep-going) DO follow the new invocation."""
    cfg = _scripted_cfg(stages=["device_probe", "kernel_vps"])
    cfg["stage"]["kernel_vps"] = {"cmd": [PY, "-c",
                                          "import sys; sys.exit(3)"]}
    run = WitnessRun.create(REPO, run_id="parked", cfg=cfg,
                            out_dir=str(tmp_path),
                            artifact_path=str(tmp_path / "a.json"),
                            log=lambda *a: None)
    assert run.run() == 1                       # parked at the failure
    # different stage list -> fresh run, not a hijacked resume
    other = WitnessRun.create(REPO, cfg=_scripted_cfg(
        stages=["device_probe"]), out_dir=str(tmp_path),
        artifact_path=str(tmp_path / "b.json"), log=lambda *a: None)
    assert other.doc["run_id"] != "parked"
    # same plan + keep_going override -> resumes AND keeps going past
    # the (still-failing) stage to finalize
    cfg2 = dict(cfg)
    cfg2["keep_going"] = True
    again = WitnessRun.create(REPO, cfg=cfg2, out_dir=str(tmp_path),
                              artifact_path=str(tmp_path / "a.json"),
                              log=lambda *a: None)
    assert again.doc["run_id"] == "parked"
    assert again.doc["keep_going"] is True
    assert again.run() == 0
    assert again.finalized()
    # the failed kernel stage is in the chain but contributes NO
    # headline metrics — a keep-going artifact carries gaps, not
    # clean-looking numbers from a failed run
    doc = json.load(open(tmp_path / "a.json"))
    assert "value" not in doc and "value" not in doc["witnessed"]
    assert [s["status"] for s in doc["witness"]["stages"]] \
        == ["ok", "failed"]


def test_cpu_record_never_clobbers_chip_artifact(tmp_path):
    """A cpu-measured run pointed (or defaulted) at an existing
    chip-witnessed artifact diverts into its run dir instead of
    overwriting the irreplaceable chip number; and a cpu-smoke run's
    DEFAULT artifact path never leaves the run dir at all."""
    target = tmp_path / "BENCH_r90_witnessed.json"
    target.write_text(json.dumps({"platform": "tpu",
                                  "value": 402819.5}))
    cfg = _scripted_cfg(stages=["device_probe", "kernel_vps"])
    cfg["stage"]["device_probe"] = {"cmd": _ok_cmd(
        {"platform": "cpu", "device_count": 1})}
    cfg["stage"]["kernel_vps"] = {"cmd": _ok_cmd(
        {"metric": "x", "value": 1.0, "platform": "cpu"})}
    run = WitnessRun.create(REPO, run_id="clobber", cfg=cfg,
                            out_dir=str(tmp_path),
                            artifact_path=str(target),
                            log=lambda *a: None)
    assert run.run() == 0
    assert json.load(open(target))["value"] == 402819.5   # intact
    diverted = os.path.join(run.run_dir, target.name)
    assert json.load(open(diverted))["platform"] == "cpu"
    # cpu-smoke default path: inside the run dir, never the repo root
    smoke = WitnessRun.create(REPO, run_id="smokeart",
                              cfg=_scripted_cfg(
                                  stages=["device_probe"]),
                              cpu_smoke=True, out_dir=str(tmp_path),
                              log=lambda *a: None)
    assert smoke.doc["artifact"].startswith(smoke.run_dir)


# -- watch mode --------------------------------------------------------------

def test_watch_parks_on_hanging_probe(tmp_path):
    """The probe child hangs forever; the watcher kills it at the
    deadline, parks with backoff, and gives up cleanly at max_probes
    without ever blocking."""
    cfg = _scripted_cfg(stages=["device_probe"])
    run = WitnessRun.create(REPO, run_id="park", cfg=cfg,
                            out_dir=str(tmp_path),
                            artifact_path=str(tmp_path / "a.json"),
                            log=lambda *a: None)
    t0 = time.monotonic()
    rc = watch(run, probe_timeout_s=0.5, park_s=0.05, park_max_s=0.1,
               max_probes=3,
               probe_cmd=[PY, "-c", "import time; time.sleep(60)"],
               log=lambda *a: None)
    assert rc == 3
    assert time.monotonic() - t0 < 10
    assert run.checkpoints() == []            # nothing ran


def test_watch_parks_on_cpu_then_runs_when_up(tmp_path):
    cfg = _scripted_cfg(stages=["device_probe", "kernel_vps"])
    art = str(tmp_path / "BENCH_r96_witnessed.json")
    run = WitnessRun.create(REPO, run_id="updown", cfg=cfg,
                            out_dir=str(tmp_path), artifact_path=art,
                            log=lambda *a: None)
    # cpu-only backend + require_accel -> parked
    rc = watch(run, probe_timeout_s=5, park_s=0.05, park_max_s=0.1,
               max_probes=2, probe_cmd=_ok_cmd({"platform": "cpu"}),
               log=lambda *a: None)
    assert rc == 3 and run.checkpoints() == []
    # device answers -> the sweep runs to the artifact
    rc = watch(run, probe_timeout_s=5, park_s=0.05, park_max_s=0.1,
               max_probes=2,
               probe_cmd=_ok_cmd({"platform": "tpu",
                                  "device_kind": "fake"}),
               log=lambda *a: None)
    assert rc == 0 and os.path.exists(art)


# -- artifact / report / discovery -------------------------------------------

@pytest.fixture(scope="module")
def sweep(tmp_path_factory):
    """One full scripted sweep shared by the artifact-facing tests."""
    tmp = tmp_path_factory.mktemp("sweep")
    art = str(tmp / "BENCH_r95_witnessed.json")
    run = WitnessRun.create(REPO, run_id="full", cfg=_scripted_cfg(),
                            out_dir=str(tmp), artifact_path=art,
                            log=lambda *a: None)
    assert run.run() == 0
    return {"tmp": tmp, "artifact": art,
            "report": os.path.splitext(art)[0] + ".report.html"}


def test_artifact_merges_all_stanzas(sweep):
    doc = json.load(open(sweep["artifact"]))
    # bare bench.py record shape: every reader consumes it unchanged
    assert doc["value"] == 402819.5 and doc["platform"] == "tpu"
    assert doc["rlc_bulk_vps"] == 551000.0
    assert doc["e2e_tps"] == 53000.0
    assert doc["e2e_leader_knee_tps"] == 1200.0
    assert doc["flood_pass"] is True
    assert doc["tuned_vs_default_tps"] == 1.04
    assert doc["mxu_fmul"]["mxu_verdict"] == "NO-GO"
    assert doc["multichip_choice"] == "rr_tiles"
    # witnessed-vs-fallback is explicit per metric
    assert doc["witnessed"]["e2e_tps"]["witnessed"] is True
    assert doc["witnessed"]["e2e_leader_tps"]["witnessed"] is False
    # self-describing: chain + seal verify offline
    assert verify_chain(doc["witness"]) == []
    assert doc["witness"]["record_sha256"] == record_sha256(doc)
    # every stage stamped with provenance
    for ck in doc["witness"]["stages"]:
        assert ck["provenance"]["git"]["sha"]
        assert "knobs" in ck["provenance"]


def test_fdbench_verifies_and_detects_tamper(sweep, tmp_path):
    r = subprocess.run([PY, "-m", "firedancer_tpu.prof.bench_diff",
                        "--verify", sweep["artifact"]],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "chain intact" in r.stdout
    assert "[witnessed]" in r.stdout and "[cpu]" in r.stdout
    doc = json.load(open(sweep["artifact"]))
    doc["witness"]["stages"][1]["result"]["value"] = 1.0
    bad = tmp_path / "tampered.json"
    bad.write_text(json.dumps(doc))
    r = subprocess.run([PY, "-m", "firedancer_tpu.prof.bench_diff",
                        "--verify", str(bad)],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 1
    assert "TAMPERED" in r.stderr


def test_fdbench_diff_reports_witnessed_vs_fallback(sweep):
    """The diff names each number's provenance: [wit] chain-stamped,
    [cpu] smoke, [fb] carried-forward witnessed record."""
    from firedancer_tpu.prof.bench_diff import (diff_bench, load_bench,
                                                render_text)
    new = load_bench(sweep["artifact"])
    old = {"metric": "ed25519_verifies_per_sec", "value": 100.0,
           "platform": "cpu (fallback)", "e2e": "skipped",
           "witnessed_tpu": {"e2e_tps": 13273.8},
           "multichip_choice": "one_mesh_tile"}
    d = diff_bench(old, new)
    m = d["metrics"]
    assert m["value"]["old_src"] == "cpu"
    assert m["e2e_tps"]["old_src"] == "fallback"
    assert m["value"]["new_src"] == "witnessed"
    assert m["e2e_leader_knee_tps"]["new_src"] == "cpu"
    assert d["multichip"] == {"old": "one_mesh_tile",
                              "new": "rr_tiles", "changed": True}
    txt = render_text(d, [], 0.05)
    assert "[wit]" in txt and "[fb]" in txt and "[cpu]" in txt
    assert "multichip layout" in txt and "CHANGED" in txt


def test_report_carries_provenance_panel(sweep):
    html = open(sweep["report"]).read()
    assert "renderProv" in html          # the panel renderer shipped
    data = json.loads(html.split("window.FDGUI_DATA=", 1)[1]
                      .split("</script>", 1)[0].replace("<\\/", "</"))
    w = data["witness"]
    assert w["run_id"] == "full"
    assert len(w["git"]["sha"]) >= 7
    assert w["device"]["platform"] == "tpu"
    badges = {s["stage"]: s["witnessed"] for s in w["stages"]}
    assert badges["kernel_vps"] is True
    assert badges["leader_knee"] is False
    # the artifact itself is the trend page's last round
    assert data["bench"][-1]["file"].endswith("_witnessed.json")


def test_load_multichip_from_tail_and_fields(tmp_path):
    """The dryrun layout stanza is machine-readable from BOTH artifact
    shapes: a driver MULTICHIP json (stanza in the `tail` string) and
    a BENCH json persisting it as fields."""
    from firedancer_tpu.prof.bench_diff import load_multichip
    stanza = {"mesh": {"devices": 8}, "choose_by": "measurement"}
    mc = tmp_path / "MULTICHIP_r05.json"
    mc.write_text(json.dumps({
        "rc": 0, "tail": "noise\n"
        + json.dumps({"multichip_layout": stanza}) + "\n"}))
    assert load_multichip(str(mc)) == stanza
    be = tmp_path / "BENCH_r05.json"
    be.write_text(json.dumps({"multichip_layout": stanza}))
    assert load_multichip(str(be)) == stanza
    empty = tmp_path / "none.json"
    empty.write_text("{}")
    assert load_multichip(str(empty)) is None
    # the factored stanza bench.py persists matches what
    # dryrun_multichip prints (same function, pure data)
    sys.path.insert(0, REPO)
    from __graft_entry__ import multichip_layout_stanza
    s = multichip_layout_stanza(8)
    assert s["mesh"]["devices"] == 8
    assert s["rr_sharded_tiles"]["tile_cnt"] == 8


def test_latest_witnessed_numeric_discovery(tmp_path):
    """Glob-latest discovery orders rounds NUMERICALLY (r10 > r9) and
    honors the platform filter — the bench.py fallback contract that
    replaced the hardcoded filename."""
    for rnd, plat in ((4, "tpu"), (9, "tpu"), (10, "cpu")):
        (tmp_path / f"BENCH_r{rnd:02d}_witnessed.json").write_text(
            json.dumps({"platform": plat, "value": rnd}))
    assert [r for r, _ in witnessed_rounds(str(tmp_path))] == [4, 9, 10]
    path, doc = latest_witnessed(str(tmp_path))
    assert doc["value"] == 9                 # r10 is cpu: filtered
    path, doc = latest_witnessed(str(tmp_path), require_platform=None)
    assert doc["value"] == 10
    # corrupt latest -> falls back to the next readable round
    (tmp_path / "BENCH_r11_witnessed.json").write_text("{broken")
    assert latest_witnessed(str(tmp_path),
                            require_platform=None)[1]["value"] == 10
    assert next_round(str(tmp_path)) == 11


def test_dry_run_validates_without_running(tmp_path):
    r = subprocess.run([PY, "-m", "firedancer_tpu.witness", "run",
                        "--dry-run", "--cpu-smoke"],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["dry_run"] is True
    assert [s["name"] for s in doc["plan"]] == list(STAGES)
    assert doc["genesis"] and doc["header"]["git"]["sha"]
    # a broken [witness] config fails the dry run with the did-you-mean
    bad = tmp_path / "bad.toml"
    bad.write_text("[witness]\nstages = [\"kernel_vp\"]\n")
    r = subprocess.run([PY, "-m", "firedancer_tpu.witness", "run",
                        "--dry-run", "--config", str(bad)],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 2
    assert "did you mean 'kernel_vps'" in r.stderr


def test_status_lists_runs(sweep):
    r = subprocess.run([PY, "-m", "firedancer_tpu.witness", "status",
                        "--out-dir", str(sweep["tmp"])],
                       cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0
    assert "full" in r.stdout and "[final]" in r.stdout
    assert "multichip=ok" in r.stdout


# -- the real thing (slow) ---------------------------------------------------

@pytest.mark.slow
def test_cpu_smoke_end_to_end(tmp_path):
    """The acceptance drill: `tools/fdwitness run --cpu-smoke` over the
    cheap real stages (probe + kernel + multichip — the ones that fit
    a test budget; the full sweep is the driver's run), producing a
    chain-verified artifact + merged report from real measurements."""
    art = str(tmp_path / "BENCH_r94_witnessed.json")
    r = subprocess.run(
        [os.path.join(REPO, "tools", "fdwitness"), "run", "--cpu-smoke",
         "--stages", "device_probe,kernel_vps,multichip",
         "--out-dir", str(tmp_path), "--artifact", art],
        cwd=REPO, capture_output=True, text=True, timeout=1500)
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
    doc = json.load(open(art))
    assert doc["platform"] == "cpu" and doc["value"] > 0
    assert doc["witnessed"]["value"]["witnessed"] is False  # cpu smoke
    assert doc["multichip"]["multichip_devices"] == 2
    assert set(doc["multichip"]["layouts"]) == {"one_mesh_tile",
                                                "rr_tiles"}
    assert doc["multichip_choice"] in ("one_mesh_tile", "rr_tiles")
    assert verify_chain(doc["witness"]) == []
    v = subprocess.run([PY, "-m", "firedancer_tpu.witness", "verify",
                        art], cwd=REPO, capture_output=True, text=True)
    assert v.returncode == 0, v.stderr
    assert os.path.exists(os.path.splitext(art)[0] + ".report.html")
