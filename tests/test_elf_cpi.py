"""sBPF ELF loading + CPI tests.

The ELF fixture is built instruction-by-instruction here (no Solana
toolchain in the image) but is a structurally valid sBPF ELF64 — the
loader must parse real section headers, the dynamic symbol table, and
apply all three relocation kinds exactly as it would for a
cargo-build-sbf artifact (ref: src/ballet/sbpf/fd_sbpf_loader.c:390-395,
747; CPI: src/flamenco/vm/syscall/fd_vm_syscall_cpi.c, PDA:
fd_vm_syscall_pda.c)."""
import struct

import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID
from firedancer_tpu.svm.programs import (
    BPF_LOADER_ID, ERR_VM, OK, create_program_address,
    find_program_address,
)
from firedancer_tpu.vm import INPUT_START, asm
from firedancer_tpu.vm import elf


def k(n):
    return bytes([n]) * 32


PAYER, DEST, PROG = k(1), k(3), k(9)
RODATA_MSG = b"hello from elf"


# ---------------------------------------------------------------------------
# minimal-but-valid sBPF ELF builder
# ---------------------------------------------------------------------------

def _build_elf(machine=elf.EM_SBPF):
    """entry: log a .rodata string via sol_log_ (syscall reloc), call a
    defined helper (pc-hash reloc), exit 0."""
    ehdr_sz = 64
    text = asm(f"""
        lddw r1, 0
        mov64 r2, {len(RODATA_MSG)}
        call 0
        call 0
        exit
        mov64 r0, 0
        exit
    """)
    text_off = ehdr_sz
    rodata_off = text_off + len(text)
    # pre-reloc imm holds the file offset; R_BPF_64_RELATIVE adds base
    text = bytearray(text)
    struct.pack_into("<I", text, 4, rodata_off)
    text = bytes(text)

    dynstr = b"\x00sol_log_\x00helper\x00"
    dynstr_off = rodata_off + len(RODATA_MSG)
    dynsym_off = (dynstr_off + len(dynstr) + 7) & ~7
    helper_off = text_off + 6 * 8       # pc 6: after entry's exit (pc 5)
    dynsym = struct.pack("<IBBHQQ", 0, 0, 0, 0, 0, 0)
    dynsym += struct.pack("<IBBHQQ", 1, 0x10, 0, 0, 0, 0)     # sol_log_
    dynsym += struct.pack("<IBBHQQ", 10, 0x12, 0, 1, helper_off, 16)
    rel_off = dynsym_off + len(dynsym)
    # pc layout: lddw occupies pc 0-1, mov64 pc 2, calls pc 3 and 4
    rel = struct.pack("<QQ", text_off + 0, elf.R_BPF_64_RELATIVE)
    rel += struct.pack("<QQ", text_off + 3 * 8,
                       (1 << 32) | elf.R_BPF_64_32)
    rel += struct.pack("<QQ", text_off + 4 * 8,
                       (2 << 32) | elf.R_BPF_64_32)
    shstr = (b"\x00.text\x00.rodata\x00.dynstr\x00.dynsym\x00"
             b".rel.dyn\x00.shstrtab\x00")
    shstr_off = rel_off + len(rel)
    shoff = (shstr_off + len(shstr) + 7) & ~7

    def shdr(name, typ, addr, off, size, link=0, entsize=0):
        return struct.pack("<IIQQQQIIQQ", name, typ, 0, addr, off,
                           size, link, 0, 8, entsize)

    shdrs = shdr(0, 0, 0, 0, 0)                               # NULL
    shdrs += shdr(1, 1, text_off, text_off, len(text))        # .text
    shdrs += shdr(7, 1, rodata_off, rodata_off, len(RODATA_MSG))
    shdrs += shdr(15, 3, dynstr_off, dynstr_off, len(dynstr))
    shdrs += shdr(23, 11, dynsym_off, dynsym_off, len(dynsym),
                  link=3, entsize=24)
    shdrs += shdr(31, 9, rel_off, rel_off, len(rel), link=4,
                  entsize=16)
    shdrs += shdr(40, 3, shstr_off, shstr_off, len(shstr))

    ehdr = (b"\x7fELF" + bytes([2, 1, 1]) + bytes(9)
            + struct.pack("<HHIQQQIHHHHHH", 3, machine, 1,
                          text_off,              # e_entry
                          0, shoff, 0, ehdr_sz, 0, 0, 64, 7, 6))
    img = bytearray(ehdr)
    img += text
    img += RODATA_MSG
    img += dynstr
    img += bytes(dynsym_off - dynstr_off - len(dynstr))
    img += dynsym
    img += rel
    img += shstr
    img += bytes(shoff - shstr_off - len(shstr))
    img += shdrs
    return bytes(img)


def test_loader_parses_and_relocates():
    prog = elf.load(_build_elf())
    assert prog.entry_pc == 0
    assert prog.syscalls_used == {"sol_log_"}
    # helper registered under its pc hash
    assert prog.calls[elf.pc_hash(6)] == 6
    # lddw imm pair patched to rodata vaddr
    lo = struct.unpack_from("<I", prog.text, 4)[0]
    hi = struct.unpack_from("<I", prog.text, 12)[0]
    assert (lo | (hi << 32)) == elf.MM_PROGRAM_START + 64 + len(prog.text)
    # call imms carry murmur hashes
    sysc = struct.unpack_from("<I", prog.text, 3 * 8 + 4)[0]
    assert sysc == elf.murmur3_32(b"sol_log_")


def test_loader_rejects_bad_machine():
    img = bytearray(_build_elf())
    struct.pack_into("<H", img, 18, 62)          # EM_X86_64
    with pytest.raises(elf.ElfError):
        elf.load(bytes(img))


def test_loader_rejects_entry_outside_text():
    img = bytearray(_build_elf())
    struct.pack_into("<Q", img, 24, 8)           # e_entry into ehdr
    with pytest.raises(elf.ElfError):
        elf.load(bytes(img))


@pytest.fixture
def env():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, PAYER, Account(lamports=1_000_000))
    funk.txn_prepare(None, "blk")
    return funk, db, TxnExecutor(db, enforce_rent=False)


def _txn(instr_accounts, data, extra=()):
    msg = build_message([PAYER], list(extra) + [PROG], b"\x11" * 32,
                        [(1 + len(extra), bytes(instr_accounts), data)],
                        n_ro_unsigned=1)
    return build_txn([bytes(64)], msg)


def test_elf_program_executes_in_txn(env):
    funk, db, ex = env
    funk.rec_write("blk", PROG, Account(
        lamports=1, data=_build_elf(), owner=BPF_LOADER_ID,
        executable=True))
    r = ex.execute("blk", _txn([], b""))
    assert r.status == OK
    assert any(RODATA_MSG.decode() in ln for ln in r.logs)


# ---------------------------------------------------------------------------
# CPI: program-derived-address signing + invoke
# ---------------------------------------------------------------------------

PDA, BUMP = find_program_address([b"vault"], PROG)
SEEDS_BLOB = (bytes([1])                      # one signer
              + bytes([2])                    # two seeds
              + bytes([5]) + b"vault"
              + bytes([1, BUMP]))


def _cpi_blob(amount, pda_signer=True, seeds=SEEDS_BLOB):
    """Instruction data handed to the outer program: CPI instruction
    (system transfer PDA -> DEST) followed by the signer seeds."""
    ix = (SYSTEM_PROGRAM_ID + struct.pack("<H", 2)
          + PDA + bytes([1 if pda_signer else 0, 1])
          + DEST + bytes([0, 1])
          + struct.pack("<H", 12)
          + struct.pack("<IQ", 2, amount))
    return ix, seeds


def _cpi_prog(n_outer_accounts, cpi_len):
    """Outer sBPF program: point r1 at the CPI instruction (inside its
    own instruction data in the input region), r2 at the seeds, invoke."""
    data_va = INPUT_START + 2 + 42 * n_outer_accounts + 2
    return asm(f"""
        lddw r1, {data_va}
        lddw r2, {data_va + cpi_len}
        call {hex(elf.murmur3_32(b"sol_invoke_signed_c"))}
        mov64 r0, 0
        exit
    """)


def _setup_cpi(funk, amount=500, pda_signer=True, seeds=SEEDS_BLOB,
               pda_lamports=1000):
    ix, sd = _cpi_blob(amount, pda_signer, seeds)
    prog = _cpi_prog(2, len(ix))
    funk.rec_write("blk", PROG, Account(
        lamports=1, data=prog, owner=BPF_LOADER_ID, executable=True))
    funk.rec_write("blk", PDA, Account(lamports=pda_lamports))
    # outer instruction accounts: [PDA, DEST] (txn idx 1, 2)
    return _txn([1, 2], ix + sd, extra=[PDA, DEST])


def test_cpi_transfer_with_pda_signer(env):
    funk, db, ex = env
    txn = _setup_cpi(funk)
    before = db.lamports("blk", PAYER) + db.lamports("blk", PDA)
    r = ex.execute("blk", txn)
    assert r.status == OK, r.logs
    assert db.lamports("blk", PDA) == 500
    assert db.lamports("blk", DEST) == 500
    # lamports conservation across the CPI (fee aside, nothing minted)
    after = (db.lamports("blk", PAYER) + db.lamports("blk", PDA)
             + db.lamports("blk", DEST))
    assert after == before - 5000                # exactly the fee


def test_cpi_rejects_wrong_seeds(env):
    funk, db, ex = env
    bad = (bytes([1]) + bytes([1]) + bytes([4]) + b"evil")
    txn = _setup_cpi(funk, seeds=bad)
    r = ex.execute("blk", txn)
    assert r.status == ERR_VM
    assert db.lamports("blk", PDA) == 1000       # untouched


def test_cpi_rejects_signer_escalation_without_seeds(env):
    funk, db, ex = env
    txn = _setup_cpi(funk, seeds=bytes([0]))     # no signers
    r = ex.execute("blk", txn)
    assert r.status == ERR_VM
    assert db.lamports("blk", PDA) == 1000


def test_cpi_insufficient_funds_aborts_txn(env):
    funk, db, ex = env
    txn = _setup_cpi(funk, amount=10_000)        # > pda balance
    r = ex.execute("blk", txn)
    assert r.status == ERR_VM
    assert db.lamports("blk", PDA) == 1000
    assert db.lamports("blk", DEST) == 0


def test_pda_is_off_curve_and_deterministic():
    a1 = create_program_address([b"vault", bytes([BUMP])], PROG)
    assert a1 == PDA
    from firedancer_tpu.utils.ed25519_ref import pt_decompress
    assert pt_decompress(PDA) is None


# ---------------------------------------------------------------------------
# real toolchain artifact (read-only from the reference fixture tree)
# ---------------------------------------------------------------------------

REAL_SO = ("/root/reference/src/ballet/sbpf/fixtures/"
           "hello_solana_program.so")


@pytest.mark.skipif(not __import__("os").path.exists(REAL_SO),
                    reason="reference fixture tree not present")
def test_real_cargo_build_sbf_program_executes(env):
    """A REAL compiled Solana program (cargo-build-sbf artifact, read
    from the reference's fixture tree — binary test data, not code)
    loads, relocates, and runs to completion inside a transaction,
    deserializing the real Solana input ABI."""
    funk, db, ex = env
    data = open(REAL_SO, "rb").read()
    funk.rec_write("blk", PROG, Account(
        lamports=1, data=data, owner=BPF_LOADER_ID, executable=True))
    r = ex.execute("blk", _txn([], b""))
    assert r.status == OK, r.logs
    assert any("Hello, Solana!" in ln for ln in r.logs)
    # the program base58-prints its program id from the input region
    assert any("Program ID" in ln for ln in r.logs)


REAL_CLOCK_SO = ("/root/reference/src/ballet/sbpf/fixtures/"
                 "clock_sysvar_program.so")


@pytest.mark.skipif(not __import__("os").path.exists(REAL_CLOCK_SO),
                    reason="reference fixture tree not present")
def test_real_clock_sysvar_program_reads_injected_clock(env):
    """The real clock-sysvar fixture program executes against OUR
    sysvar injection (sol_get_clock_sysvar) and returns clean."""
    funk, db, ex = env
    ex.slot, ex.epoch = 12345, 77
    funk.rec_write("blk", PROG, Account(
        lamports=1, data=open(REAL_CLOCK_SO, "rb").read(),
        owner=BPF_LOADER_ID, executable=True))
    r = ex.execute("blk", _txn([], b""))
    assert r.status == OK, r.logs


def test_cpi_return_data_propagates(env):
    """A CPI callee's sol_set_return_data is visible to the caller and
    surfaces in the txn result (the CPI-result ABI)."""
    funk, db, ex = env
    PROG_B = k(0x0B)
    # B: set_return_data(input_data_ptr, 6); exit 0
    data_va_b = INPUT_START + 4          # compact layout, 0 accounts
    prog_b = asm(f"""
        lddw r1, {data_va_b}
        mov64 r2, 6
        call {hex(elf.murmur3_32(b"sol_set_return_data"))}
        mov64 r0, 0
        exit
    """)
    funk.rec_write("blk", PROG_B, Account(
        lamports=1, data=prog_b, owner=BPF_LOADER_ID, executable=True))
    # A: CPI to B with data "from-B", no accounts, no signers
    ix = PROG_B + struct.pack("<H", 0) + struct.pack("<H", 6) + b"from-B"
    seeds = bytes([0])
    data_va_a = INPUT_START + 2 + 0 * 42 + 2
    prog_a = asm(f"""
        lddw r1, {data_va_a}
        lddw r2, {data_va_a + len(ix)}
        call {hex(elf.murmur3_32(b"sol_invoke_signed_c"))}
        mov64 r0, 0
        exit
    """)
    funk.rec_write("blk", PROG, Account(
        lamports=1, data=prog_a, owner=BPF_LOADER_ID, executable=True))
    r = ex.execute("blk", _txn([], ix + seeds))
    assert r.status == OK, r.logs
    assert r.return_data == b"from-B"
