"""Checkpoint frame + funk snapshot tests (ref: src/util/checkpt/
fd_checkpt.h — bit-identical restore, integrity; src/discof/restore/
fd_snapin_tile.c — stream -> account DB)."""
import io

import numpy as np
import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm import Account, SystemTxn, execute_block
from firedancer_tpu.utils.checkpt import (
    CheckptError, CheckptReader, CheckptWriter, funk_checkpt, funk_restore,
)


def test_frames_roundtrip_and_integrity():
    rng = np.random.default_rng(3)
    frames = [rng.bytes(int(rng.integers(0, 5000))) for _ in range(20)]
    frames.append(b"\x00" * 100_000)          # compressible
    buf = io.BytesIO()
    w = CheckptWriter(buf, compress=True)
    for f in frames:
        w.frame(f)
    w.fini()
    raw = buf.getvalue()
    got = list(CheckptReader(io.BytesIO(raw)).frames())
    assert got == frames
    # compression engaged for the compressible frame
    assert len(raw) < sum(len(f) for f in frames)

    # single flipped byte in any frame body is caught by the trailer
    bad = bytearray(raw)
    bad[len(bad) // 2] ^= 1
    with pytest.raises(CheckptError):
        list(CheckptReader(io.BytesIO(bytes(bad))).frames())


def test_frames_raw_mode():
    buf = io.BytesIO()
    w = CheckptWriter(buf, compress=False)
    w.frame(b"hello")
    w.fini()
    assert list(CheckptReader(io.BytesIO(buf.getvalue())).frames()) \
        == [b"hello"]


def test_funk_checkpt_bit_identical():
    rng = np.random.default_rng(5)
    funk = Funk()
    for i in range(50):
        k = rng.bytes(32)
        if i % 3 == 0:
            funk.rec_write(None, k, int(rng.integers(0, 1 << 60)))
        elif i % 3 == 1:
            funk.rec_write(None, k, Account(
                lamports=int(rng.integers(0, 1 << 50)),
                data=rng.bytes(int(rng.integers(0, 200))),
                owner=rng.bytes(32),
                executable=bool(i % 2), rent_epoch=i))
        else:
            funk.rec_write(None, k, rng.bytes(40))

    buf = io.BytesIO()
    funk_checkpt(funk, buf)
    buf.seek(0)
    restored = funk_restore(Funk, buf)
    assert restored.root_items() == funk.root_items()

    # determinism: same state -> byte-identical checkpoint
    buf2 = io.BytesIO()
    funk_checkpt(funk, buf2)
    assert buf2.getvalue() == buf.getvalue()


def test_checkpt_resume_execution():
    """Snapshot -> restore -> continue executing blocks: the restored
    node's state matches the uninterrupted node's (the snapshot-load
    cold-start path, ref fd_snapin_tile.c)."""
    k1, k2 = b"\x01" * 32, b"\x02" * 32
    funk = Funk()
    funk.rec_write(None, k1, Account(lamports=10_000))
    execute_block(funk, None, "b1", [SystemTxn(k1, k2, 1000, 10)])
    funk.txn_publish("b1")

    buf = io.BytesIO()
    funk_checkpt(funk, buf)
    buf.seek(0)
    cold = funk_restore(Funk, buf)

    for f in (funk, cold):
        execute_block(f, None, "b2", [SystemTxn(k2, k1, 500, 0)])
        f.txn_publish("b2")
    assert cold.root_items() == funk.root_items()


def test_zlib_bomb_is_bounded():
    # ADVICE r3: a hostile frame header must not drive a huge
    # decompression before the size check — inflate is capped at raw_sz
    import struct
    import zlib

    from firedancer_tpu.utils.checkpt import MAGIC, STYLE_ZLIB
    bomb = zlib.compress(b"\x00" * 50_000_000, 9)     # ~48 KiB encoded
    frame = struct.pack("<BQQ", STYLE_ZLIB, 10, len(bomb)) + bomb
    with pytest.raises(CheckptError):
        list(CheckptReader(io.BytesIO(MAGIC + frame)).frames())


def test_zlib_trailing_garbage_rejected():
    import struct
    import zlib

    from firedancer_tpu.utils.checkpt import MAGIC, STYLE_ZLIB
    body = zlib.compress(b"hello") + b"JUNK"
    frame = struct.pack("<BQQ", STYLE_ZLIB, 5, len(body)) + body
    with pytest.raises(CheckptError):
        list(CheckptReader(io.BytesIO(MAGIC + frame)).frames())


# -- v2 snapshots over both funk backends (r17) --------------------------
#
# The follower cold-start path snapshots a leader-side store and
# restores it into whichever backend the topology carved — so every
# drill below runs against the process funk AND the shm store facade
# (plus the cross-backend restore the catch-up bench actually does).

from firedancer_tpu.tiles.snapshot import state_fingerprint
from firedancer_tpu.utils.checkpt import (
    RESTORE_MARKER_KEY, snapshot_checkpt, snapshot_restore_into,
    snapshot_write_atomic,
)

BACKENDS = ["process", "shm"]


def _mk_funk(backend):
    if backend == "process":
        return Funk()
    from firedancer_tpu.funk.shmfunk import ShmFunk
    return ShmFunk(rec_max=1024, txn_max=16, heap_sz=1 << 20)


def _fini_funk(funk):
    close = getattr(funk, "close", None)
    if close is not None:
        close(unlink=True)


def _populate(funk, n=20, seed=11):
    rng = np.random.default_rng(seed)
    for i in range(n):
        k = rng.bytes(32)
        if i % 2:
            funk.rec_write(None, k, int(rng.integers(0, 1 << 60)))
        else:
            funk.rec_write(None, k, Account(
                lamports=int(rng.integers(1, 1 << 50)),
                data=rng.bytes(int(rng.integers(0, 64))),
                owner=rng.bytes(32), rent_epoch=i))


def _snap_bytes(funk, slot=7, bank_hash=None, compress=True):
    bank_hash = bank_hash or bytes(range(32))
    buf = io.BytesIO()
    snapshot_checkpt(funk, buf, slot=slot, bank_hash=bank_hash,
                     compress=compress)
    return buf.getvalue(), bank_hash


@pytest.mark.parametrize("src", BACKENDS)
@pytest.mark.parametrize("dst", BACKENDS)
def test_snapshot_roundtrip_across_backends(src, dst):
    """slot + bank hash + every record survive src->dst restore, and
    the restored store fingerprints identically to the source (the
    snapin handoff invariant)."""
    a, b = _mk_funk(src), _mk_funk(dst)
    try:
        _populate(a)
        raw, bank_hash = _snap_bytes(a)
        slot, got_hash, cnt = snapshot_restore_into(b, io.BytesIO(raw))
        assert (slot, got_hash, cnt) == (7, bank_hash, 20)
        assert b.root_items() == a.root_items()
        assert state_fingerprint(b) == state_fingerprint(a)
    finally:
        _fini_funk(a)
        _fini_funk(b)


@pytest.mark.parametrize("dst", BACKENDS)
def test_snapshot_truncation_installs_nothing(dst):
    """Mid-stream truncation at EVERY prefix length must refuse the
    snapshot with the target left untouched — never partial state."""
    a, b = _mk_funk("process"), _mk_funk(dst)
    try:
        _populate(a, n=6)
        raw, _ = _snap_bytes(a)
        sentinel = b"\x05" * 32
        b.rec_write(None, sentinel, 123)
        for cut in range(0, len(raw) - 1, 97):
            with pytest.raises(CheckptError):
                snapshot_restore_into(b, io.BytesIO(raw[:cut]))
            assert b.root_items() == {sentinel: 123}
    finally:
        _fini_funk(a)
        _fini_funk(b)


@pytest.mark.parametrize("dst", BACKENDS)
def test_snapshot_corrupt_frame_installs_nothing(dst):
    a, b = _mk_funk("process"), _mk_funk(dst)
    try:
        _populate(a, n=6)
        raw, _ = _snap_bytes(a)
        bad = bytearray(raw)
        bad[len(bad) * 2 // 3] ^= 0x40
        with pytest.raises(CheckptError):
            snapshot_restore_into(b, io.BytesIO(bytes(bad)))
        assert b.root_items() == {}
    finally:
        _fini_funk(a)
        _fini_funk(b)


@pytest.mark.parametrize("dst", BACKENDS)
def test_snapshot_stale_offer_refused(dst):
    """A snapshot older than the restorer's min_slot is refused loudly
    (stale_snapshot_offer drill) with zero writes."""
    a, b = _mk_funk("process"), _mk_funk(dst)
    try:
        _populate(a, n=4)
        raw, _ = _snap_bytes(a, slot=7)
        with pytest.raises(CheckptError, match="stale"):
            snapshot_restore_into(b, io.BytesIO(raw), min_slot=8)
        assert b.root_items() == {}
        # boundary: slot == min_slot is acceptable
        snapshot_restore_into(b, io.BytesIO(raw), min_slot=7)
        assert len(b.root_items()) == 4
    finally:
        _fini_funk(a)
        _fini_funk(b)


@pytest.mark.parametrize("backend", BACKENDS)
def test_snapshot_atomic_crash_keeps_previous_file(backend, tmp_path):
    """A writer crash mid-stream (the _frame_hook chaos seam) must
    leave the previous snapshot restorable and the torn .tmp
    unverifiable."""
    funk = _mk_funk(backend)
    path = str(tmp_path / "snap.ckpt")
    try:
        _populate(funk, n=4, seed=3)
        snapshot_write_atomic(path, funk, slot=3,
                              bank_hash=bytes(range(32)))
        before = open(path, "rb").read()
        funk.rec_write(None, b"\x07" * 32, 777)

        def boom(i):
            if i >= 2:
                raise RuntimeError("simulated crash mid-snapshot")
        with pytest.raises(RuntimeError):
            snapshot_write_atomic(path, funk, slot=4,
                                  bank_hash=bytes(32), _frame_hook=boom)
        assert open(path, "rb").read() == before
        restored = _mk_funk("process")
        try:
            slot, _, _ = snapshot_restore_into(
                restored, io.BytesIO(open(path, "rb").read()))
            assert slot == 3
        finally:
            _fini_funk(restored)
        import os as _os
        if _os.path.exists(path + ".tmp"):
            bad = _mk_funk("process")
            try:
                with pytest.raises(CheckptError):
                    snapshot_restore_into(
                        bad, io.BytesIO(open(path + ".tmp", "rb").read()))
            finally:
                _fini_funk(bad)
    finally:
        _fini_funk(funk)


@pytest.mark.parametrize("backend", BACKENDS)
def test_snapshot_excludes_restore_marker(backend):
    """The restore marker is local runtime state: a snapshot taken
    from a restored store must not re-export it (else a second-hop
    restore would release a follower's gate with a stale boundary)."""
    funk = _mk_funk(backend)
    try:
        _populate(funk, n=3, seed=9)
        funk.rec_write(None, RESTORE_MARKER_KEY, (3, bytes(32)))
        raw, _ = _snap_bytes(funk)
        fresh = _mk_funk("process")
        try:
            _, _, cnt = snapshot_restore_into(fresh, io.BytesIO(raw))
            assert cnt == 3
            assert RESTORE_MARKER_KEY not in fresh.root_items()
        finally:
            _fini_funk(fresh)
    finally:
        _fini_funk(funk)


def test_legacy_checkpt_restores_as_slot_zero():
    """app/genesis.py output (a legacy meta-less funk_checkpt) must
    bootstrap a follower: restore accepts it as slot 0 with a zero
    bank hash — the cfg/follower-demo.toml cold-start path."""
    funk = Funk()
    _populate(funk, n=5, seed=2)
    buf = io.BytesIO()
    funk_checkpt(funk, buf)
    cold = _mk_funk("shm")
    try:
        slot, bank_hash, cnt = snapshot_restore_into(
            cold, io.BytesIO(buf.getvalue()))
        assert (slot, bank_hash, cnt) == (0, bytes(32), 5)
        assert cold.root_items() == funk.root_items()
    finally:
        _fini_funk(cold)


def test_funk_restore_refuses_short_record_keys():
    """A checkpoint frame carrying a non-32-byte record key must abort
    the restore, not install a key no other process could derive (the
    native store reads exactly 32 key bytes; a short buffer hashes
    per-process trailing garbage)."""
    import struct
    from firedancer_tpu.utils.checkpt import _enc_val
    buf = io.BytesIO()
    w = CheckptWriter(buf, compress=False)
    w.frame(struct.pack("<Q", 1))
    k = b"root8byt"                           # 8-byte key
    ev = _enc_val(7)
    w.frame(struct.pack("<II", len(k), len(ev)) + k + ev)
    w.fini()
    buf.seek(0)
    with pytest.raises(CheckptError, match="8-byte record key"):
        funk_restore(Funk, buf)
