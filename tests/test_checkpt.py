"""Checkpoint frame + funk snapshot tests (ref: src/util/checkpt/
fd_checkpt.h — bit-identical restore, integrity; src/discof/restore/
fd_snapin_tile.c — stream -> account DB)."""
import io

import numpy as np
import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm import Account, SystemTxn, execute_block
from firedancer_tpu.utils.checkpt import (
    CheckptError, CheckptReader, CheckptWriter, funk_checkpt, funk_restore,
)


def test_frames_roundtrip_and_integrity():
    rng = np.random.default_rng(3)
    frames = [rng.bytes(int(rng.integers(0, 5000))) for _ in range(20)]
    frames.append(b"\x00" * 100_000)          # compressible
    buf = io.BytesIO()
    w = CheckptWriter(buf, compress=True)
    for f in frames:
        w.frame(f)
    w.fini()
    raw = buf.getvalue()
    got = list(CheckptReader(io.BytesIO(raw)).frames())
    assert got == frames
    # compression engaged for the compressible frame
    assert len(raw) < sum(len(f) for f in frames)

    # single flipped byte in any frame body is caught by the trailer
    bad = bytearray(raw)
    bad[len(bad) // 2] ^= 1
    with pytest.raises(CheckptError):
        list(CheckptReader(io.BytesIO(bytes(bad))).frames())


def test_frames_raw_mode():
    buf = io.BytesIO()
    w = CheckptWriter(buf, compress=False)
    w.frame(b"hello")
    w.fini()
    assert list(CheckptReader(io.BytesIO(buf.getvalue())).frames()) \
        == [b"hello"]


def test_funk_checkpt_bit_identical():
    rng = np.random.default_rng(5)
    funk = Funk()
    for i in range(50):
        k = rng.bytes(32)
        if i % 3 == 0:
            funk.rec_write(None, k, int(rng.integers(0, 1 << 60)))
        elif i % 3 == 1:
            funk.rec_write(None, k, Account(
                lamports=int(rng.integers(0, 1 << 50)),
                data=rng.bytes(int(rng.integers(0, 200))),
                owner=rng.bytes(32),
                executable=bool(i % 2), rent_epoch=i))
        else:
            funk.rec_write(None, k, rng.bytes(40))

    buf = io.BytesIO()
    funk_checkpt(funk, buf)
    buf.seek(0)
    restored = funk_restore(Funk, buf)
    assert restored.root_items() == funk.root_items()

    # determinism: same state -> byte-identical checkpoint
    buf2 = io.BytesIO()
    funk_checkpt(funk, buf2)
    assert buf2.getvalue() == buf.getvalue()


def test_checkpt_resume_execution():
    """Snapshot -> restore -> continue executing blocks: the restored
    node's state matches the uninterrupted node's (the snapshot-load
    cold-start path, ref fd_snapin_tile.c)."""
    k1, k2 = b"\x01" * 32, b"\x02" * 32
    funk = Funk()
    funk.rec_write(None, k1, Account(lamports=10_000))
    execute_block(funk, None, "b1", [SystemTxn(k1, k2, 1000, 10)])
    funk.txn_publish("b1")

    buf = io.BytesIO()
    funk_checkpt(funk, buf)
    buf.seek(0)
    cold = funk_restore(Funk, buf)

    for f in (funk, cold):
        execute_block(f, None, "b2", [SystemTxn(k2, k1, 500, 0)])
        f.txn_publish("b2")
    assert cold.root_items() == funk.root_items()


def test_zlib_bomb_is_bounded():
    # ADVICE r3: a hostile frame header must not drive a huge
    # decompression before the size check — inflate is capped at raw_sz
    import struct
    import zlib

    from firedancer_tpu.utils.checkpt import MAGIC, STYLE_ZLIB
    bomb = zlib.compress(b"\x00" * 50_000_000, 9)     # ~48 KiB encoded
    frame = struct.pack("<BQQ", STYLE_ZLIB, 10, len(bomb)) + bomb
    with pytest.raises(CheckptError):
        list(CheckptReader(io.BytesIO(MAGIC + frame)).frames())


def test_zlib_trailing_garbage_rejected():
    import struct
    import zlib

    from firedancer_tpu.utils.checkpt import MAGIC, STYLE_ZLIB
    body = zlib.compress(b"hello") + b"JUNK"
    frame = struct.pack("<BQQ", STYLE_ZLIB, 5, len(body)) + body
    with pytest.raises(CheckptError):
        list(CheckptReader(io.BytesIO(MAGIC + frame)).frames())
