"""PoH chain + bmtree merkle kernel tests vs host oracles
(ref test model: src/ballet/poh/, src/ballet/bmtree/test_bmtree.c —
known-topology trees checked node by node)."""
import hashlib

import jax.numpy as jnp
import numpy as np
import pytest

from firedancer_tpu.ops.bmtree import (bmtree_root, host_bmtree_root,
                                       LEAF_PREFIX_SHREDS,
                                       NODE_PREFIX_SHREDS)
from firedancer_tpu.ops.poh import (PohChain, poh_verify_entries,
                                    host_poh_append)


def test_host_poh_append_is_repeated_sha256():
    s = hashlib.sha256(b"seed").digest()
    out = host_poh_append(s, 3)
    want = s
    for _ in range(3):
        want = hashlib.sha256(want).digest()
    assert out == want


def test_poh_verify_entries_batch():
    chain = PohChain(hashlib.sha256(b"genesis").digest())
    chain.tick(7)
    chain.record(hashlib.sha256(b"txn merkle 1").digest(), 5)
    chain.tick(12)
    chain.record(hashlib.sha256(b"txn merkle 2").digest(), 1)
    chain.tick(3)

    prev, num, mix, has, exp = chain.entry_arrays(max_hashes=16)
    ok = np.asarray(poh_verify_entries(
        jnp.asarray(prev), jnp.asarray(num), jnp.asarray(mix),
        jnp.asarray(has), jnp.asarray(exp), max_hashes=16))
    assert ok.all()

    # corrupt one expected hash -> only that entry fails
    exp2 = exp.copy()
    exp2[2, 0] ^= 1
    ok = np.asarray(poh_verify_entries(
        jnp.asarray(prev), jnp.asarray(num), jnp.asarray(mix),
        jnp.asarray(has), jnp.asarray(exp2), max_hashes=16))
    assert list(ok) == [True, True, False, True, True]

    # wrong num_hashes -> fails
    num2 = num.copy()
    num2[1] += 1
    ok = np.asarray(poh_verify_entries(
        jnp.asarray(prev), jnp.asarray(num2), jnp.asarray(mix),
        jnp.asarray(has), jnp.asarray(exp), max_hashes=16))
    assert not ok[1] and ok[0]


@pytest.mark.parametrize("n_leaves", [1, 2, 3, 4, 5, 7, 8, 11, 16])
def test_bmtree_root_matches_host(n_leaves):
    rng = np.random.default_rng(n_leaves)
    blobs = [rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
             for _ in range(n_leaves)]
    want = host_bmtree_root(blobs)

    max_leaves = 16
    leaves = np.zeros((max_leaves, 32), np.uint8)
    for i, b in enumerate(blobs):
        leaves[i] = np.frombuffer(b, np.uint8)
    got = np.asarray(bmtree_root(jnp.asarray(leaves),
                                 jnp.asarray(n_leaves, jnp.int32),
                                 max_leaves))
    assert bytes(got) == want


def test_bmtree_batched_and_shred_prefixes():
    rng = np.random.default_rng(99)
    batch, max_leaves = 8, 8
    leaves = rng.integers(0, 256, (batch, max_leaves, 32), dtype=np.uint8)
    cnts = np.array([1, 2, 3, 4, 5, 6, 7, 8], np.int32)
    got = np.asarray(bmtree_root(
        jnp.asarray(leaves), jnp.asarray(cnts), max_leaves,
        leaf_prefix=LEAF_PREFIX_SHREDS, node_prefix=NODE_PREFIX_SHREDS))
    for b in range(batch):
        blobs = [leaves[b, i].tobytes() for i in range(cnts[b])]
        want = host_bmtree_root(blobs, LEAF_PREFIX_SHREDS,
                                NODE_PREFIX_SHREDS)
        assert bytes(got[b]) == want, f"batch lane {b} (cnt {cnts[b]})"
