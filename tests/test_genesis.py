"""Genesis builder tests: the boot state feeds a non-empty leader
schedule, restores bit-identically through the checkpoint path, and
its stake accounts drive consensus weights (ref: src/discof/genesi/,
fd_genesis create path)."""
import io

from firedancer_tpu.app.genesis import build_genesis
from firedancer_tpu.flamenco.leaders import EpochLeaders
from firedancer_tpu.flamenco.stakes import node_stakes, total_stake
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm import AccDb, TxnExecutor
from firedancer_tpu.utils.checkpt import funk_checkpt, funk_restore


def test_oversized_user_pool_refused():
    import pytest
    with pytest.raises(ValueError, match="capped"):
        build_genesis(n_user_accounts=100)


def test_genesis_drives_leader_schedule():
    funk, validators = build_genesis(n_validators=3, stake=500)
    ns = node_stakes(funk, None, 1)
    assert len(ns) == 3
    assert all(s == 500 for s in ns.values())
    assert total_stake(funk, None, 1) == 1500
    # epoch 0: delegations activate strictly AFTER epoch 0
    assert total_stake(funk, None, 0) == 0
    sched = EpochLeaders(1, b"\x01" * 32, ns, 64)
    counts = {n: len(sched.leader_slots(n)) for n in ns}
    assert sum(counts.values()) == 64
    assert all(c > 0 for c in counts.values())   # equal stakes rotate


def test_genesis_restores_and_executes():
    import struct

    from firedancer_tpu.protocol.txn import build_message, build_txn
    from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID
    funk, validators = build_genesis(n_validators=2)
    buf = io.BytesIO()
    funk_checkpt(funk, buf)
    buf.seek(0)
    funk2 = funk_restore(Funk, buf)
    assert funk2.root_items().keys() == funk.root_items().keys()
    # a validator identity can pay for and execute a transfer
    ident = validators[0][0]
    funk2.txn_prepare(None, "blk")
    db = AccDb(funk2)
    ex = TxnExecutor(db)
    dest = b"\x77" * 32
    msg = build_message([ident], [dest, SYSTEM_PROGRAM_ID],
                        b"\x11" * 32,
                        [(2, bytes([0, 1]),
                          struct.pack("<IQ", 2, 1 << 20))],
                        n_ro_unsigned=1)
    r = ex.execute("blk", build_txn([bytes(64)], msg))
    assert r.status == "ok"
    assert db.lamports("blk", dest) == 1 << 20


def test_genesis_cli(tmp_path, capsys):
    from firedancer_tpu.app.genesis import main
    out = str(tmp_path / "g.checkpt")
    assert main([out, "--validators", "2", "--stake", "99"]) == 0
    text = capsys.readouterr().out
    assert "2 validators" in text
    with open(out, "rb") as f:
        funk = funk_restore(Funk, f)
    assert total_stake(funk, None, 1) == 198
