"""fdprof: whole-topology continuous profiler (firedancer_tpu/prof/).

Covers the ISSUE 6 test checklist: sampler on/off overhead bound,
folded-stack shm ABI round-trip, post-mortem export after tile death,
merged Perfetto bundle schema (single clock domain, no colliding
thread/span ids), the SLO-triggered device-capture drill under chaos,
and the fdbench diff gate's pass + regression exit paths.
"""
import json
import os
import threading
import time

import pytest

from firedancer_tpu.prof import (
    PROF_DEFAULTS, STATE_NAMES, TILE_PROF_KEYS, ProfRegion, ProfState,
    Sampler, effective_prof, folded_text, merged_chrome, normalize_prof,
    profile_summary, read_folded, read_samples,
)
from firedancer_tpu.runtime import Workspace

pytestmark = pytest.mark.prof


# -- schema ------------------------------------------------------------------

def test_normalize_prof_defaults_and_validation():
    d = normalize_prof(None)
    assert d["enable"] is False and d["hz"] == 97.0
    assert d["tiles"] is None and d["breach_capture"] == []
    on = normalize_prof({"enable": True, "hz": 29, "slots": 64})
    assert on["enable"] is True and on["hz"] == 29.0
    with pytest.raises(ValueError, match="did you mean 'slots'"):
        normalize_prof({"slotz": 64})
    with pytest.raises(ValueError, match="power of two"):
        normalize_prof({"ring": 100})
    with pytest.raises(ValueError, match="hz"):
        normalize_prof({"hz": 0})
    with pytest.raises(ValueError, match="stack_depth"):
        normalize_prof({"stack_depth": 0})
    with pytest.raises(ValueError, match="capture_ms"):
        normalize_prof({"capture_ms": -1})
    with pytest.raises(ValueError, match="list of tile names"):
        normalize_prof({"breach_capture": "verify"})
    # per-tile override: only the TILE_PROF_KEYS subset
    with pytest.raises(ValueError, match="unknown prof key"):
        normalize_prof({"tiles": ["x"]}, per_tile=True)


def test_registry_mirrors_prof_keys():
    """The fdlint key registry's [prof] mirror must track the one
    validator's schema (the same honesty contract [trace]/[slo]
    have)."""
    from firedancer_tpu.lint import registry as reg
    assert set(reg.PROF_SECTION_KEYS) == set(PROF_DEFAULTS)
    assert set(reg.TILE_PROF_KEYS) == set(TILE_PROF_KEYS)
    assert "prof" in reg.COMMON_KEYS


def test_effective_prof_resolution():
    topo = normalize_prof({"enable": True, "hz": 50, "tiles": ["a"]})
    assert effective_prof(topo, "a", {}) == {
        "hz": 50.0, "slots": 256, "ring": 2048, "stack_depth": 16}
    assert effective_prof(topo, "b", {}) is None        # allowlist
    assert effective_prof(topo, "b", {"enable": True})["hz"] == 50.0
    assert effective_prof(topo, "a", {"enable": False}) is None
    off = normalize_prof(None)
    assert effective_prof(off, "a", {}) is None
    assert effective_prof(off, "a", {"enable": True, "hz": 9})["hz"] \
        == 9


# -- shm ABI round-trip ------------------------------------------------------

@pytest.fixture
def wksp():
    w = Workspace(f"/fdtpu_proftest{os.getpid()}", 1 << 21)
    yield w
    w.close()
    Workspace.unlink_name(w.name)


def test_region_abi_roundtrip(wksp):
    """Writer-side records must read back identically through a SECOND
    region instance over the same offsets — the cross-process ABI."""
    r = ProfRegion.create(wksp, slots=64, ring=128)
    r.record("root:main;mod:fn", 1, 1000)
    r.record("root:main;mod:fn", 0, 2000)
    r.record("root:main;other:fn2", 2, 3000)
    r2 = ProfRegion(wksp, r.off, 64, 128)       # the reader's join
    assert r2.samples == 3 and r2.dropped == 0
    folded = r2.folded()
    assert folded["root:main;mod:fn"] == {"wait": 1, "work": 1}
    assert folded["root:main;other:fn2"] == {"housekeep": 1}
    ring = r2.snapshot_ring()
    assert [(ts, st) for ts, _, st in ring] == [(1000, 1), (2000, 0),
                                               (3000, 2)]
    assert r2.stack_at(ring[2][1]) == "root:main;other:fn2"
    # capture doorbell: requester and owner write DIFFERENT words
    r2.request_capture()
    assert r.capture_req == 1 and r.capture_ack == 0
    r.ack_capture(r.capture_req)
    assert r2.capture_ack == 1


def test_region_ring_wraps_and_table_drops(wksp):
    r = ProfRegion.create(wksp, slots=8, ring=8)
    for i in range(40):
        r.record(f"stack-{i}", 1, i)
    assert r.samples == 40
    # only the newest `ring` samples are materialized; cursor counts all
    assert r.ring_cursor == 40 and len(r.snapshot_ring()) == 8
    # 8 slots minus probe-collision losses: overflow counted, not lost
    assert r.dropped > 0
    assert len(r.folded()) <= 8


def test_folded_text_stable_format():
    text = folded_text({"tileB": {"a;b": {"work": 3}},
                        "tileA": {"x;y": {"wait": 1, "work": 2}}})
    assert text.splitlines() == [
        "tileA;wait;x;y 1",
        "tileA;work;x;y 2",
        "tileB;work;a;b 3",
    ]


# -- sampler -----------------------------------------------------------------

def _busy(dur_s: float):
    t0 = time.perf_counter()
    acc = 0
    while time.perf_counter() - t0 < dur_s:
        acc += sum(range(200))
    return acc


def test_sampler_collects_and_attributes(wksp):
    r = ProfRegion.create(wksp, slots=256, ring=512)
    st = ProfState()
    st.state = 1
    st.link = "in_link"
    s = Sampler(r, 400, threading.get_ident(), st, stack_depth=8)
    s.start()
    _busy(0.25)
    st.state = 0
    st.link = None
    _busy(0.1)
    s.stop()
    assert r.samples > 5
    folded = r.folded()
    # work samples carry the active in-link as the flamegraph root
    work = [k for k, v in folded.items() if "work" in v]
    assert any(k.startswith("[in_link];") for k in work)
    assert any("test_prof:_busy" in k for k in folded)
    by_state = set()
    for v in folded.values():
        by_state |= set(v)
    assert "work" in by_state and "wait" in by_state


def test_sampler_overhead_bound(wksp):
    """ISSUE 6 acceptance companion: the sampler must be cheap. The
    e2e bench criterion is <=2% at the bench's 29 Hz; here a noisy CI
    box gets a loose 1.5x bound at a much hotter 250 Hz (best-of-3
    each way to shed scheduler noise), plus proof the sampler actually
    sampled during the measured window."""
    base = min(_timed() for _ in range(3))
    r = ProfRegion.create(wksp, slots=256, ring=256)
    s = Sampler(r, 250, threading.get_ident(), ProfState(),
                stack_depth=12)
    s.start()
    on = min(_timed() for _ in range(3))
    s.stop()
    assert r.samples > 5
    assert on < base * 1.5, (base, on)


def _timed() -> float:
    t0 = time.perf_counter()
    _busy(0.2)
    return time.perf_counter() - t0


# -- topology build plumbing -------------------------------------------------

def _build(prof=None, **topo_kw):
    from firedancer_tpu.disco import Topology
    topo = (Topology(f"pfb{os.getpid()}", wksp_size=1 << 22, prof=prof,
                     **topo_kw)
            .link("a_b", depth=16, mtu=256)
            .tile("a", "synth", outs=["a_b"], count=4)
            .tile("b", "sink", ins=["a_b"]))
    return topo.build()


def test_build_carves_regions_only_when_enabled():
    from firedancer_tpu.disco.stem import Stem
    from firedancer_tpu.disco.topo import TileCtx
    plan = _build()                      # default: unprofiled
    try:
        assert not any("prof_off" in s for s in plan["tiles"].values())
        ctx = TileCtx(plan, "b")
        try:
            assert ctx.prof is None

            class _T:
                def poll_once(self):
                    return 0
            stem = Stem(ctx, _T(), idle_sleep_s=0)
            assert stem._prof_region is None    # whole disabled path
            stem.run(max_iters=4)
            assert stem._sampler is None
        finally:
            ctx.close()
    finally:
        Workspace.unlink_name(plan["wksp"]["name"])
    plan = _build(prof={"enable": True, "slots": 64, "ring": 128,
                        "tiles": ["b"]})
    try:
        assert "prof_off" in plan["tiles"]["b"]
        assert "prof_off" not in plan["tiles"]["a"]     # allowlist
        assert plan["tiles"]["b"]["prof_slots"] == 64
        assert plan["prof"]["enable"] is True
    finally:
        Workspace.unlink_name(plan["wksp"]["name"])
    with pytest.raises(ValueError, match="unknown tile"):
        _build(prof={"enable": True, "tiles": ["ghost"]})
    with pytest.raises(ValueError, match="unknown tile"):
        _build(prof={"enable": True, "breach_capture": ["ghost"]})


def test_config_toml_prof_section_roundtrip(tmp_path):
    from firedancer_tpu.app.config import build_topology, load_config
    p = tmp_path / "t.toml"
    p.write_text("""
[prof]
enable = true
hz = 31
tiles = ["snk"]

[[link]]
name = "a_b"
depth = 16
mtu = 256

[[tile]]
name = "src"
kind = "synth"
outs = ["a_b"]
count = 4

[[tile]]
name = "snk"
kind = "sink"
ins = ["a_b"]

[tile.prof]
hz = 59
""")
    cfg = load_config(str(p))
    topo = build_topology(cfg, name=f"pft{os.getpid()}")
    assert topo.prof["hz"] == 31
    plan = topo.build()
    try:
        assert plan["tiles"]["snk"]["prof_hz"] == 59   # override wins
        assert "prof_off" not in plan["tiles"]["src"]
    finally:
        Workspace.unlink_name(plan["wksp"]["name"])
    bad = tmp_path / "bad.toml"
    bad.write_text("[prof]\nhzz = 10\n")
    with pytest.raises(ValueError, match="did you mean 'hz'"):
        build_topology(load_config(str(bad)))


# -- fdbench (bench-trend observatory) ---------------------------------------

_OLD_BENCH = {
    "value": 400_000.0, "e2e_tps": 13_000.0, "e2e_knee_tps": 11_000.0,
    "e2e_link_budget": {"ingest": {"pub": 100, "lost": 0,
                                   "backpressure": 2,
                                   "consume_p99_us": 40.0}},
    "e2e_profile": {"verify": {"top": [
        {"stack": "a;b", "count": 50}, {"stack": "c;d", "count": 10}]}},
}


def test_fdbench_diff_and_gate_paths(tmp_path):
    from firedancer_tpu.prof.bench_diff import (diff_bench,
                                                gate_regressions, main)
    good = dict(_OLD_BENCH, value=410_000.0, e2e_tps=13_500.0,
                e2e_knee_tps=11_100.0)
    d = diff_bench(_OLD_BENCH, good)
    assert gate_regressions(d) == []
    bad = dict(_OLD_BENCH, value=300_000.0)          # -25% kernel
    regs = gate_regressions(diff_bench(_OLD_BENCH, bad),
                            threshold=0.05)
    assert [r["metric"] for r in regs] == ["value"]
    assert regs[0]["frac"] < -0.2
    # a missing metric is reported but never gated (CPU-fallback round)
    nope = {"value": 420_000.0}
    assert gate_regressions(diff_bench(_OLD_BENCH, nope)) == []
    # ...but the witnessed fallback stands in when present
    wit = {"value": 420_000.0,
           "witnessed_tpu": {"e2e_tps": 9_000.0}}
    regs = gate_regressions(diff_bench(_OLD_BENCH, wit))
    assert [r["metric"] for r in regs] == ["e2e_tps"]
    # CLI exit codes: clean diff -> 0, --gate on a regression -> 1
    po, pn = tmp_path / "old.json", tmp_path / "new.json"
    po.write_text(json.dumps(_OLD_BENCH))
    pn.write_text(json.dumps(bad))
    assert main([str(po), str(pn)]) == 0             # report only
    assert main([str(po), str(pn), "--gate"]) == 1
    assert main([str(po), str(pn), "--gate", "--threshold", "0.9"]) \
        == 0
    pn.write_text(json.dumps(good))
    assert main([str(po), str(pn), "--gate"]) == 0


def test_fdbench_loads_driver_wrapper_and_bare_record(tmp_path):
    """The committed BENCH_r*.json round artifacts are driver wrappers
    whose `tail` string holds the bench record as its last JSON line;
    witnessed files are the bare record — load_bench takes both."""
    from firedancer_tpu.prof.bench_diff import load_bench
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps(_OLD_BENCH | {"metric": "x"}))
    assert load_bench(str(bare))["value"] == 400_000.0
    wrapped = tmp_path / "wrapped.json"
    wrapped.write_text(json.dumps({
        "n": 4, "rc": 0,
        "tail": "noise\n" + json.dumps(
            {"metric": "x", "value": 123.0}) + "\n"}))
    assert load_bench(str(wrapped))["value"] == 123.0
    # unparseable tail falls back to the outer document
    broken = tmp_path / "broken.json"
    broken.write_text(json.dumps({"tail": "{trunc", "value": 7}))
    assert load_bench(str(broken))["value"] == 7


def test_fdbench_profile_topk_deltas():
    from firedancer_tpu.prof.bench_diff import diff_bench
    new = dict(_OLD_BENCH, e2e_profile={"verify": {"top": [
        {"stack": "a;b", "count": 80}, {"stack": "z;z", "count": 5}]}})
    d = diff_bench(_OLD_BENCH, new)
    rows = d["profile"]["verify"]
    assert rows["a;b"] == {"old": 50, "new": 80}
    assert rows["c;d"] == {"old": 10, "new": 0}
    assert rows["z;z"] == {"old": 0, "new": 5}


# -- the live acceptance drill ----------------------------------------------

N_TXNS = 24


@pytest.fixture(scope="module")
def prof_pipeline():
    """verify + sink + metric over an external ingest ring, fully
    profiled and traced, with (a) an SLO objective that MUST breach,
    (b) breach_capture pointed at the verify tile, and (c) seeded
    chaos crashing the sink mid-stream (restart policy) — the
    'SLO-triggered device-capture drill under chaos'."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.runtime import Ring
    from firedancer_tpu.tiles.synth import make_signed_txns
    txns = make_signed_txns(N_TXNS, seed=11)
    topo = (
        Topology(f"pfl{os.getpid()}", wksp_size=1 << 23,
                 trace={"enable": True, "depth": 1024, "sample": 1},
                 prof={"enable": True, "hz": 200, "slots": 256,
                       "ring": 1024, "capture_ms": 150.0,
                       "breach_capture": ["verify"]},
                 slo={"fast_window_s": 0.4, "slow_window_s": 30.0,
                      "burn_fast": 1.0,
                      "target": [{"name": "impossible-latency",
                                  "expr": "verify.work p99 < 1ns"}]})
        .link("in_verify", depth=64, mtu=1280, external=True)
        .link("verify_sink", depth=64, mtu=1280)
        .tcache("vtc", depth=512)
        .tile("verify", "verify", ins=["in_verify"],
              outs=["verify_sink"], batch=32, tcache="vtc")
        .tile("sink", "sink", ins=["verify_sink"],
              supervise={"policy": "restart", "backoff_s": 0.05,
                         "max_restarts": 3, "window_s": 60.0},
              chaos={"seed": 3,
                     "events": [{"action": "crash", "at_rx": 8}]})
        .tile("metric", "metric", port=0)
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=540)
        li = plan["links"]["in_verify"]
        ring = Ring(runner.wksp, li["ring_off"], li["depth"],
                    li["arena_off"], li["mtu"])
        for i, t in enumerate(txns):
            ring.publish(t, sig=i)
        # full recovery under chaos: all txns verified AND the crashed
        # sink respawned (frags published while down are the
        # documented loss — rx <= N)
        t0 = time.time()
        while time.time() - t0 < 120:
            runner.check_failures()
            if runner.metrics("verify")["rx"] >= N_TXNS \
                    and runner.metrics("sink")["sup_restarts"] >= 1 \
                    and runner.metrics("sink")["sup_down"] == 0:
                break
            time.sleep(0.05)
        # the drill: wait for breach -> doorbell -> capture ack
        from firedancer_tpu.prof import region_for
        region = region_for(plan, runner.wksp, "verify")
        t0 = time.time()
        while time.time() - t0 < 150:      # generous: 2-core CI boxes
            runner.check_failures()
            if region.capture_ack >= 1:
                break
            time.sleep(0.05)
        time.sleep(0.3)                    # one housekeeping flush
        yield runner
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()


def test_live_folded_stacks_for_at_least_two_tiles(prof_pipeline):
    runner = prof_pipeline
    folded = read_folded(runner.plan, runner.wksp)
    populated = [tn for tn, f in folded.items()
                 if sum(sum(v.values()) for v in f.values()) > 0]
    assert len(populated) >= 2, folded.keys()


def test_merged_bundle_single_clock_and_device_events(prof_pipeline):
    """ACCEPTANCE: the merged Perfetto bundle holds host flamegraph
    slices for >=2 tiles AND the verify tile's device/compile events
    on one timeline — one clock domain, no colliding thread ids."""
    runner = prof_pipeline
    doc = json.loads(json.dumps(
        merged_chrome(runner.plan, runner.wksp)))
    te = doc["traceEvents"]
    names = {}
    for e in te:
        if e.get("name") == "thread_name":
            # no two threads may share a tid (fdtrace tiles vs /host)
            assert e["tid"] not in names, (e, names)
            names[e["tid"]] = e["args"]["name"]
    host_tids = {t for t, n in names.items() if n.endswith("/host")}
    assert len(host_tids) >= 2, names
    trace_tids = {n: t for t, n in names.items()
                  if not n.endswith("/host")}
    # host slices actually present for >=2 tiles
    hosts_with_slices = {e["tid"] for e in te
                         if e.get("cat") == "fdprof"}
    assert len(hosts_with_slices & host_tids) >= 2
    # verify's device + compile events ride the same timeline
    vtid = trace_tids["verify"]
    vnames = {e["name"] for e in te if e.get("tid") == vtid}
    assert "tpu_dispatch" in vnames and "compile" in vnames
    # single clock domain: host slices interleave the fdtrace span
    # range (both are utils/tempo.monotonic_ns)
    trace_ts = [e["ts"] for e in te
                if e.get("tid") in set(trace_tids.values())
                and e.get("ph") in ("X", "i")]
    host_ts = [e["ts"] for e in te if e.get("cat") == "fdprof"]
    assert host_ts and trace_ts
    lo, hi = min(trace_ts), max(trace_ts)
    assert any(lo <= t <= hi for t in host_ts), (lo, hi)


def test_slo_breach_triggered_capture_under_chaos(prof_pipeline):
    """The drill's artifacts: doorbell acked, capture manifest on
    disk, EV_PROF_CAPTURE + EV_COMPILE in the verify ring, breach
    history in the engine's /summary.json surface, and the chaos
    restart actually happened (the 'under chaos' half)."""
    runner = prof_pipeline
    from firedancer_tpu.prof import region_for
    from firedancer_tpu.prof.device import capture_manifest_path
    region = region_for(runner.plan, runner.wksp, "verify")
    assert region.capture_ack >= 1, "capture never acked"
    path = capture_manifest_path(runner.plan["topology"], "verify")
    with open(path) as f:
        doc = json.load(f)
    assert doc["tile"] == "verify" and doc["window_ms"] == 150.0
    assert doc["t1_ns"] > doc["t0_ns"]
    assert runner.metrics("verify")["prof_captures"] >= 1
    from firedancer_tpu.trace import read_rings
    evs = read_rings(runner.plan, runner.wksp, tiles=["verify"])
    kinds = {e["ev"] for e in evs["verify"]}
    assert "prof_capture" in kinds and "compile" in kinds
    assert runner.metrics("sink")["sup_restarts"] >= 1
    os.unlink(path)                        # test hygiene (/dev/shm)


def test_summary_json_and_monitor_surface_breach_history(prof_pipeline):
    runner = prof_pipeline
    import urllib.request
    port = runner.metrics("metric")["port"]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/summary.json", timeout=10) as r:
        doc = json.loads(r.read())
    hist = doc["slo_history"]
    assert hist and hist[0]["target"] == "impossible-latency"
    assert hist[0]["kind"] == "breach"
    # the monitor recovers the same breaches from shm alone: EV_SLO in
    # the metric tile's ring when recent, the engine's durable breach
    # dump when the wrapping ring has moved on — this read happens
    # MINUTES after the breach, so it exercises the dump fallback
    from firedancer_tpu.disco.monitor import slo_breach_events
    evs = slo_breach_events(runner.plan, runner.wksp)
    assert evs and evs[-1]["target"] == "impossible-latency"


def test_profile_summary_shape_for_bench(prof_pipeline):
    runner = prof_pipeline
    prof = profile_summary(runner.plan, runner.wksp, top_k=3)
    assert "verify" in prof and "sink" in prof
    v = prof["verify"]
    assert v["samples"] > 0 and v["top"]
    assert set(v["top"][0]) == {"stack", "count", "states"}
    assert all(len(t["stack"]) for t in v["top"])


def test_fdprof_cli_live(prof_pipeline, tmp_path, capsys):
    from firedancer_tpu.prof.cli import main as prof_main
    runner = prof_pipeline
    out = tmp_path / "bundle.json"
    folded = tmp_path / "run.folded"
    rc = prof_main([runner.plan["topology"], "--out", str(out),
                    "--folded", str(folded)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["otherData"]["prof"] == "fdprof"
    assert any(e.get("cat") == "fdprof" for e in doc["traceEvents"])
    lines = folded.read_text().splitlines()
    assert lines and all(" " in ln for ln in lines)
    text = capsys.readouterr().out
    assert "fdprof summary" in text and "samples" in text


def test_post_mortem_export_after_tile_death():
    """The shm regions outlive the tile processes: halt everything,
    THEN read folded stacks and the merged bundle (the same
    post-mortem contract as fdtrace black boxes)."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    topo = (Topology(f"pfpm{os.getpid()}", wksp_size=1 << 22,
                     prof={"enable": True, "hz": 300, "slots": 128,
                           "ring": 256})
            .link("a_b", depth=32, mtu=256)
            .tile("a", "synth", outs=["a_b"], count=200, unique=8,
                  burst=8)
            .tile("b", "sink", ins=["a_b"]))
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=540)
        runner.wait_idle("b", "rx", 8, timeout_s=120)
        time.sleep(0.2)
        runner.halt(join_timeout_s=10)     # tiles are DEAD now
        assert all(not p.is_alive() for p in runner.procs.values())
        folded = read_folded(plan, runner.wksp)
        assert any(sum(sum(v.values()) for v in f.values()) > 0
                   for f in folded.values()), folded
        samples = read_samples(plan, runner.wksp)
        assert any(samples.values())
    finally:
        runner.halt(join_timeout_s=5)
        runner.close()
