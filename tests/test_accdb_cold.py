"""accdb v2: hot funk + cold groove fallthrough/promotion/eviction
(ref: src/flamenco/accdb/fd_accdb_impl_v2.c role over funk+vinyl)."""
import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm.accdb import Account
from firedancer_tpu.svm.accdb_cold import (AccDbCold, ColdEvictError,
                                           account_from_bytes,
                                           account_to_bytes)


def K(n):
    return bytes([n]) * 32


def test_account_codec_roundtrip():
    a = Account(lamports=12345, data=b"\x07" * 99, owner=K(9),
                executable=True, rent_epoch=3)
    b = account_from_bytes(account_to_bytes(a))
    assert (b.lamports, bytes(b.data), b.owner, b.executable,
            b.rent_epoch) == (12345, b"\x07" * 99, K(9), True, 3)


def test_evict_fallthrough_and_promotion(tmp_path):
    funk = Funk()
    db = AccDbCold(funk, str(tmp_path))
    funk.rec_write(None, K(1), Account(5_000, bytearray(b"big" * 100)))
    db.evict(K(1))
    assert funk.rec_query(None, K(1)) is None      # gone from hot
    funk.txn_prepare(None, "blk")
    a = db.peek("blk", K(1))                       # cold fallthrough
    assert a.lamports == 5_000 and bytes(a.data) == b"big" * 100
    assert db.cold_stats["hits"] == 1
    # promoted: second peek is a hot hit
    db.peek("blk", K(1))
    assert db.cold_stats["hits"] == 1
    # handles work over promoted records
    h = db.open_rw("blk", K(1))
    h.account.lamports = 6_000
    db.close_rw(h)
    assert db.lamports("blk", K(1)) == 6_000
    db.close()


def test_evict_refuses_fork_dirty_keys(tmp_path):
    funk = Funk()
    db = AccDbCold(funk, str(tmp_path))
    funk.rec_write(None, K(2), Account(10))
    funk.txn_prepare(None, "f1")
    funk.rec_write("f1", K(2), Account(99))        # unpublished state
    with pytest.raises(ColdEvictError, match="fork"):
        db.evict(K(2))
    funk.txn_publish("f1")
    db.evict(K(2))                                 # now legal
    assert db.peek(None, K(2)).lamports == 99      # cold holds latest
    db.close()


def test_bulk_evict_and_restart_generation(tmp_path):
    funk = Funk()
    db = AccDbCold(funk, str(tmp_path))
    for i in range(1, 9):
        funk.rec_write(None, K(i),
                       Account(i, bytearray(b"x" * (i * 40))))
    n = db.evict_larger_than(150)                  # data > 150: i >= 4
    assert n == 5
    assert db.cold_stats["evicted"] == 5
    db.close()

    # restart: fresh funk, same cold dir — everything evicted serves
    funk2 = Funk()
    db2 = AccDbCold(funk2, str(tmp_path))
    funk2.txn_prepare(None, "blk")
    for i in range(4, 9):
        a = db2.peek("blk", K(i))
        assert a is not None and a.lamports == i
    assert db2.peek("blk", K(1)) is None           # never evicted,
    db2.close()                                    # lived in old funk


def test_evict_missing_key_raises(tmp_path):
    db = AccDbCold(Funk(), str(tmp_path))
    with pytest.raises(ColdEvictError, match="rooted"):
        db.evict(K(7))
    db.close()


def test_promotion_deletes_cold_copy_no_stale_resurrection(tmp_path):
    """r4 review: hot XOR cold — promotion removes the cold record, so
    later hot updates survive a restart and deletions via the facade
    reach both layers."""
    funk = Funk()
    db = AccDbCold(funk, str(tmp_path))
    funk.rec_write(None, K(1), Account(5))
    db.evict(K(1))
    funk.txn_prepare(None, "blk")
    db.peek("blk", K(1))                   # promote (cold copy dies)
    assert db.cold.get(K(1)) is None
    # hot update then restart generation: the update must win
    funk.rec_write(None, K(1), Account(77))
    db.close()
    funk2 = Funk()
    db2 = AccDbCold(funk2, str(tmp_path))
    assert db2.peek(None, K(1)) is None    # cold holds NOTHING stale
    db2.close()


def test_facade_remove_reaches_both_layers(tmp_path):
    funk = Funk()
    db = AccDbCold(funk, str(tmp_path))
    funk.rec_write(None, K(3), Account(9))
    db.evict(K(3))
    db.remove(None, K(3))                  # never promoted; facade del
    assert db.peek(None, K(3)) is None
    db.close()
    db2 = AccDbCold(Funk(), str(tmp_path))
    assert db2.peek(None, K(3)) is None    # not resurrected
    db2.close()
