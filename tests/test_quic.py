"""QUIC ingest tests: RFC 9001 key-schedule vectors, packet protection
round-trips, stream reassembly, and the quic tile replacing sock in a
live verify topology (ref: src/waltz/quic/fd_quic.h:11-60,
src/disco/quic/fd_quic_tile.c)."""
import os
import socket
import time

import pytest

# waltz/quic needs the AEAD primitives at import time; a bare
# environment must collect this module clean (skip, not error)
pytest.importorskip("cryptography")

from firedancer_tpu.waltz import quic


def test_rfc9001_appendix_a_initial_keys():
    """The RFC 9001 A.1 client Initial secrets — byte-exact; proves the
    HKDF/expand-label/key-derivation tower is interoperable."""
    dcid = bytes.fromhex("8394c8f03e515708")
    ck, sk, _ = quic.initial_keys(dcid)
    assert ck.key == bytes.fromhex("1f369613dd76d5467730efcbe3b1a22d")
    assert ck.iv == bytes.fromhex("fa044b2f42a3fd3b46fb255c")
    assert ck.hp == bytes.fromhex("9f50449e04a0e810283a1e9933adedd2")
    assert sk.key == bytes.fromhex("cf3a5331653c364c88f0f379b6067e37")
    assert sk.iv == bytes.fromhex("0ac1493ca1905853b0bba03e")
    assert sk.hp == bytes.fromhex("c206b8d9b9f0f37644430b490eeaa314")


def test_varint_roundtrip():
    for v in (0, 63, 64, 16383, 16384, (1 << 30) - 1, 1 << 30,
              (1 << 62) - 1):
        b = quic.enc_varint(v)
        got, off = quic.dec_varint(b, 0)
        assert got == v and off == len(b)


def test_long_packet_roundtrip():
    dcid = os.urandom(8)
    ck, sk, _ = quic.initial_keys(dcid)
    payload = quic.enc_crypto_frame(0, b"A" * 32) + bytes(100)
    pkt = quic.seal_long(ck, quic.PT_INITIAL, dcid, b"\x01" * 8, 0,
                         payload)
    ptype, d, s, got, _ = quic.open_long(ck, pkt)
    assert (ptype, d, s, got) == (quic.PT_INITIAL, dcid, b"\x01" * 8,
                                  payload)
    # a flipped ciphertext byte must fail the AEAD, not misparse
    bad = bytearray(pkt)
    bad[-1] ^= 1
    with pytest.raises(quic.QuicError):
        quic.open_long(ck, bytes(bad))


def test_short_packet_roundtrip():
    dcid = os.urandom(8)
    ck, sk, isec = quic.initial_keys(dcid)
    c1 = quic.Keys(quic.hkdf_expand_label(isec, b"test c", 32))
    frame = quic.enc_stream_frame(2, 0, b"txn-bytes", True)
    pkt = quic.seal_short(c1, dcid, 7, frame)
    pn, payload = quic.open_short(c1, pkt, 8)
    assert pn == 7
    frames = list(quic.parse_frames(payload))
    assert frames == [(quic.FRAME_STREAM,
                       {"stream": 2, "offset": 0, "data": b"txn-bytes",
                        "fin": True})]


def test_server_client_handshake_and_streams():
    """Loopback handshake + txns over uni streams, including an
    out-of-order multi-packet stream."""
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    got = []
    server = quic.QuicServer(srv_sock, got.append)

    cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli_sock.bind(("127.0.0.1", 0))
    client = quic.QuicClient(cli_sock, srv_sock.getsockname())

    def pump_server():
        while True:
            try:
                data, addr = srv_sock.recvfrom(2048)
            except OSError:
                return
            server.on_datagram(data, addr)

    # handshake needs the server to answer the Initial
    import threading
    t = threading.Thread(target=lambda: (time.sleep(0.05),
                                         pump_server()), daemon=True)
    t.start()
    client.handshake(timeout=10)
    assert client.c1rtt is not None

    txns = [b"tx-%03d" % i + bytes(i) for i in range(5)]
    for txn in txns:
        client.send_txn(txn)
    big = bytes(range(256)) * 12            # multi-packet stream
    client.send_txn(big)
    deadline = time.time() + 5
    while len(got) < 6 and time.time() < deadline:
        pump_server()
        time.sleep(0.01)
    assert got[:5] == txns
    assert got[5] == big
    assert server.metrics["txns"] == 6
    assert client.recv_acks() >= 1          # server acked stream pkts
    srv_sock.close()
    cli_sock.close()


def test_server_rejects_garbage_and_wrong_keys():
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    server = quic.QuicServer(srv_sock, lambda t: None)
    server.on_datagram(b"\xff" + os.urandom(40), ("127.0.0.1", 1))
    server.on_datagram(os.urandom(200), ("127.0.0.1", 1))
    # well-formed header, wrong keys -> AEAD failure counted, no crash
    dcid = os.urandom(8)
    ck, _, _ = quic.initial_keys(os.urandom(8))      # mismatched dcid
    pkt = quic.seal_long(ck, quic.PT_INITIAL, dcid, b"\x02" * 8, 0,
                         quic.enc_crypto_frame(0, b"x" * 32))
    server.on_datagram(pkt, ("127.0.0.1", 1))
    assert server.metrics["bad_pkts"] == 3
    assert server.metrics["txns"] == 0
    srv_sock.close()


@pytest.mark.slow
def test_quic_tile_feeds_verify_topology():
    """The quic tile replaces sock in the ingest topology: signed txns
    over real QUIC -> verify -> sink at nonzero TPS."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.tiles.synth import make_signed_txns
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    N = 24
    topo = (
        Topology(f"qc{os.getpid()}", wksp_size=1 << 24)
        .link("quic_verify", depth=128, mtu=1280)
        .link("verify_sink", depth=128, mtu=1280)
        .tcache("verify_tc", depth=4096)
        .tile("quic", "quic", outs=["quic_verify"], port=0, batch=64)
        .tile("verify", "verify", ins=["quic_verify"],
              outs=["verify_sink"], batch=16, tcache="verify_tc")
        .tile("sink", "sink", ins=["verify_sink"])
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        deadline = time.time() + 30
        while runner.metrics("quic")["port"] == 0 \
                and time.time() < deadline:
            time.sleep(0.1)
        port = int(runner.metrics("quic")["port"])
        assert port

        cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        cli_sock.bind(("127.0.0.1", 0))
        client = quic.QuicClient(cli_sock, ("127.0.0.1", port))
        client.handshake(timeout=30)
        txns = make_signed_txns(N, seed=5)
        deadline = time.time() + 120
        sent_rounds = 0
        while time.time() < deadline:
            if runner.metrics("sink")["rx"] >= N:
                break
            for t in txns:
                client.send_txn(t)
            sent_rounds += 1
            time.sleep(0.5)
        assert runner.metrics("sink")["rx"] >= N
        v = runner.metrics("verify")
        assert v["verify_fail"] == 0 and v["parse_fail"] == 0
        q = runner.metrics("quic")
        assert q["txns"] >= N and q["conns"] == 1
        cli_sock.close()
    finally:
        runner.halt()
        runner.close()


def test_packet_number_reconstruction():
    # RFC 9000 A.3: 16-bit truncation recovers the full pn near largest
    assert quic.decode_pn(0x0000, 2, 0xFFFF) == 0x10000
    assert quic.decode_pn(0x0001, 2, 0xFFFF) == 0x10001
    assert quic.decode_pn(0xFFFE, 2, 0xFFFF) == 0xFFFE
    assert quic.decode_pn(0x9b32, 2, 0xa82f30ea) == 0xa82f9b32  # RFC ex.
    # round-trip through seal/open across the 16-bit boundary
    dcid = os.urandom(8)
    _, _, isec = quic.initial_keys(dcid)
    c1 = quic.Keys(quic.hkdf_expand_label(isec, b"test c", 32))
    # gaps stay under the 2-byte half-window (RFC A.3 recoverability)
    largest = -1
    for pn in (0, 1, 0xFFFF, 0x10000, 0x10001, 0x17FFF):
        pkt = quic.seal_short(c1, dcid, pn, bytes([quic.FRAME_PING]))
        got, _ = quic.open_short(c1, pkt, 8, largest)
        assert got == pn, (hex(pn), hex(got))
        largest = pn


def test_replayed_datagram_rejected():
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    got = []
    server = quic.QuicServer(srv_sock, got.append)
    cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli_sock.bind(("127.0.0.1", 0))
    client = quic.QuicClient(cli_sock, srv_sock.getsockname())
    import threading
    threading.Thread(target=lambda: (time.sleep(0.05), _pump(server,
                     srv_sock)), daemon=True).start()
    client.handshake(timeout=10)
    # pump until the client Finished lands — the 1-RTT gate
    # (RFC 9001 §5.7) refuses stream data until then
    deadline = time.time() + 5
    while time.time() < deadline:
        conns = list(server.conns.values())
        if conns and conns[0].tls.complete:
            break
        try:
            d, a = srv_sock.recvfrom(4096)
            server.on_datagram(d, a)
        except OSError:
            time.sleep(0.01)
    frame = quic.enc_stream_frame(2, 0, b"one-txn", True)
    pkt = quic.seal_short(client.c1rtt, client.dcid, client.tx_pn, frame)
    for _ in range(3):                      # replay the SAME datagram
        server.on_datagram(pkt, cli_sock.getsockname())
    assert got == [b"one-txn"]              # delivered exactly once
    assert server.metrics["replayed"] == 2
    srv_sock.close()
    cli_sock.close()


def test_never_fin_stream_is_bounded():
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    server = quic.QuicServer(srv_sock, lambda t: None)
    cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli_sock.bind(("127.0.0.1", 0))
    client = quic.QuicClient(cli_sock, srv_sock.getsockname())
    import threading
    threading.Thread(target=lambda: (time.sleep(0.05), _pump(server,
                     srv_sock)), daemon=True).start()
    client.handshake(timeout=10)
    # stream frames far past the reassembly cap, never FIN
    for i in range(100):
        frame = quic.enc_stream_frame(2, i * 1200, b"z" * 1200, False)
        pkt = quic.seal_short(client.c1rtt, client.dcid,
                              client.tx_pn, frame)
        client.tx_pn += 1
        server.on_datagram(pkt, cli_sock.getsockname())
    st = server.conns[client.dcid].streams.get(2)
    assert st is None or st.buffered <= quic.MAX_STREAM_BYTES
    assert server.metrics["bad_pkts"] > 0   # over-cap frames rejected
    srv_sock.close()
    cli_sock.close()


def test_handshake_response_retransmitted():
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    server = quic.QuicServer(srv_sock, lambda t: None)
    cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli_sock.bind(("127.0.0.1", 0))
    client = quic.QuicClient(cli_sock, srv_sock.getsockname())
    client.tls.start()
    _, ch = client.tls.emit.pop(0)
    hello = quic.enc_crypto_frame(0, ch)
    hello += bytes(max(0, 1162 - len(hello)))
    pkt = quic.seal_long(client.ckeys, quic.PT_INITIAL, client.dcid,
                         client.scid, 0, hello)
    server.on_datagram(pkt, cli_sock.getsockname())
    cli_sock.settimeout(5)
    first, _ = cli_sock.recvfrom(4096)
    # client "lost" it: retransmit the Initial; server resends verbatim
    server.on_datagram(pkt, cli_sock.getsockname())
    second, _ = cli_sock.recvfrom(4096)
    assert first == second
    srv_sock.close()
    cli_sock.close()


def _pump(server, sock):
    while True:
        try:
            data, addr = sock.recvfrom(2048)
        except OSError:
            return
        server.on_datagram(data, addr)


def test_hostile_key_share_does_not_crash_server():
    """A ClientHello carrying an all-zero (small-order) or wrong-length
    x25519 key share must be counted bad, not raise out of
    on_datagram (review r4: ValueError escaped the catch)."""
    from firedancer_tpu.waltz import tls as fdtls
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    server = quic.QuicServer(srv_sock, lambda t: None)
    for evil_share in (bytes(32), b"\x01" * 7):
        dcid, scid = os.urandom(8), os.urandom(8)
        ck, _, _ = quic.initial_keys(dcid)
        ch = fdtls.build_client_hello(os.urandom(32), evil_share, b"")
        hello = quic.enc_crypto_frame(0, ch)
        hello += bytes(max(0, 1162 - len(hello)))
        pkt = quic.seal_long(ck, quic.PT_INITIAL, dcid, scid, 0, hello)
        n = server.on_datagram(pkt, ("127.0.0.1", 1))
        assert n == 0
        assert dcid not in server.conns          # no half-open leak
    assert server.metrics["bad_pkts"] == 2
    srv_sock.close()


def test_server_handles_coalesced_client_flight():
    """Initial(ACK-ish padding) + Handshake(Finished) coalesced into
    ONE datagram — the standard client second flight (RFC 9001 §4.1)
    — must complete the handshake (review r4: server read only the
    first packet)."""
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    server = quic.QuicServer(srv_sock, lambda t: None)
    cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli_sock.bind(("127.0.0.1", 0))
    client = quic.QuicClient(cli_sock, srv_sock.getsockname())
    client.tls.start()
    _, ch = client.tls.emit.pop(0)
    hello = quic.enc_crypto_frame(0, ch)
    hello += bytes(max(0, 1162 - len(hello)))
    pkt = quic.seal_long(client.ckeys, quic.PT_INITIAL, client.dcid,
                         client.scid, 0, hello)
    server.on_datagram(pkt, cli_sock.getsockname())
    cli_sock.settimeout(5)
    data, _ = cli_sock.recvfrom(4096)
    client._on_hs_datagram_collect = []
    # feed the server flight but intercept the client Finished
    off = 0
    while off < len(data) and data[off] & 0x80:
        chunk = data[off:]
        pt = (chunk[0] >> 4) & 0x03
        keys = client.skeys if pt == quic.PT_INITIAL else client.shs
        ptype, _, _, payload, consumed = quic.open_long(keys, chunk)
        off += consumed
        lvl = 0 if ptype == quic.PT_INITIAL else 1
        for ft, f in quic.parse_frames(payload):
            if ft == quic.FRAME_CRYPTO:
                client.cbuf[lvl].add(f["offset"], f["data"])
                client.tls.on_crypto(lvl, client.cbuf[lvl].drain())
        if client.tls.sched.s_hs is not None and client.shs is None:
            client.chs = quic.Keys(client.tls.sched.c_hs)
            client.shs = quic.Keys(client.tls.sched.s_hs)
    assert client.tls.complete
    _, fin = client.tls.emit.pop(0)
    # coalesce: Initial(PING) + Handshake(Finished) in one datagram
    ini = quic.seal_long(client.ckeys, quic.PT_INITIAL, client.dcid,
                         client.scid, 1, bytes([quic.FRAME_PING]))
    hs = quic.seal_long(client.chs, quic.PT_HANDSHAKE, client.dcid,
                        client.scid, 0, quic.enc_crypto_frame(0, fin))
    conn = server.conns[client.dcid]
    assert not conn.tls.complete
    server.on_datagram(ini + hs, cli_sock.getsockname())
    assert conn.tls.complete                    # Finished was read
    srv_sock.close()
    cli_sock.close()


def test_cryptobuf_overlapping_refragmented_retransmit():
    """RFC 9000 §19.6: a retransmit may re-slice consumed ranges; the
    unseen tail must still be delivered (review r4: dropped)."""
    buf = quic.CryptoBuf()
    buf.add(0, b"a" * 50)
    assert buf.drain() == b"a" * 50
    buf.add(0, b"a" * 50 + b"b" * 50)           # re-fragmented [0,100)
    assert buf.drain() == b"b" * 50
    # overlapping duplicate entirely inside consumed range: ignored
    buf.add(10, b"a" * 20)
    assert buf.drain() == b""
    # stored-chunk overlap: [110,130) buffered, then [100,140) arrives
    buf.add(110, b"c" * 20)
    buf.add(100, b"d" * 40)
    assert buf.drain() == b"d" * 40


def _handshaken_pair():
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    got = []
    server = quic.QuicServer(srv_sock, got.append)
    cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli_sock.bind(("127.0.0.1", 0))
    client = quic.QuicClient(cli_sock, srv_sock.getsockname())
    import threading

    def pump():
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                data, addr = srv_sock.recvfrom(4096)
            except OSError:
                time.sleep(0.005)
                continue
            server.on_datagram(data, addr)
            if server.conns and next(
                    iter(server.conns.values())).tls.complete:
                return
    t = threading.Thread(target=pump, daemon=True)
    t.start()
    client.handshake(timeout=10)
    t.join(timeout=10)
    return srv_sock, cli_sock, server, client, got


def test_forged_initial_cannot_tear_down_established_conn():
    """Initial keys derive from the public dcid; RFC 9001 §4.9.1
    requires discarding them post-handshake. A forged Initial with
    garbage CRYPTO must not evict the established conn (review r4)."""
    srv_sock, cli_sock, server, client, got = _handshaken_pair()
    conn = server.conns[client.dcid]
    assert conn.tls.complete and conn.initial_done
    # attacker: valid Initial protection for this dcid, junk CRYPTO
    ck, _, _ = quic.initial_keys(client.dcid)
    evil = quic.seal_long(ck, quic.PT_INITIAL, client.dcid,
                          os.urandom(8), 9,
                          quic.enc_crypto_frame(0, b"\x02" + b"\x00\x00\x04" + b"evil"))
    server.on_datagram(evil, ("127.0.0.1", 9))
    assert client.dcid in server.conns          # conn survived
    # and 1-RTT txns still flow
    client.send_txn(b"post-attack-txn")
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        try:
            data, addr = srv_sock.recvfrom(4096)
        except OSError:
            time.sleep(0.005)
            continue
        server.on_datagram(data, addr)
    assert got == [b"post-attack-txn"]
    srv_sock.close()
    cli_sock.close()


def test_on_txn_exception_surfaces_not_swallowed():
    """A consumer bug inside on_txn must propagate out of on_datagram,
    not be miscounted as a hostile packet (review r4)."""
    class Boom(ValueError):
        pass

    def bad_consumer(txn):
        raise Boom("consumer bug")

    srv_sock, cli_sock, server, client, _ = _handshaken_pair()
    server.on_txn = bad_consumer
    client.send_txn(b"txn")
    deadline = time.time() + 5
    raised = False
    while time.time() < deadline and not raised:
        try:
            data, addr = srv_sock.recvfrom(4096)
        except OSError:
            time.sleep(0.005)
            continue
        try:
            server.on_datagram(data, addr)
        except Boom:
            raised = True
    assert raised
    assert server.metrics["bad_pkts"] == 0
    srv_sock.close()
    cli_sock.close()


def test_client_handshake_survives_stray_datagrams():
    """Garbage datagrams racing the server flight must be ignored by
    the client, not abort the handshake (review r4)."""
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    server = quic.QuicServer(srv_sock, lambda t: None)
    cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli_sock.bind(("127.0.0.1", 0))
    client = quic.QuicClient(cli_sock, srv_sock.getsockname())
    cli_addr = cli_sock.getsockname()
    import threading

    def pump():
        stray = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sent_stray = False
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                data, addr = srv_sock.recvfrom(4096)
            except OSError:
                time.sleep(0.005)
                continue
            if not sent_stray:
                # garbage beats the server flight to the client
                stray.sendto(b"\xc0" + os.urandom(60), cli_addr)
                stray.sendto(os.urandom(30), cli_addr)
                sent_stray = True
            server.on_datagram(data, addr)
            if server.conns and next(
                    iter(server.conns.values())).tls.complete:
                break
        stray.close()
    t = threading.Thread(target=pump, daemon=True)
    t.start()
    client.handshake(timeout=10)
    assert client.c1rtt is not None
    srv_sock.close()
    cli_sock.close()


def test_server_requires_tpu_alpn():
    """A ClientHello without the solana-tpu ALPN is refused (review
    r4: ALPN was advertised but never enforced)."""
    from firedancer_tpu.waltz import tls as fdtls
    seed = os.urandom(32)
    srv = fdtls.TlsServer(seed)
    import struct as _s
    # a CH built like ours but with the ALPN extension stripped
    from firedancer_tpu.utils import x25519 as _x
    ch = fdtls.build_client_hello(os.urandom(32),
                                  _x.pubkey(os.urandom(32)), b"")
    body = ch[4:]
    # rebuild without ALPN: parse exts region and filter
    off = 2 + 32
    off += 1 + body[off]
    cs_len = _s.unpack_from(">H", body, off)[0]
    off += 2 + cs_len
    off += 1 + body[off]
    ext_len = _s.unpack_from(">H", body, off)[0]
    head = body[:off]
    exts = body[off + 2:off + 2 + ext_len]
    keep = b""
    eoff = 0
    while eoff < len(exts):
        et, ln = _s.unpack_from(">HH", exts, eoff)
        if et != fdtls.EXT_ALPN:
            keep += exts[eoff:eoff + 4 + ln]
        eoff += 4 + ln
    nb = head + _s.pack(">H", len(keep)) + keep
    msg = bytes([fdtls.HT_CLIENT_HELLO]) + len(nb).to_bytes(3, "big") + nb
    import pytest as _pt
    with _pt.raises(fdtls.TlsError):
        srv.on_crypto(fdtls.EL_INITIAL, msg)
    assert srv.alert == "no_application_protocol"


def test_server_rejects_1rtt_before_client_finished():
    """RFC 9001 §5.7: stream data on a connection whose client never
    sent Finished must be refused (review r4)."""
    srv_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv_sock.bind(("127.0.0.1", 0))
    srv_sock.setblocking(False)
    got = []
    server = quic.QuicServer(srv_sock, got.append)
    cli_sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli_sock.bind(("127.0.0.1", 0))
    client = quic.QuicClient(cli_sock, srv_sock.getsockname())
    client.tls.start()
    _, ch = client.tls.emit.pop(0)
    hello = quic.enc_crypto_frame(0, ch)
    hello += bytes(max(0, 1162 - len(hello)))
    pkt = quic.seal_long(client.ckeys, quic.PT_INITIAL, client.dcid,
                         client.scid, 0, hello)
    server.on_datagram(pkt, cli_sock.getsockname())
    cli_sock.settimeout(5)
    data, _ = cli_sock.recvfrom(4096)
    # process the server flight BY HAND so the Finished is never sent
    # (QuicClient._on_hs_datagram would flush it automatically)
    off = 0
    while off < len(data) and data[off] & 0x80:
        chunk = data[off:]
        pt = (chunk[0] >> 4) & 0x03
        keys = client.skeys if pt == quic.PT_INITIAL else client.shs
        ptype, _, _, payload, consumed = quic.open_long(keys, chunk)
        off += consumed
        lvl = 0 if ptype == quic.PT_INITIAL else 1
        for ft, f in quic.parse_frames(payload):
            if ft == quic.FRAME_CRYPTO:
                client.cbuf[lvl].add(f["offset"], f["data"])
                client.tls.on_crypto(lvl, client.cbuf[lvl].drain())
        if client.tls.sched.s_hs is not None and client.shs is None:
            client.chs = quic.Keys(client.tls.sched.c_hs)
            client.shs = quic.Keys(client.tls.sched.s_hs)
    assert client.tls.complete           # client side thinks it's done
    client.tls.emit.clear()              # ...but WITHHOLD Finished
    client.c1rtt = quic.Keys(client.tls.sched.c_ap)
    client.s1rtt = quic.Keys(client.tls.sched.s_ap)
    client.send_txn(b"premature")
    deadline = time.time() + 2
    while time.time() < deadline:
        try:
            d, a = srv_sock.recvfrom(4096)
        except OSError:
            time.sleep(0.01)
            continue
        server.on_datagram(d, a)
    assert got == []                     # never ingested
    assert server.metrics["bad_pkts"] >= 1
    srv_sock.close()
    cli_sock.close()
