"""Bank hash / accounts-lattice tests: the delta path must agree with
the full-recompute oracle, deletions subtract cleanly, and replay's
per-slot chain is deterministic and state-sensitive
(ref: fd_runtime bank-hash assembly, src/ballet/lthash/fd_lthash.h)."""
import numpy as np
import pytest

from firedancer_tpu.flamenco.bank_hash import (
    BankHasher, accounts_lthash, lthash_of_root,
)
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.funk.shmfunk import ShmFunk
from firedancer_tpu.svm.accdb import Account


def k(n):
    return bytes([n]) * 32


@pytest.fixture(params=["process", "shm"])
def mk_funk(request):
    """Both funk backends feed the lattice: the bank-hash suite is the
    second half of the shm store's byte-compat oracle (a store that
    round-trips accounts differently diverges here immediately)."""
    made = []

    def mk():
        f = Funk() if request.param == "process" else ShmFunk()
        made.append(f)
        return f

    yield mk
    for f in made:
        if isinstance(f, ShmFunk):
            f.close(unlink=True)


def test_delta_matches_full_recompute(mk_funk):
    funk = mk_funk()
    rng = np.random.default_rng(5)
    h = BankHasher()
    for step in range(6):
        old_items, new_items = [], []
        for _ in range(4):
            key = bytes([int(rng.integers(0, 12))]) * 32
            old = funk.rec_query(None, key)
            new = Account(lamports=int(rng.integers(1, 1 << 40)),
                          data=rng.bytes(int(rng.integers(0, 64))),
                          owner=k(9))
            old_items.append((key, old))
            new_items.append((key, new))
            funk.rec_write(None, key, new)
        h.apply_delta(old_items, new_items)
        full = lthash_of_root(funk)
        assert np.array_equal(h.acc, full), f"diverged at step {step}"


def test_deletion_subtracts(mk_funk):
    funk = mk_funk()
    h = BankHasher()
    a = Account(lamports=100, data=b"abc", owner=k(2))
    funk.rec_write(None, k(1), a)
    h.apply_delta([(k(1), None)], [(k(1), a)])
    assert np.array_equal(h.acc, lthash_of_root(funk))
    # delete: new value None (zero-lamport discipline)
    funk.rec_remove(None, k(1))
    h.apply_delta([(k(1), a)], [(k(1), None)])
    assert not h.acc.any()                   # back to the empty lattice


def test_bank_hash_sensitivity():
    h = BankHasher()
    base = h.bank_hash(bytes(32), 3, k(7))
    assert h.bank_hash(bytes(32), 4, k(7)) != base      # sig count
    assert h.bank_hash(bytes(32), 3, k(8)) != base      # blockhash
    assert h.bank_hash(k(1), 3, k(7)) != base           # parent
    h2 = BankHasher()
    h2.apply_delta([], [(k(1), Account(lamports=1))])
    assert h2.bank_hash(bytes(32), 3, k(7)) != base     # state


def test_order_independence():
    """The lattice is commutative: delta order must not matter."""
    a1 = (k(1), Account(lamports=5, data=b"x"))
    a2 = (k(2), Account(lamports=9, data=b"y"))
    h1, h2 = BankHasher(), BankHasher()
    h1.apply_delta([], [a1])
    h1.apply_delta([], [a2])
    h2.apply_delta([], [a2, a1])
    assert np.array_equal(h1.acc, h2.acc)


def test_replay_bank_hash_deterministic_and_state_sensitive():
    """Two replays of the same slices produce identical bank-hash
    chains; replaying with different genesis diverges even though the
    PoH stream is identical."""
    from firedancer_tpu.tiles.replay import ReplayCore
    from firedancer_tpu.tiles.synth import make_signed_txns, synth_signer_seed
    from firedancer_tpu.utils.ed25519_ref import keypair
    from tests.test_repair_replay import _run_leader_slots, _CaptureRing
    from firedancer_tpu.tiles.shred import ShredRecoverCore
    txns = make_signed_txns(4, seed=6)
    LEADER_PUB = keypair(bytes(range(32)))[-1]
    sent, _, _ = _run_leader_slots(3, txns_in_slot={1: txns})
    slices = _CaptureRing()
    rec = ShredRecoverCore(LEADER_PUB, slices, None)
    for w in sent:
        rec.on_shred(w)
    frames = [f for f, _ in slices.frames]

    genesis = {keypair(synth_signer_seed(i))[-1]: 1 << 44
               for i in range(16)}

    def replay(gen):
        core = ReplayCore(genesis=gen, hashes_per_tick=8)
        for f in frames:
            core.on_slice(f)
        assert core.metrics["exec_fail"] == 0
        return dict(core.bank_hash_of)

    h_a = replay(dict(genesis))
    h_b = replay(dict(genesis))
    assert h_a == h_b                        # deterministic
    rich = dict(genesis)
    rich[k(0x33)] = 1 << 20                  # different pre-state
    h_c = replay(rich)
    assert h_c[1] != h_a[1]                  # state-sensitive
    # chain property: changing slot 1 changes slot 2's hash too
    assert h_c[2] != h_a[2]
