"""Solana gossip wire codec vs the reference's parser contract
(src/flamenco/gossip/fd_gossip_msg_parse.c), using the reference
tree's REAL vote transaction fixture (test_vote_txn.bin, read as
binary TEST DATA — the same fixture test_gossip_ser.c uses)."""
import hashlib
import os
import struct

import pytest

from firedancer_tpu.flamenco import gossip_wire as gw
from firedancer_tpu.utils.ed25519_ref import keypair, sign, verify

VOTE_TXN_PATH = "/root/reference/src/flamenco/gossip/test_vote_txn.bin"
SEED = bytes(range(32))


def _vote_txn() -> bytes:
    if not os.path.exists(VOTE_TXN_PATH):
        pytest.skip("reference fixture unavailable")
    return open(VOTE_TXN_PATH, "rb").read()


def test_real_vote_txn_parses_and_crds_vote_roundtrips():
    txn = _vote_txn()
    _, _, pub = keypair(SEED)
    now_ms = 1234
    payload = gw.encode_vote(0, pub, txn, now_ms)
    # pinned layout: index u8 + pubkey 32 + txn + wallclock u64
    assert len(payload) == 1 + 32 + len(txn) + 8
    sig = sign(SEED, gw.signable(gw.V_VOTE, payload))
    wire = gw.encode_value(gw.V_VOTE, payload, sig)
    assert len(wire) == 64 + 4 + len(payload)   # sig + tag + data
    v, end = gw.decode_value(wire, 0)
    assert end == len(wire)
    assert v["tag"] == gw.V_VOTE and v["origin"] == pub
    assert v["wallclock_ms"] == now_ms
    decoded, _ = gw.decode_vote(v["payload"], 0)
    assert decoded["txn"] == txn and decoded["index"] == 0
    # the signature verifies over exactly the signable region
    assert verify(sig, pub, gw.signable(gw.V_VOTE, v["payload"]))
    # identity hash covers the full serialized value
    assert gw.value_hash(wire) == hashlib.sha256(wire).digest()


def test_contact_info_roundtrip_and_port_delta_encoding():
    _, _, pub = keypair(SEED)
    ci = gw.ContactInfo(
        pubkey=pub, wallclock_ms=987_654_321, outset_us=17,
        shred_version=50093, version=(0, 6, 3), commit=0xDEADBEEF,
        feature_set=1234, client=gw.CLIENT_FIREDANCER,
        sockets={gw.SOCKET_GOSSIP: ("127.0.0.1", 8001),
                 gw.SOCKET_TVU: ("127.0.0.1", 8002),
                 gw.SOCKET_TPU: ("10.0.0.7", 8003),
                 gw.SOCKET_RPC: ("127.0.0.1", 7000)})
    payload = ci.encode()
    got, end = gw.ContactInfo.decode(payload, 0)
    assert end == len(payload)
    assert got == ci
    assert got.gossip_addr() == ("127.0.0.1", 8001)
    # negative port deltas must survive the u16 wraparound
    assert got.sockets[gw.SOCKET_RPC] == ("127.0.0.1", 7000)
    # envelope round-trip through a push container
    sig = sign(SEED, gw.signable(gw.V_CONTACT_INFO, payload))
    wire = gw.encode_value(gw.V_CONTACT_INFO, payload, sig)
    msg = gw.encode_container(gw.MSG_PUSH, pub, [wire])
    view = gw.parse_message(msg)
    assert view["kind"] == "push" and view["from"] == pub
    assert view["values"][0]["wire"] == wire
    assert view["values"][0]["wallclock_ms"] == 987_654_321


def test_pull_request_bloom_roundtrip():
    _, _, pub = keypair(SEED)
    ci = gw.ContactInfo(pubkey=pub, wallclock_ms=5,
                        sockets={gw.SOCKET_GOSSIP: ("127.0.0.1", 9)})
    pay = ci.encode()
    sig = sign(SEED, gw.signable(gw.V_CONTACT_INFO, pay))
    civ = gw.encode_value(gw.V_CONTACT_INFO, pay, sig)
    bits = struct.pack("<4Q", 1, 2, 4, 8)
    msg = gw.encode_pull_request([7, 11], bits, 4, 0xFFFF, 16, civ)
    view = gw.parse_message(msg)
    assert view["kind"] == "pull_request"
    assert view["bloom_keys"] == [7, 11]
    assert view["bloom_bits"] == bits
    assert view["mask"] == 0xFFFF and view["mask_bits"] == 16
    assert view["ci"]["origin"] == pub


def test_prune_message_and_both_signable_forms():
    _, _, pub = keypair(SEED)
    origins = [hashlib.sha256(b"%d" % i).digest() for i in range(3)]
    dest = hashlib.sha256(b"dest").digest()
    wc = 777
    signable = gw.prune_signable(pub, origins, dest, wc, prefixed=True)
    assert signable.startswith(b"\xffSOLANA_PRUNE_DATA")
    # layout check against fd_gossvf_tile.c verify_prune offsets
    assert len(signable) == 98 + 32 * len(origins)
    sig = sign(SEED, signable)
    msg = gw.encode_prune(pub, origins, sig, dest, wc)
    view = gw.parse_message(msg)
    assert view["kind"] == "prune" and view["origins"] == origins
    assert view["destination"] == dest and view["wallclock_ms"] == wc
    # the unprefixed form is the same bytes minus the 18-byte prefix
    assert gw.prune_signable(pub, origins, dest, wc,
                             prefixed=False) == signable[18:]


def test_ping_pong_layout():
    _, _, pub = keypair(SEED)
    token = hashlib.sha256(b"tok").digest()
    psig = sign(SEED, token)
    ping = gw.encode_ping(pub, token, psig)
    assert len(ping) == 4 + 128
    view = gw.parse_message(ping)
    assert view["kind"] == "ping" and view["token"] == token
    pre = gw.pong_preimage(token)
    assert pre == b"SOLANA_PING_PONG" + token
    pong = gw.encode_pong(pub, token, sign(SEED, hashlib.sha256(pre)
                                           .digest()))
    view = gw.parse_message(pong)
    assert view["kind"] == "pong"
    assert view["token"] == hashlib.sha256(pre).digest()


def test_hostile_wire_rejected():
    _, _, pub = keypair(SEED)
    with pytest.raises(gw.WireError):
        gw.parse_message(struct.pack("<I", 9) + bytes(32))
    # trailing bytes rejected (payload_sz==CUR_OFFSET contract)
    token = bytes(32)
    ping = gw.encode_ping(pub, token, bytes(64)) + b"x"
    with pytest.raises(gw.WireError):
        gw.parse_message(ping)
    # oversize CRDS count
    bad = struct.pack("<I", gw.MSG_PUSH) + pub + struct.pack("<Q", 500)
    with pytest.raises(gw.WireError):
        gw.parse_message(bad)
    # vote with out-of-range index
    with pytest.raises(gw.WireError):
        gw.encode_vote(32, pub, b"", 0)


def test_all_reference_crds_tags_scan_in_containers():
    """A push datagram mixing every CRDS tag the reference parses must
    scan value-by-value without aborting (real peers batch EpochSlots /
    DuplicateShred / snapshot hashes alongside ContactInfos)."""
    _, _, pub = keypair(SEED)
    wc = struct.pack("<Q", 123)
    payloads = [
        (gw.V_ACCOUNT_HASHES, pub + struct.pack("<Q", 2)
         + (struct.pack("<Q", 5) + bytes(32)) * 2 + wc),
        (gw.V_INC_SNAPSHOT_HASHES, pub + struct.pack("<Q", 9) + bytes(32)
         + struct.pack("<Q", 1) + struct.pack("<Q", 10) + bytes(32) + wc),
        (gw.V_EPOCH_SLOTS, bytes([0]) + pub + struct.pack("<Q", 1)
         + struct.pack("<I", 1) + struct.pack("<QQ", 7, 8)
         + bytes([1]) + struct.pack("<Q", 2) + bytes(2)
         + struct.pack("<Q", 16) + wc),
        (gw.V_DUPLICATE_SHRED, struct.pack("<H", 1) + pub + wc
         + struct.pack("<Q", 9) + bytes(5) + bytes([2, 0])
         + struct.pack("<Q", 3) + b"abc"),
        (gw.V_RESTART_HEAVIEST_FORK, pub + wc + struct.pack("<Q", 4)
         + bytes(32) + struct.pack("<Q", 11)[:8] + struct.pack("<H", 1)),
        (gw.V_NODE_INSTANCE, gw.encode_node_instance(pub, 123, 5, 6)),
    ]
    values = [gw.encode_value(t, p, bytes(64)) for t, p in payloads]
    msg = gw.encode_container(gw.MSG_PUSH, pub, values)
    view = gw.parse_message(msg)
    assert [v["tag"] for v in view["values"]] == [t for t, _ in payloads]
    assert all(v["origin"] == pub for v in view["values"])
    assert all(v["wallclock_ms"] == 123 for v in view["values"])
