"""FEC store + reassembly tests, driven end-to-end from the repo's own
shredder through the FEC resolver (ref: src/disco/store/fd_store.h,
src/discof/reasm/)."""
import numpy as np

from firedancer_tpu.shred import FecResolver, Shredder
from firedancer_tpu.shred.store import FecStore, Reassembler
from firedancer_tpu.utils.ed25519_ref import keypair, sign, verify

SEED = bytes(range(32))
_, _, LEADER = keypair(SEED)


def _sets(batch, slot=9):
    sh = Shredder(sign_fn=lambda r: sign(SEED, r), shred_version=7)
    return sh.shred_batch(batch, slot=slot, parent_off=1, ref_tick=3,
                          block_complete=True)


def test_store_insert_query_prune_evict():
    st = FecStore(max_sets=3)
    assert st.insert(b"r1" * 16, 5, 0, b"a")
    assert not st.insert(b"r1" * 16, 5, 0, b"a")      # dup
    assert st.query(b"r1" * 16) == b"a"
    assert st.query(b"zz" * 16) is None
    st.insert(b"r2" * 16, 6, 0, b"b")
    st.insert(b"r3" * 16, 7, 0, b"c")
    st.insert(b"r4" * 16, 8, 0, b"d")                 # evicts oldest
    assert st.query(b"r1" * 16) is None
    assert len(st) == 3
    st.publish(8)                                     # prune below root
    assert st.query(b"r2" * 16) is None and st.query(b"r4" * 16) == b"d"


def test_reasm_end_to_end_via_resolver():
    """Shred a 2-batch block, deliver FEC sets OUT of order with loss,
    reassemble byte-identical slices."""
    rng = np.random.default_rng(3)
    b1 = rng.integers(0, 256, 70_000, dtype=np.uint8).tobytes()
    sets = _sets(b1)
    assert len(sets) >= 2
    r = FecResolver(lambda sig, root, slot: verify(sig, LEADER, root))
    reasm = Reassembler()
    store = FecStore()
    slices = []
    # deliver sets in reverse order, dropping one data shred per set
    completed = []
    for fs in reversed(sets):
        wires = list(fs.data_shreds)[1:] + list(fs.parity_shreds)
        for w in wires:
            done, _ = r.add_shred(w)
            if done:
                completed.append(done)
    for done in completed:
        store.insert(done.merkle_root, done.slot, done.fec_set_idx,
                     b"".join(done.data_payloads))
        slices.extend(reasm.add_fec(done))
    assert slices, "no slices emitted"
    assert slices[-1].slot_complete
    assert b"".join(s.payload for s in slices) == b1
    assert len(store) == len(sets)
    assert reasm.metrics["done_slots"] == 1


def test_reasm_multiple_batches_ordered():
    """Two entry batches in one slot -> at least two slices, in order,
    only the last carrying slot_complete."""
    rng = np.random.default_rng(4)
    b1 = rng.integers(0, 256, 3000, dtype=np.uint8).tobytes()
    b2 = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
    sh = Shredder(sign_fn=lambda r: sign(SEED, r), shred_version=7)
    sets = sh.shred_batch(b1, slot=5, parent_off=1, ref_tick=0,
                          block_complete=False)
    sets += sh.shred_batch(b2, slot=5, parent_off=1, ref_tick=0,
                           block_complete=True)
    r = FecResolver(lambda sig, root, slot: verify(sig, LEADER, root))
    reasm = Reassembler()
    slices = []
    for fs in sets:
        for w in list(fs.data_shreds) + list(fs.parity_shreds):
            done, _ = r.add_shred(w)
            if done:
                slices.extend(reasm.add_fec(done))
    assert len(slices) >= 2
    assert not slices[0].slot_complete and slices[-1].slot_complete
    assert b"".join(s.payload for s in slices) == b1 + b2
