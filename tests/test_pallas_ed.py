"""Differential tests: Pallas verify/sha kernels vs the jnp reference path.

Run in Pallas interpreter mode on the CPU backend (tile constraints
relaxed), tiny batches — the full Wycheproof/malleability gates run
against the jnp implementation, and these tests pin the Pallas kernels
to it bit-for-bit. On real TPU hardware the same comparison runs
compiled (see tools/profile_kernel*.py and bench.py).
"""
import hashlib
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from firedancer_tpu.ops import ed25519 as ed  # noqa: E402
from firedancer_tpu.ops import pallas_ed as ped  # noqa: E402
from firedancer_tpu.ops import pallas_sha as psha  # noqa: E402
from firedancer_tpu.utils import ed25519_ref as ref  # noqa: E402


def _mixed_batch(n, msg_len, rng):
    """Valid sigs with a spread of corruptions (sig, pub, msg, edge cases)."""
    sigs, pubs, msgs = [], [], []
    for i in range(n):
        seed = rng.bytes(32)
        _, _, pk = ref.keypair(seed)
        m = rng.bytes(msg_len)
        s = ref.sign(seed, m)
        if i % 5 == 1:
            s = bytes([s[0] ^ 1]) + s[1:]           # corrupt R
        elif i % 5 == 2:
            s = s[:32] + bytes([s[32] ^ 1]) + s[33:]  # corrupt S
        elif i % 5 == 3:
            m = m[:-1] + bytes([m[-1] ^ 0x80])      # corrupt msg
        elif i % 5 == 4 and i % 2 == 0:
            pk = bytes([pk[0] ^ 1]) + pk[1:]        # corrupt A
        sigs.append(np.frombuffer(s, np.uint8))
        pubs.append(np.frombuffer(pk, np.uint8))
        msgs.append(np.frombuffer(m, np.uint8))
    return (jnp.asarray(np.stack(sigs)), jnp.asarray(np.stack(pubs)),
            jnp.asarray(np.stack(msgs)),
            jnp.full((n,), msg_len, jnp.int32))


@pytest.mark.skipif(os.environ.get("FDTPU_SLOW_TESTS") != "1",
                    reason="interpret-mode full-verify takes hours on a "
                           "1-core host; opt in with FDTPU_SLOW_TESTS=1. "
                           "The kernel is gated on hardware instead: "
                           "bench.py asserts every vector verifies on "
                           "the TPU backend, and the jnp reference path "
                           "it is pinned to passes Wycheproof + "
                           "malleability + differential fuzz.")
def test_pallas_verify_matches_jnp():
    """One 8-lane interpret run (grid 1) carrying the full verdict mix:
    valid, corrupted R/S/msg/A, small-order A, small-order R, and
    non-canonical S. Interpret-mode cost is dominated by the ~400-point-
    op program (not the lane count), so the edge cases ride the same
    kernel invocation instead of a second full run."""
    rng = np.random.default_rng(11)
    sig, pub, msg, ml = _mixed_batch(8, 32, rng)
    sig = np.array(sig)   # np.asarray over a jax array is a read-only view
    pub = np.array(pub)
    # lane 1 already corrupt-R, 2 corrupt-S, 3 corrupt-msg (mixed_batch);
    # overwrite lanes 5-7 with the structural edge cases:
    pub[5] = np.frombuffer((1).to_bytes(32, "little"), np.uint8)
    sig[6, :32] = np.frombuffer((1).to_bytes(32, "little"), np.uint8)
    s_big = (ed.L + 5).to_bytes(32, "little")
    sig[7, 32:] = np.frombuffer(s_big, np.uint8)
    sig, pub = jnp.asarray(sig), jnp.asarray(pub)
    want = np.asarray(ed.verify_batch(sig, pub, msg, ml))
    got = np.asarray(ped.verify_batch(sig, pub, msg, ml, tb=8,
                                      interpret=True))
    assert (want == got).all()
    assert want.any() and not want.all()   # mix of verdicts exercised
    assert not want[5] and not want[6] and not want[7]


@pytest.mark.skipif(os.environ.get("FDTPU_SLOW_TESTS") != "1",
                    reason="XLA compile of the interpret-mode sha512 "
                           "program takes tens of minutes on a 1-core "
                           "host when the persistent cache misses; opt "
                           "in with FDTPU_SLOW_TESTS=1. The jnp sha512 "
                           "path is CAVP-gated in test_sha2.py and the "
                           "Pallas kernel is exercised on hardware by "
                           "bench.py.")
def test_pallas_sha512_matches_hashlib():
    rng = np.random.default_rng(13)
    n, max_len = 8, 300
    msg = rng.integers(0, 256, (n, max_len), np.uint8)
    ln = rng.integers(0, max_len + 1, (n,)).astype(np.int32)
    for i, l in enumerate(ln):
        msg[i, l:] = 0
    out = np.asarray(psha.sha512(jnp.asarray(msg), jnp.asarray(ln),
                                 interpret=True))
    for i in range(n):
        want = hashlib.sha512(bytes(msg[i, : ln[i]])).digest()
        assert bytes(out[i]) == want


def test_scalar_row_helpers_match_jnp_reference():
    """The in-kernel byte→digit, mod-l reduction and window extraction
    (r5: moved from jnp glue into the fused kernel) are pure row
    functions — diff them directly against ops/ed25519.py."""
    rng = np.random.default_rng(11)
    b64 = rng.integers(0, 256, (64, 8), dtype=np.uint8).astype(np.int32)
    b32 = b64[:32]
    # byte -> digit conversion vs fe.frombytes (which masks bit 255)
    d = ped._bytes_to_digits(jnp.asarray(b32), ped.NL, mask_top7=True)
    want = np.asarray(ed.fe.frombytes(jnp.asarray(
        b32.T.astype(np.uint8)))).T
    np.testing.assert_array_equal(np.asarray(d), want)
    # 64-byte digits + mod-l reduction vs sc_reduce64
    kd = ped._sc_reduce_rows(
        ped._bytes_to_digits(jnp.asarray(b64), 40), 40)
    want_k = np.asarray(ed.sc_reduce64(jnp.asarray(
        b64.T.astype(np.uint8)))).T
    np.testing.assert_array_equal(np.asarray(kd), want_k)
    # window extraction vs sc_windows4
    sd, _ = ed.sc_from_bytes32(jnp.asarray(b32.T.astype(np.uint8)))
    got_w = np.concatenate(
        [np.asarray(ped._win4(ped._bytes_to_digits(
            jnp.asarray(b32), ped.NL), j)) for j in range(64)], axis=0)
    want_w = np.asarray(ed.sc_windows4(sd)).T
    np.testing.assert_array_equal(got_w, want_w)


def test_bytes_lt_matches_digit_compare():
    rng = np.random.default_rng(12)
    b = rng.integers(0, 256, (64, 32), dtype=np.uint8)
    # edge values around l and p
    b[0] = np.frombuffer(ed.L.to_bytes(32, "little"), np.uint8)
    b[1] = np.frombuffer((ed.L - 1).to_bytes(32, "little"), np.uint8)
    b[2] = np.frombuffer(ed.fe.P.to_bytes(32, "little"), np.uint8)
    b[3] = np.frombuffer((ed.fe.P - 1).to_bytes(32, "little"), np.uint8)
    b[4] = 0xFF
    got_s = np.asarray(ped._bytes_lt(jnp.asarray(b), ed.L))
    d, want_s = ed.sc_from_bytes32(jnp.asarray(b))
    np.testing.assert_array_equal(got_s, np.asarray(want_s))
    got_p = np.asarray(ped._bytes_lt(jnp.asarray(b), ed.fe.P,
                                     mask_top7=True))
    want_p = np.asarray(ed.fe.digits_lt(
        ed.fe.frombytes(jnp.asarray(b)), ed.fe.P_LIMBS))
    np.testing.assert_array_equal(got_p, np.asarray(want_p))


def test_verify_core_pure_matches_reference():
    """Run the ENTIRE fused kernel body as pure jnp on CPU (swapping
    the Mosaic roll for jnp.roll — bit-identical here since rotated-in
    rows are zeros) against the RFC 8032 oracle + jnp verify_batch:
    full-function validation without hardware or interpret mode."""
    rng = np.random.default_rng(13)
    n, msg_len = 16, 64
    sig, pub, msg, ln = _mixed_batch(n, msg_len, rng)
    want = np.asarray(ed.verify_batch(sig, pub, msg, ln))

    import hashlib as _h
    k64 = np.stack([
        np.frombuffer(_h.sha512(
            bytes(np.asarray(sig[i, :32])) + bytes(np.asarray(pub[i]))
            + bytes(np.asarray(msg[i]))).digest(), np.uint8)
        for i in range(n)])

    old = ped._ROLL
    ped._ROLL = lambda x, shift, axis: jnp.roll(x, shift, axis)
    try:
        ymx, ypx, t2d = ped._fb_tables()
        ok = ped._verify_core(
            jnp.asarray(np.asarray(pub).T.astype(np.int32)),
            jnp.asarray(np.asarray(sig[:, :32]).T.astype(np.int32)),
            jnp.asarray(k64.T.astype(np.int32)),
            jnp.asarray(np.asarray(sig[:, 32:]).T.astype(np.int32)),
            jnp.asarray(ymx), jnp.asarray(ypx), jnp.asarray(t2d))
    finally:
        ped._ROLL = old
    got = np.asarray(ok)[0] == 1
    # the kernel core omits the glue-side S/A/R canonicity masks; the
    # mixed batch has canonical S and non-small-order points, so the
    # core verdict must equal the full reference verdict here
    np.testing.assert_array_equal(got, want)
