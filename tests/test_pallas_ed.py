"""Differential tests: Pallas verify/sha kernels vs the jnp reference path.

Run in Pallas interpreter mode on the CPU backend (tile constraints
relaxed), tiny batches — the full Wycheproof/malleability gates run
against the jnp implementation, and these tests pin the Pallas kernels
to it bit-for-bit. On real TPU hardware the same comparison runs
compiled (see tools/profile_kernel*.py and bench.py).
"""
import hashlib
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from firedancer_tpu.ops import ed25519 as ed  # noqa: E402
from firedancer_tpu.ops import pallas_ed as ped  # noqa: E402
from firedancer_tpu.ops import pallas_sha as psha  # noqa: E402
from firedancer_tpu.utils import ed25519_ref as ref  # noqa: E402


def _mixed_batch(n, msg_len, rng):
    """Valid sigs with a spread of corruptions (sig, pub, msg, edge cases)."""
    sigs, pubs, msgs = [], [], []
    for i in range(n):
        seed = rng.bytes(32)
        _, _, pk = ref.keypair(seed)
        m = rng.bytes(msg_len)
        s = ref.sign(seed, m)
        if i % 5 == 1:
            s = bytes([s[0] ^ 1]) + s[1:]           # corrupt R
        elif i % 5 == 2:
            s = s[:32] + bytes([s[32] ^ 1]) + s[33:]  # corrupt S
        elif i % 5 == 3:
            m = m[:-1] + bytes([m[-1] ^ 0x80])      # corrupt msg
        elif i % 5 == 4 and i % 2 == 0:
            pk = bytes([pk[0] ^ 1]) + pk[1:]        # corrupt A
        sigs.append(np.frombuffer(s, np.uint8))
        pubs.append(np.frombuffer(pk, np.uint8))
        msgs.append(np.frombuffer(m, np.uint8))
    return (jnp.asarray(np.stack(sigs)), jnp.asarray(np.stack(pubs)),
            jnp.asarray(np.stack(msgs)),
            jnp.full((n,), msg_len, jnp.int32))


@pytest.mark.skipif(os.environ.get("FDTPU_SLOW_TESTS") != "1",
                    reason="interpret-mode full-verify takes hours on a "
                           "1-core host; opt in with FDTPU_SLOW_TESTS=1. "
                           "The kernel is gated on hardware instead: "
                           "bench.py asserts every vector verifies on "
                           "the TPU backend, and the jnp reference path "
                           "it is pinned to passes Wycheproof + "
                           "malleability + differential fuzz.")
def test_pallas_verify_matches_jnp():
    """One 8-lane interpret run (grid 1) carrying the full verdict mix:
    valid, corrupted R/S/msg/A, small-order A, small-order R, and
    non-canonical S. Interpret-mode cost is dominated by the ~400-point-
    op program (not the lane count), so the edge cases ride the same
    kernel invocation instead of a second full run."""
    rng = np.random.default_rng(11)
    sig, pub, msg, ml = _mixed_batch(8, 32, rng)
    sig = np.array(sig)   # np.asarray over a jax array is a read-only view
    pub = np.array(pub)
    # lane 1 already corrupt-R, 2 corrupt-S, 3 corrupt-msg (mixed_batch);
    # overwrite lanes 5-7 with the structural edge cases:
    pub[5] = np.frombuffer((1).to_bytes(32, "little"), np.uint8)
    sig[6, :32] = np.frombuffer((1).to_bytes(32, "little"), np.uint8)
    s_big = (ed.L + 5).to_bytes(32, "little")
    sig[7, 32:] = np.frombuffer(s_big, np.uint8)
    sig, pub = jnp.asarray(sig), jnp.asarray(pub)
    want = np.asarray(ed.verify_batch(sig, pub, msg, ml))
    got = np.asarray(ped.verify_batch(sig, pub, msg, ml, tb=8,
                                      interpret=True))
    assert (want == got).all()
    assert want.any() and not want.all()   # mix of verdicts exercised
    assert not want[5] and not want[6] and not want[7]


@pytest.mark.skipif(os.environ.get("FDTPU_SLOW_TESTS") != "1",
                    reason="XLA compile of the interpret-mode sha512 "
                           "program takes tens of minutes on a 1-core "
                           "host when the persistent cache misses; opt "
                           "in with FDTPU_SLOW_TESTS=1. The jnp sha512 "
                           "path is CAVP-gated in test_sha2.py and the "
                           "Pallas kernel is exercised on hardware by "
                           "bench.py.")
def test_pallas_sha512_matches_hashlib():
    rng = np.random.default_rng(13)
    n, max_len = 8, 300
    msg = rng.integers(0, 256, (n, max_len), np.uint8)
    ln = rng.integers(0, max_len + 1, (n,)).astype(np.int32)
    for i, l in enumerate(ln):
        msg[i, l:] = 0
    out = np.asarray(psha.sha512(jnp.asarray(msg), jnp.asarray(ln),
                                 interpret=True))
    for i in range(n):
        want = hashlib.sha512(bytes(msg[i, : ln[i]])).digest()
        assert bytes(out[i]) == want
