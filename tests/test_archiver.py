"""Archiver record/replay tests: capture a live verify-pipeline stream,
then re-drive the SAME downstream tiles from the file and get identical
results — the deterministic-replay CI tier (ref: src/disco/archiver/
fd_archiver.h:1-20; SURVEY §4 tier 10)."""
import pytest

pytestmark = pytest.mark.slow
import os

from firedancer_tpu.disco import Topology, TopologyRunner

N = 24


def test_record_then_replay_identical(tmp_path):
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    path = tmp_path / "stream.arch"

    # phase 1: record the synth stream while verify consumes it live
    topo = (
        Topology(f"ar{os.getpid()}", wksp_size=1 << 23)
        .link("ingest", depth=64, mtu=1280)
        .link("verify_out", depth=64, mtu=1280)
        .tcache("tc", depth=4096)
        .tile("synth", "synth", outs=["ingest"], count=N, unique=N,
              seed=13)
        .tile("verify", "verify", ins=["ingest"], outs=["verify_out"],
              batch=16, tcache="tc")
        .tile("rec", "archiver", ins=[("ingest", False)],
              path=str(path))
        .tile("sink", "sink", ins=["verify_out"])
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        runner.wait_idle("sink", "rx", N, timeout_s=540)
        runner.wait_idle("rec", "frags", N, timeout_s=60)
        live_tx = runner.metrics("verify")["tx"]
        assert runner.metrics("rec")["overruns"] == 0
    finally:
        runner.halt()
        runner.close()
    assert path.exists() and path.stat().st_size > 0

    # phase 2: re-drive verify purely from the recording
    topo2 = (
        Topology(f"ar2{os.getpid()}", wksp_size=1 << 23)
        .link("ingest", depth=64, mtu=1280)
        .link("verify_out", depth=64, mtu=1280)
        .tcache("tc", depth=4096)
        .tile("play", "playback", outs=["ingest"], path=str(path))
        .tile("verify", "verify", ins=["ingest"], outs=["verify_out"],
              batch=16, tcache="tc")
        .tile("sink", "sink", ins=["verify_out"])
    )
    runner2 = TopologyRunner(topo2.build()).start()
    try:
        runner2.wait_running(timeout_s=540)
        runner2.wait_idle("play", "done", 1, timeout_s=120)
        runner2.wait_idle("sink", "rx", live_tx, timeout_s=120)
        assert runner2.metrics("play")["frags"] == N
        v = runner2.metrics("verify")
        assert v["rx"] == N
        assert v["tx"] == live_tx          # byte-identical re-drive
        assert v["verify_fail"] == 0
    finally:
        runner2.halt()
        runner2.close()
