"""Exec tile family + resolv tile + shm funk store (r16).

The bank's execution stage moves out-of-process: the bank partitions
each gathered wave into account-disjoint conflict groups, ships them
over dedicated rings to N exec tiles that execute against the
shm-resident funk store at the fork the bank prepared, and publishes
the fork only after every dispatch frame completed. These suites pin:

* the [funk] registry mirror (lint/registry.py vs funk/shmfunk.py),
* the conflict-group partition invariants,
* byte-identity of the fan-out path's poh/done egress vs the
  in-process svm wave path (same frames in, same bytes out),
* cross-tile conflict isolation on the wire (no account appears in
  two tiles' dispatch frames),
* the supervision drill: an exec tile dying mid-wave (its frames
  lost) leads to cancel + whole-wave redispatch under a fresh fork —
  exactly-once application, no wedged producer,
* the resolv tile's RESOLVED egress vs pack's meta_from_payload.
"""
import hashlib
import os
import struct
import time
from types import SimpleNamespace

import pytest

from firedancer_tpu.runtime import Ring, Store, Workspace

pytestmark = pytest.mark.exec

os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")


# ---------------------------------------------------------------------------
# registry mirror + conflict groups
# ---------------------------------------------------------------------------

def test_funk_registry_mirrors_defaults():
    """lint/registry.py FUNK_SECTION_KEYS is the static mirror of
    funk/shmfunk.py FUNK_DEFAULTS (the bad-funk rule and the config
    gate both trust it)."""
    from firedancer_tpu.funk.shmfunk import (FUNK_BACKENDS,
                                             FUNK_DEFAULTS,
                                             normalize_funk)
    from firedancer_tpu.lint.registry import FUNK_SECTION_KEYS
    assert set(FUNK_SECTION_KEYS) == set(FUNK_DEFAULTS)
    assert FUNK_DEFAULTS["backend"] in FUNK_BACKENDS
    with pytest.raises(ValueError, match="did you mean"):
        normalize_funk({"bakend": "shm"})
    with pytest.raises(ValueError, match="backend"):
        normalize_funk({"backend": "sm"})
    cfg = normalize_funk({"backend": "shm", "heap_mb": 4})
    assert cfg["rec_max"] == FUNK_DEFAULTS["rec_max"]


def test_conflict_groups_partition():
    """Union-find partition: transitively-linked transfers share one
    group (in original order); groups are pairwise account-disjoint."""
    from firedancer_tpu.disco.tiles import _conflict_groups
    from firedancer_tpu.svm.executor import SystemTxn
    k = [bytes([i]) * 32 for i in range(8)]
    txns = [
        SystemTxn(src=k[0], dst=k[1], amount=1, fee=0),   # g0
        SystemTxn(src=k[2], dst=k[3], amount=2, fee=0),   # g1
        SystemTxn(src=k[1], dst=k[4], amount=3, fee=0),   # g0 (via k1)
        SystemTxn(src=k[5], dst=k[6], amount=4, fee=0),   # g2
        SystemTxn(src=k[4], dst=k[0], amount=5, fee=0),   # g0 (via k4)
        SystemTxn(src=k[6], dst=k[7], amount=6, fee=0),   # g2 (via k6)
    ]
    groups = _conflict_groups(txns)
    assert sorted(len(g) for g in groups) == [1, 2, 3]
    accts = [set(x for t in g for x in (t.src, t.dst)) for g in groups]
    for i in range(len(groups)):
        for j in range(i + 1, len(groups)):
            assert not (accts[i] & accts[j])
    big = next(g for g in groups if len(g) == 3)
    assert [t.amount for t in big] == [1, 3, 5]   # original order kept


# ---------------------------------------------------------------------------
# in-process harness: bank + N exec adapters over real rings
# ---------------------------------------------------------------------------

def _mk_family(wksp, n_exec=2, redispatch_s=5.0, genesis=None,
               disp_mtu=4096):
    from firedancer_tpu.disco.tiles import BankAdapter, ExecAdapter
    st = Store(wksp, rec_max=4096, txn_max=64, heap_sz=1 << 20)
    funk_plan = {"backend": "shm", "rec_max": 4096, "txn_max": 64,
                 "heap_mb": 1, "off": st.off, "heap_sz": 1 << 20}
    links = {"pack_bank0": {"mtu": 1 << 15},
             "bank0_done": {"mtu": 64},
             "bank0_poh": {"mtu": 1 << 16}}
    for i in range(n_exec):
        links[f"exec_disp{i}"] = {"mtu": disp_mtu}
        links[f"exec_done{i}"] = {"mtu": 64}
    rings = {ln: Ring.create(wksp, depth=64, mtu=li["mtu"])
             for ln, li in links.items()}
    plan = {"links": links, "funk": funk_plan}
    bank_ctx = SimpleNamespace(
        tile_name="bank0", plan=plan, wksp=wksp,
        in_rings={"pack_bank0": rings["pack_bank0"],
                  **{f"exec_done{i}": rings[f"exec_done{i}"]
                     for i in range(n_exec)}},
        out_rings={"bank0_done": rings["bank0_done"],
                   "bank0_poh": rings["bank0_poh"],
                   **{f"exec_disp{i}": rings[f"exec_disp{i}"]
                      for i in range(n_exec)}},
        out_fseqs={ln: [] for ln in links},
        in_seq0={})
    bank = BankAdapter(bank_ctx, {
        "exec": "svm", "wave": 8, "poh_link": "bank0_poh",
        "exec_links": [f"exec_disp{i}" for i in range(n_exec)],
        "exec_done": [f"exec_done{i}" for i in range(n_exec)],
        "genesis": genesis or {}, "forward_payloads": True,
        "redispatch_s": redispatch_s})
    execs = []
    for i in range(n_exec):
        ctx = SimpleNamespace(
            tile_name=f"exec{i}", plan=plan, wksp=wksp,
            in_rings={f"exec_disp{i}": rings[f"exec_disp{i}"]},
            out_rings={f"exec_done{i}": rings[f"exec_done{i}"]},
            out_fseqs={f"exec_done{i}": []},
            in_seq0={})
        execs.append(ExecAdapter(ctx, {"batch": 8}))
    return bank, execs, rings


def _microblocks(txns, per=6, slot=3):
    frames = []
    for mb_id in range(0, len(txns), per):
        chunk = txns[mb_id:mb_id + per]
        body = b"".join(struct.pack("<H", len(p)) + p for p in chunk)
        frames.append(struct.pack("<HHQQ", 0, len(chunk),
                                  mb_id // per, slot) + body)
    return frames


def _synth_genesis(n=16):
    from firedancer_tpu.tiles.synth import synth_signer_seed
    from firedancer_tpu.utils.ed25519_ref import keypair
    return {keypair(synth_signer_seed(i))[-1].hex(): 1 << 44
            for i in range(n)}


def _drain(ring, seq=0):
    out = []
    while True:
        rc, frag = ring.consume(seq)
        if rc != 0:
            break
        out.append((bytes(ring.payload(frag)), frag.sig))
        seq += 1
    return out, seq


@pytest.fixture()
def wksp():
    w = Workspace(f"/fdtpu_ext_{os.getpid()}", 1 << 24)
    yield w
    w.close()
    w.unlink()


def test_exec_family_byte_identity_vs_in_process(wksp):
    """Same microblock frames through (a) the in-process svm wave path
    and (b) the exec fan-out over 2 tiles: every poh frame, every done
    frag, and every touched balance is IDENTICAL — the fan-out is a
    pure throughput change."""
    from firedancer_tpu.disco.tiles import BankAdapter
    from firedancer_tpu.tiles.synth import make_signed_txns
    genesis = _synth_genesis()
    frames = _microblocks(make_signed_txns(18, seed=77), per=6)

    # (a) in-process oracle bank
    links = {"pb": {"mtu": 1 << 15}, "dn": {"mtu": 64},
             "ph": {"mtu": 1 << 16}}
    rings_a = {ln: Ring.create(wksp, depth=64, mtu=li["mtu"])
               for ln, li in links.items()}
    ctx_a = SimpleNamespace(
        tile_name="bankA", plan={"links": links},
        in_rings={"pb": rings_a["pb"]},
        out_rings={"dn": rings_a["dn"], "ph": rings_a["ph"]},
        out_fseqs={"dn": [], "ph": []}, in_seq0={})
    bank_a = BankAdapter(ctx_a, {
        "exec": "svm", "wave": 8, "poh_link": "ph",
        "genesis": genesis, "forward_payloads": True})
    for i, f in enumerate(frames):
        rings_a["pb"].publish(f, sig=i)
    bank_a.poll_once()
    bank_a.poll_once()            # drain-on-idle retires the wave

    # (b) the exec tile family
    bank, execs, rings = _mk_family(wksp, n_exec=2, genesis=genesis)
    for i, f in enumerate(frames):
        rings["pack_bank0"].publish(f, sig=i)
    bank.poll_once()
    assert bank.fanout.wave is not None \
        and bank.fanout.wave["remaining"] >= 1
    for e in execs:
        e.poll_once()
    bank.poll_once()
    assert bank.fanout.wave is None

    assert bank.m["transfers"] == bank_a.m["transfers"] > 0
    assert bank.m["exec_fail"] == bank_a.m["exec_fail"]
    got_poh, _ = _drain(rings["bank0_poh"])
    want_poh, _ = _drain(rings_a["ph"])
    assert got_poh == want_poh           # bytes AND sigs, in order
    got_dn, _ = _drain(rings["bank0_done"])
    want_dn, _ = _drain(rings_a["dn"])
    assert got_dn == want_dn
    for hex_key in genesis:
        k = bytes.fromhex(hex_key)
        assert bank.funk.rec_query(None, k) \
            == bank_a.funk.rec_query(None, k)
    # both exec tiles actually carried work
    assert all(e.m["txns"] > 0 for e in execs)


def test_exec_cross_tile_conflict_isolation(wksp):
    """On the wire: no account key appears in two different tiles'
    dispatch frames (conflict groups are account-disjoint across
    tiles), a conflict CHAIN lands on one tile in order, and the final
    balances match the serial oracle despite the cross-frame
    conflicts."""
    from firedancer_tpu.svm.executor import execute_block_serial
    keys = [hashlib.sha256(b"ct%d" % i).digest() for i in range(9)]
    genesis = {k.hex(): 1_000_000 for k in keys}
    bank, execs, rings = _mk_family(wksp, n_exec=2, genesis=genesis)
    # chain: k0->k1->k2->k3 (conflicting, order-sensitive) + disjoint
    # pairs k4->k5, k6->k7, k8->k8
    from firedancer_tpu.svm.executor import SystemTxn
    txns = [
        SystemTxn(src=keys[0], dst=keys[1], amount=900_000, fee=0),
        SystemTxn(src=keys[1], dst=keys[2], amount=1_800_000, fee=0),
        SystemTxn(src=keys[2], dst=keys[3], amount=2_000_000, fee=0),
        SystemTxn(src=keys[4], dst=keys[5], amount=5, fee=7),
        SystemTxn(src=keys[6], dst=keys[7], amount=11, fee=0),
        SystemTxn(src=keys[8], dst=keys[8], amount=13, fee=0),
    ]
    # inject directly at the scheduler layer (the wire carries raw
    # payloads; here the partition itself is under test)
    bank.fanout.dispatch(txns, tag=[])
    per_tile_accts = []
    chain_frames = []
    from firedancer_tpu.disco.tiles import (_EXEC_HDR, _EXEC_TXN,
                                            _EXEC_TXN_SZ)
    for i in range(2):
        accts = set()
        frames, _ = _drain(rings[f"exec_disp{i}"])
        for frame, _sig in frames:
            ws, xid, cnt = _EXEC_HDR.unpack_from(frame, 0)
            off = _EXEC_HDR.size
            for _ in range(cnt):
                src = frame[off:off + 32]
                dst = frame[off + 32:off + 64]
                amt, _fee = _EXEC_TXN.unpack_from(frame, off + 64)
                accts |= {src, dst}
                if src in keys[:4]:
                    chain_frames.append((i, amt))
                off += _EXEC_TXN_SZ
        per_tile_accts.append(accts)
    assert per_tile_accts[0] and per_tile_accts[1]
    assert not (per_tile_accts[0] & per_tile_accts[1])
    # the whole chain went to ONE tile, in original order
    assert len({t for t, _ in chain_frames}) == 1
    assert [a for _, a in chain_frames] \
        == [900_000, 1_800_000, 2_000_000]
    for e in execs:
        e.poll_once()
    bank.poll_once()
    assert bank.fanout.wave is None
    oracle = {k: 1_000_000 for k in keys}
    execute_block_serial(oracle, txns)
    for k in keys:
        assert bank.funk.rec_query(None, k) == oracle[k]


def test_exec_tile_death_redispatch_drill(wksp):
    """Supervision drill, in-process: the exec tile 'dies' mid-wave —
    its dispatch frames are never executed (a supervised restart
    rejoins at the ring TAIL, skipping them) — so the bank times out,
    CANCELS the fork (store back to pre-wave state) and re-dispatches
    the whole wave under a fresh fork. The restarted tile abandons any
    stale frames it does see (cancelled fork -> no completion) and
    completes the fresh ones: exactly-once application, no wedge."""
    from firedancer_tpu.disco.tiles import ExecAdapter
    from firedancer_tpu.svm.executor import execute_block_serial
    from firedancer_tpu.tiles.synth import make_signed_txns
    genesis = _synth_genesis()
    bank, execs, rings = _mk_family(wksp, n_exec=2,
                                    redispatch_s=30.0,
                                    genesis=genesis)
    txns = make_signed_txns(12, seed=91)
    for i, f in enumerate(_microblocks(txns, per=6)):
        rings["pack_bank0"].publish(f, sig=i)
    bank.poll_once()
    assert bank.fanout.wave is not None
    xid1 = bank.fanout.wave["xid"]
    # tile 0 'dies': nobody drains exec_disp0. Tile 1 completes its
    # share — the wave must NOT publish on a partial completion set.
    execs[1].poll_once()
    bank.poll_once()
    assert bank.fanout.wave is not None \
        and bank.fanout.wave["xid"] == xid1
    # mid-wave store state is invisible at the root
    root0 = {bytes.fromhex(k): bank.funk.rec_query(
        None, bytes.fromhex(k)) for k in genesis}
    assert root0 == {bytes.fromhex(k): v for k, v in genesis.items()}
    # timeout (forced, no wall-clock flake) -> cancel + redispatch
    # under a fresh fork
    bank.fanout.wave["deadline"] = time.monotonic() - 1
    bank.poll_once()
    assert bank.m["exec_redispatch"] == 1
    assert bank.fanout.wave is not None \
        and bank.fanout.wave["xid"] != xid1
    assert not bank.funk.txn_is_prepared(xid1)
    # 'restart': fresh adapters from seq 0 — they see the STALE frames
    # first (cancelled fork -> abandoned, no completion), then the
    # fresh ones
    stale = 0
    for i in range(2):
        ctx = SimpleNamespace(
            tile_name=f"exec{i}r", plan=execs[i].ctx.plan, wksp=wksp,
            in_rings={f"exec_disp{i}": rings[f"exec_disp{i}"]},
            out_rings={f"exec_done{i}": rings[f"exec_done{i}"]},
            out_fseqs={f"exec_done{i}": []},
            in_seq0={})
        e = ExecAdapter(ctx, {"batch": 16})
        e.poll_once()
        stale += e.m["stale_xid"]
    assert stale >= 1      # cancelled-fork frames replayed, abandoned
    deadline = time.monotonic() + 10
    while bank.fanout.wave is not None \
            and time.monotonic() < deadline:
        bank.poll_once()
    assert bank.fanout.wave is None        # not wedged
    assert bank.m["exec_redispatch"] == 1
    # exactly-once: balances match ONE serial application
    all_t = []
    for f in _microblocks(txns, per=6):
        t, _ = bank._parse_transfers(f, struct.unpack_from(
            "<HHQQ", f)[1])
        all_t.extend(t)
    oracle = {bytes.fromhex(k): v for k, v in genesis.items()}
    execute_block_serial(oracle, all_t)
    for k, v in oracle.items():
        assert bank.funk.rec_query(None, k) == v
    # done + poh flushed exactly once per microblock
    assert rings["bank0_done"].seq == 2
    assert rings["bank0_poh"].seq == 2


# ---------------------------------------------------------------------------
# resolv tile
# ---------------------------------------------------------------------------

def _mk_resolv(wksp, funk_plan=None, **args):
    from firedancer_tpu.disco.tiles import ResolvAdapter
    links = {"dr": {"mtu": 1280}, "rp": {"mtu": 2048}}
    rings = {ln: Ring.create(wksp, depth=64, mtu=li["mtu"])
             for ln, li in links.items()}
    plan = {"links": links}
    if funk_plan:
        plan["funk"] = funk_plan
    ctx = SimpleNamespace(
        tile_name="resolv", plan=plan, wksp=wksp,
        in_rings={"dr": rings["dr"]}, out_rings={"rp": rings["rp"]},
        out_fseqs={"rp": []}, in_seq0={})
    return ResolvAdapter(ctx, args), rings


def test_resolv_resolved_frames_match_meta_from_payload(wksp):
    """For legacy txns the resolv tile's RESOLVED frame decodes (via
    pack's meta_from_resolved) to the SAME scheduling inputs
    meta_from_payload computes from the raw payload — account sets,
    cost, reward, vote flag."""
    from firedancer_tpu.pack.scheduler import (meta_from_payload,
                                               meta_from_resolved)
    from firedancer_tpu.tiles.synth import make_signed_txns
    tile, rings = _mk_resolv(wksp)
    assert tile.db is None and not tile.fee_check
    txns = make_signed_txns(8, seed=13)
    for i, p in enumerate(txns):
        rings["dr"].publish(p, sig=i)
    tile.poll_once()
    assert tile.m["resolved"] == len(txns)
    frames, _ = _drain(rings["rp"])
    assert len(frames) == len(txns)
    for (frame, _sig), payload in zip(frames, txns):
        got = meta_from_resolved(frame)
        want = meta_from_payload(payload)
        assert got.payload == want.payload == payload
        assert got.writes == want.writes
        assert got.reads == want.reads
        assert (got.cost, got.reward, got.is_vote) \
            == (want.cost, want.reward, want.is_vote)


def test_resolv_fee_payer_gate_and_junk(wksp):
    """With the shm store joined, a fee payer below the signature fee
    drops (fee_fail); funded payers pass; junk bytes count
    parse_fail."""
    from firedancer_tpu.pack.scheduler import FEE_PER_SIGNATURE
    from firedancer_tpu.tiles.synth import make_signed_txns
    st = Store(wksp, rec_max=512, txn_max=16, heap_sz=1 << 18)
    funk_plan = {"backend": "shm", "rec_max": 512, "txn_max": 16,
                 "heap_mb": 1, "off": st.off, "heap_sz": 1 << 18}
    tile, rings = _mk_resolv(wksp, funk_plan=funk_plan)
    assert tile.db is not None and tile.fee_check
    txns = make_signed_txns(4, seed=29)
    # fund the first two txns' fee payers only
    from firedancer_tpu.protocol.txn import parse_txn
    for p in txns[:2]:
        t = parse_txn(p)
        tile.db.funk.rec_write(None, t.account_keys(p)[0],
                               FEE_PER_SIGNATURE * 4)
    for i, p in enumerate(txns):
        rings["dr"].publish(p, sig=i)
    rings["dr"].publish(b"\x00junk", sig=99)
    tile.poll_once()
    assert tile.m["parse_fail"] == 1
    assert tile.m["resolved"] + tile.m["fee_fail"] == len(txns)
    assert tile.m["fee_fail"] >= 1
    frames, _ = _drain(rings["rp"])
    assert len(frames) == tile.m["resolved"]


# ---------------------------------------------------------------------------
# the full topology: sharded exec family, resolv ahead of pack,
# supervised exec restart under fire (the process-level drill)
# ---------------------------------------------------------------------------

def _family_topology(name, n=24, exec_cnt=2, chaos0=None,
                     redispatch_s=1.0):
    from firedancer_tpu.disco import Topology
    genesis = _synth_genesis()
    topo = (
        Topology(name, wksp_size=1 << 26,
                 funk={"backend": "shm", "heap_mb": 4})
        .link("ingest", depth=128, mtu=1280)
        .link("vd0", depth=128, mtu=1280)
        .link("dedup_resolv", depth=128, mtu=1280)
        .link("resolv_pack", depth=128, mtu=2048)
        .link("pack_bank0", depth=32, mtu=1 << 15)
        .link("bank0_done", depth=32, mtu=64)
        .link("bank0_poh", depth=64, mtu=1 << 16)
        .link("poh_entries", depth=256, mtu=(1 << 16) + 128)
        .link("poh_slots", depth=64, mtu=64)
        .tcache("vtc0", depth=4096).tcache("dedup_tc", depth=4096)
        # unique == count: every frame distinct (the deep tcaches would
        # dedup pool replays — this drill counts executed transfers,
        # not dedup behavior); signer seeds cycle mod 16, so the
        # 16-key genesis still funds every fee payer
        .tile("synth", "synth", outs=["ingest"], count=n, unique=n,
              seed=6)
        .tile("verify0", "verify", ins=["ingest"], outs=["vd0"],
              batch=16, tcache="vtc0")
        .tile("dedup", "dedup", ins=["vd0"], outs=["dedup_resolv"],
              tcache="dedup_tc")
        .tile("resolv", "resolv", ins=["dedup_resolv"],
              outs=["resolv_pack"], fee_payer_check=False)
        .tile("pack", "pack",
              ins=["resolv_pack", ("bank0_done", False),
                   ("poh_slots", False)],
              outs=["pack_bank0"], txn_in="resolv_pack",
              resolved_in=True, bank_links=["pack_bank0"],
              done_links=["bank0_done"], slot_in="poh_slots",
              max_txn_per_microblock=8, wave=4))
    disp = [f"exec_disp{i}" for i in range(exec_cnt)]
    done = [f"exec_done{i}" for i in range(exec_cnt)]
    for ln in disp:
        topo.link(ln, depth=64, mtu=4096)
    for ln in done:
        topo.link(ln, depth=64, mtu=64)
    topo.tile("bank0", "bank",
              ins=["pack_bank0"] + [(ln, False) for ln in done],
              outs=["bank0_done", "bank0_poh"] + disp,
              exec="svm", wave=4, poh_link="bank0_poh",
              exec_links=disp, exec_done=done, genesis=genesis,
              redispatch_s=redispatch_s)
    exec_args = {}
    if chaos0 is not None:
        exec_args["chaos"] = chaos0
        exec_args["supervise"] = {"policy": "restart",
                                  "backoff_s": 0.05,
                                  "max_restarts": 3, "window_s": 60.0}
    topo.sharded_tile("exec", "exec", exec_cnt, ins=[disp],
                      outs=done, batch=8, **exec_args)
    topo.tile("poh", "poh", ins=["bank0_poh"],
              outs=["poh_entries", "poh_slots"],
              slot_link="poh_slots", hashes_per_tick=16,
              ticks_per_slot=4)
    topo.tile("entsink", "sink", ins=["poh_entries"])
    return topo, genesis


def test_family_topology_builds_and_lints():
    """Topology-level wiring: sharded exec tiles get ONE dispatch ring
    each (per-shard ins distribution), topo.build carves the shm store
    into the plan, and the static linter accepts the model with zero
    errors."""
    topo, _ = _family_topology(f"efb{os.getpid()}", exec_cnt=2)
    for i in range(2):
        t = topo.tiles[f"exec{i}"]
        assert [x["link"] for x in t.ins] == [f"exec_disp{i}"]
        assert t.outs == [f"exec_done{i}"]
        assert t.args["rr_cnt"] == 2 and t.args["rr_idx"] == i
    from firedancer_tpu.lint.graph import lint_topology
    assert not [f for f in lint_topology(topo)
                if f.severity == "error"]
    plan = topo.build()
    try:
        assert plan["funk"]["backend"] == "shm"
        assert plan["funk"]["off"] > 0
        assert plan["funk"]["heap_sz"] == 4 << 20
    finally:
        from firedancer_tpu.runtime import Workspace as _W
        _W(plan["wksp"]["name"], plan["wksp"]["size"]).unlink()


@pytest.mark.slow
def test_exec_family_leader_loop_with_supervised_kill():
    """The process-level supervision drill: the full leader loop with
    resolv + exec_tile_cnt=2, where exec0 CRASHES mid-stream (seeded
    chaos) and the restart policy respawns it. The bank's redispatch
    path re-runs any wave the dead tile dropped: every funded transfer
    applies exactly once (balances match the serial oracle), the loop
    drains completely, and nobody wedges."""
    from firedancer_tpu.disco import TopologyRunner
    from firedancer_tpu.svm.executor import execute_block_serial
    n = 24
    topo, genesis = _family_topology(
        f"efk{os.getpid()}", n=n, exec_cnt=2,
        chaos0=[{"seed": 1, "events": [{"action": "crash",
                                        "at_rx": 1}]},
                None])
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            runner.check_failures()
            b = runner.metrics("bank0")
            if b["transfers"] >= n and runner.metrics("poh")["mixins"] \
                    == b["microblocks"] and b["microblocks"] > 0:
                break
            time.sleep(0.05)
        b = runner.metrics("bank0")
        assert b["transfers"] == n and b["exec_fail"] == 0
        e0 = runner.metrics("exec0")
        assert e0["sup_restarts"] >= 1         # the drill actually fired
        assert runner.metrics("resolv")["resolved"] == n
        assert runner.metrics("pack")["inserted"] == n
    finally:
        runner.halt()
        runner.close()


@pytest.mark.slow
def test_exec_family_leader_loop_clean():
    """exec_tile_cnt=2, no faults: the full loop executes every funded
    transfer exactly once and BOTH exec shards carry traffic."""
    from firedancer_tpu.disco import TopologyRunner
    n = 24
    # generous redispatch: a cold exec tile's first wave can take
    # seconds on a loaded 1-core box, and this test asserts ZERO
    # redispatches — only the kill drill wants a twitchy deadline
    topo, _ = _family_topology(f"efc{os.getpid()}", n=n, exec_cnt=2,
                               redispatch_s=60.0)
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            runner.check_failures()
            b = runner.metrics("bank0")
            if b["transfers"] >= n and runner.metrics("poh")["mixins"] \
                    == b["microblocks"] and b["microblocks"] > 0:
                break
            time.sleep(0.05)
        b = runner.metrics("bank0")
        assert b["transfers"] == n and b["exec_fail"] == 0
        assert b["exec_redispatch"] == 0
        ex = [runner.metrics(f"exec{i}") for i in range(2)]
        assert sum(e["txns"] for e in ex) >= n
        assert all(e["stale_xid"] == 0 for e in ex)
    finally:
        runner.halt()
        runner.close()
