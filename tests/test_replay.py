"""rdisp conflict-DAG + wave executor: serial-fiction equivalence tests.

The gate from VERDICT r3 item 3: randomized blocks with heavy account
conflicts must replay bit-identically to the serial oracle, across funk
forks, in both consumption modes (dispatcher and wave-scan).
"""
import numpy as np
import pytest

from firedancer_tpu.replay import ConflictDag, TxnState
from firedancer_tpu.replay.rdisp import StagedDispatcher
from firedancer_tpu.funk import Funk
from firedancer_tpu.svm import (SystemTxn, execute_block,
                                execute_block_serial, STATUS_OK,
                                STATUS_INSUFFICIENT, STATUS_FEE_FAIL)


def _rand_block(rng, n_txn, n_acct, hot_frac=0.5):
    """Conflict-heavy random block: a few hot accounts appear in half the
    txns, so the DAG has long chains AND wide waves."""
    keys = [bytes([i]) * 32 for i in range(n_acct)]
    hot = keys[: max(1, n_acct // 8)]
    txns = []
    for _ in range(n_txn):
        pool = hot if rng.random() < hot_frac else keys
        src = pool[rng.integers(len(pool))]
        dst = keys[rng.integers(len(keys))]
        txns.append(SystemTxn(src, dst,
                              int(rng.integers(0, 2_000)),
                              int(rng.integers(0, 10))))
    return keys, txns


def test_dag_edges_and_dispatcher_serial_fiction():
    rng = np.random.default_rng(5)
    for trial in range(20):
        keys, txns = _rand_block(rng, 60, 16)
        dag = ConflictDag()
        for t in txns:
            dag.add_txn(writes=(t.src, t.dst), reads=())
        # dispatcher mode: drain in ready order, simulate execution
        balances = {k: 10_000 for k in keys[:8]}
        got_status = [None] * len(txns)
        order = []
        while not dag.done:
            i = dag.next_ready()
            assert i is not None, "DAG stalled with work remaining"
            order.append(i)
            dag.complete(i)
        # executing in `order` must equal serial execution: replay both
        ser_bal = dict(balances)
        want = execute_block_serial(ser_bal, txns)
        got_bal = dict(balances)
        for i in sorted(range(len(txns)),
                        key=order.index):  # execution order
            got_status[i] = execute_block_serial(got_bal, [txns[i]])[0]
        assert got_bal == ser_bal
        assert got_status == want


def test_wave_levels_are_conflict_free():
    rng = np.random.default_rng(6)
    keys, txns = _rand_block(rng, 80, 12)
    dag = ConflictDag()
    for t in txns:
        dag.add_txn(writes=(t.src, t.dst), reads=())
    waves = dag.waves()
    assert sum(len(w) for w in waves) == len(txns)
    for w in waves:
        seen = set()
        for i in w:
            accts = {txns[i].src, txns[i].dst}
            assert not (accts & seen), "conflicting txns in one wave"
            seen |= accts


def test_read_write_edges():
    dag = ConflictDag()
    a, b = b"a" * 32, b"b" * 32
    t0 = dag.add_txn(writes=(a,), reads=())
    t1 = dag.add_txn(writes=(), reads=(a,))
    t2 = dag.add_txn(writes=(), reads=(a,))
    t3 = dag.add_txn(writes=(a,), reads=())     # waits for both readers
    waves = dag.waves()
    assert waves[0] == [t0]
    assert sorted(waves[1]) == [t1, t2]          # readers parallel
    assert waves[2] == [t3]


def test_wave_executor_matches_serial_oracle():
    rng = np.random.default_rng(7)
    for trial in range(8):
        keys, txns = _rand_block(rng, 100, 20)
        funk = Funk()
        # seed root balances
        funk.txn_prepare(None, "seed")
        for i, k in enumerate(keys):
            if i % 3 != 2:
                funk.rec_write("seed", k, int(rng.integers(0, 50_000)))
        funk.txn_publish("seed")

        seed_bal = {k: funk.rec_query(None, k) for k in keys
                    if funk.rec_query(None, k) is not None}
        want_bal = dict(seed_bal)
        want_status = execute_block_serial(want_bal, txns)

        got_status = execute_block(funk, None, "blk", txns)
        assert got_status == want_status
        for k in keys:
            got = funk.rec_query("blk", k)
            want = want_bal.get(k, 0 if any(
                t.src == k or t.dst == k for t in txns) else None)
            if got is not None or want is not None:
                assert (got or 0) == (want or 0), k.hex()[:4]
        assert {STATUS_OK} <= set(want_status)   # non-trivial block


def test_wave_executor_across_forks():
    rng = np.random.default_rng(8)
    keys, txns_a = _rand_block(rng, 40, 10)
    _, txns_b = _rand_block(rng, 40, 10)
    funk = Funk()
    funk.txn_prepare(None, "root")
    for k in keys:
        funk.rec_write("root", k, 25_000)
    funk.txn_publish("root")

    # two competing forks from the same parent
    st_a = execute_block(funk, None, "fork_a", txns_a)
    st_b = execute_block(funk, None, "fork_b", txns_b)

    oracle_a, oracle_b = ({k: 25_000 for k in keys} for _ in range(2))
    assert st_a == execute_block_serial(oracle_a, txns_a)
    assert st_b == execute_block_serial(oracle_b, txns_b)
    for k in keys:
        assert funk.rec_query("fork_a", k) == oracle_a.get(k, 0)
        assert funk.rec_query("fork_b", k) == oracle_b.get(k, 0)

    # publish fork_a; fork_b's lane is abandoned (cancelled by publish)
    funk.txn_publish("fork_a")
    for k in keys:
        assert funk.rec_query(None, k) == oracle_a.get(k, 0)

    # chain a second block on the published root (multi-bank sequencing)
    st2 = execute_block(funk, None, "blk2", txns_b)
    oracle2 = dict(oracle_a)
    assert st2 == execute_block_serial(oracle2, txns_b)


def test_staged_dispatcher_lanes():
    sd = StagedDispatcher(max_lanes=2)
    a = sd.stage("fork1")
    b = sd.stage("fork2")
    assert a is not b
    a.add_txn(writes=(b"x" * 32,), reads=())
    with pytest.raises(RuntimeError):
        sd.stage("fork3")
    sd.abandon("fork2")
    sd.stage("fork3")
