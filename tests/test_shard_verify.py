"""P2 horizontal sharding tests (ref: src/disco/verify/fd_verify_tile.c:
49-53 — N verify tiles round-robin one ingest link by seq % cnt — and
the TPU-native form: shard_map over the device mesh inside one tile)."""
import os

import pytest

pytestmark = pytest.mark.slow

from firedancer_tpu.disco import Topology, TopologyRunner

N = 32


def test_two_verify_tiles_round_robin_one_link():
    """Both verify tiles consume the SAME ingest link; ownership is
    disjoint by seq parity; dedup fans both outs into one stream. Every
    unique txn arrives exactly once — nothing dropped, nothing doubled."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"rr{os.getpid()}", wksp_size=1 << 24)
        .link("ingest", depth=64, mtu=1280)
        .link("v0_dedup", depth=64, mtu=1280)
        .link("v1_dedup", depth=64, mtu=1280)
        .link("dedup_sink", depth=128, mtu=1280)
        .tcache("v0_tc", depth=4096)
        .tcache("v1_tc", depth=4096)
        .tcache("dedup_tc", depth=4096)
        .tile("synth", "synth", outs=["ingest"], count=N, unique=N, seed=11)
        .tile("v0", "verify", ins=["ingest"], outs=["v0_dedup"],
              batch=16, tcache="v0_tc", rr_cnt=2, rr_idx=0)
        .tile("v1", "verify", ins=["ingest"], outs=["v1_dedup"],
              batch=16, tcache="v1_tc", rr_cnt=2, rr_idx=1)
        .tile("dedup", "dedup", ins=["v0_dedup", "v1_dedup"],
              outs=["dedup_sink"], tcache="dedup_tc")
        .tile("sink", "sink", ins=["dedup_sink"])
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        runner.wait_idle("sink", "rx", N, timeout_s=540)
        # shm metrics flush one housekeeping interval behind the frag
        # flow — poll the upstream counters, don't snapshot
        runner.wait_idle("dedup", "rx", N, timeout_s=60)
        v0, v1 = runner.metrics("v0"), runner.metrics("v1")
        # disjoint ownership: each tile verified its share, no overlap
        assert v0["tx"] + v1["tx"] == N
        assert v0["tx"] > 0 and v1["tx"] > 0, (v0, v1)
        assert v0["verify_fail"] == 0 and v1["verify_fail"] == 0
        d = runner.metrics("dedup")
        assert d["rx"] == N and d["dup"] == 0 and d["tx"] == N
        assert runner.metrics("sink")["rx"] == N
    finally:
        runner.halt()
        runner.close()


def test_verify_tile_shard_map_multidevice():
    """One verify tile sharding its batch over the 8-device virtual CPU
    mesh (conftest forces xla_force_host_platform_device_count=8):
    verdicts must match the single-device kernel exactly."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device platform")
    import numpy as np

    from firedancer_tpu.runtime import Ring, Tcache, Workspace
    from firedancer_tpu.tiles.synth import make_signed_txns
    from firedancer_tpu.tiles.verify import VerifyTile

    w = Workspace(f"/fdtpu_sh{os.getpid()}", 1 << 23)
    try:
        in_ring = Ring.create(w, depth=64, mtu=1280)
        out_ring = Ring.create(w, depth=64, mtu=1280)
        tc = Tcache(w, depth=4096)
        tile = VerifyTile(in_ring, out_ring, tc, batch=16,
                          devices=len(jax.devices()))
        assert tile.devices >= 2
        txns = make_signed_txns(12, seed=3)
        for i, t in enumerate(txns):
            in_ring.publish(t, sig=i)
        # corrupt one more txn's signature: the sharded kernel must
        # reject it on whichever device shard it lands
        bad = bytearray(txns[0])
        bad[10] ^= 1
        in_ring.publish(bytes(bad), sig=99)
        got = 0
        for _ in range(8):
            got += tile.poll_once()
            if got >= 13:
                break
        # r5 async pipelining: verdicts publish at drain, not inside
        # poll_once — retire every in-flight batch before asserting
        tile.flush()
        assert tile.metrics["tx"] == 12
        # the corrupted copy fails verify (same first-sig tag would have
        # been dedup-dropped only AFTER verify; corruption hits earlier)
        assert tile.metrics["verify_fail"] + tile.metrics["dedup_drop"] >= 1
        n, _, buf, sizes, sigs, _ = out_ring.gather(0, 32, 1280)
        assert n == 12
    finally:
        w.close()
        w.unlink()
