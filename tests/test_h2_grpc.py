"""HTTP/2 + HPACK + gRPC (waltz/h2.py, hpack.py, grpc.py) and the
bundle tile end-to-end (ref: src/waltz/h2/, src/waltz/grpc/,
src/disco/bundle/fd_bundle_tile.c)."""
import os
import struct
import time

import pytest

from firedancer_tpu.waltz import h2, hpack
from firedancer_tpu.waltz.grpc import (GrpcClient, GrpcError,
                                       GrpcServer, pb_decode, pb_field)


# -- hpack -------------------------------------------------------------------

def test_hpack_rfc7541_huffman_vectors():
    assert hpack.huff_decode(
        bytes.fromhex("f1e3c2e5f23a6ba0ab90f4ff")) == b"www.example.com"
    assert hpack.huff_decode(bytes.fromhex("6402")) == b"302"
    assert hpack.huff_decode(bytes.fromhex("aec3771a4b")) == b"private"
    assert hpack.huff_decode(
        bytes.fromhex("d07abe941054d444a8200595040b8166e082a62d1bff")) \
        == b"Mon, 21 Oct 2013 20:13:21 GMT"


def test_hpack_roundtrip_and_static_refs():
    hdrs = [(b":method", b"POST"), (b":status", b"200"),
            (b"content-type", b"application/grpc"),
            (b"x-custom", b"abc"), (b"te", b"trailers")]
    blob = hpack.encode(hdrs)
    assert hpack.decode(blob) == hdrs
    # pure static pair encodes to a single byte
    assert hpack.encode([(b":method", b"GET")]) == b"\x82"


def test_hpack_integer_boundaries():
    for v in (0, 30, 31, 32, 127, 128, 16383, 1 << 20):
        b = hpack.enc_int(v, 5)
        got, off = hpack.dec_int(b, 0, 5)
        assert got == v and off == len(b)


def test_hpack_rejects_dynamic_refs():
    with pytest.raises(hpack.HpackError):
        hpack.decode(bytes([0x80 | 62]))     # beyond the static table


# -- h2 in-memory pair -------------------------------------------------------

def _pump_pair(a, b, rounds=4):
    for _ in range(rounds):
        b.feed(a.take_tx())
        a.feed(b.take_tx())


def test_h2_handshake_headers_data_trailers():
    cli = h2.Conn(is_client=True)
    srv = h2.Conn(is_client=False)
    _pump_pair(cli, srv)
    assert cli._settings_acked and srv._settings_acked

    st = cli.open_stream([(b":method", b"POST"), (b":path", b"/x")])
    cli.send_data(st, b"hello-world", end_stream=True)
    _pump_pair(cli, srv)
    sst = srv.streams[st.sid]
    assert dict(sst.headers)[b":path"] == b"/x"
    assert bytes(sst.data) == b"hello-world" and sst.remote_closed

    srv.send_headers(sst, [(b":status", b"200")])
    srv.send_data(sst, b"resp")
    srv.send_headers(sst, [(b"grpc-status", b"0")], end_stream=True)
    _pump_pair(cli, srv)
    assert dict(st.headers)[b":status"] == b"200"
    assert bytes(st.data) == b"resp"
    assert dict(st.trailers)[b"grpc-status"] == b"0"
    assert st.remote_closed


def test_h2_large_data_fragments_and_flow_control():
    cli = h2.Conn(is_client=True)
    srv = h2.Conn(is_client=False)
    _pump_pair(cli, srv)
    st = cli.open_stream([(b":method", b"POST"), (b":path", b"/big")])
    big = bytes(range(256)) * 200            # 51200 bytes > 16384 frame
    cli.send_data(st, big, end_stream=True)
    _pump_pair(cli, srv, rounds=6)
    sst = srv.streams[st.sid]
    assert bytes(sst.data) == big
    # server's WINDOW_UPDATEs replenished the client's send window
    assert cli.send_window > 0


def test_h2_ping_and_rst():
    cli = h2.Conn(is_client=True)
    srv = h2.Conn(is_client=False)
    _pump_pair(cli, srv)
    cli._tx += h2.frame(h2.FT_PING, 0, 0, b"12345678")
    _pump_pair(cli, srv)
    st = cli.open_stream([(b":method", b"POST"), (b":path", b"/r")])
    _pump_pair(cli, srv)
    srv.rst(srv.streams[st.sid], code=0x8)
    _pump_pair(cli, srv)
    assert st.reset == 0x8 and st.remote_closed


def test_h2_rejects_oversized_frame_announcement():
    # RFC 9113 §4.2: a declared length beyond our SETTINGS_MAX_FRAME_SIZE
    # must fail fast instead of accumulating in the rx buffer.
    srv = h2.Conn(is_client=False)
    srv.feed(h2.PREFACE + h2.frame(h2.FT_SETTINGS, 0, 0, b""))
    hdr = (1 << 20).to_bytes(3, "big") + bytes([h2.FT_DATA, 0]) \
        + struct.pack(">I", 1)
    with pytest.raises(h2.H2Error, match="FRAME_SIZE"):
        srv.feed(hdr)


def test_h2_rejects_pad_length_ge_payload():
    # RFC 9113 §6.1/6.2: pad length >= payload length is PROTOCOL_ERROR.
    cli = h2.Conn(is_client=True)
    srv = h2.Conn(is_client=False)
    _pump_pair(cli, srv)
    st = cli.open_stream([(b":method", b"POST"), (b":path", b"/p")])
    _pump_pair(cli, srv)
    bad = bytes([200]) + b"xy"           # pad 200 >= 3-byte payload
    with pytest.raises(h2.H2Error, match="pad"):
        srv.feed(h2.frame(h2.FT_DATA, h2.F_PADDED, st.sid, bad))
    srv2 = h2.Conn(is_client=False)
    srv2.feed(h2.PREFACE + h2.frame(h2.FT_SETTINGS, 0, 0, b""))
    with pytest.raises(h2.H2Error, match="pad"):
        srv2.feed(h2.frame(h2.FT_HEADERS,
                           h2.F_PADDED | h2.F_END_HEADERS, 1, bad))


# -- protobuf codec ----------------------------------------------------------

def test_protobuf_codec_roundtrip():
    msg = pb_field(1, b"abc") + pb_field(2, 300) + pb_field(1, b"def")
    d = pb_decode(msg)
    assert d[1] == [b"abc", b"def"] and d[2] == [300]
    with pytest.raises(ValueError):
        pb_decode(b"\x0a\xff")               # truncated length


# -- gRPC over real TCP ------------------------------------------------------

def test_grpc_unary_stream_and_errors():
    def echo(req):
        return pb_field(1, b"echo:" + pb_decode(req)[1][0])

    def counter(req):
        return [pb_field(1, i) for i in range(pb_decode(req)[1][0])]

    def boom(req):
        raise RuntimeError("handler exploded")

    srv = GrpcServer({"/t.S/Echo": echo, "/t.S/Count": counter,
                      "/t.S/Boom": boom})
    try:
        cli = GrpcClient(("127.0.0.1", srv.port))
        rsp = cli.call_unary("a", "/t.S/Echo", pb_field(1, b"hi"))
        assert pb_decode(rsp)[1][0] == b"echo:hi"
        _, nxt = cli.open_server_stream("a", "/t.S/Count",
                                        pb_field(1, 5))
        got = []
        while True:
            m = nxt()
            if m is None:
                break
            got.append(pb_decode(m)[1][0])
        assert got == [0, 1, 2, 3, 4]
        with pytest.raises(GrpcError) as e:
            cli.call_unary("a", "/t.S/Missing", b"")
        assert e.value.status == 12          # UNIMPLEMENTED
        with pytest.raises(GrpcError) as e:
            cli.call_unary("a", "/t.S/Boom", b"")
        assert e.value.status == 13          # INTERNAL
        cli.close()
    finally:
        srv.close()


# -- bundle tile end-to-end --------------------------------------------------

def test_bundle_tile_feeds_pack_atomically():
    """block-engine gRPC stream -> bundle tile -> pack bundle_in ->
    an exclusive in-order microblock on the bank link."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.runtime import Ring, Workspace
    from firedancer_tpu.tiles.synth import make_signed_txns

    txns = [bytes(t) for t in make_signed_txns(3, seed=21)]

    sent = []

    def subscribe(req):
        # emit the bundle on the FIRST subscription only; later
        # reconnects get an empty stream (the tile's reconnect loop is
        # expected — the server is single-shot test scaffolding)
        if not sent:
            sent.append(1)
            yield b"".join(pb_field(1, t) for t in txns)

    srv = GrpcServer({"/fdtpu.BlockEngine/SubscribeBundles": subscribe})
    plan = None
    runner = None
    try:
        topo = (
            Topology(f"bd{os.getpid()}", wksp_size=1 << 23)
            .link("txn_in", depth=64, mtu=1280, external=True)
            .link("bundles", depth=64, mtu=4096)
            .link("bank0", depth=64, mtu=4200, external=True)
            .link("done0", depth=64, mtu=64, external=True)
            .tile("bundle", "bundle", outs=["bundles"],
                  engine=f"127.0.0.1:{srv.port}")
            .tile("pack", "pack",
                  ins=[("txn_in", False), ("bundles", False),
                       ("done0", False)],
                  outs=["bank0"], txn_in="txn_in", bundle_in="bundles",
                  bank_links=["bank0"], done_links=["done0"])
        )
        plan = topo.build()
        runner = TopologyRunner(plan).start()
        runner.wait_running(timeout_s=60)

        w = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                      create=False)
        li = plan["links"]["bank0"]
        bank_ring = Ring(w, li["ring_off"], li["depth"],
                         li["arena_off"], li["mtu"])
        seq = 0
        deadline = time.time() + 60
        frames = []
        while time.time() < deadline and not frames:
            n, seq, buf, sizes, sigs, _ = bank_ring.gather(seq, 8,
                                                           li["mtu"])
            frames += [bytes(buf[i, :sizes[i]]) for i in range(n)]
            time.sleep(0.02)
        assert frames, "no microblock emitted"
        bank, cnt, mb_id, slot = struct.unpack_from("<HHQQ",
                                                    frames[0], 0)
        assert cnt == 3                      # the bundle, exclusively
        off = 20
        got = []
        for _ in range(cnt):
            (ln,) = struct.unpack_from("<H", frames[0], off)
            off += 2
            got.append(frames[0][off:off + ln])
            off += ln
        assert got == txns                   # exact order preserved
        m = runner.metrics("pack")
        assert m["bundles"] >= 1 and m["bundle_rejects"] == 0
        assert runner.metrics("bundle")["txns"] >= 3
    finally:
        if runner:
            runner.halt()
            runner.close()
        srv.close()


def test_hpack_padding_must_be_eos_prefix():
    # '0' (code 00000) + 000 padding: zeros padding is a decode error
    with pytest.raises(hpack.HpackError, match="padding"):
        hpack.huff_decode(b"\x00")
    # valid: '0' + 111 padding (EOS prefix)
    assert hpack.huff_decode(b"\x07") == b"0"


def test_h2_send_respects_flow_control_window():
    """Data beyond the peer's 64KiB initial window waits for
    WINDOW_UPDATE instead of overshooting (RFC 9113 §5.2)."""
    cli = h2.Conn(is_client=True)
    srv = h2.Conn(is_client=False)
    _pump_pair(cli, srv)
    st = cli.open_stream([(b":method", b"POST"), (b":path", b"/w")])
    big = b"z" * (h2.DEFAULT_WINDOW + 10_000)
    cli.send_data(st, big, end_stream=True)
    # without feeding the server's WINDOW_UPDATEs back, the client
    # must emit at most the initial window
    first = cli.take_tx()
    sent = sum(int.from_bytes(first[i:i+3], "big")
               for i in _frame_offsets(first, h2.FT_DATA))
    assert sent <= h2.DEFAULT_WINDOW
    assert cli.send_window >= 0 and st.send_window >= 0
    # deliver the withheld flight, then pump the rest
    srv.feed(first)
    _pump_pair(cli, srv, rounds=10)
    assert bytes(srv.streams[st.sid].data) == big
    assert srv.streams[st.sid].remote_closed


def _frame_offsets(blob, want_type):
    off = 0
    out = []
    while off + 9 <= len(blob):
        ln = int.from_bytes(blob[off:off+3], "big")
        if blob[off+3] == want_type:
            out.append(off)
        off += 9 + ln
    return out


def test_bundle_oversize_message_counted_not_crash():
    """>5-txn subscribe messages are remote garbage: counted as
    errors, never framed (the u8-count wire caps and pack's bundle
    size cap both sit behind this check)."""
    from firedancer_tpu.waltz.grpc import pb_field

    def subscribe(req):
        yield b"".join(pb_field(1, bytes([i]) * 10) for i in range(9))

    srv = GrpcServer({"/fdtpu.BlockEngine/SubscribeBundles": subscribe})
    try:
        # drive the stream loop logic directly (no topology needed)
        from firedancer_tpu.waltz.grpc import GrpcClient, pb_decode
        cli = GrpcClient(("127.0.0.1", srv.port))
        _, nxt = cli.open_server_stream(
            "a", "/fdtpu.BlockEngine/SubscribeBundles", b"")
        msg = nxt()
        txns = [v for v in pb_decode(msg).get(1, [])]
        assert len(txns) == 9              # arrives; the TILE rejects it
        cli.close()
    finally:
        srv.close()


def test_h2_empty_padded_frame_rejected():
    cli = h2.Conn(is_client=True)
    srv = h2.Conn(is_client=False)
    _pump_pair(cli, srv)
    st = cli.open_stream([(b":method", b"POST"), (b":path", b"/e")])
    _pump_pair(cli, srv)
    with pytest.raises(h2.H2Error, match="pad"):
        srv.feed(h2.frame(h2.FT_DATA, h2.F_PADDED, st.sid, b""))


def test_h2_large_header_block_splits_into_continuations():
    # sender must not emit a HEADERS frame beyond the peer frame size;
    # RFC 9113 §6.10 CONTINUATION splitting, round-tripped here.
    cli = h2.Conn(is_client=True)
    srv = h2.Conn(is_client=False)
    _pump_pair(cli, srv)
    hdrs = [(b":method", b"POST"), (b":path", b"/big")]
    hdrs += [(b"x-meta-%d" % i, bytes(90) + b"%d" % i) for i in range(400)]
    st = cli.open_stream(hdrs, end_stream=True)
    _pump_pair(cli, srv, rounds=6)
    sst = srv.streams[st.sid]
    got = dict(sst.headers)
    assert got[b":path"] == b"/big"
    assert got[b"x-meta-399"].endswith(b"399")
    assert sst.remote_closed


def test_h2_continuation_accumulation_capped():
    srv = h2.Conn(is_client=False)
    srv.feed(h2.PREFACE + h2.frame(h2.FT_SETTINGS, 0, 0, b""))
    srv.feed(h2.frame(h2.FT_HEADERS, 0, 1, b"\x00" * 100))  # no END_HEADERS
    blk = h2.frame(h2.FT_CONTINUATION, 0, 1, b"\x00" * h2.MAX_FRAME)
    with pytest.raises(h2.H2Error, match="CALM"):
        for _ in range(2 + h2.MAX_HEADER_BLOCK // h2.MAX_FRAME):
            srv.feed(blk)


def test_h2_headers_pad_cannot_eat_priority_fields():
    # RFC 9113 §6.2: padding exceeding the fragment space is
    # PROTOCOL_ERROR even when a priority section hides the overlap.
    srv = h2.Conn(is_client=False)
    srv.feed(h2.PREFACE + h2.frame(h2.FT_SETTINGS, 0, 0, b""))
    payload = bytes([8]) + bytes(5) + bytes(4)   # pad 8 > 4-byte fragment
    with pytest.raises(h2.H2Error, match="pad"):
        srv.feed(h2.frame(h2.FT_HEADERS,
                          h2.F_PADDED | h2.F_PRIORITY | h2.F_END_HEADERS,
                          1, payload))
