"""Turbine shred-destination parity with Agave, pinned against the
reference's fixtures (real cluster data, read as binary TEST DATA from
/root/reference/src/disco/shred/fixtures — the same oracle the
reference's test_shred_dest.c "matches_agave" tests use).

Locks down (VERDICT r4 item 5, turbine half): the per-shred sha256
seed struct, MODE_SHIFT rejection rolls, without-replacement
cumulative inversion (incl. leader/source removal BEFORE drawing),
swap-remove unstaked sampling, and the fanout-tree addressing.
"""
import os
import struct

import pytest

from firedancer_tpu.flamenco.leaders import EpochLeaders
from firedancer_tpu.shred.shred_dest import ClusterNode, ShredDest

FIXDIR = "/root/reference/src/disco/shred/fixtures"


def _load():
    if not os.path.isdir(FIXDIR):
        pytest.skip("reference fixtures unavailable")
    raw = open(os.path.join(FIXDIR, "cluster_info.bin"), "rb").read()
    nodes = []
    for off in range(0, len(raw), 48):
        pk = raw[off:off + 32]
        stake, ip4, port = struct.unpack_from("<QIH", raw, off + 32)
        nodes.append(ClusterNode(pk, stake, addr=(ip4, port)))
    src = open(os.path.join(FIXDIR,
                            "cluster_info_pubkey.bin"), "rb").read()
    return nodes, src


def _shred_iter():
    # mirror of test_shred_dest.c's query loops: data then code,
    # idx = type+1, type+4, ... < 67
    for t, is_data in ((0, True), (1, False)):
        for idx in range(t + 1, 67, 3):
            yield idx, is_data


def test_compute_first_matches_agave():
    nodes, src = _load()
    staked = {n.pubkey: n.stake for n in nodes if n.stake > 0}
    lsched = EpochLeaders(0, None, staked, 10_000)
    sdest = ShredDest(nodes, self_pubkey=src, fanout=200)
    want = open(os.path.join(FIXDIR, "broadcast_peers.bin"),
                "rb").read()
    j = 0
    for slot in range(10_000):
        if lsched.leader_for(slot) != src:
            continue
        for idx, is_data in _shred_iter():
            node = sdest.first_hop(slot, idx,
                                   1 if is_data else 0, src)
            got = bytes(32)
            if node is not None and node.addr[0]:
                got = node.pubkey
            assert got == want[32 * j:32 * j + 32], \
                f"first-hop diverged at slot {slot} idx {idx}"
            j += 1
    assert j * 32 == len(want)          # covered every fixture row


def test_compute_children_matches_agave():
    nodes, src = _load()
    staked = {n.pubkey: n.stake for n in nodes if n.stake > 0}
    lsched = EpochLeaders(0, None, staked, 4_000)
    sdest = ShredDest(nodes, self_pubkey=src, fanout=200)
    ans = open(os.path.join(FIXDIR, "retransmit_peers.bin"),
               "rb").read()
    j = 0
    for slot in range(1, 2_000, 97):
        leader = lsched.leader_for(slot)
        for idx, is_data in _shred_iter():
            got = sdest.children(slot, idx,
                                 1 if is_data else 0, leader)
            answer_cnt, = struct.unpack_from("<Q", ans, j)
            j += 8
            assert len(got) == answer_cnt, \
                f"child count diverged at slot {slot} idx {idx}"
            for i in range(answer_cnt):
                assert got[i].pubkey == ans[j:j + 32], \
                    f"child {i} diverged at slot {slot} idx {idx}"
                j += 32
    assert j == len(ans)                # consumed the whole fixture
