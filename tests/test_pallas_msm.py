"""Pallas MSM (RLC batch verify) correctness gates.

Three tiers, matching the repo's kernel-testing precedent
(tests/test_pallas_ed.py):

1. FAST schedule simulation — the novel machinery in pallas_msm is the
   merge-fold reduction (full-utilization roll/select packing into the
   bit-reversed lane layout) and the stage-2 fold-Horner. Both are
   LINEAR over the group, so they are simulated here over the integers
   (add = +, double = ×2) with numpy rolls carrying pltpu.roll's exact
   semantics: the result must equal Σ_j 16^j Σ_lanes c[j, lane]. The
   field/point primitives themselves are shared with pallas_ed and
   pinned by its tests + Wycheproof on the jnp reference.
2. Interpret-mode full equality vs ops.ed25519.rlc_verify_batch —
   exact but hours-slow on a 1-core host, gated FDTPU_SLOW_TESTS=1.
3. Hardware gate — bench.py's rlc stage asserts kernel verdicts
   against the jnp reference on every run (on the real chip).
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import pallas_msm
from firedancer_tpu.utils import ed25519_ref


# ---------------------------------------------------------------------------
# tier 1: schedule simulation over the integers
# ---------------------------------------------------------------------------

def _simulate_stage1(c, tb):
    """Mirror _msm_stage1_kernel's merge-fold on integer 'points'.
    c: (64, tb) int array of per-window per-lane contributions.
    np.roll(x, shift) == pltpu.roll(x, shift, axis=1): out[i]=x[i-s]."""
    blocks = [c[j].copy() for j in range(64)]
    iota = np.arange(tb)
    w = tb
    for lvl in range(6):
        half = w // 2
        first = (iota % w) < half
        nxt = []
        for m in range(len(blocks) // 2):
            a, b = blocks[2 * m], blocks[2 * m + 1]
            left = np.where(first, a, np.roll(b, half))
            right = np.where(first, np.roll(a, -half), b)
            nxt.append(left + right)
        blocks = nxt
        w = half
    acc = blocks[0]
    while w > 1:
        acc = acc + np.roll(acc, -(w // 2))
        w //= 2
    return acc


def _simulate_stage2(acc, tb):
    """Mirror _msm_stage2_kernel's fold-Horner (double = ×2, 4
    doublings per level step = ×16^(2^(l-1)))."""
    for lvl in range(1, 7):
        dist = tb >> lvl
        dbl = acc * (16 ** (1 << (lvl - 1)))
        acc = acc + np.roll(dbl, -dist)
    return acc[0]


@pytest.mark.parametrize("tb", [64, 128, 256])
def test_merge_fold_and_horner_schedule(tb):
    rng = np.random.default_rng(7)
    c = rng.integers(0, 1 << 20, (64, tb)).astype(object)
    got = _simulate_stage2(_simulate_stage1(c, tb), tb)
    want = sum((16 ** j) * int(c[j].sum()) for j in range(64))
    assert got == want


def test_bitrev_lane_layout():
    """Window j's reduced value lands at lane (tb/64)·bitrev6(j) —
    the layout the stage-2 tree and the s_w scatter both assume."""
    tb = 128
    for j in (0, 1, 5, 42, 63):
        c = np.zeros((64, tb), np.int64)
        c[j, :] = 1                       # only window j contributes
        acc = _simulate_stage1(c, tb)
        lane = (tb // 64) * pallas_msm._bitrev6(j)
        assert acc[lane] == tb
        # stage-2 then weights it by 16^j
        assert _simulate_stage2(
            _simulate_stage1(c.astype(object), tb), tb) \
            == (16 ** j) * tb


def test_stage2_fb_scatter_layout_matches():
    """The s_w lane scatter in the glue uses the same bitrev map the
    schedule produces."""
    stride = 128 // 64
    lanes = [stride * pallas_msm._bitrev6(j) for j in range(64)]
    assert sorted(lanes) == list(range(0, 128, stride))


# ---------------------------------------------------------------------------
# tier 2: full interpret equality (slow-gated)
# ---------------------------------------------------------------------------

TB = 64
B = 64
MSG_LEN = 16

slow = pytest.mark.skipif(
    os.environ.get("FDTPU_SLOW_TESTS") != "1",
    reason="interpret-mode MSM takes hours on a 1-core host; opt in "
           "with FDTPU_SLOW_TESTS=1. The schedule is pinned by the "
           "fast simulation tests above; full verdicts are gated on "
           "hardware by bench.py's rlc stage.")


def _mk_batch(n, seed=0, forge=(), bad_s=(), bad_pub=()):
    rng = np.random.default_rng(seed)
    sigs = np.zeros((n, 64), np.uint8)
    pubs = np.zeros((n, 32), np.uint8)
    msgs = rng.integers(0, 256, (n, MSG_LEN), dtype=np.uint8)
    for i in range(n):
        seed_i = rng.bytes(32)
        _, _, pub = ed25519_ref.keypair(seed_i)
        sig = ed25519_ref.sign(seed_i, bytes(msgs[i]))
        sigs[i] = np.frombuffer(sig, np.uint8)
        pubs[i] = np.frombuffer(pub, np.uint8)
    for i in forge:
        msgs[i, 0] ^= 1
    for i in bad_s:
        sigs[i, 32:] = 0xFF
    for i in bad_pub:
        pubs[i] = 0xEC
        pubs[i, 31] = 0x7F
    z = rng.integers(0, 256, (n, 16), dtype=np.uint8)
    return (jnp.asarray(sigs), jnp.asarray(pubs), jnp.asarray(msgs),
            jnp.full((n,), MSG_LEN, jnp.int32), jnp.asarray(z))


def _both(sig, pub, msg, ml, z):
    ok_ref, pre_ref = ed.rlc_verify_batch(sig, pub, msg, ml, z)
    ok_pl, pre_pl = pallas_msm.rlc_verify_batch_tpu(
        sig, pub, msg, ml, z, tb=TB, interpret=True)
    return ((bool(ok_ref), np.asarray(pre_ref)),
            (bool(ok_pl), np.asarray(pre_pl)))


@slow
def test_interpret_valid_and_forged_and_masked():
    (ok_r, pre_r), (ok_p, pre_p) = _both(*_mk_batch(B, seed=1))
    assert ok_r and ok_p and pre_r.all()
    np.testing.assert_array_equal(pre_r, pre_p)

    (ok_r, pre_r), (ok_p, pre_p) = _both(
        *_mk_batch(B, seed=2, forge=(5,), bad_s=(0,), bad_pub=(7,)))
    assert not ok_r and not ok_p
    np.testing.assert_array_equal(pre_r, pre_p)
    assert not pre_r[0] and not pre_r[7] and pre_r[5]
