"""Tests for the native tango-semantics layer (rings/fseq/fctl/cnc/tcache).

Mirrors the reference's tango test tiers (ref: src/tango/test_ipc_full,
test_ipc_meta; src/util/tmpl unit tests): single-process semantic checks
plus a true multi-process producer/consumer shell test over shared memory.
"""
import multiprocessing as mp
import os

import numpy as np
import pytest

from firedancer_tpu.runtime import (Workspace, Ring, Fseq, Cnc, Tcache,
                                    CNC_RUN)

MTU = 256


@pytest.fixture
def wksp():
    name = f"/fdtpu_test_{os.getpid()}"
    w = Workspace(name, 1 << 22)
    yield w
    w.close()
    w.unlink()


def test_ring_publish_consume(wksp):
    ring = Ring.create(wksp, depth=8, mtu=MTU)
    msgs = [bytes([i]) * (i + 1) for i in range(5)]
    for i, m in enumerate(msgs):
        ring.publish(m, sig=100 + i)
    for i, m in enumerate(msgs):
        rc, frag = ring.consume(i)
        assert rc == 0
        assert frag.sig == 100 + i
        assert bytes(ring.payload(frag)) == m
    rc, _ = ring.consume(5)
    assert rc == 1  # not yet published


def test_ring_overrun_detection(wksp):
    ring = Ring.create(wksp, depth=4, mtu=MTU)
    for i in range(10):  # laps the depth-4 ring twice
        ring.publish(b"x%d" % i, sig=i)
    rc, _ = ring.consume(2)   # slot 2 now holds seq 6
    assert rc == -1
    rc, frag = ring.consume(7)
    assert rc == 0 and frag.sig == 7


def test_ring_gather_batch(wksp):
    ring = Ring.create(wksp, depth=64, mtu=MTU)
    for i in range(20):
        ring.publish(bytes([i]) * (10 + i), sig=i)
    n, seq, buf, sizes, sigs, ovr = ring.gather(0, 16, MTU)
    assert n == 16 and seq == 16 and ovr == 0
    assert sizes[:16].tolist() == [10 + i for i in range(16)]
    assert sigs[:16].tolist() == list(range(16))
    assert buf[3, :13].tolist() == [3] * 13
    assert buf[3, 13:].sum() == 0  # zero-padded
    n, seq, *_ = ring.gather(seq, 16, MTU)
    assert n == 4 and seq == 20


def test_fseq_fctl_credits(wksp):
    ring = Ring.create(wksp, depth=8, mtu=MTU)
    f1, f2 = Fseq(wksp), Fseq(wksp)
    assert ring.credits([f1, f2]) == 8
    for i in range(6):
        ring.publish(b"m", sig=i)
    assert ring.credits([f1, f2]) == 2   # slowest consumer at 0
    f1.update(6)
    assert ring.credits([f1, f2]) == 2
    f2.update(4)
    assert ring.credits([f1, f2]) == 6
    f2.update(6)
    assert ring.credits([f1, f2]) == 8


def test_cnc(wksp):
    cnc = Cnc(wksp)
    assert cnc.state == 0  # BOOT
    cnc.state = CNC_RUN
    assert cnc.state == CNC_RUN
    assert cnc.last_heartbeat == 0
    cnc.heartbeat()
    assert cnc.last_heartbeat > 0


def test_tcache_dedup(wksp):
    tc = Tcache(wksp, depth=4)
    assert not tc.insert(10)
    assert not tc.insert(11)
    assert tc.insert(10)        # dup
    assert not tc.insert(12)
    assert not tc.insert(13)
    assert not tc.insert(14)    # evicts 10
    assert not tc.insert(10)    # 10 was evicted -> fresh again
    assert tc.insert(13)        # still resident


def test_tcache_query_no_mutation(wksp):
    tc = Tcache(wksp, depth=4)
    assert not tc.query(42)     # absent
    assert not tc.query(42)     # query never inserts
    assert not tc.insert(42)
    assert tc.query(42)
    assert tc.insert(42)        # still a dup after queries


def test_wksp_create_replaces_stale_segment():
    """create=True over a leftover segment must produce fresh zeroed
    memory, not silently reuse stale contents (advisor finding r1)."""
    name = f"/fdtpu_stale_{os.getpid()}"
    w1 = Workspace(name, 1 << 20)
    w1.view(0, 8)[:] = np.arange(1, 9, dtype=np.uint8)
    w1.close()                  # crash simulation: no unlink
    w2 = Workspace(name, 1 << 20)   # re-create
    assert w2.view(0, 8).sum() == 0
    w2.close()
    w2.unlink()


def test_wksp_exclusive_create_fails_on_existing():
    """replace=False is a strict O_EXCL create: safe under racing
    creators (never destroys a live segment)."""
    name = f"/fdtpu_excl_{os.getpid()}"
    w1 = Workspace(name, 1 << 16, replace=False)
    try:
        with pytest.raises(OSError):
            Workspace(name, 1 << 16, replace=False)
    finally:
        w1.close()
        w1.unlink()


def test_wksp_join_missing_or_small_fails():
    name = f"/fdtpu_missing_{os.getpid()}"
    with pytest.raises(OSError):
        Workspace(name, 1 << 20, create=False)
    w = Workspace(name, 1 << 16)
    try:
        with pytest.raises(OSError):
            Workspace(name, 1 << 20, create=False)  # larger than segment
    finally:
        w.close()
        w.unlink()


def test_tcache_eviction_map_consistency(wksp):
    tc = Tcache(wksp, depth=16)
    rng = np.random.default_rng(3)
    tags = rng.integers(1, 1 << 62, size=500, dtype=np.uint64)
    window = []
    for t in tags.tolist():
        dup = tc.insert(t)
        assert dup == (t in window)
        if not dup:
            window.append(t)
            if len(window) > 16:
                window.pop(0)


def _producer(name, ring_off, arena_off, depth, fseq_off, n_msgs):
    w = Workspace(name, 1 << 22, create=False)
    ring = Ring(w, ring_off, depth, arena_off, MTU)
    fseq = Fseq(w, off=fseq_off)
    rng = np.random.default_rng(1)
    for i in range(n_msgs):
        while ring.credits([fseq]) <= 0:   # reliable consumer: backpressure
            pass
        body = rng.integers(0, 256, size=32, dtype=np.uint8)
        body[:8] = np.frombuffer(np.uint64(i).tobytes(), np.uint8)
        ring.publish(body, sig=int(body[8:16].view(np.uint64)[0]))
    w.close()


def test_ipc_producer_consumer(wksp):
    """True multi-process: child publishes (credit-gated on the parent's
    fseq), parent consumes every frag in order with zero gaps."""
    depth, n_msgs = 256, 2000
    ring = Ring.create(wksp, depth=depth, mtu=MTU)
    fseq = Fseq(wksp)
    ctx = mp.get_context("fork")
    p = ctx.Process(target=_producer,
                    args=(wksp.name, ring.off, ring.arena_off, depth,
                          fseq.off, n_msgs), daemon=True)
    p.start()
    got, seq, spins = 0, 0, 0
    rng = np.random.default_rng(1)
    while got < n_msgs and spins < 100_000_000:
        rc, frag = ring.consume(seq)
        if rc == 1:
            spins += 1
            continue
        assert rc == 0, "consumer overrun despite flow control"
        body = ring.payload(frag).copy()
        want = rng.integers(0, 256, size=32, dtype=np.uint8)
        idx = int(body[:8].view(np.uint64)[0])
        assert idx == got                       # in-order, gap-free
        assert body[8:].tolist() == want[8:].tolist()
        assert frag.sig == int(want[8:16].view(np.uint64)[0])
        got += 1
        seq += 1
        fseq.update(seq)
    p.join(timeout=60)
    if p.is_alive():
        p.terminate()
    assert got == n_msgs


def test_wksp_alternate_backing_dir(tmp_path):
    """FDTPU_HUGETLBFS redirects workspace backing files to a
    hugetlbfs mount (ref: src/util/shmem/fd_shmem.h hugepage
    workspaces). No hugetlbfs exists in this container, so the test
    proves the selection + cross-process-visibility logic against a
    plain directory — on a real mount the identical path yields
    kernel-enforced huge pages."""
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env = dict(os.environ, FDTPU_HUGETLBFS=str(tmp_path),
               PYTHONPATH=repo_root)
    code = """
import os
from firedancer_tpu.runtime import Workspace
w = Workspace("hugetest", 1 << 20, create=True)
import numpy as np
v = w.view(0, 8)
v[:] = np.frombuffer(b"hugedata", np.uint8)
print("created")
"""
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=60)
    assert "created" in r.stdout, r.stderr
    # the backing landed in the alternate dir, not /dev/shm
    assert (tmp_path / "hugetest").exists()
    # second process joins and sees the data
    code2 = """
from firedancer_tpu.runtime import Workspace
w = Workspace("hugetest", 1 << 20, create=False)
print(bytes(bytearray(w.view(0, 8))))
"""
    r2 = subprocess.run([sys.executable, "-c", code2], env=env,
                        capture_output=True, text=True, timeout=60)
    assert "hugedata" in r2.stdout, r2.stderr


def test_ring_publish_batch_masked_and_credit_gated(wksp):
    ring = Ring.create(wksp, depth=8, mtu=MTU)
    f = Fseq(wksp)
    n = 12
    buf = np.zeros((n, MTU), np.uint8)
    for i in range(n):
        buf[i, :4] = i
    sizes = np.full(n, 4, np.uint32)
    sigs = np.arange(n, dtype=np.uint64)
    mask = np.ones(n, np.uint8)
    mask[5] = 0                       # hole: row 5 must not publish
    stop, pub = ring.publish_batch(buf, sizes, sigs, mask, fseqs=[f])
    assert pub == 8                   # depth-limited by the consumer
    assert stop < n
    f.update(8)                       # consumer catches up
    stop, pub2 = ring.publish_batch(buf, sizes, sigs, mask, fseqs=[f],
                                    start=stop)
    assert stop == n and pub + pub2 == n - 1
    # 11 publishes on a depth-8 ring: the first 3 slots were lapped;
    # the live window holds the last 8 published sigs
    published = [i for i in range(n) if i != 5]
    got = []
    seq = 3
    while True:
        rc, frag = ring.consume(seq)
        if rc != 0:
            break
        got.append(int(frag.sig))
        seq += 1
    assert got == published[3:]
