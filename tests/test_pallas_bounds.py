"""Interval-arithmetic closure proof for the Pallas kernel's loose bound.

`ops/pallas_ed.py` keeps every in-kernel field element "loose": per-limb
non-negative with upper bound B = 10624. After the r4 carry tightening,
`_reduce39` runs only TWO relaxed carry passes after a schoolbook
multiply, and the int32 coefficient accumulation is allowed to pass
int32 max (wrap-tolerant masking recovers the low 13 bits and the
19-bit logical hi, valid while the true value stays < 2^32 — i.e.
while B ≤ ⌊√(2^32/20)⌋ = 14654). The r10 tightening drops fsub/fneg
from two relaxed passes to ONE: their 1-pass worst case (limb0 =
8191 + FOLD·((B + max SUB_C)>>13) = 10623) now DEFINES the loose
bound. Random differential tests cannot exercise these bounds —
worst-case limb patterns are unreachable from random inputs — so the
safety argument is numeric, and this test walks it mechanically:

  1. every arithmetic primitive maps inputs bounded by B back to
     outputs bounded by B (closure: any kernel composition is safe);
  2. schoolbook accumulations stay < 2^32 (the wrap-masking premise);
  3. fsub/fneg stay limb-wise non-negative (SUB_C dominates B).

The propagation here mirrors the primitive set of pallas_ed
(fadd/fsub/fneg/fmul/fmul_const/fmul_small2/_carry/_reduce39); any
change to the carry discipline there must keep this test green.
"""
import numpy as np

from firedancer_tpu.ops import fe25519 as fe

NL = fe.NLIMB
BITS = fe.BITS
MASK = fe.MASK
FOLD = fe.FOLD

B = 10624                       # the kernel-wide loose bound (r10)


def test_loose_bound_within_uint32_multiply_window():
    """The wrap-tolerance premise in one line: 20·B² < 2^32, with the
    maximal admissible bound pinned so a future tightening knows its
    headroom."""
    assert 20 * B * B < 2 ** 32
    assert B <= 14654 == int((2 ** 32 / 20) ** 0.5)


def carry_pass(ub):
    """Exact sup-propagation of one relaxed carry pass over per-limb
    upper bounds (all values non-negative). pallas_ed._carry uses a
    plain arithmetic `x >> 13` with NO wrap masking, so its inputs must
    stay below int32 max — asserted here for every modeled pass."""
    for u in ub:
        assert u < 2 ** 31, f"carry input sup {u} would wrap int32"
    lo = [min(u, MASK) for u in ub]
    hi = [u >> BITS for u in ub]
    out = [lo[0] + FOLD * hi[-1]]
    out += [lo[i] + hi[i - 1] for i in range(1, NL)]
    return out


def carry(ub, passes):
    for _ in range(passes):
        ub = carry_pass(ub)
    return ub


def reduce39(coeff_ub):
    """Sup-propagation of pallas_ed._reduce39 (2 carry passes).
    Asserts the wrap-masking premise: true coefficients < 2^32."""
    assert len(coeff_ub) == 2 * NL - 1
    for c in coeff_ub:
        assert c < 2 ** 32, f"coefficient sup {c} can wrap past uint32"
    lo = [min(c, MASK) for c in coeff_ub] + [0]
    hi = [0] + [c >> BITS for c in coeff_ub]
    rows = [lo[i] + hi[i] for i in range(2 * NL)]
    x = [rows[i] + FOLD * rows[NL + i] for i in range(NL)]
    # the folded rows feed pallas_ed._carry, whose arithmetic shift has
    # no wrap masking — they must stay below int32 max (carry_pass also
    # asserts this for each subsequent pass)
    for v in x:
        assert v < 2 ** 31, f"folded row sup {v} would wrap int32"
    return carry(x, 2)


def fmul_ub(a_ub, b_ub):
    coeff = [
        sum(a_ub[i] * b_ub[k - i] for i in range(NL) if 0 <= k - i < NL)
        for k in range(2 * NL - 1)
    ]
    return reduce39(coeff)


def test_sub_const_dominates_loose_bound():
    """fsub/fneg compute a + C - b; non-negativity needs min(C) >= B."""
    sub_c = np.asarray(fe.SUB_C, np.int64)
    assert int(sub_c.min()) >= B
    # and C must itself be carry-safe: a + C < 2^31 trivially
    assert int(sub_c.max()) + B < 2 ** 31


def test_fmul_closure():
    """loose x loose -> loose: the core invariant behind the 2-pass
    reduction. Also pins the interior bounds quoted in the _reduce39
    docstring (limb0 <= 8799, limb1 <= 8270)."""
    out = fmul_ub([B] * NL, [B] * NL)
    assert max(out) <= B, out
    assert out[0] <= 8799 and out[1] <= 8270, out


def test_fadd_closure():
    out = carry([2 * B] * NL, 1)
    assert max(out) <= B, out


def test_fsub_closure():
    """ONE pass (r10) closes fsub/fneg; the fsub worst case IS the
    loose bound's defining corner (limb0 = 10623 = B − 1)."""
    sub_c = [int(v) for v in np.asarray(fe.SUB_C, np.int64)]
    out = carry([B + c for c in sub_c], 1)
    assert max(out) <= B, out
    assert max(out) == B - 1        # the bound is tight, not slack
    # fneg is the b=0 case of the same expression
    out = carry(sub_c, 1)
    assert max(out) <= B, out


def test_fmul_small2_closure():
    out = carry([2 * B] * NL, 1)
    assert max(out) <= B, out


def test_fmul_const_closure():
    """Constants are canonical (< 2^13 per limb); products of a loose
    element against all-max constant limbs must not wrap and must
    return to the loose bound."""
    out = fmul_ub([B] * NL, [MASK] * NL)
    assert max(out) <= B, out


def test_decompress_handoff_within_bound():
    """The fused kernel hands `ax = where(flip, fneg(x), x)` straight
    into fmul with no intervening carry: both branches must already be
    loose. fneg(x) is carry(SUB_C - x, 1) <= the fsub bound; the
    un-flipped x is a _reduce39 output."""
    sub_c = [int(v) for v in np.asarray(fe.SUB_C, np.int64)]
    neg_branch = carry(sub_c, 1)
    mul_branch = fmul_ub([B] * NL, [B] * NL)
    handoff = [max(a, b) for a, b in zip(neg_branch, mul_branch)]
    assert max(handoff) <= B, handoff


def test_kernel_inputs_within_bound():
    """Exact-digit kernel inputs (y digits, table entries) are canonical:
    13-bit limbs with an 8-bit top limb — comfortably below B."""
    assert MASK <= B
    top = (1 << (255 - BITS * (NL - 1))) - 1
    assert top <= B
