"""vinyl service tile: DB driven over request/completion rings from
another process, with durability across tile restarts
(ref: src/vinyl/fd_vinyl.h:13-29, src/discof/vinyl/fd_vinyl_tile.c)."""
import os
import struct
import time

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.disco.tiles import VinylAdapter
from firedancer_tpu.runtime import Ring, Workspace

OP_PUT, OP_GET, OP_DEL = (VinylAdapter.OP_PUT, VinylAdapter.OP_GET,
                          VinylAdapter.OP_DEL)
ST_OK, ST_MISS = VinylAdapter.ST_OK, VinylAdapter.ST_MISS


def _ring(plan, ln):
    w = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                  create=False)
    li = plan["links"][ln]
    return Ring(w, li["ring_off"], li["depth"], li["arena_off"],
                li["mtu"])


def _req(op, req_id, key, val=b""):
    return bytes([op]) + struct.pack("<Q", req_id) + key + val


class _Client:
    def __init__(self, plan):
        self.rq = _ring(plan, "rq")
        self.cq = _ring(plan, "cq")
        self.seq = 0
        self.mtu = plan["links"]["cq"]["mtu"]

    def call(self, op, req_id, key, val=b"", timeout=15):
        self.rq.publish(_req(op, req_id, key, val), sig=req_id)
        deadline = time.time() + timeout
        while time.time() < deadline:
            n, self.seq, buf, sizes, sigs, _ = self.cq.gather(
                self.seq, 4, self.mtu)
            for i in range(n):
                frame = bytes(buf[i, :sizes[i]])
                rid, st = struct.unpack_from("<QB", frame, 0)
                if rid == req_id:
                    return st, frame[9:]
            time.sleep(0.005)
        raise TimeoutError(f"no completion for req {req_id}")


def _topo(name, path):
    return (
        Topology(name, wksp_size=1 << 22)
        .link("rq", depth=64, mtu=1200, external=True)
        .link("cq", depth=64, mtu=1200, external=True)
        .tile("vinyl", "vinyl", ins=[("rq", False)], outs=["cq"],
              path=path)
    )


def test_vinyl_tile_serves_and_persists(tmp_path):
    path = str(tmp_path / "store.vinyl")
    K1, K2 = b"\x01" * 32, b"\x02" * 32

    plan = _topo(f"vy{os.getpid()}", path).build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=60)
        c = _Client(plan)
        assert c.call(OP_PUT, 1, K1, b"account-bytes-1")[0] == ST_OK
        assert c.call(OP_PUT, 2, K2, b"x" * 900)[0] == ST_OK
        st, val = c.call(OP_GET, 3, K1)
        assert (st, val) == (ST_OK, b"account-bytes-1")
        assert c.call(OP_GET, 4, b"\x09" * 32)[0] == ST_MISS
        assert c.call(OP_DEL, 5, K1)[0] == ST_OK
        assert c.call(OP_GET, 6, K1)[0] == ST_MISS
        # metrics flush at the housekeeping cadence — poll
        deadline = time.time() + 10
        while time.time() < deadline:
            m = runner.metrics("vinyl")
            if m["gets"] == 3:
                break
            time.sleep(0.02)
        assert m["puts"] == 2 and m["gets"] == 3 and m["hits"] == 1
        assert m["records"] >= 1
    finally:
        runner.halt()
        runner.close()

    # restart generation: the log recovers; K2 survives, K1 stays dead
    plan2 = _topo(f"vy2{os.getpid()}", path).build()
    runner2 = TopologyRunner(plan2).start()
    try:
        runner2.wait_running(timeout_s=60)
        c2 = _Client(plan2)
        st, val = c2.call(OP_GET, 10, K2)
        assert (st, val) == (ST_OK, b"x" * 900)
        assert c2.call(OP_GET, 11, K1)[0] == ST_MISS
    finally:
        runner2.halt()
        runner2.close()


def test_oversize_value_typed_error_not_crash(tmp_path):
    """A PUT whose GET completion could not fit the cq mtu is refused
    with ST_ERR; the tile survives (r4 review)."""
    path = str(tmp_path / "store2.vinyl")
    # cq mtu deliberately smaller than rq: a request can arrive whose
    # completion could never be published
    plan = (
        Topology(f"vy3{os.getpid()}", wksp_size=1 << 22)
        .link("rq", depth=64, mtu=1200, external=True)
        .link("cq", depth=64, mtu=128, external=True)
        .tile("vinyl", "vinyl", ins=[("rq", False)], outs=["cq"],
              path=path)
    ).build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=60)
        c = _Client(plan)
        big = b"z" * 500                     # fits rq, not cq
        assert c.call(OP_PUT, 1, b"\x05" * 32, big)[0] == \
            VinylAdapter.ST_ERR
        # tile still serves
        assert c.call(OP_PUT, 2, b"\x06" * 32, b"ok")[0] == ST_OK
        st, val = c.call(OP_GET, 3, b"\x06" * 32)
        assert (st, val) == (ST_OK, b"ok")
        # the errs counter lands at the tile's next housekeeping flush
        # — wait for it instead of racing it (1-core CI deflake)
        deadline = time.monotonic() + 10
        while runner.metrics("vinyl")["errs"] != 1 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert runner.metrics("vinyl")["errs"] == 1
    finally:
        runner.halt()
        runner.close()
