"""Snapshot restore pipeline test: checkpoint file -> snapld (multi-frag
stream) -> snapin (reassemble + restore) across OS processes
(ref: src/discof/restore/ pipeline shape; multi-frag ctl SOM/EOM
discipline src/tango/fd_tango_base.h).

r17: the drill runs over BOTH funk backends — without a carved store
snapin restores into a private process funk; with [funk] backend="shm"
it restores into the topology's shared store and installs the restore
marker the replay tile's cold-start gate polls for.
"""
import pytest

pytestmark = pytest.mark.slow
import os

import numpy as np

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm import Account
from firedancer_tpu.tiles.snapshot import state_fingerprint
from firedancer_tpu.utils.checkpt import funk_checkpt


@pytest.mark.parametrize("backend", ["process", "shm"])
def test_snapshot_restore_pipeline(tmp_path, backend):
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    rng = np.random.default_rng(11)
    funk = Funk()
    for i in range(200):
        key = rng.bytes(32)
        if i % 2:
            funk.rec_write(None, key, Account(
                lamports=int(rng.integers(0, 1 << 50)),
                data=rng.bytes(int(rng.integers(0, 300))),
                owner=rng.bytes(32)))
        else:
            funk.rec_write(None, key, int(rng.integers(0, 1 << 60)))
    want_fp = state_fingerprint(funk)
    path = tmp_path / "snap.ckpt"
    with open(path, "wb") as f:
        funk_checkpt(funk, f)
    # the stream must span MANY frags (multi-frag path exercised)
    assert os.path.getsize(path) > 16 * 1024

    topo_kw = {}
    if backend == "shm":
        topo_kw["funk"] = {"backend": "shm", "heap_mb": 4,
                           "rec_max": 1024}
    topo = (
        Topology(f"sn{os.getpid()}", wksp_size=1 << 23, **topo_kw)
        .link("snap", depth=32, mtu=1280)          # depth << frag count
        .tile("snapld", "snapld", outs=["snap"], path=str(path),
              chunk=1024)
        .tile("snapin", "snapin", ins=["snap"])
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        runner.wait_idle("snapin", "restored", 1, timeout_s=120)
        m = runner.metrics("snapin")
        assert m["accounts"] == 200
        assert m["fingerprint"] == want_fp, "restored state diverged"
        assert m["stream_err"] == 0
        ld = runner.metrics("snapld")
        assert ld["frags"] > 16 and ld["done"] == 1
        assert m["frags"] == ld["frags"]
        if backend == "shm":
            # the shared-store restore is visible to a fresh join of
            # the SAME region — marker installed, fingerprint holds
            # with the marker excluded (the replay handoff contract)
            import json
            from firedancer_tpu.funk.shmfunk import WireFunk
            from firedancer_tpu.runtime import Workspace
            from firedancer_tpu.utils.checkpt import RESTORE_MARKER_KEY
            name = f"/fdtpu_sn{os.getpid()}"
            plan = json.load(open(f"/dev/shm/fdtpu_sn{os.getpid()}"
                                  f".plan.json"))
            w = Workspace(name, os.path.getsize("/dev/shm" + name),
                          create=False)
            try:
                shared = WireFunk.from_plan(w, plan["funk"])
                slot, bank_hash = shared.rec_query(
                    None, RESTORE_MARKER_KEY)
                assert slot == 0 and bank_hash == bytes(32)
                assert state_fingerprint(shared) == want_fp
            finally:
                w.close()
    finally:
        runner.halt()
        runner.close()
