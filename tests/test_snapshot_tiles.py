"""Snapshot restore pipeline test: checkpoint file -> snapld (multi-frag
stream) -> snapin (reassemble + restore) across OS processes
(ref: src/discof/restore/ pipeline shape; multi-frag ctl SOM/EOM
discipline src/tango/fd_tango_base.h)."""
import pytest

pytestmark = pytest.mark.slow
import os

import numpy as np

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm import Account
from firedancer_tpu.tiles.snapshot import state_fingerprint
from firedancer_tpu.utils.checkpt import funk_checkpt


def test_snapshot_restore_pipeline(tmp_path):
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    rng = np.random.default_rng(11)
    funk = Funk()
    for i in range(200):
        key = rng.bytes(32)
        if i % 2:
            funk.rec_write(None, key, Account(
                lamports=int(rng.integers(0, 1 << 50)),
                data=rng.bytes(int(rng.integers(0, 300))),
                owner=rng.bytes(32)))
        else:
            funk.rec_write(None, key, int(rng.integers(0, 1 << 60)))
    want_fp = state_fingerprint(funk)
    path = tmp_path / "snap.ckpt"
    with open(path, "wb") as f:
        funk_checkpt(funk, f)
    # the stream must span MANY frags (multi-frag path exercised)
    assert os.path.getsize(path) > 16 * 1024

    topo = (
        Topology(f"sn{os.getpid()}", wksp_size=1 << 23)
        .link("snap", depth=32, mtu=1280)          # depth << frag count
        .tile("snapld", "snapld", outs=["snap"], path=str(path),
              chunk=1024)
        .tile("snapin", "snapin", ins=["snap"])
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        runner.wait_idle("snapin", "restored", 1, timeout_s=120)
        m = runner.metrics("snapin")
        assert m["accounts"] == 200
        assert m["fingerprint"] == want_fp, "restored state diverged"
        assert m["stream_err"] == 0
        ld = runner.metrics("snapld")
        assert ld["frags"] > 16 and ld["done"] == 1
        assert m["frags"] == ld["frags"]
    finally:
        runner.halt()
        runner.close()
