"""One-off converter: extract the Wycheproof ed25519 verify vectors from
the reference's generated table (test_ed25519_wycheproof.c, itself
generated from the Wycheproof project's eddsa_test.json) into JSON.

Vectors are public test DATA (Wycheproof, Apache-2.0); only the data is
extracted, no code. The `ok` field is the expected verdict of a strict
cofactorless verifier (what fd_ed25519_verify implements — our parity
target).

Usage: python convert_wycheproof.py <path-to-test_ed25519_wycheproof.c>
Writes ed25519_wycheproof.json next to this script.
"""
import json
import os
import re
import sys


def c_string_to_bytes(s: str) -> bytes:
    # the generated file uses only \xNN escapes and ASCII
    return s.encode("latin1").decode("unicode_escape").encode("latin1")


def main(path: str):
    src = open(path).read()
    rec_re = re.compile(
        r"\{\s*\.tc_id\s*=\s*(\d+),\s*"
        r"\.comment\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
        r"\.msg\s*=\s*\(uchar const \*\)\"((?:[^\"\\]|\\.)*)\",\s*"
        r"\.msg_sz\s*=\s*(\d+)UL,\s*"
        r"\.sig\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
        r"\.pub\s*=\s*\"((?:[^\"\\]|\\.)*)\",\s*"
        r"\.ok\s*=\s*(\d+)\s*\}", re.S)
    out = []
    for m in rec_re.finditer(src):
        tc_id, comment, msg, msg_sz, sig, pub, ok = m.groups()
        msg_b = c_string_to_bytes(msg)
        sig_b = c_string_to_bytes(sig)
        pub_b = c_string_to_bytes(pub)
        msg_sz = int(msg_sz)
        # C string literals NUL-terminate: a trailing \x00 in the data
        # is dropped by the literal only if explicitly... they are
        # written fully escaped, so lengths should match exactly.
        assert len(msg_b) >= msg_sz, (tc_id, len(msg_b), msg_sz)
        assert len(sig_b) == 64 and len(pub_b) == 32, tc_id
        out.append({
            "tc_id": int(tc_id),
            "comment": comment,
            "msg": msg_b[:msg_sz].hex(),
            "sig": sig_b.hex(),
            "pub": pub_b.hex(),
            "ok": bool(int(ok)),
        })
    dst = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "ed25519_wycheproof.json")
    with open(dst, "w") as f:
        json.dump(out, f, indent=0)
    print(f"wrote {len(out)} vectors to {dst}")


if __name__ == "__main__":
    main(sys.argv[1])
