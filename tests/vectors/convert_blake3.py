"""Extract the standard BLAKE3 test vectors from the reference's table
(public test data from BLAKE3-team/BLAKE3 test_vectors.json, embedded at
/root/reference/src/ballet/blake3/fd_blake3_test_vector.c) into
blake3_vectors.json: [{"sz": N, "hash": hex}] — the message is always
the standard repeating pattern i % 251 (and the reference's extra
all-zeros rows are kept with "zeros": true)."""
import json
import os
import re

SRC = "/root/reference/src/ballet/blake3/fd_blake3_test_vector.c"
OUT = os.path.join(os.path.dirname(__file__), "blake3_vectors.json")


def main():
    text = open(SRC).read()
    rows = []
    pat = re.compile(
        r'\{\s*(zeros|"(?:[^"\\]|\\x[0-9a-fA-F]{2}|\\[0-7]{1,3})*")\s*,'
        r'\s*(\d+)UL,\s*\{((?:\s*_\([0-9a-f]{2}\),?)+)\s*\}')
    for m in pat.finditer(text):
        msg_tok, sz, hx = m.group(1), int(m.group(2)), m.group(3)
        digest = "".join(re.findall(r'_\(([0-9a-f]{2})\)', hx))
        rows.append({"sz": sz, "zeros": msg_tok == "zeros",
                     "hash": digest})
    assert rows, "no vectors parsed"
    with open(OUT, "w") as f:
        json.dump(rows, f, indent=0)
    print(f"wrote {len(rows)} vectors -> {OUT}")


if __name__ == "__main__":
    main()
