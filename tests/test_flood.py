"""Front-door survival: the WIRED bulk RLC prefilter path and the
policed ingest tiles under adversarial traffic (ROADMAP item 4).

tests/test_rlc.py pins the RLC kernel's semantics (cofactored, the
torsion divergence class); this suite pins the TOPOLOGY WIRING on top:

  * a torsion-point batch that passes the naive cofactored equation is
    still rejected by the deployed prefilter -> strict-re-verify path
    (zero falsely-accepted frags),
  * an all-garbage forged-sig chunk is shed at MSM cost under ingest
    saturation, while a mixed chunk never loses legitimate traffic,
  * the sock/quic/gossip doors police hostile traffic (token buckets,
    bounded Sybil tables, malformed frames dying in the parser),
  * the chaos traffic plans flow through the stem -> on_chaos hook in
    a live topology.

The tier-1 half drives the wired path with a HOST-ARITHMETIC naive-RLC
oracle injected as the tile's _rlc_fn (the MSM graph's CPU compile is
~100 s/shape — the kernel itself is already pinned by test_rlc); the
`slow` half runs the identical drills through the real jitted kernel.
"""
import hashlib
import os
import socket
import time

import numpy as np
import pytest

from firedancer_tpu.runtime import Fseq, Ring, Tcache, Workspace
from firedancer_tpu.tiles.synth import SynthTile, make_signed_txns
from firedancer_tpu.utils import ed25519_ref as ref
from firedancer_tpu.utils.chaos import attack_frames

pytestmark = pytest.mark.flood

BATCH = 32          # matches test_verify_tile: one shared strict jit


@pytest.fixture(scope="module", autouse=True)
def _jax_cache():
    # every prefilter test constructs its own VerifyTile (fresh rings)
    # and each construction jits its own _packed closure — share the
    # repo's persistent compile cache so only the first-ever run pays
    # the strict-kernel compile (the tile adapters' _setup_jax config)
    from firedancer_tpu.disco.tiles import _setup_jax
    _setup_jax()


@pytest.fixture(scope="module")
def wksp():
    w = Workspace(f"/fdtpu_fl_{os.getpid()}", 1 << 24)
    yield w
    w.close()
    w.unlink()


# -- host-arithmetic naive-RLC oracle ---------------------------------------

def _pt_neg(p):
    return (ref.P - p[0], p[1], p[2], ref.P - p[3])


def _pt_is_identity(p):
    zi = pow(p[2], ref.P - 2, ref.P)
    return (p[0] * zi % ref.P, p[1] * zi % ref.P) == (0, 1)


def host_rlc(sig, pub, msg, ln, z):
    """The naive cofactored RLC batch equation in reference
    arithmetic: sum_i z_i * ([S_i]B - [k_i]A_i - R_i) == identity,
    prechecked lanes only — verdict-compatible with
    ops/ed25519.rlc_verify_batch (which tests/test_rlc.py pins),
    including the torsion acceptance when z_i ≡ 0 (mod 8)."""
    sig, pub, msg = map(np.asarray, (sig, pub, msg))
    ln, z = np.asarray(ln), np.asarray(z)
    n = sig.shape[0]
    pre = np.zeros(n, bool)
    acc = (0, 1, 1, 0)
    for i in range(n):
        sb, pb = bytes(sig[i]), bytes(pub[i])
        m = bytes(msg[i, :int(ln[i])])
        s = int.from_bytes(sb[32:], "little")
        a = ref.pt_decompress(pb)
        r = ref.pt_decompress(sb[:32])
        pre[i] = (s < ref.L and a is not None and r is not None
                  and not ref.is_small_order(a)
                  and not ref.is_small_order(r))
        zi = int.from_bytes(bytes(z[i]), "little")
        if not pre[i] or not zi:
            continue
        k = int.from_bytes(
            hashlib.sha512(sb[:32] + pb + m).digest(), "little") % ref.L
        resid = ref.pt_add(
            ref.pt_mul(s, ref.BASEPOINT),
            ref.pt_add(ref.pt_mul(k, _pt_neg(a)), _pt_neg(r)))
        acc = ref.pt_add(acc, ref.pt_mul(zi, resid))
    return _pt_is_identity(acc), pre


def _mk_prefilter_tile(wksp, monkeypatch, rlc_fn=host_rlc, depth=128):
    """A bulk_prefilter VerifyTile wired to an injected RLC backend
    (warmup skipped — the injection replaces the lazy kernel resolve,
    everything downstream of _rlc_fn is the deployed path)."""
    from firedancer_tpu.tiles.verify import VerifyTile
    monkeypatch.setenv("FDTPU_VERIFY_SKIP_RLC_WARMUP", "1")
    in_ring = Ring.create(wksp, depth=depth, mtu=1280)
    out_ring = Ring.create(wksp, depth=depth, mtu=1280)
    tc = Tcache(wksp, depth=512)
    tile = VerifyTile(in_ring, out_ring, tc, batch=BATCH,
                      mode="bulk_prefilter")
    tile._rlc_fn = rlc_fn
    return tile, in_ring, out_ring


def _drain(tile):
    while tile.poll_once():
        pass
    tile.flush()


def _collect(out_ring):
    got, seq = [], 0
    while True:
        rc, frag = out_ring.consume(seq)
        if rc != 0:
            return got
        got.append(bytes(out_ring.payload(frag)))
        seq += 1


def _rig_z(tile, val=8):
    """Pin the z draw to a constant ≡ 0 (mod 8): the cofactored batch
    equation cannot see a pure-8-torsion residual under this draw —
    the strongest position an RLC-evasion attacker can be in."""
    def draw(n):
        z = np.zeros((n, 16), np.uint8)
        z[:, 0] = val
        return z
    tile._draw_z = draw


# -- the wired evasion path -------------------------------------------------

def test_torsion_batch_passes_naive_rlc_but_wired_path_rejects_all(
        wksp, monkeypatch):
    """THE acceptance drill: a torsion-point batch (R* = rB + T,
    S = r + k·a) passes the naive cofactored equation when the z draw
    cooperates — the deployed prefilter must still forward it to the
    strict kernel, which rejects every lane. Zero falsely-accepted
    frags, no shedding of the batch (it LOOKED clean)."""
    tile, in_ring, out_ring = _mk_prefilter_tile(wksp, monkeypatch)
    _rig_z(tile)
    tile._hot_until = 1 << 62   # saturation window: the filter engages
    frames = attack_frames("flood_torsion", 8, seed=21)
    assert len(set(frames)) == 8
    # oracle sanity: under the rigged draw the naive equation ACCEPTS
    # the torsion batch — this is exactly the evasion being attempted
    for i, f in enumerate(frames):
        in_ring.publish(f, sig=i)
    _drain(tile)
    m = tile.metrics
    assert m["rlc_batches"] >= 1 and m["rlc_pass"] >= 1, \
        "the evasion batch must PASS the naive prefilter equation"
    assert m["rlc_shed"] == 0          # it looked clean: no shedding
    assert m["verify_fail"] == 8       # strict caught every lane
    assert m["tx"] == 0
    assert _collect(out_ring) == []    # zero falsely-accepted frags

    # and the same rigged tile still forwards honest traffic
    txns = make_signed_txns(6, seed=31)
    SynthTile(in_ring, txns).run(len(txns))
    _drain(tile)
    assert tile.metrics["tx"] == 6
    assert _collect(out_ring) == txns


def test_forged_flood_sheds_garbage_chunks_mixed_never_collateral(
        wksp, monkeypatch):
    """Forged-sig flood under ingest saturation: an all-garbage chunk
    sheds at (oracle) MSM cost without a strict dispatch; a chunk
    shared with honest traffic always proceeds to strict and the
    honest txns land."""
    tile, in_ring, out_ring = _mk_prefilter_tile(wksp, monkeypatch)
    forged = attack_frames("flood_forged", 8, seed=3)
    for i, f in enumerate(forged):
        in_ring.publish(f, sig=i)
    tile._hot_until = 1 << 62          # saturation window forced open
    _drain(tile)
    m = tile.metrics
    assert m["rlc_shed"] == 8, "all-garbage chunk must shed whole"
    assert m["tx"] == 0 and _collect(out_ring) == []
    shed_before = m["rlc_shed"]

    # mixed chunk: forged + honest gathered together
    txns = make_signed_txns(4, seed=41)
    for i, f in enumerate(attack_frames("flood_forged", 4, seed=5)):
        in_ring.publish(f, sig=100 + i)
    SynthTile(in_ring, txns).run(len(txns))
    tile._hot_until = 1 << 62
    _drain(tile)
    assert tile.metrics["rlc_shed"] == shed_before, \
        "a mixed chunk must never shed (bisect saw a clean half)"
    assert tile.metrics["tx"] == 4
    assert _collect(out_ring) == txns

    # off-hot (peacetime): a sub-full chunk skips the equation
    # entirely and the garbage dies in the strict kernel as usual —
    # fail-closed, nothing shed, the filter idle
    lanes_before = tile.metrics["rlc_lanes"]
    for i, f in enumerate(attack_frames("flood_forged", 8, seed=7)):
        in_ring.publish(f, sig=200 + i)
    tile._hot_until = 0
    _drain(tile)
    assert tile.metrics["rlc_shed"] == shed_before
    assert tile.metrics["rlc_lanes"] == lanes_before   # filter idle
    assert tile.metrics["tx"] == 4     # nothing new forwarded


def test_duplicate_storm_earns_no_device_work(wksp, monkeypatch):
    """flood_dup: one valid txn replayed — every copy past the first
    dies in ha-dedup / the in-flight reservation, and the storm never
    fills a chunk, so the prefilter stays idle too."""
    tile, in_ring, out_ring = _mk_prefilter_tile(wksp, monkeypatch)
    frames = attack_frames("flood_dup", 64, seed=9)
    assert len(set(frames)) == 1
    for i, f in enumerate(frames):
        in_ring.publish(f, sig=i)
    _drain(tile)
    assert tile.metrics["tx"] == 1
    assert tile.metrics["dedup_drop"] == 63
    assert tile.metrics["rlc_lanes"] <= 2


# -- sock door --------------------------------------------------------------

def _send_from(socks, port, payload=b"x" * 64, rounds=1):
    for _ in range(rounds):
        for s in socks:
            s.sendto(payload, ("127.0.0.1", port))


def _drain_sock(tile, spins=200):
    tot = 0
    for _ in range(spins):
        n = tile.poll_once()
        tot += n
        if not n:
            time.sleep(0.002)
    return tot


def test_sock_batch_grain_bytes_exact_and_credit_bounded(wksp):
    """r14 satellite: the sock tile drains a burst into ONE
    publish_batch — frames land byte-identical and in order, jumbos
    drop, and with no shed policy a full ring leaves packets in the
    kernel buffer (the seed behavior)."""
    from firedancer_tpu.tiles.sock import SockTile
    out = Ring.create(wksp, depth=8, mtu=512)
    fseq = Fseq(wksp)
    tile = SockTile(out, [fseq], port=0, batch=16, mtu=256)
    tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    frames = [bytes([i]) * (20 + i) for i in range(6)]
    for f in frames:
        tx.sendto(f, ("127.0.0.1", tile.port))
    tx.sendto(b"J" * 300, ("127.0.0.1", tile.port))   # jumbo: dropped
    deadline = time.monotonic() + 5
    while tile.metrics["rx"] < 6 and time.monotonic() < deadline:
        tile.poll_once()
        time.sleep(0.002)
    assert tile.metrics["rx"] == 6
    assert tile.metrics["oversz"] == 1
    assert _collect(out) == frames     # byte-exact, in order

    # ring full + consumer frozen + no shed: backpressure counts,
    # packets stay queued in the kernel (nothing lost, nothing wedged)
    for i in range(12):
        tx.sendto(b"q%d" % i, ("127.0.0.1", tile.port))
    deadline = time.monotonic() + 5
    while tile.metrics["rx"] < 8 and time.monotonic() < deadline:
        tile.poll_once()
        time.sleep(0.002)
    assert tile.metrics["rx"] == 8     # depth 8, fseq never advanced
    tile.poll_once()                   # one poll against the full ring
    assert tile.metrics["backpressure"] > 0
    fseq.update(6)                     # consumer catches up
    deadline = time.monotonic() + 5
    while tile.metrics["rx"] < 14 and time.monotonic() < deadline:
        tile.poll_once()
        time.sleep(0.002)
    assert tile.metrics["rx"] == 14    # kernel queue preserved the rest
    tile.close()
    tx.close()


def test_sock_shed_flood_drops_newest_staked_lands(wksp):
    """Forged-sig flood drill at the sock door: with the shed armed, a
    full ring drain-and-DROPS hostile bursts (never wedges, never
    grows), shed counters tick, and a staked peer's traffic still
    lands once pressure clears."""
    from firedancer_tpu.tiles.sock import SockTile
    staked_tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    staked_tx.bind(("127.0.0.1", 0))
    skey = f"127.0.0.1:{staked_tx.getsockname()[1]}"
    out = Ring.create(wksp, depth=16, mtu=512)
    fseq = Fseq(wksp)
    tile = SockTile(out, [fseq], port=0, batch=16, mtu=256,
                    shed={"rate_pps": 500.0, "burst": 4,
                          "max_peers": 8, "min_stake": 1,
                          "overload_hold_s": 5.0,
                          "stakes": {skey: 1000}})
    floods = []
    for i in range(20):
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.bind(("127.0.0.1", 0))
        floods.append(s)
    _send_from(floods, tile.port, rounds=8)     # 160 hostile datagrams
    _drain_sock(tile, spins=400)
    m = dict(tile.metrics)             # snapshot (metrics is live)
    assert m["shed"] > 0, "flood must tick shed counters"
    assert m["peers"] <= 8             # bounded Sybil table
    # ring full (depth 16, frozen consumer) + shed armed: overload
    # trips and everything arriving is dropped-newest at the door
    assert m["overload"] == 1
    before = m["rx"]
    _send_from(floods, tile.port, rounds=4)
    _drain_sock(tile, spins=200)
    assert tile.metrics["rx"] == before          # nothing admitted
    assert tile.metrics["shed"] > m["shed"]      # ...everything counted
    # consumer drains -> credits return; the STAKED peer (token budget
    # intact, above min_stake) lands through the still-open overload
    fseq.update(16)
    for i in range(3):
        staked_tx.sendto(b"staked-%d" % i, ("127.0.0.1", tile.port))
    deadline = time.monotonic() + 5
    while tile.metrics["rx"] < before + 3 \
            and time.monotonic() < deadline:
        tile.poll_once()
        time.sleep(0.002)
    assert tile.metrics["rx"] >= before + 3
    payloads = []
    seq = before                       # the flood's 16 filled the ring
    while True:
        rc, frag = out.consume(seq)
        if rc != 0:
            break
        payloads.append(bytes(out.payload(frag)))
        seq += 1
    assert b"staked-0" in payloads and b"staked-2" in payloads
    tile.close()
    staked_tx.close()
    for s in floods:
        s.close()


def test_sock_staked_waiting_room_survives_full_door(wksp):
    """A garbage burst that saturates the ring must not take the
    staked trickle down with it: staked datagrams caught in the full
    door's drain-and-drop park in the bounded waiting room (memory
    O(batch*mtu)) and re-enter through the normal admission gate when
    credits return, in arrival order; unstaked burst-mates are
    dropped-newest as before."""
    from firedancer_tpu.tiles.sock import SockTile
    staked_tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    staked_tx.bind(("127.0.0.1", 0))
    skey = f"127.0.0.1:{staked_tx.getsockname()[1]}"
    out = Ring.create(wksp, depth=8, mtu=512)
    fseq = Fseq(wksp)
    tile = SockTile(out, [fseq], port=0, batch=8, mtu=256,
                    shed={"rate_pps": 500.0, "burst": 16,
                          "max_peers": 8, "min_stake": 1,
                          "overload_hold_s": 30.0,
                          "stakes": {skey: 1000}})
    junk_tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    junk_tx.bind(("127.0.0.1", 0))
    # saturate the ring against a frozen consumer
    for i in range(8):
        junk_tx.sendto(b"fill-%d" % i, ("127.0.0.1", tile.port))
    _drain_sock(tile, spins=100)
    assert tile.metrics["rx"] == 8
    # full door: the staked trickle arrives mixed into a junk burst
    for i in range(3):
        staked_tx.sendto(b"held-%d" % i, ("127.0.0.1", tile.port))
        junk_tx.sendto(b"junk-%d" % i, ("127.0.0.1", tile.port))
    _drain_sock(tile, spins=100)
    assert tile.metrics["rx"] == 8             # ring still full
    assert len(tile._staked_hold) == 3, "staked must park, not drop"
    assert tile.metrics["shed"] >= 3           # junk dropped-newest
    # hold is BOUNDED at batch frames whatever the staked peer sends
    for i in range(2 * tile.batch):
        staked_tx.sendto(b"over-%d" % i, ("127.0.0.1", tile.port))
    _drain_sock(tile, spins=200)
    assert len(tile._staked_hold) <= tile.batch
    # credits return: the waiting room drains FIRST, byte-exact and
    # in arrival order, through the same admission gate
    fseq.update(8)
    deadline = time.monotonic() + 5
    while tile.metrics["rx"] < 11 and time.monotonic() < deadline:
        tile.poll_once()
        time.sleep(0.002)
    payloads = []
    seq = 8
    while True:
        rc, frag = out.consume(seq)
        if rc != 0:
            break
        payloads.append(bytes(out.payload(frag)))
        seq += 1
    assert payloads[:3] == [b"held-0", b"held-1", b"held-2"]
    tile.close()
    staked_tx.close()
    junk_tx.close()


# -- quic door --------------------------------------------------------------

def test_quic_malformed_flood_dies_in_parser_zero_txns(wksp):
    """flood_malformed_quic: garbage wearing QUIC long headers must
    die as bad_pkts — never a crash, never a published txn frag — and
    the Sybil source addresses stay inside the bounded peer table."""
    pytest.importorskip("cryptography")
    from firedancer_tpu.tiles.quic import QuicTile
    out = Ring.create(wksp, depth=64, mtu=1280)
    tile = QuicTile(out, [], port=0, batch=16,
                    shed={"rate_pps": 1000.0, "max_peers": 8})
    frames = attack_frames("flood_malformed_quic", 48, seed=13)
    for i, f in enumerate(frames):
        tile.inject(f, (f"203.0.113.{i % 32 + 1}", 4000 + i))
    tile.poll_once()                   # flush server metrics
    m = tile.metrics
    assert m["txns"] == 0              # zero falsely-accepted frags
    assert m["bad_pkts"] > 0 or m["shed"] > 0
    assert m["peers"] <= 8
    assert _collect(out) == []
    tile.close()


# -- gossip door ------------------------------------------------------------

def test_crds_spam_bounded_table_and_overload_shed(wksp):
    """flood_crds_spam: validly signed values from throwaway unstaked
    origins. The second policing axis (CRDS sender identity) keeps the
    peer table bounded, and overload sheds the spam at the door while
    a staked origin still lands."""
    from firedancer_tpu.tiles.gossip import GossipTile
    staked_seed = hashlib.sha256(b"staked-origin").digest()
    _, _, staked_pub = ref.keypair(staked_seed)
    tile = GossipTile(
        hashlib.sha256(b"node").digest(), port=0,
        shed={"rate_pps": 1000.0, "burst": 64, "max_peers": 8,
              "min_stake": 1, "overload_hold_s": 30.0,
              "stakes": {staked_pub.hex(): 500,
                         "127.0.0.1:65000": 500}})
    spam = attack_frames("flood_crds_spam", 24, seed=17)
    for i, d in enumerate(spam):
        tile.inject(d, (f"198.51.100.{i % 16 + 1}", 3000 + i))
    assert tile.shed.counters()["peers"] <= 8    # bounded Sybil table
    values_peacetime = len(tile.node.crds.values)
    assert values_peacetime > 0        # peacetime: spam is admitted...

    tile.shed.trip_overload()          # ...until pressure trips
    more = attack_frames("flood_crds_spam", 24, seed=18)
    shed0 = tile.shed.shed_total
    for i, d in enumerate(more):
        tile.inject(d, (f"198.51.100.{i % 16 + 101}", 5000 + i))
    assert tile.shed.shed_total > shed0
    assert len(tile.node.crds.values) == values_peacetime, \
        "overloaded door must not grow the CRDS store with spam"
    assert tile.shed.counters()["peers"] <= 8

    # the staked origin's validly signed value still lands, from a
    # staked socket address, through the same overloaded door
    from firedancer_tpu.flamenco import gossip_wire as gw
    from firedancer_tpu.gossip.crds import CrdsValue, KIND_NODE_INSTANCE
    data = staked_pub + (1).to_bytes(8, "little") + b"\x07" * 16
    v = CrdsValue(staked_pub, KIND_NODE_INSTANCE, 0, 1, data)
    sv = CrdsValue(staked_pub, KIND_NODE_INSTANCE, 0, 1, data,
                   ref.sign(staked_seed, v.signable()))
    pkt = gw.encode_container(gw.MSG_PUSH, staked_pub, [sv.to_wire()])
    tile.inject(pkt, ("127.0.0.1", 65000))
    assert len(tile.node.crds.values) == values_peacetime + 1
    tile.close()


def test_repair_door_polices_requests_and_responses():
    """The repair port is internet-facing too (r16): every datagram —
    signed request or shred response — pays one PeerGate admission
    BEFORE the ed25519 verify / shred parse, so a flood dies at the
    cheapest layer; out-ring backpressure trips stake-weighted
    overload and a staked repair peer still lands through the
    overloaded door."""
    from firedancer_tpu.repair.policy import REQ_LEN
    from firedancer_tpu.shred import format as fmt
    from firedancer_tpu.tiles.repair import RepairCore
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.setblocking(False)
    core = RepairCore(
        b"\x01" * 32, lambda p: None, sock,
        shed={"rate_pps": 1000.0, "burst": 8, "max_peers": 8,
              "min_stake": 1, "overload_hold_s": 30.0,
              "stakes": {"127.0.0.1:65001": 500}})
    junk_req = hashlib.sha256(b"junk").digest() * 3  # 96B payload..
    junk_req = (junk_req + junk_req)[:REQ_LEN + 64]  # ..+ garbage sig
    # peacetime Sybil flood: admitted per-peer, dies in sigverify as
    # reqs_refused, and the table never exceeds max_peers
    for i in range(64):
        core.on_datagram(junk_req, (f"203.0.113.{i % 32 + 1}", 4000 + i))
    assert core.metrics["reqs_refused"] == 64
    assert core.shed.counters()["peers"] <= 8
    # pressure trips: the same flood now sheds AT THE DOOR — the
    # refused counter freezes because no signature verify ever runs
    core.shed.trip_overload()
    refused0 = core.metrics["reqs_refused"]
    shed0 = core.shed.shed_total
    for i in range(64):
        core.on_datagram(junk_req, (f"198.51.100.{i % 32 + 1}", 7000 + i))
    assert core.metrics["reqs_refused"] == refused0
    assert core.shed.shed_total >= shed0 + 64
    # shred-sized response spam from an unstaked peer: shed the same
    # way, never counted as a response
    resp = b"\x00" * fmt.SHRED_MIN_SZ
    assert core.on_datagram(resp, ("9.9.9.9", 1)) == 0
    assert core.metrics["resps_in"] == 0
    # the staked repair peer's response still lands
    assert core.on_datagram(resp, ("127.0.0.1", 65001)) == 1
    assert core.metrics["resps_in"] == 1
    sock.close()


def test_repair_backpressure_trips_overload():
    """A stalled FEC-resolver consumer (zero out-ring credits) must
    latch the repair door into overload — the same pressure->shed
    coupling the sock door has, on the response-forward path."""
    from firedancer_tpu.shred import format as fmt
    from firedancer_tpu.tiles.repair import RepairCore

    class _StubRing:
        def __init__(self):
            self.calls = 0
            self.pub = []

        def credits(self, fseqs):
            self.calls += 1
            return 0 if self.calls == 1 else 1   # stalled, then drained

        def publish(self, data, sig=0):
            self.pub.append(bytes(data))

    ring = _StubRing()
    core = RepairCore(
        b"\x02" * 32, lambda p: None, sock=None,
        out_ring=ring, out_fseqs=[object()],
        shed={"rate_pps": 1000.0, "burst": 64, "max_peers": 8,
              "min_stake": 1, "overload_hold_s": 30.0})
    assert not core.shed.overloaded()
    resp = b"\x00" * fmt.SHRED_MIN_SZ
    assert core.on_datagram(resp, ("10.0.0.7", 9)) == 1
    assert core.shed.overloaded()        # pressure latched the door
    assert len(ring.pub) == 1            # ...but the response still went


def test_repair_adapter_declares_shed_slots_and_lint_allows():
    """The adapter exports the shed counters as metric slots (the
    prometheus renderer + flood bench judge off them) and fdlint's
    dead-config check knows repair has an ingest door to police."""
    from firedancer_tpu.disco.tiles import RepairAdapter
    from firedancer_tpu.lint.graph import SHED_KINDS
    assert {"shed", "shed_unstaked", "peers",
            "overload"} <= set(RepairAdapter.METRICS)
    assert {"peers", "overload"} <= set(RepairAdapter.GAUGES)
    assert "repair" in SHED_KINDS


# -- gossvf bulk mode -------------------------------------------------------

def test_gossvf_bulk_wiring_matches_individual(monkeypatch):
    """mode='bulk' verdicts == mode='individual' verdicts for both an
    all-valid packet (bulk accept) and a packet with a corrupt value
    (bulk equation fails -> strict re-verify of survivors)."""
    from firedancer_tpu.gossip import gossvf
    from firedancer_tpu.gossip.crds import CrdsValue, KIND_NODE_INSTANCE

    def oracle(sig, pub, msg, ln, z):
        ok, pre = host_rlc(sig, pub, msg, ln, z)
        return np.bool_(ok), pre
    monkeypatch.setattr(gossvf, "_RLC_FN", oracle)

    vals = []
    for i in range(4):
        seed = hashlib.sha256(b"gv-%d" % i).digest()
        _, _, pub = ref.keypair(seed)
        data = pub + i.to_bytes(8, "little") + bytes(8)
        v = CrdsValue(pub, KIND_NODE_INSTANCE, 0, i, data)
        vals.append(CrdsValue(pub, KIND_NODE_INSTANCE, 0, i, data,
                              ref.sign(seed, v.signable())))
    assert gossvf.batch_verify(vals, mode="bulk") == [True] * 4
    # corrupt one signature: bulk must fall back to strict and agree
    bad = CrdsValue(vals[1].origin, KIND_NODE_INSTANCE, 0, 1,
                    vals[1].data, b"\x01" * 64)
    mixed = [vals[0], bad, vals[2]]
    assert gossvf.batch_verify(mixed, mode="bulk") \
        == gossvf.batch_verify(mixed, mode="individual") \
        == [True, False, True]
    with pytest.raises(ValueError, match="unknown gossvf mode"):
        gossvf.batch_verify(vals, mode="warp")


# -- traffic plans through a live topology ----------------------------------

def test_synth_attack_plan_floods_through_stem_hook():
    """A seeded traffic plan on the synth tile: the stem records the
    injection (EV_CHAOS with the flood action id) and the on_chaos
    hook floods the rendered frames into the out ring — the sink sees
    legit traffic + the attack burst."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    n, frames = 64, 48
    topo = (
        Topology(f"atk{os.getpid()}", wksp_size=1 << 22,
                 trace={"enable": True, "depth": 512, "sample": 1})
        .link("a_b", depth=256, mtu=1280)
        .tile("a", "synth", outs=["a_b"], count=n, unique=16, burst=16,
              chaos={"events": [{"action": "flood_dup", "at_iter": 4,
                                 "frames": frames, "seed": 5}]})
        .tile("b", "sink", ins=["a_b"]))
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=60)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            runner.check_failures()
            if runner.metrics("b")["rx"] >= n + frames:
                break
            time.sleep(0.02)
        a = runner.metrics("a")
        assert a["attack_tx"] + a["attack_drop"] == frames
        assert runner.metrics("b")["rx"] >= n + a["attack_tx"]
        # the injection is on the flight recorder, named
        from firedancer_tpu.trace import read_rings
        from firedancer_tpu.trace.events import CHAOS_ACTION_IDS
        evs = read_rings(runner.plan, runner.wksp)["a"]
        chaos = [e for e in evs if e["ev"] == "chaos"]
        assert chaos and chaos[0]["count"] == \
            CHAOS_ACTION_IDS["flood_dup"]
    finally:
        runner.halt()
        runner.close()


# -- the real kernel (slow) -------------------------------------------------

@pytest.mark.slow
def test_real_kernel_prefilter_flood_and_torsion(wksp, monkeypatch):
    """The identical torsion + forged-flood drills through the REAL
    jitted RLC kernel (CPU limb kernel here, Pallas MSM on
    accelerators) — pinning that the host oracle the tier-1 half used
    is verdict-faithful to the deployed kernel on the wired path."""
    from firedancer_tpu.disco.tiles import _setup_jax
    _setup_jax()                       # persistent compile cache
    monkeypatch.setenv("FDTPU_VERIFY_SKIP_RLC_WARMUP", "1")
    from firedancer_tpu.tiles.verify import VerifyTile
    in_ring = Ring.create(wksp, depth=128, mtu=1280)
    out_ring = Ring.create(wksp, depth=128, mtu=1280)
    tc = Tcache(wksp, depth=512)
    tile = VerifyTile(in_ring, out_ring, tc, batch=16,
                      mode="bulk_prefilter")
    _rig_z(tile)
    tile._hot_until = 1 << 62
    for i, f in enumerate(attack_frames("flood_torsion", 8, seed=21)):
        in_ring.publish(f, sig=i)
    _drain(tile)
    assert tile.metrics["rlc_pass"] >= 1     # naive equation evaded
    assert tile.metrics["verify_fail"] == 8  # strict caught all
    assert tile.metrics["tx"] == 0 and _collect(out_ring) == []

    tile._draw_z = VerifyTile._draw_z.__get__(tile)   # honest draw back
    for i, f in enumerate(attack_frames("flood_forged", 8, seed=3)):
        in_ring.publish(f, sig=100 + i)
    tile._hot_until = 1 << 62
    _drain(tile)
    assert tile.metrics["rlc_shed"] == 8
    assert tile.metrics["tx"] == 0

    txns = make_signed_txns(4, seed=51)
    SynthTile(in_ring, txns).run(len(txns))
    _drain(tile)
    assert tile.metrics["tx"] == 4
    assert _collect(out_ring) == txns
