"""Upgradeable BPF loader tests: the full deploy path through
transactions — buffer write, deploy, execute, upgrade, authority
discipline (ref: src/flamenco/runtime/program/fd_bpf_loader_program.c)."""
import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.pack.cost import BPF_UPGRADEABLE_LOADER_ID
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.loader import (
    ix_deploy, ix_init_buffer, ix_upgrade, ix_write, parse_state,
)
from firedancer_tpu.svm.programs import (
    ERR_BAD_IX_DATA, ERR_INVALID_OWNER, ERR_MISSING_SIG, OK,
)
from tests.test_elf_cpi import RODATA_MSG, _build_elf


def k(n):
    return bytes([n]) * 32


PAYER, BUFFER, PROGRAM, PROGDATA = k(1), k(0x21), k(0x22), k(0x23)


@pytest.fixture
def env():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, PAYER, Account(lamports=1 << 30))
    for a in (BUFFER, PROGRAM, PROGDATA):
        funk.rec_write(None, a, Account(
            lamports=1, owner=BPF_UPGRADEABLE_LOADER_ID))
    funk.txn_prepare(None, "blk")
    ex = TxnExecutor(db, enforce_rent=False)
    ex.slot = 50
    return funk, db, ex


def _run(ex, accts, data, signers=None):
    signers = signers or [PAYER]
    extra = [a for a in accts if a not in signers] \
        + [BPF_UPGRADEABLE_LOADER_ID]
    keys = list(signers) + extra
    prog_idx = len(keys) - 1
    idxs = [keys.index(a) for a in accts]     # signers map to slot 0..
    msg = build_message(signers, extra, b"\x11" * 32,
                        [(prog_idx, bytes(idxs), data)],
                        n_ro_unsigned=1)
    return ex.execute("blk", build_txn(
        [bytes(64)] * len(signers), msg))


def _deploy(ex, elf):
    assert _run(ex, [BUFFER, PAYER], ix_init_buffer()).status == OK
    # write in two chunks
    mid = len(elf) // 2
    assert _run(ex, [BUFFER, PAYER], ix_write(0, elf[:mid])).status == OK
    assert _run(ex, [BUFFER, PAYER],
                ix_write(mid, elf[mid:])).status == OK
    r = _run(ex, [PROGRAM, PROGDATA, BUFFER, PAYER],
             ix_deploy(len(elf)))
    assert r.status == OK, r.status


def test_deploy_and_execute(env):
    funk, db, ex = env
    elf = _build_elf()
    _deploy(ex, elf)
    prog = db.peek("blk", PROGRAM)
    assert prog.executable
    st, info = parse_state(prog.data)
    assert info["programdata"] == PROGDATA
    pst, pinfo = parse_state(db.peek("blk", PROGDATA).data)
    assert pinfo["elf"] == elf and pinfo["slot"] == 50
    # the deployed program EXECUTES through the indirection
    msg = build_message([PAYER], [PROGRAM], b"\x11" * 32,
                        [(1, b"", b"")], n_ro_unsigned=1)
    r = ex.execute("blk", build_txn([bytes(64)], msg))
    assert r.status == OK, r.logs
    assert any(RODATA_MSG.decode() in ln for ln in r.logs)


def test_write_requires_buffer_authority(env):
    funk, db, ex = env
    assert _run(ex, [BUFFER, PAYER], ix_init_buffer()).status == OK
    evil = k(0x66)
    funk.rec_write("blk", evil, Account(lamports=1 << 30))
    r = _run(ex, [BUFFER, evil], ix_write(0, b"x" * 8), signers=[evil])
    assert r.status == ERR_MISSING_SIG


def test_deploy_rejects_broken_elf(env):
    funk, db, ex = env
    assert _run(ex, [BUFFER, PAYER], ix_init_buffer()).status == OK
    assert _run(ex, [BUFFER, PAYER],
                ix_write(0, b"\x7fELFjunk" * 4)).status == OK
    r = _run(ex, [PROGRAM, PROGDATA, BUFFER, PAYER], ix_deploy(64))
    assert r.status == ERR_BAD_IX_DATA
    assert not db.peek("blk", PROGRAM).executable


def test_upgrade_swaps_elf_with_authority_check(env):
    funk, db, ex = env
    elf = _build_elf()
    _deploy(ex, elf)
    # stage a second buffer with a (different but valid) ELF
    elf2 = _build_elf()
    BUF2 = k(0x31)
    funk.rec_write("blk", BUF2, Account(
        lamports=1, owner=BPF_UPGRADEABLE_LOADER_ID))
    assert _run(ex, [BUF2, PAYER], ix_init_buffer()).status == OK
    assert _run(ex, [BUF2, PAYER], ix_write(0, elf2)).status == OK
    # wrong authority refused
    evil = k(0x66)
    funk.rec_write("blk", evil, Account(lamports=1 << 30))
    r = _run(ex, [PROGDATA, PROGRAM, BUF2, evil], ix_upgrade(),
             signers=[evil])
    assert r.status == ERR_INVALID_OWNER
    # right authority upgrades
    ex.slot = 60
    r = _run(ex, [PROGDATA, PROGRAM, BUF2, PAYER], ix_upgrade())
    assert r.status == OK, r.status
    pst, pinfo = parse_state(db.peek("blk", PROGDATA).data)
    assert pinfo["slot"] == 60


def test_upgrade_cannot_repoint_foreign_program(env):
    """Security pin: Upgrade with accounts [attacker_pdata,
    victim_program, attacker_buffer, attacker] must refuse — the
    program's state must point at the PASSED programdata."""
    funk, db, ex = env
    elf = _build_elf()
    _deploy(ex, elf)                      # victim PROGRAM deployed
    A_PD, A_BUF = k(0x41), k(0x42)
    evil = k(0x66)
    funk.rec_write("blk", evil, Account(lamports=1 << 30))
    for a in (A_PD, A_BUF):
        funk.rec_write("blk", a, Account(
            lamports=1, owner=BPF_UPGRADEABLE_LOADER_ID))
    assert _run(ex, [A_BUF, evil], ix_init_buffer(),
                signers=[evil]).status == OK
    assert _run(ex, [A_BUF, evil], ix_write(0, _build_elf()),
                signers=[evil]).status == OK
    # attacker deploys their own pdata so it has THEIR authority
    A_PROG = k(0x43)
    funk.rec_write("blk", A_PROG, Account(
        lamports=1, owner=BPF_UPGRADEABLE_LOADER_ID))
    assert _run(ex, [A_PROG, A_PD, A_BUF, evil],
                ix_deploy(4096), signers=[evil]).status == OK
    # refill a buffer and try to repoint the VICTIM program
    assert _run(ex, [A_BUF, evil], ix_init_buffer(),
                signers=[evil]).status == OK
    assert _run(ex, [A_BUF, evil], ix_write(0, _build_elf()),
                signers=[evil]).status == OK
    r = _run(ex, [A_PD, PROGRAM, A_BUF, evil], ix_upgrade(),
             signers=[evil])
    assert r.status == ERR_INVALID_OWNER
    st, info = parse_state(db.peek("blk", PROGRAM).data)
    assert info["programdata"] == PROGDATA       # untouched


def test_deploy_cannot_overwrite_live_programdata(env):
    """Security pin: Deploy into an initialized programdata refuses."""
    funk, db, ex = env
    _deploy(ex, _build_elf())                    # PROGDATA now live
    evil = k(0x66)
    A_BUF, A_PROG = k(0x42), k(0x43)
    funk.rec_write("blk", evil, Account(lamports=1 << 30))
    for a in (A_BUF, A_PROG):
        funk.rec_write("blk", a, Account(
            lamports=1, owner=BPF_UPGRADEABLE_LOADER_ID))
    assert _run(ex, [A_BUF, evil], ix_init_buffer(),
                signers=[evil]).status == OK
    assert _run(ex, [A_BUF, evil], ix_write(0, _build_elf()),
                signers=[evil]).status == OK
    r = _run(ex, [A_PROG, PROGDATA, A_BUF, evil], ix_deploy(4096),
             signers=[evil])
    assert r.status == ERR_INVALID_OWNER
    pst, pinfo = parse_state(db.peek("blk", PROGDATA).data)
    assert pinfo["authority"] == PAYER           # untouched


def test_write_offset_bounded(env):
    funk, db, ex = env
    assert _run(ex, [BUFFER, PAYER], ix_init_buffer()).status == OK
    r = _run(ex, [BUFFER, PAYER], ix_write(0xFFFF_FF00, b"x"))
    assert r.status == ERR_BAD_IX_DATA
    assert len(db.peek("blk", BUFFER).data) < 1024
