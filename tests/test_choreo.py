"""choreo tests: the worked examples from the reference's tower/ghost
tutorial comments, replayed against our implementation
(ref: src/choreo/tower/fd_tower.h:1-340, src/choreo/ghost/fd_ghost.h,
src/choreo/eqvoc/fd_eqvoc.h)."""
import pytest

from firedancer_tpu.choreo import (
    EqvocDetector, FecMeta, Ghost, Tower,
)


def bid(n: int) -> bytes:
    return n.to_bytes(32, "little")


# ---------------------------------------------------------------------------
# tower state transitions (fd_tower.h worked examples)
# ---------------------------------------------------------------------------

def tower_of(*pairs):
    t = Tower()
    for slot, conf in pairs:
        t.votes.append(__import__(
            "firedancer_tpu.choreo.tower", fromlist=["TowerVote"]
        ).TowerVote(slot, conf))
    return t


def as_pairs(t: Tower):
    return [(v.slot, v.conf) for v in t.votes]


def test_vote_expiry_doc_example():
    """Tower [1:4, 2:3, 3:2, 4:1]; vote 9 expires 4 (exp 6) and 3 (exp 7)
    giving [1:4, 2:3, 9:1] (ref: fd_tower.h 'vote for slot 9')."""
    t = tower_of((1, 4), (2, 3), (3, 2), (4, 1))
    assert t.vote(9) is None
    assert as_pairs(t) == [(1, 4), (2, 3), (9, 1)]


def test_vote_doubling_doc_example():
    """Then vote 10: only the consecutive run doubles -> 9's conf becomes
    2, while 2 and 1 are unchanged (gap at conf 2)."""
    t = tower_of((1, 4), (2, 3), (9, 1))
    assert t.vote(10) is None
    assert as_pairs(t) == [(1, 4), (2, 3), (9, 2), (10, 1)]


def test_expiry_is_top_down_contiguous():
    """fd_tower.h: voting 11 does NOT expire vote 2 (exp 10 < 11)
    because 10 (exp 12) and 9 (exp 13) survive on top and expiry stops
    at the first survivor; the fully-consecutive tower then doubles
    every lockout."""
    t = tower_of((1, 4), (2, 3), (9, 2), (10, 1))
    t.vote(11)
    assert as_pairs(t) == [(1, 5), (2, 4), (9, 3), (10, 2), (11, 1)]


def test_rooting_pops_bottom_at_max():
    t = Tower(max_lockout_history=4)
    assert t.vote(1) is None
    assert t.vote(2) is None
    assert t.vote(3) is None
    assert t.vote(4) is None
    # 5th consecutive vote roots the bottom
    assert t.vote(5) == 1
    assert t.root == 1
    assert as_pairs(t) == [(2, 4), (3, 3), (4, 2), (5, 1)]


def test_full_depth_rooting():
    t = Tower()
    roots = [t.vote(s) for s in range(1, 40)]
    # the 32nd consecutive vote roots slot 1
    assert roots[:31] == [None] * 31
    assert roots[31] == 1
    assert roots[32] == 2
    assert len(t.votes) == 31


def test_vote_must_advance():
    t = tower_of((5, 1))
    with pytest.raises(ValueError):
        t.vote(5)


# ---------------------------------------------------------------------------
# ghost (fd_ghost.h)
# ---------------------------------------------------------------------------

def make_fork_tree():
    """fd_tower.h switch-check diagram:
               /-- 7
          /-- 3-- 4
    1-- 2  -- 6
          \\-- 5-- 9
    """
    g = Ghost(bid(1), 1, total_stake=100)
    g.insert(bid(2), 2, bid(1))
    g.insert(bid(3), 3, bid(2))
    g.insert(bid(4), 4, bid(3))
    g.insert(bid(7), 7, bid(3))
    g.insert(bid(6), 6, bid(2))
    g.insert(bid(5), 5, bid(2))
    g.insert(bid(9), 9, bid(5))
    return g


def test_ghost_weight_rollup_and_best():
    g = make_fork_tree()
    g.replay_vote(b"v1", 30, bid(4))
    g.replay_vote(b"v2", 38, bid(9))
    # subtree weights roll up (fd_ghost.h "subtree" paragraph)
    assert g.weight(bid(2)) == 68
    assert g.weight(bid(3)) == 30
    assert g.weight(bid(5)) == 38
    # greedy heaviest traversal picks 9
    assert g.best() == bid(9)


def test_ghost_lmd_revote_moves_stake():
    g = make_fork_tree()
    g.replay_vote(b"v1", 30, bid(4))
    assert g.best() == bid(4)
    g.replay_vote(b"v1", 30, bid(9))   # latest message replaces the old
    assert g.weight(bid(3)) == 0
    assert g.weight(bid(9)) == 30
    assert g.best() == bid(9)


def test_ghost_tie_break_lower_slot():
    """Equal weights tie-break to the LOWER slot
    (ref: fd_ghost.c:149-153)."""
    g = make_fork_tree()
    g.replay_vote(b"v1", 10, bid(4))
    g.replay_vote(b"v2", 10, bid(9))
    # weights at 2's children: 3 -> 10, 5 -> 10, 6 -> 0; 3 < 5 wins
    assert g.best() == bid(4)


def test_ghost_equivocation_invalid_then_confirmed():
    g = Ghost(bid(1), 1, total_stake=100)
    g.insert(bid(2), 2, bid(1))
    g.insert(bid(40), 4, bid(2))    # block 4
    g.insert(bid(41), 4, bid(2))    # equivocating 4'
    g.replay_vote(b"v1", 30, bid(41))
    g.replay_vote(b"v2", 52, bid(40))
    g.mark_invalid(bid(40))
    g.mark_invalid(bid(41))
    # both versions invalid: fork choice stops at 2 (fd_ghost.h)
    assert g.best() == bid(2)
    # 52% on the real 4: duplicate confirmed, valid again
    assert g.check_duplicate_confirmed(bid(40))
    assert not g.check_duplicate_confirmed(bid(41))
    assert g.best() == bid(40)


def test_ghost_gca_and_publish():
    g = make_fork_tree()
    assert g.gca(bid(4), bid(9)) == bid(2)
    assert g.gca(bid(7), bid(4)) == bid(3)
    assert g.is_ancestor(bid(2), bid(9))
    assert not g.is_ancestor(bid(4), bid(9))
    g.replay_vote(b"v1", 10, bid(4))
    g.replay_vote(b"v2", 20, bid(9))
    g.publish(bid(5))
    assert set(g.nodes) == {bid(5), bid(9)}
    assert g.root == bid(5)
    assert g.weight(bid(5)) == 20            # pruned fork's stake is gone
    # votes for pruned blocks are dropped; new votes still work
    g.replay_vote(b"v1", 10, bid(9))
    assert g.weight(bid(9)) == 30


# ---------------------------------------------------------------------------
# tower checks against ghost
# ---------------------------------------------------------------------------

def test_lockout_check_doc_example():
    """fd_tower.h: tower [1:4,2:3,3:2,4:1] on fork ...-3-4; slot 5 on the
    other fork is locked out (exp of 4 is 6); slot 9 descending 5 passes
    (9 > every cross-fork expiration)."""
    g = make_fork_tree()
    t = tower_of((1, 4), (2, 3), (3, 2), (4, 1))
    vote_blocks = {1: bid(1), 2: bid(2), 3: bid(3), 4: bid(4)}
    assert not t.lockout_check(bid(5), 5, g, vote_blocks)
    assert t.lockout_check(bid(9), 9, g, vote_blocks)
    # same-fork voting is never locked out
    assert t.lockout_check(bid(7), 7, g, vote_blocks)


def test_threshold_check():
    t = Tower()
    for s in range(1, 10):
        t.vote(s)
    # tower depth 9; vote at depth 8 incl. simulated vote 10 -> slot 2.
    # voter towers need lockouts surviving the simulated vote for 10
    # (conf >= 3 at slot 5: exp 13), else they expire and don't count
    voters_pass = [(70, tower_of((5, 3))), (30, tower_of((1, 5)))]
    voters_fail = [(50, tower_of((5, 3))), (50, tower_of((1, 5)))]
    assert t.threshold_check(10, voters_pass, 100)
    assert not t.threshold_check(10, voters_fail, 100)
    # shallow towers always pass
    assert Tower().threshold_check(10, [], 100)


def test_threshold_check_expires_stale_votes():
    """A voter whose only vote expires under the simulated vote must not
    count (ref: fd_tower.c threshold_check comment)."""
    t = Tower()
    for s in range(1, 10):
        t.vote(s)
    # voter's vote for slot 5 conf 1 expires at 7 < 10 -> not counted
    voters = [(70, tower_of((5, 1))), (30, tower_of((2, 5)))]
    assert not t.threshold_check(10, voters, 100)


def test_switch_check_doc_example():
    """The fd_tower.h switch diagram: last vote 4, target 9, GCA 2.
    Stake on 7 does NOT count (same GCA-subtree as our vote); stake on
    5/9 and 6 does."""
    g = make_fork_tree()
    t = tower_of((4, 1))
    g.replay_vote(b"us", 10, bid(4))
    g.replay_vote(b"v7", 30, bid(7))          # our own GCA-subtree
    g.replay_vote(b"v9", 30, bid(9))
    assert not t.switch_check(bid(9), bid(4), g)   # 30 < 38
    g.replay_vote(b"v6", 8, bid(6))
    assert t.switch_check(bid(9), bid(4), g)       # 38 >= 38
    # switching within our own fork is always allowed
    assert t.switch_check(bid(7), bid(4), g) is True \
        or t.switch_check(bid(4), bid(4), g)


# ---------------------------------------------------------------------------
# eqvoc (fd_eqvoc.h)
# ---------------------------------------------------------------------------

def test_eqvoc_direct_proof():
    d = EqvocDetector()
    a = FecMeta(7, 0, b"r1" * 16, b"s1" * 32, data_cnt=32)
    assert d.insert_fec(a) is None
    assert d.insert_fec(a) is None            # identical re-insert: fine
    b = FecMeta(7, 0, b"r2" * 16, b"s2" * 32, data_cnt=32)
    proof = d.insert_fec(b)
    assert proof is not None and proof.kind == "direct"
    assert proof.slot == 7 and proof.a == a and proof.b == b


def test_eqvoc_overlap_proof():
    d = EqvocDetector()
    assert d.insert_fec(FecMeta(7, 0, b"r1" * 16, b"s1" * 32,
                                data_cnt=32)) is None
    # a second set starting inside [0, 32) implies two block layouts
    p = d.insert_fec(FecMeta(7, 16, b"r3" * 16, b"s3" * 32, data_cnt=32))
    assert p is not None and p.kind == "overlap"
    # non-overlapping set is fine
    assert d.insert_fec(FecMeta(7, 32, b"r4" * 16, b"s4" * 32,
                                data_cnt=32)) is None


def test_eqvoc_block_ids_and_prune():
    d = EqvocDetector()
    assert not d.note_block_id(5, bid(50))
    assert d.note_block_id(5, bid(51))        # duplicate block
    assert not d.note_block_id(6, bid(60))
    d.insert_fec(FecMeta(5, 0, b"r" * 16, b"s" * 32, 32))
    d.prune(6)
    assert 5 not in d.block_ids and (5, 0) not in d.fecs
    assert 6 in d.block_ids
