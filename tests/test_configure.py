"""configure preflight stages (ref: src/app/shared/commands/configure/
check/fix contract)."""
import resource

from firedancer_tpu.app import configure as cf


def test_check_runs_on_live_host():
    stages = cf.check(wksp_bytes=1 << 20)
    names = [s["stage"] for s in stages]
    assert names == ["shm", "hugepages", "nofile", "memlock", "cpus",
                     "somaxconn", "overcommit"]
    for s in stages:
        assert s["status"] in (cf.PASS, cf.WARN, cf.FAIL)
        assert s["detail"]
    # 1 MiB of shm must exist on any runnable host
    assert stages[0]["status"] == cf.PASS


def test_shm_fail_when_impossible():
    st = cf.stage_shm(wksp_bytes=1 << 50)      # petabyte: impossible
    assert st["status"] in (cf.WARN, cf.FAIL)
    assert st["fix"]


def test_fix_nofile_raises_soft_toward_hard():
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    try:
        want = min(hard, soft + 1) if hard != resource.RLIM_INFINITY \
            else soft + 1
        assert cf.fix_nofile(want)
        assert resource.getrlimit(resource.RLIMIT_NOFILE)[0] >= want
    finally:
        resource.setrlimit(resource.RLIMIT_NOFILE, (soft, hard))


def test_cli_prints_and_exits(capsys):
    rc = cf.main(["check", "--wksp-bytes", str(1 << 20)])
    out = capsys.readouterr().out
    assert "shm" in out and '"result"' in out
    assert rc in (0, 2)
