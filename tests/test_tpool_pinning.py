"""tpool fork-join + tile core pinning tests
(ref: src/util/tpool/fd_tpool.h:933-972 exec_all range splitting;
src/util/tile/fd_tile.h:6-38 core pinning)."""
import hashlib
import os
import time

import pytest

from firedancer_tpu.shred.shredder import Shredder
from firedancer_tpu.utils.tpool import TPool


def test_exec_all_covers_every_index():
    tp = TPool(4)
    hits = [0] * 103
    def fn(wid, i0, i1):
        for i in range(i0, i1):
            hits[i] += 1
    tp.exec_all(fn, 103)
    assert hits == [1] * 103
    tp.exec_all(fn, 3)                   # fewer items than workers
    assert sum(hits) == 106
    tp.exec_all(fn, 0)                   # empty is a no-op
    tp.close()


def test_exec_all_reraises_worker_exception():
    tp = TPool(3)
    def boom(wid, i0, i1):
        if i0 == 0:
            raise RuntimeError("worker died")
    with pytest.raises(RuntimeError, match="worker died"):
        tp.exec_all(boom, 9)
    # pool survives a failed fork-join
    out = []
    tp.exec_all(lambda w, a, b: out.append((a, b)), 6)
    assert sorted(out) == [(0, 2), (2, 4), (4, 6)]
    tp.close()


def test_map_chunks_preserves_order():
    tp = TPool(4)
    items = list(range(50))
    got = tp.map_chunks(lambda chunk: [x * 2 for x in chunk], items)
    assert got == [x * 2 for x in items]
    tp.close()


def test_gil_releasing_workload_actually_parallelizes():
    """sha256 releases the GIL: the pool must beat serial on a chunky
    hashing workload (the shredder's leaf profile)."""
    if len(os.sched_getaffinity(0)) < 2:
        pytest.skip("single-core machine")
    blobs = [bytes([i & 0xFF]) * 200_000 for i in range(64)]
    def hash_all(chunk):
        return [hashlib.sha256(b).digest() for b in chunk]
    t0 = time.perf_counter()
    serial = hash_all(blobs)
    t_serial = time.perf_counter() - t0
    tp = TPool(4)
    tp.map_chunks(hash_all, blobs)       # warm
    t0 = time.perf_counter()
    par = tp.map_chunks(hash_all, blobs)
    t_par = time.perf_counter() - t0
    tp.close()
    assert par == serial
    assert t_par < t_serial * 0.9, (t_par, t_serial)


def test_shredder_with_tpool_is_byte_identical():
    tp = TPool(3)
    batch = bytes(range(256)) * 40
    sets_serial = Shredder(lambda r: b"\x05" * 64).shred_batch(
        batch, 3, 1, 0, True)
    sets_pool = Shredder(lambda r: b"\x05" * 64, tpool=tp).shred_batch(
        batch, 3, 1, 0, True)
    tp.close()
    assert len(sets_serial) == len(sets_pool)
    for a, b in zip(sets_serial, sets_pool):
        assert a.merkle_root == b.merkle_root
        assert a.data_shreds == b.data_shreds
        assert a.parity_shreds == b.parity_shreds


@pytest.mark.slow
def test_tile_process_pinning():
    """cpu_idx pins the tile process to one core (sched_getaffinity
    observed from inside via /proc)."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"pin{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=32, mtu=256)
        .tile("src", "synth", outs=["a_b"], count=0, cpu_idx=1)
        .tile("dst", "sink", ins=["a_b"], cpu_idx=2)
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        avail = sorted(os.sched_getaffinity(0))
        want = {"src": avail[1 % len(avail)],
                "dst": avail[2 % len(avail)]}
        for name, proc in runner.procs.items():
            allowed = os.sched_getaffinity(proc.pid)
            assert allowed == {want[name]}, (name, allowed)
    finally:
        runner.halt()
        runner.close()
