"""Chaos harness + graceful TPU degradation (utils/chaos.py,
tiles/verify.py device-fault path).

The verify-tile drills run in-process (no topology spawn): transient
device failure must be absorbed by bounded retry, persistent failure
must degrade to the CPU reference ed25519 path with verdicts
byte-identical to utils/ed25519_ref — sigverify survives a lost TPU.
The stalled-consumer drill runs a live topology: a consumer whose fseq
freezes while it keeps heartbeating is the watchdog's
consumer-progress case.
"""
import os
import time

import numpy as np
import pytest

from firedancer_tpu.utils.chaos import ChaosPlan


def _wedge_s() -> float:
    """The ONE 2-core deflake policy: test_supervise.py owns the
    watchdog-window scaling; import it so a retune cannot drift."""
    from test_supervise import WEDGE_S
    return WEDGE_S

pytestmark = pytest.mark.chaos


# -- fault-plan semantics ---------------------------------------------------

def test_plan_parses_fires_once_and_rejects_unknown_actions():
    plan = ChaosPlan({"events": [{"action": "crash", "at_iter": 5},
                                 {"action": "freeze_hb", "at_rx": 3}]})
    assert plan.poll(1, 0) == []
    due = plan.poll(5, 0)
    assert [e["action"] for e in due] == ["crash"]
    assert plan.poll(6, 0) == []               # fires exactly once
    assert [e["action"] for e in plan.poll(6, 3)] == ["freeze_hb"]
    with pytest.raises(ValueError, match="unknown chaos action"):
        ChaosPlan({"events": [{"action": "meteor"}]})
    with pytest.raises(ValueError, match="dict"):
        ChaosPlan([1, 2])


def test_seeded_ranges_are_deterministic():
    spec = {"seed": 42,
            "events": [{"action": "crash", "at_iter": [100, 10000]}]}
    a = ChaosPlan(spec).events[0]["at_iter"]
    b = ChaosPlan(spec).events[0]["at_iter"]
    assert a == b and 100 <= a <= 10000
    c = ChaosPlan({**spec, "seed": 43}).events[0]["at_iter"]
    assert a != c                      # a different seed moves the point


def test_fail_dispatch_budget_counts_down():
    p = ChaosPlan({"events": [{"action": "fail_dispatch", "count": 2}]})
    assert p.take_dispatch_failure() and p.take_dispatch_failure()
    assert not p.take_dispatch_failure()
    forever = ChaosPlan(
        {"events": [{"action": "fail_dispatch", "count": -1}]})
    assert all(forever.take_dispatch_failure() for _ in range(64))


# -- verify tile: transient + persistent device failure ---------------------

BATCH = 32


@pytest.fixture(scope="module")
def wksp():
    from firedancer_tpu.runtime import Workspace
    w = Workspace(f"/fdtpu_ch_{os.getpid()}", 1 << 24)
    yield w
    w.close()
    w.unlink()


@pytest.fixture(scope="module")
def txns():
    from firedancer_tpu.tiles.synth import make_signed_txns
    return make_signed_txns(12, seed=7)


def _mk_tile(wksp, **kw):
    from firedancer_tpu.runtime import Ring, Tcache
    from firedancer_tpu.tiles.verify import VerifyTile
    in_ring = Ring.create(wksp, depth=64, mtu=1280)
    out_ring = Ring.create(wksp, depth=64, mtu=1280)
    tc = Tcache(wksp, depth=512)
    return VerifyTile(in_ring, out_ring, tc, batch=BATCH, **kw), \
        in_ring, out_ring


def _drive(tile, in_ring, txns, extra=()):
    for i, t in enumerate(txns):
        in_ring.publish(t, sig=i)
    for j, t in enumerate(extra):
        in_ring.publish(t, sig=1000 + j)
    while tile.poll_once():
        pass
    tile.flush()


def _collect(out_ring):
    got, seq = [], 0
    while True:
        rc, frag = out_ring.consume(seq)
        if rc != 0:
            break
        got.append(bytes(out_ring.payload(frag)))
        seq += 1
    return got


def test_transient_dispatch_failure_absorbed_by_retry(wksp, txns):
    """One injected dispatch failure < retry budget: every txn still
    verifies on the device path, no fallback engaged."""
    tile, in_ring, out_ring = _mk_tile(
        wksp, device_retries=2,
        chaos={"events": [{"action": "fail_dispatch", "count": 1}]})
    _drive(tile, in_ring, txns)
    assert tile.metrics["tx"] == len(txns)
    assert tile.metrics["device_errors"] == 1
    assert tile.metrics["cpu_fallback"] == 0 and not tile.degraded
    assert _collect(out_ring) == list(txns)


def test_persistent_dispatch_failure_degrades_to_cpu(wksp, txns):
    """Every dispatch fails: after device_fail_limit consecutive
    failures the tile flips to the CPU reference path and KEEPS
    serving — valid txns forwarded byte-identical, a corrupted
    signature still rejected (fail-closed)."""
    bad = bytearray(txns[0])
    bad[10] ^= 1          # corrupt inside signature 0
    bad[-1] ^= 1          # ...and the message, so the tag differs
    tile, in_ring, out_ring = _mk_tile(
        wksp, device_retries=1, device_fail_limit=2,
        chaos={"events": [{"action": "fail_dispatch", "count": -1}]})
    # two waves -> two failed dispatches == device_fail_limit
    _drive(tile, in_ring, txns[:6])
    assert not tile.degraded              # first failure: still trying
    _drive(tile, in_ring, txns[6:], extra=[bytes(bad)])
    m = tile.metrics
    assert tile.degraded and m["cpu_fallback"] == 1
    assert m["device_errors"] >= 2
    assert m["tx"] == len(txns)
    assert m["verify_fail"] == 1          # the corrupted txn
    # byte-identical to the reference verifier's accept set
    assert _collect(out_ring) == list(txns)


def test_degraded_verdicts_match_reference_verifier(wksp, txns):
    """The CPU fallback IS utils/ed25519_ref.verify: cross-check every
    forwarded payload against it directly."""
    from firedancer_tpu.protocol.txn import parse_txn
    from firedancer_tpu.utils.ed25519_ref import verify as ref_verify
    tile, in_ring, out_ring = _mk_tile(
        wksp, device_retries=0, device_fail_limit=1,
        chaos={"events": [{"action": "fail_dispatch", "count": -1}]})
    _drive(tile, in_ring, txns)
    assert tile.degraded
    forwarded = _collect(out_ring)
    assert forwarded == list(txns)
    for p in forwarded:
        t = parse_txn(p)
        msg = t.message(p)
        assert all(ref_verify(s, k, msg)
                   for s, k in zip(t.signatures(p), t.signer_pubkeys(p)))


def test_inflight_duplicate_window_closed(wksp, txns):
    """A duplicate arriving while its twin is still in device flight
    must not be forwarded twice (the r5 pipeline-window hole): publish
    the same txn, poll (dispatch, do NOT drain), publish again, poll —
    exactly one copy may ever be forwarded."""
    tile, in_ring, out_ring = _mk_tile(wksp)
    tile.inflight = 4          # keep batches pending across polls
    in_ring.publish(txns[0], sig=0)
    tile.poll_once()           # txn 0 now in flight (not finalized)
    in_ring.publish(txns[0], sig=1)
    tile.poll_once()           # duplicate inside the pipeline window
    tile.flush()
    assert tile.metrics["tx"] == 1
    assert tile.metrics["dedup_drop"] == 1
    assert _collect(out_ring) == [txns[0]]


def test_inflight_reservation_cannot_censor_victim(wksp, txns):
    """A garbage txn carrying the victim's signature (same dedup tag)
    dispatched just ahead of the victim must not censor it: the
    reservation DEFERS the victim, the garbage fails verify, and the
    victim is re-verified and forwarded at finalize."""
    tile, in_ring, out_ring = _mk_tile(wksp)
    tile.inflight = 4
    victim = txns[1]
    attacker = bytearray(victim)
    attacker[-1] ^= 0xFF       # victim's sig bytes, corrupted message
    in_ring.publish(bytes(attacker), sig=0)
    tile.poll_once()           # attacker in flight, tag reserved
    in_ring.publish(victim, sig=1)
    tile.poll_once()           # victim deferred against the reservation
    tile.flush()
    assert tile.metrics["verify_fail"] == 1     # the attacker
    assert tile.metrics["tx"] == 1              # the victim, delivered
    assert _collect(out_ring) == [victim]


# -- live topology: stalled consumer ----------------------------------------

def test_stalled_consumer_fseq_recovers_via_watchdog():
    """Chaos freezes the sink's fseq publication while it keeps
    heartbeating and consuming: the producer backpressures on a full
    ring, the watchdog's consumer-progress check trips, the sink is
    restarted with a tail rejoin, and the producer finishes every
    send — the topology never wedges.

    With the flight recorder armed, the WHOLE causal chain must also be
    reconstructable post-hoc: the chaos injection and the watchdog trip
    land in the supervisor's black-box dump (snapshotted from shm at
    trip time, before the restart reuses the ring), and the restart +
    respawned boot land in the live ring after it — fault ->
    watchdog-trip -> restart, in timestamp order, from trace data
    alone."""
    import json

    from firedancer_tpu.disco import Topology, TopologyRunner
    n = 600
    topo = (
        Topology(f"cs{os.getpid()}", wksp_size=1 << 22,
                 trace={"enable": True, "depth": 1024, "sample": 1})
        .link("a_b", depth=32, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=n, unique=16, burst=8)
        .tile("b", "sink", ins=["a_b"],
              supervise={"policy": "restart", "backoff_s": 0.05,
                         "max_restarts": 4, "window_s": 30.0,
                         # THE shared 2-core deflake window (a small
                         # box's scheduler stalls healthy tiles past a
                         # 0.4 s deadline — the r10 tier-1 flake)
                         "wedge_timeout_s": _wedge_s()},
              chaos={"events": [{"action": "stall_fseq", "at_rx": 8}]})
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        t0 = time.time()
        while time.time() - t0 < 90:
            runner.check_failures()
            m = runner.metrics
            # the producer unwedges the moment the stalled fseq is
            # marked stale (before the respawn even lands) — wait for
            # the full recovery: all sends done AND the sink respawned
            if m("a")["tx"] >= n and m("b")["sup_restarts"] >= 1 \
                    and m("b")["sup_down"] == 0:
                break
            time.sleep(0.02)
        assert runner.metrics("a")["tx"] == n, "producer wedged"
        b = runner.metrics("b")
        assert b["sup_watchdog_trips"] >= 1
        assert b["sup_restarts"] >= 1

        # -- black-box reconstruction (fdtrace) ---------------------------
        path = runner.supervisor.blackbox["b"]
        with open(path) as f:
            dump = json.load(f)
        assert dump["tile"] == "b" and "watchdog" in dump["reason"]
        evs = dump["events"]
        chaos_ts = [e["ts"] for e in evs if e["ev"] == "chaos"]
        trip_ts = [e["ts"] for e in evs if e["ev"] == "watchdog"]
        assert chaos_ts and trip_ts, [e["ev"] for e in evs]
        assert chaos_ts[0] < trip_ts[-1]       # fault BEFORE the trip
        # the injected action is named in the dump's chrome view
        from firedancer_tpu.trace.events import CHAOS_ACTION_IDS
        assert [e["count"] for e in evs if e["ev"] == "chaos"][0] \
            == CHAOS_ACTION_IDS["stall_fseq"]
        # ...and the dump is directly Perfetto-openable
        assert any(e.get("name") == "watchdog"
                   for e in dump["chrome"]["traceEvents"])

        # live ring: restart marker + the respawned tile's boot, both
        # AFTER the trip — the recorder survives the tile's death
        from firedancer_tpu.trace import read_rings
        deadline = time.time() + 30
        while time.time() < deadline:
            live = read_rings(runner.plan, runner.wksp)["b"]
            boots = [e["ts"] for e in live if e["ev"] == "boot"
                     and e["ts"] > trip_ts[-1]]
            if boots:
                break
            time.sleep(0.05)
        restarts = [e["ts"] for e in live if e["ev"] == "restart"]
        assert restarts and boots, [e["ev"] for e in live[-12:]]
        assert trip_ts[-1] <= restarts[-1] <= boots[-1]
        os.unlink(path)                    # test hygiene (/dev/shm)
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()


# -- adversarial traffic plans (r14) ----------------------------------------

def test_traffic_plan_schema_and_deterministic_frames():
    """Traffic-plan events carry a frame budget + per-event seed
    derived from the plan seed: same plan -> same attack bytes; the
    CHAOS_ACTION_IDS lockstep (test_trace) covers the new actions."""
    from firedancer_tpu.utils.chaos import attack_frames
    spec = {"seed": 7, "events": [
        {"action": "flood_forged", "at_iter": 10, "frames": 32},
        {"action": "flood_crds_spam", "at_iter": 20}]}
    a = ChaosPlan(spec).events
    b = ChaosPlan(spec).events
    assert a[0]["frames"] == 32 and a[1]["frames"] == 256  # default
    assert [e["seed"] for e in a] == [e["seed"] for e in b]
    assert attack_frames("flood_forged", 8, seed=a[0]["seed"]) \
        == attack_frames("flood_forged", 8, seed=b[0]["seed"])
    with pytest.raises(ValueError, match="unknown traffic action"):
        attack_frames("flood_meteor", 4)
    assert attack_frames("flood_dup", 0) == []


def test_attack_plan_injection_survives_tile_crash():
    """An attack plan's injection events survive the attacker tile's
    own crash mid-flood (the stalled-consumer drill's contract,
    extended to traffic actions): the stem records EV_CHAOS BEFORE
    rendering frames, so the supervisor's black-box dump names the
    attack — flood first, crash after — and the flooded frames
    already reached the sink."""
    import json

    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.trace.events import CHAOS_ACTION_IDS
    topo = (
        Topology(f"atkbb{os.getpid()}", wksp_size=1 << 22,
                 trace={"enable": True, "depth": 512, "sample": 1})
        .link("a_b", depth=256, mtu=1280)
        .tile("a", "synth", outs=["a_b"], count=4096, unique=16,
              burst=8,
              supervise={"policy": "restart", "backoff_s": 0.1,
                         "max_restarts": 1, "window_s": 30.0},
              chaos={"events": [
                  {"action": "flood_forged", "at_iter": 6,
                   "frames": 24, "seed": 9},
                  {"action": "crash", "at_iter": 40, "code": 71}]})
        .tile("b", "sink", ins=["a_b"]))
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=60)
        deadline = time.time() + 60
        while time.time() < deadline:
            if "a" in runner.supervisor.blackbox:
                break
            try:
                runner.check_failures()
            except RuntimeError:
                pass                   # the crash IS the drill
            time.sleep(0.05)
        path = runner.supervisor.blackbox.get("a")
        assert path, "crash must leave a black-box dump"
        with open(path) as f:
            dump = json.load(f)
        chaos = [(e["ts"], e["count"]) for e in dump["events"]
                 if e["ev"] == "chaos"]
        ids = [c for _, c in chaos]
        assert CHAOS_ACTION_IDS["flood_forged"] in ids
        assert CHAOS_ACTION_IDS["crash"] in ids
        flood_ts = min(t for t, c in chaos
                       if c == CHAOS_ACTION_IDS["flood_forged"])
        crash_ts = max(t for t, c in chaos
                       if c == CHAOS_ACTION_IDS["crash"])
        assert flood_ts < crash_ts     # attack named BEFORE the death
        # the flood's frames made it out before the crash
        assert runner.metrics("a")["attack_tx"] > 0
        assert runner.metrics("b")["rx"] > 0
        os.unlink(path)                # test hygiene (/dev/shm)
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()
