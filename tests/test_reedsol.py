"""Reed-Solomon: host oracle properties + MXU bit-matrix path equality."""
import numpy as np
import pytest

from firedancer_tpu.utils import gf256


def test_gf_field_axioms():
    rng = np.random.default_rng(1)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert gf256.gf_mul(a, b) == gf256.gf_mul(b, a)
        assert gf256.gf_mul(a, gf256.gf_mul(b, c)) == \
            gf256.gf_mul(gf256.gf_mul(a, b), c)
        # distributes over xor
        assert gf256.gf_mul(a, b ^ c) == \
            gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
        if a:
            assert gf256.gf_mul(a, gf256.gf_inv(a)) == 1


def test_parity_matrix_systematic_construction():
    # spot-check the construction against hand-computed Vandermonde math
    m = gf256.parity_matrix(4, 2)
    v = np.array([[gf256.gf_pow(i, j) for j in range(4)] for i in range(6)],
                 np.uint8)
    want = gf256.mat_mul(v[4:], gf256.mat_inv(v[:4]))
    assert (m == want).all()
    # encode-then-recover identity for several erasure patterns
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (4, 64), np.uint8)
    par = gf256.encode(data, 2)
    code = {i: data[i] for i in range(4)} | {4 + i: par[i] for i in range(2)}
    for missing in ([0], [3], [0, 2], [1, 3]):
        have = {k: v for k, v in code.items() if k not in missing}
        got = gf256.recover(have, 4, 2)
        assert (got == data).all(), missing


@pytest.mark.parametrize("d,p", [(32, 32), (16, 4), (8, 8), (67, 67)])
def test_mxu_encode_matches_oracle(d, p):
    from firedancer_tpu.ops import reedsol
    rng = np.random.default_rng(d * 100 + p)
    sz = 64
    data = rng.integers(0, 256, (d, sz), np.uint8)
    want = gf256.encode(data, p)
    got = np.asarray(reedsol.encode(data, p))
    assert (got == want).all()


def test_mxu_encode_batched_and_recover():
    from firedancer_tpu.ops import reedsol
    rng = np.random.default_rng(9)
    d, p, sz, sets = 32, 32, 128, 4
    data = rng.integers(0, 256, (sets, d, sz), np.uint8)
    par = np.asarray(reedsol.encode(data, p))
    for s in range(sets):
        assert (par[s] == gf256.encode(data[s], p)).all()

    # erase 20 data shreds + 12 parity shreds, rebuild on device
    missing = set(range(0, 40, 2))
    present = sorted(set(range(d + p)) - missing)[:d]
    code = np.concatenate([data, par], axis=1)          # (sets, d+p, sz)
    surv = code[:, present, :]
    got = np.asarray(reedsol.recover(surv, tuple(present), d, p))
    assert (got == data).all()
