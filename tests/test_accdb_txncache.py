"""accdb facade + transaction status cache tests
(ref: src/flamenco/accdb/fd_accdb_user.h vtable semantics,
src/flamenco/runtime/fd_txncache.c fork-aware status queries)."""
import numpy as np
import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm import (
    AccDb, Account, SystemTxn, TxnCache, execute_block,
    execute_block_serial,
)


def k(n: int) -> bytes:
    return n.to_bytes(32, "big")


# ---------------------------------------------------------------------------
# accdb
# ---------------------------------------------------------------------------

def test_accdb_handles_and_fork_visibility():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, k(1), Account(lamports=500, data=b"hello"))

    assert db.peek(None, k(1)).lamports == 500
    assert db.peek(None, k(2)) is None

    funk.txn_prepare(None, "f1")
    # open_rw is copy-on-write: nothing lands until close_rw
    h = db.open_rw("f1", k(1))
    h.account.lamports = 400
    assert db.peek("f1", k(1)).lamports == 500
    db.close_rw(h)
    assert db.peek("f1", k(1)).lamports == 400
    assert db.peek("f1", k(1)).data == b"hello"    # fields preserved
    assert db.peek(None, k(1)).lamports == 500     # root untouched

    # discard path: a failed txn drops its handle without landing
    h2 = db.open_rw("f1", k(1))
    h2.account.lamports = 1
    db.close_rw(h2, discard=True)
    assert db.peek("f1", k(1)).lamports == 400

    # publish folds the fork into the root
    funk.txn_publish("f1")
    assert db.peek(None, k(1)).lamports == 400
    assert db.rw_active == 0 and db.ro_active == 0


def test_accdb_create_and_double_close():
    funk = Funk()
    db = AccDb(funk)
    funk.txn_prepare(None, "x")
    assert db.open_rw("x", k(9)) is None           # absent, no create
    h = db.open_rw("x", k(9), do_create=True)
    assert h.created and h.account.lamports == 0
    h.account.lamports = 77
    db.close_rw(h)
    assert db.lamports("x", k(9)) == 77
    with pytest.raises(RuntimeError, match="double close"):
        db.close_rw(h)


def test_accdb_ro_copy_is_defensive():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, k(3), Account(lamports=10))
    ro = db.open_ro(None, k(3))
    ro.lamports = 999
    assert db.peek(None, k(3)).lamports == 10
    db.close_ro(ro)


def test_executor_over_typed_accounts():
    """The wave executor reads/writes accdb-typed Accounts, preserving
    non-balance fields across a block."""
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, k(1), Account(lamports=1000, data=b"vote-state"))
    funk.rec_write(None, k(2), Account(lamports=5))
    txns = [SystemTxn(src=k(1), dst=k(2), amount=300, fee=10),
            SystemTxn(src=k(2), dst=k(1), amount=100, fee=0)]
    oracle = {k(1): 1000, k(2): 5}
    want = execute_block_serial(oracle, txns)
    got = execute_block(funk, None, "blk", txns)
    assert got == want
    for kk in (k(1), k(2)):
        assert db.lamports("blk", kk) == oracle.get(kk, 0)
    assert db.peek("blk", k(1)).data == b"vote-state"


# ---------------------------------------------------------------------------
# txncache
# ---------------------------------------------------------------------------

def test_txncache_fork_aware_queries():
    tc = TxnCache()
    bh, sig = b"h" * 32, b"s" * 64
    tc.insert(10, bh, sig, status=0)
    # visible on the fork containing slot 10, invisible on a rival fork
    assert tc.query(bh, sig, {8, 9, 10}) == 0
    assert tc.query(bh, sig, {8, 9, 11}) is None
    assert tc.query(bh, b"z" * 64, {10}) is None
    assert tc.query(b"x" * 32, sig, {10}) is None
    # the same sig landing on the rival fork too: each fork sees its own
    tc.insert(11, bh, sig, status=1)
    assert tc.query(bh, sig, {11}) == 1
    assert tc.query(bh, sig, {10}) == 0


def test_txncache_rooted_history_always_visible():
    tc = TxnCache()
    bh, sig = b"h" * 32, b"s" * 64
    tc.insert(10, bh, sig)
    tc.register_root(12)
    # slot 10 <= root: published history, on every fork
    assert tc.query(bh, sig, set()) == 0


def test_txncache_prunes_aged_blockhashes():
    tc = TxnCache(max_age_slots=20)
    old, new = b"o" * 32, b"n" * 32
    tc.insert(5, old, b"a" * 64)
    tc.insert(100, new, b"b" * 64)
    tc.register_root(50)
    assert tc.query(old, b"a" * 64, {5}) is None      # pruned
    assert tc.query(new, b"b" * 64, {100}) == 0
    assert len(tc) == 1


def test_accdb_reads_legacy_int_records():
    """Genesis writes bare lamport ints; the facade must see the
    balance, and an rw open over one must preserve it (upgrade to a
    typed record on close), never wipe it."""
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, k(4), 500)
    assert db.lamports(None, k(4)) == 500
    assert db.peek(None, k(4)).lamports == 500
    funk.txn_prepare(None, "x")
    h = db.open_rw("x", k(4), do_create=True)
    assert not h.created and h.account.lamports == 500
    h.account.data = b"upgraded"
    db.close_rw(h)
    assert db.peek("x", k(4)).lamports == 500
    assert funk.rec_query("x", k(4)).data == b"upgraded"


def test_executor_typed_block_creates_typed_accounts():
    """In a typed block, a brand-new destination account must land as a
    typed Account (visible to accdb), not a bare int."""
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, k(1), Account(lamports=1000))
    st = execute_block(funk, None, "blk",
                       [SystemTxn(src=k(1), dst=k(7), amount=100, fee=0)])
    assert st == [0]
    assert isinstance(funk.rec_query("blk", k(7)), Account)
    assert db.lamports("blk", k(7)) == 100


def test_txncache_abandoned_fork_entries_purged_on_root():
    """An entry recorded on a fork that loses must not become visible
    as rooted history when the root passes its slot."""
    tc = TxnCache()
    bh, sig = b"h" * 32, b"s" * 64
    tc.insert(5, bh, sig)             # minority fork, slot 5
    tc.insert(6, bh, b"t" * 64)       # rooted fork, slot 6
    tc.register_root(10, rooted_slots={6, 7, 8, 9, 10})
    assert tc.query(bh, sig, {6, 7, 8, 9, 10}) is None
    assert tc.query(bh, b"t" * 64, set()) == 0


def test_eqvoc_partial_then_complete_extent():
    """A set first seen with unknown extent (data_cnt=0) must still
    yield an overlap proof once its true extent is known."""
    from firedancer_tpu.choreo import EqvocDetector, FecMeta
    d = EqvocDetector()
    assert d.insert_fec(FecMeta(7, 0, b"r" * 16, b"s" * 32,
                                data_cnt=0)) is None
    assert d.insert_fec(FecMeta(7, 16, b"q" * 16, b"t" * 32,
                                data_cnt=16)) is None
    # completing set 0's metadata reveals it spans [0, 32) over set 16
    p = d.insert_fec(FecMeta(7, 0, b"r" * 16, b"s" * 32, data_cnt=32))
    assert p is not None and p.kind == "overlap"


def test_txncache_blocks_replay_within_window():
    """The consensus property: a txn can't execute twice on one fork
    while its blockhash is live."""
    rng = np.random.default_rng(5)
    tc = TxnCache()
    ancestors = set()
    bh = b"r" * 32
    executed = set()
    for slot in range(1, 30):
        ancestors.add(slot)
        sig = bytes(rng.integers(0, 4, 64, dtype=np.uint8))  # collisions
        if tc.query(bh, sig, ancestors) is None:
            tc.insert(slot, bh, sig)
            assert sig not in executed, "replayed a signature!"
            executed.add(sig)
