"""Sysvar accounts: layouts, slot-boundary materialization, and the
account-view == syscall-view invariant (ref: src/flamenco/runtime/
sysvar/fd_sysvar_clock.c, fd_sysvar_cache.h)."""
import struct

import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm.accdb import AccDb, Account
from firedancer_tpu.svm import sysvars as sv
from firedancer_tpu.svm.programs import TxnExecutor
from firedancer_tpu.utils.base58 import b58_encode_32


@pytest.fixture
def env():
    funk = Funk()
    funk.txn_prepare(None, "blk")
    db = AccDb(funk)
    return funk, db


def test_wellknown_addresses_roundtrip():
    assert b58_encode_32(sv.CLOCK_ID) == \
        "SysvarC1ock11111111111111111111111111111111"
    assert b58_encode_32(sv.RENT_ID) == \
        "SysvarRent111111111111111111111111111111111"
    assert b58_encode_32(sv.SLOT_HASHES_ID) == \
        "SysvarS1otHashes111111111111111111111111111"


def test_layout_sizes_and_rent_pin():
    assert len(sv.enc_clock(1, 2)) == 40
    assert len(sv.enc_rent()) == 17
    assert len(sv.enc_epoch_schedule(432_000)) == 33
    # the well-known mainnet minimum for a 0-byte account
    assert sv.rent_exempt_minimum(0) == 890_880


def test_clock_roundtrip():
    b = sv.enc_clock(777, 3, epoch_start_ts=-5, unix_ts=42)
    d = sv.dec_clock(b)
    assert d["slot"] == 777 and d["epoch"] == 3
    assert d["epoch_start_timestamp"] == -5
    assert d["unix_timestamp"] == 42
    assert d["leader_schedule_epoch"] == 4


def test_update_materializes_accounts(env):
    funk, db = env
    sv.update_sysvars(db, "blk", slot=10, epoch=0,
                      blockhash=b"\xAB" * 32)
    clock = db.peek("blk", sv.CLOCK_ID)
    assert clock is not None
    assert clock.owner == sv.SYSVAR_OWNER
    assert sv.dec_clock(bytes(clock.data))["slot"] == 10
    assert clock.lamports == sv.rent_exempt_minimum(len(clock.data))
    sh = db.peek("blk", sv.SLOT_HASHES_ID)
    assert sv.dec_slot_hashes(bytes(sh.data)) == [(9, b"\xAB" * 32)]


def test_slot_hashes_accumulate_newest_first_capped(env):
    funk, db = env
    for s in range(1, 20):
        sv.update_sysvars(db, "blk", slot=s, epoch=0,
                          blockhash=bytes([s]) * 32)
    got = sv.dec_slot_hashes(
        bytes(db.peek("blk", sv.SLOT_HASHES_ID).data))
    assert got[0] == (18, bytes([19]) * 32)
    assert got[-1] == (0, bytes([1]) * 32)
    assert len(got) == 19
    # cap
    entries = [(i, bytes(32)) for i in range(600)]
    assert len(sv.dec_slot_hashes(sv.enc_slot_hashes(entries))) == 512


def test_syscall_view_equals_account_view(env):
    funk, db = env
    ex = TxnExecutor(db, enforce_rent=False)
    ex.begin_slot("blk", slot=55, blockhash=b"\x01" * 32)
    cache = sv.read_sysvar_cache(db, "blk", 0, 0)
    clock_acct = bytes(db.peek("blk", sv.CLOCK_ID).data)
    rent_acct = bytes(db.peek("blk", sv.RENT_ID).data)
    assert cache["clock"] == clock_acct[:40]
    assert cache["rent"] == rent_acct[:17]
    assert ex.slot == 55 and ex.epoch == 0


def test_syscall_view_falls_back_without_accounts(env):
    funk, db = env
    cache = sv.read_sysvar_cache(db, "blk", 9, 2)
    assert sv.dec_clock(cache["clock"])["slot"] == 9
    assert sv.dec_clock(cache["clock"])["epoch"] == 2
    assert struct.unpack_from("<Q", cache["rent"], 0)[0] == \
        sv.LAMPORTS_PER_BYTE_YEAR


def test_epoch_schedule_syscall_serves_account_bytes(env):
    """sol_get_epoch_schedule_sysvar returns the SAME bytes as the
    materialized sysvar account (the two-view invariant)."""
    funk, db = env
    from firedancer_tpu.svm.programs import TxnExecutor
    from firedancer_tpu.vm import Vm
    from firedancer_tpu.vm.interp import INPUT_START
    from firedancer_tpu.vm.syscalls import (
        sys_get_epoch_schedule_sysvar)
    ex = TxnExecutor(db, enforce_rent=False)
    ex.begin_slot("blk", slot=7, slots_per_epoch=1000)
    cache = sv.read_sysvar_cache(db, "blk", 0, 0)
    vm = Vm(b"\x95" + bytes(7), input_data=bytes(64))
    vm._cu = 0
    vm.sysvars = cache
    assert sys_get_epoch_schedule_sysvar(vm, INPUT_START,
                                         0, 0, 0, 0) == 0
    got = vm.mem_read(INPUT_START, 33)
    assert got == bytes(db.peek("blk", sv.EPOCH_SCHEDULE_ID).data[:33])
    assert struct.unpack_from("<Q", got, 0)[0] == 1000
