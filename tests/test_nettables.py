"""Route/neighbor tables (waltz/nettables.py): procfs parsing, LPM
semantics, live-kernel smoke (ref: src/waltz/ip/fd_fib4.h,
src/disco/netlink/fd_netlink_tile.c)."""
import os

from firedancer_tpu.waltz.nettables import (Fib4, NeighTable, Route,
                                            ip_str, parse_neigh,
                                            parse_routes,
                                            refresh_from_proc)

ROUTE_FIXTURE = """\
Iface\tDestination\tGateway \tFlags\tRefCnt\tUse\tMetric\tMask\t\tMTU\tWindow\tIRTT
eth0\t00000000\t010011AC\t0003\t0\t0\t100\t00000000\t0\t0\t0
eth0\t000011AC\t00000000\t0001\t0\t0\t100\t0000FFFF\t0\t0\t0
docker0\t000012AC\t00000000\t0001\t0\t0\t200\t0000FFFF\t0\t0\t0
eth0\t040011AC\t00000000\t0005\t0\t0\t50\t FFFFFFFF\t0\t0\t0
"""

ARP_FIXTURE = """\
IP address       HW type     Flags       HW address            Mask     Device
172.17.0.1       0x1         0x2         02:42:ac:11:00:01     *        eth0
172.17.0.9       0x1         0x0         00:00:00:00:00:00     *        eth0
"""


def test_parse_routes_and_lpm():
    fib = Fib4(parse_routes(ROUTE_FIXTURE))
    assert len(fib) == 4
    # host route wins over the /16
    r = fib.lookup("172.17.0.4")
    assert r.prefix_len == 32 and ip_str(r.dst) == "172.17.0.4"
    # /16 beats default
    r = fib.lookup("172.17.5.5")
    assert r.prefix_len == 16 and r.iface == "eth0" and r.gw == 0
    # off-subnet goes to the default route's gateway
    iface, hop = fib.next_hop("8.8.8.8")
    assert iface == "eth0" and ip_str(hop) == "172.17.0.1"
    # directly-connected next hop is the destination itself
    iface, hop = fib.next_hop("172.17.0.9")
    assert ip_str(hop) == "172.17.0.9"
    # no match at all
    assert Fib4([]).lookup("1.2.3.4") is None


def test_metric_tiebreak_same_prefix():
    fib = Fib4(parse_routes(ROUTE_FIXTURE))
    # 172.18/16 exists only via docker0
    assert fib.lookup("172.18.0.7").iface == "docker0"
    # add a better-metric duplicate prefix: it must win
    fib.insert(Route(dst=fib.lookup("172.18.0.7").dst,
                     mask=0xFFFF0000, gw=0, iface="fast0", metric=10,
                     flags=1))
    assert fib.lookup("172.18.0.7").iface == "fast0"


def test_parse_neigh():
    nt = NeighTable(parse_neigh(ARP_FIXTURE))
    # the incomplete (flags 0x0, zero-MAC) entry is filtered: an
    # in-progress neighbor reads as unresolved
    assert len(nt) == 1
    assert nt.mac_of("172.17.0.1") == "02:42:ac:11:00:01"
    assert nt.mac_of("172.17.0.9") is None
    assert nt.mac_of("10.0.0.1") is None


def test_live_kernel_smoke():
    """Against the real procfs: parses without error; when routes
    exist, the default lookup resolves to some interface."""
    fib, neigh = refresh_from_proc()
    if os.path.exists("/proc/net/route") and len(fib):
        hop = fib.next_hop("8.8.8.8")
        assert hop is None or isinstance(hop[0], str)
