"""FEC resolver + turbine destination tests
(ref: src/disco/shred/fd_fec_resolver.c, fd_shred_dest.c).

The resolver is exercised against the repo's own Shredder output —
shred -> drop a random subset -> resolve -> the recovered entry batch
must be byte-identical to the original."""
import os

import numpy as np
import pytest

from firedancer_tpu.shred import ClusterNode, FecResolver, ShredDest, Shredder
from firedancer_tpu.utils.ed25519_ref import keypair, sign, verify

SEED = bytes(range(32))
_, _, LEADER_PK = keypair(SEED)


def make_sets(batch: bytes, chained=False):
    sh = Shredder(sign_fn=lambda root: sign(SEED, root), shred_version=7)
    return sh.shred_batch(batch, slot=9, parent_off=1, ref_tick=3,
                          block_complete=True,
                          chained_root=bytes(32) if chained else None)


def resolver():
    return FecResolver(
        verify_sig=lambda sig, root, slot: verify(sig, LEADER_PK, root))


def roundtrip(batch: bytes, drop, chained=False):
    """Shred, deliver all shreds except indices in `drop` (per set,
    data-first ordering), return concatenated resolved payloads."""
    sets = make_sets(batch, chained)
    r = resolver()
    out = {}
    for fs in sets:
        wires = list(fs.data_shreds) + list(fs.parity_shreds)
        keep = [w for i, w in enumerate(wires) if i not in drop]
        for w in keep:
            done, eq = r.add_shred(w)
            assert eq is None
            if done:
                assert done.merkle_root == fs.merkle_root
                out[done.fec_set_idx] = b"".join(done.data_payloads)
    assert len(out) == len(sets), (len(out), r.metrics)
    return b"".join(out[k] for k in sorted(out)), r


def test_resolve_no_loss():
    rng = np.random.default_rng(1)
    batch = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    got, r = roundtrip(batch, drop=set())
    assert got == batch
    assert r.metrics["recovered"] == 0


@pytest.mark.parametrize("chained", [False, True])
def test_resolve_with_data_loss(chained):
    """Drop data shreds; parity must reconstruct them bit-exactly."""
    rng = np.random.default_rng(2)
    batch = rng.integers(0, 256, 12_000, dtype=np.uint8).tobytes()
    got, r = roundtrip(batch, drop={0, 3, 5}, chained=chained)
    assert got == batch
    assert r.metrics["recovered"] >= 3
    assert r.metrics["root_mismatch"] == 0


def test_resolve_data_only_completion():
    """All data shreds arrive, no parity needed."""
    rng = np.random.default_rng(3)
    batch = rng.integers(0, 256, 3000, dtype=np.uint8).tobytes()
    sets = make_sets(batch)
    r = resolver()
    done = None
    for w in sets[0].data_shreds:
        done, _ = r.add_shred(w)
    assert done is not None and b"".join(done.data_payloads) == batch


def test_resolver_rejects_bad_signature():
    rng = np.random.default_rng(4)
    batch = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
    sets = make_sets(batch)
    r = FecResolver(verify_sig=lambda sig, root, slot: False)
    for w in sets[0].data_shreds:
        done, _ = r.add_shred(w)
        assert done is None
    assert r.metrics["bad_sig"] > 0


def test_resolver_rejects_corrupt_payload():
    """A flipped payload byte breaks the inclusion proof."""
    rng = np.random.default_rng(5)
    batch = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
    sets = make_sets(batch)
    w = bytearray(sets[0].data_shreds[0])
    w[0x100] ^= 1
    r = resolver()
    done, _ = r.add_shred(bytes(w))
    assert done is None
    assert r.metrics["bad_sig"] + r.metrics["bad_proof"] == 1


def test_resolver_flags_equivocation():
    """Two shredder runs over different content for the same slot/set
    key must produce an equivocation signal, not a silent overwrite."""
    rng = np.random.default_rng(6)
    a = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
    b = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
    sa = make_sets(a)[0]
    sb = make_sets(b)[0]
    r = resolver()
    r.add_shred(sa.data_shreds[0])
    done, eq = r.add_shred(sb.data_shreds[1])
    assert done is None and eq == (9, 0)
    assert r.metrics["eqvoc"] == 1


# ---------------------------------------------------------------------------
# turbine destinations
# ---------------------------------------------------------------------------

def _cluster(n, seed=7):
    rng = np.random.default_rng(seed)
    return [ClusterNode(pubkey=bytes([i]) * 32,
                        stake=int(rng.integers(1, 1000)) * 1000,
                        addr=(f"10.0.0.{i}", 8000 + i))
            for i in range(n)]


def test_turbine_tree_partition():
    """Every non-leader node appears exactly once; children sets are
    disjoint; root + all children cover the cluster (fanout chosen so
    the 3-level Agave tree spans: cnt-1 <= fanout^2 + fanout)."""
    nodes = _cluster(50)
    leader = nodes[0].pubkey
    # first_hop is the LEADER's query (compute_first removes source)
    sd = ShredDest(nodes, self_pubkey=leader, fanout=7)
    order = sd.tree_positions(5, 17, 0x80, leader)
    assert len(order) == 49 and leader not in order
    assert len(set(order)) == 49
    seen = set()
    for n in nodes:
        if n.pubkey == leader:
            continue
        sdn = ShredDest(nodes, self_pubkey=n.pubkey, fanout=7)
        for c in sdn.children(5, 17, 0x80, leader):
            assert c.pubkey not in seen, "child claimed twice"
            seen.add(c.pubkey)
    root = sd.first_hop(5, 17, 0x80, leader).pubkey
    assert seen | {root} == set(order)


def test_turbine_deterministic_and_shred_dependent():
    nodes = _cluster(30)
    leader = nodes[3].pubkey
    sd = ShredDest(nodes, self_pubkey=nodes[1].pubkey)
    a = sd.tree_positions(5, 17, 0x80, leader)
    b = sd.tree_positions(5, 17, 0x80, leader)
    assert a == b                       # deterministic
    c = sd.tree_positions(5, 18, 0x80, leader)
    assert a != c                       # different shred -> different tree


def test_turbine_stake_weighting():
    """A dominant-stake node should be the first hop for most shreds."""
    nodes = _cluster(20)
    whale = ClusterNode(pubkey=b"\xaa" * 32, stake=10**12)
    nodes.append(whale)
    leader = nodes[0].pubkey
    # the leader runs compute_first (source == self is removed)
    sd = ShredDest(nodes, self_pubkey=leader, fanout=6)
    hits = sum(sd.first_hop(5, i, 0x80, leader).pubkey == whale.pubkey
               for i in range(40))
    assert hits >= 30, hits
    # unstaked nodes sort after all staked nodes
    nodes.append(ClusterNode(pubkey=b"\xbb" * 32, stake=0))
    sd2 = ShredDest(nodes, self_pubkey=whale.pubkey)
    order = sd2.tree_positions(6, 1, 0x80, leader)
    assert order[-1] == b"\xbb" * 32
