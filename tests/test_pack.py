"""Pack scheduler property tests.

Reference model: src/disco/pack/test_pack.c + test_pack_tile.c — the
no-conflict invariant, cost-limit enforcement, and priority order are
checked against brute-force recomputation from the raw account sets
(never trusting the scheduler's own bitsets).
"""
import random

import pytest

from firedancer_tpu.pack import PackScheduler, PackLimits, TxnMeta


def mk_meta(writes, reads=(), reward=5000, cost=10_000, vote=False):
    return TxnMeta(payload=b"", txn=None, reward=reward, cost=cost,
                   writes=tuple(bytes([w]) * 32 for w in writes),
                   reads=tuple(bytes([r]) * 32 for r in reads),
                   is_vote=vote)


def brute_conflict(a: TxnMeta, b: TxnMeta) -> bool:
    aw, ar = set(a.writes), set(a.reads)
    bw, br = set(b.writes), set(b.reads)
    return bool(aw & bw) or bool(aw & br) or bool(ar & bw)


def test_non_conflicting_parallel_banks():
    s = PackScheduler(bank_cnt=2)
    s.insert(mk_meta([1], reward=9000))
    s.insert(mk_meta([2], reward=8000))
    s.insert(mk_meta([1], reward=7000))   # conflicts with first
    mb0 = s.schedule_microblock(0)
    mb1 = s.schedule_microblock(1)
    # bank 0 takes accts {1,2} (both fit, no mutual conflict); bank 1
    # cannot take the acct-1 txn while bank 0 holds it
    assert len(mb0) == 2
    assert len(mb1) == 0
    s.microblock_done(0)
    mb1 = s.schedule_microblock(1)
    assert len(mb1) == 1 and mb1[0].writes[0] == bytes([1]) * 32


def test_read_write_conflicts():
    s = PackScheduler(bank_cnt=2)
    s.insert(mk_meta([1], [], reward=9000))       # writes 1
    s.insert(mk_meta([], [1], reward=8000))       # reads 1
    s.insert(mk_meta([], [2], reward=7000))       # reads 2
    s.insert(mk_meta([2], [], reward=6000))       # writes 2
    mb0 = s.schedule_microblock(0)
    # within one microblock w1 + r1 conflict; w1 + r2 don't
    accts = [(m.writes, m.reads) for m in mb0]
    for i in range(len(mb0)):
        for j in range(i + 1, len(mb0)):
            assert not brute_conflict(mb0[i], mb0[j])
    mb1 = s.schedule_microblock(1)
    for a in mb0:
        for b in mb1:
            assert not brute_conflict(a, b)


def test_priority_order_no_conflicts():
    s = PackScheduler(bank_cnt=1,
                      limits=PackLimits(max_txn_per_microblock=100))
    rewards = [3000, 9000, 1000, 7000, 5000]
    for i, r in enumerate(rewards):
        s.insert(mk_meta([i + 1], reward=r, cost=10_000))
    mb = s.schedule_microblock(0)
    got = [m.reward for m in mb]
    assert got == sorted(rewards, reverse=True)


def test_block_cost_limit():
    lim = PackLimits(max_cost_per_block=25_000,
                     max_txn_per_microblock=10)
    s = PackScheduler(bank_cnt=1, limits=lim)
    for i in range(5):
        s.insert(mk_meta([i + 1], cost=10_000))
    mb = s.schedule_microblock(0)
    assert len(mb) == 2                     # 3rd would exceed 25k
    s.microblock_done(0)
    assert s.schedule_microblock(0) == []   # block full
    s.end_block()
    mb = s.schedule_microblock(0)
    assert len(mb) == 2                     # fresh block budget


def test_per_account_write_cost_limit():
    lim = PackLimits(max_write_cost_per_acct=15_000,
                     max_txn_per_microblock=10)
    s = PackScheduler(bank_cnt=1, limits=lim)
    for _ in range(4):
        s.insert(mk_meta([7], cost=10_000))     # same hot account
    total = 0
    for _ in range(4):
        mb = s.schedule_microblock(0)
        total += len(mb)
        s.microblock_done(0)
    assert total == 1     # only one fits under the 15k per-acct cap
    s.end_block()
    mb = s.schedule_microblock(0)
    assert len(mb) == 1   # next block admits the next one


def test_vote_cost_limit():
    lim = PackLimits(max_vote_cost_per_block=10_000,
                     max_txn_per_microblock=10)
    s = PackScheduler(bank_cnt=1, limits=lim)
    for i in range(3):
        s.insert(mk_meta([i + 1], cost=6_000, vote=True))
    mb = s.schedule_microblock(0)
    assert len(mb) == 1   # second vote would exceed the vote budget


def test_randomized_invariants():
    """Fuzz: random txns over a small hot account universe, random
    completions across 4 banks; every scheduled set must be conflict
    free vs all outstanding (brute force), nothing lost or duplicated,
    block limits never violated."""
    rng = random.Random(42)
    lim = PackLimits(max_cost_per_block=500_000,
                     max_write_cost_per_acct=120_000,
                     max_txn_per_microblock=4, probe_depth=32)
    s = PackScheduler(bank_cnt=4, limits=lim)
    metas = []
    for i in range(200):
        nw = rng.randint(1, 3)
        nr = rng.randint(0, 2)
        univ = list(range(1, 12))
        rng.shuffle(univ)
        m = mk_meta(univ[:nw], univ[nw:nw + nr],
                    reward=rng.randint(1000, 50_000),
                    cost=rng.randint(5_000, 30_000))
        metas.append(m)
        s.insert(m)

    scheduled_ids = []
    busy = [False] * 4
    blocks = 0
    for step in range(5000):
        bank = rng.randrange(4)
        if busy[bank] and rng.random() < 0.6:
            s.microblock_done(bank)
            busy[bank] = False
            continue
        if busy[bank]:
            continue
        mb = s.schedule_microblock(bank)
        if not mb:
            # nothing schedulable: drain banks, then try a new block
            if all(not b for b in busy):
                s.end_block()
                blocks += 1
                if blocks > 300:
                    break
            continue
        busy[bank] = True
        # INVARIANT 1: no conflicts inside the microblock or vs any
        # other bank's outstanding txns (brute force on account sets)
        outstanding = [m for b in range(4) if b != bank
                       for m in s.outstanding(b)]
        for i, a in enumerate(mb):
            for b2 in mb[i + 1:]:
                assert not brute_conflict(a, b2)
            for o in outstanding:
                assert not brute_conflict(a, o)
        # INVARIANT 2: per-microblock txn count
        assert len(mb) <= lim.max_txn_per_microblock
        scheduled_ids.extend(id(m) for m in mb)
        if s.pending_cnt == 0 and all(not b for b in busy):
            break

    # INVARIANT 3: nothing scheduled twice
    assert len(scheduled_ids) == len(set(scheduled_ids))
    # INVARIANT 4: everything eventually scheduled (no starvation under
    # enough blocks)
    assert len(scheduled_ids) == len(metas), \
        f"only {len(scheduled_ids)}/{len(metas)} scheduled"
    assert s.metrics["scheduled"] == len(metas)


def test_bitset_bit_reuse():
    """Bits are refcounted and reused; masks of live txns stay valid."""
    s = PackScheduler(bank_cnt=1)
    s.insert(mk_meta([1]))
    mb = s.schedule_microblock(0)
    assert len(mb) == 1
    s.microblock_done(0)          # acct 1's bit freed
    s.insert(mk_meta([2]))        # may reuse the freed bit
    s.insert(mk_meta([2]))        # same account -> same bit
    mb = s.schedule_microblock(0)
    assert len(mb) == 1           # second write-2 txn must conflict


def _meta(payload_tag, writes=(), reads=(), reward=1000, cost=1000,
          vote=False):
    from firedancer_tpu.pack.scheduler import TxnMeta
    return TxnMeta(payload=bytes([payload_tag]) * 40, txn=None,
                   writes=tuple(writes), reads=tuple(reads), cost=cost,
                   reward=reward, is_vote=vote)


def test_bundle_atomic_ordered_exclusive():
    """Bundles (ref: fd_pack bundle contract): never reordered, never
    split, own microblock, outrank the pool, intra-bundle conflicts
    legal."""
    from firedancer_tpu.pack.scheduler import PackScheduler
    s = PackScheduler(bank_cnt=2)
    A, B = b"\xaa" * 32, b"\xbb" * 32
    # a high-reward regular txn that would normally be scheduled first
    s.insert(_meta(9, writes=[b"\xcc" * 32], reward=10**9))
    # bundle with INTERNAL conflicts (all write A), ordered 1,2,3
    bundle = [_meta(1, writes=[A]), _meta(2, writes=[A, B]),
              _meta(3, writes=[A])]
    s.insert_bundle(bundle)
    mb = s.schedule_microblock(0)
    # the bundle wins and is exclusive + in order
    assert [m.payload[0] for m in mb] == [1, 2, 3]
    assert s.metrics["bundles"] == 1
    # other banks cannot touch the bundle's accounts while in flight
    s.insert(_meta(7, writes=[B]))
    mb2 = s.schedule_microblock(1)
    assert [m.payload[0] for m in mb2] == [9]       # the regular txn
    s.microblock_done(0)
    s.microblock_done(1)
    mb3 = s.schedule_microblock(1)
    assert [m.payload[0] for m in mb3] == [7]


def test_bundle_whole_or_not_at_all():
    """A bundle that conflicts with an outstanding microblock is
    deferred entirely — no partial placement."""
    from firedancer_tpu.pack.scheduler import PackScheduler
    s = PackScheduler(bank_cnt=2)
    A = b"\xaa" * 32
    s.insert(_meta(5, writes=[A]))
    mb = s.schedule_microblock(0)
    assert [m.payload[0] for m in mb] == [5]
    s.insert_bundle([_meta(1, writes=[b"\x01" * 32]),
                     _meta(2, writes=[A])])       # txn 2 conflicts
    assert s.schedule_microblock(1) == []
    assert s.metrics["bundle_skip"] >= 1
    s.microblock_done(0)
    mb2 = s.schedule_microblock(1)
    assert [m.payload[0] for m in mb2] == [1, 2]  # now placed whole


def test_bundle_size_cap():
    import pytest as _pt
    from firedancer_tpu.pack.scheduler import PackScheduler
    s = PackScheduler()
    with _pt.raises(ValueError):
        s.insert_bundle([_meta(i) for i in range(6)])
    with _pt.raises(ValueError):
        s.insert_bundle([])


def test_unschedulable_bundle_rejected_at_insert():
    """Bundles whose limits can NEVER be met are refused up front —
    they must not wedge the FIFO head (r4 review)."""
    import pytest as _pt
    from firedancer_tpu.pack.scheduler import PackScheduler
    s = PackScheduler()
    with _pt.raises(ValueError, match="cost"):
        s.insert_bundle([_meta(i, cost=10_000_000, writes=[bytes([i]) * 32])
                         for i in range(5)])
    # a legal bundle inserted AFTER a rejection still schedules
    s.insert_bundle([_meta(1, writes=[b"\x01" * 32])])
    assert [m.payload[0] for m in s.schedule_microblock(0)] == [1]
