"""Stake program + epoch stake plumbing tests: delegation lifecycle
through the executor, epoch-windowed activation, and the VERDICT r3
gate — a delegation change MOVES the leader schedule at the epoch
boundary (ref: src/flamenco/runtime/program/fd_stake_program.c,
fd_stakes.c epoch stakes -> fd_leaders.c schedule)."""
import struct

import pytest

from firedancer_tpu.flamenco.leaders import EpochLeaders
from firedancer_tpu.svm.stake import EPOCH_NONE
from firedancer_tpu.flamenco.stakes import (
    node_stakes, total_stake, vote_stakes,
)
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.shred.shred_dest import ClusterNode, ShredDest
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID
from firedancer_tpu.svm.programs import (
    ERR_INSUFFICIENT, ERR_INVALID_OWNER, ERR_MISSING_SIG, OK,
    SYS_CREATE_ACCOUNT,
)
from firedancer_tpu.svm.stake import (
    STAKE_PROGRAM_ID, STATE_SZ, StakeState, ix_deactivate, ix_delegate,
    ix_initialize, ix_withdraw,
)
from firedancer_tpu.svm.vote import VOTE_PROGRAM_ID, VoteState


def k(n):
    return bytes([n]) * 32


PAYER = k(1)
S1, S2, S3 = k(0x11), k(0x12), k(0x13)
V1, V2 = k(0x21), k(0x22)
N1, N2 = k(0x31), k(0x32)
DEST = k(0x41)
FEE = 5000


def txn(signers, extra, instrs, n_ro_unsigned=0):
    msg = build_message(signers, extra, b"\x11" * 32, instrs,
                        n_ro_unsigned=n_ro_unsigned)
    return build_txn([bytes(64)] * len(signers), msg)


@pytest.fixture
def env():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, PAYER, Account(lamports=1 << 40))
    # withdrawal destination pre-exists rent-exempt (modern rent rules
    # refuse creating rent-paying accounts via transfer)
    funk.rec_write(None, DEST, Account(lamports=1 << 20))
    for v, n in ((V1, N1), (V2, N2)):
        vs = VoteState(n, PAYER, PAYER)
        funk.rec_write(None, v, Account(
            lamports=1, data=vs.to_bytes(), owner=VOTE_PROGRAM_ID))
    funk.txn_prepare(None, "blk")
    return funk, db, TxnExecutor(db)


def _mk_stake(ex, stake_key, lamports):
    """CreateAccount(owner=stake) + Initialize(staker=withdrawer=PAYER).
    `lamports` is the DELEGATABLE stake; the rent-exempt reserve is
    funded on top (locked by initialize, r5 rent discipline)."""
    from firedancer_tpu.svm.sysvars import rent_exempt_minimum
    create = struct.pack("<IQQ", SYS_CREATE_ACCOUNT,
                         lamports + rent_exempt_minimum(STATE_SZ),
                         STATE_SZ) + STAKE_PROGRAM_ID
    r = ex.execute("blk", txn(
        [PAYER, stake_key], [SYSTEM_PROGRAM_ID],
        [(2, bytes([0, 1]), create)]))
    assert r.status == OK, r.status
    r = ex.execute("blk", txn(
        [PAYER], [stake_key, STAKE_PROGRAM_ID],
        [(2, bytes([1]), ix_initialize(PAYER, PAYER))],
        n_ro_unsigned=1))
    assert r.status == OK, r.status


def _delegate(ex, stake_key, vote_key):
    return ex.execute("blk", txn(
        [PAYER], [stake_key, vote_key, STAKE_PROGRAM_ID],
        [(3, bytes([1, 2]), ix_delegate())], n_ro_unsigned=2))


def _deactivate(ex, stake_key):
    return ex.execute("blk", txn(
        [PAYER], [stake_key, STAKE_PROGRAM_ID],
        [(2, bytes([1]), ix_deactivate())], n_ro_unsigned=1))


def _withdraw(ex, stake_key, amount):
    return ex.execute("blk", txn(
        [PAYER], [stake_key, DEST, STAKE_PROGRAM_ID],
        [(3, bytes([1, 2]), ix_withdraw(amount))], n_ro_unsigned=1))


def test_delegation_lifecycle_and_epoch_window(env):
    funk, db, ex = env
    _mk_stake(ex, S1, 1000)
    r = _delegate(ex, S1, V1)
    assert r.status == OK
    st = StakeState.from_bytes(db.peek("blk", S1).data)
    assert st.voter == V1 and st.amount == 1000
    # step activation: not counted for the delegation epoch itself
    assert st.active_at(0) == 0
    assert st.active_at(1) == 1000
    assert vote_stakes(funk, "blk", 1) == {V1: 1000}
    assert total_stake(funk, "blk", 1) == 1000

    # live stake cannot re-delegate
    assert _delegate(ex, S1, V2).status == ERR_INVALID_OWNER
    # live stake cannot withdraw past the locked amount
    assert _withdraw(ex, S1, 500).status == ERR_INSUFFICIENT

    ex.epoch = 1
    assert _deactivate(ex, S1).status == OK
    st = StakeState.from_bytes(db.peek("blk", S1).data)
    assert st.active_at(1) == 1000        # still counted through epoch 1
    assert st.active_at(2) == 0           # gone after the boundary
    # fully inactive at epoch 2: full withdraw allowed
    ex.epoch = 2
    assert _withdraw(ex, S1, 1000).status == OK
    assert db.lamports("blk", DEST) == (1 << 20) + 1000


def test_unauthorized_staker_refused(env):
    funk, db, ex = env
    _mk_stake(ex, S1, 1000)
    evil = k(0x66)
    funk.rec_write("blk", evil, Account(lamports=1 << 30))
    r = ex.execute("blk", txn(
        [evil], [S1, V1, STAKE_PROGRAM_ID],
        [(3, bytes([1, 2]), ix_delegate())], n_ro_unsigned=2))
    assert r.status == ERR_MISSING_SIG


def test_delegation_change_moves_leader_schedule(env):
    """The VERDICT gate: epoch-boundary stake movement re-shapes the
    schedule, turbine weights, and tower total from ONE stake source."""
    funk, db, ex = env
    _mk_stake(ex, S1, 10_000)
    _mk_stake(ex, S2, 1_000)
    assert _delegate(ex, S1, V1).status == OK
    assert _delegate(ex, S2, V2).status == OK

    seed = b"\x07" * 32
    SLOTS = 64
    ns1 = node_stakes(funk, "blk", 1)
    assert ns1 == {N1: 10_000, N2: 1_000}
    sched1 = EpochLeaders(1, seed, ns1, SLOTS)
    lead1 = {n: len(sched1.leader_slots(n)) for n in (N1, N2)}
    assert lead1[N1] > lead1[N2]          # stake majority leads

    # epoch 1: drain V1's backing, pile onto V2
    ex.epoch = 1
    assert _deactivate(ex, S1).status == OK
    _mk_stake(ex, S3, 100_000)
    assert _delegate(ex, S3, V2).status == OK

    ns2 = node_stakes(funk, "blk", 2)
    assert ns2 == {N2: 101_000}           # N1 fully off the table
    sched2 = EpochLeaders(2, seed, ns2, SLOTS)
    assert len(sched2.leader_slots(N1)) == 0
    assert len(sched2.leader_slots(N2)) == SLOTS

    # the SAME stake dict drives turbine dest weighting and the tower
    dest = ShredDest(
        [ClusterNode(n, s, ("127.0.0.1", 1)) for n, s in ns2.items()],
        self_pubkey=N2)
    # the leader (now the only staked node) never retransmits to itself
    assert dest.first_hop(5, 0, 1, leader=N2) is None
    assert total_stake(funk, "blk", 2) == 101_000


# ---------------------------------------------------------------------------
# r5: rate-limited warmup/cooldown under the StakeHistory sysvar
# ---------------------------------------------------------------------------

def test_warmup_is_rate_limited_and_pro_rata():
    from firedancer_tpu.svm.stake import (
        ST_DELEGATED, StakeState, stake_activating_and_deactivating)
    # cluster: 1M effective, our 500K delegation activates at epoch 10
    # alongside another 500K (cluster activating = 1M)
    hist = {10: (1_000_000, 1_000_000, 0),
            11: (1_090_000, 910_000, 0),
            12: (1_188_100, 811_900, 0)}
    st = StakeState(state=ST_DELEGATED, amount=500_000,
                    activation_epoch=10)
    assert stake_activating_and_deactivating(st, 9, hist) == (0, 0, 0)
    assert stake_activating_and_deactivating(st, 10, hist) \
        == (0, 500_000, 0)
    # epoch 11: rate 0.09 x 1M cluster effective = 90K activates,
    # our share = 500K/1M -> 45K
    eff, act, _ = stake_activating_and_deactivating(st, 11, hist)
    assert eff == 45_000 and act == 455_000
    # epoch 12 compounds against the new cluster state
    eff2, act2, _ = stake_activating_and_deactivating(st, 12, hist)
    assert eff2 > eff and eff2 + act2 == 500_000
    # far future with full history coverage keeps ramping; without
    # history entries past 12 the ramp stops (partial knowledge)
    eff3, _, _ = stake_activating_and_deactivating(st, 13, hist)
    assert eff3 >= eff2


def test_cooldown_is_rate_limited():
    from firedancer_tpu.svm.stake import (
        ST_DELEGATED, StakeState, stake_activating_and_deactivating)
    hist = {5: (1_000_000, 0, 800_000),
            6: (920_000, 0, 720_000)}
    st = StakeState(state=ST_DELEGATED, amount=400_000,
                    activation_epoch=EPOCH_NONE,   # bootstrap: all in
                    deactivation_epoch=5)
    assert stake_activating_and_deactivating(st, 4, hist) \
        == (400_000, 0, 0)
    assert stake_activating_and_deactivating(st, 5, hist) \
        == (400_000, 0, 400_000)
    # epoch 6: 0.09 x 1M = 90K cools cluster-wide; our share
    # 400K/800K -> 45K leaves
    eff, act, deact = stake_activating_and_deactivating(st, 6, hist)
    assert (eff, act, deact) == (355_000, 0, 355_000)


def test_step_activation_unchanged_without_history():
    from firedancer_tpu.svm.stake import ST_DELEGATED, StakeState
    st = StakeState(state=ST_DELEGATED, amount=1000,
                    activation_epoch=0)
    assert st.active_at(0) == 0 and st.active_at(1) == 1000


def test_stake_history_sysvar_roundtrip_and_update(env):
    import firedancer_tpu.flamenco.stakes as fstakes
    from firedancer_tpu.svm.sysvars import STAKE_HISTORY_ID
    funk, db, ex = env
    totals = fstakes.update_stake_history(funk, "blk", 3)
    hist = fstakes.read_stake_history(funk, "blk")
    assert hist is not None and 3 in hist and hist[3] == totals
    # appending another epoch keeps both, newest first
    fstakes.update_stake_history(funk, "blk", 4)
    hist = fstakes.read_stake_history(funk, "blk")
    assert set(hist) >= {3, 4}
    acct = funk.rec_query("blk", STAKE_HISTORY_ID)
    assert acct is not None and len(acct.data) >= 8
