"""Conformance gate: table-driven transaction-execution vectors.

The reference gates its runtime on solfuzz fixtures (pre-state + txn ->
expected post-state; ref: src/flamenco/runtime/tests/fd_solfuzz.c,
contrib/test/run_test_vectors.sh:25-40). The protobuf corpora aren't in
this image, so these vectors are HAND-TRANSLATED from the reference's
program sources, each citing the semantic it pins:

  fd_system_program.c   :59-137 transfer, :143-200 allocate,
                        :202-230 assign, :254-330 create_account
  fd_executor.c         fee-before-dispatch, atomic rollback
  fd_vote_program.c     authority checks
  fd_stake_program.c    delegation lifecycle

Every vector asserts status, fee, AND full post-state balances — if
fee/status/rollback semantics drift from the reference contract, this
fails. Extend the table as more programs land.
"""
import struct

import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.funk.shmfunk import ShmFunk
from firedancer_tpu.protocol.txn import build_message, build_txn


@pytest.fixture(params=["process", "shm"])
def mk_funk(request):
    """Both funk backends run every vector: the in-process dict tree and
    the shm-resident store (native/fdtpu.cc) behind the same Funk API —
    the conformance table IS the byte-compat oracle for the shm
    re-expression."""
    made = []

    def mk():
        f = Funk() if request.param == "process" else ShmFunk()
        made.append(f)
        return f

    yield mk
    for f in made:
        if isinstance(f, ShmFunk):
            f.close(unlink=True)
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID
from firedancer_tpu.svm.stake import (
    STAKE_PROGRAM_ID, STATE_SZ, ix_deactivate, ix_delegate, ix_initialize,
)
from firedancer_tpu.svm.sysvars import rent_exempt_minimum
from firedancer_tpu.svm.vote import VOTE_PROGRAM_ID, VoteState, ix_vote

_STAKE_MIN = rent_exempt_minimum(STATE_SZ)
from firedancer_tpu.svm.programs import (
    NONCE_STATE_SZ, SYS_ADVANCE_NONCE, SYS_CREATE_WITH_SEED,
    SYS_TRANSFER,
    SYS_INIT_NONCE, create_with_seed,
)

FEE = 5000


def k(n):
    return bytes([n]) * 32


A, B, C, D = k(1), k(2), k(3), k(4)
EVIL = k(0x66)
VOTER = k(0x21)
NODE = k(0x31)


def sys_ix(disc, *fields):
    data = struct.pack("<I", disc)
    for f in fields:
        data += f if isinstance(f, bytes) else struct.pack("<Q", f)
    return data


def vote_acct(node=NODE, voter=A, withdrawer=A):
    vs = VoteState(node, voter, withdrawer)
    return {"lamports": 10, "owner": VOTE_PROGRAM_ID,
            "data": vs.to_bytes()}


# each vector: pre-state accounts, txn (signers, extra accounts,
# instrs, n_ro_unsigned, n_ro_signed), expected status + post balances.
# Balances omitted from `post` are asserted unchanged from pre.
VECTORS = [
    # --- fees (fd_executor.c fee-before-dispatch) ---
    dict(name="fee_charged_on_success",
         pre={A: 100_000}, signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(2, 300))], n_ro_unsigned=1,
         expect="ok", fee=FEE, post={A: 100_000 - FEE - 300, B: 300}),
    dict(name="fee_charged_on_failure",
         pre={A: 100_000}, signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(2, 10**12))], n_ro_unsigned=1,
         expect="insufficient_funds", fee=FEE,
         post={A: 100_000 - FEE, B: 0}),
    dict(name="fee_payer_cannot_pay",
         pre={A: FEE - 1}, signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(2, 1))], n_ro_unsigned=1,
         expect="fee_payer_insufficient", fee=0, post={A: FEE - 1}),
    dict(name="fee_per_signature_scales",
         pre={A: 100_000, B: 50_000}, signers=[A, B],
         extra=[C, SYSTEM_PROGRAM_ID],
         instrs=[(3, [0, 2], sys_ix(2, 100))], n_ro_unsigned=1,
         expect="ok", fee=2 * FEE,
         post={A: 100_000 - 2 * FEE - 100, C: 100}),

    # --- transfer (fd_system_program.c:59-137) ---
    dict(name="transfer_requires_signer",
         pre={A: 100_000, B: 9_000}, signers=[A],
         extra=[B, C, SYSTEM_PROGRAM_ID],
         instrs=[(3, [1, 2], sys_ix(2, 100))], n_ro_unsigned=1,
         expect="missing_required_signature", fee=FEE,
         post={A: 100_000 - FEE, B: 9_000, C: 0}),
    dict(name="transfer_from_data_account_refused",
         pre={A: 100_000,
              B: {"lamports": 9_000, "data": b"x"}},
         signers=[A, B], extra=[C, SYSTEM_PROGRAM_ID],
         instrs=[(3, [1, 2], sys_ix(2, 100))], n_ro_unsigned=1,
         expect="account_has_data", fee=2 * FEE,
         post={B: 9_000, C: 0}),
    dict(name="transfer_from_foreign_owner_refused",
         pre={A: 100_000,
              B: {"lamports": 9_000, "owner": k(9)}},
         signers=[A, B], extra=[C, SYSTEM_PROGRAM_ID],
         instrs=[(3, [1, 2], sys_ix(2, 100))], n_ro_unsigned=1,
         expect="invalid_account_owner", fee=2 * FEE,
         post={B: 9_000, C: 0}),
    dict(name="transfer_to_readonly_refused",
         pre={A: 100_000}, signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(2, 100))], n_ro_unsigned=2,
         expect="account_not_writable", fee=FEE,
         post={A: 100_000 - FEE, B: 0}),
    dict(name="transfer_zero_lamports_ok",
         pre={A: 100_000}, signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(2, 0))], n_ro_unsigned=1,
         expect="ok", fee=FEE, post={A: 100_000 - FEE, B: 0}),
    dict(name="self_transfer_ok",
         pre={A: 100_000}, signers=[A], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(1, [0, 0], sys_ix(2, 500))], n_ro_unsigned=1,
         expect="ok", fee=FEE, post={A: 100_000 - FEE}),

    # --- atomic rollback (fd_executor.c) ---
    dict(name="second_instr_failure_rolls_back_first",
         pre={A: 100_000}, signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(2, 100)),
                 (2, [0, 1], sys_ix(2, 10**12))], n_ro_unsigned=1,
         expect="insufficient_funds", fee=FEE,
         post={A: 100_000 - FEE, B: 0}),

    # --- create_account (fd_system_program.c:254-330) ---
    dict(name="create_account_ok",
         pre={A: 100_000}, signers=[A, B], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(0, 2_000, 16) + k(7))],
         n_ro_unsigned=1, expect="ok", fee=2 * FEE,
         post={A: 100_000 - 2 * FEE - 2_000, B: 2_000}),
    dict(name="create_in_use_account_refused",
         pre={A: 100_000, B: 50}, signers=[A, B],
         extra=[SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(0, 2_000, 16) + k(7))],
         n_ro_unsigned=1, expect="account_already_in_use", fee=2 * FEE,
         post={B: 50}),
    dict(name="create_requires_both_signatures",
         pre={A: 100_000}, signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1], sys_ix(0, 2_000, 16) + k(7))],
         n_ro_unsigned=1, expect="missing_required_signature",
         fee=FEE, post={B: 0}),

    # --- assign / allocate (fd_system_program.c:143-230) ---
    dict(name="assign_ok",
         pre={A: 100_000}, signers=[A], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(1, [0], sys_ix(1) + k(9))], n_ro_unsigned=1,
         expect="ok", fee=FEE, post={A: 100_000 - FEE}),
    dict(name="assign_foreign_owned_refused",
         pre={A: 100_000,
              B: {"lamports": 10, "owner": k(9)}},
         signers=[A, B], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(2, [1], sys_ix(1) + k(8))], n_ro_unsigned=1,
         expect="invalid_account_owner", fee=2 * FEE, post={B: 10}),
    dict(name="allocate_over_max_refused",
         pre={A: 100_000}, signers=[A], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(1, [0], sys_ix(8, 10 * 1024 * 1024 + 1))],
         n_ro_unsigned=1, expect="invalid_space", fee=FEE,
         post={A: 100_000 - FEE}),
    dict(name="allocate_with_data_refused",
         pre={A: 100_000,
              B: {"lamports": 10, "data": b"y"}},
         signers=[A, B], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(2, [1], sys_ix(8, 32))], n_ro_unsigned=1,
         expect="account_has_data", fee=2 * FEE, post={B: 10}),

    # --- vote program authority (fd_vote_program.c) ---
    dict(name="vote_needs_authorized_voter_signature",
         pre={EVIL: 100_000, VOTER: vote_acct()},
         signers=[EVIL], extra=[VOTER, VOTE_PROGRAM_ID],
         instrs=[(2, [1], ix_vote([5], k(5)))], n_ro_unsigned=1,
         expect="missing_required_signature", fee=FEE),
    dict(name="vote_ok_with_authority",
         pre={A: 100_000, VOTER: vote_acct()},
         signers=[A], extra=[VOTER, VOTE_PROGRAM_ID],
         instrs=[(2, [1], ix_vote([5], k(5)))], n_ro_unsigned=1,
         expect="ok", fee=FEE),
    dict(name="vote_on_nonvote_account_refused",
         pre={A: 100_000, B: 10},
         signers=[A, B], extra=[VOTE_PROGRAM_ID],
         instrs=[(2, [1], ix_vote([5], k(5)))], n_ro_unsigned=1,
         expect="invalid_account_owner", fee=2 * FEE),

    # --- rent-state discipline (enforce_rent=True vectors; Agave
    #     check_rent_state / fd_sysvar_rent.c) ---
    dict(name="rent_transfer_below_minimum_to_new_refused",
         pre={A: 1 << 30},
         signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1],
                  struct.pack("<IQ", SYS_TRANSFER, 1_000))],
         n_ro_unsigned=1, enforce_rent=True,
         expect="insufficient_funds_for_rent", fee=FEE),
    dict(name="rent_transfer_at_minimum_to_new_ok",
         pre={A: 1 << 30},
         signers=[A], extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1],
                  struct.pack("<IQ", SYS_TRANSFER,
                              rent_exempt_minimum(0)))],
         n_ro_unsigned=1, enforce_rent=True,
         expect="ok", fee=FEE, post={B: rent_exempt_minimum(0)}),

    # --- stake program (fd_stake_program.c) ---
    # stake accounts fund the rent-exempt reserve that initialize
    # locks (r5 rent discipline)
    dict(name="stake_initialize_ok",
         pre={A: 100_000,
              B: {"lamports": _STAKE_MIN + 5_000,
                  "owner": STAKE_PROGRAM_ID,
                  "data": bytes(STATE_SZ)}},
         signers=[A], extra=[B, STAKE_PROGRAM_ID],
         instrs=[(2, [1], ix_initialize(A, A))], n_ro_unsigned=1,
         expect="ok", fee=FEE, post={B: _STAKE_MIN + 5_000}),
    dict(name="stake_initialize_below_reserve_refused",
         pre={A: 100_000,
              B: {"lamports": _STAKE_MIN - 1,
                  "owner": STAKE_PROGRAM_ID,
                  "data": bytes(STATE_SZ)}},
         signers=[A], extra=[B, STAKE_PROGRAM_ID],
         instrs=[(2, [1], ix_initialize(A, A))], n_ro_unsigned=1,
         expect="insufficient_funds", fee=FEE),
    dict(name="stake_delegate_to_nonvote_refused",
         pre={A: 100_000,
              B: {"lamports": _STAKE_MIN + 5_000,
                  "owner": STAKE_PROGRAM_ID,
                  "data": bytes(STATE_SZ)},
              C: 10},
         signers=[A], extra=[B, C, STAKE_PROGRAM_ID],
         instrs=[(3, [1], ix_initialize(A, A)),
                 (3, [1, 2], ix_delegate())], n_ro_unsigned=2,
         expect="invalid_account_owner", fee=FEE),
    dict(name="stake_deactivate_undelegated_refused",
         pre={A: 100_000,
              B: {"lamports": _STAKE_MIN + 5_000,
                  "owner": STAKE_PROGRAM_ID,
                  "data": bytes(STATE_SZ)}},
         signers=[A], extra=[B, STAKE_PROGRAM_ID],
         instrs=[(2, [1], ix_initialize(A, A)),
                 (2, [1], ix_deactivate())], n_ro_unsigned=1,
         expect="invalid_account_owner", fee=FEE,
         post={B: _STAKE_MIN + 5_000}),

    # --- seed derivation (fd_system_program.c:389-554) ---
    dict(name="create_with_seed_ok",
         pre={A: 100_000}, signers=[A],
         extra=[create_with_seed(A, b"s1", k(9)), SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1],
                  sys_ix(SYS_CREATE_WITH_SEED, A)
                  + struct.pack("<Q", 2) + b"s1"
                  + struct.pack("<QQ", 4_000, 8) + k(9))],
         n_ro_unsigned=1, expect="ok", fee=FEE,
         post={A: 100_000 - FEE - 4_000,
               create_with_seed(A, b"s1", k(9)): 4_000}),
    dict(name="create_with_seed_wrong_address",
         pre={A: 100_000}, signers=[A],
         extra=[B, SYSTEM_PROGRAM_ID],
         instrs=[(2, [0, 1],
                  sys_ix(SYS_CREATE_WITH_SEED, A)
                  + struct.pack("<Q", 2) + b"s1"
                  + struct.pack("<QQ", 4_000, 8) + k(9))],
         n_ro_unsigned=1, expect="invalid_account_owner", fee=FEE,
         post={B: 0}),

    # --- durable nonces (fd_system_program nonce family) ---
    dict(name="nonce_init_requires_allocation",
         pre={A: 100_000, B: 50},
         signers=[A, B], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(2, [1], sys_ix(SYS_INIT_NONCE, A))], n_ro_unsigned=1,
         expect="invalid_account_owner", fee=2 * FEE, post={B: 50}),
    dict(name="nonce_init_ok_on_allocated_account",
         pre={A: 100_000,
              B: {"lamports": 50, "data": bytes(NONCE_STATE_SZ)}},
         signers=[A, B], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(2, [1], sys_ix(SYS_INIT_NONCE, A))], n_ro_unsigned=1,
         expect="ok", fee=2 * FEE, post={B: 50}),
    dict(name="nonce_advance_needs_authority",
         pre={A: 100_000, EVIL: 100_000,
              B: {"lamports": 50, "data": bytes(NONCE_STATE_SZ)}},
         signers=[A, B], extra=[SYSTEM_PROGRAM_ID],
         instrs=[(2, [1], sys_ix(SYS_INIT_NONCE, EVIL)),
                 (2, [1], sys_ix(SYS_ADVANCE_NONCE))], n_ro_unsigned=1,
         expect="missing_required_signature", fee=2 * FEE,
         post={B: 50}),

    # --- dispatch (fd_executor.c program routing) ---
    dict(name="unknown_program_refused",
         pre={A: 100_000}, signers=[A], extra=[k(0x77)],
         instrs=[(1, [0], b"\x00\x00\x00\x00")], n_ro_unsigned=1,
         expect="unknown_program", fee=FEE, post={A: 100_000 - FEE}),
    dict(name="nonexecutable_program_refused",
         pre={A: 100_000, B: {"lamports": 5, "data": b"\x95" * 8}},
         signers=[A], extra=[B],
         instrs=[(1, [0], b"")], n_ro_unsigned=1,
         expect="unknown_program", fee=FEE),
]


def _mk_account(spec):
    if isinstance(spec, int):
        return Account(lamports=spec)
    return Account(lamports=spec.get("lamports", 0),
                   data=spec.get("data", b""),
                   owner=spec.get("owner", SYSTEM_PROGRAM_ID),
                   executable=spec.get("executable", False))


@pytest.mark.parametrize("vec", VECTORS, ids=lambda v: v["name"])
def test_conformance(vec, mk_funk):
    funk = mk_funk()
    db = AccDb(funk)
    pre_balances = {}
    for key, spec in vec["pre"].items():
        a = _mk_account(spec)
        pre_balances[key] = a.lamports
        funk.rec_write(None, key, a)
    funk.txn_prepare(None, "blk")
    ex = TxnExecutor(db, enforce_rent=vec.get("enforce_rent",
                                              False))

    msg = build_message(
        vec["signers"], vec["extra"], b"\x11" * 32,
        [(p, bytes(ai), d) for p, ai, d in vec["instrs"]],
        n_ro_signed=vec.get("n_ro_signed", 0),
        n_ro_unsigned=vec.get("n_ro_unsigned", 0))
    r = ex.execute("blk", build_txn(
        [bytes(64)] * len(vec["signers"]), msg))

    assert r.status == vec["expect"], \
        f'{vec["name"]}: {r.status} != {vec["expect"]} ({r.logs})'
    assert r.fee == vec["fee"], vec["name"]
    post = dict(vec.get("post", {}))
    # unlisted accounts must be untouched (rollback discipline),
    # except the fee payer when a fee was charged
    for key, bal in pre_balances.items():
        if key in post or key == vec["signers"][0]:
            continue
        post[key] = bal
    for key, want in post.items():
        assert db.lamports("blk", key) == want, \
            f'{vec["name"]}: {key.hex()[:8]} balance'


# ---------------------------------------------------------------------------
# r5: machine-importable fixture corpus (solfuzz shape)
# tests/vectors/conformance/*.json, regenerated by
# tests/gen_conformance_vectors.py — pre-state txn-context -> expected
# effects, statuses/balances hand-derived from the cited reference
# semantics.
# ---------------------------------------------------------------------------

import json as _json
import os as _os

_FIX_DIR = _os.path.join(_os.path.dirname(__file__), "vectors",
                         "conformance")


def _load_fixtures():
    out = []
    if not _os.path.isdir(_FIX_DIR):
        return out
    for fn in sorted(_os.listdir(_FIX_DIR)):
        if fn.endswith(".json"):
            with open(_os.path.join(_FIX_DIR, fn)) as f:
                out.extend(_json.load(f))
    return out


_FIXTURES = _load_fixtures()


def test_fixture_corpus_size():
    # VERDICT r4 item 6 gate: >= 200 vectors incl. every implemented
    # program family (fixtures + the hand table above)
    assert len(_FIXTURES) + len(VECTORS) >= 200
    assert len(_FIXTURES) >= 150


@pytest.mark.parametrize(
    "fx", _FIXTURES, ids=[f["name"] for f in _FIXTURES])
def test_fixture(fx, mk_funk):
    ctx = fx["context"]
    funk = mk_funk()
    db = AccDb(funk)
    for spec in ctx["accounts"]:
        funk.rec_write(None, bytes.fromhex(spec["address"]), Account(
            lamports=spec["lamports"],
            data=bytearray(bytes.fromhex(spec["data"])),
            owner=bytes.fromhex(spec["owner"]),
            executable=spec.get("executable", False)))
    funk.txn_prepare(None, "blk")
    ex = TxnExecutor(db, enforce_rent=ctx.get("enforce_rent", True))
    ex.epoch = ctx.get("epoch", 0)
    ex.slot = ctx.get("slot", 0)

    tx = ctx["tx"]
    signers = [bytes.fromhex(s) for s in tx["signers"]]
    extra = [bytes.fromhex(e) for e in tx["extra"]]
    msg = build_message(
        signers, extra, b"\x11" * 32,
        [(i["program_index"], bytes(i["accounts"]),
          bytes.fromhex(i["data"])) for i in tx["instructions"]],
        n_ro_signed=tx.get("n_ro_signed", 0),
        n_ro_unsigned=tx.get("n_ro_unsigned", 0))
    r = ex.execute("blk", build_txn([bytes(64)] * len(signers), msg))

    eff = fx["effects"]
    assert r.status == eff["status"], \
        f'{fx["name"]}: {r.status} != {eff["status"]} ({r.logs})'
    assert r.fee == eff["fee"], fx["name"]
    for want in eff["accounts"]:
        addr = bytes.fromhex(want["address"])
        a = db.peek("blk", addr)
        got_l = a.lamports if a is not None else 0
        assert got_l == want["lamports"], \
            f'{fx["name"]}: {addr[:4].hex()} lamports {got_l} != ' \
            f'{want["lamports"]}'
        if "data" in want:
            got_d = bytes(a.data) if a is not None else b""
            assert got_d == bytes.fromhex(want["data"]), \
                f'{fx["name"]}: {addr[:4].hex()} data mismatch'
