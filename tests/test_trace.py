"""fdtrace flight recorder: ring semantics, config schema, the
zero-cost disabled path, and the tier-1 acceptance drill — a live
two-tile topology (verify + downstream sink over an external ingest
ring) whose Perfetto/Chrome JSON export shows one frag's lineage as
correlated spans across both tiles.
"""
import json
import os
import time

import pytest

from firedancer_tpu.runtime import TraceRing, Workspace
from firedancer_tpu.trace import (
    TRACE_DEFAULTS, TILE_TRACE_KEYS, TraceWriter, effective_trace,
    events as tev, lineage, normalize_trace, read_rings, summary,
    to_chrome,
)

pytestmark = pytest.mark.trace


# -- ring + writer semantics ------------------------------------------------

@pytest.fixture(scope="module")
def wksp():
    w = Workspace(f"/fdtpu_tr_{os.getpid()}", 1 << 22)
    yield w
    w.close()
    w.unlink()


def test_ring_wraps_keeps_newest_in_order(wksp):
    r = TraceRing.create(wksp, 8)
    for i in range(11):
        r.append(1000 + i, tev.EV_CONSUME, sig=i, link=2, count=1)
    assert r.cursor == 11                 # counts ALL writes ever
    cur, recs = r.snapshot()
    assert cur == 11 and len(recs) == 8   # ring keeps the newest depth
    evs = [tev.decode(x, ["a", "b", "c"]) for x in recs]
    assert [e["sig"] for e in evs] == list(range(3, 11))  # oldest-first
    assert evs[0]["link"] == "c" and evs[0]["ev"] == "consume"
    # a second reader attached by offset sees the same history
    r2 = TraceRing(wksp, r.off, 8)
    assert r2.snapshot()[0] == 11


def test_ring_rejects_non_pow2_depth(wksp):
    with pytest.raises(ValueError, match="power of two"):
        TraceRing.create(wksp, 100)


def test_writer_samples_frag_events_records_all_lifecycle(wksp):
    r = TraceRing.create(wksp, 64)
    tw = TraceWriter(r, sample=4, links={"x": 0})
    for i in range(16):
        tw.frag(tev.EV_CONSUME, sig=i, link=tw.link_id("x"))
    assert r.cursor == 4                  # every 4th frag event
    tw.event(tev.EV_BOOT)                 # lifecycle: always recorded
    tw.event(tev.EV_CPU_FALLBACK)
    assert r.cursor == 6
    _, recs = r.snapshot()
    sigs = [tev.decode(x)["sig"] for x in recs[:4]]
    assert sigs == [3, 7, 11, 15]


def test_append_batch_one_cursor_bump_and_wrap_accounting(wksp):
    """Vectorized append: the whole batch lands under one cursor bump;
    oversized batches keep only the newest `depth` records but the
    cursor still counts every one (history-loss accounting)."""
    r = TraceRing.create(wksp, 8)
    r.append_batch(500, tev.EV_PUBLISH, list(range(5)), link=1)
    assert r.cursor == 5
    cur, recs = r.snapshot()
    assert [tev.decode(x)["sig"] for x in recs] == [0, 1, 2, 3, 4]
    # batch larger than depth: newest 8 survive, cursor counts all 20
    r.append_batch(501, tev.EV_PUBLISH, list(range(100, 120)))
    assert r.cursor == 25
    _, recs = r.snapshot()
    assert [tev.decode(x)["sig"] for x in recs] == list(range(112, 120))
    r.append_batch(502, tev.EV_PUBLISH, [])          # empty: no-op
    assert r.cursor == 25


def test_frag_batch_matches_sequential_sampling_stream(wksp):
    """frag_batch is n sequential frag() calls: same records selected
    from the running frag count regardless of batch boundaries."""
    ra = TraceRing.create(wksp, 64)
    rb = TraceRing.create(wksp, 64)
    ta = TraceWriter(ra, sample=3, links={"x": 0})
    tb = TraceWriter(rb, sample=3, links={"x": 0})
    sigs = list(range(21))
    for s in sigs:
        ta.frag(tev.EV_CONSUME, sig=s, link=0)
    for lo, hi in ((0, 7), (7, 12), (12, 12), (12, 21)):
        tb.frag_batch(tev.EV_CONSUME, sigs[lo:hi], link=0)
    assert ra.cursor == rb.cursor == 7            # every 3rd of 21
    got_a = [tev.decode(x)["sig"] for x in ra.snapshot()[1]]
    got_b = [tev.decode(x)["sig"] for x in rb.snapshot()[1]]
    assert got_a == got_b == [2, 5, 8, 11, 14, 17, 20]
    # sample=1 fast path records everything
    r1 = TraceRing.create(wksp, 64)
    t1 = TraceWriter(r1, sample=1, links={"x": 0})
    t1.frag_batch(tev.EV_CONSUME, sigs[:5], link=0)
    assert r1.cursor == 5


def test_span_records_end_ts_and_duration(wksp):
    from firedancer_tpu.utils.tempo import monotonic_ns
    r = TraceRing.create(wksp, 8)
    tw = TraceWriter(r)
    t0 = monotonic_ns()
    time.sleep(0.002)
    tw.span(tev.EV_WAIT, t0)
    e = tev.decode(r.snapshot()[1][0])
    assert e["ev"] == "wait" and e["arg"] >= 1_500_000
    assert e["ts"] >= t0 + e["arg"]


def test_shared_clock_is_the_heartbeat_clock():
    """Satellite contract: traces and watchdog staleness share ONE
    monotonic-ns source (utils/tempo.monotonic_ns == the native
    fdtpu_ticks that stamps cnc heartbeats)."""
    from firedancer_tpu.runtime.tango import lib
    from firedancer_tpu.utils.tempo import monotonic_ns
    a = lib.fdtpu_ticks()
    b = monotonic_ns()
    c = lib.fdtpu_ticks()
    assert a <= b <= c
    from firedancer_tpu.disco import topo as topo_mod
    assert abs(topo_mod.now_ticks() - monotonic_ns()) < 1e9
    # the stem stamps wait-end records with time.perf_counter_ns
    # directly (disco/stem.py) — pin that it shares the CLOCK_MONOTONIC
    # epoch with the heartbeat/trace clock on this platform
    assert abs(time.perf_counter_ns() - monotonic_ns()) < 1e9


# -- config schema ----------------------------------------------------------

def test_normalize_trace_defaults_and_validation():
    assert normalize_trace(None) == TRACE_DEFAULTS
    assert normalize_trace(None)["enable"] is False   # off by default
    full = normalize_trace({"enable": True, "depth": 64, "sample": 8,
                            "tiles": ["a"]})
    assert full == {"enable": True, "depth": 64, "sample": 8,
                    "tiles": ["a"]}
    with pytest.raises(ValueError, match="did you mean 'depth'"):
        normalize_trace({"dept": 64})
    with pytest.raises(ValueError, match="power of two"):
        normalize_trace({"depth": 100})
    with pytest.raises(ValueError, match="sample"):
        normalize_trace({"sample": 0})
    with pytest.raises(ValueError, match="list of tile names"):
        normalize_trace({"tiles": "verify"})
    with pytest.raises(ValueError, match="unknown trace key"):
        normalize_trace({"tiles": ["a"]}, per_tile=True)  # no allowlist
    with pytest.raises(ValueError, match="table"):
        normalize_trace([1, 2])


def test_effective_trace_resolution():
    topo = normalize_trace({"enable": True, "depth": 256,
                            "tiles": ["a"]})
    assert effective_trace(topo, "a", {}) == {"depth": 256, "sample": 1}
    assert effective_trace(topo, "b", {}) is None       # not allowlisted
    # per-tile override wins in both directions
    assert effective_trace(topo, "a", {"enable": False}) is None
    assert effective_trace(topo, "b", {"enable": True,
                                       "depth": 64, "sample": 4}) \
        == {"depth": 64, "sample": 4}


def test_registry_mirrors_trace_keys():
    """fdlint's key registry and the trace schema must not drift."""
    from firedancer_tpu.lint import registry as reg
    assert set(reg.TRACE_SECTION_KEYS) == set(TRACE_DEFAULTS)
    assert set(reg.TILE_TRACE_KEYS) == set(TILE_TRACE_KEYS)
    assert "trace" in reg.COMMON_KEYS


def _fe(ts, etype, sig, link):
    return {"ts": ts, "ev": tev.NAMES[etype], "etype": etype,
            "sig": sig, "arg": 0, "link": link, "count": 0}


def test_lineage_sig_zero_and_per_hop_latency():
    """sig=0 is a real lineage key (synth sigs start at 0), and the
    summary's per-link latency is the PER-HOP delta (consume vs the
    most recent publish), not cumulative from the chain's origin."""
    evs = {
        "a": [_fe(100_000, tev.EV_PUBLISH, 0, "a_b")],
        "b": [_fe(150_000, tev.EV_CONSUME, 0, "a_b"),
              _fe(160_000, tev.EV_PUBLISH, 0, "b_c")],
        "c": [_fe(200_000, tev.EV_CONSUME, 0, "b_c")],
    }
    chains = lineage(evs)
    assert 0 in chains and len(chains[0]) == 4
    text = summary(evs)
    row = next(ln for ln in text.splitlines() if ln.startswith("b_c"))
    # 200us - 160us = 40us per-hop (NOT 100us from the origin publish)
    assert row.split()[2] == "40.0"
    doc = to_chrome(evs)
    flows = [e for e in doc["traceEvents"] if e.get("id") == "0x0"]
    assert any(e["ph"] == "s" for e in flows)
    assert any(e["ph"] == "f" for e in flows)


def test_chaos_action_ids_mirror_chaos_harness():
    """Every chaos action the harness can fire has a trace id, so a
    dumped black box always names the exact injected fault."""
    from firedancer_tpu.utils.chaos import ACTIONS
    assert set(tev.CHAOS_ACTION_IDS) == set(ACTIONS)
    assert all(tev.CHAOS_ACTION_NAMES[i] == a
               for a, i in tev.CHAOS_ACTION_IDS.items())


def test_config_toml_trace_section_roundtrip(tmp_path):
    """[trace] flows TOML -> load_config -> build_topology -> Topology;
    an unknown key fails at config load with a did-you-mean."""
    from firedancer_tpu.app.config import build_topology, load_config
    p = tmp_path / "t.toml"
    p.write_text("""
[trace]
enable = true
depth = 256

[[link]]
name = "a_b"
depth = 64
mtu = 256

[[tile]]
name = "a"
kind = "synth"
outs = ["a_b"]

[[tile]]
name = "b"
kind = "sink"
ins = ["a_b"]

[tile.trace]
sample = 4
""")
    cfg = load_config(str(p))
    topo = build_topology(cfg, name=f"trc{os.getpid()}")
    assert topo.trace == {"enable": True, "depth": 256}
    assert topo.tiles["b"].args["trace"] == {"sample": 4}
    bad = tmp_path / "bad.toml"
    bad.write_text(p.read_text().replace("enable = true",
                                         "enabled = true"))
    with pytest.raises(ValueError, match="did you mean 'enable'"):
        build_topology(load_config(str(bad)))


# -- build-time carving + the zero-cost disabled path -----------------------

def _build(trace=None, tiles=None):
    from firedancer_tpu.disco import Topology
    topo = Topology(f"trb{os.getpid()}_{_build.n}", wksp_size=1 << 21,
                    trace=trace)
    _build.n += 1
    topo.link("a_b", depth=32, mtu=256)
    topo.tile("a", "synth", outs=["a_b"], count=8, unique=4,
              **(tiles or {}).get("a", {}))
    topo.tile("b", "sink", ins=["a_b"], **(tiles or {}).get("b", {}))
    return topo.build()


_build.n = 0


def test_build_carves_rings_only_when_enabled():
    plan = _build()                        # no [trace] section at all
    try:
        for tn in ("a", "b"):
            assert "trace_off" not in plan["tiles"][tn]
        assert plan["trace"]["enable"] is False
    finally:
        Workspace.unlink_name(plan["wksp"]["name"])
    plan = _build(trace={"enable": True, "depth": 128},
                  tiles={"a": {"trace": {"enable": False}}})
    try:
        assert "trace_off" not in plan["tiles"]["a"]   # opted out
        b = plan["tiles"]["b"]
        assert b["trace_depth"] == 128 and b["trace_sample"] == 1
        assert b["trace_off"] % 64 == 0
    finally:
        Workspace.unlink_name(plan["wksp"]["name"])
    with pytest.raises(ValueError, match="unknown tile"):
        _build(trace={"enable": True, "tiles": ["ghost"]})


def test_disabled_path_is_a_single_none_check():
    """Acceptance: tracing off (the default) leaves NO trace region in
    the plan, TileCtx.trace is None, and the stem's cached hook
    attribute is None — the hot loop's only tracing cost is that one
    attribute test (no allocation, no syscall, no ring)."""
    from firedancer_tpu.disco.stem import Stem
    from firedancer_tpu.disco.topo import TileCtx
    from firedancer_tpu.runtime import CNC_HALT
    plan = _build()
    try:
        ctx = TileCtx(plan, "b")
        try:
            assert ctx.trace is None

            class _Tile:
                def __init__(self):
                    self.polls = 0

                def poll_once(self):
                    self.polls += 1
                    return 0

            stem = Stem(ctx, _Tile(), idle_sleep_s=0)
            assert stem._trace is None        # the whole disabled path
            stem.run(max_iters=16)
            assert stem.tile.polls == 16
            assert ctx.cnc.state == CNC_HALT
            # and nothing anywhere in the plan points at a ring
            assert not any("trace_off" in s
                           for s in plan["tiles"].values())
        finally:
            ctx.close()
    finally:
        Workspace.unlink_name(plan["wksp"]["name"])


# -- the live acceptance drill ---------------------------------------------

N_TXNS = 12


@pytest.fixture(scope="module")
def traced_pipeline():
    """verify + sink (two tiles) over an external ingest ring; the
    test process IS the producer, so the frag lineage under test is
    exactly verify -> downstream consumer."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.runtime import Ring
    from firedancer_tpu.tiles.synth import make_signed_txns
    txns = make_signed_txns(N_TXNS, seed=7)
    topo = (
        Topology(f"trl{os.getpid()}", wksp_size=1 << 23,
                 trace={"enable": True, "depth": 1024, "sample": 1})
        .link("in_verify", depth=64, mtu=1280, external=True)
        .link("verify_sink", depth=64, mtu=1280)
        .tcache("vtc", depth=512)
        .tile("verify", "verify", ins=["in_verify"],
              outs=["verify_sink"], batch=32, tcache="vtc")
        .tile("sink", "sink", ins=["verify_sink"])
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    try:
        runner.wait_running(timeout_s=540)
        li = plan["links"]["in_verify"]
        ring = Ring(runner.wksp, li["ring_off"], li["depth"],
                    li["arena_off"], li["mtu"])
        for i, t in enumerate(txns):
            ring.publish(t, sig=i)
        runner.wait_idle("sink", "rx", N_TXNS, timeout_s=180)
        time.sleep(0.3)                   # one housekeeping flush
        yield runner
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()


def test_frag_lineage_appears_as_correlated_spans(traced_pipeline):
    """ACCEPTANCE: export Perfetto/Chrome JSON from the live topology
    and prove a single frag's lineage — published by verify, consumed
    downstream — appears as correlated events, by parsing the JSON."""
    runner = traced_pipeline
    evs = read_rings(runner.plan, runner.wksp)
    assert set(evs) == {"verify", "sink"}
    # raw-event view: each forwarded txn's dedup tag is a sig that
    # verify PUBLISHED and sink CONSUMED
    chains = lineage(evs)
    correlated = [
        sig for sig, chain in chains.items()
        if any(t == "verify" and n == "publish" for _, t, n, _ in chain)
        and any(t == "sink" and n == "consume" for _, t, n, _ in chain)]
    assert len(correlated) == N_TXNS
    for sig in correlated:                 # publish precedes consume
        names = [(t, n) for _, t, n, _ in chains[sig]]
        assert names.index(("verify", "publish")) \
            < names.index(("sink", "consume"))

    # JSON view (what Perfetto ingests): thread-named tiles, X spans,
    # and s/f flow arrows binding the two tiles through the sig id
    doc = json.loads(json.dumps(to_chrome(evs, runner.plan["topology"])))
    te = doc["traceEvents"]
    tids = {e["args"]["name"]: e["tid"] for e in te
            if e.get("name") == "thread_name"}
    assert set(tids) == {"verify", "sink"}
    sig = correlated[0]
    fid = f"{sig:#x}"
    starts = [e for e in te if e.get("ph") == "s" and e["id"] == fid]
    finishes = [e for e in te if e.get("ph") == "f" and e["id"] == fid]
    assert starts and finishes
    assert starts[0]["tid"] == tids["verify"]
    assert finishes[-1]["tid"] == tids["sink"]
    assert starts[0]["ts"] <= finishes[-1]["ts"]
    # the verify tile's device spans are present as complete events
    span_names = {e["name"] for e in te if e.get("ph") == "X"
                  and e["tid"] == tids["verify"]}
    assert {"tpu_dispatch", "tpu_readback"} <= span_names


def test_cli_exports_live_and_post_mortem(traced_pipeline, tmp_path,
                                          capsys):
    """tools/fdtrace drains by topology name — live now, and the shm
    rings outlive the tile processes for post-mortem drains."""
    from firedancer_tpu.trace.cli import main as trace_main
    runner = traced_pipeline
    out = tmp_path / "trace.json"
    rc = trace_main([runner.plan["topology"], "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["otherData"]["source"] == "fdtrace"
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])
    text = capsys.readouterr().out
    assert "verify_sink" in text           # per-link latency table
    assert "tile" in text and "wait_ms" in text


def test_summary_attributes_wait_and_link_latency(traced_pipeline):
    runner = traced_pipeline
    evs = read_rings(runner.plan, runner.wksp)
    text = summary(evs)
    assert "verify_sink" in text and "p99_us" in text
    # the idle sink accumulated wait spans; verify did device work
    assert "sink" in text and "verify" in text


def test_monitor_snapshot_surfaces_trace_cursor(traced_pipeline):
    from firedancer_tpu.disco.monitor import snapshot
    runner = traced_pipeline
    snap = snapshot(runner.plan, runner.wksp)
    assert snap["verify"]["trace"]["events"] > 0
    assert snap["verify"]["trace"]["depth"] == 1024
