"""Deterministic interleaving tests for the ring contract — the
racesan tier (ref: src/util/racesan/README.md:1-30 — drive lockfree
code through seeded operation interleavings and assert invariants;
SURVEY §4 tier 5).

The native ring ops (publish / consume / gather) are the atomic units;
a seeded scheduler interleaves producer and consumer steps — including
forced laps — and asserts the consumer-facing contract after every
step: payloads read back exactly as published for their seq, overruns
are detected as seq gaps (never as corrupt data), and credit-gated
producers never lap a reliable consumer."""
import os

import numpy as np
import pytest

from firedancer_tpu.runtime import FSEQ_STALE, Fseq, Ring, Workspace

DEPTH = 8


def payload_for(seq: int) -> bytes:
    rng = np.random.default_rng(seq * 7 + 1)
    return rng.bytes(int(rng.integers(1, 64)))


@pytest.fixture
def ring():
    w = Workspace(f"/fdtpu_race{os.getpid()}", 1 << 20)
    try:
        yield Ring.create(w, depth=DEPTH, mtu=64)
    finally:
        w.close()
        w.unlink()


@pytest.mark.parametrize("seed", range(8))
def test_seeded_interleavings_preserve_contract(ring, seed):
    """Random but DETERMINISTIC schedules of publish/consume ops; the
    consumer must only ever observe (a) the exact bytes published for a
    seq, (b) 'not yet', or (c) an overrun signal — never torn data."""
    rng = np.random.default_rng(seed)
    pub_seq = 0
    con_seq = 0
    overruns = 0
    consumed = 0
    for _ in range(400):
        if rng.random() < 0.55:
            ring.publish(payload_for(pub_seq), sig=pub_seq)
            pub_seq += 1
        else:
            rc, frag = ring.consume(con_seq)
            if rc == 1:
                continue                      # caught up
            if rc == -1:
                # lapped: resync like the native gather
                resync = max(pub_seq - DEPTH, con_seq + 1)
                overruns += resync - con_seq
                con_seq = resync
                continue
            data = bytes(ring.payload(frag))[:frag.sz]
            # re-validate (speculative read contract)
            rc2, check = ring.consume(con_seq)
            if rc2 != 0 or check.seq != frag.seq:
                continue
            assert frag.sig == con_seq
            assert data == payload_for(con_seq), \
                f"torn/corrupt read at seq {con_seq}"
            con_seq += 1
            consumed += 1
    # accounting: everything published is consumed, skipped, or pending
    assert consumed + overruns + (pub_seq - con_seq) == pub_seq
    if pub_seq - con_seq > DEPTH:
        assert overruns > 0


def test_forced_lap_is_detected_not_corrupt(ring):
    """Producer laps the consumer by exactly depth+3: the consumer's
    next consume must signal overrun (not return stale bytes), and
    after resync every surviving slot reads back exactly."""
    for s in range(DEPTH + 3):
        ring.publish(payload_for(s), sig=s)
    rc, _ = ring.consume(0)
    assert rc == -1
    start = DEPTH + 3 - DEPTH
    for s in range(start, DEPTH + 3):
        rc, frag = ring.consume(s)
        assert rc == 0
        assert bytes(ring.payload(frag))[:frag.sz] == payload_for(s)


def test_stale_consumer_unwedges_producer(ring):
    """The PR 1 FSEQ_STALE contract, smallest case: a consumer dies
    mid-credit (fseq frozen), the producer runs out of credits exactly
    at depth; mark_stale excludes the dead fseq from fctl and the full
    window returns immediately."""
    w = ring.wksp
    fs = Fseq(w)
    # consumer advances a little, then dies with its cursor frozen
    for s in range(3):
        ring.publish(payload_for(s), sig=s)
    fs.update(3)
    pub = 3
    while ring.credits([fs]) > 0:
        ring.publish(payload_for(pub), sig=pub)
        pub += 1
    assert pub == 3 + DEPTH          # wedged exactly at the window
    fs.mark_stale()                  # supervisor's _mark_down step
    assert fs.is_stale()
    assert ring.credits([fs]) > 0    # sentinel skipped by native fctl
    ring.publish(payload_for(pub), sig=pub)


def test_restarted_consumer_rejoins_at_tail(ring):
    """Consumer dies, producer keeps flowing over the stale window,
    restarted consumer rejoins at the producer's CURRENT seq (the
    TileCtx rejoin_at_tail seeding): frags published while down are
    skipped — never replayed, never torn — and the fseq update clears
    the sentinel so credits gate on the consumer again."""
    w = ring.wksp
    fs = Fseq(w)
    con = 0
    for s in range(5):
        ring.publish(payload_for(s), sig=s)
        rc, frag = ring.consume(con)
        assert rc == 0
        con += 1
        fs.update(con)
    fs.mark_stale()                              # consumer died
    pub = 5
    for _ in range(3 * DEPTH):                   # producer flows on
        assert ring.credits([fs]) > 0
        ring.publish(payload_for(pub), sig=pub)
        pub += 1
    # rejoin: seed the cursor AND the fseq from ring.seq (TileCtx)
    con = ring.seq
    fs.update(con)
    assert not fs.is_stale()
    assert ring.credits([fs]) == DEPTH           # full window at tail
    ring.publish(payload_for(pub), sig=pub)
    pub += 1
    rc, frag = ring.consume(con)
    assert rc == 0 and frag.seq == con
    assert bytes(ring.payload(frag))[:frag.sz] == payload_for(con)


@pytest.mark.parametrize("seed", range(6))
def test_seeded_stale_rejoin_interleavings(ring, seed):
    """Seeded schedules over the full die/skip/rejoin protocol: the
    producer only publishes within credits, the consumer randomly dies
    (fseq -> STALE) and later rejoins at tail. Invariants after every
    step: a LIVE consumer is never lapped (exact payload readback), a
    stale fseq never blocks the producer for more than the depth
    window, and every rejoin lands exactly at the producer's seq."""
    rng = np.random.default_rng(seed + 100)
    w = ring.wksp
    fs = Fseq(w)
    pub = con = 0
    alive = True
    rejoins = deaths = 0
    for _ in range(600):
        r = rng.random()
        if r < 0.45:
            if ring.credits([fs]) > 0:
                ring.publish(payload_for(pub), sig=pub)
                pub += 1
            else:
                # blocked: only ever on a LIVE consumer's window
                assert alive
                assert pub - con == DEPTH
        elif r < 0.80:
            if alive and con < pub:
                rc, frag = ring.consume(con)
                assert rc == 0, \
                    f"live reliable consumer lapped at {con}"
                assert bytes(ring.payload(frag))[:frag.sz] \
                    == payload_for(con)
                con += 1
                fs.update(con)
        elif r < 0.90:
            if alive:                       # die mid-credit
                fs.mark_stale()
                alive = False
                deaths += 1
        else:
            if not alive:                   # respawn: rejoin at tail
                con = ring.seq
                fs.update(con)
                assert fs.query() == con != FSEQ_STALE
                alive = True
                rejoins += 1
    assert deaths and rejoins               # schedules exercised both
    assert con <= pub


def test_reliable_consumer_is_never_lapped(ring):
    """With an fseq attached, the producer's credits hit zero before it
    can lap; publishing only within credits preserves every frag."""
    w = ring.wksp
    fs = Fseq(w)
    pub = 0
    seen = 0
    rng = np.random.default_rng(42)
    for _ in range(300):
        if rng.random() < 0.6 and ring.credits([fs]) > 0:
            ring.publish(payload_for(pub), sig=pub)
            pub += 1
        elif seen < pub:
            rc, frag = ring.consume(seen)
            assert rc == 0, f"reliable consumer lapped at {seen}"
            assert bytes(ring.payload(frag))[:frag.sz] \
                == payload_for(seen)
            seen += 1
            fs.update(seen)
    assert pub >= DEPTH            # the window actually wrapped
    assert seen >= pub - DEPTH
