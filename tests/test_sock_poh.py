"""UDP ingest + full leader loop topology tests.

Covers VERDICT r2 items 5/6: packets over localhost UDP flow e2e
through verify to the sink (sock-tile analog,
src/disco/net/sock/fd_sock_tile.c), and the leader pipeline closes
pack -> bank(SVM wave executor) -> poh with PoH-tick-driven slot
boundaries (src/discof/poh/fd_poh.h:4-31) and a verified entry chain.
"""
import hashlib
import os
import socket
import struct
import time

import pytest

pytestmark = pytest.mark.slow

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.disco.monitor import attach
from firedancer_tpu.ops.poh import host_poh_append, host_poh_mixin
from firedancer_tpu.runtime import Ring
from firedancer_tpu.tiles.synth import make_signed_txns, synth_signer_seed
from firedancer_tpu.utils.ed25519_ref import keypair

N_TXNS = 24


def _wait(fn, timeout_s=540, dt=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if fn():
            return True
        time.sleep(dt)
    return False


def test_udp_ingest_to_verify_e2e():
    """Real UDP datagrams -> sock tile -> verify -> sink."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"sk{os.getpid()}", wksp_size=1 << 24)
        .link("sock_verify", depth=128, mtu=1280)
        .link("verify_sink", depth=128, mtu=1280)
        .tcache("verify_tc", depth=4096)
        .tile("sock", "sock", outs=["sock_verify"], port=0, batch=32)
        .tile("verify", "verify", ins=["sock_verify"],
              outs=["verify_sink"], batch=16, tcache="verify_tc")
        .tile("sink", "sink", ins=["verify_sink"])
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        assert _wait(lambda: runner.metrics("sock")["port"] != 0,
                     timeout_s=30)
        port = int(runner.metrics("sock")["port"])
        txns = make_signed_txns(N_TXNS, seed=5)
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        # resend until the pipeline confirms receipt (UDP may drop)
        deadline = time.monotonic() + 60
        while runner.metrics("sink")["rx"] < N_TXNS \
                and time.monotonic() < deadline:
            for t in txns:
                tx.sendto(t, ("127.0.0.1", port))
            time.sleep(0.25)
        tx.close()
        sink_rx = runner.metrics("sink")["rx"]
        assert sink_rx >= N_TXNS
        v = runner.metrics("verify")
        assert v["verify_fail"] == 0 and v["parse_fail"] == 0
        assert runner.metrics("sock")["rx"] >= N_TXNS
    finally:
        runner.halt()
        runner.close()


@pytest.fixture(scope="module")
def leader():
    """synth -> verify -> dedup -> pack -> bank(svm) -> poh loop, with
    poh slot frags closing the loop back to pack."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    genesis = {}
    for i in range(16):
        pub = keypair(synth_signer_seed(i))[-1]
        genesis[pub.hex()] = 1 << 44
    topo = (
        Topology(f"ld{os.getpid()}", wksp_size=1 << 25)
        .link("synth_verify", depth=128, mtu=1280)
        .link("verify_dedup", depth=128, mtu=1280)
        .link("dedup_pack", depth=128, mtu=1280)
        .link("pack_bank0", depth=32, mtu=1 << 15)
        .link("bank0_done", depth=32, mtu=64)
        .link("bank0_poh", depth=64, mtu=64)
        .link("poh_entries", depth=2048, mtu=256)
        .link("poh_slots", depth=64, mtu=64)
        .tcache("verify_tc", depth=4096)
        .tcache("dedup_tc", depth=4096)
        .tile("synth", "synth", outs=["synth_verify"], count=N_TXNS,
              unique=N_TXNS, seed=6)
        .tile("verify", "verify", ins=["synth_verify"],
              outs=["verify_dedup"], batch=16, tcache="verify_tc")
        .tile("dedup", "dedup", ins=["verify_dedup"],
              outs=["dedup_pack"], tcache="dedup_tc")
        .tile("pack", "pack", ins=["dedup_pack", "bank0_done",
                                   "poh_slots"],
              outs=["pack_bank0"], txn_in="dedup_pack",
              bank_links=["pack_bank0"], done_links=["bank0_done"],
              slot_in="poh_slots", max_txn_per_microblock=8)
        .tile("bank0", "bank", ins=["pack_bank0"],
              outs=["bank0_done", "bank0_poh"], exec="svm",
              poh_link="bank0_poh", genesis=genesis, rpc_port=0)
        .tile("poh", "poh", ins=["bank0_poh"],
              outs=["poh_entries", "poh_slots"], slot_link="poh_slots",
              hashes_per_tick=16, ticks_per_slot=4)
        .tile("entsink", "sink", ins=["poh_entries"])
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start()
    yield runner
    runner.halt()
    runner.close()


def test_leader_loop_executes_and_entries_flow(leader):
    leader.wait_running(timeout_s=540)
    # all synth txns are funded system transfers: they must execute
    assert _wait(lambda: leader.metrics("bank0")["transfers"] == N_TXNS)
    b = leader.metrics("bank0")
    assert b["exec_fail"] == 0
    assert b["txns"] == N_TXNS
    # every executed microblock was mixed into the PoH chain
    assert _wait(
        lambda: leader.metrics("poh")["mixins"]
        == leader.metrics("bank0")["microblocks"])
    # PoH ticks advance slots, and pack consumes the slot frags
    assert _wait(lambda: leader.metrics("poh")["slots"] >= 2,
                 timeout_s=120)
    assert _wait(
        lambda: leader.metrics("pack")["blocks"]
        >= leader.metrics("poh")["slots"] - 1, timeout_s=60)


def test_poh_entry_chain_verifies(leader):
    """A recent window of the entry stream re-verifies: host recompute
    pins the chain, and the batched device kernel (ops/poh.py) verifies
    the same window the way a replay consumer would."""
    import numpy as np

    from firedancer_tpu.ops.poh import poh_verify_entries

    leader.wait_running(timeout_s=540)
    assert _wait(lambda: leader.metrics("poh")["entries"] >= 8,
                 timeout_s=60)
    plan, wksp = attach(leader.plan["topology"])
    try:
        li = plan["links"]["poh_entries"]
        ring = Ring(wksp, li["ring_off"], li["depth"], li["arena_off"],
                    li["mtu"])
        # late-attaching unreliable consumer: start near the producer's
        # seq, not 0 (old frags are long overwritten)
        start = max(0, ring.seq - li["depth"] // 4)
        n, _, buf, sizes, sigs, ovr = ring.gather(start, 256, li["mtu"])
        assert n >= 8 and ovr == 0
        prev_hash = None
        prevs, nums, mixes, has, exps = [], [], [], [], []
        max_hashes = 1
        for i in range(n):
            frame = bytes(buf[i, :sizes[i]])
            slot, tick, num_hashes, has_mix = struct.unpack_from(
                "<QIIB", frame, 0)
            prev = frame[17:49]
            h = frame[49:81]
            mixin = frame[81:113]
            # chain continuity across consecutive entries
            if prev_hash is not None:
                assert prev == prev_hash, i
            # entry recomputes (fd_poh append/mixin semantics)
            if has_mix:
                st = host_poh_append(prev, num_hashes - 1)
                assert host_poh_mixin(st, mixin) == h, i
            else:
                assert host_poh_append(prev, num_hashes) == h, i
            prev_hash = h
            prevs.append(np.frombuffer(prev, np.uint8))
            nums.append(num_hashes)
            mixes.append(np.frombuffer(mixin, np.uint8))
            has.append(bool(has_mix))
            exps.append(np.frombuffer(h, np.uint8))
            max_hashes = max(max_hashes, num_hashes)
        ok = np.asarray(poh_verify_entries(
            np.stack(prevs), np.asarray(nums, np.int32),
            np.stack(mixes), np.asarray(has), np.stack(exps),
            max_hashes=max_hashes))
        assert ok.all()
        # corrupting one expected hash must fail that lane only
        exps[0] = exps[0] ^ 1
        bad = np.asarray(poh_verify_entries(
            np.stack(prevs), np.asarray(nums, np.int32),
            np.stack(mixes), np.asarray(has), np.stack(exps),
            max_hashes=max_hashes))
        assert not bad[0] and bad[1:].all()
    finally:
        wksp.close()


def test_leader_bank_serves_rpc(leader):
    """The bank tile's JSON-RPC surface answers over HTTP while the
    leader loop runs (ref: src/discof/rpc/fd_rpc_tile.c subset)."""
    import json
    import urllib.request

    leader.wait_running(timeout_s=540)
    assert _wait(lambda: leader.metrics("bank0")["transfers"] == N_TXNS)
    assert _wait(lambda: leader.metrics("bank0")["rpc_port"] > 0)
    port = leader.metrics("bank0")["rpc_port"]

    def call(method, params=None):
        body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                           "params": params or []}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    assert call("getHealth")["result"] == "ok"
    assert call("getTransactionCount")["result"] == N_TXNS
    # a genesis account's balance is queryable over the wire
    from firedancer_tpu.utils.base58 import b58_encode_32
    pub = keypair(synth_signer_seed(0))[-1]
    bal = call("getBalance", [b58_encode_32(pub)])["result"]["value"]
    assert 0 < bal <= (1 << 44)


@pytest.mark.slow
def test_general_execution_bank():
    """exec="general": the bank runs the FULL host SVM per microblock
    (not just the transfer fast path) inside the live leader loop."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    genesis = {}
    for i in range(16):
        pub = keypair(synth_signer_seed(i))[-1]
        genesis[pub.hex()] = 1 << 44
    topo = (
        Topology(f"gx{os.getpid()}", wksp_size=1 << 25)
        .link("synth_verify", depth=128, mtu=1280)
        .link("verify_pack", depth=128, mtu=1280)
        .link("pack_bank0", depth=32, mtu=1 << 14)
        .link("bank0_done", depth=32, mtu=64)
        .tcache("verify_tc", depth=4096)
        .tile("synth", "synth", outs=["synth_verify"], count=N_TXNS,
              unique=N_TXNS, seed=6)
        .tile("verify", "verify", ins=["synth_verify"],
              outs=["verify_pack"], batch=16, tcache="verify_tc")
        .tile("pack", "pack", ins=["verify_pack", "bank0_done"],
              outs=["pack_bank0"], txn_in="verify_pack",
              bank_links=["pack_bank0"], done_links=["bank0_done"],
              slot_ms=200.0, max_txn_per_microblock=8)
        .tile("bank0", "bank", ins=["pack_bank0"],
              outs=["bank0_done"], exec="general", genesis=genesis)
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        assert _wait(
            lambda: runner.metrics("bank0")["transfers"] == N_TXNS,
            timeout_s=180)
        b = runner.metrics("bank0")
        assert b["exec_fail"] == 0 and b["txns"] == N_TXNS
    finally:
        runner.halt()
        runner.close()
