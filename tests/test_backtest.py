"""Backtest harness tests: record a synthetic ledger, replay it
bit-identically, and detect divergence (ref: src/discof/backtest/
fd_backtest_tile.c replay-and-assert-bank-hash discipline)."""
import io
import struct

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from firedancer_tpu.app.backtest import record, replay
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import Account


def k(n):
    return bytes([n]) * 32


def transfer_txn(src_i, dst_i, amount, blockhash=b"\x55" * 32):
    data = struct.pack("<IQ", 2, amount)
    msg = build_message([k(src_i)], [k(dst_i), bytes(32)], blockhash,
                        [(2, bytes([0, 1]), data)])
    return build_txn([bytes(64)], msg)


def _ledger(rng):
    genesis = Funk()
    for i in range(1, 6):
        genesis.rec_write(None, k(i), Account(lamports=10_000_000))
    blocks = []
    for slot in range(1, 9):
        payloads = [
            transfer_txn(int(rng.integers(1, 6)), int(rng.integers(1, 9)),
                         int(rng.integers(1, 5000)))
            for _ in range(int(rng.integers(1, 6)))]
        blocks.append((slot, payloads))
    return genesis, blocks


def test_record_replay_roundtrip():
    rng = np.random.default_rng(3)
    genesis, blocks = _ledger(rng)
    buf = io.BytesIO()
    fp = record(genesis, blocks, buf)
    buf.seek(0)
    out = replay(buf)
    assert out["fingerprint"] == fp
    assert out["blocks"] == 8
    assert out["txns"] == sum(len(p) for _, p in blocks)
    assert out["executed_ok"] >= 1
    assert out["sec_per_slot"] > 0
    # determinism: a second replay gives the same fingerprint
    buf.seek(0)
    assert replay(buf)["fingerprint"] == fp


def test_replay_detects_divergence():
    """Flipping one byte of one transaction payload must change the
    final state and fail the fingerprint assertion."""
    rng = np.random.default_rng(4)
    genesis, blocks = _ledger(rng)
    buf = io.BytesIO()
    record(genesis, blocks, buf)
    raw = bytearray(buf.getvalue())
    # find a lamports byte of the first block frame and bump it: frames
    # are zlib-or-raw, so tampering mid-stream corrupts integrity OR
    # diverges state — both must fail loudly
    raw[len(raw) // 2] ^= 1
    with pytest.raises(Exception):
        replay(io.BytesIO(bytes(raw)))


def test_replay_detects_bank_hash_divergence(tmp_path):
    """Tampering one recorded txn byte trips the PER-SLOT bank-hash
    assert (not just the final fingerprint) — the reference backtest's
    bank-hash gate (fd_backtest_tile.c:317)."""
    import io

    from firedancer_tpu.app.backtest import record, replay
    from firedancer_tpu.funk.funk import Funk
    from firedancer_tpu.svm.accdb import Account
    from firedancer_tpu.tiles.synth import make_signed_txns, synth_signer_seed
    from firedancer_tpu.utils.checkpt import CheckptReader, CheckptWriter
    from firedancer_tpu.utils.ed25519_ref import keypair

    genesis = Funk()
    for i in range(16):
        genesis.rec_write(None, keypair(synth_signer_seed(i))[-1],
                          Account(lamports=1 << 40))
    txns = make_signed_txns(8, seed=3)
    blocks = [(0, txns[:4]), (1, txns[4:])]
    buf = io.BytesIO()
    record(genesis, blocks, buf)

    # clean replay passes
    buf.seek(0)
    out = replay(buf)
    assert out["blocks"] == 2

    # tamper one byte of block 1's first txn amount; re-frame the
    # stream (frames are integrity-checked, so rewrite cleanly)
    buf.seek(0)
    frames = list(CheckptReader(buf).frames())
    blk = bytearray(frames[2])
    blk[-40] ^= 1                       # inside the last txn payload
    frames[2] = bytes(blk)
    buf2 = io.BytesIO()
    w = CheckptWriter(buf2)
    for f in frames:
        w.frame(f)
    w.fini()
    buf2.seek(0)
    with pytest.raises(AssertionError, match="slot 1"):
        replay(buf2)
