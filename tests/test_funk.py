"""funk fork-tree property tests (ref model: src/funk/test_funk_txn.c —
random fork trees checked against a naive snapshot model)."""
import random

import pytest

from firedancer_tpu.funk import Funk, FunkTxnError


def test_basic_fork_shadowing():
    f = Funk()
    f.rec_write(None, b"a", 1)        # published state
    f.txn_prepare(None, "t1")
    assert f.rec_query("t1", b"a") == 1      # inherited
    f.rec_write("t1", b"a", 2)
    assert f.rec_query("t1", b"a") == 2      # own update shadows
    assert f.rec_query(None, b"a") == 1      # root unaffected

    f.txn_prepare("t1", "t2")
    assert f.rec_query("t2", b"a") == 2      # ancestor update visible
    f.rec_remove("t2", b"a")
    assert f.rec_query("t2", b"a") is None   # tombstone shadows
    assert f.rec_query("t1", b"a") == 2


def test_competing_forks_isolated():
    f = Funk()
    f.rec_write(None, b"k", 0)
    f.txn_prepare(None, "a")
    f.txn_prepare(None, "b")
    f.rec_write("a", b"k", 1)
    f.rec_write("b", b"k", 2)
    assert f.rec_query("a", b"k") == 1
    assert f.rec_query("b", b"k") == 2
    assert f.rec_query(None, b"k") == 0


def test_publish_folds_ancestors_and_cancels_rivals():
    f = Funk()
    f.rec_write(None, b"x", 0)
    f.txn_prepare(None, "p")          # ancestor
    f.rec_write("p", b"x", 1)
    f.rec_write("p", b"y", 10)
    f.txn_prepare("p", "c")           # to publish
    f.rec_write("c", b"x", 2)
    f.txn_prepare("p", "rival")       # competing sibling
    f.rec_write("rival", b"x", 99)
    f.txn_prepare("c", "child")       # descendant of published
    f.rec_write("child", b"z", 5)

    f.txn_publish("c")
    assert f.rec_query(None, b"x") == 2       # c's update (shadowed p's)
    assert f.rec_query(None, b"y") == 10      # ancestor's fold
    assert not f.txn_is_prepared("p")         # published away
    assert not f.txn_is_prepared("c")
    assert not f.txn_is_prepared("rival")     # cancelled
    assert f.txn_is_prepared("child")         # survives, reparented
    assert f.rec_query("child", b"z") == 5
    assert f.rec_query("child", b"x") == 2    # sees new root
    assert f.last_publish == "c"


def test_cancel_subtree():
    f = Funk()
    f.txn_prepare(None, "a")
    f.txn_prepare("a", "b")
    f.txn_prepare("b", "c")
    f.txn_prepare("a", "d")
    f.txn_cancel("b")                 # kills b and c, not a/d
    assert f.txn_is_prepared("a")
    assert not f.txn_is_prepared("b")
    assert not f.txn_is_prepared("c")
    assert f.txn_is_prepared("d")


def test_errors():
    f = Funk()
    f.txn_prepare(None, "a")
    with pytest.raises(FunkTxnError):
        f.txn_prepare(None, "a")      # dup xid
    with pytest.raises(FunkTxnError):
        f.txn_prepare("zz", "b")      # unknown parent
    with pytest.raises(FunkTxnError):
        f.rec_write("zz", b"k", 1)
    with pytest.raises(FunkTxnError):
        f.rec_query("zz", b"k")
    with pytest.raises(FunkTxnError):
        f.txn_cancel("zz")
    with pytest.raises(FunkTxnError):
        f.txn_publish("zz")


class NaiveForkModel:
    """Deliberately-simple oracle: per-txn write dicts + parent links,
    query = walk up. REMOVED sentinel models tombstones."""

    REMOVED = ("REMOVED",)

    def __init__(self):
        self.root = {}
        self.writes = {}              # xid -> {key: val|REMOVED}
        self.parent = {}
        self.kids = {None: []}

    def prepare(self, parent, xid):
        self.writes[xid] = {}
        self.parent[xid] = parent
        self.kids[xid] = []
        self.kids[parent].append(xid)

    def write(self, xid, k, v):
        if xid is None:
            self.root[k] = v
        else:
            self.writes[xid][k] = v

    def remove(self, xid, k):
        if xid is None:
            self.root.pop(k, None)
        else:
            self.writes[xid][k] = self.REMOVED

    def query(self, xid, k):
        x = xid
        while x is not None:
            if k in self.writes[x]:
                v = self.writes[x][k]
                return None if v is self.REMOVED else v
            x = self.parent[x]
        return self.root.get(k)

    def _subtree(self, xid):
        out = [xid]
        for c in self.kids[xid]:
            out.extend(self._subtree(c))
        return out

    def cancel(self, xid):
        self.kids[self.parent[xid]].remove(xid)
        for x in self._subtree(xid):
            del self.writes[x], self.parent[x], self.kids[x]

    def publish(self, xid):
        chain = []
        x = xid
        while x is not None:
            chain.append(x)
            x = self.parent[x]
        for x in reversed(chain):
            for k, v in self.writes[x].items():
                if v is self.REMOVED:
                    self.root.pop(k, None)
                else:
                    self.root[k] = v
        survivors = set()
        for c in self.kids[xid]:
            survivors.update(self._subtree(c))
        new_kids = {None: list(self.kids[xid])}
        self.writes = {x: self.writes[x] for x in survivors}
        for x in survivors:
            new_kids[x] = self.kids[x]
        self.parent = {x: (self.parent[x] if self.parent[x] in survivors
                           else None) for x in survivors}
        self.kids = new_kids

    def live(self):
        return list(self.writes)


def test_randomized_vs_naive_model():
    rng = random.Random(7)
    f = Funk()
    m = NaiveForkModel()
    next_xid = 0
    keys = [bytes([k]) for k in range(8)]

    for step in range(4000):
        op = rng.random()
        live = m.live()
        if op < 0.28 or not live:     # prepare
            parent = rng.choice([None] + live)
            xid = f"t{next_xid}"
            next_xid += 1
            f.txn_prepare(parent, xid)
            m.prepare(parent, xid)
        elif op < 0.55:               # write (root writes included)
            tx = rng.choice([None] + live)
            k, v = rng.choice(keys), rng.randrange(1000)
            f.rec_write(tx, k, v)
            m.write(tx, k, v)
        elif op < 0.65:               # remove
            tx = rng.choice([None] + live)
            k = rng.choice(keys)
            f.rec_remove(tx, k)
            m.remove(tx, k)
        elif op < 0.8:                # query spot check
            tx = rng.choice([None] + live)
            k = rng.choice(keys)
            assert f.rec_query(tx, k) == m.query(tx, k), \
                f"step {step} txn {tx} key {k!r}"
        elif op < 0.9:                # cancel
            tx = rng.choice(live)
            f.txn_cancel(tx)
            m.cancel(tx)
        else:                         # publish
            tx = rng.choice(live)
            f.txn_publish(tx)
            m.publish(tx)
        assert set(x for x in m.live()) == \
            set(x for x in m.live() if f.txn_is_prepared(x))

    # final coherence sweep over every live txn and key
    for tx in [None] + m.live():
        for k in keys:
            assert f.rec_query(tx, k) == m.query(tx, k)
    assert f.root_items() == m.root
