"""Vote program tests: initialize/vote/withdraw semantics with the
choreo tower as the on-chain state machine (ref: src/flamenco/runtime/
program/fd_vote_program.c subset; tower rules src/choreo/tower)."""
import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.programs import (
    ERR_INSUFFICIENT, ERR_INVALID_OWNER, ERR_MISSING_SIG, OK,
)
from firedancer_tpu.svm.vote import (
    VOTE_PROGRAM_ID, VoteState, ix_initialize, ix_vote, ix_withdraw,
)


def k(n):
    return bytes([n]) * 32


PAYER, VOTER, NODE, VOTE_ACCT, DEST = k(1), k(2), k(3), k(4), k(5)


def txn(signers, extra, instrs):
    msg = build_message(signers, extra, b"\x22" * 32, instrs)
    return build_txn([bytes(64)] * len(signers), msg)


@pytest.fixture
def env():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, PAYER, Account(lamports=10_000_000))
    funk.rec_write(None, VOTE_ACCT,
                   Account(lamports=5_000, owner=VOTE_PROGRAM_ID))
    funk.txn_prepare(None, "blk")
    # legacy micro-balance vectors predate the rent-state
    # discipline; rent coverage lives in tests/test_rent.py +
    # the conformance vectors (enforce_rent defaults ON)
    return funk, db, TxnExecutor(db, enforce_rent=False)


def _init(ex):
    # the node identity must SIGN initialization (hijack prevention)
    t = txn([PAYER, NODE], [VOTE_ACCT, VOTE_PROGRAM_ID],
            [(3, bytes([2]), ix_initialize(NODE, VOTER, VOTER))])
    return ex.execute("blk", t)


def test_initialize_and_vote(env):
    funk, db, ex = env
    assert _init(ex).status == OK
    st = VoteState.from_bytes(db.peek("blk", VOTE_ACCT).data)
    assert st.node_pubkey == NODE and st.authorized_voter == VOTER

    # vote for slots 1..3 signed by the authorized voter
    t = txn([PAYER, VOTER], [VOTE_ACCT, VOTE_PROGRAM_ID],
            [(3, bytes([2]), ix_vote([1, 2, 3]))])
    assert ex.execute("blk", t).status == OK
    st = VoteState.from_bytes(db.peek("blk", VOTE_ACCT).data)
    assert [v.slot for v in st.tower.votes] == [1, 2, 3]
    assert [v.conf for v in st.tower.votes] == [3, 2, 1]

    # stale slots skipped; new slot expires per tower rules
    t2 = txn([PAYER, VOTER], [VOTE_ACCT, VOTE_PROGRAM_ID],
             [(3, bytes([2]), ix_vote([2, 50]))])
    assert ex.execute("blk", t2).status == OK
    st = VoteState.from_bytes(db.peek("blk", VOTE_ACCT).data)
    # slot 50 expired votes 3 (exp 5) and 2 (exp 6) but not 1 (exp 9)?
    # exp(1, conf3)=9 < 50: all expired
    assert [v.slot for v in st.tower.votes] == [50]


def test_vote_requires_authorized_voter(env):
    funk, db, ex = env
    assert _init(ex).status == OK
    t = txn([PAYER], [VOTE_ACCT, VOTE_PROGRAM_ID],
            [(2, bytes([1]), ix_vote([1]))])
    assert ex.execute("blk", t).status == ERR_MISSING_SIG


def test_initialize_requires_node_signature(env):
    funk, db, ex = env
    t = txn([PAYER], [VOTE_ACCT, VOTE_PROGRAM_ID],
            [(2, bytes([1]), ix_initialize(NODE, VOTER, VOTER))])
    assert ex.execute("blk", t).status == ERR_MISSING_SIG


def test_vote_rooting_accrues_credits(env):
    funk, db, ex = env
    assert _init(ex).status == OK
    t = txn([PAYER, VOTER], [VOTE_ACCT, VOTE_PROGRAM_ID],
            [(3, bytes([2]), ix_vote(list(range(1, 40))))])
    assert ex.execute("blk", t).status == OK
    st = VoteState.from_bytes(db.peek("blk", VOTE_ACCT).data)
    # 39 consecutive votes with 31-deep tower root slots 1..8
    assert st.root_slot == 8 and st.credits == 8
    assert len(st.tower.votes) == 31


def test_withdraw_authority_and_funds(env):
    funk, db, ex = env
    assert _init(ex).status == OK
    t = txn([PAYER, VOTER], [VOTE_ACCT, DEST, VOTE_PROGRAM_ID],
            [(4, bytes([2, 3]), ix_withdraw(3_000))])
    assert ex.execute("blk", t).status == OK
    assert db.lamports("blk", VOTE_ACCT) == 2_000
    assert db.lamports("blk", DEST) == 3_000
    # overdraw refused
    t2 = txn([PAYER, VOTER], [VOTE_ACCT, DEST, VOTE_PROGRAM_ID],
             [(4, bytes([2, 3]), ix_withdraw(10_000))])
    assert ex.execute("blk", t2).status == ERR_INSUFFICIENT
    # wrong authority refused
    t3 = txn([PAYER], [VOTE_ACCT, DEST, VOTE_PROGRAM_ID],
             [(3, bytes([1, 2]), ix_withdraw(1))])
    assert ex.execute("blk", t3).status == ERR_MISSING_SIG


def test_vote_on_non_vote_account_refused(env):
    funk, db, ex = env
    t = txn([PAYER, VOTER], [PAYER, VOTE_PROGRAM_ID],
            [(3, bytes([0]), ix_vote([1]))])
    # wait: account 0 = PAYER (system-owned)
    assert ex.execute("blk", t).status == ERR_INVALID_OWNER


def test_state_roundtrip():
    st = VoteState(NODE, VOTER, VOTER, commission=5)
    st.apply_vote([1, 2, 3, 9])
    st.credits = 7
    st.root_slot = 1
    b = st.to_bytes()
    rt = VoteState.from_bytes(b)
    assert rt.to_bytes() == b
    assert [v.slot for v in rt.tower.votes] == \
        [v.slot for v in st.tower.votes]
    assert rt.root_slot == 1 and rt.credits == 7 and rt.commission == 5


def test_authorize_and_update_commission(env):
    import struct as _s

    from firedancer_tpu.svm.vote import (
        AUTH_KIND_VOTER, AUTH_KIND_WITHDRAWER, VOTE_IX_AUTHORIZE,
        VOTE_IX_UPDATE_COMMISSION,
    )
    funk, db, ex = env
    assert _init(ex).status == OK          # withdrawer == VOTER
    new_voter = k(0x51)
    # the withdrawer authorizes a new voter
    t = txn([PAYER, VOTER], [VOTE_ACCT, VOTE_PROGRAM_ID],
            [(3, bytes([2]), _s.pack("<I", VOTE_IX_AUTHORIZE)
              + new_voter + _s.pack("<I", AUTH_KIND_VOTER))])
    assert ex.execute("blk", t).status == OK
    st = VoteState.from_bytes(db.peek("blk", VOTE_ACCT).data)
    assert st.authorized_voter == new_voter
    # a non-authority cannot flip the withdrawer
    evil = k(0x66)
    funk.rec_write("blk", evil, Account(lamports=1 << 30))
    t = txn([evil], [VOTE_ACCT, VOTE_PROGRAM_ID],
            [(2, bytes([1]), _s.pack("<I", VOTE_IX_AUTHORIZE)
              + evil + _s.pack("<I", AUTH_KIND_WITHDRAWER))])
    assert ex.execute("blk", t).status == ERR_MISSING_SIG
    # commission update needs the withdrawer
    t = txn([PAYER, VOTER], [VOTE_ACCT, VOTE_PROGRAM_ID],
            [(3, bytes([2]),
              _s.pack("<I", VOTE_IX_UPDATE_COMMISSION) + bytes([42]))])
    assert ex.execute("blk", t).status == OK
    st = VoteState.from_bytes(db.peek("blk", VOTE_ACCT).data)
    assert st.commission == 42


def test_epoch_credits_seed_matches_agave():
    # Agave increment_credits seeds an empty history with (epoch, 0, 0)
    # so pre-existing account credits never inflate the first rewarded
    # epoch's earned delta (ADVICE r4).
    from firedancer_tpu.svm.vote import VoteState
    st = VoteState(node_pubkey=b"\x01" * 32, authorized_voter=b"\x02" * 32,
                   authorized_withdrawer=b"\x02" * 32)
    st.credits = 1000                       # pre-existing, empty history
    st._increment_credits(epoch=7)
    ep, cr, prev = st.epoch_credits[-1]
    assert (ep, cr, prev) == (7, 1, 0)
    st._increment_credits(epoch=7)
    assert st.epoch_credits[-1] == (7, 2, 0)
    st._increment_credits(epoch=8)
    assert st.epoch_credits[-1] == (8, 3, 2)


def test_epoch_credits_empty_epoch_moves_in_place():
    """Agave increment_credits: when the open entry earned nothing
    (credits == prev_credits — e.g. a deserialized account whose last
    epochs were quiet), an epoch change MOVES the entry instead of
    appending, so empty epochs never consume 64-entry window slots
    (ADVICE r5 last open item)."""
    from firedancer_tpu.svm.vote import VoteState
    st = VoteState(node_pubkey=b"\x01" * 32, authorized_voter=b"\x02" * 32,
                   authorized_withdrawer=b"\x02" * 32)
    # deserialized shape: history ends in an entry that earned nothing
    st.epoch_credits = [(3, 10, 4), (5, 10, 10)]
    st.credits = 10
    st._increment_credits(epoch=9)
    # the empty epoch-5 entry was moved to epoch 9, NOT appended after
    assert st.epoch_credits == [(3, 10, 4), (9, 11, 10)]
    # and an entry that DID earn still appends on epoch change
    st._increment_credits(epoch=10)
    assert st.epoch_credits == [(3, 10, 4), (9, 11, 10), (10, 12, 11)]
    # window cap still enforced on the append path
    st.epoch_credits = [(e, e + 1, e) for e in range(64)]
    st.credits = 64
    st._increment_credits(epoch=99)
    assert len(st.epoch_credits) == 64
    assert st.epoch_credits[-1] == (99, 65, 64)
    assert st.epoch_credits[0] == (1, 2, 1)
