"""RLC batch verification tests: the batch check must accept exactly
the batches whose prechecked lanes all verify individually, and the
wrapper's per-lane verdicts must equal verify_batch bit-for-bit
(ref: src/ballet/ed25519/fd_ed25519_user.c:232 batch entry point;
PERF.md path-to-1M item 1)."""
import hashlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from firedancer_tpu.ops import ed25519 as ed  # noqa: E402
from firedancer_tpu.utils import ed25519_ref as ref  # noqa: E402

B, MLEN = 8, 48


def _batch(rng, corrupt=()):
    sig = np.zeros((B, 64), np.uint8)
    pub = np.zeros((B, 32), np.uint8)
    msg = np.zeros((B, MLEN), np.uint8)
    ln = np.full((B,), MLEN, np.int32)
    for i in range(B):
        seed = hashlib.sha256(b"rlc-%d" % i).digest()
        _, _, pk = ref.keypair(seed)
        m = rng.bytes(MLEN)
        s = ref.sign(seed, m)
        sig[i] = np.frombuffer(s, np.uint8)
        pub[i] = np.frombuffer(pk, np.uint8)
        msg[i] = np.frombuffer(m, np.uint8)
    for i in corrupt:
        sig[i, 40] ^= 1                   # corrupt S
    return (jnp.asarray(sig), jnp.asarray(pub), jnp.asarray(msg),
            jnp.asarray(ln))


def _z(rng):
    return jnp.asarray(rng.integers(0, 256, (B, 16), dtype=np.uint8))


def test_sc_mul_sum_mod_l():
    rng = np.random.default_rng(1)
    a = int.from_bytes(rng.bytes(32), "little") % ed.L
    z = int.from_bytes(rng.bytes(16), "little")
    a_d = jnp.asarray(ed._int_digits(a, 20))[None]
    z_d = jnp.asarray(ed._int_digits(z, 10))[None]
    got = np.asarray(ed.sc_mul_mod_l(a_d, z_d))[0]
    want = ed._int_digits(a * z % ed.L, 20)
    assert (got == want).all()
    # sum
    vals = [int.from_bytes(rng.bytes(32), "little") % ed.L
            for _ in range(50)]
    d = jnp.asarray(np.stack([ed._int_digits(v, 20) for v in vals]))
    got = np.asarray(ed.sc_sum_mod_l(d, axis=0))
    assert (got == ed._int_digits(sum(vals) % ed.L, 20)).all()


def test_rlc_accepts_valid_batch():
    rng = np.random.default_rng(2)
    sig, pub, msg, ln = _batch(rng)
    ok, lane_pre = ed.rlc_verify_batch(sig, pub, msg, ln, _z(rng))
    assert bool(ok)
    assert np.asarray(lane_pre).all()


def test_rlc_rejects_corrupt_batch():
    rng = np.random.default_rng(3)
    sig, pub, msg, ln = _batch(rng, corrupt=(3,))
    ok, _ = ed.rlc_verify_batch(sig, pub, msg, ln, _z(rng))
    assert not bool(ok)


def test_rlc_masks_structural_rejects():
    """Lanes failing prechecks (non-canonical S, bad A encoding) are
    excluded from the sum: the REST of the batch still passes, and the
    bad lanes report lane_pre False."""
    rng = np.random.default_rng(4)
    sig, pub, msg, ln = _batch(rng)
    sig = np.array(sig)
    pub = np.array(pub)
    s_big = (ed.L + 7).to_bytes(32, "little")
    sig[1, 32:] = np.frombuffer(s_big, np.uint8)      # S >= l
    pub[2] = np.frombuffer((1).to_bytes(32, "little"), np.uint8)  # small order
    ok, lane_pre = ed.rlc_verify_batch(jnp.asarray(sig), jnp.asarray(pub),
                                       msg, ln, _z(rng))
    lane_pre = np.asarray(lane_pre)
    assert bool(ok)
    assert not lane_pre[1] and not lane_pre[2]
    assert lane_pre[[0, 3, 4, 5, 6, 7]].all()


def test_wrapper_matches_verify_batch():
    rng = np.random.default_rng(5)
    for corrupt in ((), (0,), (2, 5)):
        sig, pub, msg, ln = _batch(rng, corrupt=corrupt)
        got = ed.verify_batch_rlc(sig, pub, msg, ln,
                                  rng=np.random.default_rng(9))
        want = np.asarray(ed.verify_batch(sig, pub, msg, ln))
        assert (np.asarray(got) == want).all(), corrupt


def _order8_torsion_point():
    """A torsion point of exact order 8 from the small-order encoding
    table (host oracle arithmetic)."""
    from firedancer_tpu.ops.ed25519 import _small_order_encodings
    for enc in np.asarray(_small_order_encodings()):
        pt = ref.pt_decompress(bytes(enc))
        if pt is None:
            continue
        p2 = ref.pt_add(pt, pt)
        p4 = ref.pt_add(p2, p2)
        if not ref.is_small_order(p4):      # [4]T has order 2 -> ord 8
            continue
        # exact order 8: [4]T != identity
        zi = pow(p4[2], -1, ref.P)
        if (p4[0] * zi % ref.P, p4[1] * zi % ref.P) != (0, 1):
            return pt
    raise AssertionError("no order-8 point found")


def test_rlc_is_cofactored_not_consensus_exact():
    """The documented divergence class: R* = R + T (T pure 8-torsion,
    not a small-order encoding) gives a residual −zT. Individual verify
    ALWAYS rejects; the RLC batch verdict equals the cofactored
    equation, so over many random z draws it must accept sometimes
    (z ≡ 0 mod 8, p = 1/8) and reject otherwise — pinning exactly why
    rlc stays out of the consensus verify tile."""
    rng = np.random.default_rng(11)
    seed = hashlib.sha256(b"torsion").digest()
    a_int, _, pk = ref.keypair(seed)
    m = rng.bytes(MLEN)
    t_pt = _order8_torsion_point()
    # forge: R* = rB + T; k = H(R*, A, m); S = r + k·a (valid relation
    # up to the torsion component)
    r_scalar = int.from_bytes(hashlib.sha512(b"r" + m).digest(), "little") % ed.L
    r_pt = ref.pt_mul(r_scalar, ref._basepoint())
    r_star = ref.pt_add(r_pt, t_pt)
    r_bytes = ref.pt_compress(r_star)
    k = int.from_bytes(hashlib.sha512(
        r_bytes + pk + m).digest(), "little") % ed.L
    s = (r_scalar + k * a_int) % ed.L
    sig_t = r_bytes + s.to_bytes(32, "little")

    sig, pub, msg, ln = _batch(rng)
    sig = np.array(sig)
    pub = np.array(pub)
    msg = np.array(msg)
    sig[0] = np.frombuffer(sig_t, np.uint8)
    pub[0] = np.frombuffer(pk, np.uint8)
    msg[0] = np.frombuffer(m, np.uint8)
    args = (jnp.asarray(sig), jnp.asarray(pub), jnp.asarray(msg), ln)

    # individual (cofactorless, reference semantics): always rejects
    assert not np.asarray(ed.verify_batch(*args))[0]

    # batch: z with low 3 bits zero kills the torsion -> accepts;
    # z odd keeps it -> rejects. Both outcomes must occur as documented.
    z = np.array(np.random.default_rng(1).integers(
        0, 256, (B, 16), dtype=np.uint8))
    z[0, 0] &= 0xF8                       # z_0 ≡ 0 (mod 8)
    ok, lane_pre = ed.rlc_verify_batch(*args, jnp.asarray(z))
    assert bool(ok) and np.asarray(lane_pre)[0]      # cofactored accept
    z[0, 0] |= 1                          # z_0 odd: torsion survives
    ok, _ = ed.rlc_verify_batch(*args, jnp.asarray(z))
    assert not bool(ok)
