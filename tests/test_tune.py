"""fdtune: offline knob autotuning + the adaptive controller tile.

The r20 acceptance checklist: [tune] schema triple gate (config load /
topo.build / fdlint bad-tune) + the lint registry mirror; knob-mailbox
ABI round-trip + single-writer ownership lint fixture; controller
hysteresis non-oscillation under a scripted step load AND a flapping
flood (decision count bounded by the window budget, no limit cycle,
relief sticky, revert never overshoots); offline sweep resumability
(kill mid-sweep -> resume skips completed points, tuned_vs_default_tps
>= 1.0 by construction); tuned-profile provenance round-trip +
FDTPU_TUNED_PROFILE application; and the live acceptance drill —
real shm pressure -> controller widens the coalesce window + tightens
the shed -> EV_TUNE in the trace ring -> fdgui tune panel data ->
knobs revert after the recovery dwell.
"""
import json
import os

import pytest

from firedancer_tpu.runtime import KnobMailbox, Workspace
from firedancer_tpu.tune import (KNOB_KEYS, KNOBS, RUNTIME_KNOBS,
                                 TUNE_DEFAULTS, KnobReader, knob_space,
                                 normalize_tune, reader_for)
from firedancer_tpu.tune.controller import Controller
from firedancer_tpu.tune.search import (axis_candidates, load_state,
                                        point_key, run_sweep)

pytestmark = pytest.mark.tune

_N = [0]


def _wksp(size=1 << 16):
    _N[0] += 1
    return Workspace(f"/fdtpu_tune_{os.getpid()}_{_N[0]}", size)


# ---------------------------------------------------------------------------
# [tune] schema: the one validator + the triple gate + registry mirror
# ---------------------------------------------------------------------------

def test_normalize_tune_defaults():
    cfg = normalize_tune(None)
    assert cfg["enable"] is True and cfg["knob"] == {}
    assert cfg["cooldown_s"] >= cfg["interval_s"]
    assert 0 < cfg["hysteresis"] < 1
    # explicit section: defaults fill, overrides land
    cfg = normalize_tune({"interval_s": 0.5, "cooldown_s": 1.0})
    assert cfg["interval_s"] == 0.5 and cfg["max_moves"] == 4


def test_normalize_tune_rejections():
    with pytest.raises(ValueError, match="did you mean 'interval_s'"):
        normalize_tune({"intervals": 1})
    with pytest.raises(ValueError, match="must be > 0"):
        normalize_tune({"interval_s": 0})
    with pytest.raises(ValueError, match="hysteresis"):
        normalize_tune({"hysteresis": 1.5})
    with pytest.raises(ValueError, match="cooldown_s must be >="):
        normalize_tune({"interval_s": 2.0, "cooldown_s": 0.5})
    with pytest.raises(ValueError, match="max_moves"):
        normalize_tune({"max_moves": 0})
    with pytest.raises(ValueError, match="did you mean 'coalesce_us'"):
        normalize_tune({"knob": {"coalesce_u": {"max": 100}}})
    with pytest.raises(ValueError, match="did you mean 'default'"):
        normalize_tune({"knob": {"coalesce_us": {"defalt": 100}}})
    with pytest.raises(ValueError, match="min.*> max"):
        normalize_tune({"knob": {"coalesce_us": {"min": 10,
                                                 "max": 5}}})
    with pytest.raises(ValueError, match="outside"):
        normalize_tune({"knob": {"pack_wave": {"default": 99}}})
    with pytest.raises(ValueError, match="step must be > 0"):
        normalize_tune({"knob": {"pack_wave": {"step": 0}}})


def test_knob_space_merges_overrides():
    sp = knob_space(normalize_tune(
        {"knob": {"coalesce_us": {"max": 800, "step": 50}}}))
    assert sp["coalesce_us"]["max"] == 800
    assert sp["coalesce_us"]["step"] == 50
    assert sp["coalesce_us"]["default"] == KNOBS["coalesce_us"]["default"]
    assert sp["pack_wave"]["max"] == KNOBS["pack_wave"]["max"]
    # runtime subset = the mailbox slot ABI, catalog order
    assert RUNTIME_KNOBS == tuple(n for n, s in KNOBS.items()
                                  if s["runtime"])
    assert "verify_batch" not in RUNTIME_KNOBS     # offline-only


def test_registry_mirrors_tune_keys():
    """The fdlint key registry's [tune] mirror must track the one
    validator's schema (the [trace]/[slo]/[witness] honesty rule)."""
    from firedancer_tpu.lint import registry as reg
    assert set(reg.TUNE_SECTION_KEYS) == set(TUNE_DEFAULTS)
    assert set(reg.TUNE_KNOB_KEYS) == set(KNOB_KEYS)


def test_config_load_gate():
    """Gate 1 of the triple: a bad [tune] fails build_topology before
    any topology exists."""
    from firedancer_tpu.app.config import build_topology
    base = {"tile": [{"name": "s", "kind": "synth", "outs": ["a_b"]},
                     {"name": "d", "kind": "sink", "ins": ["a_b"]}],
            "link": [{"name": "a_b", "depth": 64, "mtu": 256}]}
    with pytest.raises(ValueError, match="did you mean 'interval_s'"):
        build_topology({**base, "tune": {"intervals": 1}})
    topo = build_topology({**base, "tune": {"enable": True}})
    assert topo.tune == {"enable": True}


def _build(tune=None, controller=False, trace=None, metric=False,
           slo=None):
    from firedancer_tpu.disco import Topology
    topo = Topology(f"tnb{os.getpid()}_{_N[0]}", wksp_size=1 << 21,
                    tune=tune, trace=trace, slo=slo)
    _N[0] += 1
    topo.link("a_b", depth=32, mtu=256)
    topo.tile("src", "synth", outs=["a_b"], count=8, unique=4)
    topo.tile("dst", "sink", ins=["a_b"])
    if metric:
        topo.tile("metric", "metric", port=0)
    if controller:
        topo.tile("ctl", "controller")
    return topo.build()


def test_build_carves_mailbox_only_when_enabled():
    """Gate 2: topo.build. Enabled -> mailbox carved + the runtime
    knob order frozen as plan ABI; disabled/absent -> NO plan keys
    (the fdtrace disabled-path contract)."""
    plan = _build()
    try:
        assert plan["tune"] is None
        assert "tune_mailbox_off" not in plan
        assert "tune_knobs" not in plan
    finally:
        Workspace.unlink_name(plan["wksp"]["name"])
    plan = _build(tune={"enable": True})
    try:
        assert plan["tune"]["enable"] is True
        assert plan["tune_knobs"] == list(RUNTIME_KNOBS)
        assert plan["tune_mailbox_off"] % 8 == 0
    finally:
        Workspace.unlink_name(plan["wksp"]["name"])


def test_build_rejects_controller_without_tune():
    with pytest.raises(ValueError, match="no knob mailbox"):
        _build(controller=True)
    with pytest.raises(ValueError, match="no knob mailbox"):
        _build(tune={"enable": False}, controller=True)


def test_lint_bad_tune():
    """Gate 3: the fdlint graph rule — typo'd key with did-you-mean,
    bad bounds, controller-without-tune, and clean when valid."""
    from firedancer_tpu.lint.graph import lint_config

    def cfg(**extra):
        c = {"link": [{"name": "a_b", "depth": 64, "mtu": 1280}],
             "tile": [{"name": "src", "kind": "synth",
                       "outs": ["a_b"]},
                      {"name": "dst", "kind": "sink", "ins": ["a_b"]}]}
        c.update(extra)
        return c

    def fires_once(findings, rule):
        hits = [f for f in findings if f.rule == rule]
        assert len(hits) == 1, findings
        return hits[0]

    f = fires_once(lint_config(cfg(tune={"intervals": 1}),
                               "<fixture>"), "bad-tune")
    assert "did you mean 'interval_s'" in f.message
    fires_once(lint_config(cfg(tune={"hysteresis": 2.0}),
                           "<fixture>"), "bad-tune")
    fires_once(lint_config(
        cfg(tune={"knob": {"coalesce_us": {"min": 9, "max": 3}}}),
        "<fixture>"), "bad-tune")
    # a controller tile with no (or disabled) [tune] has nothing to
    # steer — same message as the build-time gate
    c = cfg()
    c["tile"].append({"name": "ctl", "kind": "controller"})
    f = fires_once(lint_config(c, "<fixture>"), "bad-tune")
    assert "no knob mailbox" in f.message
    c2 = cfg(tune={"enable": False})
    c2["tile"].append({"name": "ctl", "kind": "controller"})
    fires_once(lint_config(c2, "<fixture>"), "bad-tune")
    c3 = cfg(tune={"enable": True,
                   "knob": {"coalesce_us": {"max": 1000}}})
    c3["tile"].append({"name": "ctl", "kind": "controller"})
    assert lint_config(c3, "<fixture>") == []


def test_tune_demo_config_is_lint_clean():
    from firedancer_tpu.lint.graph import lint_config_file
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "cfg", "tune-demo.toml")
    assert lint_config_file(path) == []


# ---------------------------------------------------------------------------
# knob mailbox: ABI round-trip + reader side + ownership catalog
# ---------------------------------------------------------------------------

def test_mailbox_roundtrip():
    w = _wksp()
    try:
        mb = KnobMailbox.create(w, 4)
        assert mb.generation == 0
        assert mb.read(2) == (0, 0)              # never posted
        mb.post(2, 500, ts_ns=123)
        assert mb.read(2) == (500, 1)
        assert mb.generation == 1
        mb.post(2, 600)
        assert mb.read(2) == (600, 2)
        mb.post(0, 7)
        assert mb.generation == 3
        gen, slots = mb.snapshot()
        assert gen == 3 and slots.shape == (4, 4)
        assert int(slots[2][0]) == 600 and int(slots[2][1]) == 2
        # a second attach over the same offsets sees the same state
        # (the inter-process ABI)
        mb2 = KnobMailbox(w, mb.off, 4)
        assert mb2.read(2) == (600, 2)
        with pytest.raises(IndexError):
            mb.post(4, 1)
        with pytest.raises(ValueError):
            KnobMailbox.create(w, 0)
    finally:
        w.close()
        w.unlink()


def test_reader_for_resolves_by_tile_kind():
    """TileCtx.knobs contract: None without a mailbox, None for kinds
    with no runtime knob, a slot-resolved KnobReader otherwise —
    values None until the controller has ever posted."""
    plan = _build(tune={"enable": True})
    w = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                  create=False)
    try:
        assert reader_for(plan, w, "dst") is None        # sink: none
        # a disabled plan has no keys at all -> None fast path
        assert reader_for({"tiles": plan["tiles"]}, w, "src") is None
        # synth has no runtime knob either
        assert reader_for(plan, w, "src") is None
        # fabricate a verify-kind tile entry to exercise resolution
        plan["tiles"]["v"] = {"kind": "verify"}
        rd = reader_for(plan, w, "v")
        assert isinstance(rd, KnobReader)
        assert set(rd.knobs) == {"coalesce_us", "bulk_prefilter"}
        assert rd.get("coalesce_us") is None             # seq 0
        assert rd.get("pack_wave") is None               # not his knob
        mb = KnobMailbox(w, plan["tune_mailbox_off"],
                         len(plan["tune_knobs"]))
        mb.post(plan["tune_knobs"].index("coalesce_us"), 400)
        assert rd.get("coalesce_us") == 400
    finally:
        w.close()
        Workspace.unlink_name(plan["wksp"]["name"])


def test_mailbox_ownership_lint():
    """The knob mailbox is a cataloged single-writer region: a post
    from anywhere but the controller's decision loop is a dual-writer
    finding; the cataloged writer is clean."""
    import textwrap
    from firedancer_tpu.lint.ownership import lint_ownership_source
    body = textwrap.dedent("""
        def hijack(self, idx, value):
            self.mailbox.post(idx, value)
    """)
    findings = lint_ownership_source(body, "tiles/evil.py")
    hits = [f for f in findings if f.rule == "dual-writer"]
    assert len(hits) == 1 and "knob-mailbox" in hits[0].message
    assert lint_ownership_source(body, "tune/controller.py") == []
    # the shipped controller passes its own catalog
    from firedancer_tpu.lint.abi import pkg_root
    with open(os.path.join(pkg_root(), "tune", "controller.py")) as f:
        src = f.read()
    assert lint_ownership_source(src, "tune/controller.py") == []


# ---------------------------------------------------------------------------
# controller hysteresis: the non-oscillation proofs (scripted clock)
# ---------------------------------------------------------------------------

CALM = {"breached": 0, "burn": 0.0, "bp_delta": 0, "worst_link": None,
        "overloaded": False}
SATURATED = {"breached": 1, "burn": 1.0, "bp_delta": 500,
             "worst_link": "a_b", "overloaded": True}


class FakeProbe:
    def __init__(self):
        self.sample = dict(CALM)

    def poll(self):
        return dict(self.sample)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


CFG = {"enable": True, "interval_s": 0.25, "cooldown_s": 1.0,
       "recovery_s": 2.0, "hysteresis": 0.5, "max_moves": 3,
       "window_s": 4.0, "bp_ref": 100.0}


def _controller(cfg=None):
    w = _wksp()
    mb = KnobMailbox.create(w, len(RUNTIME_KNOBS))
    plan = {"tune_knobs": list(RUNTIME_KNOBS),
            "tune_mailbox_off": mb.off, "tiles": {}, "links": {}}
    clock, probe = FakeClock(), FakeProbe()
    c = Controller(plan, w, cfg=dict(cfg or CFG), clock=clock,
                   probe=probe)
    return c, clock, probe, w


def test_controller_requires_mailbox():
    w = _wksp()
    try:
        with pytest.raises(ValueError, match="no knob mailbox"):
            Controller({"tiles": {}}, w, cfg=dict(CFG))
    finally:
        w.close()
        w.unlink()


def test_step_load_bounded_and_reverts_after_recovery():
    """Scripted step load: saturation escalates one cooldown-paced
    step at a time under the shared window budget; calm holds for
    recovery_s before ONE revert step at a time walks every knob back
    to its default; then the loop goes quiet (no limit cycle)."""
    c, clock, probe, w = _controller()
    try:
        probe.sample = dict(SATURATED)
        decisions = []
        while clock.t < 10.0:
            decisions.append(c.poll())
            clock.t += 0.25
        moved = [d for d in decisions if d]
        n_moves = sum(len(d) for d in moved)
        # hard budget: max_moves per rolling window_s
        windows = 10.0 / CFG["window_s"] + 1
        assert 0 < n_moves <= CFG["max_moves"] * windows
        # every accepted move is relief, paced by per-knob cooldown
        per_knob = {}
        for batch in moved:
            for d in batch:
                assert d["why"] == "relief"
                assert d["worst_link"] == "a_b"
                per_knob.setdefault(d["knob"], []).append(d["t"])
        for knob, ts in per_knob.items():
            for a, b in zip(ts, ts[1:]):
                assert b - a >= CFG["cooldown_s"], knob
        # the mailbox saw the steering (seq > 0, escalated values)
        sp = knob_space(c.cfg)
        steered = [n for n in c.names
                   if c.mailbox.read(c._slot[n])[1] > 0]
        assert steered
        for n in steered:
            v, _ = c.mailbox.read(c._slot[n])
            assert v > sp[n]["default"] or sp[n]["default"] == \
                sp[n]["max"]
        # step ends: calm must persist recovery_s before ANY revert
        probe.sample = dict(CALM)
        t0 = clock.t
        reverted = []
        while clock.t < t0 + 15.0:
            reverted.extend(c.poll())
            clock.t += 0.25
        assert all(d["why"] == "revert" for d in reverted)
        assert min(d["t"] for d in reverted) >= t0 + CFG["recovery_s"]
        # fully recovered: every knob back at its default, and the
        # controller is QUIET (the no-limit-cycle assertion)
        assert c.value == {n: sp[n]["default"] for n in c.names}
        t1 = clock.t
        while clock.t < t1 + 5.0:
            assert c.poll() == []
            clock.t += 0.25
    finally:
        w.close()
        w.unlink()


def test_dead_band_holds_everything():
    """Pressure inside the hysteresis band moves nothing — no
    escalation, no revert, no calm reset (the anti-flap core)."""
    c, clock, probe, w = _controller()
    try:
        # bp folds to 0.5: exactly the band center (act_lo=0.25,
        # act_hi=0.75 at hysteresis 0.5)
        probe.sample = {**CALM, "bp_delta": 50}
        while clock.t < 8.0:
            assert c.poll() == []
            clock.t += 0.25
        assert c.decisions == 0
    finally:
        w.close()
        w.unlink()


def test_flapping_flood_no_oscillation():
    """Pressure flapping 1.0/0.0 every interval: relief stays sticky
    (a blip resets the recovery dwell, so there are NO reverts), the
    escalations pace at per-knob cooldown, and total decisions stay
    inside the rolling window budget — the limit-cycle killer."""
    c, clock, probe, w = _controller()
    try:
        decisions = []
        times = []
        flip = False
        while clock.t < 12.0:
            probe.sample = dict(SATURATED if flip else CALM)
            flip = not flip
            for d in c.poll():
                decisions.append(d)
                times.append(clock.t)
            clock.t += 0.25
        assert decisions, "flapping saturation must still escalate"
        assert all(d["why"] == "relief" for d in decisions), \
            "a revert during a flap means the dwell is broken"
        # rolling window budget holds at every instant
        for t in times:
            in_win = [x for x in times
                      if t - CFG["window_s"] < x <= t]
            assert len(in_win) <= CFG["max_moves"]
        # and once the flood genuinely ends, it recovers + goes quiet
        probe.sample = dict(CALM)
        t0 = clock.t
        while clock.t < t0 + 20.0:
            c.poll()
            clock.t += 0.25
        sp = knob_space(c.cfg)
        assert c.value == {n: sp[n]["default"] for n in c.names}
    finally:
        w.close()
        w.unlink()


def test_revert_never_overshoots_default():
    c, clock, probe, w = _controller(
        {**CFG, "knob": {"coalesce_us": {"step": 300}}})
    try:
        probe.sample = dict(SATURATED)
        c.poll()                                  # one relief step
        assert c.value["coalesce_us"] == 200 + 300
        probe.sample = dict(CALM)
        clock.t = 100.0                           # long past recovery
        c.poll()
        clock.t += CFG["recovery_s"] + 0.1
        moved = c.poll()
        assert any(d["knob"] == "coalesce_us" and d["value"] == 200
                   for d in moved)
        assert c.value["coalesce_us"] == 200      # not 200 - 100
    finally:
        w.close()
        w.unlink()


def test_controller_status_document():
    c, clock, probe, w = _controller()
    try:
        probe.sample = dict(SATURATED)
        c.poll()
        st = c.status()
        assert st["pressure"] == 1.0
        assert st["decisions"] >= 1
        assert st["max_moves"] == CFG["max_moves"]
        assert st["last"]["worst_link"] == "a_b"
        steered = [n for n, k in st["knobs"].items() if k["steered"]]
        assert steered
        for n in steered:
            assert st["knobs"][n]["value"] != st["knobs"][n]["default"]
    finally:
        w.close()
        w.unlink()


# ---------------------------------------------------------------------------
# offline sweep: checkpointed search, resumable by construction
# ---------------------------------------------------------------------------

def _score(pt):
    # interior optimum at coalesce_us=400: the coarse grid can't land
    # on it, the refinement step gets closer — and every score beats
    # nothing (the default point is always in the argmax set)
    return 1000.0 - abs(pt["coalesce_us"] - 400) * 0.1 \
        - abs(pt["verify_batch"] - 32)


def test_axis_candidates_are_bounded_and_deduped():
    sp = knob_space(None)
    for name in ("coalesce_us", "verify_batch"):
        vals = axis_candidates(sp[name], points=5)
        assert len(vals) <= 5 and len(set(vals)) == len(vals)
        assert all(sp[name]["min"] <= v <= sp[name]["max"]
                   for v in vals)
        assert vals[0] == sp[name]["default"]


def test_sweep_finds_knee_and_ratio_floor(tmp_path):
    calls = []

    def bench(pt):
        calls.append(dict(pt))
        return _score(pt)

    res = run_sweep(bench, str(tmp_path / "s.json"), points=3)
    assert res["measured"] == len(calls) == res["points"]
    # default point measured FIRST: the ratio floor by construction
    assert calls[0] == {"coalesce_us": 200, "verify_batch": 32}
    assert res["tuned_vs_default_tps"] >= 1.0
    assert res["default_tps"] == _score(calls[0])
    # the refinement walked one step toward the interior optimum
    assert res["knobs"]["coalesce_us"] == 300
    assert res["tuned_tps"] == _score(res["knobs"])


def test_sweep_kill_and_resume(tmp_path):
    """A sweep killed mid-flight resumes from its checkpoint: every
    completed point is skipped (never re-measured), the final result
    matches an uninterrupted run."""
    state = str(tmp_path / "s.json")
    first = []

    def dying_bench(pt):
        if len(first) == 3:
            raise RuntimeError("SIGKILL stand-in")
        first.append(dict(pt))
        return _score(pt)

    with pytest.raises(RuntimeError):
        run_sweep(dying_bench, state, points=3)
    assert len(load_state(state)["points"]) == 3     # landed pre-kill
    second = []

    def resumed_bench(pt):
        second.append(dict(pt))
        return _score(pt)

    res = run_sweep(resumed_bench, state, points=3)
    done = {point_key(p) for p in first}
    assert all(point_key(p) not in done for p in second), \
        "resume re-measured a completed point"
    assert res["measured"] == len(second)
    assert res["points"] == len(first) + len(second)
    assert res["knobs"]["coalesce_us"] == 300        # same knee
    assert res["tuned_vs_default_tps"] >= 1.0
    # a corrupt checkpoint degrades to a fresh sweep, never a crash
    with open(state, "w") as f:
        f.write("not json")
    assert load_state(state)["points"] == {}


def test_sweep_rejects_unknown_axis(tmp_path):
    with pytest.raises(ValueError, match="unknown knob axis"):
        run_sweep(lambda pt: 1.0, str(tmp_path / "s.json"),
                  axes=("coalesce_us", "warp_factor"))


# ---------------------------------------------------------------------------
# tuned profiles: provenance round-trip + application
# ---------------------------------------------------------------------------

def test_profile_roundtrip_and_validation(tmp_path):
    from firedancer_tpu.tune.profile import (diff_profiles,
                                             load_profile,
                                             make_profile,
                                             save_profile)
    doc = make_profile({"coalesce_us": 400, "verify_batch": 64},
                       tuned_tps=1200.0, default_tps=1000.0,
                       sweep={"count": 2048})
    assert doc["measured"]["tuned_vs_default_tps"] == 1.2
    assert doc["host"]["hostname"] and doc["host"]["cpus"]
    path = str(tmp_path / "p.json")
    save_profile(doc, path)
    back = load_profile(path)
    assert back == doc
    with pytest.raises(ValueError, match="unknown knob"):
        make_profile({"warp_factor": 9}, 1.0, 1.0)
    bad = dict(doc)
    bad["fdtune_profile"] = 99
    p2 = str(tmp_path / "bad.json")
    with open(p2, "w") as f:
        json.dump(bad, f)
    with pytest.raises(ValueError, match="not an fdtune profile"):
        load_profile(p2)
    d = diff_profiles(doc, make_profile({"coalesce_us": 200},
                                        1.0, 1.0))
    assert d == {"coalesce_us": (400, 200), "verify_batch": (64, 32)}


def test_profile_applies_to_unbuilt_topology(tmp_path, monkeypatch):
    from firedancer_tpu.app.config import build_topology
    from firedancer_tpu.tune.profile import (apply_profile,
                                             make_profile,
                                             save_profile)
    doc = make_profile({"coalesce_us": 700, "verify_batch": 64,
                        "shed_tighten": 2}, 1100.0, 1000.0)
    cfg = {"link": [{"name": "a_b", "depth": 64, "mtu": 1280},
                    {"name": "b_c", "depth": 64, "mtu": 1280}],
           "tile": [{"name": "src", "kind": "synth", "outs": ["a_b"]},
                    {"name": "v", "kind": "verify", "ins": ["a_b"],
                     "outs": ["b_c"], "batch": 32},
                    {"name": "dst", "kind": "sink", "ins": ["b_c"]}]}
    topo = build_topology(cfg)
    applied = apply_profile(topo, doc)
    assert topo.tiles["v"].args["coalesce_us"] == 700
    assert topo.tiles["v"].args["batch"] == 64
    # shed_tighten is runtime-only: no boot-time arg to seed
    assert sorted(a for _, a, _ in applied) == ["batch", "coalesce_us"]
    # the FDTPU_TUNED_PROFILE hook does the same through the env
    path = str(tmp_path / "p.json")
    save_profile(doc, path)
    monkeypatch.setenv("FDTPU_TUNED_PROFILE", path)
    topo2 = build_topology(cfg)
    assert topo2.tiles["v"].args["coalesce_us"] == 700
    assert topo2.tiles["v"].args["batch"] == 64


# ---------------------------------------------------------------------------
# the live acceptance drill: shm pressure -> decisions -> EV_TUNE ->
# fdgui panel -> recovery
# ---------------------------------------------------------------------------

def test_live_acceptance_drill():
    """Real plan + wksp (metric tile, [slo], [tune], [trace], a
    controller tile): inject an SLO breach + link backpressure
    straight into shm, drive the controller on a scripted clock, and
    assert the whole reporting chain — mailbox posts, EV_TUNE in the
    trace ring with the saturating hop, the fdgui delta's tune
    document — then recovery walks the knobs back to defaults."""
    import numpy as np
    from firedancer_tpu.disco.metrics import LINK_PROD_COUNTERS
    from firedancer_tpu.disco.slo import PressureProbe
    from firedancer_tpu.disco.topo import METRICS_SLOTS
    from firedancer_tpu.gui.schema import DeltaSource
    from firedancer_tpu.trace import export
    from firedancer_tpu.trace.events import EV_TUNE
    plan = _build(
        tune={"enable": True, "interval_s": 0.25, "cooldown_s": 1.0,
              "recovery_s": 2.0, "hysteresis": 0.5, "max_moves": 3,
              "window_s": 4.0, "bp_ref": 100.0},
        controller=True, metric=True,
        trace={"enable": True, "depth": 256},
        slo={"fast_window_s": 2.0,
             "target": [{"name": "bp",
                         "expr": "link.a_b.backpressure rate "
                                 "< 100/s"}]})
    w = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                  create=False)
    try:
        # the controller tile's trace writer (what TileCtx would give
        # the adapter)
        from firedancer_tpu.trace import writer_for
        tw = writer_for(plan, w, "ctl")
        assert tw is not None
        clock = FakeClock()
        c = Controller(plan, w, cfg=plan["tune"], clock=clock,
                       trace=tw, probe=PressureProbe(plan, w))
        # calm baseline poll (seeds the probe's bp counters)
        assert c.poll() == []
        # inject pressure: flip the metric tile's slo_breach gauge and
        # burn backpressure ticks on a_b's producer counters
        moff = plan["tiles"]["metric"]["metrics_off"]
        mview = w.view(moff, METRICS_SLOTS * 8).view(np.uint64)
        names = plan["tiles"]["metric"]["metrics_names"]
        mview[names.index("slo_breach")] = 1
        mview[names.index("slo_breaches")] = 1
        bp_i = LINK_PROD_COUNTERS.index("backpressure")
        lview = w.view(plan["links"]["a_b"]["prod_metrics_off"],
                       len(LINK_PROD_COUNTERS) * 8).view(np.uint64)
        lview[bp_i] = 500
        clock.t = 0.5
        moved = c.poll()
        assert moved and all(d["why"] == "relief" for d in moved)
        assert moved[0]["worst_link"] == "a_b"
        # the mailbox carries the steering for every adapter to read
        assert any(c.mailbox.read(i)[1] > 0
                   for i in range(len(c.names)))
        # EV_TUNE landed in the ring with the saturating hop
        evs = export.read_rings(plan, w, tiles=["ctl"])["ctl"]
        tunes = [e for e in evs if e["etype"] == EV_TUNE]
        assert len(tunes) == len(moved)
        assert tunes[0]["link"] == "a_b"
        knob = plan["tune_knobs"][tunes[0]["count"]]
        assert tunes[0]["arg"] == c.value[knob]
        # the fdgui delta exposes the whole tuning panel
        ds = DeltaSource(plan, w, tps_tile="dst", tps_metric="rx")
        d = ds.delta()
        tu = d["tune"]
        assert tu is not None
        assert [k for k, v in tu["knobs"].items() if v["steered"]]
        assert tu["recent"] and tu["recent"][0]["hop"] == "a_b"
        assert tu["recent"][0]["knob"] in plan["tune_knobs"]
        # recovery: clear the pressure, dwell, revert to defaults
        mview[names.index("slo_breach")] = 0
        sp = knob_space(plan["tune"])
        t = clock.t
        while clock.t < t + 30.0:
            clock.t += 0.25
            c.poll()
        assert c.value == {n: sp[n]["default"] for n in c.names}
        assert c.reverts > 0
        # and the drill's decisions are all in the flight keep-list
        from firedancer_tpu.flight.recorder import _TRACE_KEEP
        assert "tune" in _TRACE_KEEP
    finally:
        w.close()
        Workspace.unlink_name(plan["wksp"]["name"])
