"""Differential tests for the batched ed25519 verify kernel.

Mirrors the reference's test strategy (ref: src/ballet/ed25519/test_ed25519.c,
test_ed25519_signature_malleability.c, fuzz_ed25519_sigverify_diff.c):
self-generated sign/verify vectors from an independent pure-python RFC 8032
implementation, plus malleability / non-canonical-encoding edge cases.
"""
import hashlib

import numpy as np
import jax.numpy as jnp
import pytest

from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import fe25519 as fe

P = (1 << 255) - 19
L = ed.L
D = -121665 * pow(121666, P - 2, P) % P


# --- independent pure-python RFC 8032 reference ----------------------------

def _pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = t1 * (2 * D) % P * t2 % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = (b - a) % P, (dd - c) % P, (dd + c) % P, (b + a) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _pt_mul(k, p):
    q = (0, 1, 1, 0)
    while k:
        if k & 1:
            q = _pt_add(q, p)
        p = _pt_add(p, p)
        k >>= 1
    return q


def _pt_compress(p):
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    x, y = x * zi % P, y * zi % P
    return ((y | ((x & 1) << 255)).to_bytes(32, "little"))


def _pt_decompress(b):
    v = int.from_bytes(b, "little")
    sign, y = v >> 255, v & ((1 << 255) - 1)
    if y >= P:
        return None
    u, vv = (y * y - 1) % P, (D * y * y + 1) % P
    x = u * pow(vv, 3, P) % P * pow(u * pow(vv, 7, P) % P, (P - 5) // 8, P) % P
    if vv * x * x % P == u:
        pass
    elif vv * x * x % P == P - u:
        x = x * pow(2, (P - 1) // 4, P) % P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y, 1, x * y % P)


BX, BY = ed.BASEPOINT
BPT = (BX, BY, 1, BX * BY % P)


def keypair(seed: bytes):
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    pub = _pt_compress(_pt_mul(a, BPT))
    return a, h[32:], pub


def sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix, pub = keypair(seed)
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    rb = _pt_compress(_pt_mul(r, BPT))
    k = int.from_bytes(hashlib.sha512(rb + pub + msg).digest(), "little") % L
    s = (r + k * a) % L
    return rb + s.to_bytes(32, "little")


def ref_verify(sig: bytes, pub: bytes, msg: bytes) -> bool:
    if int.from_bytes(sig[32:], "little") >= L:
        return False
    a = _pt_decompress(pub)
    if a is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    k = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(),
                       "little") % L
    neg_a = (P - a[0], a[1], a[2], P - a[3])
    rp = _pt_add(_pt_mul(s, BPT), _pt_mul(k, neg_a))
    return _pt_compress(rp) == sig[:32]


def _batch(cases, max_len=128):
    """cases: list of (sig, pub, msg) -> device arrays."""
    n = len(cases)
    sig = np.zeros((n, 64), np.uint8)
    pub = np.zeros((n, 32), np.uint8)
    msg = np.zeros((n, max_len), np.uint8)
    ln = np.zeros((n,), np.int32)
    for i, (s, p, m) in enumerate(cases):
        sig[i] = np.frombuffer(s, np.uint8)
        pub[i] = np.frombuffer(p, np.uint8)
        msg[i, :len(m)] = np.frombuffer(m, np.uint8)
        ln[i] = len(m)
    return (jnp.asarray(sig), jnp.asarray(pub), jnp.asarray(msg),
            jnp.asarray(ln))


# --- scalar reduction ------------------------------------------------------

def test_sc_reduce64():
    rng = np.random.default_rng(7)
    b = rng.integers(0, 256, size=(8, 64), dtype=np.uint8)
    got = ed.sc_reduce64(jnp.asarray(b))
    for i in range(8):
        want = int.from_bytes(bytes(b[i]), "little") % L
        have = sum(int(got[i, j]) << (13 * j) for j in range(fe.NLIMB))
        assert have == want


def test_sc_reduce64_edges():
    cases = [0, 1, L - 1, L, L + 1, 2 * L, (1 << 512) - 1,
             (L << 258) + 12345, 1 << 252]
    b = np.zeros((len(cases), 64), np.uint8)
    for i, v in enumerate(cases):
        b[i] = np.frombuffer(v.to_bytes(64, "little"), np.uint8)
    got = ed.sc_reduce64(jnp.asarray(b))
    for i, v in enumerate(cases):
        have = sum(int(got[i, j]) << (13 * j) for j in range(fe.NLIMB))
        assert have == v % L


# --- decompression ---------------------------------------------------------

def test_decompress_roundtrip():
    pts = [_pt_mul(k, BPT) for k in [1, 2, 3, 12345, L - 1]]
    enc = [_pt_compress(p) for p in pts]
    b = jnp.asarray(np.stack([np.frombuffer(e, np.uint8) for e in enc]))
    pt, ok = ed.decompress(b)
    assert bool(ok.all())
    back = np.asarray(ed.pt_tobytes(pt))
    for i, e in enumerate(enc):
        assert bytes(back[i]) == e


def test_decompress_invalid():
    bad = []
    # y >= p (non-canonical)
    bad.append((P + 1).to_bytes(32, "little"))
    # non-square x^2: find y with no valid x
    y = 2
    while _pt_decompress(y.to_bytes(32, "little")) is not None:
        y += 1
    bad.append(y.to_bytes(32, "little"))
    # x = 0 with sign bit set: y = 1 point has x = 0
    bad.append((1 | (1 << 255)).to_bytes(32, "little"))
    b = jnp.asarray(np.stack([np.frombuffer(e, np.uint8) for e in bad]))
    _, ok = ed.decompress(b)
    assert not bool(ok.any())


# --- verify ----------------------------------------------------------------

def test_verify_valid_sigs():
    cases = []
    for i in range(4):
        seed = bytes([i]) * 32
        msg = bytes(range(i * 7 % 256))[: 5 + 17 * i]
        _, _, pub = keypair(seed)
        sig = sign(seed, msg)
        assert ref_verify(sig, pub, msg)
        cases.append((sig, pub, msg))
    out = ed.verify_batch(*_batch(cases))
    assert bool(out.all())


def test_verify_rejects_corruption():
    seed = b"\x05" * 32
    msg = b"firedancer tpu"
    _, _, pub = keypair(seed)
    sig = sign(seed, msg)

    bad_sig = bytes([sig[0] ^ 1]) + sig[1:]
    bad_s = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
    bad_pub = bytes([pub[0] ^ 1]) + pub[1:]
    bad_msg = b"firedancer tpX"
    # S + l: classic malleability — must be rejected even though the curve
    # equation holds (ref: test_ed25519_signature_malleability.c).
    s_val = int.from_bytes(sig[32:], "little")
    mall = sig[:32] + ((s_val + L) % (1 << 256)).to_bytes(32, "little")

    cases = [
        (sig, pub, msg),          # control: valid
        (bad_sig, pub, msg),
        (bad_s, pub, msg),
        (sig, bad_pub, msg),
        (sig, pub, bad_msg),
        (mall, pub, msg),
    ]
    out = np.asarray(ed.verify_batch(*_batch(cases)))
    assert out.tolist() == [True, False, False, False, False, False]
    for (s, p, m), want in zip(cases, out.tolist()):
        assert ref_verify(s, p, m) == want


def test_verify_empty_and_long_msg():
    seed = b"\x09" * 32
    _, _, pub = keypair(seed)
    m0 = b""
    m1 = bytes(x % 251 for x in range(1232))  # txn MTU sized
    cases = [(sign(seed, m0), pub, m0), (sign(seed, m1), pub, m1)]
    out = ed.verify_batch(*_batch(cases, max_len=1232))
    assert bool(out.all())
