"""Differential tests for the batched ed25519 verify kernel.

Mirrors the reference's test strategy (ref: src/ballet/ed25519/test_ed25519.c,
test_ed25519_signature_malleability.c, fuzz_ed25519_sigverify_diff.c):
sign/verify vectors from the independent pure-python RFC 8032 oracle
(firedancer_tpu/utils/ed25519_ref.py — bigint math, no shared code with
the limb kernel), plus malleability / non-canonical-encoding edge cases.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import fe25519 as fe
from firedancer_tpu.utils.ed25519_ref import (
    keypair, sign, verify as ref_verify, pt_mul as _pt_mul,
    pt_compress as _pt_compress, pt_decompress as _pt_decompress,
    BASEPOINT as BPT, P, L)


def _batch(cases, max_len=128):
    """cases: list of (sig, pub, msg) -> device arrays."""
    n = len(cases)
    sig = np.zeros((n, 64), np.uint8)
    pub = np.zeros((n, 32), np.uint8)
    msg = np.zeros((n, max_len), np.uint8)
    ln = np.zeros((n,), np.int32)
    for i, (s, p, m) in enumerate(cases):
        sig[i] = np.frombuffer(s, np.uint8)
        pub[i] = np.frombuffer(p, np.uint8)
        msg[i, :len(m)] = np.frombuffer(m, np.uint8)
        ln[i] = len(m)
    return (jnp.asarray(sig), jnp.asarray(pub), jnp.asarray(msg),
            jnp.asarray(ln))


# --- scalar reduction ------------------------------------------------------

def test_sc_reduce64():
    rng = np.random.default_rng(7)
    b = rng.integers(0, 256, size=(8, 64), dtype=np.uint8)
    got = ed.sc_reduce64(jnp.asarray(b))
    for i in range(8):
        want = int.from_bytes(bytes(b[i]), "little") % L
        have = sum(int(got[i, j]) << (13 * j) for j in range(fe.NLIMB))
        assert have == want


def test_sc_reduce64_edges():
    cases = [0, 1, L - 1, L, L + 1, 2 * L, (1 << 512) - 1,
             (L << 258) + 12345, 1 << 252]
    b = np.zeros((len(cases), 64), np.uint8)
    for i, v in enumerate(cases):
        b[i] = np.frombuffer(v.to_bytes(64, "little"), np.uint8)
    got = ed.sc_reduce64(jnp.asarray(b))
    for i, v in enumerate(cases):
        have = sum(int(got[i, j]) << (13 * j) for j in range(fe.NLIMB))
        assert have == v % L


# --- decompression ---------------------------------------------------------

def test_decompress_roundtrip():
    pts = [_pt_mul(k, BPT) for k in [1, 2, 3, 12345, L - 1]]
    enc = [_pt_compress(p) for p in pts]
    b = jnp.asarray(np.stack([np.frombuffer(e, np.uint8) for e in enc]))
    pt, ok = ed.decompress(b)
    assert bool(ok.all())
    back = np.asarray(ed.pt_tobytes(pt))
    for i, e in enumerate(enc):
        assert bytes(back[i]) == e


def test_decompress_invalid():
    bad = []
    # y >= p (non-canonical)
    bad.append((P + 1).to_bytes(32, "little"))
    # non-square x^2: find y with no valid x
    y = 2
    while _pt_decompress(y.to_bytes(32, "little")) is not None:
        y += 1
    bad.append(y.to_bytes(32, "little"))
    # x = 0 with sign bit set: y = 1 point has x = 0
    bad.append((1 | (1 << 255)).to_bytes(32, "little"))
    b = jnp.asarray(np.stack([np.frombuffer(e, np.uint8) for e in bad]))
    _, ok = ed.decompress(b)
    assert not bool(ok.any())


# --- verify ----------------------------------------------------------------

def test_verify_valid_sigs():
    cases = []
    for i in range(4):
        seed = bytes([i]) * 32
        msg = bytes(range(i * 7 % 256))[: 5 + 17 * i]
        _, _, pub = keypair(seed)
        sig = sign(seed, msg)
        assert ref_verify(sig, pub, msg)
        cases.append((sig, pub, msg))
    out = ed.verify_batch(*_batch(cases))
    assert bool(out.all())


def test_verify_rejects_corruption():
    seed = b"\x05" * 32
    msg = b"firedancer tpu"
    _, _, pub = keypair(seed)
    sig = sign(seed, msg)

    bad_sig = bytes([sig[0] ^ 1]) + sig[1:]
    bad_s = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
    bad_pub = bytes([pub[0] ^ 1]) + pub[1:]
    bad_msg = b"firedancer tpX"
    # S + l: classic malleability — must be rejected even though the curve
    # equation holds (ref: test_ed25519_signature_malleability.c).
    s_val = int.from_bytes(sig[32:], "little")
    mall = sig[:32] + ((s_val + L) % (1 << 256)).to_bytes(32, "little")

    cases = [
        (sig, pub, msg),          # control: valid
        (bad_sig, pub, msg),
        (bad_s, pub, msg),
        (sig, bad_pub, msg),
        (sig, pub, bad_msg),
        (mall, pub, msg),
    ]
    out = np.asarray(ed.verify_batch(*_batch(cases)))
    assert out.tolist() == [True, False, False, False, False, False]
    for (s, p, m), want in zip(cases, out.tolist()):
        assert ref_verify(s, p, m) == want


def test_verify_empty_and_long_msg():
    seed = b"\x09" * 32
    _, _, pub = keypair(seed)
    m0 = b""
    m1 = bytes(x % 251 for x in range(1232))  # txn MTU sized
    cases = [(sign(seed, m0), pub, m0), (sign(seed, m1), pub, m1)]
    out = ed.verify_batch(*_batch(cases, max_len=1232))
    assert bool(out.all())
