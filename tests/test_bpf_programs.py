"""Deployed sBPF programs executing inside transactions: the full
loader->VM->runtime path with the lamports-conservation invariant
(ref: fd_executor -> fd_vm_exec; sum-of-lamports rule of the runtime)."""
import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.programs import (
    ERR_BALANCE_VIOLATION, ERR_VM, OK, BPF_LOADER_ID,
)
from firedancer_tpu.vm import asm


def k(n):
    return bytes([n]) * 32


PAYER, A1, A2, PROG = k(1), k(2), k(3), k(9)

# account record stride in the input blob: 32 pubkey + 8 lamports +
# signer + writable
STRIDE = 42


def mover_prog(amount):
    """Moves `amount` lamports from instruction account 0 to 1."""
    base = 2                 # after u16 n_accounts
    lam0 = base + 32
    lam1 = base + STRIDE + 32
    return asm(f"""
        mov64 r6, r1
        ldxdw r2, [r6+{lam0}]
        ldxdw r3, [r6+{lam1}]
        sub64 r2, {amount}
        add64 r3, {amount}
        stxdw [r6+{lam0}], r2
        stxdw [r6+{lam1}], r3
        mov64 r0, 0
        exit
    """)


def minter_prog(amount):
    base = 2
    lam0 = base + 32
    return asm(f"""
        mov64 r6, r1
        ldxdw r2, [r6+{lam0}]
        add64 r2, {amount}
        stxdw [r6+{lam0}], r2
        mov64 r0, 0
        exit
    """)


@pytest.fixture
def env():
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, PAYER, Account(lamports=1_000_000))
    # A1/A2 are PROGRAM-owned: the ownership rule only lets a program
    # debit accounts it owns (test_ownership_rule covers the refusal)
    funk.rec_write(None, A1, Account(lamports=500, owner=PROG))
    funk.rec_write(None, A2, Account(lamports=50, owner=PROG))
    funk.txn_prepare(None, "blk")
    return funk, db, TxnExecutor(db, enforce_rent=False)


def deploy(funk, code):
    funk.rec_write("blk", PROG, Account(
        lamports=1, data=code, owner=BPF_LOADER_ID, executable=True))


def txn(instrs):
    msg = build_message([PAYER], [A1, A2, PROG], b"\x33" * 32, instrs)
    return build_txn([bytes(64)], msg)


def test_bpf_program_moves_lamports(env):
    funk, db, ex = env
    deploy(funk, mover_prog(100))
    r = ex.execute("blk", txn([(3, bytes([1, 2]), b"")]))
    assert r.status == OK, r
    assert db.lamports("blk", A1) == 400
    assert db.lamports("blk", A2) == 150


def test_bpf_program_cannot_mint(env):
    """The conservation invariant: a program inflating its accounts'
    total lamports fails the transaction."""
    funk, db, ex = env
    deploy(funk, minter_prog(777))
    r = ex.execute("blk", txn([(3, bytes([1, 2]), b"")]))
    assert r.status == ERR_BALANCE_VIOLATION
    assert db.lamports("blk", A1) == 500          # rolled back


def test_bpf_nonzero_return_fails_txn(env):
    funk, db, ex = env
    deploy(funk, asm("mov64 r0, 1; exit"))
    r = ex.execute("blk", txn([(3, bytes([1, 2]), b"")]))
    assert r.status == ERR_VM


def test_bpf_fault_fails_txn(env):
    funk, db, ex = env
    deploy(funk, asm("mov64 r1, 0; ldxdw r0, [r1+0]; exit"))
    r = ex.execute("blk", txn([(3, bytes([1, 2]), b"")]))
    assert r.status == ERR_VM


def test_duplicate_account_indices_cannot_mint(env):
    """An instruction listing the same account at two indices must not
    double-count it in the conservation sum (review-found mint bug):
    slots [A, A, B] with A=500: program writes slot0=0, slot1=500,
    B-slot += 500 — naive before-sum (1000) would pass; unique-account
    accounting must reject it."""
    funk, db, ex = env
    base = 2
    lam = [base + i * STRIDE + 32 for i in range(3)]
    code = asm(f"""
        mov64 r6, r1
        mov64 r2, 0
        stxdw [r6+{lam[0]}], r2
        mov64 r2, 500
        stxdw [r6+{lam[1]}], r2
        ldxdw r3, [r6+{lam[2]}]
        add64 r3, 500
        stxdw [r6+{lam[2]}], r3
        mov64 r0, 0
        exit
    """)
    deploy(funk, code)
    msg = build_message([PAYER], [A1, A2, PROG], b"\x33" * 32,
                        [(3, bytes([1, 1, 2]), b"")])
    r = ex.execute("blk", build_txn([bytes(64)], msg))
    assert r.status == ERR_BALANCE_VIOLATION
    assert db.lamports("blk", A1) == 500
    assert db.lamports("blk", A2) == 50


def test_duplicate_account_indices_consistent_move(env):
    """Duplicates ARE legal when conservation holds over unique
    accounts: [A, A, B] moving 100 A->B with consistent slots."""
    funk, db, ex = env
    base = 2
    lam = [base + i * STRIDE + 32 for i in range(3)]
    code = asm(f"""
        mov64 r6, r1
        ldxdw r2, [r6+{lam[0]}]
        sub64 r2, 100
        stxdw [r6+{lam[0]}], r2
        stxdw [r6+{lam[1]}], r2
        ldxdw r3, [r6+{lam[2]}]
        add64 r3, 100
        stxdw [r6+{lam[2]}], r3
        mov64 r0, 0
        exit
    """)
    deploy(funk, code)
    msg = build_message([PAYER], [A1, A2, PROG], b"\x33" * 32,
                        [(3, bytes([1, 1, 2]), b"")])
    r = ex.execute("blk", build_txn([bytes(64)], msg))
    assert r.status == OK, r
    assert db.lamports("blk", A1) == 400
    assert db.lamports("blk", A2) == 150


def test_ownership_rule_blocks_victim_drain(env):
    """Review-found theft scenario: a program must NOT be able to debit
    a writable account it does not own — txn-level writability (which
    the ATTACKER authors) is not authorization."""
    funk, db, ex = env
    victim = k(7)
    funk.rec_write("blk", victim, Account(lamports=900))  # system-owned
    deploy(funk, mover_prog(100))
    msg = build_message([PAYER], [victim, A2, PROG], b"\x33" * 32,
                        [(3, bytes([1, 2]), b"")])
    r = ex.execute("blk", build_txn([bytes(64)], msg))
    from firedancer_tpu.svm.programs import ERR_INVALID_OWNER
    assert r.status == ERR_INVALID_OWNER
    assert db.lamports("blk", victim) == 900          # untouched


def test_non_executable_account_is_not_a_program(env):
    funk, db, ex = env
    funk.rec_write("blk", PROG, Account(
        lamports=1, data=asm("mov64 r0, 0; exit"),
        owner=BPF_LOADER_ID, executable=False))
    r = ex.execute("blk", txn([(3, bytes([1, 2]), b"")]))
    assert r.status == "unknown_program"
