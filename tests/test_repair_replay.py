"""Repair + replay tile tests: drop-a-block fault injection, repair
request/response over UDP, ordered replay with buffering, and the full
non-leader topology emitting a keyguard-signed vote
(ref: src/discof/repair/fd_repair_tile.c:1-15,
src/discof/replay/fd_replay_tile.c:77-95, src/discof/tower,
src/discof/send)."""
import os
import socket
import struct
import time

import pytest

pytestmark = pytest.mark.slow

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.shred.shred_dest import ClusterNode
from firedancer_tpu.tiles.repair import RepairCore
from firedancer_tpu.tiles.replay import ReplayCore
from firedancer_tpu.tiles.shred import ShredLeaderCore, ShredRecoverCore
from firedancer_tpu.tiles.synth import make_signed_txns, synth_signer_seed
from firedancer_tpu.utils.ed25519_ref import keypair, sign, verify

LEADER_SEED = bytes(range(32))
_, _, LEADER_PUB = keypair(LEADER_SEED)
B_SEED = bytes(range(1, 33))
_, _, B_PUB = keypair(B_SEED)
PEER = b"\x55" * 32


def _wait(fn, timeout_s=540, dt=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if fn():
            return True
        time.sleep(dt)
    return False


class _CaptureRing:
    def __init__(self):
        self.frames = []

    def publish(self, frame, sig=0):
        self.frames.append((bytes(frame), sig))

    def credits(self, fseqs):
        return 1 << 30


def _run_leader_slots(n_slots, drop_slot_every=0, txns_in_slot=None):
    """Drive a leader core over synthetic poh entries for n_slots;
    returns (turbine-sent wires, all wires incl dropped, batches)."""
    from tests.test_shred_tile import _gen_entries

    sent, mirror = [], _CaptureRing()
    batches = _CaptureRing()

    class _Sock:
        def sendto(self, wire, addr):
            sent.append(bytes(wire))

    core = ShredLeaderCore(
        lambda root: sign(LEADER_SEED, root), LEADER_PUB,
        [ClusterNode(PEER, 100, ("127.0.0.1", 9))], _Sock(),
        out_ring=mirror, batch_out=batches,
        drop_slot_every=drop_slot_every)
    state = bytes(32)
    for slot in range(n_slots):
        txns = (txns_in_slot or {}).get(slot, [])
        groups = [txns] if txns else []
        frames, state = _gen_entries(slot, groups, seed=state)
        for f in frames:
            core.on_entry(f)
    return sent, [w for w, _ in mirror.frames], batches.frames


def test_repair_fills_dropped_block_over_udp():
    """Slot 3 is never transmitted; B's forest detects the gap from
    slot 4's parent link, sends signed requests over real UDP, A serves
    from its cache, and B's resolver completes the slot."""
    sent, all_wires, _ = _run_leader_slots(6, drop_slot_every=4)
    dropped_slots = {3}
    assert any(struct.unpack_from("<Q", w, 0x41)[0] == 3
               for w in all_wires)
    assert not any(struct.unpack_from("<Q", w, 0x41)[0] == 3
                   for w in sent)

    # A: serve-only repair tile (cache = leader's own shreds)
    sock_a = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock_a.bind(("127.0.0.1", 0))
    sock_a.setblocking(False)
    a = RepairCore(LEADER_PUB, lambda p: None, sock_a)
    for w in all_wires:
        a.on_shred(w)

    # B: recover + repair client
    sock_b = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock_b.bind(("127.0.0.1", 0))
    sock_b.setblocking(False)
    slices = _CaptureRing()
    repaired = _CaptureRing()
    rec = ShredRecoverCore(LEADER_PUB, slices, None)
    b = RepairCore(B_PUB, lambda p: sign(B_SEED, p), sock_b,
                   peers=[(LEADER_PUB, sock_a.getsockname())],
                   out_ring=repaired)
    for w in sent:
        rec.on_shred(w)
        b.on_shred(w)
    assert rec.metrics["slots_done"] == 5        # all but slot 3
    assert b.metrics["incomplete"] == 0          # not yet planned

    deadline = time.monotonic() + 30
    fed = 0
    while time.monotonic() < deadline:
        # force past the policy dedup window with a fake clock step
        b.plan_and_send(now_ns=time.monotonic_ns() + fed * 10**12)
        time.sleep(0.02)
        a.poll_socket()
        time.sleep(0.02)
        b.poll_socket()
        while fed < len(repaired.frames):
            rec.on_shred(repaired.frames[fed][0])
            fed += 1
        if rec.metrics["slots_done"] == 6:
            break
    assert rec.metrics["slots_done"] == 6
    assert b.metrics["reqs_sent"] >= 1
    assert a.metrics["reqs_served"] >= 1
    got_slots = {struct.unpack_from("<Q", f, 0)[0]
                 for f, _ in slices.frames}
    assert 3 in got_slots
    sock_a.close()
    sock_b.close()


def test_replay_core_executes_and_buffers_out_of_order():
    """Slices arriving out of order buffer until the chain is
    contiguous; txns execute with real balance effects; tower frames
    carry the PoH tip as block id."""
    txns = make_signed_txns(4, seed=6)
    sent, _, batches = _run_leader_slots(
        4, txns_in_slot={1: txns[:2], 2: txns[2:]})
    slices = _CaptureRing()
    rec = ShredRecoverCore(LEADER_PUB, slices, None)
    for w in sent:
        rec.on_shred(w)
    assert rec.metrics["slots_done"] == 4
    frames = [f for f, _ in slices.frames]
    # deliver slot 1's slice LAST: 0, 2, 3 first
    reordered = [frames[0]] + frames[2:] + [frames[1]]

    genesis = {}
    for i in range(16):
        pub = keypair(synth_signer_seed(i))[-1]
        genesis[pub] = 1 << 44
    tower_ring = _CaptureRing()
    core = ReplayCore(out_ring=tower_ring, genesis=genesis,
                      hashes_per_tick=8)
    for f in reordered[:-1]:
        core.on_slice(f)
    assert core.metrics["slots_replayed"] == 1      # only slot 0 ran
    assert core.metrics["buffered"] == 2            # 2 and 3 parked
    core.on_slice(reordered[-1])                    # slot 1 arrives
    assert core.metrics["slots_replayed"] == 4
    assert core.metrics["buffered"] == 0
    assert core.metrics["exec_ok"] == 4
    assert core.metrics["exec_fail"] == 0
    assert core.metrics["poh_fail"] == 0
    # tower frames: one per slot, block id = slot's final PoH hash,
    # parent chain consistent
    assert len(tower_ring.frames) == 4
    ids = {}
    for f, _ in tower_ring.frames:
        slot, parent_slot = struct.unpack_from("<QQ", f, 1)
        ids[slot] = (f[17:49], f[49:81])
    for s in (1, 2, 3):
        assert ids[s][1] == ids[s - 1][0]          # parent_id links
    # balances moved: synth transfers debit sender by amount+fee
    from firedancer_tpu.svm.accdb import SYSTEM_PROGRAM_ID  # noqa: F401
    assert core.funk is not None


def test_replay_rejects_poh_tamper():
    """A flipped byte in a mid-slot entry hash is caught by the batched
    PoH verification."""
    txns = make_signed_txns(2, seed=8)
    sent, _, _ = _run_leader_slots(3, txns_in_slot={1: txns})
    slices = _CaptureRing()
    rec = ShredRecoverCore(LEADER_PUB, slices, None)
    for w in sent:
        rec.on_shred(w)
    frames = [bytearray(f) for f, _ in slices.frames]
    # tamper slot 1's batch: flip one byte in the first entry's hash
    # (offset: slice hdr 13 + num_hashes u32 = 17)
    frames[1][17] ^= 1
    core = ReplayCore(genesis={}, hashes_per_tick=8)
    for f in frames:
        core.on_slice(bytes(f))
    assert core.metrics["poh_fail"] >= 1


# ---------------------------------------------------------------------------
# full non-leader topology: drop-a-block -> repair -> replay -> vote
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_nonleader_repairs_replays_and_votes():
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    vote_rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    vote_rx.bind(("127.0.0.1", 0))
    vote_rx.settimeout(120)
    vote_dest = f"127.0.0.1:{vote_rx.getsockname()[1]}"
    # reserve a port for A's repair tile (B must know it at boot)
    tmp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    tmp.bind(("127.0.0.1", 0))
    a_repair_port = tmp.getsockname()[1]
    tmp.close()

    genesis = {}
    for i in range(16):
        pub = keypair(synth_signer_seed(i))[-1]
        genesis[pub.hex()] = 1 << 44

    # --- B: non-leader ---
    topo_b = (
        Topology(f"rrB{os.getpid()}", wksp_size=1 << 25)
        .link("sock_shred", depth=1024, mtu=1280)
        .link("repair_shreds", depth=256, mtu=1280)
        .link("shred_slices", depth=64, mtu=1 << 16)
        .link("replay_tower", depth=128, mtu=128)
        .link("tower_votes", depth=32, mtu=512)
        .link("repair_req", depth=16, mtu=1280)
        .link("repair_sign_resp", depth=16, mtu=128)
        .link("send_req", depth=16, mtu=1280)
        .link("send_sign_resp", depth=16, mtu=128)
        .tile("sock", "sock", outs=["sock_shred"], port=0, batch=64,
              mtu=1280)
        .tile("repair", "repair",
              ins=["sock_shred", ("repair_sign_resp", False)],
              outs=["repair_req", "repair_shreds"],
              identity_hex=B_PUB.hex(),
              peers=[{"pubkey_hex": LEADER_PUB.hex(),
                      "addr": f"127.0.0.1:{a_repair_port}"}],
              req="repair_req", resp="repair_sign_resp")
        .tile("shred", "shred", ins=["sock_shred", "repair_shreds"],
              outs=["shred_slices"], mode="recover",
              leader_pubkey_hex=LEADER_PUB.hex())
        .tile("replay", "replay", ins=["shred_slices"],
              outs=["replay_tower"], genesis=genesis,
              hashes_per_tick=16)
        .tile("tower", "tower", ins=["replay_tower"],
              outs=["tower_votes"], total_stake=100)
        .tile("send", "send",
              ins=["tower_votes", ("send_sign_resp", False)],
              outs=["send_req"], identity_hex=B_PUB.hex(),
              vote_account_hex=(b"\x42" * 32).hex(), dest=vote_dest,
              req="send_req", resp="send_sign_resp")
        .tile("sign", "sign",
              ins=[("repair_req", False), ("send_req", False)],
              outs=["repair_sign_resp", "send_sign_resp"],
              seed=B_SEED.hex(),
              clients=[{"role": "repair", "req": "repair_req",
                        "resp": "repair_sign_resp"},
                       {"role": "send", "req": "send_req",
                        "resp": "send_sign_resp"}])
    )
    plan_b = topo_b.build()
    runner_b = TopologyRunner(plan_b).start()
    try:
        runner_b.wait_running(timeout_s=540)
        assert _wait(lambda: runner_b.metrics("sock")["port"] != 0,
                     timeout_s=30)
        port_b = int(runner_b.metrics("sock")["port"])

        # --- A: leader, dropping every 4th slot from turbine ---
        cluster = [{"pubkey_hex": PEER.hex(), "stake": 100,
                    "addr": f"127.0.0.1:{port_b}"}]
        topo_a = (
            Topology(f"rrA{os.getpid()}", wksp_size=1 << 25)
            .link("synth_verify", depth=128, mtu=1280)
            .link("verify_dedup", depth=128, mtu=1280)
            .link("dedup_pack", depth=128, mtu=1280)
            .link("pack_bank0", depth=32, mtu=1 << 14)
            .link("bank0_done", depth=32, mtu=64)
            .link("bank0_poh", depth=64, mtu=(1 << 14) + 22)
            .link("poh_entries", depth=256, mtu=(1 << 14) + 256)
            .link("poh_slots", depth=64, mtu=64)
            .link("shreds_mirror", depth=1024, mtu=1280)
            .link("shred_req", depth=16, mtu=1280)
            .link("sign_resp", depth=16, mtu=128)
            .tcache("verify_tc", depth=4096)
            .tcache("dedup_tc", depth=4096)
            .tile("synth", "synth", outs=["synth_verify"], count=24,
                  unique=24, seed=6)
            .tile("verify", "verify", ins=["synth_verify"],
                  outs=["verify_dedup"], batch=16, tcache="verify_tc")
            .tile("dedup", "dedup", ins=["verify_dedup"],
                  outs=["dedup_pack"], tcache="dedup_tc")
            .tile("pack", "pack", ins=["dedup_pack", "bank0_done",
                                       "poh_slots"],
                  outs=["pack_bank0"], txn_in="dedup_pack",
                  bank_links=["pack_bank0"], done_links=["bank0_done"],
                  slot_in="poh_slots", max_txn_per_microblock=8)
            .tile("bank0", "bank", ins=["pack_bank0"],
                  outs=["bank0_done", "bank0_poh"], exec="svm",
                  poh_link="bank0_poh", genesis=genesis,
                  forward_payloads=True)
            .tile("poh", "poh", ins=["bank0_poh"],
                  outs=["poh_entries", "poh_slots"],
                  slot_link="poh_slots", hashes_per_tick=16,
                  ticks_per_slot=4)
            .tile("shred", "shred",
                  ins=["poh_entries", ("sign_resp", False)],
                  outs=["shred_req", "shreds_mirror"], mode="leader",
                  identity_hex=LEADER_PUB.hex(), cluster=cluster,
                  req="shred_req", resp="sign_resp",
                  shreds_link="shreds_mirror", drop_slot_every=4)
            .tile("arepair", "repair", ins=["shreds_mirror"], outs=[],
                  identity_hex=LEADER_PUB.hex(), port=a_repair_port)
            .tile("sign", "sign", ins=[("shred_req", False)],
                  outs=["sign_resp"], seed=LEADER_SEED.hex(),
                  clients=[{"role": "leader", "req": "shred_req",
                            "resp": "sign_resp"}])
        )
        plan_a = topo_a.build()
        runner_a = TopologyRunner(plan_a).start()
        try:
            runner_a.wait_running(timeout_s=540)
            # leader drops whole slots from turbine...
            assert _wait(
                lambda: runner_a.metrics("shred")["dropped"] > 0,
                timeout_s=300)
            # ...B notices the gaps and repairs them from A
            assert _wait(
                lambda: runner_b.metrics("repair")["reqs_sent"] >= 1,
                timeout_s=120)
            assert _wait(
                lambda: runner_a.metrics("arepair")["reqs_served"] >= 1,
                timeout_s=120)
            assert _wait(
                lambda: runner_b.metrics("repair")["resps_in"] >= 1,
                timeout_s=120)
            # replay crosses at least two dropped slots (8+ contiguous)
            assert _wait(
                lambda: runner_b.metrics("replay")["slots_replayed"] >= 8,
                timeout_s=300)
            assert runner_b.metrics("replay")["poh_fail"] == 0
            # tower votes and the send tile egresses a SIGNED vote txn
            assert _wait(
                lambda: runner_b.metrics("tower")["votes_out"] >= 1,
                timeout_s=120)
            data, _ = vote_rx.recvfrom(2048)
            from firedancer_tpu.protocol.txn import parse_txn
            t = parse_txn(data)
            keys = t.account_keys(data)
            assert keys[0] == B_PUB
            assert verify(t.signatures(data)[0], B_PUB, t.message(data))
            assert runner_b.metrics("send")["sign_fail"] == 0
        finally:
            runner_a.halt()
            runner_a.close()
    finally:
        runner_b.halt()
        runner_b.close()
        vote_rx.close()
