"""Leader-loop batching byte-exactness (r13).

The wave discipline (pack microblock waves, bank device-wave
execution, batched PoH mixin, batched entry/slot/mirror publishes)
must be a pure THROUGHPUT change: every frame on every ring is
byte-identical to what the per-frag path produced. These suites pin
that down component by component with sequential oracles, plus the
scheduler's multi-outstanding (wave) conflict invariant and the synth
ramp schedule's token integral.
"""
import hashlib
import os
import struct
from types import SimpleNamespace

import numpy as np
import pytest

from firedancer_tpu.ops.poh import (host_poh_append, host_poh_mixin,
                                    host_poh_mixin_chain)
from firedancer_tpu.runtime import Fseq, Ring, Workspace

pytestmark = pytest.mark.leader


@pytest.fixture(scope="module")
def wksp():
    w = Workspace(f"/fdtpu_lb_{os.getpid()}", 1 << 24)
    yield w
    w.close()
    w.unlink()


def _drain(ring, seq=0):
    out = []
    while True:
        rc, frag = ring.consume(seq)
        if rc != 0:
            break
        out.append((bytes(ring.payload(frag)), frag.sig))
        seq += 1
    return out, seq


# ---------------------------------------------------------------------------
# PoH: batched mixin chain + tile-level frame oracle
# ---------------------------------------------------------------------------

def test_poh_mixin_chain_matches_sequential_fold():
    state = bytes(range(32))
    mixins = [hashlib.sha256(b"m%d" % i).digest() for i in range(37)]
    got = host_poh_mixin_chain(state, mixins)
    want, s = [], state
    for m in mixins:
        s = host_poh_mixin(s, m)
        want.append(s)
    assert got == want
    assert host_poh_mixin_chain(state, []) == []


def _poh_oracle(frames_in, hpt, tps, seed=bytes(32)):
    """The r12 per-record sequential PoH walk: returns the exact entry
    frames + slot frags the old tile published for this input."""
    state = seed
    slot = tick_in_slot = hashes_in_tick = 0
    entries, slots = [], []

    def emit(num_hashes, prev, mixin, blob=b"", cnt=0, slot_done=False):
        f = struct.pack("<QII B", slot, tick_in_slot, num_hashes,
                        1 if mixin else 0)
        f += prev + state + (mixin or bytes(32))
        f += bytes([1 if slot_done else 0]) + struct.pack("<H", cnt) \
            + blob
        entries.append(f)

    def tick():
        nonlocal state, hashes_in_tick, tick_in_slot, slot
        remaining = hpt - hashes_in_tick
        prev = state
        state = host_poh_append(prev, remaining)
        emit(remaining, prev, None,
             slot_done=tick_in_slot + 1 >= tps)
        hashes_in_tick = 0
        tick_in_slot += 1
        if tick_in_slot >= tps:
            slots.append(slot)
            slot += 1
            tick_in_slot = 0

    for mixin, cnt, blob in frames_in:
        if hashes_in_tick + 1 >= hpt:
            tick()
        prev = state
        state = host_poh_mixin(prev, mixin)
        hashes_in_tick += 1
        emit(1, prev, mixin, blob=blob, cnt=cnt if blob else 0)
    return entries, slots


def _mk_poh(wksp, hpt=4, tps=2, in_depth=64):
    """PohAdapter over real rings with a minimal fake ctx."""
    from firedancer_tpu.disco.tiles import PohAdapter
    in_ring = Ring.create(wksp, depth=in_depth, mtu=256)
    entry_ring = Ring.create(wksp, depth=256, mtu=512)
    slot_ring = Ring.create(wksp, depth=64, mtu=64)
    plan = {"links": {"in": {"mtu": 256}, "entries": {"mtu": 512},
                      "slots": {"mtu": 64}}}
    ctx = SimpleNamespace(
        tile_name="poh", plan=plan,
        in_rings={"in": in_ring},
        out_rings={"entries": entry_ring, "slots": slot_ring},
        out_fseqs={"entries": [], "slots": []},
        in_seqs0=lambda: {"in": 0})
    tile = PohAdapter(ctx, {"hashes_per_tick": hpt,
                            "ticks_per_slot": tps,
                            "slot_link": "slots"})
    return tile, in_ring, entry_ring, slot_ring


def test_poh_wave_frames_byte_identical_to_sequential(wksp):
    """Drive the batched PoH tile with uneven waves of bank frames
    (runs crossing tick boundaries) and compare every entry frame and
    slot frag against the sequential oracle, byte for byte."""
    tile, in_ring, entry_ring, slot_ring = _mk_poh(wksp, hpt=4, tps=2)
    frames_in = []
    for i in range(11):
        mixin = hashlib.sha256(b"mb-%d" % i).digest()
        blob = (b"\x05\x00" + bytes([i]) * 5) if i % 3 else b""
        frames_in.append((mixin, 1 if blob else 0, blob))
    # publish in uneven bursts so poll-time waves split across runs
    sent = 0
    for burst in (1, 4, 6):
        for mixin, cnt, blob in frames_in[sent:sent + burst]:
            in_ring.publish(struct.pack("<QH", sent, cnt) + mixin
                            + blob, sig=sent)
            sent += 1
        tile.poll_once()
    tile.poll_once()          # idle flush (nothing pending expected)
    want_entries, want_slots = _poh_oracle(frames_in, hpt=4, tps=2)
    got_entries, _ = _drain(entry_ring)
    got_slots, _ = _drain(slot_ring)
    assert [f for f, _ in got_entries] == want_entries
    assert [sig for _, sig in got_entries] == list(range(len(
        want_entries)))
    assert [struct.unpack("<Q", f)[0] for f, _ in got_slots] \
        == want_slots
    assert tile.m["mixins"] == len(frames_in)


def test_poh_wave_backpressure_resumes_exact(wksp):
    """A reliable consumer smaller than the wave: the batched entry
    publish stalls mid-wave and resumes from the stop row with no
    frame lost, reordered, or altered."""
    from firedancer_tpu.disco.tiles import PohAdapter
    in_ring = Ring.create(wksp, depth=64, mtu=256)
    entry_ring = Ring.create(wksp, depth=8, mtu=512)   # tiny window
    fs = Fseq(wksp)
    plan = {"links": {"in": {"mtu": 256}, "entries": {"mtu": 512}}}
    ctx = SimpleNamespace(
        tile_name="poh", plan=plan, in_rings={"in": in_ring},
        out_rings={"entries": entry_ring},
        out_fseqs={"entries": [fs]},
        in_seqs0=lambda: {"in": 0})
    tile = PohAdapter(ctx, {"hashes_per_tick": 64,
                            "ticks_per_slot": 8})
    frames_in = []
    for i in range(12):
        mixin = hashlib.sha256(b"bp-%d" % i).digest()
        in_ring.publish(struct.pack("<QH", i, 0) + mixin, sig=i)
        frames_in.append((mixin, 0, b""))

    import threading
    got = []

    def consumer():
        seq = 0
        import time
        deadline = time.monotonic() + 30
        while len(got) < 12 and time.monotonic() < deadline:
            rc, frag = entry_ring.consume(seq)
            if rc != 0:
                time.sleep(0.002)
                continue
            got.append(bytes(entry_ring.payload(frag)))
            seq += 1
            fs.update(seq)
            time.sleep(0.001)     # keep the window tight

    t = threading.Thread(target=consumer)
    t.start()
    tile.poll_once()
    t.join(timeout=30)
    want_entries, _ = _poh_oracle(frames_in, hpt=64, tps=8)
    assert got == want_entries
    assert tile.m["backpressure"] >= 1


# ---------------------------------------------------------------------------
# Pack: wave scheduling + batched bank-link publish
# ---------------------------------------------------------------------------

def _mk_pack(wksp, wave=4, banks=1):
    from firedancer_tpu.disco.tiles import PackAdapter
    txn_ring = Ring.create(wksp, depth=256, mtu=1280)
    bank_rings = [Ring.create(wksp, depth=64, mtu=16384)
                  for _ in range(banks)]
    done_rings = [Ring.create(wksp, depth=64, mtu=64)
                  for _ in range(banks)]
    links = {"txns": {"mtu": 1280}}
    in_rings = {"txns": txn_ring}
    out_rings, out_fseqs = {}, {}
    done_names = []
    for b in range(banks):
        links[f"bank{b}"] = {"mtu": 16384}
        links[f"done{b}"] = {"mtu": 64}
        out_rings[f"bank{b}"] = bank_rings[b]
        out_fseqs[f"bank{b}"] = []
        in_rings[f"done{b}"] = done_rings[b]
        done_names.append(f"done{b}")
    ctx = SimpleNamespace(
        tile_name="pack", plan={"links": links}, in_rings=in_rings,
        out_rings=out_rings, out_fseqs=out_fseqs,
        in_seqs0=lambda: {ln: 0 for ln in in_rings})
    tile = PackAdapter(ctx, {
        "txn_in": "txns",
        "bank_links": [f"bank{b}" for b in range(banks)],
        "done_links": done_names,
        "max_txn_per_microblock": 4, "wave": wave, "slot_ms": 1e9})
    return tile, txn_ring, bank_rings, done_rings


def test_pack_wave_frames_byte_identical(wksp):
    """One poll emits a WAVE of microblocks through publish_batch;
    every frame on the ring is byte-identical to the per-frag
    serializer's output for that microblock (recorded via the same
    _serialize the old per-microblock publish shipped verbatim)."""
    from firedancer_tpu.tiles.synth import make_signed_txns
    tile, txn_ring, bank_rings, done_rings = _mk_pack(wksp, wave=4)
    recorded = {}
    real = tile._serialize

    def record(bank, mb_id, metas):
        f = real(bank, mb_id, metas)
        recorded[mb_id] = f
        return f

    tile._serialize = record
    txns = make_signed_txns(16, seed=21)
    for i, t in enumerate(txns):
        txn_ring.publish(t, sig=i)
    tile.poll_once()
    frames, _ = _drain(bank_rings[0])
    # synth txns share 16 signer keys -> conflicts bound microblock
    # fill, but the wave cap (4) bounds the poll's emission
    assert 1 <= len(frames) <= 4
    assert tile.m["microblocks"] == len(frames)
    for frame, sig in frames:
        assert frame == recorded[sig]
    # wire-format roundtrip: every payload is one of the inserted txns
    seen = []
    for frame, _ in frames:
        bank, cnt, mb_id, slot = struct.unpack_from("<HHQQ", frame, 0)
        assert bank == 0
        off = 20
        for _ in range(cnt):
            (ln,) = struct.unpack_from("<H", frame, off)
            off += 2
            seen.append(frame[off:off + ln])
            off += ln
        assert off == len(frame)
    assert set(seen) <= set(txns) and len(seen) == len(set(seen))
    # completions retire the wave FIFO and free the budget
    q0 = list(tile.busy[0])
    for mb_id in q0:
        done_rings[0].publish(struct.pack("<Q", mb_id), sig=mb_id)
    tile.poll_once()
    assert tile.m["completions"] == len(q0)
    assert not tile.busy[0]


def test_pack_wave_respects_credit_window(wksp):
    """The wave is bounded by the bank link's credit window: with a
    reliable consumer that never advances, only `credits` microblocks
    are scheduled and published — the batched publish cannot stall
    mid-wave against a live consumer."""
    from firedancer_tpu.disco.tiles import PackAdapter
    from firedancer_tpu.tiles.synth import make_signed_txns
    txn_ring = Ring.create(wksp, depth=256, mtu=1280)
    bank_ring = Ring.create(wksp, depth=2, mtu=16384)  # 2 credits
    done_ring = Ring.create(wksp, depth=64, mtu=64)
    fs = Fseq(wksp)
    ctx = SimpleNamespace(
        tile_name="pack",
        plan={"links": {"txns": {"mtu": 1280},
                        "bank0": {"mtu": 16384},
                        "done0": {"mtu": 64}}},
        in_rings={"txns": txn_ring, "done0": done_ring},
        out_rings={"bank0": bank_ring},
        out_fseqs={"bank0": [fs]},
        in_seqs0=lambda: {"txns": 0, "done0": 0})
    tile = PackAdapter(ctx, {
        "txn_in": "txns", "bank_links": ["bank0"],
        "done_links": ["done0"], "max_txn_per_microblock": 1,
        "wave": 8, "slot_ms": 1e9})
    for i, t in enumerate(make_signed_txns(8, seed=23)):
        txn_ring.publish(t, sig=i)
    tile.poll_once()
    assert len(tile.busy[0]) == 2       # depth-capped, not wave-capped
    assert bank_ring.seq == 2


def test_pack_scheduler_multi_outstanding_no_cross_bank_conflict():
    """Wave discipline invariant: with several microblocks outstanding
    per bank, no txn in flight on bank A writes an account any txn in
    flight on bank B touches (brute force on the raw account sets,
    never trusting the bitsets)."""
    import random

    from firedancer_tpu.pack import PackScheduler, TxnMeta
    rng = random.Random(7)
    s = PackScheduler(bank_cnt=2)
    for i in range(64):
        accts = rng.sample(range(24), k=3)
        s.insert(TxnMeta(
            payload=bytes([i]), txn=None, reward=rng.randint(1, 9999),
            cost=10_000,
            writes=tuple(bytes([a]) * 32 for a in accts[:2]),
            reads=(bytes([accts[2]]) * 32,)))
    for _ in range(20):
        bank = rng.randrange(2)
        if s.outstanding_cnt(bank) < 4:
            s.schedule_microblock(bank)
        elif s.outstanding_cnt(bank):
            s.microblock_done(bank)
        a, b = s.outstanding(0), s.outstanding(1)
        for ma in a:
            for mb in b:
                aw, ar = set(ma.writes), set(ma.reads)
                bw, br = set(mb.writes), set(mb.reads)
                assert not (aw & bw) and not (aw & br) \
                    and not (ar & bw)


# ---------------------------------------------------------------------------
# Bank: device-wave execution == per-microblock == serial oracle
# ---------------------------------------------------------------------------

def test_bank_wave_execution_matches_serial_oracle():
    """Concatenating a wave of microblocks into ONE staged dispatch is
    bit-identical to executing the microblocks one block at a time,
    and both match the serial host oracle."""
    import random

    from firedancer_tpu.funk.funk import Funk
    from firedancer_tpu.svm.executor import (SystemTxn, WaveExecutor,
                                             execute_block,
                                             execute_block_serial)
    rng = random.Random(11)
    keys = [hashlib.sha256(b"k%d" % i).digest() for i in range(12)]
    bal0 = {k: rng.randint(0, 50_000) for k in keys}
    txns = [SystemTxn(src=rng.choice(keys), dst=rng.choice(keys),
                      amount=rng.randint(0, 20_000),
                      fee=rng.choice((0, 10, 5_000)))
            for _ in range(28)]
    microblocks = [txns[i:i + 7] for i in range(0, len(txns), 7)]

    def fresh_funk():
        f = Funk()
        for k, v in bal0.items():
            f.rec_write(None, k, v)
        return f

    # (a) serial oracle
    oracle = dict(bal0)
    want_st = execute_block_serial(oracle, txns)
    # (b) one execute_block per microblock
    f_seq = fresh_funk()
    st_seq = []
    for i, mb in enumerate(microblocks):
        st_seq.extend(execute_block(f_seq, None, f"mb{i}", mb))
        f_seq.txn_publish(f"mb{i}")
    # (c) the wave path: stage -> dispatch -> finalize, pipelined the
    # way the bank tile drives it (stage k+1 before finalize k)
    f_wave = fresh_funk()
    wx = WaveExecutor()
    pending = None
    st_wave = []
    waves = [sum(microblocks[i:i + 2], [])
             for i in range(0, len(microblocks), 2)]
    for wi, wave in enumerate(waves):
        staged = wx.stage(wave)
        if pending is not None:
            st_wave.extend(wx.finalize(f_wave, pending))
            f_wave.txn_publish(pending.xid)
        pending = wx.dispatch(f_wave, None, f"w{wi}", staged)
    st_wave.extend(wx.finalize(f_wave, pending))
    f_wave.txn_publish(pending.xid)

    assert st_seq == want_st
    assert st_wave == want_st
    for k in keys:
        assert f_seq.rec_query(None, k) == oracle.get(k, 0) \
            or (oracle.get(k, 0) == 0
                and f_seq.rec_query(None, k) in (0, None))
        assert f_wave.rec_query(None, k) == f_seq.rec_query(None, k)


def test_bank_wave_padding_buckets_are_inert():
    """Padded wave/lane/account slots (the power-of-two jit buckets)
    never touch live balances: a 1-txn wave and a bucket-boundary wave
    both match the oracle exactly."""
    from firedancer_tpu.funk.funk import Funk
    from firedancer_tpu.svm.executor import (SystemTxn, execute_block,
                                             execute_block_serial)
    a, b = b"\xaa" * 32, b"\xbb" * 32
    for n in (1, 5, 17):          # crosses the pow2 bucket boundaries
        txns = [SystemTxn(src=a, dst=b, amount=10, fee=1)
                for _ in range(n)]
        funk = Funk()
        funk.rec_write(None, a, 1_000_000)
        oracle = {a: 1_000_000}
        want = execute_block_serial(oracle, txns)
        got = execute_block(funk, None, "x", txns)
        funk.txn_publish("x")
        assert got == want
        assert funk.rec_query(None, a) == oracle[a]
        assert funk.rec_query(None, b) == oracle[b]


# ---------------------------------------------------------------------------
# Shred: batched mirror egress
# ---------------------------------------------------------------------------

def test_shred_mirror_batch_byte_identical(wksp):
    """The leader core's buffered mirror egress publishes exactly the
    wires (and sigs) the per-shred path published, in order."""
    from firedancer_tpu.shred.shred_dest import ClusterNode
    from firedancer_tpu.tiles.shred import ShredLeaderCore
    from firedancer_tpu.tiles.synth import make_signed_txns
    from firedancer_tpu.utils.ed25519_ref import keypair, sign
    from tests.test_shred_tile import _gen_entries
    seed = bytes(range(32))
    _, _, pub = keypair(seed)
    sent = []

    class _Sock:
        def sendto(self, wire, addr):
            sent.append(bytes(wire))

    mirror = Ring.create(wksp, depth=256, mtu=1280)
    core = ShredLeaderCore(
        lambda root: sign(seed, root), pub,
        [ClusterNode(b"\x55" * 32, 100, ("127.0.0.1", 9))], _Sock(),
        out_ring=mirror, out_fseqs=[])
    txns = make_signed_txns(4, seed=31)
    frames, _ = _gen_entries(5, [txns[:2], txns[2:]])
    for f in frames:
        core.on_entry(f)
    assert core._egress and not mirror.seq     # buffered, not shipped
    n = core.flush_egress()
    got, _ = _drain(mirror)
    assert n == len(got) == len(sent) > 0
    assert [w for w, _ in got] == sent         # byte-identical, in order
    for wire, sig in got:
        idx, = struct.unpack_from("<I", wire, 0x49)
        assert sig == idx
    assert core.flush_egress() == 0            # drained


# ---------------------------------------------------------------------------
# verify_tile_cnt >= 2: rr-sharded topology expansion + live loop
# ---------------------------------------------------------------------------

def test_sharded_tile_expansion():
    """Builder + config expansion: N shards share the ins, own one out
    link each, carry rr_cnt/rr_idx, distribute list args, and pin
    cpu0+i."""
    from firedancer_tpu.disco import Topology
    topo = (
        Topology("shardx")
        .link("ingest", depth=64).link("vd0", depth=64)
        .link("vd1", depth=64).link("out", depth=64)
        .tcache("tc0").tcache("tc1").tcache("dtc")
        .tile("synth", "synth", outs=["ingest"], count=4)
        .sharded_tile("verify", "verify", 2, ins=["ingest"],
                      outs=["vd0", "vd1"], cpu0=3, batch=16,
                      tcache=["tc0", "tc1"])
        .tile("dedup", "dedup", ins=["vd0", "vd1"], outs=["out"],
              tcache="dtc")
        .tile("sink", "sink", ins=["out"]))
    for i in range(2):
        t = topo.tiles[f"verify{i}"]
        assert t.args["rr_cnt"] == 2 and t.args["rr_idx"] == i
        assert t.args["cpu_idx"] == 3 + i
        assert t.args["tcache"] == f"tc{i}"
        assert t.outs == [f"vd{i}"]
        assert [i_["link"] for i_ in t.ins] == ["ingest"]
    # config-side: tile_cnt on a [[tile]] stanza expands identically
    from firedancer_tpu.app.config import build_topology
    cfg = {
        "link": [{"name": "ingest", "depth": 64},
                 {"name": "vd0", "depth": 64},
                 {"name": "vd1", "depth": 64},
                 {"name": "out", "depth": 64}],
        "tcache": [{"name": "tc0"}, {"name": "tc1"},
                   {"name": "dtc"}],
        "tile": [
            {"name": "synth", "kind": "synth", "outs": ["ingest"],
             "count": 4},
            {"name": "verify", "kind": "verify", "tile_cnt": 2,
             "ins": ["ingest"], "outs": ["vd0", "vd1"],
             "batch": 16, "tcache": ["tc0", "tc1"], "cpu0": 1},
            {"name": "dedup", "kind": "dedup",
             "ins": ["vd0", "vd1"], "outs": ["out"], "tcache": "dtc"},
            {"name": "sink", "kind": "sink", "ins": ["out"]},
        ],
    }
    topo2 = build_topology(cfg, name="shardy")
    assert set(topo2.tiles) == {"synth", "verify0", "verify1",
                                "dedup", "sink"}
    assert topo2.tiles["verify1"].args["rr_idx"] == 1
    assert topo2.tiles["verify1"].args["tcache"] == "tc1"
    # and the static pass accepts the sharded model (incl. the
    # list-valued tcache arg)
    from firedancer_tpu.lint.graph import lint_config, lint_topology
    assert not [f for f in lint_topology(topo2) if f.level == "error"]
    assert not [f for f in lint_config(cfg, "<cfg>")
                if f.level == "error"]


@pytest.mark.slow
def test_leader_loop_with_two_verify_tiles():
    """Conformance with verify_tile_cnt=2: the full leader loop
    (synth -> verify x2 rr-sharded -> dedup -> pack -> bank(svm waves)
    -> poh) executes every funded transfer exactly once, mixes every
    microblock into a chain that re-verifies, and both shards carry
    traffic — dedup stays the cross-shard convergence point."""
    import time

    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.tiles.synth import synth_signer_seed
    from firedancer_tpu.utils.ed25519_ref import keypair
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    n = 24
    genesis = {keypair(synth_signer_seed(i))[-1].hex(): 1 << 44
               for i in range(16)}
    topo = (
        Topology(f"l2v{os.getpid()}", wksp_size=1 << 25)
        .link("ingest", depth=128, mtu=1280)
        .link("vd0", depth=128, mtu=1280)
        .link("vd1", depth=128, mtu=1280)
        .link("dedup_pack", depth=128, mtu=1280)
        .link("pack_bank0", depth=32, mtu=1 << 15)
        .link("bank0_done", depth=32, mtu=64)
        .link("bank0_poh", depth=64, mtu=64)
        .link("poh_entries", depth=2048, mtu=256)
        .link("poh_slots", depth=64, mtu=64)
        .tcache("vtc0", depth=4096).tcache("vtc1", depth=4096)
        .tcache("dedup_tc", depth=4096)
        .tile("synth", "synth", outs=["ingest"], count=n, unique=n,
              seed=6)
        .sharded_tile("verify", "verify", 2, ins=["ingest"],
                      outs=["vd0", "vd1"], batch=16,
                      tcache=["vtc0", "vtc1"])
        .tile("dedup", "dedup", ins=["vd0", "vd1"],
              outs=["dedup_pack"], tcache="dedup_tc")
        .tile("pack", "pack",
              ins=["dedup_pack", "bank0_done", "poh_slots"],
              outs=["pack_bank0"], txn_in="dedup_pack",
              bank_links=["pack_bank0"], done_links=["bank0_done"],
              slot_in="poh_slots", max_txn_per_microblock=8, wave=4)
        .tile("bank0", "bank", ins=["pack_bank0"],
              outs=["bank0_done", "bank0_poh"], exec="svm", wave=4,
              poh_link="bank0_poh", genesis=genesis)
        .tile("poh", "poh", ins=["bank0_poh"],
              outs=["poh_entries", "poh_slots"],
              slot_link="poh_slots", hashes_per_tick=16,
              ticks_per_slot=4)
        .tile("entsink", "sink", ins=["poh_entries"]))
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            runner.check_failures()
            if runner.metrics("bank0")["transfers"] == n and \
                    runner.metrics("poh")["mixins"] \
                    == runner.metrics("bank0")["microblocks"]:
                break
            time.sleep(0.05)
        b = runner.metrics("bank0")
        assert b["transfers"] == n and b["exec_fail"] == 0
        v0, v1 = (runner.metrics(f"verify{i}") for i in (0, 1))
        # disjoint rr ownership covers every frag exactly once
        assert v0["rx"] + v1["rx"] == n
        assert v0["rx"] > 0 and v1["rx"] > 0
        assert v0["verify_fail"] == v1["verify_fail"] == 0
        assert runner.metrics("dedup")["tx"] == n
        assert runner.metrics("poh")["mixins"] == b["microblocks"]
    finally:
        runner.halt()
        runner.close()


# ---------------------------------------------------------------------------
# Synth: ramp schedule token integral
# ---------------------------------------------------------------------------

def test_synth_ramp_earned_integral():
    from firedancer_tpu.disco.tiles import SynthAdapter
    sa = SynthAdapter.__new__(SynthAdapter)
    sa.ramp = None
    sa.rate_tps = 100.0
    assert sa._earned(0.5) == 50
    sa.ramp = [(1.0, 100.0), (2.0, 50.0)]
    assert sa._earned(0.5) == 50
    assert sa._earned(1.0) == 100
    assert sa._earned(2.0) == 150
    assert sa._earned(3.0) == 200
    # past the schedule: the LAST stanza's rate holds
    assert sa._earned(5.0) == 300
