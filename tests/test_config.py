"""Layered TOML config tests: merge semantics + an e2e topology launched
from config files (the fdctl config-stack analog,
ref: src/app/fdctl/config/default.toml, src/app/shared/fd_config.h)."""
import os
import textwrap

import pytest

from firedancer_tpu.app.config import build_topology, load_config
from firedancer_tpu.disco.launch import TopologyRunner

BASE = """
[topology]
wksp_size = 16777216

[[link]]
name = "synth_verify"
depth = 64
mtu = 1280

[[link]]
name = "verify_sink"
depth = 64
mtu = 1280

[[tcache]]
name = "verify_tc"
depth = 4096

[[tile]]
name = "synth"
kind = "synth"
outs = ["synth_verify"]
count = 16
unique = 16
seed = 9

[[tile]]
name = "verify"
kind = "verify"
ins = ["synth_verify"]
outs = ["verify_sink"]
batch = 16
tcache = "verify_tc"

[[tile]]
name = "sink"
kind = "sink"
ins = ["verify_sink"]
"""

OVERRIDE = """
[[link]]
name = "synth_verify"
depth = 128

[[tile]]
name = "synth"
count = 24
unique = 24
"""


@pytest.fixture()
def cfgdir(tmp_path):
    (tmp_path / "base.toml").write_text(textwrap.dedent(BASE))
    (tmp_path / "override.toml").write_text(textwrap.dedent(OVERRIDE))
    return tmp_path


def test_layer_merge_semantics(cfgdir):
    cfg = load_config(cfgdir / "base.toml", cfgdir / "override.toml")
    links = {e["name"]: e for e in cfg["link"]}
    assert links["synth_verify"]["depth"] == 128      # overridden
    assert links["synth_verify"]["mtu"] == 1280       # inherited
    assert links["verify_sink"]["depth"] == 64        # untouched
    tiles = {e["name"]: e for e in cfg["tile"]}
    assert tiles["synth"]["count"] == 24
    assert tiles["synth"]["unique"] == 24
    assert tiles["synth"]["seed"] == 9


def test_unknown_section_rejected(tmp_path):
    p = tmp_path / "bad.toml"
    p.write_text("[nonsense]\nx = 1\n")
    with pytest.raises(ValueError, match="nonsense"):
        load_config(p)


def test_unknown_tile_key_rejected_with_hint():
    """A typo'd tile arg used to pass through silently as an arg the
    adapter never reads; the schema gate (key registry shared with
    fdlint) rejects it with a did-you-mean."""
    with pytest.raises(ValueError, match=r"bacth.*did you mean 'batch'"):
        build_topology({"tile": [{"name": "v", "kind": "verify",
                                  "bacth": 32}]})


def test_unknown_tile_kind_rejected_with_hint():
    with pytest.raises(ValueError, match=r"verfy.*did you mean 'verify'"):
        build_topology({"tile": [{"name": "v", "kind": "verfy"}]})


def test_common_tile_keys_accepted():
    # supervise/chaos/cpu_idx etc. are stem/launcher keys valid on any kind
    topo = build_topology({
        "link": [{"name": "a_b"}],
        "tile": [{"name": "s", "kind": "synth", "outs": ["a_b"],
                  "supervise": {"policy": "restart"},
                  "chaos": {"events": []}, "cpu_idx": 0,
                  "lazy_auto": True},
                 {"name": "d", "kind": "sink", "ins": ["a_b"]}]})
    assert topo.tiles["s"].args["supervise"]["policy"] == "restart"


def test_overrides_dict(cfgdir):
    cfg = load_config(cfgdir / "base.toml",
                      overrides={"topology": {"wksp_size": 1 << 25}})
    assert cfg["topology"]["wksp_size"] == 1 << 25


def test_topology_launched_from_toml(cfgdir):
    """The e2e pipeline declared purely in TOML runs to completion."""
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    cfg = load_config(cfgdir / "base.toml", cfgdir / "override.toml")
    topo = build_topology(cfg, name=f"cfg{os.getpid()}")
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        import time
        deadline = time.monotonic() + 120
        while runner.metrics("sink")["rx"] < 24 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert runner.metrics("synth")["tx"] == 24    # override applied
        assert runner.metrics("sink")["rx"] == 24
        assert runner.metrics("verify")["verify_fail"] == 0
    finally:
        runner.halt()
        runner.close()
